#!/usr/bin/env python
"""Sub-10s CPU chaos smoke for tools/precommit.sh (ISSUE 12).

Exercises the fault-injection + guarded-dispatch machinery
(utils/faults, runtime/resilience) against stub dispatch functions —
deterministic replay, retry/backoff, watchdog hang containment,
fallback degrade, checkpoint roundtrip — WITHOUT importing jax or
compiling anything, so the gate stays sub-second and works while the
TPU probe hangs (the jaxlint-subcommand discipline). The full
device-path chaos matrix lives in tests/test_resilience.py and the
bench `resilience` stage; this is the commit-time canary.

Exit 0 = all checks passed; nonzero = the resilience layer itself is
broken (precommit refuses the commit).
"""

import os
import sys
import time

# run as a script from tools/: only tools/ lands on sys.path, the repo
# root is not — same bootstrap as rx_dispatch_bench.py
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from ziria_tpu.runtime import resilience as rz
    from ziria_tpu.utils import faults

    # jax must NOT have been imported by the above (the no-jax pin)
    assert "jax" not in sys.modules, \
        "chaos_smoke imported jax — the smoke must stay host-only"

    # 1. deterministic replay: same plan, same workload, same faults
    def run_once():
        fired = []
        with faults.inject(
                faults.FaultSpec("rx.stream_chunk", "transient",
                                 every=3),
                faults.FaultSpec("rx.push.s*", "nan_slab",
                                 calls=(1,)), seed=7) as plan:
            for i in range(9):
                try:
                    faults.maybe_fail("rx.stream_chunk")
                except faults.InjectedTransientError:
                    fired.append(i)
            a = np.ones((16, 2), np.float32)
            slabs = [faults.corrupt_slab("rx.push.s0", a)[0]
                     for _ in range(3)]
        return fired, slabs, list(plan.fired)

    f1, s1, log1 = run_once()
    f2, s2, log2 = run_once()
    assert f1 == f2 == [2, 5, 8], (f1, f2)
    assert log1 == log2
    assert np.array_equal(np.isnan(s1[1]), np.isnan(s2[1]))
    assert np.isnan(s1[1]).any() and not np.isnan(s1[0]).any()

    # 2. guarded: transient retries recover; backoff is deterministic
    calls, slept = [], []
    pol = rz.FaultPolicy(max_retries=2, backoff_base_s=1e-4)
    with faults.inject(faults.FaultSpec("site", "transient",
                                        calls=(0, 1))):
        out = rz.guarded(
            "site", lambda x: calls.append(x) or x * 2, 21,
            policy=pol, _sleep=slept.append)
    assert out == 42 and calls == [21] and len(slept) == 2
    assert slept[0] == rz.backoff_delay("site", 0, pol)
    assert slept[1] == rz.backoff_delay("site", 1, pol) > slept[0]

    # 3. fatal: immediate degrade to the fallback twin
    with faults.inject(faults.FaultSpec("s2", "fatal", every=1)):
        out = rz.guarded("s2", lambda: "compiled",
                         fallback=lambda: "twin")
    assert out == "twin"

    # 4. a hang is cut by the watchdog and the retry succeeds
    t0 = time.perf_counter()
    with faults.inject(faults.FaultSpec("hang", "hang", calls=(0,),
                                        delay_s=30.0)):
        out = rz.guarded(
            "hang", lambda: "ok",
            policy=rz.FaultPolicy(max_retries=1, backoff_base_s=1e-4,
                                  timeout_s=0.05),
            _sleep=lambda s: None)
    assert out == "ok" and time.perf_counter() - t0 < 5.0

    # 5. classification: retry only what may heal
    assert rz.classify_error(
        RuntimeError("UNAVAILABLE: tunnel")) == "transient"
    assert rz.classify_error(
        RuntimeError("INVALID_ARGUMENT: shape")) == "fatal"

    # 6. carry checkpoint roundtrip (the npz blob, format-gated)
    class Carry:
        tail = np.arange(8, dtype=np.float32).reshape(4, 2)
        offset, emitted, watermark = 4096, 3, 4000
    blob = rz.checkpoint_carry(Carry, seen=(4100, 4200),
                               geometry={"chunk_len": 4096})
    st = rz.restore_carry(blob)
    assert np.array_equal(st.tail, Carry.tail)
    assert (st.offset, st.emitted, st.watermark) == (4096, 3, 4000)
    assert st.seen == frozenset((4100, 4200))
    try:
        rz.restore_carry(b"garbage")
        raise AssertionError("garbage checkpoint must not restore")
    except rz.CarryCheckpointError:
        pass

    # 7. the channel-profile grammar (ISSUE 15): parses jax-free,
    # validates names against the profile registry, and the `channel`
    # data kind corrupts slabs deterministically in pure numpy — the
    # precommit gate keeps working through TPU probe hangs
    from ziria_tpu.phy import profiles as chp

    assert "jax" not in sys.modules, \
        "phy/profiles imported jax — the registry must stay host-only"
    assert chp.parse_profile_spec("flat,severe") == ("flat", "severe")
    assert chp.resolve_profiles("flat", 4) is None, \
        "flat must resolve to the unprofiled path"
    assert chp.resolve_profiles(("flat", "severe"), 4) == \
        ("flat", "severe", "flat", "severe")
    for name, prof in chp.CHANNEL_PROFILES.items():
        e = sum(r * r + i * i for r, i in prof.taps)
        assert abs(e - 1.0) < 1e-6, f"{name} taps not unit-energy"
    try:
        chp.parse_profile_spec("nope")
        raise AssertionError("unknown profile must not parse")
    except ValueError:
        pass
    specs, cseed = faults.parse_chaos_spec(
        "seed=5;rx.push.s*:channel:profile=severe,every=2")
    assert specs[0].profile == "severe" and specs[0].every == 2
    slab = np.ones((64, 2), np.float32)
    outs = []
    for _ in range(2):
        with faults.inject(*specs, seed=cseed):
            a0, k0 = faults.corrupt_slab("rx.push.s0", slab)
            a1, k1 = faults.corrupt_slab("rx.push.s0", slab)
        assert k0 == () and k1 == ("channel",)
        outs.append(a1)
    assert np.array_equal(outs[0], outs[1]), "channel kind must replay"
    assert not np.array_equal(outs[0], slab), "channel kind must act"
    assert outs[0].shape == slab.shape
    try:
        faults.parse_chaos_spec("x:channel:profile=nope")
        raise AssertionError("bad channel profile must not parse")
    except ValueError:
        pass
    assert "jax" not in sys.modules, \
        "channel-kind corruption imported jax — must stay host-only"

    # 8. disabled-path pin: the seams are free when no plan is active
    assert not faults.active()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.maybe_fail("x")
    per = (time.perf_counter() - t0) / n
    assert per < 5e-6, f"disabled maybe_fail: {per:.2e}s/call"

    dt = time.perf_counter() - t_start
    print(f"chaos smoke OK ({dt:.2f}s, no jax, "
          f"disabled-seam {per * 1e9:.0f}ns/call)")
    assert dt < 10.0, f"chaos smoke exceeded its 10s budget: {dt:.1f}s"
    return 0


if __name__ == "__main__":
    sys.exit(main())
