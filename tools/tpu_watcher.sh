#!/bin/bash
# Round-5 TPU evidence watcher (chain-v5).
#
# The axon TPU backend hangs for hours at a time (BENCH_PROBES.jsonl
# availability ledger). This loop probes it every 10 minutes with a
# hard-kill timeout; whenever a probe succeeds it runs the evidence
# chain, ordered by VERDICT r4's deliverable priority:
#   bench.py (stage-resumable)  -> BENCH_LIVE.json  headline + batch
#                                  sweep + framebatch + fxp + fence
#   tools/calibrate_vect.py     -> VECT_CALIB.json   vectorizer model
#   tools/hybrid_tpu_check.py   -> HYBRID_TPU.json   compiled-DSL chip
#   tools/viterbi_batch_sweep.py-> VITERBI_SWEEP.json B=512 regression
#   bench.py again              -> cheap resume pass merging every
#                                  stage the window managed to land
# bench.py accumulates stages across invocations (BENCH_PARTIAL.jsonl
# resume), so a window that flaps mid-chain keeps its progress.
#
# Hygiene (VERDICT r4 weak #7): all partial output is staged under
# .bench_scratch/ and atomically moved into the repo root only when
# complete and accepted — no 0-byte *.tmp litter.
#
# Mutual exclusion: all TPU access serializes on /tmp/tpu_busy (two
# concurrent axon clients both hang). `touch /tmp/stop_tpu_watcher`
# stops the loop.
set -u
cd /root/repo
LOG=/root/repo/BENCH_LIVE.log
PROBES=/root/repo/BENCH_PROBES.jsonl   # machine-readable availability ledger
SCRATCH=/root/repo/.bench_scratch
mkdir -p "$SCRATCH"
DEADLINE=$(( $(date +%s) + 41400 ))   # ~11.5 h
echo "[watcher] start chain-v5 $(date -u +%H:%M:%S)" >> "$LOG"

probe_log() {  # probe_log ok|fail|busy
  echo "{\"t\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\", \"probe\": \"$1\"}" >> "$PROBES"
}

accept_fresh() {  # accept_fresh <json>: a real chip capture from THIS run?
  python -c "
import json, sys
j = json.load(open('$1'))
ok = j.get('platform') not in (None, 'cpu') and not j.get('value_source')
sys.exit(0 if ok else 1)
" 2>> "$LOG"
}

harvest() {  # harvest <tool.py> <target.json> <timeout_s>
  [ -s "$2" ] && return 0
  touch /tmp/tpu_busy   # refresh: bench.py treats >35min-old flags as leaked
  local tmp="$SCRATCH/$(basename "$2").tmp"
  if timeout -k 15 "$3" env -u ZIRIA_TOOL_ALLOW_CPU \
       python "$1" > "$tmp" 2>> "$LOG" \
     && accept_fresh "$tmp"; then
    mv "$tmp" "$2"
    echo "[watcher] $(basename "$1") ok" >> "$LOG"
  else
    rm -f "$tmp"
    echo "[watcher] $(basename "$1") failed" >> "$LOG"
  fi
}

run_bench() {  # one stage-resumable bench pass -> BENCH_LIVE.json
  touch /tmp/tpu_busy
  local tmp="$SCRATCH/BENCH_LIVE.json.tmp"
  timeout -k 15 1500 env TPU_BUSY_HELD=1 BENCH_SELF_DEADLINE=1400 \
    python bench.py > "$tmp" 2>> "$LOG"
  local rc=$?
  echo "[watcher] bench rc=$rc" >> "$LOG"
  if [ $rc -eq 0 ] && accept_fresh "$tmp"; then
    mv "$tmp" /root/repo/BENCH_LIVE.json
    return 0
  fi
  rm -f "$tmp"
  pkill -9 -f "bench.py --tpu-" 2>/dev/null   # child AND probe modes
  return 1
}

while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -e /tmp/stop_tpu_watcher ]; do
  # take the flag atomically BEFORE touching the backend: the probe
  # itself is a TPU client, and a concurrent bench.py would hang both
  if ! ( set -C; echo "watcher pid $$" > /tmp/tpu_busy ) 2>/dev/null; then
    probe_log busy
    sleep 60
    continue
  fi
  if timeout -k 10 180 python -c "
import jax
d = jax.devices()[0]
assert d.platform != 'cpu', d.platform
print('probe ok:', d.platform, d.device_kind)
" >> "$LOG" 2>&1; then
    probe_log ok
    echo "[watcher] probe ok $(date -u +%H:%M:%S)" >> "$LOG"
    # 1) bench first: the headline + batch sweep are VERDICT r4's top
    # deliverable, and the stage-resumable child banks each stage
    run_bench; bench_ok=$?
    # 2) the three still-missing calibration artifacts
    harvest tools/calibrate_vect.py /root/repo/VECT_CALIB.json 1200
    harvest tools/hybrid_tpu_check.py /root/repo/HYBRID_TPU.json 900
    harvest tools/viterbi_batch_sweep.py /root/repo/VITERBI_SWEEP.json 900
    # 3) cheap resume pass merging everything the window landed
    if run_bench || [ "$bench_ok" -eq 0 ]; then
      echo "[watcher] CHAIN DONE $(date -u +%H:%M:%S); re-harvest in 1h" >> "$LOG"
      rm -f /tmp/tpu_busy
      sleep 3600
      continue
    fi
    rm -f /tmp/tpu_busy
  else
    probe_log fail
    echo "[watcher] probe failed/hung $(date -u +%H:%M:%S)" >> "$LOG"
    rm -f /tmp/tpu_busy     # release the flag taken before the probe
  fi
  sleep 600
done
rm -f /tmp/tpu_busy
echo "[watcher] exit (deadline/stop) $(date -u +%H:%M:%S)" >> "$LOG"
