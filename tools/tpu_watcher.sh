#!/bin/bash
# Round-3 TPU evidence watcher.
#
# The axon TPU backend hangs for hours at a time (BENCH_NOTES.md
# availability log). This loop probes it every 10 minutes with a
# hard-kill timeout; whenever a probe succeeds it immediately runs the
# evidence chain:
#   bench.py                  -> BENCH_LIVE.json   (headline RX sps/chip)
#   tools/calibrate_vect.py   -> VECT_CALIB.json   (vectorizer utility model)
#   tools/hybrid_tpu_check.py -> HYBRID_TPU.json   (hybrid RX on-chip)
# After a full success it keeps running and re-harvests every 3 h so
# later bench.py improvements are re-measured within the same round.
#
# Mutual exclusion: all TPU access must be serialized (two clients both
# hang). `touch /tmp/tpu_busy` pauses the watcher for manual TPU work;
# `touch /tmp/stop_tpu_watcher` stops it. The watcher takes /tmp/tpu_busy
# itself while harvesting.
set -u
cd /root/repo
LOG=/root/repo/BENCH_LIVE.log
PROBES=/root/repo/BENCH_PROBES.jsonl   # machine-readable availability ledger
DEADLINE=$(( $(date +%s) + 42000 ))   # ~11.5 h
echo "[watcher] start chain-v4 $(date -u +%H:%M:%S)" >> "$LOG"
probe_log() {  # probe_log ok|fail|busy
  echo "{\"t\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\", \"probe\": \"$1\"}" >> "$PROBES"
}
while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -e /tmp/stop_tpu_watcher ]; do
  # take the flag atomically BEFORE touching the backend: the probe
  # itself is a TPU client, and a concurrent bench.py would hang both
  if ! ( set -C; echo "watcher pid $$" > /tmp/tpu_busy ) 2>/dev/null; then
    probe_log busy
    sleep 60
    continue
  fi
  if timeout -k 10 180 python -c "
import jax
d = jax.devices()[0]
assert d.platform != 'cpu', d.platform
print('probe ok:', d.platform, d.device_kind)
" >> "$LOG" 2>&1; then
    probe_log ok
    echo "[watcher] probe ok $(date -u +%H:%M:%S)" >> "$LOG"
    # MISSING ARTIFACTS FIRST: a round-4 headline already exists in
    # BENCH_LIVE.json, so a short window is worth more spent on the
    # three still-missing calibration artifacts (three-round ask)
    # than on a bench re-harvest that happens every cycle anyway.
    # Each harvest strips ZIRIA_TOOL_ALLOW_CPU (a leaked smoke env
    # must not run the tools on CPU) AND verifies the record's
    # platform before promoting it — CPU output is never published.
    harvest() {  # harvest <tool.py> <target.json> <timeout_s>
      [ -s "$2" ] && return 0
      touch /tmp/tpu_busy   # refresh: bench.py treats >35min-old flags as leaked
      if timeout -k 15 "$3" env -u ZIRIA_TOOL_ALLOW_CPU \
           python "$1" > "$2.tmp" 2>> "$LOG" \
         && python -c "
import json, sys
j = json.load(open('$2.tmp'))
sys.exit(0 if j.get('platform') not in (None, 'cpu') else 1)
" 2>> "$LOG"; then
        mv "$2.tmp" "$2"
        echo "[watcher] $(basename "$1") ok" >> "$LOG"
      else
        echo "[watcher] $(basename "$1") failed" >> "$LOG"
      fi
    }
    harvest tools/calibrate_vect.py /root/repo/VECT_CALIB.json 1500
    harvest tools/hybrid_tpu_check.py /root/repo/HYBRID_TPU.json 900
    harvest tools/viterbi_batch_sweep.py /root/repo/VITERBI_SWEEP.json 900
    echo "[watcher] running bench $(date -u +%H:%M:%S)" >> "$LOG"
    touch /tmp/tpu_busy
    # self-deadline below the hard timeout so the parent can give the
    # child the full CHILD_TIMEOUT_MAX and still retry once
    timeout -k 15 1500 env TPU_BUSY_HELD=1 BENCH_SELF_DEADLINE=1400 \
      python bench.py > /root/repo/BENCH_LIVE.json.tmp 2>> "$LOG"
    rc=$?
    echo "[watcher] bench rc=$rc" >> "$LOG"
    if [ $rc -eq 0 ] && python -c "
import json,sys
j = json.load(open('/root/repo/BENCH_LIVE.json.tmp'))
sys.exit(0 if j.get('platform') not in (None,'cpu') else 1)
" 2>> "$LOG"; then
      mv /root/repo/BENCH_LIVE.json.tmp /root/repo/BENCH_LIVE.json
      echo "[watcher] bench SUCCESS; CHAIN DONE $(date -u +%H:%M:%S); sleeping 3h" >> "$LOG"
      rm -f /tmp/tpu_busy
      sleep 10800
      continue
    fi
    pkill -9 -f "bench.py --tpu-" 2>/dev/null   # child AND probe modes
    rm -f /tmp/tpu_busy
  else
    probe_log fail
    echo "[watcher] probe failed/hung $(date -u +%H:%M:%S)" >> "$LOG"
    rm -f /tmp/tpu_busy     # release the flag taken before the probe
  fi
  sleep 600
done
rm -f /tmp/tpu_busy
echo "[watcher] exit (deadline/stop) $(date -u +%H:%M:%S)" >> "$LOG"
