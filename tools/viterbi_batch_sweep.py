"""Viterbi Pallas batch sweep (VERDICT r2 weak #6): measure the kernel
across batch sizes on the real chip and attribute the r2 "B=512
regressed" observation. Emits ONE JSON object.

Static working-set arithmetic first (independent of the chip):

per grid step (one 128-lane batch tile x one UNROLL=64 time block)
  llr in      (1, 64, 2, 128) f32   64 KiB   } x2 with pipeline
  dec out     (1, 64, 8, 128) u8    64 KiB   } double-buffering
  metrics out (64, 128) f32         32 KiB
  m scratch   (64, 128) f32         32 KiB
  total VMEM  ~0.4 MiB  — far under a v5e core's VMEM, so VMEM
  pressure inside the kernel does NOT scale with B (batch enters as
  extra GRID tiles, not bigger blocks).

What DOES scale with B:
  - the lane-transpose pre/post passes ((B,T,2) <-> (nb,T,2,128)):
    pure HBM traffic, ~8 B x T x B bytes round-tripped;
  - the packed decision stream (T x 8 x 128 B per tile) read back by
    the traceback kernel: 2 x 8.2 MB of HBM per tile at T=8208.

The sweep times (a) the full decode, (b) the ACS+traceback kernels
alone (pre-transposed inputs), per frame, so the regression's locus
(kernel vs layout passes) is measured, not guessed.
"""

import json
import os
import sys
import time

import numpy as np

# run as a script from tools/: only tools/ lands on sys.path, the repo
# root is not — same bootstrap as hybrid_tpu_check.py (this exact miss
# cost the first successful TPU window its sweep artifact, r4)
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def main():
    import jax

    # ZIRIA_TOOL_ALLOW_CPU=1: smoke-test the whole sweep body on CPU
    # (interpret-mode kernels, shrunk sizes) so a broken tool cannot
    # waste a real TPU window — the sys.path bug above already cost
    # one. Results are labelled platform=cpu and never mistakable for
    # chip evidence.
    smoke = os.environ.get("ZIRIA_TOOL_ALLOW_CPU") == "1"
    if smoke:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from ziria_tpu.ops import viterbi_pallas as vp

    dev = jax.devices()[0]
    if dev.platform == "cpu" and not smoke:
        print(json.dumps({"error": "no TPU visible"}))
        return 1
    interp = dev.platform == "cpu"

    T = 1040 if smoke else 8208
    rng = np.random.default_rng(0)
    out = {"platform": dev.platform,
           "device_kind": getattr(dev, "device_kind", "?"),
           "T": T, "unroll": vp.UNROLL, "points": []}

    def fence(x):
        np.asarray(x.ravel()[:1])

    # per-point resume across window flaps (same idea as bench.py's
    # stage resume): finished B points are banked in the scratch dir
    # keyed by platform+T with per-point capture times, so a window
    # that dies after B=256 spends its successor on 512/1024.
    import _bank
    bank = _bank.load_bank("vit_sweep", dev.platform, match={"T": T})
    if bank:
        print(f"[sweep] resuming B={sorted(bank)} from the scratch "
              f"bank", file=sys.stderr, flush=True)

    for B in ((128, 256) if smoke else (128, 256, 512, 1024)):
        if str(B) in bank:
            out["points"].append(_bank.strip(bank[str(B)]))
            continue
        llrs = jnp.asarray(rng.normal(size=(B, T, 2)).astype(np.float32))
        full = jax.jit(lambda x: vp.viterbi_decode_batch(
            x, interpret=interp))
        # kernel-only: pre-tiled input, no lane transposes in the timed
        # region
        x = jnp.transpose(llrs, (1, 2, 0)).reshape(
            T, 2, B // 128, 128).transpose(2, 0, 1, 3)
        kern = jax.jit(lambda t: vp._decode_tiles(t, interp))

        def timed(fn, arg, reps=8):
            fence(fn(arg))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                o = None
                for _ in range(reps):
                    o = fn(arg)
                fence(o)
                best = min(best, (time.perf_counter() - t0) / reps)
            return best

        t_full = timed(full, llrs)
        t_kern = timed(kern, x)
        point = {
            "B": B,
            "t_full_ms": round(t_full * 1e3, 3),
            "t_kernel_ms": round(t_kern * 1e3, 3),
            "t_layout_ms": round((t_full - t_kern) * 1e3, 3),
            "mbit_per_s_full": round(B * T / t_full / 1e6, 1),
            "mbit_per_s_kernel": round(B * T / t_kern / 1e6, 1),
        }
        out["points"].append(point)
        _bank.save_entry("vit_sweep", dev.platform, str(B), point,
                         match={"T": T})
        print(f"[sweep] B={B}: full {t_full*1e3:.2f} ms, kernel "
              f"{t_kern*1e3:.2f} ms", file=sys.stderr, flush=True)

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
