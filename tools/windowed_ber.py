"""BER study: windowed parallel Viterbi vs the exact decode.

The windowed decoder's accuracy rests on the truncated-traceback
argument (survivors of the K=7 code merge within ~5-10 constraint
lengths). This study MEASURES that claim where it could fail — low
SNR — by decoding the same noisy frames with the exact decoder and
with the windowed math at several overlaps, and reporting BER plus
the windowed-vs-exact disagreement rate.

The windowing math under test is the production implementation
(ops/viterbi_pallas.viterbi_decode_batch_windowed) with the lax.scan
engine injected via its ``_decode`` hook, so CPU runs measure exactly
the shipped window/overlap/stitch logic without interpret-mode Pallas
cost. Output: one JSON object (committed into docs/windowed_viterbi.md).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def make_coded_frames(rng, n_frames, n_bits, amp):
    """Terminated K=7 frames + AWGN LLRs at amplitude ``amp`` — THE
    signal recipe shared by this study, its guard tests, and the
    staged-ext flag test (one definition so they can never measure
    different signals; review r5). Returns (msgs (F, n), llrs
    (F, n, 2) float32)."""
    from ziria_tpu.ops import coding
    msgs, llrs = [], []
    for _ in range(n_frames):
        bits = rng.integers(0, 2, n_bits).astype(np.uint8)
        bits[-coding.K + 1:] = 0          # zero-tail termination
        coded = np.asarray(coding.np_conv_encode_ref(bits), np.float32)
        llr = (2.0 * coded - 1.0) * amp + rng.normal(0, 1.0, coded.size)
        msgs.append(bits)
        llrs.append(llr.astype(np.float32).reshape(-1, 2))
    return np.stack(msgs), np.stack(llrs)


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ziria_tpu.ops import viterbi, viterbi_pallas

    def scan_engine(x):
        return jax.vmap(viterbi.viterbi_decode)(x)

    rng = np.random.default_rng(2026)
    n_bits, n_frames = 4096, 16
    window = 512
    out = {"n_bits": n_bits, "n_frames": n_frames, "window": window,
           "engine": "lax.scan via _decode hook (same windowing math "
                     "as the Pallas path)",
           "points": []}

    for amp in (0.5, 0.7, 0.9, 1.2):
        msgs, llrs = make_coded_frames(rng, n_frames, n_bits, amp)
        llrs = jnp.asarray(llrs)

        exact = np.asarray(scan_engine(llrs))
        total = msgs.size
        point = {"llr_amp": amp,
                 "ber_exact": round(int((exact != msgs).sum()) / total,
                                    6),
                 "overlaps": {}}
        for overlap in (32, 64, 96):
            win = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
                llrs, window=window, overlap=overlap,
                _decode=scan_engine))
            point["overlaps"][str(overlap)] = {
                "ber": round(int((win != msgs).sum()) / total, 6),
                "disagree_vs_exact":
                    round(int((win != exact).sum()) / total, 6),
            }
        out["points"].append(point)
        print(f"[ber] amp={amp}: exact {point['ber_exact']:.2e}, "
              + ", ".join(
                  f"ov{o}: {v['ber']:.2e} (diff {v['disagree_vs_exact']:.2e})"
                  for o, v in point["overlaps"].items()),
              file=sys.stderr, flush=True)

    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
