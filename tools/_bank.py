"""Scratch-dir resume bank shared by the TPU harvest tools.

The axon window flaps; each tool banks every finished unit of work
(a calibration pipeline, a sweep point) so a re-entering run spends
the next window only on what is missing. One implementation so the
aging rules cannot diverge between tools (review r5): every entry
carries its OWN capture time ``_t`` and ages out individually —
re-banking a new entry must not revive old ones (the same
chained-resume hazard bench.py's ``captured_t`` guards against).
"""

from __future__ import annotations

import contextlib
import json
import os
import time

try:
    import fcntl
except ImportError:                   # pragma: no cover - non-POSIX
    fcntl = None

SCRATCH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "..", ".bench_scratch")
MAX_AGE_S = 6 * 3600.0


def _path(name: str) -> str:
    return os.path.join(SCRATCH, name + ".json")


@contextlib.contextmanager
def _bank_lock(name: str):
    """Serialize the read-modify-write of one bank file across
    concurrent bankers (ADVICE r5 #4: two tools banking at once could
    lose each other's entries — previously mitigated only by the
    /tmp/tpu_busy serialization convention). An flock on a sidecar
    .lock file: advisory, crash-safe (the OS releases with the fd),
    so no stale-lock aging is needed."""
    if fcntl is None:                 # pragma: no cover - non-POSIX
        yield
        return
    fd = os.open(_path(name) + ".lock",
                 os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def load_bank(name: str, platform: str, match: dict = None,
              max_age_s: float = MAX_AGE_S, now: float = None) -> dict:
    """key -> entry for this platform (and ``match`` file-level fields,
    e.g. a trellis length), dropping entries older than ``max_age_s``
    by their individual capture times."""
    try:
        with open(_path(name)) as f:
            saved = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if saved.get("platform") != platform:
        return {}
    for k, v in (match or {}).items():
        if saved.get(k) != v:
            return {}
    now = time.time() if now is None else now
    return {k: e for k, e in saved.get("entries", {}).items()
            if isinstance(e, dict) and now - e.get("_t", 0) < max_age_s}


def save_entry(name: str, platform: str, key: str, entry: dict,
               match: dict = None) -> None:
    """Bank one finished unit (stamped with its capture time),
    atomically. A platform/match mismatch discards the old bank.
    The whole read-modify-write runs under the bank's lock file so
    concurrent bankers serialize instead of losing entries."""
    os.makedirs(SCRATCH, exist_ok=True)
    with _bank_lock(name):
        try:
            with open(_path(name)) as f:
                saved = json.load(f)
        except (OSError, json.JSONDecodeError):
            saved = {}
        if saved.get("platform") != platform or any(
                saved.get(k) != v for k, v in (match or {}).items()):
            saved = {}
        saved["platform"] = platform
        saved.update(match or {})
        saved.setdefault("entries", {})[key] = {**entry,
                                                "_t": time.time()}
        tmp = _path(name) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(saved, f)
        os.replace(tmp, _path(name))


def strip(entry: dict) -> dict:
    """An entry's payload without the bank's bookkeeping."""
    return {k: v for k, v in entry.items() if k != "_t"}
