#!/bin/bash
# "committed => executed" gate (VERDICT r3 weak #1 / next #2): refuse to
# commit a staged test file that has not been run. Runs pytest on every
# staged tests/test_*.py; skips cleanly when none are staged. Install:
#   ln -sf ../../tools/precommit.sh .git/hooks/pre-commit
# Escape hatch for WIP commits: ZIRIA_SKIP_TESTGATE=1 git commit ...
set -u
cd "$(git rev-parse --show-toplevel)"
[ "${ZIRIA_SKIP_TESTGATE:-0}" = "1" ] && exit 0

# jaxlint gate (ISSUE 8/9): pure AST, no jax import, sub-5s — a
# cache-key/hygiene finding must not reach a commit
if ! python -m ziria_tpu lint ziria_tpu/; then
  echo "[precommit] jaxlint found issues — commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

# chaos smoke (ISSUE 12): the fault-injection + guarded-dispatch
# machinery exercised against stub dispatches — sub-10s, CPU-only,
# never imports jax (works through TPU probe hangs, like the lint
# gate). A broken resilience layer must not reach a commit.
if ! timeout 30 python tools/chaos_smoke.py; then
  echo "[precommit] chaos smoke FAILED (tools/chaos_smoke.py) —" \
       "commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

# serve smoke (ISSUE 13): the continuous-batching server's
# admission/backpressure/shed/evict/drain state machine exercised
# against a stub receiver — sub-second, never imports jax (works
# through TPU probe hangs, like chaos_smoke and the lint gate).
if ! timeout 30 python tools/serve_smoke.py; then
  echo "[precommit] serve smoke FAILED (tools/serve_smoke.py) —" \
       "commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

# durability smoke (ISSUE 14): the crash-safe journal / snapshot /
# recovery machinery exercised against a stub receiver — sub-second,
# never imports jax (works through TPU probe hangs, like its
# siblings). A broken durability layer must not reach a commit.
if ! timeout 30 python tools/durability_smoke.py; then
  echo "[precommit] durability smoke FAILED" \
       "(tools/durability_smoke.py) — commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

# geometry smoke (ISSUE 16): the declarative Geometry object's
# construct/resolve/serialize/tuned() round trip plus the pinned
# default constants — sub-second, never imports jax (works through
# TPU probe hangs, like its siblings). A drifted default would break
# the no-op-by-construction guarantee behind every compiled surface.
if ! timeout 30 python tools/geometry_smoke.py; then
  echo "[precommit] geometry smoke FAILED (tools/geometry_smoke.py)" \
       "— commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

# perf-ledger regression gate (ISSUE 9): latest vs previous
# same-platform run in BENCH_TRAJECTORY.jsonl. Lenient tolerance —
# bench numbers on a shared box are noisy; the gate exists to catch
# collapses, not jitter. Exits 0 when there is nothing to compare.
if ! python tools/perf_report.py --check --tolerance 0.5; then
  echo "[precommit] perf_report --check flagged a regression in" \
       "BENCH_TRAJECTORY.jsonl — commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi

mapfile -t staged < <(git diff --cached --name-only --diff-filter=ACM |
                      grep -E '^tests/test_.*\.py$' || true)
[ ${#staged[@]} -eq 0 ] && exit 0
# pytest runs the WORKTREE copy; that only certifies the INDEX content
# when the two are identical — refuse a partially-staged test file
for f in "${staged[@]}"; do
  if ! git diff --quiet -- "$f"; then
    echo "[precommit] $f differs between index and worktree;" >&2
    echo "[precommit] re-add it (or stash the WIP) so the gate runs" \
         "what will be committed" >&2
    exit 1
  fi
done
echo "[precommit] running staged test files: ${staged[*]}" >&2
if ! timeout 1200 python -m pytest "${staged[@]}" -q -x; then
  echo "[precommit] staged tests FAILED — commit refused" >&2
  echo "[precommit] (ZIRIA_SKIP_TESTGATE=1 to override for WIP)" >&2
  exit 1
fi
exit 0
