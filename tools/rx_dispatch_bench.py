"""RX hot-path lever bench: quantized Viterbi metrics + one-dispatch
mixed-rate decode (ISSUE 1 tentpole; VERDICT r5 "Next round" #2/#5).

Two measurements, each importable by bench.py as a resumable child
stage (the tools-module discipline of VERDICT #9 — bench.py loads this
file, it does not re-implement it) and runnable standalone for a CPU
smoke or a manual chip window:

- ``quantized_sweep``: marginal per-step time of the batched DATA
  decode at the bench shape with float32 vs int16 path metrics — the
  SORA trade (half the LLR HBM stream, half the metric VMEM footprint)
  measured, not asserted. The marginal time comes from a jitted
  fori_loop K-spread (t(K2)-t(K1))/(K2-K1) with runtime-zero data
  feedback, the same tunnel-cancelling method as bench.py's headline.

- ``mixed_dispatch_stats``: the DATA-stage compile count and decode
  wall time for an all-8-rates corpus through (a) the host-side
  bucketed dispatch (one jit per (rate, symbol bucket) — O(rates x
  log lengths) compiles) and (b) the one-``lax.switch`` mixed-rate
  dispatch (one jit per symbol bucket — O(log lengths)), asserting
  the two decode bit-identically lane for lane. Compile counts are
  read off the real lru_cache entry counts after clearing them, so
  the artifact records measured cache growth, not arithmetic.

- ``batched_acquire_stats`` (ISSUE 2 tentpole): acquisition dispatch
  count and wall time of ``receive_many`` with the host-driven
  per-capture loop (>= 3N+1 dispatches) vs the one-dispatch batched
  acquisition (acquire -> gather -> mixed decode, <= 3 dispatches),
  measured by the instrumented utils/dispatch counter and
  identity-gated lane for lane.

- ``link_loopback_stats`` (ISSUE 3 tentpole): the full device-resident
  TX -> channel -> RX loopback (phy/link.loopback_many) over an
  all-8-rates mixed-length batch — <= 5 dispatches and frames/s for
  the batched link vs >= 5N for the per-frame encode/impair/receive
  loop, identity-gated lane for lane; dispatch counts from the
  instrumented counter, so the artifact records the measured
  O(N) -> O(1) collapse of the transmit side too. Pins
  ``fused=False`` so this artifact keeps measuring the staging lever
  alone, comparable with prior rounds; the fused graph is
  ``fused_link_stats``'s job.

- ``fused_link_stats`` (ISSUE 4 tentpole): the staged ~5-dispatch
  loopback vs the ONE-dispatch fused graph (encode -> channel ->
  acquire -> classify -> gather -> decode -> batched CRC in a single
  jitted program), with ``check_fcs=True`` so the batched-CRC
  satellite is measured too; per-site dispatch wall times from the
  extended utils/dispatch counter, identity-gated lane for lane.

- ``ber_sweep_stats`` (ISSUE 4 tentpole): an n-rates x K-SNR BER
  sweep through ``link.sweep_ber`` (ONE lax.scan dispatch) vs the
  python loop of per-batch ``loopback_ber_bits`` points (~3 dispatches
  per point), error counts gated integer-identical, sweep points/s
  and samples/s recorded.

- ``viterbi_breakdown`` (ISSUE 6 satellite): the decode step cut into
  front-end-only / ACS-only / traceback-only / full with the marginal-K
  method — the measured answer to "dependency-chain-bound, but WHERE?".

- ``viterbi_kernel_stats`` (ISSUE 6 tentpole): per-lever decode-core
  samples/s for the rebuilt ACS (radix-4, int16, int8+LUT, fused
  demap front end, stacked), dispatch counts + per-site times from
  utils/dispatch, identity-gated: radix-4 exactly bit-identical vs
  the float32 radix-2 oracle on noisy inputs, the fused levers within
  a vanishing mismatch budget (their renorm cadence differs), int8
  gated on its BER envelope.

- ``streaming_stats`` (ISSUE 5 tentpole): a long multi-frame I/Q
  stream (``link.stream_many``: all 8 rates, random gaps, CFO, delay,
  AWGN) through ``framebatch.receive_stream`` — <= 2 dispatches per
  CHUNK (O(chunks), frame count free) vs >= 3 per FRAME for the
  per-capture path over the same detected windows — identity-gated
  frame for frame (results AND starts vs ground truth), samples/s,
  dispatch counts, and the double-buffer in-flight depth gauge.
  Since ISSUE 7, ``streaming_stats`` and ``fused_link_stats`` also
  report per-site latency DISTRIBUTIONS (p50/p90/p99/max ms) off the
  utils/telemetry histogram layer (``latency_ms_*`` blocks), and
  ``streaming_stats(trace_path=...)`` leaves a Chrome trace of one
  streaming pass for tools/trace_report.py. Since ISSUE 9 both blocks
  additionally report ``roofline_by_site`` — achieved GB/s / GFLOP/s
  and %-of-peak per dispatch site from XLA's own cost analysis
  (utils/programs observatory) x the measured p50, replacing hand
  byte/FLOP formulas with compiled-graph truth — and the exported
  trace embeds the ``siteCosts``/``devicePeaks`` riders so
  trace_report prints GB/s per span label.

- ``multi_stream_stats`` (ISSUE 11 tentpole): S concurrent streams
  through the stream-axis fleet receiver
  (``framebatch.receive_streams``) vs S independent single-stream
  receivers — <= 2 dispatches per CHUNK-STEP independent of S
  (asserted), lane-for-lane bit-identity per stream, aggregate
  samples/s per dp mesh size (``sps_by_devices`` — the scaling
  record the ROADMAP's "many streams, one device fleet" item asks
  for), active-streams gauge, latency + roofline blocks.

Standalone: ``ZIRIA_TOOL_ALLOW_CPU=1 python tools/rx_dispatch_bench.py``
runs all at shrunk sizes on CPU (results labelled platform=cpu,
never mistakable for chip evidence). Emits ONE JSON object.
"""

import json
import os
import sys
import time

import numpy as np

# run as a script from tools/: only tools/ lands on sys.path, the repo
# root is not — same bootstrap as viterbi_batch_sweep.py
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def _fence(x):
    # device arrays need a copy-out fence; host-complete results (the
    # receive paths return numpy-backed RxResult lists) do not
    if hasattr(x, "ravel"):
        np.asarray(np.ravel(x)[:1])


def _timed(fn, *args, reps=1, tries=3):
    fn(*args)                       # warm (compile)
    best = float("inf")
    for _ in range(tries):
        t0 = time.perf_counter()
        o = None
        for _ in range(reps):
            o = fn(*args)
        _fence(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def _roofline_by_site(obs, lat_blocks, device_kind):
    """Per-site achieved GB/s / GFLOP/s (ISSUE 9): XLA's own cost
    analysis for the site's compiled program (utils/programs — the
    observatory noted fn+avals at the dispatch site) divided by the
    site's measured p50 latency from the telemetry histograms. The
    p50 is the histogram's power-of-two bucket UPPER bound (<= 2x the
    true p50), so the achieved numbers are conservative lower bounds.
    ``pct_hbm_peak``/``pct_flops_peak`` appear only for device kinds
    in the peaks table (utils/programs.DEVICE_PEAKS) — unknown kinds
    report absolutes, never a percentage of the wrong ceiling."""
    from ziria_tpu.utils import programs

    lat = {}
    for b in lat_blocks:
        lat.update({k: v for k, v in b.items() if v})
    out = {}
    for site, c in sorted(obs.site_costs().items()):
        row = {"flops": c["flops"],
               "bytes_accessed": c["bytes_accessed"]}
        if c.get("peak_bytes"):
            row["peak_bytes"] = c["peak_bytes"]
        p50_ms = (lat.get(site) or {}).get("p50")
        if p50_ms:
            row["p50_ms"] = p50_ms
            row.update(programs.roofline(
                p50_ms / 1e3, bytes_accessed=c["bytes_accessed"],
                flops=c["flops"], device_kind=device_kind))
        out[site] = row
    return out


def _device_kind():
    import jax
    return getattr(jax.devices()[0], "device_kind", "?")


def _latency_block(reg):
    """Per-site latency summaries (ms) off a telemetry registry's
    dispatch histograms: {site: {count, mean, p50, p90, p99, max}} —
    distribution-level numbers from the histogram layer, NOT summed
    means (p50/p99 are the power-of-two bucket quantile bounds, max
    and mean exact)."""
    from ziria_tpu.utils import telemetry

    out = {}
    for (name, labels), m in reg.metrics():
        if name == telemetry.DISPATCH_HISTOGRAM:
            out[dict(labels).get("site", "")] = m.summary(
                scale=1e3, ndigits=4)
    return out


def quantized_sweep(B=128, n_bytes=1000, rate_mbps=54,
                    k1=4, k2=12):
    """float32 vs int16 saturating path metrics on the batched DATA
    decode: correctness gate + marginal step time for each. Returns a
    flat dict (bench.py stages store it verbatim)."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.bits import bytes_to_bits

    rate = RATES[rate_mbps]
    n_sym = n_symbols(n_bytes, rate)
    n_psdu_bits = 8 * n_bytes
    rng = np.random.default_rng(11)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, rate_mbps))
    want = np.asarray(bytes_to_bits(psdu))
    frames = jnp.asarray(np.broadcast_to(
        frame, (B,) + frame.shape).copy())

    out = {"batch": B, "frame_bytes": n_bytes, "rate_mbps": rate_mbps,
           "frame_len": int(frame.shape[0])}
    bits_by_md = {}
    for md in ("float32", "int16"):
        def decode(f, _md=md):
            return rx.decode_data_batch(
                f, rate, n_sym, n_psdu_bits, viterbi_metric=_md)[0]

        got = np.asarray(jax.jit(decode)(frames))
        assert np.array_equal(got[0], want) \
            and np.array_equal(got[-1], want), f"{md} decode mismatch"
        bits_by_md[md] = got

        # marginal step: K-spread of a jitted device-side loop with
        # runtime-zero feedback (the next input depends on the last
        # output, so the body cannot be hoisted), cancelling the fixed
        # per-call dispatch/tunnel cost
        @jax.jit
        def loop(x, k, _md=md):
            def body(_i, carry):
                s, acc = carry
                bits = rx.decode_data_batch(
                    x + s, rate, n_sym, n_psdu_bits,
                    viterbi_metric=_md)[0]
                s2 = bits[0, 0].astype(jnp.float32) * 1e-30
                return s2, acc + bits.sum() * 1e-30
            return jax.lax.fori_loop(
                0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

        t_k1 = _timed(loop, frames, jnp.int32(k1))
        t_k2 = _timed(loop, frames, jnp.int32(k2))
        t_step = max((t_k2 - t_k1) / (k2 - k1), 1e-9)
        short = "f32" if md == "float32" else "i16"
        out[f"t_step_{short}_s"] = round(t_step, 6)
        out[f"sps_{short}"] = round(B * frame.shape[0] / t_step, 1)
    out["i16_matches_f32"] = bool(
        np.array_equal(bits_by_md["int16"], bits_by_md["float32"]))
    out["i16_over_f32"] = round(
        out["t_step_i16_s"] / max(out["t_step_f32_s"], 1e-12), 3)
    return out


def mixed_dispatch_stats(n_bytes=100, viterbi_metric=None):
    """All-8-rates corpus through the bucketed host dispatch vs the
    one-``lax.switch`` mixed dispatch: DATA-stage compile counts
    (measured lru_cache growth), wall times, and a lane-for-lane
    bit-identity gate. Returns a flat dict."""
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES

    rng = np.random.default_rng(12)
    caps = []
    for m in sorted(RATES):
        psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        caps.append(np.concatenate(
            [np.zeros((50, 2), np.float32), s], axis=0))

    # -- before: host-side bucketed dispatch, one jit per (rate, bucket)
    rx._jit_decode_data_bucketed.cache_clear()
    res_b = [rx.receive(c, viterbi_metric=viterbi_metric) for c in caps]
    compiles_bucketed = rx._jit_decode_data_bucketed.cache_info().currsize
    t_bucketed = _timed(
        lambda: [rx.receive(c, viterbi_metric=viterbi_metric)
                 for c in caps])

    # -- after: ONE jitted lax.switch serving every rate in the batch.
    # batched_acquire is pinned OFF so this artifact keeps measuring
    # the mixed-dispatch lever alone, comparable with prior rounds;
    # the acquisition before/after is batched_acquire_stats's job
    rx._jit_decode_data_mixed.cache_clear()
    res_m = framebatch.receive_many(caps, viterbi_metric=viterbi_metric,
                                    batched_acquire=False)
    compiles_mixed = rx._jit_decode_data_mixed.cache_info().currsize
    t_mixed = _timed(
        lambda: framebatch.receive_many(
            caps, viterbi_metric=viterbi_metric, batched_acquire=False))

    assert all(a.ok and b.ok for a, b in zip(res_b, res_m))
    assert all(np.array_equal(a.psdu_bits, b.psdu_bits)
               for a, b in zip(res_b, res_m)), \
        "mixed dispatch diverged from the bucketed path"

    samples = sum(c.shape[0] for c in caps)
    return {
        "rates": len(caps), "frame_bytes": n_bytes,
        "viterbi_metric": viterbi_metric or "float32",
        "compiles_bucketed": compiles_bucketed,
        "compiles_mixed": compiles_mixed,
        # the DATA stage's device dispatch count per mixed batch:
        # one bucketed jit call per decodable frame vs one switch call
        "data_dispatches_bucketed": len(caps),
        "data_dispatches_mixed": 1,
        "t_bucketed_s": round(t_bucketed, 4),
        "t_mixed_s": round(t_mixed, 4),
        "sps_bucketed": round(samples / t_bucketed, 1),
        "sps_mixed": round(samples / t_mixed, 1),
        "bit_identical": True,
    }


def batched_acquire_stats(n_bytes=100, viterbi_metric=None):
    """Acquisition dispatch count + wall time of `receive_many` over
    an all-8-rates corpus, host-driven per-capture acquisition vs the
    one-dispatch batched path (acquire -> gather -> mixed decode),
    identity-gated lane for lane. Dispatches are measured with the
    instrumented counter (utils/dispatch.count_dispatches), so the
    artifact records the real before/after O(N) -> O(1) collapse, not
    arithmetic."""
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy.wifi import tx
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(13)
    caps = []
    for m in sorted(RATES):
        psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        caps.append(np.concatenate(
            [np.zeros((50, 2), np.float32), s], axis=0))

    # -- before: host loop — sync + head CFO + SIGNAL per capture,
    #    a per-lane segment CFO, then the one mixed decode
    with count_dispatches() as d_host:
        res_h = framebatch.receive_many(
            caps, viterbi_metric=viterbi_metric, batched_acquire=False)
    t_host = _timed(lambda: framebatch.receive_many(
        caps, viterbi_metric=viterbi_metric, batched_acquire=False))

    # -- after: acquire -> gather -> decode, three dispatches total
    with count_dispatches() as d_bat:
        res_b = framebatch.receive_many(
            caps, viterbi_metric=viterbi_metric, batched_acquire=True)
    t_bat = _timed(lambda: framebatch.receive_many(
        caps, viterbi_metric=viterbi_metric, batched_acquire=True))

    assert all(a.ok and b.ok for a, b in zip(res_h, res_b))
    assert all(np.array_equal(a.psdu_bits, b.psdu_bits)
               for a, b in zip(res_h, res_b)), \
        "batched acquisition diverged from the host-acquire path"

    samples = sum(c.shape[0] for c in caps)
    return {
        "rates": len(caps), "frame_bytes": n_bytes,
        "viterbi_metric": viterbi_metric or "float32",
        "dispatches_host_acquire": d_host.total,
        "dispatches_batched_acquire": d_bat.total,
        "dispatch_breakdown_batched": dict(d_bat.counts),
        "dispatch_times_ms_host": d_host.times_ms(),
        "dispatch_times_ms_batched": d_bat.times_ms(),
        "t_host_acquire_s": round(t_host, 4),
        "t_batched_acquire_s": round(t_bat, 4),
        "sps_host_acquire": round(samples / t_host, 1),
        "sps_batched_acquire": round(samples / t_bat, 1),
        "bit_identical": True,
    }


def link_loopback_stats(n_frames=8, n_bytes=100, snr_db=28.0):
    """The closed TX -> channel -> RX loop, batched vs per-frame:
    dispatch counts (instrumented counter), wall times, frames/s, and
    a lane-for-lane identity gate. All 8 rates with mixed lengths ride
    one batch; the channel applies per-lane CFO + delay + AWGN with
    counter-derived keys, identical in both paths. Returns a flat
    dict."""
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(14)
    mbps = sorted(RATES) * (-(-n_frames // len(RATES)))
    mbps = mbps[:n_frames]
    lens = [max(5, n_bytes - 7 * (k % 5)) for k in range(n_frames)]
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in lens]
    cfo = [(-1) ** k * 1e-4 * (k % 7 + 1) for k in range(n_frames)]
    delay = [20 + 13 * k for k in range(n_frames)]
    # fused=False: this artifact measures the STAGING lever alone
    # (comparable with prior rounds); fused_link_stats owns the fused
    # graph's numbers
    kw = dict(snr_db=snr_db, cfo=cfo, delay=delay, seed=6, fused=False)

    with count_dispatches() as d_pf:
        res_f = link.loopback_many(psdus, mbps, batched_tx=False, **kw)
    t_pf = _timed(lambda: link.loopback_many(
        psdus, mbps, batched_tx=False, **kw))

    with count_dispatches() as d_bat:
        res_b = link.loopback_many(psdus, mbps, batched_tx=True, **kw)
    t_bat = _timed(lambda: link.loopback_many(
        psdus, mbps, batched_tx=True, **kw))

    assert all(a.ok and b.ok for a, b in zip(res_f, res_b))
    assert all(np.array_equal(a.psdu_bits, b.psdu_bits)
               for a, b in zip(res_f, res_b)), \
        "batched loopback diverged from the per-frame path"

    return {
        "frames": n_frames, "max_frame_bytes": max(lens),
        "rates": sorted(set(mbps)), "snr_db": snr_db,
        "dispatches_perframe": d_pf.total,
        "dispatches_batched": d_bat.total,
        "dispatch_breakdown_batched": dict(d_bat.counts),
        "dispatch_times_ms_batched": d_bat.times_ms(),
        "t_perframe_s": round(t_pf, 4),
        "t_batched_s": round(t_bat, 4),
        "fps_perframe": round(n_frames / t_pf, 1),
        "fps_batched": round(n_frames / t_bat, 1),
        "bit_identical": True,
    }


def fused_link_stats(n_frames=8, n_bytes=100, snr_db=28.0):
    """The ONE-dispatch fused loopback graph vs its staged ~5-dispatch
    oracle: dispatch counts AND per-site wall times (the extended
    utils/dispatch counter), wall times, frames/s, and a lane-for-lane
    identity gate — with ``check_fcs=True`` so the batched-CRC tail
    (one vmapped dispatch instead of a host check per lane) is in the
    measurement. Returns a flat dict."""
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(15)
    mbps = (sorted(RATES) * (-(-n_frames // len(RATES))))[:n_frames]
    lens = [max(5, n_bytes - 7 * (k % 5)) for k in range(n_frames)]
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in lens]
    cfo = [(-1) ** k * 1e-4 * (k % 7 + 1) for k in range(n_frames)]
    delay = [20 + 13 * k for k in range(n_frames)]
    kw = dict(snr_db=snr_db, cfo=cfo, delay=delay, seed=6,
              add_fcs=True, check_fcs=True)

    from ziria_tpu.utils import programs, telemetry

    # collect() around BOTH the counted run and the timed repeats so
    # the per-site latency histograms hold enough samples for the
    # p50/p99 bounds to mean something; the observatory wraps both
    # variants so every fired site contributes its compiled program's
    # analytical cost to the per-site roofline block
    with programs.observing() as obs:
        with telemetry.collect() as reg_st:
            with count_dispatches() as d_st:
                res_s = link.loopback_many(psdus, mbps, fused=False,
                                           **kw)
            t_st = _timed(lambda: link.loopback_many(
                psdus, mbps, fused=False, **kw))

        with telemetry.collect() as reg_fu:
            with count_dispatches() as d_fu:
                res_f = link.loopback_many(psdus, mbps, fused=True,
                                           **kw)
            t_fu = _timed(lambda: link.loopback_many(
                psdus, mbps, fused=True, **kw))

    assert all(a.ok == b.ok and a.crc_ok == b.crc_ok
               and a.rate_mbps == b.rate_mbps
               and a.length_bytes == b.length_bytes
               and np.array_equal(a.psdu_bits, b.psdu_bits)
               for a, b in zip(res_s, res_f)), \
        "fused loopback diverged from the staged path"

    return {
        "frames": n_frames, "max_frame_bytes": max(lens),
        "rates": sorted(set(mbps)), "snr_db": snr_db,
        "check_fcs": True,
        "dispatches_staged": d_st.total,
        "dispatches_fused": d_fu.total,
        "dispatch_breakdown_staged": dict(d_st.counts),
        "dispatch_times_ms_staged": d_st.times_ms(),
        "dispatch_times_ms_fused": d_fu.times_ms(),
        # per-dispatch latency DISTRIBUTIONS (telemetry histograms):
        # the fused block's "link.fused" row is the per-dispatch
        # p50/p99 the serving work asks for
        "latency_ms_staged": _latency_block(reg_st),
        "latency_ms_fused": _latency_block(reg_fu),
        # per-site achieved GB/s / GFLOP/s and %-of-peak from XLA
        # cost analysis x measured p50 — the "link.fused" row is the
        # fused dispatch's distance to the roofline (compiled-graph
        # truth, not bench.py's hand formulas)
        "roofline_by_site": _roofline_by_site(
            obs, [_latency_block(reg_st), _latency_block(reg_fu)],
            _device_kind()),
        "t_staged_s": round(t_st, 4),
        "t_fused_s": round(t_fu, 4),
        "fps_staged": round(n_frames / t_st, 1),
        "fps_fused": round(n_frames / t_fu, 1),
        "bit_identical": True,
    }


def ber_sweep_stats(n_frames=16, n_bytes=50, rates=(6, 24, 54),
                    snrs=(2.0, 5.0, 8.0), seeds=(7,)):
    """A rates x SNR x seeds BER sweep through `link.sweep_ber` (ONE
    lax.scan dispatch) vs the python loop of per-batch
    `loopback_ber_bits` points (~3 instrumented dispatches per
    rate-point), error counts gated integer-identical. Records sweep
    points/s and samples/s. Returns a flat dict."""
    from ziria_tpu.phy import link
    from ziria_tpu.utils.bits import np_bytes_to_bits
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(16)
    psdus = rng.integers(0, 256, (n_frames, n_bytes)).astype(np.uint8)
    want = np.stack([np_bytes_to_bits(p) for p in psdus])

    with count_dispatches() as d_sw:
        errs = link.sweep_ber(psdus, rates, snrs, seeds)
    t_sw = _timed(lambda: link.sweep_ber(psdus, rates, snrs, seeds))

    with count_dispatches() as d_lp:
        for ri, m in enumerate(rates):
            for si, s in enumerate(snrs):
                for ki, sd in enumerate(seeds):
                    got = link.loopback_ber_bits(psdus, m, s, sd)
                    e = int(np.sum(got != want))
                    assert e == int(errs[ri, si, ki]), \
                        "sweep diverged from the per-batch loop"
    t_lp = _timed(lambda: [
        link.loopback_ber_bits(psdus, m, s, sd)
        for m in rates for s in snrs for sd in seeds])

    n_points = len(rates) * len(snrs) * len(seeds)
    bits_per_point = n_frames * 8 * n_bytes
    return {
        "frames": n_frames, "frame_bytes": n_bytes,
        "rates": list(rates), "snrs": list(snrs),
        "seeds": list(seeds), "points": n_points,
        "dispatches_sweep": d_sw.total,
        "dispatches_loop": d_lp.total,
        "dispatch_times_ms_sweep": d_sw.times_ms(),
        "t_sweep_s": round(t_sw, 4),
        "t_loop_s": round(t_lp, 4),
        "points_per_s_sweep": round(n_points / t_sw, 2),
        "points_per_s_loop": round(n_points / t_lp, 2),
        "bits_per_point": bits_per_point,
        "sweep_sps": round(
            n_points * bits_per_point / max(t_sw, 1e-9), 1),
        "counts_identical": True,
    }


#: per-profile BER-envelope bounds at the TOP of the sweep's SNR grid
#: (the "bounded error floor at high SNR" acceptance gates of ISSUE
#: 15). flat must be error-free at high SNR; the equalizable profiles
#: (multipath-only) must stay near-clean through the LTS/ZF front
#: end; the burst/SCO/drift profiles are ALLOWED a floor — bounded,
#: never unbounded garbage. Calibrated with >= 3x margin over
#: measured CPU values at the bench geometry.
CHANNEL_BER_ENVELOPES = {
    "flat": 0.0, "mild": 0.02, "urban": 0.05, "severe": 0.15,
    "sco": 0.10, "doppler": 0.10, "bursty": 0.30, "hostile": 0.30,
}


def channel_sweep_stats(n_frames=8, n_bytes=24, rates=(6, 24, 54),
                        snrs=(12.0, 30.0), seeds=(7,),
                        profiles=("flat", "mild", "urban", "severe",
                                  "sco", "doppler", "bursty",
                                  "hostile")):
    """The channel-hostile BER gate (ISSUE 15): a rates x SNR x
    PROFILE waterfall through `link.sweep_ber`'s profile axis — STILL
    one `lax.scan` dispatch — gated three ways:

    - the ``flat`` column's error counts are bit-identical to the
      profile-less sweep (flat IS the unprofiled channel);
    - every profile's BER at the TOP SNR point stays under its
      `CHANNEL_BER_ENVELOPES` bound (bounded error floors — a deep
      fade degrades, it never explodes);
    - BER is non-increasing in SNR per profile within counting noise
      (the waterfall actually falls).

    Records ``ber_floor_<profile>`` per profile (the BENCH_TRAJECTORY
    metrics; lower is better) plus sweep timing. Returns a flat
    dict."""
    from ziria_tpu.phy import link
    from ziria_tpu.utils.dispatch import count_dispatches

    if "flat" not in profiles:
        # the stage IS the flat-identity gate: without the anchor
        # column the base-sweep comparison would be vacuous and the
        # ledger would record a gate that never ran
        raise ValueError("channel_sweep_stats needs 'flat' in "
                         "profiles (the identity-anchor column)")
    rng = np.random.default_rng(15)
    psdus = rng.integers(0, 256, (n_frames, n_bytes)).astype(np.uint8)
    bits_total = n_frames * 8 * n_bytes

    base = link.sweep_ber(psdus, rates, snrs, seeds)
    with count_dispatches() as d_sw:
        errs = link.sweep_ber(psdus, rates, snrs, seeds,
                              profiles=profiles)
    t_sw = _timed(lambda: link.sweep_ber(psdus, rates, snrs, seeds,
                                         profiles=profiles))
    assert errs.shape == (len(rates), len(profiles), len(snrs),
                          len(seeds)), errs.shape

    flat_cols = [pi for pi, p in enumerate(profiles) if p == "flat"]
    flat_identical = all(
        np.array_equal(errs[:, pi], base) for pi in flat_cols)
    assert flat_identical, \
        "flat profile column diverged from the unprofiled sweep"

    floors, monotone = {}, {}
    for pi, p in enumerate(profiles):
        # BER per SNR point, averaged over rates and seeds
        ber = errs[:, pi].sum(axis=(0, 2)) \
            / (len(rates) * len(seeds) * bits_total)
        floors[p] = float(ber[-1])
        bound = CHANNEL_BER_ENVELOPES[p]
        assert ber[-1] <= bound, \
            (f"profile {p}: BER floor {ber[-1]:.4f} at "
             f"{snrs[-1]} dB exceeds its {bound} envelope")
        # counting noise on a small smoke grid: allow a 2e-3 rise
        monotone[p] = bool(np.all(np.diff(ber) <= 2e-3))
        assert monotone[p], f"profile {p}: BER rose with SNR: {ber}"

    n_points = len(rates) * len(snrs) * len(seeds) * len(profiles)
    out = {
        "frames": n_frames, "frame_bytes": n_bytes,
        "rates": list(rates), "snrs": list(snrs),
        "seeds": list(seeds), "profiles": list(profiles),
        "points": n_points,
        "dispatches_sweep": d_sw.total,
        "dispatch_times_ms_sweep": d_sw.times_ms(),
        "t_sweep_s": round(t_sw, 4),
        "points_per_s_sweep": round(n_points / t_sw, 2),
        "flat_identical": flat_identical,
        "envelopes": {p: CHANNEL_BER_ENVELOPES[p] for p in profiles},
    }
    for p, v in floors.items():
        out[f"ber_floor_{p}"] = round(v, 6)
    return out


def streaming_stats(n_frames=16, n_bytes=12, snr_db=30.0,
                    chunk_len=4096, frame_len=1024, k=8,
                    trace_path=None):
    """An N-frame continuous stream through the chunked streaming
    receiver vs the per-capture oracle over the same detected windows:
    dispatch counts (instrumented counter — the O(chunks) vs O(frames)
    collapse), wall times, samples/s, the in-flight depth gauge, and
    a frame-for-frame identity gate (every emitted start must hit the
    synthesizer's ground truth; every RxResult must be bit-identical
    to the oracle's). ``check_fcs=True`` so the masked-CRC tail rides
    the measurement. Per-chunk/per-dispatch latency lands as p50/p99
    blocks from the telemetry histogram layer (``latency_ms_*``), and
    ``trace_path`` — when given — additionally records one streaming
    pass as a Chrome trace there (chunk/decode spans, in-flight and
    carry-depth counter tracks, compile events; summarize with
    tools/trace_report.py). Returns a flat dict."""
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils import telemetry
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(17)
    mbps = (sorted(RATES) * (-(-n_frames // len(RATES))))[:n_frames]
    psdus = [rng.integers(0, 256, n_bytes).astype(np.uint8)
             for _ in range(n_frames)]
    stream, starts = link.stream_many(
        psdus, mbps, snr_db=snr_db, cfo=1e-4, delay=60, seed=8,
        add_fcs=True, tail=frame_len)
    kw = dict(chunk_len=chunk_len, frame_len=frame_len,
              max_frames_per_chunk=k, check_fcs=True)

    from ziria_tpu.utils import programs

    # collect() spans the counted run AND the timed repeats: the
    # per-chunk latency histograms see chunks x repeats samples; the
    # observatory wraps both paths so the chunk-scan and decode
    # programs contribute their compiled cost to the per-site roofline
    with programs.observing() as obs:
        with telemetry.collect() as reg_pc:
            with count_dispatches() as d_pc:
                res_p, st_p = framebatch.receive_stream(
                    stream, streaming=False, **kw)
            t_pc = _timed(lambda: framebatch.receive_stream(
                stream, streaming=False, **kw))

        with telemetry.collect() as reg_st:
            with count_dispatches() as d_st:
                res_s, st_s = framebatch.receive_stream(
                    stream, streaming=True, **kw)
            t_st = _timed(lambda: framebatch.receive_stream(
                stream, streaming=True, **kw))

    roofline_by_site = _roofline_by_site(
        obs, [_latency_block(reg_pc), _latency_block(reg_st)],
        _device_kind())

    if trace_path:
        # one warm streaming pass under an exporting trace: spans +
        # counter tracks + (warm, so few) compile events — plus the
        # observatory's analytical site costs and the device peaks as
        # trace metadata, so tools/trace_report.py can print achieved
        # GB/s per span label straight off the file
        with telemetry.tracing(trace_path) as tr:
            framebatch.receive_stream(stream, streaming=True, **kw)
            tr.set_metadata("siteCosts", {
                s: {"flops": r["flops"],
                    "bytes_accessed": r["bytes_accessed"]}
                for s, r in roofline_by_site.items()})
            tr.set_metadata("deviceKind", _device_kind())
            tr.set_metadata("devicePeaks",
                            programs.peaks_for(_device_kind()))

    assert [f.start for f in res_s] == list(starts), \
        "streaming starts diverged from the synthesizer ground truth"
    # identity first (field for field, failures included), THEN the
    # all-decoded gate — a lane failing identically in both paths is
    # not a divergence and must not be reported as one
    assert len(res_p) == len(res_s) and all(
        a.start == b.start and a.result.ok == b.result.ok
        and a.result.crc_ok == b.result.crc_ok
        and a.result.rate_mbps == b.result.rate_mbps
        and a.result.length_bytes == b.result.length_bytes
        and np.array_equal(a.result.psdu_bits, b.result.psdu_bits)
        for a, b in zip(res_p, res_s)), \
        "streaming receive diverged from the per-capture path"
    assert all(f.result.ok and f.result.crc_ok for f in res_s), \
        "a stimulus frame failed to decode (identically in both paths)"

    n_samples = stream.shape[0]
    return {
        "frames": n_frames, "frame_bytes": n_bytes, "snr_db": snr_db,
        "stream_samples": n_samples, "chunks": st_s.chunks,
        "chunk_len": chunk_len, "frame_len": frame_len,
        "dispatches_percapture": d_pc.total,
        "dispatches_streaming": d_st.total,
        "dispatch_breakdown_streaming": dict(d_st.counts),
        "dispatch_times_ms_streaming": d_st.times_ms(),
        "dispatch_times_ms_percapture": d_pc.times_ms(),
        # distribution-level per-site latency (telemetry histograms):
        # "rx.stream_chunk" is the per-chunk p50/p99 the serving
        # harness will report against SLOs — not a summed mean
        "latency_ms_streaming": _latency_block(reg_st),
        "latency_ms_percapture": _latency_block(reg_pc),
        # per-site roofline from the compiled graphs: achieved GB/s /
        # GFLOP/s per dispatch site (rx.stream_chunk is the number the
        # serving work reports against the hardware ceiling)
        "roofline_by_site": roofline_by_site,
        "trace_path": trace_path,
        "max_in_flight": st_s.max_in_flight,
        "overflow_chunks": st_s.overflow_chunks,
        "t_percapture_s": round(t_pc, 4),
        "t_streaming_s": round(t_st, 4),
        "sps_percapture": round(n_samples / t_pc, 1),
        "sps_streaming": round(n_samples / t_st, 1),
        "bit_identical": True,
    }


def multi_stream_stats(n_streams=8, frames_per_stream=4, n_bytes=12,
                       snr_db=30.0, chunk_len=4096, frame_len=1024,
                       k=8, mesh_sizes=None):
    """S concurrent I/Q streams through the stream-axis fleet receiver
    (``framebatch.receive_streams`` + ``MultiStreamReceiver``) vs S
    independent single-stream receivers (the oracle): dispatch counts
    per chunk-step (<= 2 *independent of S* — asserted), aggregate
    samples/s, the active-streams gauge, lane-for-lane bit-identity
    per stream (results AND starts vs the synthesizer's ground
    truth), per-site latency distributions and roofline blocks, and
    — the scaling record — aggregate samples/s per dp mesh size
    (``sps_by_devices``: the unsharded run is the 1-device point,
    then ``frame_mesh(n)``-sharded fleets for every usable n in
    ``mesh_sizes``; identical per-device program, streams
    independent, so the sharded results are gated bit-identical
    too). Returns a flat dict."""
    import jax

    from ziria_tpu.backend import framebatch
    from ziria_tpu.parallel import batch as pbatch
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils import programs, telemetry
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(23)
    rates_all = sorted(RATES)
    psdus_per, rates_per = [], []
    for i in range(n_streams):
        rates = [rates_all[(i + j) % len(rates_all)]
                 for j in range(frames_per_stream)]
        rates_per.append(rates)
        psdus_per.append([rng.integers(0, 256, n_bytes)
                          .astype(np.uint8) for _ in rates])
    streams, starts = link.stream_many_multi(
        psdus_per, rates_per, snr_db=snr_db, cfo=1e-4, delay=60,
        seed=9, add_fcs=True, tail=frame_len)
    kw = dict(chunk_len=chunk_len, frame_len=frame_len,
              max_frames_per_chunk=k, check_fcs=True)
    n_samples = sum(int(s.shape[0]) for s in streams)

    def gate(res_a, res_b, what):
        assert [len(r) for r in res_a] == [len(r) for r in res_b], what
        for i in range(n_streams):
            assert [f.start for f in res_a[i]] == list(starts[i]), \
                f"{what}: stream {i} starts diverged from ground truth"
            for a, b in zip(res_a[i], res_b[i]):
                assert (a.start == b.start
                        and a.result.ok == b.result.ok
                        and a.result.crc_ok == b.result.crc_ok
                        and a.result.rate_mbps == b.result.rate_mbps
                        and a.result.length_bytes == b.result.length_bytes
                        and np.array_equal(a.result.psdu_bits,
                                           b.result.psdu_bits)), \
                    f"{what}: stream {i} diverged lane for lane"

    with programs.observing() as obs:
        with telemetry.collect() as reg_or:
            with count_dispatches() as d_or:
                res_o, st_o = framebatch.receive_streams(
                    streams, multi=False, **kw)
            t_or = _timed(lambda: framebatch.receive_streams(
                streams, multi=False, **kw))

        with telemetry.collect() as reg_ml:
            with count_dispatches() as d_ml:
                res_m, st_m = framebatch.receive_streams(
                    streams, multi=True, **kw)
            t_ml = _timed(lambda: framebatch.receive_streams(
                streams, multi=True, **kw))

    gate(res_m, res_o, "fleet vs S independent receivers")
    assert all(f.result.ok and f.result.crc_ok
               for r in res_m for f in r), \
        "a stimulus frame failed to decode (identically in both paths)"
    # the tentpole pin: <= 2 dispatches per chunk-step, S-free
    assert d_ml.total <= 2 * st_m.chunk_steps, \
        (dict(d_ml.counts), st_m)

    # aggregate samples/s per device count: the unsharded fleet is the
    # 1-device point; each usable mesh size reruns the SAME fleet with
    # the stream axis sharded over frame_mesh(n) and gates identity
    sps_by_devices = {"1": round(n_samples / t_ml, 1)}
    devs = jax.devices()
    if mesh_sizes is None:
        # the largest mesh the fleet can shard evenly over — on the
        # 8-virtual-device CPU box that is 8 for S=8 and 4 for the
        # smoke's S=4 (never silently no mesh point at all)
        usable = [n for n in range(2, len(devs) + 1)
                  if n_streams % n == 0]
        sizes = [max(usable)] if usable else []
    else:
        sizes = sorted(set(mesh_sizes))
    for n in sizes:
        if n <= 1 or n > len(devs) or n_streams % n:
            continue
        mesh = pbatch.frame_mesh(n)
        res_s, _st_s = framebatch.receive_streams(
            streams, multi=True, mesh=mesh, **kw)
        gate(res_s, res_m, f"sharded fleet (dp={n})")
        t_n = _timed(lambda _m=mesh: framebatch.receive_streams(
            streams, multi=True, mesh=_m, **kw))
        sps_by_devices[str(n)] = round(n_samples / t_n, 1)

    out = {
        "streams": n_streams, "frames_per_stream": frames_per_stream,
        "frame_bytes": n_bytes, "snr_db": snr_db,
        "stream_samples_total": n_samples,
        "chunk_steps": st_m.chunk_steps,
        "chunk_len": chunk_len, "frame_len": frame_len,
        "dispatches_oracle": d_or.total,
        "dispatches_multi": d_ml.total,
        "dispatch_breakdown_multi": dict(d_ml.counts),
        "dispatch_times_ms_multi": d_ml.times_ms(),
        "dispatch_times_ms_oracle": d_or.times_ms(),
        # the S-independence record, machine-checkable: dispatches per
        # chunk-step for THIS S (pinned <= 2 above)
        "dispatches_per_chunk_step": round(
            d_ml.total / max(st_m.chunk_steps, 1), 3),
        "max_active_streams": st_m.max_active_streams,
        "max_in_flight": st_m.max_in_flight,
        "overflow_chunks": st_m.overflow_chunks,
        "latency_ms_multi": _latency_block(reg_ml),
        "latency_ms_oracle": _latency_block(reg_or),
        "roofline_by_site": _roofline_by_site(
            obs, [_latency_block(reg_or), _latency_block(reg_ml)],
            _device_kind()),
        "t_oracle_s": round(t_or, 4),
        "t_multi_s": round(t_ml, 4),
        "sps_oracle": round(n_samples / t_or, 1),
        "sps_multi": round(n_samples / t_ml, 1),
        "sps_by_devices": sps_by_devices,
        "bit_identical": True,
    }
    ks = sorted(sps_by_devices, key=int)
    if len(ks) > 1:
        out["mesh_scaling"] = round(
            sps_by_devices[ks[-1]] / max(sps_by_devices["1"], 1e-9), 3)
        out["mesh_devices_max"] = int(ks[-1])
    return out


def resilience_stats(n_streams=4, frames_per_stream=3, n_bytes=12,
                     snr_db=30.0, chunk_len=4096, frame_len=1024,
                     k=8, seed=12):
    """Chaos run of the multi-stream fleet (ISSUE 12): the fleet is
    fed push-driven under an injected fault plan — transient scan and
    decode faults (retried), a dispatch-latency fault, a NaN slab into
    stream 0 (sanitize=True zero-and-quarantine, rejoin after 2 clean
    chunks), and a one-shot FATAL decode fault (degrade to the
    per-capture oracle) — asserting ZERO crashes, healthy-lane
    lane-for-lane bit-identity vs a fault-free run, no garbage
    emissions from the poisoned lane, full quarantine recovery
    (rejoined by stream end), and a checkpoint/restore roundtrip
    bit-identical to an uninterrupted receiver. Records
    retries/fallbacks/quarantines/sanitized counts and the fault rate
    per 100 chunk-steps. Returns a flat dict (metric:
    ``faults_recovered``)."""
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES
    from ziria_tpu.utils import faults, telemetry
    from ziria_tpu.utils.dispatch import count_dispatches

    rng = np.random.default_rng(29)
    rates_all = sorted(RATES)
    psdus_per, rates_per = [], []
    for i in range(n_streams):
        rates = [rates_all[(i + j) % len(rates_all)]
                 for j in range(frames_per_stream)]
        rates_per.append(rates)
        psdus_per.append([rng.integers(0, 256, n_bytes)
                          .astype(np.uint8) for _ in rates])
    # every stream spreads its frames ~3 chunks apart so the workload
    # spans several chunk-steps AND several decode dispatches: the
    # quarantine (on stream 0) gets clean chunks to rejoin across,
    # and the one-shot fatal decode fault has a later decode to hit
    streams, starts = link.stream_many_multi(
        psdus_per, rates_per, snr_db=snr_db, cfo=1e-4, delay=60,
        seed=11, add_fcs=True, tail=frame_len,
        gaps=[[9000] * (frames_per_stream - 1)] * n_streams)
    kw = dict(chunk_len=chunk_len, frame_len=frame_len,
              max_frames_per_chunk=k, check_fcs=True)

    # fault-free reference (also pre-compiles both fleet programs so
    # the chaos pass times recovery, not first-contact compiles)
    res_c, st_c = framebatch.receive_streams(streams, multi=True,
                                             **kw)
    per_c = res_c

    specs = (
        faults.FaultSpec("rx.stream_chunk_multi", "transient",
                         every=3),
        faults.FaultSpec("rx.stream_decode_multi", "transient",
                         every=4),
        faults.FaultSpec("rx.stream_chunk_multi", "delay",
                         calls=(4,), delay_s=0.02),
        faults.FaultSpec("rx.push.s0", "nan_slab", calls=(1,),
                         fraction=0.2),
        faults.FaultSpec("rx.stream_decode_multi", "fatal",
                         calls=(1,), count=1),
    )
    t0 = time.perf_counter()
    with telemetry.collect() as reg:
        with count_dispatches() as d:
            with faults.inject(*specs, seed=seed) as plan:
                msr = framebatch.MultiStreamReceiver(
                    n_streams, sanitize=True, rejoin_after=2, **kw)
                got = []
                step = chunk_len // 2
                hi = max(int(s.shape[0]) for s in streams)
                for a in range(0, hi, step):
                    got += msr.push_many(
                        [s[a: a + step] for s in streams])
                got += msr.flush()
    t_chaos = time.perf_counter() - t0
    # reaching here IS the first gate: zero process crashes
    per = [[] for _ in range(n_streams)]
    for i, fr in got:
        per[i].append(fr)

    # attribution: streams whose push seam a data fault actually hit
    corrupted = set()
    for site, kind, _idx in plan.fired:
        if site.startswith("rx.push.s"):
            corrupted.add(int(site[len("rx.push.s"):]))
    same = (lambda a, b: a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)
    for i in range(n_streams):
        if i in corrupted:
            # poisoned lane: every surviving frame must match the
            # clean run (dropped-while-quarantined, never garbage)
            clean_by_start = {f.start: f for f in per_c[i]}
            for f in per[i]:
                assert f.start in clean_by_start and same(
                    f.result, clean_by_start[f.start].result), \
                    f"stream {i} emitted garbage under chaos"
        else:
            # healthy lanes: lane-for-lane bit-identical
            assert [f.start for f in per[i]] == \
                [f.start for f in per_c[i]], \
                f"healthy stream {i} diverged under chaos"
            for a, b in zip(per[i], per_c[i]):
                assert same(a.result, b.result), \
                    f"healthy stream {i} diverged under chaos"
    stats = msr.stats
    assert stats.quarantined_streams == 0, \
        "a quarantined stream failed to rejoin"
    dropped = sum(len(per_c[i]) - len(per[i]) for i in corrupted)

    # checkpoint/restore roundtrip: bit-identical resumption
    sr1 = framebatch.StreamReceiver(**kw)
    cut = int(streams[1].shape[0]) // 2
    first = sr1.push(streams[1][:cut])
    state, drained = sr1.checkpoint()
    first += drained
    sr2 = framebatch.StreamReceiver(checkpoint=state, **kw)
    rest = sr2.push(streams[1][cut:])
    rest += sr2.flush()
    resumed = first + rest
    assert [f.start for f in resumed] == \
        [f.start for f in per_c[1]] and all(
            same(a.result, b.result)
            for a, b in zip(resumed, per_c[1])), \
        "checkpoint/restore resumption diverged"

    snap = reg.snapshot()
    fired_by_kind = {}
    for _s, kind, _i in plan.fired:
        fired_by_kind[kind] = fired_by_kind.get(kind, 0) + 1
    return {
        "streams": n_streams, "frames_per_stream": frames_per_stream,
        "frame_bytes": n_bytes,
        "chunk_steps": stats.chunk_steps,
        "faults_injected": plan.total_fired,
        "faults_recovered": plan.total_fired,   # zero crashes gated
        "faults_by_kind": fired_by_kind,
        "faults_per_100_steps": round(
            100.0 * plan.total_fired / max(stats.chunk_steps, 1), 1),
        "retries": snap.get("resilience.retries", 0),
        "recovered": snap.get("resilience.recovered", 0),
        "fallbacks": snap.get("resilience.fallbacks", 0),
        "sanitized": stats.sanitized,
        "quarantines": stats.quarantines,
        "quarantined_at_end": stats.quarantined_streams,
        "lane_blowups": stats.lane_blowups,
        "degraded": bool(stats.degraded),
        "frames_clean": sum(len(r) for r in per_c),
        "frames_chaos": sum(len(r) for r in per),
        "frames_dropped_quarantined": dropped,
        "corrupted_streams": sorted(corrupted),
        "dispatch_breakdown_chaos": dict(d.counts),
        "backoff_s": snap.get("resilience.backoff_seconds",
                              {"count": 0}),
        "t_chaos_s": round(t_chaos, 4),
        "healthy_bit_identical": True,
        "checkpoint_bit_identical": True,
        "zero_crashes": True,
    }


def serving_stats(n_sessions=12, n_lanes=8, frames_per_session=3,
                  n_bytes=12, snr_db=30.0, chunk_len=4096,
                  frame_len=1024, k=8, seed=17):
    """Chaos SLO run of the continuous-batching server (ISSUE 13):
    ``n_sessions`` clients (misbehaving ones included: a NaN-slab
    poisoner, a flood, a stall, an oversized-slab violator) served
    over ``n_lanes`` device lanes under a deterministic fake clock —
    three passes, all gated:

    1. **budget pass** (all-healthy): dispatches ≤ 2 per chunk-step
       independent of session count, pinned under
       ``dispatch.no_recompile`` across admission/close churn;
       sustained aggregate samples/s measured here.
    2. **SLO pass** (misbehaving clients, no chaos): the stall
       session is DEADLINE-SHED (counted, attributed), session 0 is
       EVICTED mid-stream and restored from its checkpoint into a
       fresh lane (bit-identical resumption — the acceptance round
       trip), the NaN session quarantines without garbage, and every
       healthy session's frames are bit-identical to a lone
       single-stream receiver.
    3. **chaos pass**: the same load under injected transient+fatal+
       hang+delay dispatch faults — ZERO crashes, healthy sessions
       still bit-identical, every shed/evict/restore accounted
       exactly in the telemetry counters.

    p50/p99 chunk latency (the SLO numbers) come off the server's own
    registry (``serve.chunk_seconds`` + the per-dispatch site
    histograms). Returns a flat dict (metric: ``sps_serving``)."""
    import contextlib

    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy.wifi import rx as _rx
    from ziria_tpu.runtime import serve
    from ziria_tpu.utils import dispatch, faults
    from ziria_tpu.utils.dispatch import count_dispatches

    misbehave = {1: "nan", 2: "flood", 3: "stall", 4: "oversize"}
    clients = serve.synth_load(
        n_sessions, frames_per_session, n_bytes=n_bytes,
        snr_db=snr_db, seed=seed, tail=frame_len,
        misbehave=misbehave)
    geo = dict(chunk_len=chunk_len, frame_len=frame_len,
               max_frames_per_chunk=k, check_fcs=True)
    oracle = {}
    for c in clients:
        oracle[c.sid], _ = framebatch.receive_stream(c.stream, **geo)

    def same(a, b):
        return (a.start == b.start and a.result.ok == b.result.ok
                and a.result.rate_mbps == b.result.rate_mbps
                and a.result.length_bytes == b.result.length_bytes
                and np.array_equal(a.result.psdu_bits,
                                   b.result.psdu_bits)
                and a.result.crc_ok == b.result.crc_ok)

    stall_slo = 8.0
    evict_sid = clients[0].sid

    def drive(cs, specs=None, chaos_seed=seed, stall=True,
              evict=True, watchdog=None):
        # the watchdog is only armed for the chaos pass (its hang
        # spec needs cutting): on a cold CPU cache a first-contact
        # XLA compile legitimately exceeds any hang-scale timeout,
        # and the earlier passes warm the caches
        cfg = serve.ServeConfig(
            n_lanes=n_lanes, queue_cap=n_sessions, sanitize=True,
            default_slo_s=None, watchdog_s=watchdog, **geo)
        clock = [0.0]
        srv = serve.ServeRuntime(cfg, clock=lambda: clock[0])
        frames = {c.sid: [] for c in cs}

        def collect(pairs):
            for sid, f in pairs:
                frames[sid].append(f)

        restored = not evict
        closed = set()
        todo = {c.sid: list(c.schedule) for c in cs}
        pending = {c.sid: c for c in cs}
        t0 = time.perf_counter()
        with contextlib.ExitStack() as stack:
            plan = stack.enter_context(
                faults.inject(*specs, seed=chaos_seed)) \
                if specs else None
            stack.enter_context(srv)
            for tick in range(400):
                for sid in list(pending):
                    c = pending[sid]
                    slo = stall_slo if (stall and c.mode == "stall") \
                        else None
                    r = srv.connect(sid, slo_s=slo)
                    if r.admitted or r.queued:
                        del pending[sid]
                for c in cs:
                    if c.sid in pending or c.sid in closed:
                        continue
                    q = todo[c.sid]
                    while q and q[0][0] <= tick:
                        r_ = srv.submit(c.sid, q[0][1])
                        if r_.accepted or not r_.retry_after_s:
                            q.pop(0)
                        else:
                            break
                collect(srv.step())
                if evict and not restored and tick >= 2 \
                        and evict_sid not in pending:
                    blob, ems, staged = srv.evict(evict_sid)
                    collect(ems)
                    r = srv.connect(evict_sid, checkpoint=blob)
                    assert r.admitted or r.queued, r
                    for s_ in staged:
                        srv.submit(evict_sid, s_)
                    restored = True
                for c in cs:
                    if (c.sid not in pending and c.sid not in closed
                            and not todo[c.sid] and c.mode != "stall"
                            and (c.sid != evict_sid or restored)):
                        if srv.is_active(c.sid):
                            collect(srv.close(c.sid))
                            closed.add(c.sid)
                        elif c.sid in srv._gone:
                            closed.add(c.sid)  # shed — accounted
                clock[0] += 1.0
                if (not pending and not any(todo.values())
                        and all(c.sid in closed or c.mode == "stall"
                                for c in cs)
                        and (not stall or clock[0] > stall_slo + 2)):
                    break
            collect(srv.drain())
        return srv, frames, time.perf_counter() - t0, plan

    def gate(frames, chaos=False):
        for c in clients:
            got, want = frames[c.sid], oracle[c.sid]
            if c.mode in ("nan", "stall"):
                # poisoned/shed sessions: surviving frames match the
                # clean run at their start — dropped, never garbage
                by_start = {f.start: f for f in want}
                for f in got:
                    assert f.start in by_start and same(
                        f, by_start[f.start]), \
                        f"{c.sid} emitted garbage ({c.mode})"
            else:
                assert len(got) == len(want) and all(
                    same(a, b) for a, b in zip(got, want)), \
                    f"healthy session {c.sid} diverged" \
                    f"{' under chaos' if chaos else ''}"

    # -- pass 1: SLO run (misbehaving clients, shed + evict/restore).
    # Runs first: it also pays the two fleet compiles, so the budget
    # pass below genuinely pins zero cache growth
    srv_s, frames_s, _t_slo, _ = drive(clients)
    st_s = srv_s.stats()
    gate(frames_s)
    shed_sids = {s for s, _r, _t in st_s.shed_log}
    assert clients[3].sid in shed_sids, "stall session was not shed"
    assert st_s.evicted == 1 and st_s.restored == 1
    assert st_s.rejected_slabs >= 1, "oversized slab not rejected"
    assert st_s.admitted == st_s.closed + st_s.evicted + len(
        [1 for _s, r, _t in st_s.shed_log if r == "deadline"]), \
        "session accounting does not balance"

    # -- pass 2: all-healthy dispatch-budget pin ------------------------
    # the raw arrival schedules (no misbehavior rewrite), same sids,
    # same streams: admission/close churn with every lane healthy,
    # and the caches warmed by pass 1 — zero growth is the pin
    healthy = serve.synth_load(
        n_sessions, frames_per_session, n_bytes=n_bytes,
        snr_db=snr_db, seed=seed, tail=frame_len)
    total_samples = sum(int(c.stream.shape[0]) for c in clients)
    with dispatch.no_recompile(_rx._jit_stream_chunk_multi,
                               _rx._jit_stream_decode_multi):
        with count_dispatches() as d_b:
            srv_b, frames_b, t_budget, _ = drive(
                healthy, stall=False, evict=False)
    st_b = srv_b.stats()
    assert d_b.total <= 2 * st_b.chunk_steps, \
        (dict(d_b.counts), st_b.chunk_steps)
    for c in healthy:
        got, want = frames_b[c.sid], oracle[c.sid]
        assert len(got) == len(want) and all(
            same(a, b) for a, b in zip(got, want)), \
            f"budget pass: session {c.sid} diverged"

    # -- pass 3: chaos --------------------------------------------------
    specs = (
        faults.FaultSpec("rx.stream_chunk_multi", "transient",
                         every=5),
        faults.FaultSpec("rx.stream_decode_multi", "transient",
                         every=4),
        faults.FaultSpec("rx.stream_chunk_multi", "delay",
                         calls=(3,), delay_s=0.02),
        faults.FaultSpec("rx.stream_chunk_multi", "hang",
                         calls=(6,), delay_s=10.0),
        faults.FaultSpec("rx.stream_decode_multi", "fatal",
                         calls=(2,), count=1),
    )
    srv_c, frames_c, _t_chaos, plan = drive(clients, specs=specs,
                                            watchdog=2.0)
    st_c = srv_c.stats()
    gate(frames_c, chaos=True)       # zero crashes = reaching here
    assert plan.total_fired > 0
    fired_by_kind = {}
    for _s, kind, _i in plan.fired:
        fired_by_kind[kind] = fired_by_kind.get(kind, 0) + 1
    snap = srv_c.registry.snapshot()
    lat = srv_c.registry.find("serve.chunk_seconds")

    return {
        "sessions": n_sessions, "lanes": n_lanes,
        "frames_per_session": frames_per_session,
        "frame_bytes": n_bytes, "snr_db": snr_db,
        "chunk_len": chunk_len, "frame_len": frame_len,
        "stream_samples_total": total_samples,
        "chunk_steps_budget": st_b.chunk_steps,
        "dispatches_budget": d_b.total,
        "dispatches_per_chunk_step": round(
            d_b.total / max(st_b.chunk_steps, 1), 3),
        "sps_serving": round(total_samples / t_budget, 1),
        "t_serve_s": round(t_budget, 4),
        "chunk_latency_ms": lat.summary(scale=1e3, ndigits=4)
        if lat is not None else {"count": 0},
        "p99_chunk_ms": (lat.summary(scale=1e3, ndigits=4)
                         .get("p99") if lat is not None else None),
        "latency_ms_sites": _latency_block(srv_c.registry),
        "admitted": st_c.admitted, "closed": st_c.closed,
        "shed": st_s.shed, "evicted": st_s.evicted,
        "restored": st_s.restored,
        "rejected_slabs": st_s.rejected_slabs,
        "shed_log": [[s, r, t] for s, r, t in st_s.shed_log],
        "frames_served": st_c.frames,
        "faults_injected": plan.total_fired,
        "faults_by_kind": fired_by_kind,
        "retries": snap.get("resilience.retries", 0),
        "recovered": snap.get("resilience.recovered", 0),
        "degraded": bool(srv_c._rx.stats.degraded),
        # from the registry, not the recycled lane health (a closed
        # session's lane resets; the counter is the durable record)
        "quarantines": snap.get("resilience.quarantines", 0),
        "healthy_bit_identical": True,
        "evict_restore_bit_identical": True,
        "zero_crashes": True,
    }


def viterbi_breakdown(B=128, n_bytes=1000, rate_mbps=54, k1=4, k2=12):
    """ACS-only vs traceback-only vs front-end-only vs full decode at
    the bench shape — the answer to bench.py's open question ("the
    decode is dependency-chain-bound, but WHERE?"): the decompose
    stage bounds front end vs Viterbi; this splits the Viterbi into
    its two Pallas kernels. Each piece is timed with the same
    marginal-K device-loop method as the headline (runtime-zero data
    feedback so the body cannot be hoisted), so the four numbers are
    directly comparable. Returns a flat dict."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.ops import viterbi_pallas as vp
    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols

    rate = RATES[rate_mbps]
    n_sym = n_symbols(n_bytes, rate)
    rng = np.random.default_rng(19)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, rate_mbps))
    frames = jnp.asarray(np.broadcast_to(
        frame, (B,) + frame.shape).copy())
    interpret = jax.default_backend() != "tpu"
    n_bits = n_sym * rate.n_dbps

    def marginal(loop, *args):
        t1 = _timed(loop, *args, jnp.int32(k1))
        t2 = _timed(loop, *args, jnp.int32(k2))
        return max((t2 - t1) / (k2 - k1), 1e-9)

    @jax.jit
    def front_k(f, k):
        def body(_i, carry):
            s, acc = carry
            dep = jax.vmap(
                lambda x: rx._decode_front(x, rate, n_sym))(f + s)
            return dep[0, 0, 0] * 1e-30, acc + dep.sum() * 1e-30
        return jax.lax.fori_loop(
            0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

    dep0 = jax.jit(jax.vmap(
        lambda x: rx._decode_front(x, rate, n_sym)))(frames)
    # the ACS kernel's real input: lane tiles at the UNROLL multiple
    tiles, _b = vp._to_tiles(jnp.asarray(dep0))
    T = tiles.shape[1]
    Tp = -(-T // vp.UNROLL) * vp.UNROLL
    tiles = jnp.pad(tiles, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))

    @jax.jit
    def acs_k(x, k):
        def body(_i, carry):
            s, acc = carry
            _dec, metrics = vp._acs_tiles(x + s, interpret)
            return metrics[0, 0, 0] * 1e-30, acc + metrics.sum() * 1e-30
        return jax.lax.fori_loop(
            0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

    dec0, met0 = jax.jit(
        lambda x: vp._acs_tiles(x, interpret))(tiles)

    @jax.jit
    def tb_k(d, m, k):
        def body(_i, carry):
            s, acc = carry
            bits = vp._traceback_tiles(d, m + s, interpret)
            f = bits[0, 0, 0, 0].astype(jnp.float32)
            return f * 1e-30, acc + f * 1e-30
        return jax.lax.fori_loop(
            0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

    @jax.jit
    def full_k(f, k):
        def body(_i, carry):
            s, acc = carry
            bits = rx.decode_data_batch(
                f + s, rate, n_sym, 8 * n_bytes)[0]
            s2 = bits[0, 0].astype(jnp.float32) * 1e-30
            return s2, acc + bits.sum() * 1e-30
        return jax.lax.fori_loop(
            0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

    t_front = marginal(front_k, frames)
    t_acs = marginal(acs_k, tiles)
    t_tb = marginal(tb_k, dec0, met0)
    t_full = marginal(full_k, frames)
    return {
        "batch": B, "frame_bytes": n_bytes, "rate_mbps": rate_mbps,
        "frame_len": int(frame.shape[0]), "trellis_steps": int(n_bits),
        "t_front_s": round(t_front, 6),
        "t_acs_s": round(t_acs, 6),
        "t_traceback_s": round(t_tb, 6),
        "t_full_s": round(t_full, 6),
        "front_frac": round(t_front / t_full, 3),
        "acs_frac": round(t_acs / t_full, 3),
        "traceback_frac": round(t_tb / t_full, 3),
    }


# the decode-core lever matrix viterbi_kernel_stats measures: kwargs
# for rx.decode_data_batch per lever (radix-4 ACS, quantized metrics,
# the fused in-kernel front end, and the stack)
VITERBI_LEVERS = (
    ("base", {}),
    ("radix4", {"viterbi_radix": 4}),
    ("int16", {"viterbi_metric": "int16"}),
    ("int16_radix4", {"viterbi_metric": "int16", "viterbi_radix": 4}),
    ("int8_lut", {"viterbi_metric": "int8"}),
    ("fused_demap", {"fused_demap": True}),
    ("fused_demap_radix4", {"fused_demap": True, "viterbi_radix": 4}),
)


def viterbi_kernel_stats(B=128, n_bytes=1000, rate_mbps=54,
                         k1=4, k2=12, noise_sigma=0.35,
                         levers=VITERBI_LEVERS):
    """Per-lever decode-core stats (ISSUE 6): samples/s + marginal
    step time for each lever of the rebuilt ACS (radix-4, int16,
    int8+LUT, fused demap front end, and the radix-4+fused stack),
    with dispatch counts and per-site wall times from utils/dispatch
    and the identity gates the levers promise:

    - every lever decodes the clean corpus to the TX bits (the bench
      correctness gate, green for int8 too);
    - on NOISY inputs, radix-4 / fused levers are gated BIT-IDENTICAL
      against the float32 radix-2 oracle's output (their contract),
      int16 against its own radix-2 twin, and int8 — whose contract is
      statistical — against the f32 oracle's BER (delta recorded).

    Returns a flat dict (bench.py's viterbi_kernel_stats stage stores
    it verbatim and annotates roofline percentages per lever)."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.dispatch import count_dispatches, timed

    rate = RATES[rate_mbps]
    n_sym = n_symbols(n_bytes, rate)
    n_psdu_bits = 8 * n_bytes
    rng = np.random.default_rng(21)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, rate_mbps))
    from ziria_tpu.utils.bits import bytes_to_bits
    want = np.asarray(bytes_to_bits(psdu))
    frames = jnp.asarray(np.broadcast_to(
        frame, (B,) + frame.shape).copy())
    # a small noisy batch at operating SNR for the oracle gates (the
    # clean batch decodes perfectly under EVERY lever, which gates
    # correctness but cannot distinguish bit-identity from luck)
    Bn = min(B, 8)
    noisy = (np.broadcast_to(frame, (Bn,) + frame.shape)
             + rng.normal(0, noise_sigma, (Bn,) + frame.shape)
             ).astype(np.float32)
    noisy = jnp.asarray(noisy)

    def decode(f, **kw):
        return rx.decode_data_batch(f, rate, n_sym, n_psdu_bits,
                                    **kw)[0]

    out = {"batch": B, "frame_bytes": n_bytes, "rate_mbps": rate_mbps,
           "frame_len": int(frame.shape[0]),
           "noise_sigma": noise_sigma}
    noisy_bits = {}
    with count_dispatches() as d:
        for name, kw in levers:
            with timed(f"viterbi.{name}"):
                got = np.asarray(jax.jit(
                    lambda f, _kw=kw: decode(f, **_kw))(frames))
            assert np.array_equal(got[0], want) \
                and np.array_equal(got[-1], want), \
                f"{name} failed the clean correctness gate"
            noisy_bits[name] = np.asarray(jax.jit(
                lambda f, _kw=kw: decode(f, **_kw))(noisy))
    out["dispatch_times_ms"] = d.times_ms()
    out["dispatches"] = d.total

    # identity gates on the noisy corpus. radix4 is PROVABLY identical
    # to the oracle (same renorm cadence, same expression trees), so
    # its gate is exact. The fused levers share the expression trees
    # but renorm at the symbol-block cadence instead of every UNROLL
    # steps — f32 renorm rounding can in principle flip a sub-epsilon
    # near-tie at operating noise, so their gate records the mismatch
    # fraction and asserts it stays within a vanishing budget instead
    # of erroring the whole stage on one flipped razor-edge bit.
    base = noisy_bits["base"]
    for name in ("radix4",):
        if name not in noisy_bits:
            continue                   # lever not in this run's matrix
        same = bool(np.array_equal(noisy_bits[name], base))
        out[f"{name}_bit_identical"] = same
        assert same, f"{name} diverged from the float32 radix-2 oracle"
    for name in ("fused_demap", "fused_demap_radix4"):
        if name not in noisy_bits:
            continue
        frac = float((noisy_bits[name] != base).mean())
        out[f"{name}_bit_identical"] = frac == 0.0
        out[f"{name}_mismatch_frac"] = round(frac, 8)
        assert frac <= 1e-3, \
            f"{name} diverged from the unfused front end ({frac:.2e})"
    if "int16_radix4" in noisy_bits and "int16" in noisy_bits:
        same16 = bool(np.array_equal(noisy_bits["int16_radix4"],
                                     noisy_bits["int16"]))
        out["int16_radix4_bit_identical"] = same16
        assert same16, "int16 radix-4 diverged from its radix-2 twin"
    ber_f32 = float((base != want[None]).mean())
    out["ber_f32"] = round(ber_f32, 6)
    if "int8_lut" in noisy_bits:
        ber_i8 = float((noisy_bits["int8_lut"] != want[None]).mean())
        out["ber_int8"] = round(ber_i8, 6)
        out["ber_int8_delta"] = round(ber_i8 - ber_f32, 6)
        # the int8 contract is its BER ENVELOPE (same bound as
        # tests/test_viterbi_radix4.test_int8_ber_guard): a saturation
        # or LUT regression must fail the stage, not report green
        assert abs(ber_i8 - ber_f32) < 0.05 * max(ber_f32, 1e-9) + 4e-3, \
            f"int8 BER {ber_i8:.4f} outside envelope vs f32 {ber_f32:.4f}"
        out["int8_ber_gate"] = True

    # per-lever marginal step time (the headline's tunnel-cancelling
    # K-spread method)
    for name, kw in levers:
        @jax.jit
        def loop(x, k, _kw=kw):
            def body(_i, carry):
                s, acc = carry
                bits = decode(x + s, **_kw)
                s2 = bits[0, 0].astype(jnp.float32) * 1e-30
                return s2, acc + bits.sum() * 1e-30
            return jax.lax.fori_loop(
                0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

        t_1 = _timed(loop, frames, jnp.int32(k1))
        t_2 = _timed(loop, frames, jnp.int32(k2))
        t_step = max((t_2 - t_1) / (k2 - k1), 1e-9)
        out[f"t_step_{name}_s"] = round(t_step, 6)
        out[f"sps_{name}"] = round(B * frame.shape[0] / t_step, 1)
    for name, _kw in levers[1:]:
        out[f"{name}_over_base"] = round(
            out[f"t_step_{name}_s"] / out["t_step_base_s"], 3)
    return out


def fused_mixed_stats(B=64, n_bytes=100, noise_sigma=0.3, k1=2, k2=6,
                      frame_len=1024, stream_k=8):
    """The rate-SWITCHED fused-demap lever (ISSUE 20) — the mixed
    `lax.switch` decode every streaming/fleet surface runs, with the
    8-rate stacked constant bank row-selected in-kernel:

    - identity gate: `rx.decode_data_mixed(fused_demap=True)` vs the
      unfused mixed oracle on a noisy all-8-rates batch, per-lane
      real-prefix mismatch fraction recorded and asserted vanishing
      (the radix-4 stack too — same budget as the known-rate fused
      levers in `viterbi_kernel_stats`);
    - marginal step time (K-spread) for the unfused and fused mixed
      decode -> `sps_fused_mixed` / `sps_unfused_mixed` (bench.py's
      fused_mixed stage headline);
    - the observatory's before/after on `rx._jit_stream_decode` at
      the suite-shared geometry: compiled `bytes_accessed` unfused vs
      fused, asserted STRICTLY lower fused (the roofline claim — the
      LLR round-trip leaves the program, the constant bank it buys is
      smaller).

    Returns a flat dict (bench.py stores it verbatim)."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import (RATE_MBPS_ORDER, RATES,
                                           n_symbols)
    from ziria_tpu.utils import programs

    rng = np.random.default_rng(33)
    mbps = (list(RATE_MBPS_ORDER) * (-(-B // 8)))[:B]
    n_sym_b = rx._sym_bucket(max(n_symbols(n_bytes, RATES[m])
                                 for m in mbps))
    need = rx.FRAME_DATA_START + 80 * n_sym_b
    frames = np.zeros((B, need, 2), np.float32)
    for i, m in enumerate(mbps):
        psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        ln = min(len(s), need)
        frames[i, :ln] = s[:ln]
    frames = jnp.asarray(
        frames + rng.normal(0, noise_sigma, frames.shape)
        .astype(np.float32))
    ridx = jnp.asarray([rx.RATE_INDEX[m] for m in mbps], jnp.int32)
    nb_host = np.asarray([n_symbols(n_bytes, RATES[m])
                          * RATES[m].n_dbps for m in mbps], np.int32)
    nbits = jnp.asarray(nb_host)

    def dec(fused, **kw):
        return np.asarray(jax.jit(lambda f: rx.decode_data_mixed(
            f, ridx, nbits, n_sym_b, fused_demap=fused, **kw))(frames))

    base = dec(False)
    # compare the real prefix per lane: past nbits both paths decode
    # zero-LLR erasures whose tie-broken bits carry no contract
    mask = np.arange(base.shape[1])[None, :] < nb_host[:, None]
    out = {"batch": B, "frame_bytes": n_bytes,
           "n_sym_bucket": n_sym_b, "noise_sigma": noise_sigma,
           "rates": sorted(set(mbps))}
    for name, kw in (("fused_mixed", {}),
                     ("fused_mixed_radix4", {"viterbi_radix": 4})):
        got = dec(True, **kw)
        frac = float((got != base)[mask].mean())
        out[f"{name}_bit_identical"] = frac == 0.0
        out[f"{name}_mismatch_frac"] = round(frac, 8)
        assert frac <= 1e-3, \
            f"{name} diverged from the unfused mixed decode ({frac:.2e})"

    # marginal mixed-decode step time, fused vs unfused (the
    # K-spread method of viterbi_kernel_stats)
    for name, fused in (("unfused_mixed", False),
                        ("fused_mixed", True)):
        @jax.jit
        def loop(x, kk, _f=fused):
            def body(_i, carry):
                s, acc = carry
                bits = rx.decode_data_mixed(x + s, ridx, nbits,
                                            n_sym_b, fused_demap=_f)
                s2 = bits[0, 0].astype(jnp.float32) * 1e-30
                return s2, acc + bits.sum() * 1e-30
            return jax.lax.fori_loop(
                0, kk, body, (jnp.float32(0), jnp.float32(0)))[1]

        t_1 = _timed(loop, frames, jnp.int32(k1))
        t_2 = _timed(loop, frames, jnp.int32(k2))
        t_step = max((t_2 - t_1) / (k2 - k1), 1e-9)
        out[f"t_step_{name}_s"] = round(t_step, 6)
        out[f"sps_{name}"] = round(B * need / t_step, 1)
    out["fused_over_unfused"] = round(
        out["t_step_fused_mixed_s"] / out["t_step_unfused_mixed_s"], 3)

    # before/after compiled bytes on THE streaming decode program at
    # the suite-shared geometry (tests/test_programs.py's pinned
    # site): the acceptance claim is strictly-lower fused
    sym_b = rx._sym_bucket(
        max(1, (frame_len - rx.FRAME_DATA_START) // 80))
    need_b = rx.FRAME_DATA_START + 80 * sym_b
    S = jax.ShapeDtypeStruct
    segs = S((stream_k, need_b, 2), np.float32)
    row = S((stream_k,), np.int32)
    for name, fused in (("unfused", False), ("fused", True)):
        c = programs.cost_of(
            rx._jit_stream_decode(sym_b, None, None, 2, False, fused),
            segs, row, row, row, row)
        out[f"stream_decode_bytes_{name}"] = c.get("bytes_accessed")
        out[f"stream_decode_flops_{name}"] = c.get("flops")
    b_un = out["stream_decode_bytes_unfused"]
    b_fu = out["stream_decode_bytes_fused"]
    out["stream_decode_bytes_delta"] = round(b_un - b_fu, 1)
    out["stream_decode_bytes_ratio"] = round(b_fu / b_un, 4)
    assert b_fu < b_un, \
        (f"fused stream decode bytes_accessed {b_fu} not below "
         f"unfused {b_un}")
    return out


def _multi_stream_mesh_main(argv):
    """``rx_dispatch_bench.py --multi-stream-mesh N [S]``: the mesh
    point of `multi_stream_stats` alone, in a process whose caller
    exported ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
    (virtual devices must exist BEFORE jax initializes — the
    `dryrun_multichip` mechanism). bench.py's multi_stream stage
    spawns this when its own process sees a single device, so the
    CPU smoke child still records aggregate samples/s vs mesh size.
    Prints ONE JSON object with `sps_by_devices`/`mesh_scaling`."""
    import jax

    n = int(argv[0]) if argv else 4
    n_streams = int(argv[1]) if len(argv) > 1 else n
    if os.environ.get("ZIRIA_TOOL_ALLOW_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    try:   # persistent cache: the probe's compiles are bench compiles
        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:
        pass
    if len(jax.devices()) < n:
        print(json.dumps({"error": f"{len(jax.devices())} device(s) "
                          f"visible, need {n} (export XLA_FLAGS="
                          f"--xla_force_host_platform_device_count="
                          f"{n})"}))
        return 1
    out = multi_stream_stats(n_streams=n_streams, frames_per_stream=2,
                             mesh_sizes=[n])
    print(json.dumps({k: out[k] for k in
                      ("streams", "sps_by_devices", "mesh_scaling",
                       "mesh_devices_max", "bit_identical",
                       "dispatches_per_chunk_step") if k in out}))
    return 0


def main():
    import jax

    if sys.argv[1:2] == ["--multi-stream-mesh"]:
        return _multi_stream_mesh_main(sys.argv[2:])
    smoke = os.environ.get("ZIRIA_TOOL_ALLOW_CPU") == "1"
    if smoke:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    if dev.platform == "cpu" and not smoke:
        print(json.dumps({"error": "no TPU visible"}))
        return 1

    out = {"platform": dev.platform,
           "device_kind": getattr(dev, "device_kind", "?")}
    if smoke:     # shrunk sizes: prove the path, not the number
        out["quantized"] = quantized_sweep(B=8, n_bytes=100, k1=2, k2=4)
        out["viterbi_breakdown"] = viterbi_breakdown(
            B=8, n_bytes=100, k1=2, k2=4)
        # fused levers dropped on CPU like bench.py's smoke stage: the
        # rate-54 fused kernel is a 216-step unrolled interpret-mode
        # program (minutes on CPU, milliseconds of Mosaic on chip)
        out["viterbi_kernel_stats"] = viterbi_kernel_stats(
            B=8, n_bytes=100, k1=2, k2=4, levers=VITERBI_LEVERS[:5])
        out["fused_mixed"] = fused_mixed_stats(
            B=8, n_bytes=24, k1=2, k2=4)
        out["mixed_dispatch"] = mixed_dispatch_stats(n_bytes=60)
        out["batched_acquire"] = batched_acquire_stats(n_bytes=60)
        out["link_loopback"] = link_loopback_stats(n_bytes=24)
        out["fused_link"] = fused_link_stats(n_bytes=24)
        out["ber_sweep"] = ber_sweep_stats(
            n_frames=8, n_bytes=24, rates=(6, 54), snrs=(3.0, 8.0))
        out["channel_sweep"] = channel_sweep_stats(
            n_frames=4, n_bytes=24, rates=(6, 54),
            profiles=("flat", "severe", "sco", "bursty", "hostile"))
        out["streaming_rx"] = streaming_stats(n_frames=8)
        out["multi_stream"] = multi_stream_stats(
            n_streams=4, frames_per_stream=2)
        out["resilience"] = resilience_stats(
            n_streams=4, frames_per_stream=2)
        out["serving"] = serving_stats(
            n_sessions=6, n_lanes=4, frames_per_session=2)
    else:
        out["quantized"] = quantized_sweep()
        out["viterbi_breakdown"] = viterbi_breakdown()
        out["viterbi_kernel_stats"] = viterbi_kernel_stats()
        out["fused_mixed"] = fused_mixed_stats()
        out["mixed_dispatch"] = mixed_dispatch_stats()
        out["mixed_dispatch_i16"] = mixed_dispatch_stats(
            viterbi_metric="int16")
        out["batched_acquire"] = batched_acquire_stats()
        out["link_loopback"] = link_loopback_stats()
        out["fused_link"] = fused_link_stats()
        out["ber_sweep"] = ber_sweep_stats()
        out["channel_sweep"] = channel_sweep_stats()
        out["streaming_rx"] = streaming_stats()
        out["multi_stream"] = multi_stream_stats()
        out["resilience"] = resilience_stats()
        out["serving"] = serving_stats()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
