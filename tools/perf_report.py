"""Perf-ledger report + regression gate over BENCH_TRAJECTORY.jsonl.

The trajectory holds ONE normalized flat record per completed bench
stage (`bench.py` appends them; schema below). This tool reads it:

    python tools/perf_report.py                     # trajectory table
    python tools/perf_report.py --diff RUN_A RUN_B  # two-run delta
    python tools/perf_report.py --check             # regression gate
    python tools/perf_report.py --backfill          # one-time history

- **table**: per (stage, metric) series across the most recent runs.
- **--diff A B**: per-stage delta between two run ids, regressions
  flagged against the tolerance.
- **--check**: compares the LATEST run against the most recent
  earlier run on the same platform (cpu smoke numbers never gate tpu
  numbers and vice versa); exits 1 when any shared stage regressed
  beyond tolerance — the precommit/CI gate (tools/precommit.sh).
  Fewer than two comparable runs exits 0 with a note: an empty ledger
  must not block a commit.
- **--backfill**: one-time import of the pre-ledger history, per
  FAMILY — the BENCH_r01..r05 artifacts (whose metric JSON is
  trapped inside a ``"tail"`` stderr string), BASELINE.json's pinned
  baseline, and BENCH_LIVE.json; plus the MULTICHIP_r01..r05 dryrun
  artifacts (device count + passed parallel-mode blocks per round,
  stage ``multichip``) — so the trajectory starts with every number
  the repo ever published, multichip scaling history included. Each
  family refuses to run twice (records carry
  ``source: backfill:*``).

Record schema (one JSON object per line):
  {"run_id", "unix", "stage", "metric", "value", "platform",
   "partial", "direction" ("higher"|"lower" = which way better),
   "source", ["resumed"], ["unit"], ["device_kind"], ["geometry"]}

Two records compare only when their ``device_kind`` fields agree
(absent matches absent): platform alone is too coarse once the
autotuner records per-device winners — a v5e ``autotune`` record must
never gate (or be gated by) a CPU smoke's numbers.

Regression = the newer value moving in the WORSE direction by more
than the tolerance (relative; ``--tolerance 0.1`` = 10%). Per-stage
overrides: ``--stage-tolerance streaming_rx=0.3`` (repeatable).
Records with ``partial`` or ``resumed`` still compare — a resumed
value equals its source measurement, so it can never flag.

Pure stdlib (no jax), so the gate runs while the TPU probe hangs —
the same discipline as jaxlint.
"""

import argparse
import calendar
import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATH = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")
DEFAULT_TOLERANCE = 0.10

#: built-in per-stage tolerance overrides (user --stage-tolerance
#: wins). The per-run numpy baseline swings with host load by design
#: (BENCH r4 measured 4.08-6.40 M sps for identical code; the pinned
#: denominator in BASELINE.json exists precisely because of this), so
#: it is recorded for contamination visibility but never gates.
BUILTIN_STAGE_TOLERANCE = {"numpy_baseline": 10.0}


# ------------------------------------------------------------- loading


def load_trajectory(path):
    """Every parseable record, in file order (garbage lines skipped —
    append-only jsonl survives a torn write)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("stage") \
                        and rec.get("metric") is not None \
                        and isinstance(rec.get("value"), (int, float)):
                    out.append(rec)
    except OSError:
        pass
    return out


def group_runs(records):
    """Ordered {run_id: {"t", "platform", "metrics"}} — runs sorted by
    first-seen record time; within a run the LATEST record per
    (stage, metric) wins (a resumed re-emission supersedes nothing
    newer)."""
    runs = {}
    for rec in records:
        rid = rec.get("run_id", "?")
        r = runs.setdefault(rid, {"t": rec.get("unix", 0),
                                  "platforms": set(), "metrics": {}})
        r["t"] = min(r["t"], rec.get("unix", r["t"]))
        if rec.get("platform"):
            r["platforms"].add(rec["platform"])
        key = (rec["stage"], rec["metric"])
        cur = r["metrics"].get(key)
        if cur is None or rec.get("unix", 0) >= cur.get("unix", 0):
            r["metrics"][key] = rec
    return dict(sorted(runs.items(), key=lambda kv: kv[1]["t"]))


def _main_platform(run):
    """A run's headline platform: tpu when any record is a chip
    record, else the single platform seen (cpu)."""
    p = run["platforms"]
    return "tpu" if "tpu" in p else (sorted(p)[0] if p else "?")


# ------------------------------------------------------------ diffing


def _regressed(old, new, direction, tol):
    """True when `new` is worse than `old` beyond the tolerance."""
    if direction == "lower":                # smaller is better
        if old == 0:
            return new > 0
        return (new - old) / abs(old) > tol
    if old == 0:
        return False
    return (old - new) / abs(old) > tol


def diff_runs(run_a, run_b, tolerance=DEFAULT_TOLERANCE,
              stage_tol=None):
    """Per-(stage, metric) delta rows between two grouped runs, plus
    the regressed subset. Rows: (stage, metric, a, b, delta_frac,
    flag) with delta_frac signed toward 'better' (+ = improved)."""
    stage_tol = {**BUILTIN_STAGE_TOLERANCE, **(stage_tol or {})}
    rows, regressions = [], []
    keys = sorted(set(run_a["metrics"]) | set(run_b["metrics"]))
    for key in keys:
        ra = run_a["metrics"].get(key)
        rb = run_b["metrics"].get(key)
        stage, metric = key
        if ra is None or rb is None:
            rows.append((stage, metric,
                         ra and ra["value"], rb and rb["value"],
                         None, "only in one run"))
            continue
        if ra.get("device_kind") != rb.get("device_kind"):
            # records are comparable only on the SAME device kind (a
            # v5e autotune winner must never gate a CPU smoke, even
            # when both runs are "cpu"-platform artifacts); absent
            # device_kind matches absent — legacy records keep gating
            rows.append((stage, metric, ra["value"], rb["value"],
                         None, "device_kind mismatch — not compared"))
            continue
        a, b = float(ra["value"]), float(rb["value"])
        direction = rb.get("direction", ra.get("direction", "higher"))
        tol = stage_tol.get(stage, tolerance)
        if a == 0:
            frac = None
        else:
            frac = (b - a) / abs(a)
            if direction == "lower":
                frac = -frac
        bad = _regressed(a, b, direction, tol)
        flag = f"REGRESSED (>{tol:.0%})" if bad else ""
        rows.append((stage, metric, a, b, frac, flag))
        if bad:
            regressions.append((stage, metric, a, b, frac))
    return rows, regressions


def format_diff(rid_a, rid_b, rows):
    lines = [f"{'stage':<22} {'metric':<24} {rid_a:>14} {rid_b:>14} "
             f"{'delta':>8}  flag"]
    for stage, metric, a, b, frac, flag in rows:
        fa = f"{a:.6g}" if a is not None else "-"
        fb = f"{b:.6g}" if b is not None else "-"
        fd = f"{frac:+.1%}" if frac is not None else "-"
        lines.append(f"{stage:<22} {metric:<24} {fa:>14} {fb:>14} "
                     f"{fd:>8}  {flag}")
    return "\n".join(lines)


def format_table(runs, last=6):
    """The whole-trajectory view: one row per (stage, metric), one
    column per recent run."""
    rids = list(runs)[-last:]
    keys = sorted({k for r in rids for k in runs[r]["metrics"]})
    head = f"{'stage':<22} {'metric':<24}" + "".join(
        f" {rid[-12:]:>14}" for rid in rids)
    lines = [head]
    for key in keys:
        stage, metric = key
        row = f"{stage:<22} {metric:<24}"
        for rid in rids:
            rec = runs[rid]["metrics"].get(key)
            row += (f" {rec['value']:>14.6g}" if rec else
                    f" {'-':>14}")
        lines.append(row)
    lines.append("runs: " + ", ".join(
        f"{rid} ({_main_platform(runs[rid])})" for rid in rids))
    return "\n".join(lines)


def check(runs, tolerance=DEFAULT_TOLERANCE, stage_tol=None):
    """The gate: latest run vs the most recent EARLIER run on the same
    platform. Returns (exit_code, report_text)."""
    rids = list(runs)
    if len(rids) < 2:
        return 0, "perf_report --check: fewer than two runs in the " \
                  "trajectory — nothing to gate"
    latest = rids[-1]
    plat = _main_platform(runs[latest])
    prev = None
    for rid in reversed(rids[:-1]):
        if _main_platform(runs[rid]) == plat:
            prev = rid
            break
    if prev is None:
        return 0, (f"perf_report --check: no earlier {plat} run to "
                   f"compare {latest} against — nothing to gate")
    rows, regressions = diff_runs(runs[prev], runs[latest],
                                  tolerance, stage_tol)
    text = format_diff(prev, latest, rows)
    if regressions:
        text += (f"\nperf_report: {len(regressions)} regression(s) "
                 f"beyond tolerance — failing the gate")
        return 1, text
    text += "\nperf_report: no regressions beyond tolerance"
    return 0, text


# ------------------------------------------------------------ backfill


def _tail_json(artifact):
    """The LAST parseable metric JSON inside a BENCH_r*.json 'tail'
    string (the stderr+stdout capture the driver wrapped the real
    output in) — or the artifact itself when it IS the metric JSON."""
    if "metric" in artifact and "tail" not in artifact:
        return artifact
    best = None
    for line in str(artifact.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            best = obj
    return best


def _iso_unix(s):
    try:
        return float(calendar.timegm(
            time.strptime(s, "%Y-%m-%dT%H:%M:%SZ")))
    except (ValueError, TypeError):
        return None


def backfill_records(repo=REPO):
    """The pre-ledger history as trajectory records. Undated artifacts
    get tiny ordinal 'unix' stamps (1, 2, ...) — obviously synthetic,
    but totally ordered, which is all the diffing needs."""
    out = []
    seq = [0]

    def stamp(t):
        seq[0] += 1
        return t if t else float(seq[0])

    def emit(rid, t, stage, metric, value, platform, src, **kv):
        if value is None:
            return
        out.append({"run_id": rid, "unix": stamp(t), "stage": stage,
                    "metric": metric, "value": value,
                    "platform": platform, "partial": bool(
                        kv.pop("partial", False)),
                    "direction": kv.pop("direction", "higher"),
                    "source": f"backfill:{src}", **kv})

    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as f:
                art = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        j = _tail_json(art) or {}
        rid = f"backfill:{name[:-5]}"
        t = j.get("captured_at_unix")
        emit(rid, t, "numpy_baseline", "sps",
             j.get("numpy_baseline_sps"), "cpu", name)
        if j.get("value") is not None:
            plat = j.get("platform") or (
                "tpu" if j.get("value_source") else "cpu")
            emit(rid, t, "result", "rx_sps", j["value"], plat, name,
                 partial=bool(j.get("partial")),
                 resumed=bool(j.get("value_source")),
                 unit="samples/s")
        lg = j.get("last_good")
        if isinstance(lg, dict) and lg.get("value") is not None:
            emit(f"{rid}:last_good", lg.get("captured_at_unix"),
                 "result", "rx_sps", lg["value"],
                 lg.get("platform", "tpu"), name, unit="samples/s")

    try:
        with open(os.path.join(repo, "BASELINE.json")) as f:
            pin = json.load(f).get("pinned_baseline") or {}
        emit("backfill:pinned_baseline", _iso_unix(pin.get("pinned_at")),
             "pinned_baseline", "sps", pin.get("sps"), "cpu",
             "BASELINE.json")
    except (OSError, json.JSONDecodeError):
        pass

    try:
        with open(os.path.join(repo, "BENCH_LIVE.json")) as f:
            live = json.load(f)
        emit("backfill:BENCH_LIVE", live.get("captured_at_unix"),
             "result", "rx_sps", live.get("value"),
             live.get("platform", "tpu"), "BENCH_LIVE.json",
             partial=bool(live.get("partial")), unit="samples/s")
        emit("backfill:BENCH_LIVE", live.get("captured_at_unix"),
             "numpy_baseline", "sps", live.get("numpy_baseline_sps"),
             "cpu", "BENCH_LIVE.json")
    except (OSError, json.JSONDecodeError):
        pass
    return out


def multichip_backfill_records(repo=REPO):
    """The committed MULTICHIP_r01..r05.json dryrun artifacts as
    trajectory records (stage ``multichip``): per round, the device
    count the dryrun ran on and the number of parallel-mode blocks
    that passed (the ``... ok,`` lines in the tail — dp, pp, sp and
    their products; 3 blocks in r01 grew to 7 by r05, the scaling
    history the new multi_stream stage extends). The dryruns execute
    on virtual CPU devices (``__graft_entry__.dryrun_multichip`` pins
    the platform), so the records carry ``platform: cpu``. Undated
    artifacts get the same tiny ordinal stamps as the bench family."""
    out = []
    seq = [0]
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r0*.json"))):
        name = os.path.basename(path)
        try:
            with open(path) as f:
                art = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if art.get("skipped"):
            continue
        seq[0] += 1
        rid = f"backfill:{name[:-5]}"
        # a passed block is a "<mode> ok, ..." line; require the word
        # (not a substring — "sp not ok" must not count) and refuse
        # negated forms a future partly-failing round might print
        blocks_ok = len([ln for ln in str(art.get("tail", ""))
                         .splitlines()
                         if re.search(r"\bok\b", ln)
                         and not re.search(r"\bnot ok\b", ln)])
        for metric, value in (("n_devices", art.get("n_devices")),
                              ("blocks_ok", blocks_ok)):
            if value is None:
                continue
            out.append({"run_id": rid, "unix": float(seq[0]),
                        "stage": "multichip", "metric": metric,
                        "value": value, "platform": "cpu",
                        "partial": not art.get("ok", False),
                        "direction": "higher",
                        "source": f"backfill:{name}"})
    return out


def backfill(path, repo=REPO):
    """Append the backfill records once PER FAMILY. Two independent
    one-shot families share the refuse-twice discipline: the bench
    history (BENCH_r*.json tails + BASELINE + BENCH_LIVE) and the
    multichip dryrun history (MULTICHIP_r*.json) — a trajectory that
    already holds a family's ``backfill:*`` records never gets that
    family again, but a later PR adding a NEW family (as ISSUE 11 did
    with multichip) can still land it exactly once. Returns
    (count, message)."""
    have = {str(rec.get("source", ""))
            for rec in load_trajectory(path)}
    # families are POSITIVELY identified by their source prefixes — a
    # future third family's records must never suppress these two
    done_bench = any(s.startswith(("backfill:BENCH",
                                   "backfill:BASELINE"))
                     and not s.startswith("backfill:MULTICHIP")
                     for s in have)
    done_multichip = any(s.startswith("backfill:MULTICHIP")
                         for s in have)
    recs = []
    if not done_bench:
        recs += backfill_records(repo)
    if not done_multichip:
        recs += multichip_backfill_records(repo)
    if not recs:
        return 0, "trajectory already backfilled (bench + multichip " \
                  "families) — refusing to duplicate history"
    with open(path, "a") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    skipped = [n for n, d in (("bench", done_bench),
                              ("multichip", done_multichip)) if d]
    msg = f"backfilled {len(recs)} record(s) into {path}"
    if skipped:
        msg += f" ({', '.join(skipped)} already present — skipped)"
    return len(recs), msg


# ---------------------------------------------------------------- CLI


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="perf_report",
        description="perf-ledger report + regression gate over "
                    "BENCH_TRAJECTORY.jsonl (docs/observability.md)")
    ap.add_argument("--path", default=DEFAULT_PATH,
                    help="trajectory file (default: repo ledger)")
    ap.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                    help="delta table between two run ids")
    ap.add_argument("--check", action="store_true",
                    help="regression gate: latest vs previous "
                         "same-platform run; exit 1 on regression")
    ap.add_argument("--backfill", action="store_true",
                    help="one-time import of the pre-ledger artifacts")
    ap.add_argument("--tolerance", type=float,
                    default=DEFAULT_TOLERANCE,
                    help="relative regression tolerance "
                         f"(default {DEFAULT_TOLERANCE})")
    ap.add_argument("--stage-tolerance", action="append", default=[],
                    metavar="STAGE=TOL",
                    help="per-stage tolerance override (repeatable)")
    ap.add_argument("--last", type=int, default=6,
                    help="runs shown in the trajectory table")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    stage_tol = {}
    for s in args.stage_tolerance:
        if "=" not in s:
            print(f"bad --stage-tolerance {s!r} (want STAGE=TOL)",
                  file=sys.stderr)
            return 2
        k, v = s.split("=", 1)
        try:
            stage_tol[k] = float(v)
        except ValueError:
            print(f"bad tolerance in {s!r}", file=sys.stderr)
            return 2

    if args.backfill:
        n, msg = backfill(args.path)
        print(msg)
        return 0

    records = load_trajectory(args.path)
    runs = group_runs(records)

    if args.diff:
        a, b = args.diff
        missing = [r for r in (a, b) if r not in runs]
        if missing:
            print(f"unknown run id(s): {', '.join(missing)} "
                  f"(known: {', '.join(runs) or 'none'})",
                  file=sys.stderr)
            return 2
        rows, regressions = diff_runs(runs[a], runs[b],
                                      args.tolerance, stage_tol)
        if args.json:
            print(json.dumps({"rows": rows,
                              "regressions": regressions}))
        else:
            print(format_diff(a, b, rows))
            if regressions:
                print(f"perf_report: {len(regressions)} regression(s)")
        return 1 if regressions else 0

    if args.check:
        rc, text = check(runs, args.tolerance, stage_tol)
        print(text)
        return rc

    if not runs:
        print(f"no records in {args.path}")
        return 0
    if args.json:
        print(json.dumps({
            rid: {"platform": _main_platform(r), "t": r["t"],
                  "metrics": {f"{s}.{m}": rec["value"]
                              for (s, m), rec in r["metrics"].items()}}
            for rid, r in runs.items()}))
    else:
        print(format_table(runs, last=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
