"""Validate the vectorizer's utility model against TPU measurement.

VERDICT r1 next-round #5: the model (core/vectorize.py STEP_OVERHEAD /
VPU_PARALLEL) picked widths no measurement had ever contacted. This
harness times representative pipelines at W in {pick/4, pick, 4*pick}
on the real chip using the device-loop marginal method (see bench.py:
per-call timing measures the axon tunnel, not the chip) and reports
whether the model's pick is within tolerance of the measured best.

    python tools/calibrate_vect.py            # needs the TPU reachable
    python tools/calibrate_vect.py --cpu      # smoke-test the harness
                                              # (mechanics only: the
                                              # constants are TPU-tuned,
                                              # so a CPU verdict of
                                              # MODEL OFF is expected)

Emits one JSON object: per-pipeline tables of (W, steps/s, items/s)
plus the model's pick and the measured best. If the pick is >10% off
the best W's throughput, recalibrate STEP_OVERHEAD (raise it if the
model picks too-small W; lower if too-large) and re-run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def _pipelines():
    """(name, comp, item dtype) — one stateless-wide, one stateful-
    scan-bound, one mixed (the three regimes the model trades off)."""
    import ziria_tpu as z

    def fir_step(s, x):
        import jax.numpy as jnp
        s = jnp.roll(s, 1).at[0].set(x)
        return s, (s * jnp.arange(1.0, 6.0)).sum()

    stateless = z.pipe(z.zmap(lambda x: x * 2.0 + 1.0, name="axpy"),
                       z.zmap(lambda x: x * x, name="sq"))
    stateful = z.pipe(z.map_accum(fir_step, np.zeros(5, np.float32),
                                  name="fir5"))
    mixed = z.pipe(z.zmap(lambda x: x * 0.5, name="pre"),
                   z.map_accum(lambda s, x: (s + x, s + x), 0.0,
                               name="cumsum"),
                   z.zmap(lambda x: x + 3.0, name="post"))
    return [("stateless", stateless), ("stateful", stateful),
            ("mixed", mixed)]


def _fence(x):
    np.asarray(x.ravel()[:1])


def _time_width(comp, W: int, item_shape: tuple = ()):
    """(marginal seconds per fused step at width W, items per step) —
    timed via a device-side chain of K steps (cancels the tunnel
    round-trip). ``item_shape`` is the per-item trailing shape (() for
    scalar streams, (2,) for complex16 pair streams)."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.backend.lower import lower

    lowered = lower(comp, width=W)
    take = lowered.take
    xs = jnp.asarray(np.random.default_rng(0).normal(
        size=(take,) + tuple(item_shape)).astype(np.float32))

    @jax.jit
    def step_k(x0, k):
        def body(i, carry):
            s, x, acc = carry
            st, y = lowered.step(s, x)
            # feed a perturbed copy of the same chunk back: keeps the
            # loop data-dependent so XLA cannot hoist the body
            return (st, x0 + acc * 1e-30, acc + y.sum())
        return jax.lax.fori_loop(
            0, k, body, (lowered.init_carry, x0, jnp.float32(0)))[2]

    K1, K2 = 16, 80
    def run(k):
        best = float("inf")
        _fence(step_k(xs, jnp.int32(k)))
        for _ in range(3):
            t0 = time.perf_counter()
            _fence(step_k(xs, jnp.int32(k)))
            best = min(best, time.perf_counter() - t0)
        return best
    t1, t2 = run(K1), run(K2)
    return max((t2 - t1) / (K2 - K1), 1e-9), take


def _fit_constants(pipelines: dict) -> dict:
    """Fit the utility model's two constants from the probe tables.

    Model: s_per_step(W) = a + b_par*(parallel items) + b_seq*(seq
    items). The stateless pipeline (2 vmapped stages -> 2W parallel
    items/step) yields b_par from its lstsq slope; the stateful one
    (1 scan -> W sequential items/step) yields b_seq; the stateless
    intercept estimates the fixed per-step cost a. Then, in the
    model's own units (a sequential item costs 1):

        VPU_PARALLEL  = b_seq / b_par   (parallel items per seq-item)
        STEP_OVERHEAD = a / b_seq       (seq-item-equivalents)

    Per-regime fits are used instead of one global lstsq because the
    captures are noisy (host load, cache cliffs at multi-MB widths) —
    a shared intercept fits nothing well. Treat results as
    2-significant-figure estimates.
    """
    def slope_intercept(name):
        tab = pipelines[name]["table"]
        W = np.array([r["W"] for r in tab], float)
        t = np.array([r["s_per_step"] for r in tab], float)
        b, a = np.polyfit(W, t, 1)
        return b, max(a, 0.0)

    b_sl, a_sl = slope_intercept("stateless")   # slope = 2*b_par
    b_sf, _ = slope_intercept("stateful")       # slope = b_seq
    #                         (its intercept is unused: STEP_OVERHEAD
    #                          derives from the stateless fit's a_sl)
    b_par = max(b_sl / 2.0, 1e-15)
    b_seq = max(b_sf, 1e-15)
    return {
        "a_s": round(float(a_sl), 9),
        "b_par_s": round(float(b_par), 12),
        "b_seq_s": round(float(b_seq), 12),
        "VPU_PARALLEL": round(float(b_seq / b_par), 1),
        "STEP_OVERHEAD": round(float(a_sl / b_seq), 1),
        "method": "per-regime lstsq (see _fit_constants docstring)",
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="harness smoke test on CPU")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]

    from ziria_tpu.core.vectorize import vectorize

    # per-pipeline resume across window flaps (same idea as bench.py's
    # stage resume): each finished pipeline is banked in the scratch
    # dir with its own capture time; a re-entering run on the same
    # platform reuses the still-fresh ones and spends the (possibly
    # short) window on what is missing.
    import _bank
    bank = _bank.load_bank("vect_calib", dev.platform)
    if bank:
        print(f"[calibrate] resuming {sorted(bank)} from the scratch "
              f"bank", file=sys.stderr, flush=True)

    report = {"device": str(dev), "platform": dev.platform,
              "pipelines": {}}
    for name, comp in _pipelines():
        if name in bank:
            report["pipelines"][name] = _bank.strip(bank[name])
            continue
        plan = vectorize(comp)
        pick = plan.segments[0].width if plan.segments else 1
        table = []
        for W in sorted({max(1, pick // 4), pick, pick * 4}):
            t, take = _time_width(comp, W)
            table.append({"W": W, "s_per_step": round(t, 9),
                          "items_per_s": round(take / t, 1)})
        best = max(table, key=lambda r: r["items_per_s"])
        pick_row = next(r for r in table if r["W"] == pick)
        report["pipelines"][name] = {
            "model_pick": pick,
            "table": table,
            "best_W": best["W"],
            "pick_within_10pct":
                pick_row["items_per_s"] >= 0.9 * best["items_per_s"],
        }
        _bank.save_entry("vect_calib", dev.platform, name,
                         report["pipelines"][name])
        print(f"[calibrate] banked {name}", file=sys.stderr, flush=True)
    try:
        report["fitted_constants"] = _fit_constants(report["pipelines"])
    except Exception as e:        # fit is best-effort; tables are the data
        report["fitted_constants"] = {"error": repr(e)}
    print(json.dumps(report, indent=2))
    ok = all(p["pick_within_10pct"]
             for p in report["pipelines"].values())
    print(("MODEL OK: every pick within 10% of measured best"
           if ok else
           "MODEL OFF: recalibrate STEP_OVERHEAD/VPU_PARALLEL "
           "(core/vectorize.py)"), file=sys.stderr)
    # --cpu is a mechanics smoke test: the constants are TPU-tuned, so
    # its verdict is expected to be OFF and must not fail the exit code
    return 0 if (ok or args.cpu) else 1


if __name__ == "__main__":
    sys.exit(main())
