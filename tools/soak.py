#!/usr/bin/env python
"""Chaos-soak harness for the DURABLE serving runtime (ISSUE 14).

A seeded randomized fault campaign over the crash-tolerant server:
every PR 12 fault kind (nan_slab / truncate at the push seams,
transient / fatal / delay / hang at the dispatch seams), the new
``io_torn`` / ``io_enospc`` kinds at the durability write seams
(journal appends, snapshot files), plus REAL process death —
subprocess rounds SIGKILLed mid-chunk-step — each round ending in a
crash and a ``ServeRuntime.recover``. Gates:

- **zero crashes**: no round may raise out of the serving loop or the
  recovery; injected faults are contained, retried, degraded, or
  journaled — never fatal to the harness.
- **bit-identity**: every delivered frame equals the uninterrupted
  oracle's frame at the same (session, start) — delivery is
  at-least-once (duplicates allowed and counted; (sid, start) is the
  idempotency key), and sessions untouched by data-poisoning faults
  must deliver the COMPLETE oracle set. NaN-poisoned sessions gate as
  subsets (quarantine drops, never corrupts); truncate-poisoned
  sessions gate on no-crash only (their stream genuinely differs).
- **recovery latency SLO**: ``recover()`` wall time per round, gated
  at p99 (the bench ledger's ``recovery_p99_s``, lower is better).
- **dispatch budget after recovery**: <= 2 dispatches per chunk-step
  on the recovered fleet, under ``dispatch.no_recompile`` for the
  unchanged-geometry case — recovery must not cost the compiled
  programs their one-compile contract.

``bench.py soak`` rides :func:`soak_stats` (resumable, never-fatal,
smoke-sized on CPU); ``--child`` is the subprocess serving loop the
SIGKILL rounds shoot. The jax-free protocol canary is
tools/durability_smoke.py — this harness is the full-device proof.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

N_BYTES = 12
GEO = dict(chunk_len=4096, frame_len=1024, max_frames_per_chunk=8,
           check_fcs=True)

#: the full kind menu a campaign round draws from (site, kind, kwargs)
DISPATCH_MENU = [
    ("rx.stream_chunk_multi", "transient", {"every": 4}),
    ("rx.stream_decode_multi", "transient", {"every": 3}),
    ("rx.stream_decode_multi", "fatal", {"calls": (2,), "count": 1}),
    ("rx.stream_chunk_multi", "delay", {"every": 5, "delay_s": 0.02}),
    ("rx.stream_chunk_multi", "hang",
     {"calls": (3,), "count": 1, "delay_s": 8.0}),
]
DATA_MENU = [
    ("rx.push.s*", "nan_slab", {"every": 7, "fraction": 0.2}),
    ("rx.push.s*", "truncate", {"every": 9, "fraction": 0.2}),
]
IO_MENU = [
    ("journal.append", "io_torn", {"every": 6, "fraction": 0.5}),
    ("journal.append", "io_enospc", {"every": 11}),
    ("snapshot.lane", "io_enospc", {"calls": (1,), "count": 1}),
    ("snapshot.meta", "io_torn", {"calls": (0,), "count": 1,
                                  "fraction": 0.3}),
]


def _same(a, b) -> bool:
    return (a.start == b.start and a.result.ok == b.result.ok
            and a.result.rate_mbps == b.result.rate_mbps
            and a.result.length_bytes == b.result.length_bytes
            and np.array_equal(np.asarray(a.result.psdu_bits),
                               np.asarray(b.result.psdu_bits))
            and a.result.crc_ok == b.result.crc_ok)


def _clients(n_sessions: int, frames_per_session: int, seed: int,
             channel_profile=None):
    from ziria_tpu.runtime import serve
    return serve.synth_load(n_sessions, frames_per_session,
                            n_bytes=N_BYTES, snr_db=30.0, seed=seed,
                            tail=GEO["frame_len"],
                            channel_profile=channel_profile)


def _oracle(clients):
    from ziria_tpu.backend import framebatch
    return {c.sid: framebatch.receive_stream(c.stream, **GEO)[0]
            for c in clients}


def _serve_until_crash(cfg, clients, crash_after: int, got):
    """Run a fresh server, pushing each client's stream in ragged
    slabs, until ``crash_after`` frames were delivered (or the input
    is exhausted) — then ABANDON the runtime mid-flight: no drain, no
    close, exactly what a SIGKILL leaves behind, minus the process.
    Returns the abandoned runtime (for accounting reads only)."""
    from ziria_tpu.runtime import serve

    srv = serve.ServeRuntime(cfg)
    delivered = 0
    with srv:
        for c in clients:
            srv.connect(c.sid)
        pos = {c.sid: 0 for c in clients}
        idle = 0
        while idle < 3:
            moved = False
            for c in clients:
                lo = pos[c.sid]
                hi = min(lo + 1700, c.stream.shape[0])
                if lo < hi:
                    if srv.submit(c.sid, c.stream[lo:hi]).accepted:
                        pos[c.sid] = hi
                    moved = True
            frames = srv.step()
            for sid, f in frames:
                got[sid].append(f)
                delivered += 1
            if delivered >= crash_after:
                break
            idle = 0 if (moved or frames) else idle + 1
        srv._drained = True          # the crash: nothing cleans up
    return srv


def _finish_recovered(srv2, clients, got):
    """The documented client recovery protocol: take the replayed
    rider frames, resubmit every live session's stream from its
    ``acked`` coordinate (a session the journal lost entirely —
    ENOSPC ate its admit record — reconnects fresh and resubmits from
    zero; the dedupe key (sid, start) absorbs any re-delivery), drive
    to quiescence, drain."""
    with srv2:
        for sid, f in srv2.replayed:
            got[sid].append(f)
        for c in clients:
            if c.sid not in srv2._sessions:
                if c.sid in srv2._gone:
                    continue             # terminally accounted
                srv2.connect(c.sid)      # journal-lost: fresh session
            if not (c.sid in srv2._sessions):
                continue                 # queue full: give up politely
            acked = srv2.acked(c.sid)
            for lo in range(acked, c.stream.shape[0], 1 << 14):
                srv2.submit(c.sid,
                            c.stream[lo: lo + (1 << 14)])
        idle = 0
        while idle < 3:
            frames = srv2.step()
            for sid, f in frames:
                got[sid].append(f)
            idle = 0 if frames else idle + 1
        for sid, f in srv2.drain():
            got[sid].append(f)


def _verify(clients, oracle, got, nan_sids, trunc_sids):
    """The identity gate. Returns (duplicates, frames_checked)."""
    dups = 0
    checked = 0
    for c in clients:
        if c.sid in trunc_sids:
            continue          # stream genuinely differs: no-crash only
        by_start = {}
        for f in got[c.sid]:
            if f.start in by_start:
                assert _same(f, by_start[f.start]), \
                    f"{c.sid}: duplicate at {f.start} differs"
                dups += 1
                continue
            by_start[f.start] = f
        want = {f.start: f for f in oracle[c.sid]}
        for start, f in by_start.items():
            assert start in want, \
                f"{c.sid}: unexpected frame at {start}"
            assert _same(f, want[start]), \
                f"{c.sid}: frame at {start} differs from oracle"
            checked += 1
        if c.sid not in nan_sids:
            missing = sorted(set(want) - set(by_start))
            assert not missing, \
                f"{c.sid}: frames missing after recovery: {missing}"
    return dups, checked


def _affected_sids(plan, lane_sid):
    """Map fired data-seam sites (rx.push.s<lane>) back to sessions."""
    nan_s, trunc_s = set(), set()
    for site, kind, _idx in plan.fired:
        if not site.startswith("rx.push.s"):
            continue
        lane = int(site[len("rx.push.s"):])
        sid = lane_sid.get(lane)
        if sid is None:
            continue
        (nan_s if kind == "nan_slab" else trunc_s).add(sid)
    return nan_s, trunc_s


def _round_specs(rng, dirty: bool):
    """Draw a seeded spec set for one round: always >= 1 dispatch
    kind and >= 1 io kind; data-poisoning kinds only on dirty
    rounds (their sessions cannot gate completeness)."""
    from ziria_tpu.utils import faults
    picks = [DISPATCH_MENU[i] for i in
             rng.choice(len(DISPATCH_MENU),
                        size=1 + int(rng.integers(0, 3)),
                        replace=False)]
    picks += [IO_MENU[i] for i in
              rng.choice(len(IO_MENU), size=1 + int(rng.integers(0, 2)),
                         replace=False)]
    if dirty:
        picks += [DATA_MENU[int(rng.integers(0, len(DATA_MENU)))]]
    return [faults.FaultSpec(site, kind, **kw)
            for site, kind, kw in picks]


def run_round(clients, oracle, cfg, seed: int, dirty: bool,
              budget: bool = False) -> dict:
    """One in-process campaign round: serve under a seeded fault plan
    (dispatch + io kinds, push kinds on dirty rounds), crash, recover
    with the fault plan GONE (the chaos died with the process),
    verify, time the recovery. ``budget=True`` additionally pins the
    POST-RECOVERY dispatch budget — <= 2 dispatches per chunk-step on
    the recovered fleet, zero recompiles (the unchanged-geometry
    acceptance gate; the pre-crash phase is excluded because injected
    transients legitimately retry as extra dispatches)."""
    from ziria_tpu.runtime import serve
    from ziria_tpu.utils import faults

    rng = np.random.default_rng(seed)
    specs = _round_specs(rng, dirty)
    got = {c.sid: [] for c in clients}
    crash_after = 1 + int(rng.integers(0, 3))
    with faults.inject(*specs, seed=seed) as plan:
        srv = _serve_until_crash(cfg, clients, crash_after, got)
        lane_sid = dict(srv._lane_sid)
    nan_s, trunc_s = _affected_sids(plan, lane_sid)
    st = srv.stats()

    t0 = time.perf_counter()
    srv2 = serve.ServeRuntime.recover(cfg.snapshot_dir, config=cfg)
    recovery_s = time.perf_counter() - t0
    dpcs = None
    if budget:
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.utils import dispatch
        with dispatch.no_recompile(_rx._jit_stream_chunk_multi,
                                   _rx._jit_stream_decode_multi):
            with dispatch.count_dispatches() as dc:
                _finish_recovered(srv2, clients, got)
        steps = int(srv2.stats().chunk_steps)
        if steps:
            dpcs = round(dc.total / steps, 2)
            assert dpcs <= 2.0 + 1e-9, \
                (f"dispatch budget broken after recovery: "
                 f"{dc.total} dispatches / {steps} chunk-steps")
    else:
        _finish_recovered(srv2, clients, got)
    dups, checked = _verify(clients, oracle, got, nan_s, trunc_s)
    st2 = srv2.stats()
    return {"recovery_s": recovery_s, "faults": len(plan.fired),
            "by_kind": _by_kind(plan), "duplicates": dups,
            "frames_checked": checked, "deduped": st2.deduped,
            "snapshots": st.snapshots + st2.snapshots,
            "journal_errors": st.journal_errors
            + st2.journal_errors,
            "dpcs": dpcs,
            "nan_sessions": sorted(map(str, nan_s)),
            "trunc_sessions": sorted(map(str, trunc_s))}


def _by_kind(plan) -> dict:
    out: dict = {}
    for _site, kind, _idx in plan.fired:
        out[kind] = out.get(kind, 0) + 1
    return out


# ----------------------------------------------------- SIGKILL rounds


def _child_main(args) -> int:
    """``--child``: the serving loop the SIGKILL rounds shoot. Builds
    the SAME seeded client set as the parent, serves with journaling
    + per-step snapshots, prints one flushed JSON line per delivered
    frame (delivery-before-mark: the parent's record of what the dead
    process delivered), and sleeps a little each tick so the parent
    can reliably land the kill mid-run."""
    from ziria_tpu.runtime import durability, serve

    clients = _clients(args.sessions, args.frames, args.seed)
    cfg = serve.ServeConfig(n_lanes=args.lanes, queue_cap=16,
                            sanitize=True,
                            snapshot_dir=args.dir, snapshot_every=1,
                            **GEO)
    got_n = 0
    srv = serve.ServeRuntime(cfg)
    with srv:
        for c in clients:
            srv.connect(c.sid)
        pos = {c.sid: 0 for c in clients}
        idle = 0
        while idle < 3:
            moved = False
            for c in clients:
                lo = pos[c.sid]
                hi = min(lo + 1500, c.stream.shape[0])
                if lo < hi:
                    if srv.submit(c.sid, c.stream[lo:hi]).accepted:
                        pos[c.sid] = hi
                    moved = True
            frames = srv.step()
            for sid, f in frames:
                print(json.dumps({"sid": sid,
                                  "f": durability.encode_frame(f)}),
                      flush=True)
                got_n += 1
            idle = 0 if (moved or frames) else idle + 1
            time.sleep(args.tick_sleep)
        for sid, f in srv.drain():
            print(json.dumps({"sid": sid,
                              "f": durability.encode_frame(f)}),
                  flush=True)
            got_n += 1
    print(json.dumps({"done": got_n}), flush=True)
    return 0


def run_sigkill_round(clients, oracle, workdir: str, seed: int,
                      n_lanes: int, frames_per_session: int,
                      tick_sleep: float = 0.05) -> dict:
    """One REAL process-death round: spawn the ``--child`` serving
    subprocess, SIGKILL it once frames are flowing (mid-chunk-step —
    the child sleeps between ticks, so the kill lands inside live
    journal/snapshot traffic), then recover the fleet IN THIS PROCESS
    from the directory the corpse left behind and finish the streams.
    The child's flushed stdout lines are the delivered-frame record a
    real client would hold; a torn last line is dropped exactly like
    a torn journal tail."""
    from ziria_tpu.runtime import durability, serve

    rng = np.random.default_rng(seed)
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--dir", workdir, "--seed", str(seed),
         "--sessions", str(len(clients)), "--lanes", str(n_lanes),
         "--frames", str(frames_per_session),
         "--tick-sleep", str(tick_sleep)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(
            __file__))),
        env={**os.environ, "JAX_PLATFORMS":
             os.environ.get("JAX_PLATFORMS", "cpu")})
    lines: list = []
    kill_after = 1 + int(rng.integers(0, 2))
    killed = False

    def reader():
        for raw in child.stdout:
            lines.append(raw)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    deadline = time.time() + 600
    while child.poll() is None and time.time() < deadline:
        n_frames = sum(1 for ln in lines if b'"sid"' in ln)
        if n_frames >= kill_after:
            time.sleep(float(rng.uniform(0.0, 2 * tick_sleep)))
            try:
                os.kill(child.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            killed = True
            break
        time.sleep(0.01)
    child.wait(timeout=60)
    t.join(timeout=10)

    got = {c.sid: [] for c in clients}
    done = False
    for raw in lines:
        try:
            d = json.loads(raw.decode())
        except Exception:
            continue        # torn final line: dropped like a torn tail
        if "done" in d:
            done = True
            continue
        got[d["sid"]].append(durability.decode_frame(d["f"]))

    recovery_s = 0.0
    if not done:
        cfg = serve.ServeConfig(n_lanes=n_lanes, queue_cap=16,
                                sanitize=True, snapshot_dir=workdir,
                                snapshot_every=1, **GEO)
        t0 = time.perf_counter()
        srv2 = serve.ServeRuntime.recover(workdir, config=cfg)
        recovery_s = time.perf_counter() - t0
        _finish_recovered(srv2, clients, got)
    dups, checked = _verify(clients, oracle, got, set(), set())
    return {"recovery_s": recovery_s, "killed": killed,
            "kill_missed": done, "duplicates": dups,
            "frames_checked": checked,
            "pre_kill_frames": sum(
                1 for ln in lines if b'"sid"' in ln)}


# --------------------------------------------------------- the harness


def soak_stats(n_sessions: int = 3, n_lanes: int = 4,
               frames_per_session: int = 4, rounds: int = 3,
               sigkill_rounds: int = 1, seed: int = 20260804,
               recovery_slo_s: float = 30.0,
               tick_sleep: float = 0.05,
               channel_profile: str = "urban") -> dict:
    """The bench-facing campaign (``bench.py soak``): in-process
    fault rounds (alternating clean-data / dirty-data spec draws) +
    real SIGKILL subprocess rounds, all gated, recovery latencies
    aggregated to the ledger metric ``recovery_p99_s``. The campaign
    additionally runs ONE multipath-active round (ISSUE 15): every
    client's stream rides the named physical-channel profile
    (phy/profiles; an equalizable tap set, so the oracle is complete)
    while the usual dispatch/io faults fire and the server crashes
    and recovers — physical faults and software faults campaigned
    TOGETHER, gated on zero crashes and the same per-session
    bit-identity vs the profiled oracle."""
    from ziria_tpu.runtime import serve

    clients = _clients(n_sessions, frames_per_session, seed)
    oracle = _oracle(clients)
    n_oracle = sum(len(v) for v in oracle.values())
    chan_clients = _clients(n_sessions, frames_per_session, seed + 1,
                            channel_profile=channel_profile)
    chan_oracle = _oracle(chan_clients)

    times: list = []
    by_kind: dict = {}
    totals = {"faults": 0, "duplicates": 0, "deduped": 0,
              "snapshots": 0, "journal_errors": 0}
    budget_checked = False
    dpcs = None

    with tempfile.TemporaryDirectory(prefix="ziria-soak-") as root:
        # warm pass: the fleet programs compile ONCE here, so the
        # chaos rounds' watchdogs never mistake a cold compile for a
        # hang, and the budget round can pin no_recompile
        warm_cfg = serve.ServeConfig(
            n_lanes=n_lanes, queue_cap=16, sanitize=True,
            watchdog_s=None,
            snapshot_dir=os.path.join(root, "warm"),
            snapshot_every=4, **GEO)
        got = {c.sid: [] for c in clients}
        _serve_until_crash(warm_cfg, clients, 10 ** 9, got)

        for r in range(rounds):
            d = os.path.join(root, f"round-{r}")
            cfg = serve.ServeConfig(
                n_lanes=n_lanes, queue_cap=16, sanitize=True,
                watchdog_s=2.0, snapshot_dir=d, snapshot_every=1,
                **GEO)
            # the LAST round is the unchanged-geometry budget gate:
            # <= 2 dispatches/chunk-step on the recovered fleet
            # under dispatch.no_recompile
            ev = run_round(clients, oracle, cfg, seed + 17 * r,
                           dirty=bool(r % 2),
                           budget=(r == rounds - 1))
            if ev["dpcs"] is not None:
                dpcs = ev["dpcs"]
                budget_checked = True
            times.append(ev["recovery_s"])
            for k, v in ev["by_kind"].items():
                by_kind[k] = by_kind.get(k, 0) + v
            for k in totals:
                totals[k] += ev[k]

        # the multipath-active crash->recover round: profiled client
        # streams (same geometry, own oracle) under a DIRTY-round
        # fault draw (dispatch + io + data-poisoning kinds) —
        # physical chaos UNDER software chaos, one campaign
        d = os.path.join(root, "round-channel")
        cfg = serve.ServeConfig(
            n_lanes=n_lanes, queue_cap=16, sanitize=True,
            watchdog_s=2.0, snapshot_dir=d, snapshot_every=1,
            **GEO)
        chan_ev = run_round(chan_clients, chan_oracle, cfg,
                            seed + 991, dirty=True)
        times.append(chan_ev["recovery_s"])
        for k, v in chan_ev["by_kind"].items():
            by_kind[k] = by_kind.get(k, 0) + v
        for k in totals:
            totals[k] += chan_ev[k]

        kills = {"killed": 0, "kill_missed": 0}
        for r in range(sigkill_rounds):
            d = os.path.join(root, f"kill-{r}")
            ev = run_sigkill_round(clients, oracle, d,
                                   seed, n_lanes,
                                   frames_per_session,
                                   tick_sleep=tick_sleep)
            if ev["recovery_s"]:
                times.append(ev["recovery_s"])
            totals["duplicates"] += ev["duplicates"]
            kills["killed"] += int(ev["killed"])
            kills["kill_missed"] += int(ev["kill_missed"])

    p50 = float(np.percentile(times, 50)) if times else 0.0
    p99 = float(np.percentile(times, 99)) if times else 0.0
    assert p99 <= recovery_slo_s, \
        f"recovery p99 {p99:.2f}s exceeds the {recovery_slo_s}s SLO"
    return {"sessions": n_sessions, "lanes": n_lanes,
            "rounds": rounds, "sigkill_rounds": sigkill_rounds,
            "oracle_frames": n_oracle,
            "faults_injected": totals["faults"],
            "faults_by_kind": by_kind,
            "recovery_p50_s": round(p50, 4),
            "recovery_p99_s": round(p99, 4),
            "recovery_rounds_timed": len(times),
            "duplicates": totals["duplicates"],
            "deduped": totals["deduped"],
            "snapshots": totals["snapshots"],
            "journal_errors": totals["journal_errors"],
            "dispatches_per_chunk_step_post_recovery": dpcs,
            "budget_checked": budget_checked,
            "kills": kills, "identity": "bit_identical",
            "channel_profile": channel_profile,
            "channel_round_frames": chan_ev["frames_checked"],
            "channel_round_faults": chan_ev["faults"],
            "zero_crashes": True}


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="soak", description="chaos-soak the durable serving "
                                 "runtime (docs/robustness.md)")
    p.add_argument("--child", action="store_true",
                   help="internal: the SIGKILL target serving loop")
    p.add_argument("--dir", default=None)
    p.add_argument("--seed", type=int, default=20260804)
    p.add_argument("--sessions", type=int, default=3)
    p.add_argument("--lanes", type=int, default=4)
    p.add_argument("--frames", type=int, default=4)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--sigkill-rounds", type=int, default=1)
    p.add_argument("--tick-sleep", type=float, default=0.05)
    p.add_argument("--recovery-slo", type=float, default=30.0)
    args = p.parse_args(argv)
    if args.child:
        if not args.dir:
            raise SystemExit("--child needs --dir")
        return _child_main(args)
    ev = soak_stats(args.sessions, args.lanes, args.frames,
                    args.rounds, args.sigkill_rounds, args.seed,
                    recovery_slo_s=args.recovery_slo,
                    tick_sleep=args.tick_sleep)
    print(json.dumps(ev, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
