#!/usr/bin/env python
"""Thin launcher for the geometry autotuner (ziria_tpu.utils.autotune)
so it can run straight from a checkout: cost-pruned measured search,
per-device winner recorded in BENCH_TRAJECTORY.jsonl. Equivalent to
`python -m ziria_tpu autotune`; see docs/autotune.md."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from ziria_tpu.utils.autotune import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
