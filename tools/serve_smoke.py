#!/usr/bin/env python
"""Sub-second CPU serving smoke for tools/precommit.sh (ISSUE 13).

Exercises the continuous-batching server's admission / backpressure /
deadline-shed / evict / drain state machine (runtime/serve) against a
STUB receiver — no jax import, no compile, deterministic fake clock —
so the gate works through TPU probe hangs exactly like chaos_smoke
and the lint gate. The real-fleet identity/chaos matrix lives in
tests/test_serve.py and the bench `serving` stage; this is the
commit-time canary for the host-side protocol.

Exit 0 = all checks passed; nonzero = the serving state machine is
broken (precommit refuses the commit).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


class _StubStats:
    def __init__(self, chunk_steps=0):
        self.chunk_steps = chunk_steps


class StubReceiver:
    """Duck-typed MultiStreamReceiver for the host-side state
    machine: S lanes, chunk_len/stride accounting, one token
    (lane, frame) emission per consumed chunk. No device, no jax."""

    def __init__(self, s, chunk_len=256, frame_len=64):
        self.s = s
        self.chunk_len = chunk_len
        self.stride = chunk_len - frame_len
        self._tails = [0] * s            # sample counts only
        self._offsets = [0] * s
        self._steps = 0
        self._flushed = False
        self.restored = {}               # lane -> blob (for asserts)

    @property
    def stats(self):
        return _StubStats(self._steps)

    def quarantined(self, i):
        return False

    def _consume(self):
        out = []
        while any(t >= self.chunk_len for t in self._tails):
            self._steps += 1
            for i in range(self.s):
                if self._tails[i] >= self.chunk_len:
                    out.append((i, ("frame", i, self._offsets[i])))
                    self._tails[i] -= self.stride
                    self._offsets[i] += self.stride
        return out

    def push_many(self, slabs):
        for i, a in slabs.items():
            self._tails[i] += int(a.shape[0])
        return self._consume()

    def drain_pending(self):
        return []

    def flush_stream(self, i):
        out = []
        if self._tails[i]:
            self._steps += 1
            out.append((i, ("frame", i, self._offsets[i])))
            self._offsets[i] += self._tails[i]
            self._tails[i] = 0
        return out

    def reset_stream(self, i):
        self._tails[i] = 0
        self._offsets[i] = 0
        self.restored.pop(i, None)
        return []

    def restore_stream(self, i, blob):
        self.reset_stream(i)
        self.restored[i] = blob
        self._offsets[i] = 777          # marker: restored, not fresh
        return []

    def checkpoint(self, i):
        return (b"blob-%d" % i), []

    def flush(self):
        self._flushed = True
        return []


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from ziria_tpu.runtime import serve

    assert "jax" not in sys.modules, \
        "serve_smoke imported jax — the smoke must stay host-only"

    clock = [0.0]
    cfg = serve.ServeConfig(
        n_lanes=2, chunk_len=256, frame_len=64, queue_cap=2,
        max_slab_samples=512, max_backlog_samples=1024,
        default_slo_s=10.0, retry_after_s=0.25)

    def mk():
        return serve.ServeRuntime(
            cfg, receiver=StubReceiver(2, 256, 64),
            clock=lambda: clock[0])

    slab = np.zeros((128, 2), np.float32)

    # 1. admission: lanes fill, then the bounded queue, then explicit
    #    reject-with-retry-after — never unbounded buffering
    with mk() as srv:
        rs = [srv.connect(f"c{i}") for i in range(6)]
        assert [r.admitted for r in rs] == [True, True] + [False] * 4
        assert [r.queued for r in rs] == [False, False, True, True,
                                          False, False]
        assert all(r.reason == "queue_full" and r.retry_after_s > 0
                   for r in rs[4:])
        # deterministic JITTERED backpressure hint (ISSUE 14): scales
        # with queue depth, spread by the per-session hash so
        # synchronized rejects never re-arrive in lockstep
        base = cfg.retry_after_s * 3
        assert 0.5 * base <= rs[4].retry_after_s < base
        assert rs[4].retry_after_s != rs[5].retry_after_s
        assert srv.connect("c0").reason == "duplicate"

        # 2. ingress bounds: oversized reject, backlog backpressure
        r = srv.submit("c0", np.zeros((600, 2), np.float32))
        assert not r.accepted and r.reason == "oversized"
        for _ in range(9):
            last = srv.submit("c0", slab)
        assert not last.accepted and last.reason == "backlog_full" \
            and last.retry_after_s > 0
        try:
            srv.submit("nobody", slab)
            raise AssertionError("unknown session must raise")
        except KeyError as e:
            assert "known sessions" in str(e) and "c0" in str(e)
        try:
            srv.submit("c0", np.zeros((3, 5)))
            raise AssertionError("malformed slab must raise")
        except ValueError as e:
            assert "c0" in str(e)

        # 3. scheduling: staged samples flow, chunk-steps fire,
        #    frames come back attributed to their session
        got = []
        for _ in range(8):
            got += srv.step()
        assert got and all(sid == "c0" for sid, _f in got)

        # 4. close frees the lane and admits from the queue
        srv.submit("c1", slab)
        srv.close("c1")
        st = srv.stats()
        assert st.closed == 1 and st.active_sessions == 2
        assert st.queue_depth == 1          # c2 promoted, c3 waits

        # 5. deadline shed: deterministic via the injected clock,
        #    counted and attributed
        clock[0] = 11.0
        srv.step()
        st = srv.stats()
        assert st.shed == 3                 # c0, c2 active; c3 queued
        reasons = {r for _s, r, _t in st.shed_log}
        assert reasons == {"deadline", "deadline_queued"}
        assert {s for s, _r, _t in st.shed_log} == {"c0", "c2", "c3"}
        r = srv.submit("c0", slab)
        assert not r.accepted and r.reason == "shed:deadline"

        # 6. evict hands back a checkpoint + staged slabs; reconnect
        #    with the blob restores into a fresh lane
        srv.connect("e1")
        srv.submit("e1", slab)
        blob, _fr, staged = srv.evict("e1")
        assert blob == b"blob-0" and len(staged) == 1
        r = srv.connect("e1", checkpoint=blob)
        assert r.admitted
        assert srv._rx.restored.get(0) == blob
        st = srv.stats()
        assert st.evicted == 1 and st.restored == 1

        # 7. drain: stop admitting, flush, final stats intact
        srv.connect("late-q")               # queued behind e1? no: free lane
        final = srv.drain()
        assert srv.connect("after").reason == "draining"
        st = srv.stats()
        assert st.active_sessions == 0 and st.queue_depth == 0
        assert srv._rx._flushed
        # exact accounting: every admitted session is terminally
        # accounted (closed / shed / evicted / drained-closed)
        assert st.admitted == st.closed + st.evicted + \
            sum(1 for _s, r, _t in st.shed_log if r == "deadline")
        assert st.shed == len(st.shed_log)
        srv.drain()                         # idempotent
        try:
            srv.step()
            raise AssertionError("step after drain must raise")
        except RuntimeError:
            pass

        # 8. the scrape IS the stats path: Prometheus exposition
        #    carries the serve.* series with reason labels
        page = srv.scrape()
        assert "# TYPE serve_admitted counter" in page
        assert 'serve_shed{reason="deadline"}' in page
        assert "serve_chunk_seconds_bucket" in page
        assert "# TYPE ziria_gauge gauge" in page
    assert "jax" not in sys.modules

    dt = time.perf_counter() - t_start
    print(f"serve smoke OK ({dt:.2f}s, no jax, "
          f"{st.admitted} sessions accounted)")
    assert dt < 10.0, f"serve smoke exceeded its 10s budget: {dt:.1f}s"
    return 0


if __name__ == "__main__":
    sys.exit(main())
