#!/usr/bin/env python
"""Sub-second CPU durability smoke for tools/precommit.sh (ISSUE 14).

Exercises the crash-durability layer (runtime/durability,
runtime/resilience checkpoint integrity, runtime/serve recovery)
against a STUB receiver — journal write/replay/torn-tail resync,
atomic snapshot write/load/prune, io_torn/io_enospc injection,
checkpoint CRC + legacy-blob compatibility, and a full
crash -> ``ServeRuntime.recover`` session-table reconstruction — with
no jax import, so the gate works through TPU probe hangs exactly like
chaos_smoke and serve_smoke. The real-fleet bit-identity matrix lives
in tests/test_durability.py and the bench `soak` stage; this is the
commit-time canary for the durable-serving protocol.

Exit 0 = all checks passed; nonzero = the durability layer is broken
(precommit refuses the commit).
"""

import io
import os
import shutil
import sys
import tempfile
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


class _StubStats:
    def __init__(self, chunk_steps):
        self.chunk_steps = chunk_steps


class StubReceiver:
    """Sample-count stub whose checkpoints are REAL
    ``ziria-stream-carry-v1`` blobs, so the serve recovery path
    exercises the genuine parse / acked / dedupe math."""

    GEO = {"chunk_len": 256, "frame_len": 64}

    def __init__(self, s, chunk_len=256, frame_len=64):
        import numpy as np
        self._np = np
        self.s, self.chunk_len = s, chunk_len
        self.stride = chunk_len - frame_len
        self._tails = [0] * s
        self._offsets = [0] * s
        self._emitted = [0] * s
        self._steps = 0
        self._flushed = False
        self.restored = {}

    @property
    def stats(self):
        return _StubStats(self._steps)

    def quarantined(self, i):
        return False

    def push_many(self, slabs):
        out = []
        for i, a in slabs.items():
            self._tails[i] += int(a.shape[0])
        while any(t >= self.chunk_len for t in self._tails):
            self._steps += 1
            for i in range(self.s):
                if self._tails[i] >= self.chunk_len:
                    out.append((i, ("frame", i, self._offsets[i])))
                    self._emitted[i] += 1
                    self._tails[i] -= self.stride
                    self._offsets[i] += self.stride
        return out

    def drain_pending(self):
        return []

    def flush_stream(self, i):
        out = []
        if self._tails[i]:
            self._steps += 1
            out.append((i, ("frame", i, self._offsets[i])))
            self._emitted[i] += 1
            self._tails[i] = 0
        return out

    def reset_stream(self, i):
        self._tails[i] = 0
        self._offsets[i] = 0
        self._emitted[i] = 0
        self.restored.pop(i, None)
        return []

    def restore_stream(self, i, blob):
        from ziria_tpu.runtime import resilience
        st = resilience.restore_carry(blob)
        self.restored[i] = blob
        self._offsets[i] = int(st.offset)
        self._tails[i] = int(st.tail.shape[0])
        self._emitted[i] = int(st.emitted)
        return []

    def _blob(self, i):
        from ziria_tpu.runtime import resilience
        carry = SimpleNamespace(
            tail=self._np.zeros((self._tails[i], 2), self._np.float32),
            offset=self._offsets[i], emitted=self._emitted[i],
            watermark=self._offsets[i])
        return resilience.checkpoint_carry(carry, geometry=self.GEO)

    def checkpoint(self, i):
        return self._blob(i), []

    def checkpoint_fleet(self, lanes=None):
        which = range(self.s) if lanes is None else lanes
        return {i: self._blob(i) for i in which}, []

    def flush(self):
        self._flushed = True
        return []


def main() -> int:
    t_start = time.perf_counter()
    import numpy as np

    from ziria_tpu.runtime import durability, resilience, serve
    from ziria_tpu.utils import faults

    assert "jax" not in sys.modules, \
        "durability_smoke imported jax — the smoke must stay host-only"

    root = tempfile.mkdtemp(prefix="ziria-durability-smoke-")
    try:
        # 1. journal roundtrip + rotation + reopen-seals-the-open
        jd = os.path.join(root, "j1")
        j = durability.Journal(jd, segment_records=3)
        for i in range(7):
            j.append({"ev": "t", "i": i})
        recs, st = durability.replay(jd)
        assert [r["i"] for r in recs] == list(range(7))
        assert [r["q"] for r in recs] == list(range(1, 8))
        assert st.dropped == 0
        names = sorted(os.listdir(jd))
        assert names == ["wal-000000000001.log",
                         "wal-000000000004.log",
                         "wal-000000000007.open"], names
        # a second writer (the recovered process) seals the leftover
        # .open and resumes the sequence counter past everything
        j2 = durability.Journal(jd, segment_records=3)
        assert j2.seq == 7
        assert not [n for n in os.listdir(jd) if n.endswith(".open")]
        j2.append({"ev": "t", "i": 7})
        recs, _ = durability.replay(jd)
        assert [r["i"] for r in recs] == list(range(8))
        # prune: sealed segments fully covered by a snapshot vanish
        j2.prune(6)
        left = sorted(os.listdir(jd))
        assert "wal-000000000001.log" not in left
        recs, _ = durability.replay(jd, after_seq=6)
        assert [r["i"] for r in recs] == [6, 7]

        # 2. torn tail: a truncated last record drops cleanly, and a
        #    torn MID-segment record never corrupts its neighbours
        jd = os.path.join(root, "j2")
        j = durability.Journal(jd, segment_records=100)
        j.append({"ev": "keep", "k": 1})
        with faults.inject(faults.FaultSpec(
                "journal.append", "io_torn", calls=(0,), fraction=0.5)):
            j.append({"ev": "torn"})
        j.append({"ev": "keep", "k": 2})
        j._f.write(b"ZWAL\x40\x00\x00\x00\xde\xad\xbe\xefpartial")
        j._f.flush()
        recs, st = durability.replay(jd)
        assert [r.get("k") for r in recs] == [1, 2], recs
        assert st.dropped >= 2        # torn record + torn tail

        # 3. io_enospc surfaces as OSError (serve contains + counts)
        with faults.inject(faults.FaultSpec(
                "journal.append", "io_enospc", every=1)):
            try:
                j.append({"ev": "x"})
                raise AssertionError("ENOSPC must raise")
            except OSError as e:
                assert "No space left" in str(e)

        # 4. snapshot atomicity: tmp dirs are invisible, corrupt
        #    snapshots fall back to the previous one, prune keeps 2
        sd = os.path.join(root, "snaps")
        for step in (1, 2, 3):
            durability.write_snapshot(
                sd, step, {0: b"blob-%d" % step},
                {"jseq": step}, keep=2)
        snaps = sorted(n for n in os.listdir(sd)
                       if n.startswith("snap-"))
        assert snaps == ["snap-0000000002", "snap-0000000003"]
        os.makedirs(os.path.join(sd, ".tmp-snap-0000000009.123"))
        got = durability.load_snapshot(sd)
        assert got.step == 3 and got.lanes[0] == b"blob-3"
        # corrupt the newest meta: loader falls back to snap-2
        mp = os.path.join(sd, "snap-0000000003", "meta.json")
        with open(mp, "r+b") as f:
            f.seek(10)
            f.write(b"XX")
        got = durability.load_snapshot(sd)
        assert got.step == 2 and got.lanes[0] == b"blob-2"

        # 5. checkpoint CRC integrity + legacy compatibility
        carry = SimpleNamespace(
            tail=np.arange(8, dtype=np.float32).reshape(4, 2),
            offset=512, emitted=3, watermark=448)
        blob = resilience.checkpoint_carry(
            carry, seen=(500,), geometry={"chunk_len": 256})
        st5 = resilience.restore_carry(blob)
        assert st5.offset == 512 and st5.emitted == 3
        # flip one payload byte INSIDE the npz: CRC must catch it
        bad = bytearray(blob)
        # find the tail array bytes and corrupt one
        idx = bad.find(np.float32(5.0).tobytes())
        assert idx > 0
        bad[idx] ^= 0xFF
        try:
            resilience.restore_carry(bytes(bad))
            raise AssertionError("corrupt blob must not restore")
        except resilience.CarryCheckpointError as e:
            assert "integrity" in str(e) or "unreadable" in str(e)
        # legacy blob (no crc field): loads, counted
        import numpy.lib.format  # noqa: F401  (np.load path)
        z = dict(np.load(io.BytesIO(blob), allow_pickle=False))
        z.pop("crc")
        buf = io.BytesIO()
        np.savez(buf, **z)
        from ziria_tpu.utils import telemetry
        reg = telemetry.MetricsRegistry()
        with telemetry.collect(reg):
            st5 = resilience.restore_carry(buf.getvalue())
        assert st5.offset == 512
        page = reg.exposition()
        assert "resilience_checkpoint_legacy" in page

        # 6. atomic checkpoint file write: tmp+fsync+rename
        cp = os.path.join(root, "lane.ckpt")
        resilience.save_checkpoint(cp, blob)
        assert resilience.load_checkpoint(cp).offset == 512
        assert not [n for n in os.listdir(root)
                    if n.startswith(".lane.ckpt.tmp")]

        # 7. crash -> recover: the session table reconstructs EXACTLY
        #    (lanes restored, queued repacked, terminal reasons kept,
        #    dedupe watermarks at the last durable mark)
        dd = os.path.join(root, "srv")
        clock = [0.0]
        cfg = serve.ServeConfig(
            n_lanes=2, chunk_len=256, frame_len=64, queue_cap=4,
            default_slo_s=50.0, snapshot_dir=dd, snapshot_every=1)
        slab = np.zeros((300, 2), np.float32)
        srv = serve.ServeRuntime(cfg, receiver=StubReceiver(2),
                                 clock=lambda: clock[0])
        with srv:
            srv.connect("a")
            srv.connect("b")
            srv.connect("q1")              # queued
            srv.submit("a", slab)
            srv.submit("b", slab)
            srv.step()
            srv.submit("a", slab)
            srv.step()
            srv.close("b")                 # frees a lane; q1 promotes
            srv._drained = True            # CRASH: no drain
        assert srv.stats().snapshots >= 1
        srv2 = serve.ServeRuntime.recover(
            dd, receiver=StubReceiver(2), clock=lambda: clock[0])
        assert set(srv2._sessions) == {"a", "q1"}
        assert srv2._gone.get("b") == "closed"
        assert srv2.recovered["a"]["acked"] > 0
        assert srv2.recovered["a"]["dedupe_until"] >= 1
        assert srv2._rx.restored, "lane blob must restore"
        assert srv2.stats().restarts == 1
        r = srv2.submit("b", slab)
        assert not r.accepted and r.reason == "closed"
        # ELASTIC repack: recover the same state onto ONE lane — the
        # second session waits in the queue instead of refusing
        srv3 = serve.ServeRuntime.recover(
            dd, config=cfg._replace(n_lanes=1),
            receiver=StubReceiver(1), clock=lambda: clock[0])
        assert set(srv3._sessions) == {"a", "q1"}
        assert sum(1 for s in ("a", "q1")
                   if srv3.is_active(s)) == 1
        assert len(srv3._queue) == 1

        # 8. jittered retry-after: deterministic per (sid, attempt),
        #    spread across sids — no reject lockstep
        cfg8 = serve.ServeConfig(n_lanes=1, chunk_len=256,
                                 frame_len=64, queue_cap=0,
                                 retry_after_s=1.0)

        def hints():
            s8 = serve.ServeRuntime(cfg8, receiver=StubReceiver(1),
                                    clock=lambda: 0.0)
            with s8:
                s8.connect("holder")
                return [s8.connect(f"r{i}").retry_after_s
                        for i in range(6)]

        h1, h2 = hints(), hints()
        assert h1 == h2                      # replay-deterministic
        assert len(set(h1)) == 6             # spread, not lockstep
        assert all(0.5 <= h < 1.0 for h in h1), h1
    finally:
        shutil.rmtree(root, ignore_errors=True)

    assert "jax" not in sys.modules
    dt = time.perf_counter() - t_start
    print(f"durability smoke OK ({dt:.2f}s, no jax)")
    assert dt < 10.0, f"durability smoke exceeded 10s: {dt:.1f}s"
    return 0


if __name__ == "__main__":
    sys.exit(main())
