#!/usr/bin/env python
"""jax-free smoke of the declarative geometry layer (ISSUE 16).

Constructs, resolves, serializes, and tuned()-round-trips
`ziria_tpu.utils.geometry.Geometry` WITHOUT importing jax — the same
through-TPU-probe-hangs discipline as chaos/serve/durability smokes —
and pins that the default Geometry still resolves to the tree's
historical constants (the zero-new-programs / bit-identity guarantee
rests on exactly these values; tests/test_geometry.py pins the
compiled side). Wired into tools/precommit.sh. Sub-second.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from ziria_tpu.utils import geometry  # noqa: E402
from ziria_tpu.utils.geometry import Geometry  # noqa: E402

checks = 0


def ok(cond, what):
    global checks
    checks += 1
    if not cond:
        print(f"geometry_smoke: FAIL — {what}", file=sys.stderr)
        raise SystemExit(1)


def main():
    ok("jax" not in sys.modules,
       "importing utils.geometry pulled in jax (the smoke must run "
       "through TPU probe hangs)")

    # the default IS the tree's historical constants — drift here
    # breaks the no-op-by-construction guarantee
    g = Geometry()
    ok(g.chunk_len == 8192 and g.frame_len == 2048
       and g.max_frames_per_chunk == 8 and g.n_streams == 8,
       f"default fleet geometry drifted: {g}")
    ok(g.sym_bucket_min == 4 and g.capture_bucket_min == 512
       and g.bit_bucket_min == 128,
       f"default bucket floors drifted: {g}")
    ok((g.threshold, g.min_run, g.dead_zone) == (0.75, 33, 320),
       f"default detector params drifted: {g}")
    ok(g.sym_bucket(3) == 4 and g.sym_bucket(21) == 32
       and g.capture_bucket(100) == 512 and g.bit_bucket(1) == 128,
       "bucket rules diverged from pow2_bucket floors")

    # frozen + hashable: Geometry is a dict key / part of cache keys
    ok(hash(g) == hash(Geometry()), "equal geometries hash unequal")
    try:
        g.chunk_len = 1
        ok(False, "frozen dataclass accepted a field write")
    except Exception:
        pass

    # resolve() folds env exactly once, under a scoped set+restore
    old = {k: os.environ.get(k) for k in
           ("ZIRIA_VITERBI_RADIX", "ZIRIA_RX_SCO_TRACK")}
    try:
        os.environ["ZIRIA_VITERBI_RADIX"] = "4"
        os.environ["ZIRIA_RX_SCO_TRACK"] = "1"
        r = g.resolve()
        ok(r.viterbi_radix == 4 and r.sco_track is True,
           f"resolve() missed the env knobs: {r}")
        ok(g.viterbi_radix is None,
           "resolve() mutated the source geometry")
        explicit = g.replace(viterbi_radix=2).resolve()
        ok(explicit.viterbi_radix == 2,
           "an explicit field lost to the env default")
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    r = g.resolve()
    ok((r.viterbi_window, r.viterbi_metric, r.viterbi_radix,
        r.fused_demap, r.sco_track) == (0, "float32", 2, False, False),
       f"clean-env resolve() drifted from the historical defaults: {r}")
    ok(r.resolve() == r, "resolve() is not idempotent")

    # serialization round-trips, strictly
    ok(Geometry.from_json(r.to_json()) == r,
       "to_json/from_json round trip lost a field")
    try:
        Geometry.from_dict({"chunk_len": 4096, "warp_factor": 9})
        ok(False, "from_dict accepted an unknown field")
    except ValueError:
        pass

    # tuned(): reconstructs a ledger winner; degrades to default on
    # any miss (absent ledger, foreign device, malformed record)
    with tempfile.TemporaryDirectory() as td:
        ledger = os.path.join(td, "traj.jsonl")
        ok(Geometry.tuned("v5e", path=ledger) == Geometry(),
           "tuned() with no ledger is not the default")
        win = r.replace(chunk_len=16384)
        with open(ledger, "w") as f:
            f.write("garbage line\n")
            f.write(json.dumps({
                "stage": "autotune", "metric": "sps_tuned",
                "value": 1.0, "unix": 1.0, "device_kind": "v5e",
                "geometry": win.as_dict()}) + "\n")
        ok(Geometry.tuned("v5e", path=ledger) == win,
           "tuned() did not reconstruct the recorded winner")
        ok(Geometry.tuned("cpu", path=ledger) == Geometry(),
           "tuned() served a v5e winner to a cpu device")
        ok(geometry.latest_tuned_record("cpu", path=ledger) is None,
           "latest_tuned_record matched across device kinds")

    ok("jax" not in sys.modules,
       "a geometry code path imported jax")
    print(f"geometry_smoke: OK ({checks} checks, no jax)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
