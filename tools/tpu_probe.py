#!/usr/bin/env python3
"""Out-of-band watchdogged TPU probe runner (ISSUE 20 satellite).

The axon TPU backend hangs rather than fails (BENCH_PROBES.jsonl
availability ledger), so any in-process probe risks taking its caller
down with it. This runner keeps the probe OUT of band: the actual
backend touch (``bench.py --tpu-probe``: init + one tiny computation)
runs in a subprocess under a hard kill timeout, the parent never
imports jax, and every definitive outcome is appended to the same
BENCH_PROBES.jsonl schema bench.py and tools/tpu_watcher.sh share —
so the next bench run can trust (or skip re-paying) this answer and
finally price the PR 6-16 levers on real hardware.

Outcomes and exit codes:

    ok    exit 0   backend initialized and computed on a non-CPU device
    fail  exit 1   probe exited non-zero, timed out (hang => kill), or
                   only a CPU device answered (bench exit 3)
    busy  exit 2   another client holds /tmp/tpu_busy (says nothing
                   about tunnel health; never cached as a failure)

A cached definitive outcome younger than ``--ttl`` seconds (default
600, same as bench.py's BENCH_PROBE_NEG_TTL; 0 disables) is returned
without touching the backend — ``--force`` re-probes regardless. The
/tmp/tpu_busy mutual-exclusion flag is honored exactly like bench.py:
``TPU_BUSY_HELD=1`` means the invoker already holds it, a flag older
than 35 min is treated as leaked and taken over, and this runner's
own flag is always released. stdout carries exactly one JSON object.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBES_PATH = os.path.join(REPO, "BENCH_PROBES.jsonl")
BENCH = os.path.join(REPO, "bench.py")

BUSY_FLAG = "/tmp/tpu_busy"
BUSY_STALE_S = 35 * 60        # same leak threshold as bench.py
DEFAULT_TIMEOUT = 90.0        # bench.py PROBE_TIMEOUT
DEFAULT_TTL = 600.0           # bench.py PROBE_NEG_TTL


def _record(kind: str, err=None, extra=None) -> dict:
    """Append one availability-ledger record (bench.py schema: t /
    probe / unix / src, err on failures). Best-effort append — an
    unwritable ledger degrades to stdout-only, never a crash."""
    rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "probe": kind, "unix": round(time.time(), 1),
           "src": "tools/tpu_probe.py"}
    if err:
        rec["err"] = err
    if extra:
        rec.update(extra)
    try:
        with open(PROBES_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return rec


def _cached(ttl: float):
    """Most recent definitive (ok/fail) ledger outcome within ttl, or
    None. Scans the whole ledger so out-of-order appends from
    concurrent writers can't shadow a later outcome; garbage lines
    and 'busy' records are skipped (busy says nothing about health)."""
    if ttl <= 0:
        return None
    now = time.time()
    best_t, best = None, None
    try:
        with open(PROBES_PATH) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("probe") not in ("ok", "fail"):
                    continue
                t = rec.get("unix")
                if t is None:
                    try:
                        import calendar
                        t = calendar.timegm(time.strptime(
                            rec.get("t", ""), "%Y-%m-%dT%H:%M:%SZ"))
                    except (ValueError, TypeError):
                        continue
                if t <= now and (best_t is None or t >= best_t):
                    best_t, best = t, rec
    except OSError:
        return None
    if best is not None and now - best_t < ttl:
        best = dict(best)
        best["age_s"] = round(now - best_t, 1)
        return best
    return None


def _acquire_busy() -> bool:
    """Take /tmp/tpu_busy (non-blocking — a probe that queues behind a
    long harvest defeats its own watchdog). Leaked flags older than
    BUSY_STALE_S are taken over, like bench.py."""
    if os.environ.get("TPU_BUSY_HELD") == "1":
        return True
    for _ in range(2):
        try:
            fd = os.open(BUSY_FLAG, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"tools/tpu_probe.py pid={os.getpid()}\n".encode())
            os.close(fd)
            return True
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(BUSY_FLAG)
            except OSError:
                continue          # holder just released; retry create
            if age <= BUSY_STALE_S:
                return False
            print(f"[tpu-probe] stale {BUSY_FLAG} ({age:.0f}s) — "
                  "taking over", file=sys.stderr, flush=True)
            try:
                os.unlink(BUSY_FLAG)
            except OSError:
                pass
    return False


def _release_busy() -> None:
    if os.environ.get("TPU_BUSY_HELD") == "1":
        return
    try:
        with open(BUSY_FLAG) as f:
            if "tools/tpu_probe.py" not in f.read():
                return            # not ours
        os.unlink(BUSY_FLAG)
    except OSError:
        pass


def _probe_once(timeout: float):
    """One subprocess probe under a hard kill. Returns (kind, err,
    extra): kind ok/fail, err text on failure, extra = the child's
    platform/device_kind JSON on success."""
    cmd = [sys.executable, BENCH, "--tpu-probe"]
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        # subprocess.run kills the child on timeout — the hang dies
        # with the probe, not with whoever asked for the answer
        return "fail", f"timeout after {timeout:.0f}s (hang, killed)", None
    if proc.returncode == 0:
        extra = None
        try:
            extra = json.loads(proc.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            pass
        return "ok", None, extra
    if proc.returncode == 3:
        return "fail", "no accelerator (CPU-only backend, rc=3)", None
    tail = (proc.stderr or "").strip().splitlines()[-2:]
    return "fail", f"rc={proc.returncode}: " + " | ".join(tail), None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT,
                    help="hard kill timeout for the subprocess probe "
                         f"(default {DEFAULT_TIMEOUT:.0f}s)")
    ap.add_argument("--ttl", type=float, default=DEFAULT_TTL,
                    help="trust a ledger outcome younger than this "
                         f"(default {DEFAULT_TTL:.0f}s; 0 disables)")
    ap.add_argument("--force", action="store_true",
                    help="probe even if a fresh ledger outcome exists")
    args = ap.parse_args(argv)

    if not args.force:
        hit = _cached(args.ttl)
        if hit is not None:
            hit["cached"] = True
            print(json.dumps(hit), flush=True)
            return 0 if hit["probe"] == "ok" else 1

    if not _acquire_busy():
        rec = _record("busy")
        print(json.dumps(rec), flush=True)
        return 2
    try:
        kind, err, extra = _probe_once(args.timeout)
    finally:
        _release_busy()
    rec = _record(kind, err=err, extra=extra)
    print(json.dumps(rec), flush=True)
    return 0 if kind == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
