"""Run the DSL receiver (examples/wifi_rx.zir) on the REAL TPU via the
hybrid backend and record the evidence: the same jitted do-blocks the
CPU tests exercise must compile and run on the chip, bit-identical to
the interpreter oracle.

    python tools/hybrid_tpu_check.py          # needs the TPU reachable

Emits one JSON line: platform, per-frame cold/warm wall times, and the
bit-exactness verdict. Wall times include the host-side control loop
(the hybrid design point), so they are NOT a throughput claim — the
throughput metric is bench.py's batched library receiver.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# run as `python tools/hybrid_tpu_check.py`: the script dir is on
# sys.path, the repo root is not
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def main() -> int:
    # pin the BASELINE run to the exact decoder no matter what the
    # operator's environment exports — otherwise the 'identical'
    # verdict would compare windowed vs windowed — and restore the
    # variable on exit (review r5)
    prev_vw = os.environ.pop("ZIRIA_VITERBI_WINDOW", None)
    try:
        return _run()
    finally:
        if prev_vw is not None:
            os.environ["ZIRIA_VITERBI_WINDOW"] = prev_vw


def _run() -> int:
    import jax

    # the CLI's platform pin (honors ZIRIA_PLATFORM, guards an
    # already-initialized backend) so a CPU smoke run refuses fast
    # instead of touching (and possibly hanging on) the axon backend
    from ziria_tpu.runtime.cli import _apply_platform
    # ZIRIA_TOOL_ALLOW_CPU=1: run the whole check body on CPU so a
    # broken tool cannot waste a real TPU window; the emitted record
    # is labelled platform=cpu and the watcher only keeps TPU results
    smoke = os.environ.get("ZIRIA_TOOL_ALLOW_CPU") == "1"
    if smoke:
        jax.config.update("jax_platforms", "cpu")
    else:
        _apply_platform(None)

    dev = jax.devices()[0]
    if dev.platform == "cpu" and not smoke:
        print(json.dumps({"ok": False, "error": "backend is CPU"}))
        return 1

    import jax.numpy as jnp

    from ziria_tpu.backend.hybrid import hybridize
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.interp.interp import run
    from ziria_tpu.phy import channel
    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(42)
    psdu = rng.integers(0, 256, 90).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, 54, add_fcs=True))
    x = np.concatenate([
        rng.normal(scale=0.02, size=(60, 2)).astype(np.float32),
        np.asarray(channel.apply_cfo(jnp.asarray(frame), 0.002)),
        rng.normal(scale=0.02, size=(40, 2)).astype(np.float32)])
    x = (x + rng.normal(scale=0.03, size=x.shape)).astype(np.float32)
    xi = np.clip(np.round(x * 1024), -32768, 32767).astype(np.int16)

    prog = compile_file("examples/wifi_rx.zir")
    hyb = hybridize(prog.comp)

    t0 = time.perf_counter()
    r1 = run(hyb, [p for p in xi])
    t_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    r2 = run(hyb, [p for p in xi])
    t_warm = time.perf_counter() - t0

    oracle = run(prog.comp, [p for p in xi])
    a = np.asarray(r1.out_array())
    ok = (np.array_equal(a, np.asarray(oracle.out_array()))
          and np.array_equal(a, np.asarray(r2.out_array()))
          and a.shape[0] == 8 * 90)

    # the same compiled receiver under --viterbi-window (r5): the
    # sliding-window parallel decode must produce the identical bits
    # and its warm time is the DSL path's chip gain from cutting the
    # trellis dependency chain
    win_ev = None
    try:
        os.environ["ZIRIA_VITERBI_WINDOW"] = "512"
        hyb_w = hybridize(compile_file("examples/wifi_rx.zir").comp)
        t0 = time.perf_counter()
        rw1 = run(hyb_w, [p for p in xi])
        t_wcold = time.perf_counter() - t0
        t0 = time.perf_counter()
        rw2 = run(hyb_w, [p for p in xi])
        t_wwarm = time.perf_counter() - t0
        aw = np.asarray(rw1.out_array())
        win_ev = {
            "identical": bool(np.array_equal(aw, a) and np.array_equal(
                aw, np.asarray(rw2.out_array()))),
            "window": 512,
            "t_cold_s": round(t_wcold, 3),
            "t_warm_s": round(t_wwarm, 3),
        }
    except Exception as e:              # evidence extra: never fatal
        win_ev = {"error": repr(e)}
    finally:
        os.environ.pop("ZIRIA_VITERBI_WINDOW", None)
    ok = ok and bool(win_ev.get("identical", True))

    # FIXED-POINT cross-backend exactness, measured: replay the
    # checked-in wifi_rx_fxp golden ON THIS BACKEND and require
    # byte-identity with the ground file that CPU CI pins
    # (docs/fixed_point.md's central claim, as chip evidence: the
    # input bytes are fixed on disk, so any deviation here would be a
    # backend-dependent integer op)
    from ziria_tpu.runtime.buffers import StreamSpec, read_stream
    fxp_prog = compile_file("examples/wifi_rx_fxp.zir",
                            fxp_complex16=True)
    fxp_in = read_stream(StreamSpec(
        ty="complex16", path="examples/golden/wifi_rx_fxp.infile",
        mode="bin"))
    fxp_want = read_stream(StreamSpec(
        ty="bit", path="examples/golden/wifi_rx_fxp.outfile.ground",
        mode="bin"))
    t0 = time.perf_counter()
    fxp_got = np.asarray(run(hybridize(fxp_prog.comp),
                             [p for p in np.asarray(fxp_in)])
                         .out_array(), np.uint8)
    t_fxp = time.perf_counter() - t0
    fxp_ok = np.array_equal(fxp_got,
                            np.asarray(fxp_want,
                                       np.uint8)[:fxp_got.shape[0]]) \
        and fxp_got.shape[0] == np.asarray(fxp_want).shape[0]

    print(json.dumps({
        "ok": bool(ok and fxp_ok),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "rate_mbps": 54,
        "t_cold_s": round(t_cold, 3),
        "t_warm_s": round(t_warm, 3),
        "bits": int(a.shape[0]),
        "windowed_viterbi": win_ev,
        "fxp_golden_identical": bool(fxp_ok),
        "t_fxp_cold_s": round(t_fxp, 3),
    }))
    return 0 if (ok and fxp_ok) else 2


if __name__ == "__main__":
    sys.exit(main())
