"""Summarize a telemetry Chrome trace (utils/telemetry.Trace.export)
as a per-label latency table.

A trace file answers "what happened when" in Perfetto; this tool
answers the quicker question — "where did the time go, and was any of
it compiles?" — without leaving the terminal:

    python tools/trace_report.py TRACE.json

prints one row per span label (count, exact p50/p99/max milliseconds
computed from the raw event durations — the trace has every duration,
so no bucket bounds needed here — and total ms), spans and compile
events in separate sections, plus the counter tracks' last/max levels.
`bench.py`'s streaming stage runs :func:`summarize_file` on the trace
it exports so every bench run leaves a readable summary next to its
JSON artifacts; `tests/test_telemetry.py` pins the parse against
traces the layer actually writes.

When the trace object carries the program observatory's riders
(``siteCosts``: per-label analytical FLOPs / bytes accessed from XLA
cost analysis; optional ``devicePeaks``/``deviceKind`` — all embedded
by `tools/rx_dispatch_bench.streaming_stats`), the span table grows
achieved GB/s and %-of-HBM-peak columns: measured p50 x compiled-graph
bytes, per dispatch site. ``--costs FILE`` supplies the same data
externally (the ``python -m ziria_tpu programs --json`` report, or a
bare ``{label: {bytes_accessed, flops}}`` map).

    python tools/trace_report.py --compare A.json B.json \
        [--threshold 0.2]

compares two traces per label (p50/p99 delta table, reusing the same
parse); with ``--threshold``, a p50 regression beyond the fraction on
any shared label exits 1 — a trace-level perf gate to go with
tools/perf_report.py's trajectory gate.
"""

import json
import sys


def load_obj(path):
    """The raw exported object ({"traceEvents": [...], riders...}) or
    a bare event array wrapped into that form."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict):
        return obj
    return {"traceEvents": obj}


def load(path):
    """The trace's event list. Accepts both the exported object form
    ({"traceEvents": [...]}) and a bare JSON array of events."""
    return load_obj(path).get("traceEvents", [])


def site_costs_of(obj):
    """Normalize a costs rider/file into {label: {"bytes_accessed",
    "flops"}}: accepts the trace's embedded ``siteCosts``, the
    ``programs --json`` report (``programs`` record list, keyed by
    ``label``; the largest-bytes record per label wins), or a bare
    label->cost map."""
    if not isinstance(obj, dict):
        return {}
    if "siteCosts" in obj:
        obj = obj["siteCosts"]
    if "programs" in obj and isinstance(obj["programs"], list):
        out = {}
        for r in obj["programs"]:
            label = r.get("label")
            if not label or r.get("error") or \
                    not r.get("bytes_accessed"):
                continue
            cur = out.get(label)
            if cur is None or r["bytes_accessed"] > \
                    cur["bytes_accessed"]:
                out[label] = {"bytes_accessed": r["bytes_accessed"],
                              "flops": r.get("flops", 0.0)}
        return out
    return {k: v for k, v in obj.items()
            if isinstance(v, dict) and v.get("bytes_accessed")}


def _rank(sorted_vals, q):
    """Exact nearest-rank q-quantile of an ascending list."""
    import math
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(1, math.ceil(q * n)) - 1)]


def summarize(events):
    """Per-label rollup of a trace-event list. Returns a dict:

    - ``spans``: {label: {count, p50_ms, p99_ms, max_ms, total_ms}}
      over complete ("X") events NOT in the compile category;
    - ``compiles``: the same rollup over compile-category complete
      events, plus {label: count} instant compile markers (cache
      growth deltas) under ``compile_markers``;
    - ``counters``: {name: {samples, last, max}} from counter tracks.
    """
    spans = {}
    compiles = {}
    markers = {}
    counters = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "?")
        cat = ev.get("cat", "")
        if ph == "X":
            (compiles if cat == "compile" else spans).setdefault(
                name, []).append(float(ev.get("dur", 0.0)) / 1e3)
        elif ph == "i" and cat == "compile":
            # cache-growth markers carry the entry delta in args
            # (new_entries from dispatch.cache_growth, count from a
            # bare record_compile); an unweighted marker counts as one
            a = ev.get("args", {})
            d = a.get("new_entries", a.get("count", 1))
            markers[name] = markers.get(name, 0) + int(d)
        elif ph == "C":
            v = ev.get("args", {}).get("value")
            if v is None:           # foreign counter form: first arg
                a = ev.get("args", {})
                v = next(iter(a.values()), None) if a else None
            if v is not None:
                c = counters.setdefault(name,
                                        {"samples": 0, "last": None,
                                         "max": float("-inf")})
                c["samples"] += 1
                c["last"] = float(v)
                c["max"] = max(c["max"], float(v))

    def rollup(durs_by_label):
        out = {}
        for label, ds in sorted(durs_by_label.items()):
            ds.sort()
            out[label] = {
                "count": len(ds),
                "p50_ms": round(_rank(ds, 0.50), 3),
                "p99_ms": round(_rank(ds, 0.99), 3),
                "max_ms": round(ds[-1], 3),
                "total_ms": round(sum(ds), 3),
            }
        return out

    return {"spans": rollup(spans), "compiles": rollup(compiles),
            "compile_markers": markers, "counters": counters}


def format_table(summary, site_costs=None, peaks=None):
    """The human-readable report: one aligned table per section. With
    ``site_costs`` (label -> analytical cost), the span rows gain
    achieved GB/s (compiled-graph bytes / measured p50) and — when the
    device peaks are known — %-of-HBM-peak."""
    lines = []
    site_costs = site_costs or {}

    def section(title, rows, costs=None):
        if not rows:
            return
        lines.append(title)
        w = max(len(k) for k in rows)
        head = (f"  {'label':<{w}} {'count':>6} {'p50 ms':>9} "
                f"{'p99 ms':>9} {'max ms':>9} {'total ms':>10}")
        if costs:
            head += f" {'GB/s':>8}"
            if peaks:
                head += f" {'%HBM':>7}"
        lines.append(head)
        for label, r in rows.items():
            line = (
                f"  {label:<{w}} {r['count']:>6} {r['p50_ms']:>9.3f} "
                f"{r['p99_ms']:>9.3f} {r['max_ms']:>9.3f} "
                f"{r['total_ms']:>10.3f}")
            if costs:
                c = costs.get(label)
                if c and r["p50_ms"] > 0:
                    gbps = c["bytes_accessed"] / (r["p50_ms"] / 1e3) / 1e9
                    line += f" {gbps:>8.2f}"
                    if peaks:
                        pct = 100 * gbps / peaks["hbm_gbps"]
                        line += f" {pct:>7.2f}"
                else:
                    line += f" {'-':>8}" + (f" {'-':>7}" if peaks
                                            else "")
            lines.append(line)

    section("spans:", summary["spans"], site_costs)
    section("compile events:", summary["compiles"])
    if summary["compile_markers"]:
        lines.append("compile markers (cache growth):")
        for name, n in sorted(summary["compile_markers"].items()):
            lines.append(f"  {name}: {n}")
    if summary["counters"]:
        lines.append("counter tracks:")
        for name, c in sorted(summary["counters"].items()):
            lines.append(f"  {name}: {c['samples']} samples, "
                         f"last={c['last']:g} max={c['max']:g}")
    return "\n".join(lines)


def summarize_file(path, costs_path=None):
    """(summary dict, formatted table) for a trace file — the one-call
    surface bench.py's streaming stage uses. Cost columns come from
    the trace's embedded ``siteCosts`` rider, overridable/suppliable
    via ``costs_path``."""
    def usable_peaks(p):
        # only a single resolved {hbm_gbps, ...} entry renders %HBM —
        # a per-kind TABLE or anything else is not a ceiling
        return p if isinstance(p, dict) and "hbm_gbps" in p else None

    obj = load_obj(path)
    s = summarize(obj.get("traceEvents", []))
    costs = site_costs_of(obj)
    peaks = usable_peaks(obj.get("devicePeaks"))
    if costs_path:
        with open(costs_path) as f:
            ext = json.load(f)
        costs = site_costs_of(ext) or costs
        if isinstance(ext, dict) and "devicePeaks" in ext:
            peaks = usable_peaks(ext["devicePeaks"]) or peaks
    return s, format_table(s, site_costs=costs, peaks=peaks)


def compare_summaries(sa, sb, threshold=None):
    """Per-label span delta between two summaries. Returns (rows,
    regressed): rows are (label, count_a, count_b, p50_a, p50_b,
    dp50_frac, p99_a, p99_b) over the union of span labels;
    ``regressed`` holds labels whose p50 grew by more than
    ``threshold`` (fraction) — None disables flagging."""
    rows, regressed = [], []
    labels = sorted(set(sa["spans"]) | set(sb["spans"]))
    for label in labels:
        a = sa["spans"].get(label)
        b = sb["spans"].get(label)
        if a is None or b is None:
            rows.append((label,
                         a and a["count"], b and b["count"],
                         a and a["p50_ms"], b and b["p50_ms"], None,
                         a and a["p99_ms"], b and b["p99_ms"]))
            continue
        frac = ((b["p50_ms"] - a["p50_ms"]) / a["p50_ms"]
                if a["p50_ms"] > 0 else None)
        rows.append((label, a["count"], b["count"], a["p50_ms"],
                     b["p50_ms"], frac, a["p99_ms"], b["p99_ms"]))
        if threshold is not None and frac is not None \
                and frac > threshold:
            regressed.append(label)
    return rows, regressed


def format_compare(rows, name_a="A", name_b="B", regressed=()):
    w = max([len("label")] + [len(r[0]) for r in rows])
    lines = [f"{'label':<{w}} {'n(A)':>6} {'n(B)':>6} "
             f"{'p50 A ms':>9} {'p50 B ms':>9} {'d p50':>7} "
             f"{'p99 A ms':>9} {'p99 B ms':>9}  flag"]

    def fmt(v, spec):
        return format(v, spec) if v is not None else "-"

    for label, ca, cb, p50a, p50b, frac, p99a, p99b in rows:
        lines.append(
            f"{label:<{w}} {fmt(ca, '>6')} {fmt(cb, '>6')} "
            f"{fmt(p50a, '>9.3f')} {fmt(p50b, '>9.3f')} "
            f"{fmt(frac, '>+7.1%')} "
            f"{fmt(p99a, '>9.3f')} {fmt(p99b, '>9.3f')}  "
            f"{'REGRESSED' if label in regressed else ''}")
    lines.append(f"A = {name_a}, B = {name_b}")
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="trace_report",
        description="per-label latency summary of a telemetry Chrome "
                    "trace; --compare diffs two traces")
    ap.add_argument("traces", nargs="*", metavar="TRACE.json")
    ap.add_argument("--costs", metavar="FILE", default=None,
                    help="per-label analytical costs (siteCosts map or"
                         " a `programs --json` report) for the GB/s "
                         "columns")
    ap.add_argument("--compare", nargs=2, metavar=("A.json", "B.json"),
                    default=None,
                    help="per-label p50/p99 delta table between two "
                         "traces")
    ap.add_argument("--threshold", type=float, default=None,
                    help="with --compare: exit 1 when any label's p50 "
                         "regressed by more than this fraction")
    args = ap.parse_args(argv)

    if args.compare:
        pa, pb = args.compare
        try:
            sa = summarize(load(pa))
            sb = summarize(load(pb))
        except (OSError, ValueError) as e:
            print(f"error: cannot read trace: {e}", file=sys.stderr)
            return 1
        rows, regressed = compare_summaries(sa, sb, args.threshold)
        print(format_compare(rows, pa, pb, regressed)
              or "(no spans)")
        if regressed:
            print(f"trace_report: {len(regressed)} label(s) regressed "
                  f"beyond {args.threshold:.0%}", file=sys.stderr)
            return 1
        return 0

    if len(args.traces) != 1:
        ap.print_usage(sys.stderr)
        return 2
    try:
        _s, table = summarize_file(args.traces[0],
                                   costs_path=args.costs)
    except (OSError, ValueError) as e:
        print(f"error: cannot read trace {args.traces[0]!r}: {e}",
              file=sys.stderr)
        return 1
    print(table or "(empty trace)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
