"""Summarize a telemetry Chrome trace (utils/telemetry.Trace.export)
as a per-label latency table.

A trace file answers "what happened when" in Perfetto; this tool
answers the quicker question — "where did the time go, and was any of
it compiles?" — without leaving the terminal:

    python tools/trace_report.py TRACE.json

prints one row per span label (count, exact p50/p99/max milliseconds
computed from the raw event durations — the trace has every duration,
so no bucket bounds needed here — and total ms), spans and compile
events in separate sections, plus the counter tracks' last/max levels.
`bench.py`'s streaming stage runs :func:`summarize_file` on the trace
it exports so every bench run leaves a readable summary next to its
JSON artifacts; `tests/test_telemetry.py` pins the parse against
traces the layer actually writes.
"""

import json
import sys


def load(path):
    """The trace's event list. Accepts both the exported object form
    ({"traceEvents": [...]}) and a bare JSON array of events."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict):
        return obj.get("traceEvents", [])
    return obj


def _rank(sorted_vals, q):
    """Exact nearest-rank q-quantile of an ascending list."""
    import math
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(1, math.ceil(q * n)) - 1)]


def summarize(events):
    """Per-label rollup of a trace-event list. Returns a dict:

    - ``spans``: {label: {count, p50_ms, p99_ms, max_ms, total_ms}}
      over complete ("X") events NOT in the compile category;
    - ``compiles``: the same rollup over compile-category complete
      events, plus {label: count} instant compile markers (cache
      growth deltas) under ``compile_markers``;
    - ``counters``: {name: {samples, last, max}} from counter tracks.
    """
    spans = {}
    compiles = {}
    markers = {}
    counters = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "?")
        cat = ev.get("cat", "")
        if ph == "X":
            (compiles if cat == "compile" else spans).setdefault(
                name, []).append(float(ev.get("dur", 0.0)) / 1e3)
        elif ph == "i" and cat == "compile":
            # cache-growth markers carry the entry delta in args
            # (new_entries from dispatch.cache_growth, count from a
            # bare record_compile); an unweighted marker counts as one
            a = ev.get("args", {})
            d = a.get("new_entries", a.get("count", 1))
            markers[name] = markers.get(name, 0) + int(d)
        elif ph == "C":
            v = ev.get("args", {}).get("value")
            if v is None:           # foreign counter form: first arg
                a = ev.get("args", {})
                v = next(iter(a.values()), None) if a else None
            if v is not None:
                c = counters.setdefault(name,
                                        {"samples": 0, "last": None,
                                         "max": float("-inf")})
                c["samples"] += 1
                c["last"] = float(v)
                c["max"] = max(c["max"], float(v))

    def rollup(durs_by_label):
        out = {}
        for label, ds in sorted(durs_by_label.items()):
            ds.sort()
            out[label] = {
                "count": len(ds),
                "p50_ms": round(_rank(ds, 0.50), 3),
                "p99_ms": round(_rank(ds, 0.99), 3),
                "max_ms": round(ds[-1], 3),
                "total_ms": round(sum(ds), 3),
            }
        return out

    return {"spans": rollup(spans), "compiles": rollup(compiles),
            "compile_markers": markers, "counters": counters}


def format_table(summary):
    """The human-readable report: one aligned table per section."""
    lines = []

    def section(title, rows):
        if not rows:
            return
        lines.append(title)
        w = max(len(k) for k in rows)
        lines.append(f"  {'label':<{w}} {'count':>6} {'p50 ms':>9} "
                     f"{'p99 ms':>9} {'max ms':>9} {'total ms':>10}")
        for label, r in rows.items():
            lines.append(
                f"  {label:<{w}} {r['count']:>6} {r['p50_ms']:>9.3f} "
                f"{r['p99_ms']:>9.3f} {r['max_ms']:>9.3f} "
                f"{r['total_ms']:>10.3f}")

    section("spans:", summary["spans"])
    section("compile events:", summary["compiles"])
    if summary["compile_markers"]:
        lines.append("compile markers (cache growth):")
        for name, n in sorted(summary["compile_markers"].items()):
            lines.append(f"  {name}: {n}")
    if summary["counters"]:
        lines.append("counter tracks:")
        for name, c in sorted(summary["counters"].items()):
            lines.append(f"  {name}: {c['samples']} samples, "
                         f"last={c['last']:g} max={c['max']:g}")
    return "\n".join(lines)


def summarize_file(path):
    """(summary dict, formatted table) for a trace file — the one-call
    surface bench.py's streaming stage uses."""
    s = summarize(load(path))
    return s, format_table(s)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python tools/trace_report.py TRACE.json",
              file=sys.stderr)
        return 2
    try:
        _s, table = summarize_file(argv[0])
    except (OSError, ValueError) as e:
        print(f"error: cannot read trace {argv[0]!r}: {e}",
              file=sys.stderr)
        return 1
    print(table or "(empty trace)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
