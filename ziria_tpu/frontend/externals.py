"""Externals registry: the frontend's `ext fun` binding surface.

Counterpart of the reference's `lib/` ext declarations binding SORA C
functions into the language (SURVEY.md §2.3) — here each name binds to a
jnp implementation, so `ext fun v_fft(...)` in a source program resolves
to `jnp.fft.fft` instead of a SORA SSE brick. A program must still
*declare* the ext funs it uses (declarations are checked against this
registry), keeping source files self-describing like the reference's.

Builtins (`length`, `abs`, ...) are available without declaration.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np


def _jnp():
    import jax.numpy as jnp
    return jnp


def _length(x) -> int:
    shape = np.shape(x)
    if not shape:
        raise ValueError("length() of a scalar")
    return int(shape[0])


def _f(fn_name: str) -> Callable:
    def wrapper(*args):
        jnp = _jnp()
        return getattr(jnp, fn_name)(*[jnp.asarray(a) for a in args])
    wrapper.__name__ = fn_name
    return wrapper


def _fft(x):
    jnp = _jnp()
    return jnp.fft.fft(jnp.asarray(x, jnp.complex64)).astype(jnp.complex64)


def _ifft(x):
    jnp = _jnp()
    return jnp.fft.ifft(jnp.asarray(x, jnp.complex64)).astype(jnp.complex64)


def _sum(x):
    return _jnp().sum(_jnp().asarray(x), axis=0)


# always available, no declaration needed
BUILTINS: Dict[str, Callable] = {
    "length": _length,
    "abs": _f("abs"),
    "min": _f("minimum"),
    "max": _f("maximum"),
    "sum": _sum,
}

# available via `ext fun` declaration (names mirror the reference's lib/)
EXTERNALS: Dict[str, Callable] = {
    "sqrt": _f("sqrt"),
    "log": _f("log"),
    "exp": _f("exp"),
    "sin": _f("sin"),
    "cos": _f("cos"),
    "tan": _f("tan"),
    "atan": _f("arctan"),
    "atan2": _f("arctan2"),
    "round_int": lambda x: _jnp().round(_jnp().asarray(x)).astype(
        _jnp().int32),
    "floor": _f("floor"),
    "ceil": _f("ceil"),
    "conj": _f("conj"),
    # SORA-style vector DSP (SURVEY.md §2.2 sora_ext_lib.c equivalents)
    "v_fft": _fft,
    "v_ifft": _ifft,
    "fft": _fft,
    "ifft": _ifft,
}


def register_external(name: str, fn: Callable) -> None:
    """Extend the registry (used by ops/ext_math and user code)."""
    EXTERNALS[name] = fn


def resolve_ext(name: str) -> Callable:
    fn = EXTERNALS.get(name)
    if fn is None:
        # the fixed-point math library self-registers on import
        import ziria_tpu.ops.ext_math  # noqa: F401
        fn = EXTERNALS.get(name)
    if fn is None:
        known = ", ".join(sorted(EXTERNALS))
        raise KeyError(
            f"ext fun {name!r} is not in the externals registry "
            f"(known: {known}); register it with "
            f"ziria_tpu.frontend.externals.register_external")
    return fn
