"""Externals registry: the frontend's `ext fun` binding surface.

Counterpart of the reference's `lib/` ext declarations binding SORA C
functions into the language (SURVEY.md §2.3) — here each name binds to a
jnp implementation, so `ext fun v_fft(...)` in a source program resolves
to `jnp.fft.fft` instead of a SORA SSE brick. A program must still
*declare* the ext funs it uses (declarations are checked against this
registry), keeping source files self-describing like the reference's.

Builtins (`length`, `abs`, ...) are available without declaration.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np


def _jnp():
    import jax.numpy as jnp
    return jnp


def _xp(args):
    """numpy for concrete values, jnp only under a jax trace.

    The interpreter backend evaluates ext calls on concrete scalars and
    arrays in tight per-sample loops; returning jax Arrays there makes
    every subsequent indexing/arithmetic a device dispatch (measured
    ~200x slower than numpy). The jit backend traces through the same
    registry with Tracer arguments, which must stay in jnp.
    """
    from jax.core import Tracer
    if any(isinstance(a, Tracer) for a in args):
        return _jnp()
    return np


def _length(x) -> int:
    shape = np.shape(x)
    if not shape:
        raise ValueError("length() of a scalar")
    return int(shape[0])


def _f(fn_name: str) -> Callable:
    def wrapper(*args):
        xp = _xp(args)
        return getattr(xp, fn_name)(*[xp.asarray(a) for a in args])
    wrapper.__name__ = fn_name
    return wrapper


def _fft(x):
    xp = _xp((x,))
    return xp.fft.fft(xp.asarray(x, xp.complex64)).astype(xp.complex64)


def _ifft(x):
    xp = _xp((x,))
    return xp.fft.ifft(xp.asarray(x, xp.complex64)).astype(xp.complex64)


def _sum(x):
    xp = _xp((x,))
    return xp.sum(xp.asarray(x), axis=0)


# always available, no declaration needed
BUILTINS: Dict[str, Callable] = {
    "length": _length,
    "abs": _f("abs"),
    "min": _f("minimum"),
    "max": _f("maximum"),
    "sum": _sum,
}

# available via `ext fun` declaration (names mirror the reference's lib/)
EXTERNALS: Dict[str, Callable] = {
    "sqrt": _f("sqrt"),
    "log": _f("log"),
    "exp": _f("exp"),
    "sin": _f("sin"),
    "cos": _f("cos"),
    "tan": _f("tan"),
    "atan": _f("arctan"),
    "atan2": _f("arctan2"),
    "round_int": lambda x: _jnp().round(_jnp().asarray(x)).astype(
        _jnp().int32),
    "floor": _f("floor"),
    "ceil": _f("ceil"),
    "conj": _f("conj"),
    # SORA-style vector DSP (SURVEY.md §2.2 sora_ext_lib.c equivalents)
    "v_fft": _fft,
    "v_ifft": _ifft,
    "fft": _fft,
    "ifft": _ifft,
}


def _viterbi_soft(llrs, npairs, nbits):
    """Block soft-decision Viterbi (K=7, g0=133o/g1=171o) over the first
    `npairs` (A,B) LLR pairs of a padded buffer; returns a bit array of
    half the buffer's length with the `nbits` decoded bits in front.

    The language-level binding of the hot decode kernel — counterpart of
    the reference's `ext` declaration for the SORA Viterbi brick
    (SURVEY.md §2.2/§2.3 `decoding/viterbi.blk`): programs declare

        ext fun viterbi_soft(llrs: arr[N] double, npairs: int32,
                             nbits: int32) : arr[N/2] bit
    """
    from jax.core import Tracer

    from ziria_tpu.ops.viterbi import np_viterbi_decode

    if any(isinstance(a, Tracer) for a in (llrs, npairs, nbits)):
        raise TypeError(
            "ext fun viterbi_soft needs concrete (data-dependent) "
            "lengths and runs on the interpreter backend only; the jit "
            "backend's static-shape decode is ops/viterbi.viterbi_decode"
            " / ops/viterbi_pallas.viterbi_decode_batch")
    arr = np.asarray(llrs, np.float32)
    npairs = int(np.asarray(npairs))
    nbits = int(np.asarray(nbits))
    bits = np_viterbi_decode(arr[: 2 * npairs], n_bits=nbits)
    out = np.zeros(arr.shape[0] // 2, np.uint8)
    out[:nbits] = bits
    return out


EXTERNALS["viterbi_soft"] = _viterbi_soft


def register_external(name: str, fn: Callable) -> None:
    """Extend the registry (used by ops/ext_math and user code)."""
    EXTERNALS[name] = fn


def resolve_ext(name: str) -> Callable:
    fn = EXTERNALS.get(name)
    if fn is None:
        # the fixed-point math library self-registers on import
        import ziria_tpu.ops.ext_math  # noqa: F401
        fn = EXTERNALS.get(name)
    if fn is None:
        known = ", ".join(sorted(EXTERNALS))
        raise KeyError(
            f"ext fun {name!r} is not in the externals registry "
            f"(known: {known}); register it with "
            f"ziria_tpu.frontend.externals.register_external")
    return fn
