"""Externals registry: the frontend's `ext fun` binding surface.

Counterpart of the reference's `lib/` ext declarations binding SORA C
functions into the language (SURVEY.md §2.3) — here each name binds to a
jnp implementation, so `ext fun v_fft(...)` in a source program resolves
to `jnp.fft.fft` instead of a SORA SSE brick. A program must still
*declare* the ext funs it uses (declarations are checked against this
registry), keeping source files self-describing like the reference's.

Builtins (`length`, `abs`, ...) are available without declaration.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np


def _jnp():
    # cached module lookup (hot on every ext call; see frontend/eval)
    global _JNP_MOD
    if _JNP_MOD is None:
        import jax.numpy as jnp
        _JNP_MOD = jnp
    return _JNP_MOD


_JNP_MOD = None


def _xp(args):
    """numpy for concrete values, jnp only under a jax trace.

    The interpreter backend evaluates ext calls on concrete scalars and
    arrays in tight per-sample loops; returning jax Arrays there makes
    every subsequent indexing/arithmetic a device dispatch (measured
    ~200x slower than numpy). The jit backend traces through the same
    registry with Tracer arguments, which must stay in jnp.
    """
    from jax.core import Tracer
    if any(isinstance(a, Tracer) for a in args):
        return _jnp()
    return np


def _length(x) -> int:
    shape = np.shape(x)
    if not shape:
        raise ValueError("length() of a scalar")
    return int(shape[0])


def _f(fn_name: str) -> Callable:
    def wrapper(*args):
        xp = _xp(args)
        return getattr(xp, fn_name)(*[xp.asarray(a) for a in args])
    wrapper.__name__ = fn_name
    return wrapper


def _fft(x):
    xp = _xp((x,))
    return xp.fft.fft(xp.asarray(x, xp.complex64)).astype(xp.complex64)


def _ifft(x):
    xp = _xp((x,))
    return xp.fft.ifft(xp.asarray(x, xp.complex64)).astype(xp.complex64)


def _sum(x):
    xp = _xp((x,))
    return xp.sum(xp.asarray(x), axis=0)


# always available, no declaration needed
BUILTINS: Dict[str, Callable] = {
    "length": _length,
    "abs": _f("abs"),
    "min": _f("minimum"),
    "max": _f("maximum"),
    "sum": _sum,
}

def _v_binop(op_name: str) -> Callable:
    def wrapper(a, b):
        xp = _xp((a, b))
        return getattr(xp, op_name)(xp.asarray(a), xp.asarray(b))
    wrapper.__name__ = f"v_{op_name}"
    return wrapper


def _v_shift_right(x, n):
    """Arithmetic right shift of an integer vector — the reference
    `v_shift_right` brick's role (post-multiply renormalization in
    fixed-point chains)."""
    xp = _xp((x, n))
    return xp.right_shift(xp.asarray(x), xp.asarray(n))


def _v_shift_left(x, n):
    xp = _xp((x, n))
    return xp.left_shift(xp.asarray(x), xp.asarray(n))


def _v_conj_mul(a, b):
    """a * conj(b) elementwise on complex vectors — the correlation
    inner step (reference `v_conj_mul`/`v_mul` pair)."""
    xp = _xp((a, b))
    return xp.asarray(a) * xp.conj(xp.asarray(b))


def _v_correlate(x, ref):
    """Sliding cross-correlation of complex `x` against pattern `ref`
    at all full-overlap lags: out[k] = sum_j x[k+j] * conj(ref[j]).
    Reference's correlation brick; out length = len(x) - len(ref) + 1."""
    xp = _xp((x, ref))
    xa = xp.asarray(x)
    ra = xp.conj(xp.asarray(ref))[::-1]
    return xp.convolve(xa, ra, mode="valid")


def _v_downsample(x, k):
    xp = _xp((x,))
    return xp.asarray(x)[:: int(k)]


def _v_sum_window(x, w):
    """Sliding window sum over `w` samples (moving average * w): the
    packet-detect energy window. out[k] = sum x[k:k+w]."""
    xp = _xp((x,))
    xa = xp.asarray(x)
    c = xp.cumsum(xp.concatenate([xp.zeros(1, xa.dtype), xa]))
    return c[int(w):] - c[: c.shape[0] - int(w)]


def _crc32(bits):
    """802.11 FCS over a bit stream -> 32 CRC bits (transmit order).
    Binds ops/crc.py (the reference's crc.blk role, SURVEY.md §2.3)."""
    from ziria_tpu.ops.crc import crc32_bits, np_crc32_bits_ref
    if _xp((bits,)) is np:
        return np_crc32_bits_ref(np.asarray(bits, np.uint8))
    return crc32_bits(bits)


def _bits_to_int8(bits):
    """8 LSB-first bits -> one byte value (reference bit.c role)."""
    from ziria_tpu.utils.bits import bits_to_bytes
    xp = _xp((bits,))
    if xp is np:
        from ziria_tpu.utils.bits import np_bits_to_bytes
        return np_bits_to_bytes(np.asarray(bits, np.uint8)).astype(np.int8)
    return bits_to_bytes(bits).astype(_jnp().int8)


def _int8_to_bits(v):
    from ziria_tpu.utils.bits import bytes_to_bits
    xp = _xp((v,))
    if xp is np:
        from ziria_tpu.utils.bits import np_bytes_to_bits
        return np_bytes_to_bits(np.asarray(v, np.uint8).reshape(-1))
    return bytes_to_bits(_jnp().asarray(v).astype(_jnp().uint8).reshape(-1))


# available via `ext fun` declaration (names mirror the reference's lib/)
EXTERNALS: Dict[str, Callable] = {
    "sqrt": _f("sqrt"),
    "log": _f("log"),
    "exp": _f("exp"),
    "sin": _f("sin"),
    "cos": _f("cos"),
    "tan": _f("tan"),
    "atan": _f("arctan"),
    "atan2": _f("arctan2"),
    "round_int": lambda x: _jnp().round(_jnp().asarray(x)).astype(
        _jnp().int32),
    "floor": _f("floor"),
    "ceil": _f("ceil"),
    "conj": _f("conj"),
    # SORA-style vector DSP (SURVEY.md §2.2 sora_ext_lib.c equivalents)
    "v_fft": _fft,
    "v_ifft": _ifft,
    "fft": _fft,
    "ifft": _ifft,
    "v_add": _v_binop("add"),
    "v_sub": _v_binop("subtract"),
    "v_mul": _v_binop("multiply"),
    "v_conj_mul": _v_conj_mul,
    "v_shift_right": _v_shift_right,
    "v_shift_left": _v_shift_left,
    "v_correlate": _v_correlate,
    "v_downsample": _v_downsample,
    "v_sum_window": _v_sum_window,
    # bit/byte + CRC utilities (reference bit.c / crc.blk roles)
    "crc32": _crc32,
    "bits_to_int8": _bits_to_int8,
    "int8_to_bits": _int8_to_bits,
}


def viterbi_mode() -> tuple:
    """The process-wide staged-decode mode: ``(window, metric_dtype,
    radix)`` from ZIRIA_VITERBI_WINDOW / ZIRIA_VITERBI_METRIC /
    ZIRIA_VITERBI_RADIX.

    ONE reader for the env triple so the trace-time read in
    ``_viterbi_soft`` and the backend compile-cache keys
    (backend/chunked ``_get_fn``, backend/hybrid ``_JitDo``) can never
    disagree: the mode is part of every cached program's key, so an
    in-process change after tracing re-traces instead of silently
    keeping the old decode mode (ADVICE r5 #1 — a code comment used to
    be the only guard). An unparseable window degrades to 0 (off, the
    safe default); an unknown metric or radix raises — the quantized
    kernels are an opt-in accuracy trade and the radix an opt-in
    kernel rewrite, neither of which may be silently dropped.

    The env reads themselves live with the geometry object's
    designated readers (utils/geometry): this triple is exactly the
    resolved default Geometry's decode mode."""
    from ziria_tpu.utils.geometry import Geometry

    g = Geometry().resolve()
    return g.viterbi_window, g.viterbi_metric, g.viterbi_radix


def _viterbi_soft(llrs, npairs, nbits):
    """Block soft-decision Viterbi (K=7, g0=133o/g1=171o) over the first
    `npairs` (A,B) LLR pairs of a padded buffer; returns a bit array of
    half the buffer's length with the `nbits` decoded bits in front.

    The language-level binding of the hot decode kernel — counterpart of
    the reference's `ext` declaration for the SORA Viterbi brick
    (SURVEY.md §2.2/§2.3 `decoding/viterbi.blk`): programs declare

        ext fun viterbi_soft(llrs: arr[N] double, npairs: int32,
                             nbits: int32) : arr[N/2] bit
    """
    from jax.core import Tracer

    from ziria_tpu.ops.viterbi import np_viterbi_decode

    if isinstance(npairs, Tracer) or isinstance(nbits, Tracer):
        raise TypeError(
            "ext fun viterbi_soft needs static lengths (npairs/nbits "
            "must not depend on traced data); the jit backend's "
            "static-shape decode is ops/viterbi.viterbi_decode / "
            "ops/viterbi_pallas.viterbi_decode_batch")
    npairs = int(np.asarray(npairs))
    nbits = int(np.asarray(nbits))
    if isinstance(llrs, Tracer):
        # staged call (jit / hybrid do-block): static lengths make the
        # shapes static, so decode with the lax.scan ACS kernel — or,
        # under the driver flags --viterbi-window / --viterbi-metric
        # (env ZIRIA_VITERBI_WINDOW / ZIRIA_VITERBI_METRIC), the
        # sliding-window PARALLEL Pallas decode and/or the int16
        # saturating-metric quantized decode: every compiled program's
        # hot brick accelerates without a source change (the "one
        # compiler serves every program" property; same result at
        # operating SNR, tests/test_viterbi_windowed.py /
        # docs/quantized_viterbi.md). Read at trace time via
        # viterbi_mode(), which the backend folds into its compile
        # cache keys — changing the env after tracing re-traces.
        import jax.numpy as jnp
        arr = jnp.asarray(llrs, jnp.float32)
        win, metric, radix = viterbi_mode()
        from ziria_tpu.ops import viterbi_pallas as _vp
        if win > 0 and npairs > win + 2 * _vp.DEFAULT_WINDOW_OVERLAP:
            # only frames long enough to actually window: short
            # decodes (e.g. the 48-step SIGNAL field on the sync hot
            # path) keep the scan kernel — the flag is a pure
            # optimization, never a kernel-launch tax (review r5).
            # radix reaches the windowed path's Pallas engine; the
            # unwindowed scan decode below has no radix by definition
            bits = _vp.viterbi_decode_batch_windowed(
                arr[None, : 2 * npairs], n_bits=nbits, window=win,
                metric_dtype=metric, radix=radix)[0]
        else:
            from ziria_tpu.ops.viterbi import viterbi_decode
            bits = viterbi_decode(arr[: 2 * npairs], n_bits=nbits,
                                  metric_dtype=metric)
        out = jnp.zeros(arr.shape[0] // 2, jnp.uint8)
        return out.at[:nbits].set(bits.astype(jnp.uint8))
    arr = np.asarray(llrs, np.float32)
    # host path: prefer the native C decoder (ctypes, the same brick
    # the perf baseline uses) — ~100x the numpy ACS loop on long
    # frames; fall back to numpy where no toolchain built it
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    if load() is not None and npairs > 64:
        bits = viterbi_decode_native(
            arr[: 2 * npairs].reshape(-1, 2))[:nbits].astype(np.uint8)
    else:
        bits = np_viterbi_decode(arr[: 2 * npairs], n_bits=nbits)
    out = np.zeros(arr.shape[0] // 2, np.uint8)
    out[:nbits] = bits
    return out


EXTERNALS["viterbi_soft"] = _viterbi_soft
# same brick under a second name: the ext declaration syntax pins ONE
# array size per name, and a program decoding both a 24-bit SIGNAL
# field and max-size DATA frames should not zero a 131072-double
# buffer on the sync hot path just to decode 24 bits
EXTERNALS["viterbi_soft_sig"] = _viterbi_soft


def register_external(name: str, fn: Callable) -> None:
    """Extend the registry (used by ops/ext_math and user code)."""
    EXTERNALS[name] = fn


def resolve_ext(name: str) -> Callable:
    fn = EXTERNALS.get(name)
    if fn is None:
        # the fixed-point math library self-registers on import
        import ziria_tpu.ops.ext_math  # noqa: F401
        fn = EXTERNALS.get(name)
    if fn is None:
        known = ", ".join(sorted(EXTERNALS))
        raise KeyError(
            f"ext fun {name!r} is not in the externals registry "
            f"(known: {known}); register it with "
            f"ziria_tpu.frontend.externals.register_external")
    return fn
