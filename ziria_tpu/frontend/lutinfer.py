"""LUT-ability *inference* for pure surface functions (LUTAnalysis role).

The reference's AutoLUT is two-phase (SURVEY.md §2.1): `LUTAnalysis.hs`
decides which pure expression functions have small enough input
bit-width to tabulate, and `AutoLUT.hs`/`CgLUT.hs` synthesize the
tables. Round 1 implemented only the synthesis half, keyed off
*declared* domains (`in_domain`, or scalar `bit`/`int8` surface types).
This module is the analysis half, TPU-first:

- **Bit-width analysis** over declared surface types: every parameter
  must have a finite bit-width (`bit`/`bool` = 1, `int8` = 8,
  `int16` = 16, `arr[N] bit` = N, `arr[N] int8` = 8N) and the widths
  must sum to at most ``MAX_LUT_BITS`` (64Ki entries — the same
  practical cap the reference's LUT sizes respect).
- **Purity analysis** over the function body: only local state may be
  mutated; free variables must resolve to *immutable* bindings in the
  definition scope (global ``let`` constants get baked into the
  table); calls may reach base-type casts, other pure user functions
  (no recursion), and registered ``ext`` functions — the externals
  registry is a closed pure-math library (frontend/externals.py,
  ops/ext_math.py) — but never ``print``/``error``.
- **Table synthesis** evaluates the function over its entire packed
  input domain in ONE `jax.vmap` of the staged evaluator (under
  `jax.ensure_compile_time_eval()` so tables are concrete device
  constants even when the first call happens inside an outer trace),
  and call sites become a single gather `table[pack(args)]` — on TPU
  a VMEM-resident dynamic-gather that vectorizes across the planner's
  batch axis.

Two consumers:

- the elaborator's `map f` path attaches a :class:`MapLut` to the IR
  node when `f` is inferred LUT-able, generalizing `Map.in_domain`
  (which remains the scalar-index fast path) to packed multi-bit
  items such as `arr[8] bit`; `core/autolut.py` performs the rewrite.
- the staged evaluator's expression-call path (`eval._eval_call`)
  rewrites calls with traced arguments into table gathers when the
  program is compiled with ``autolut=True`` (CLI ``--autolut``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ziria_tpu.frontend import ast as A

# synthesis caps: domains above 2^16 would build multi-MB tables and
# lose to direct evaluation on the VPU; per-entry output size is
# further capped by core/autolut.MAX_TABLE_ITEMS at build time
MAX_LUT_BITS = 16


class TableTooLarge(ValueError):
    """Raised by build_fun_table when domain x output size exceeds the
    table cap; expression-call sites fall back to the direct call."""


@dataclass(frozen=True)
class ArgSpec:
    """One parameter's packed-bits layout inside the LUT index."""

    name: str
    kind: str        # bit | bool | int8 | int16 | arr_bit | arr_int8
    bits: int        # total bits this argument contributes
    n: int = 0       # array length (arr_* kinds)


@dataclass(frozen=True)
class LutSpec:
    fun: str
    args: Tuple[ArgSpec, ...]

    @property
    def total_bits(self) -> int:
        return sum(a.bits for a in self.args)

    @property
    def domain(self) -> int:
        return 1 << self.total_bits


# ------------------------------------------------------------------ widths


def _arg_spec(name: str, ty: Optional[A.Ty],
              static_eval: Callable) -> Optional[ArgSpec]:
    if isinstance(ty, A.TBase):
        if ty.name in ("bit", "bool"):
            return ArgSpec(name, ty.name, 1)
        if ty.name == "int8":
            return ArgSpec(name, "int8", 8)
        if ty.name == "int16":
            return ArgSpec(name, "int16", 16)
        return None
    if isinstance(ty, A.TArr) and isinstance(ty.elem, A.TBase):
        if ty.n is None:
            return None                      # length-polymorphic
        try:
            n = int(static_eval(ty.n))
        except Exception:
            return None
        if n <= 0:
            return None
        if ty.elem.name in ("bit", "bool"):
            return ArgSpec(name, "arr_bit", n, n)
        if ty.elem.name == "int8":
            return ArgSpec(name, "arr_int8", 8 * n, n)
    return None


# ------------------------------------------------------------------ purity


def _lval_root(e: A.Expr) -> Optional[str]:
    while isinstance(e, (A.EIdx, A.ESlice, A.EField)):
        e = e.e if isinstance(e, A.EField) else e.arr
    return e.name if isinstance(e, A.EVar) else None


def _pure_expr(e: Optional[A.Expr], locals_: Set[str], fd, ctx,
               seen: Set[str]) -> bool:
    if e is None:
        return True
    if isinstance(e, A.EVar):
        if e.name in locals_:
            return True
        cell = fd.closure.find(e.name)
        # immutable closure bindings (global `let` constants) are baked
        # into the table; anything mutable would make the table stale
        return cell is not None and not cell.mutable
    if isinstance(e, A.ECall):
        from ziria_tpu.frontend.eval import _BASE_TYPE_NAMES
        if not all(_pure_expr(a, locals_, fd, ctx, seen) for a in e.args):
            return False
        if e.name in _BASE_TYPE_NAMES:
            return True
        if e.name in ("print", "println", "error"):
            return False
        sub = ctx.funs.get(e.name)
        if sub is not None:
            return _pure_fun_body(e.name, sub, ctx, seen)
        # registered externals: a closed pure-DSP-math registry
        return e.name in ctx.exts
    # all other node kinds are pure iff their children are
    # (A.child_exprs raises on unknown nodes — fail closed)
    return all(_pure_expr(k, locals_, fd, ctx, seen)
               for k in A.child_exprs(e))


def _pure_stmts(stmts, locals_: Set[str], fd, ctx, seen: Set[str]) -> bool:
    for st in stmts:
        if isinstance(st, (A.SVar, A.SLet)):
            init = st.init if isinstance(st, A.SVar) else st.e
            if not _pure_expr(init, locals_, fd, ctx, seen):
                return False
            locals_.add(st.name)
        elif isinstance(st, A.SAssign):
            root = _lval_root(st.lval)
            if root is None or root not in locals_:
                return False                 # writes must stay local
            if not _pure_expr(st.lval, locals_, fd, ctx, seen):
                return False
            if not _pure_expr(st.e, locals_, fd, ctx, seen):
                return False
        elif isinstance(st, A.SIf):
            if not _pure_expr(st.c, locals_, fd, ctx, seen):
                return False
            if not _pure_stmts(st.then, set(locals_), fd, ctx, seen):
                return False
            if not _pure_stmts(st.els, set(locals_), fd, ctx, seen):
                return False
        elif isinstance(st, A.SFor):
            if not _pure_expr(st.start, locals_, fd, ctx, seen):
                return False
            if not _pure_expr(st.count, locals_, fd, ctx, seen):
                return False
            if not _pure_stmts(st.body, set(locals_) | {st.var},
                               fd, ctx, seen):
                return False
        elif isinstance(st, A.SWhile):
            if not _pure_expr(st.c, locals_, fd, ctx, seen):
                return False
            if not _pure_stmts(st.body, set(locals_), fd, ctx, seen):
                return False
        elif isinstance(st, A.SReturn):
            if not _pure_expr(st.e, locals_, fd, ctx, seen):
                return False
        elif isinstance(st, A.SExpr):
            if not _pure_expr(st.e, locals_, fd, ctx, seen):
                return False
        else:
            return False
    return True


def _pure_fun_body(name: str, fd, ctx, seen: Set[str]) -> bool:
    if name in seen:
        return False                         # (mutual) recursion
    seen = seen | {name}
    locals_ = {p.name for p in fd.decl.params}
    return _pure_stmts(fd.decl.body, locals_, fd, ctx, seen)


# ------------------------------------------------------------------ analysis


def spec_for_fun(name: str, fd, ctx) -> Optional[LutSpec]:
    """LUT-ability verdict for one user function: packed-input spec if
    every parameter is small and the body is pure, else None. Memoized
    per Ctx (declarations are immutable once elaborated)."""
    memo: Dict[str, Optional[LutSpec]] = ctx.lut_specs
    if name in memo:
        return memo[name]
    spec: Optional[LutSpec] = None
    d = fd.decl
    if d.params:
        def se(e, _fd=fd, _ctx=ctx):
            return _ctx.static_eval(e, _fd.closure)
        args = [_arg_spec(p.name, p.ty, se) for p in d.params]
        if all(a is not None for a in args) \
                and sum(a.bits for a in args) <= MAX_LUT_BITS \
                and _pure_fun_body(name, fd, ctx, set()):
            spec = LutSpec(name, tuple(args))
    memo[name] = spec
    return spec


# ---------------------------------------------------------------- pack/unpack


def args_match_spec(spec: LutSpec, args: List[Any]) -> bool:
    """Shapes must agree with the spec before packing: a mismatched
    array length would silently broadcast into a garbage index, where
    the direct call raises a clear length error — so mismatches fall
    back to the direct path."""
    if len(args) != len(spec.args):
        return False
    for a, v in zip(spec.args, args):
        if a.kind in ("bit", "bool", "int8", "int16"):
            if np.ndim(v) != 0:
                return False
        else:
            shp = np.shape(v)
            if len(shp) != 1 or shp[0] != a.n:
                return False
    return True


def encode_args(spec: LutSpec, args: List[Any]) -> Any:
    """Pack runtime argument values into the LUT index (staged: works on
    traced jnp values; first arg occupies the high bits)."""
    import jax.numpy as jnp

    idx = None
    for a, v in zip(spec.args, args):
        if a.kind == "bit":
            enc = jnp.asarray(v, jnp.int32) & 1
        elif a.kind == "bool":
            # nonzero-is-True, matching cast_value's bool semantics
            enc = (jnp.asarray(v) != 0).astype(jnp.int32)
        elif a.kind == "int8":
            enc = jnp.asarray(v, jnp.int32) & 0xFF
        elif a.kind == "int16":
            enc = jnp.asarray(v, jnp.int32) & 0xFFFF
        elif a.kind == "arr_bit":
            bits = jnp.asarray(v, jnp.int32) & 1
            enc = jnp.sum(bits << jnp.arange(a.n, dtype=jnp.int32))
        else:                                # arr_int8
            by = jnp.asarray(v, jnp.int32) & 0xFF
            enc = jnp.sum(by << (8 * jnp.arange(a.n, dtype=jnp.int32)))
        idx = enc if idx is None else (idx << a.bits) | enc
    return jnp.asarray(idx, jnp.int32)


def decode_index(spec: LutSpec, idx: Any) -> List[Any]:
    """Unpack a LUT index into per-parameter values (used under vmap at
    table-build time; dtypes match the runtime item conventions —
    call_fun re-casts through the declared types anyway)."""
    import jax.numpy as jnp

    idx = jnp.asarray(idx, jnp.int32)
    out: List[Any] = []
    for a in reversed(spec.args):
        low = idx & ((1 << a.bits) - 1)
        idx = idx >> a.bits
        if a.kind == "bit":
            out.append(low.astype(jnp.uint8))
        elif a.kind == "bool":
            out.append((low & 1).astype(jnp.bool_))
        elif a.kind == "int8":
            out.append(low.astype(jnp.int8))
        elif a.kind == "int16":
            out.append(low.astype(jnp.int16))
        elif a.kind == "arr_bit":
            out.append(((low >> jnp.arange(a.n, dtype=jnp.int32)) & 1)
                       .astype(jnp.uint8))
        else:                                # arr_int8
            out.append(((low >> (8 * jnp.arange(a.n, dtype=jnp.int32)))
                        & 0xFF).astype(jnp.int8))
    out.reverse()
    return out


# ---------------------------------------------------------------- synthesis


# domains small enough to build row-by-row in the concrete evaluator
# when the staged (vmap) build hits a staging limitation — notably
# `return` inside a data-dependent if, which concrete evaluation
# handles fine (this mirrors the reference, whose LUT generation was
# compile-time evaluation and therefore immune to codegen limits)
STATIC_BUILD_MAX = 4096


def _decode_static(spec: LutSpec, idx: int) -> List[Any]:
    """Python/numpy unpack of one index for concrete row evaluation."""
    out: List[Any] = []
    for a in reversed(spec.args):
        low = idx & ((1 << a.bits) - 1)
        idx >>= a.bits
        if a.kind == "bit":
            out.append(low)
        elif a.kind == "bool":
            out.append(bool(low))
        elif a.kind == "int8":
            out.append(low - 256 if low >= 128 else low)
        elif a.kind == "int16":
            out.append(low - 65536 if low >= 32768 else low)
        elif a.kind == "arr_bit":
            out.append(np.array([(low >> i) & 1 for i in range(a.n)],
                                np.uint8))
        else:                                # arr_int8
            by = [(low >> (8 * i)) & 0xFF for i in range(a.n)]
            out.append(np.array(by, np.uint8).astype(np.int8))
    out.reverse()
    return out


def build_fun_table(spec: LutSpec, fd, ctx) -> Any:
    """Evaluate the function over its whole packed domain: one vmap of
    the staged evaluator (concrete even under an outer jit trace), or —
    for small domains, when staging rejects the body — one concrete
    evaluation per row.

    Memoized on ``ctx.lut_tables`` (shared by map-position and
    expression-call sites: one build per function per program). The
    MAX_TABLE_ITEMS output cap is enforced *before* building via
    ``jax.eval_shape`` — an oversize candidate (e.g. int16 ->
    arr[512] int16: 33.5M items) is refused instantly, not after a
    minute of wasted domain evaluation."""
    import jax
    import jax.numpy as jnp
    from ziria_tpu.core.autolut import MAX_TABLE_ITEMS
    from ziria_tpu.frontend.eval import ZiriaRuntimeError, call_fun

    memo = ctx.lut_tables
    if spec.fun in memo:
        return memo[spec.fun]

    def one(i):
        return call_fun(fd, decode_index(spec, i), ctx)

    staging_err = None
    try:
        row = jax.eval_shape(one, jax.ShapeDtypeStruct((), jnp.int32))
        row_items = sum(int(np.prod(l.shape))
                        for l in jax.tree_util.tree_leaves(row))
        if row_items * spec.domain > MAX_TABLE_ITEMS:
            raise TableTooLarge(
                f"{spec.fun}: LUT would hold {row_items * spec.domain} "
                f"items (> {MAX_TABLE_ITEMS} cap)")
    except ZiriaRuntimeError as e:
        staging_err = e                      # body is not stageable

    if staging_err is None:
        with jax.ensure_compile_time_eval():
            table = jax.vmap(one)(jnp.arange(spec.domain,
                                             dtype=jnp.int32))
    else:
        if spec.domain > STATIC_BUILD_MAX:
            raise staging_err
        rows = [call_fun(fd, _decode_static(spec, i), ctx)
                for i in range(spec.domain)]
        if any(isinstance(r, dict) for r in rows):
            raise staging_err
        table = jnp.asarray(np.stack([np.asarray(r) for r in rows]))
        # row shape was unknowable upfront on this path
        if table.size > MAX_TABLE_ITEMS:
            raise TableTooLarge(
                f"{spec.fun}: LUT of {table.size} items exceeds the "
                f"{MAX_TABLE_ITEMS}-item cap")
    memo[spec.fun] = table
    return table


def gather(table: Any, idx: Any) -> Any:
    """table[idx] across an arbitrary output pytree (struct returns)."""
    import jax
    return jax.tree_util.tree_map(lambda t: t[idx], table)


class MapLut:
    """Adapter attached to `ir.Map.lut` by the elaborator: carries the
    inferred spec plus everything `core/autolut.py` needs to rewrite the
    map into a gather without importing the frontend."""

    def __init__(self, spec: LutSpec, fd, ctx):
        self.spec = spec
        self.fd = fd
        self.ctx = ctx

    @property
    def domain(self) -> int:
        return self.spec.domain

    def build_table(self) -> Any:
        return build_fun_table(self.spec, self.fd, self.ctx)

    def encode(self, x: Any) -> Any:
        return encode_args(self.spec, [x])

    def encoder(self) -> Callable[[Any], Any]:
        """A pack closure over ONLY the spec — the rewritten map must
        not retain the FunDef/Ctx (the whole elaboration context) once
        the table is built."""
        spec = self.spec
        return lambda x: encode_args(spec, [x])

    def __repr__(self):
        return (f"MapLut({self.spec.fun}: {self.spec.total_bits} bits, "
                f"domain {self.spec.domain})")
