"""Staged evaluator for the surface expression language.

This is the expression-level *code generator*: it executes expression
and statement ASTs over jnp values, so running it eagerly gives the
interpreter semantics and running it under a `jax.jit` trace stages the
very same AST into an XLA graph (classic staged interpretation — the
TPU-first replacement for the reference's `CgExpr.hs` C emitter,
SURVEY.md §2.1).

Value representation / dtype policy:

  bit        Python int 0/1 (static) or jnp uint8
  bool       Python bool or jnp bool_
  int{8,16,32,64}, int   jnp integer scalars. Arithmetic follows C:
             int8/int16 operands promote to int32 before binops
             (_promote_narrow_np), results narrow back to the declared
             width only at assignment/cast; int32/int64 wrap at their
             own width like C int/long long. *Literals and untyped lets
             stay Python ints* so array lengths, take counts and loop
             bounds remain static under tracing (unbounded until
             assigned — diverges from C only past 2^63).
  double     float32 (TPU dtype policy — f64 would disable the MXU path;
             the golden-file differ absorbs the precision delta)
  complex{16,32}, complex  jnp complex64; `.re`/`.im` field access
  arr[n] t   jnp array; mutation via functional `.at[...]` updates
  struct     dict {field: value} tagged with "__struct__"

Static Python scalars flow through arithmetic unchanged (int+int=int),
which is what keeps `takes (n*2)` and `for i in [0, n]` compile-time
constants; anything touching a jnp value promotes to jnp.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ziria_tpu.frontend import ast as A


class ZiriaRuntimeError(RuntimeError):
    pass


class NotStatic(Exception):
    """Raised by the static-evaluation entry when a value is runtime."""


def _rt_err(loc: Tuple[int, int], msg: str) -> ZiriaRuntimeError:
    return ZiriaRuntimeError(f"{loc[0]}:{loc[1]}: {msg}")


# --------------------------------------------------------------------------
# Types → dtypes / casts
# --------------------------------------------------------------------------

_INT_DTYPES = {"int8": np.int8, "int16": np.int16, "int32": np.int32,
               "int64": np.int64, "int": np.int32}
_CPLX = ("complex", "complex16", "complex32")


_JNP = None


def _jnp():
    # cached: this is called on nearly every evaluated operation, and
    # the repeated sys.modules lookup showed up in interpreter profiles
    global _JNP
    if _JNP is None:
        import jax.numpy as jnp
        _JNP = jnp
    return _JNP


_NP_CONCRETE = (int, float, bool, complex, np.ndarray, np.generic)


def _np_ok(*vs) -> bool:
    """True when every value is a plain Python/numpy value.

    Concrete evaluation (the interpreter backend) then runs on numpy —
    measured ~50x faster per operation than jnp dispatch, which matters
    because the streaming oracle executes per-sample loops. Anything
    else (jax Tracers under the jit backend's lowering trace, or jax
    Arrays handed in by callers) keeps the jnp path. numpy>=2 NEP-50
    promotion matches jnp's weak typing for scalar-array mixes.
    """
    for v in vs:
        if not isinstance(v, _NP_CONCRETE):
            return False
    return True


def is_static(v: Any) -> bool:
    return isinstance(v, (int, float, bool, complex)) and not hasattr(
        v, "dtype")


def _is_traced(*vs) -> bool:
    """True when any value is a jax Tracer (abstract, under a trace).

    Control decisions must use THIS — not ``try: bool(v)`` — to pick
    the staged path: calling bool() on a tracer makes jax construct a
    TracerBoolConversionError whose provenance message walks the whole
    traced graph (observed quadratic: minutes inside a large do-block),
    and a *concrete* jax Array coerces to bool just fine and should
    take the eager path."""
    try:
        from jax.core import Tracer
    except Exception:
        return False
    return any(isinstance(v, Tracer) for v in vs)


def base_dtype(name: str):
    jnp = _jnp()
    if name == "bit":
        return jnp.uint8
    if name == "bool":
        return jnp.bool_
    if name in _INT_DTYPES:
        return jnp.dtype(_INT_DTYPES[name])
    if name == "double":
        return jnp.float32
    if name in _CPLX:
        return jnp.complex64
    raise ValueError(f"no dtype for base type {name!r}")


@dataclass
class StructDef:
    name: str
    fields: Tuple[Tuple[str, A.Ty], ...]


def fx_is_pair(v: Any) -> bool:
    """Is `v` plausibly a fixed-point complex16 value (signed-integer
    IQ-pair array)? A shape heuristic: under the opt-in policy a
    (..., 2) signed-int array is treated as complex16 by * and == when
    no declared type says otherwise (EBin consults declared var types
    first — see _fx_ty_hint). Unsigned arrays (bit streams) never
    match."""
    return (hasattr(v, "dtype") and v.ndim >= 1 and v.shape[-1] == 2
            and np.issubdtype(np.dtype(v.dtype), np.signedinteger))


def fx_wrap16(v):
    """Wrap components to int16 range, keep int32 storage (the C shorts
    store-narrowing, without losing the promoted width for the next
    operation). Floats wrap MODULARLY via fmod in the float domain —
    exact for every representable float (fmod is exact, and the result
    is an integer < 2^17, exactly representable), identical on numpy
    and XLA, and needing no int64 (which JAX silently truncates to
    int32 with x64 off — review r2). astype(int16) on out-of-range
    floats would saturate under XLA but wrap under numpy, breaking the
    interp == jit invariant."""
    xp = np if _np_ok(v) else _jnp()
    x = xp.asarray(v)
    if not np.issubdtype(np.dtype(x.dtype), np.integer):
        r = xp.fmod(xp.round(x), 65536.0)      # (-65536, 65536), exact
        r = xp.where(r >= 32768.0, r - 65536.0, r)
        r = xp.where(r < -32768.0, r + 65536.0, r)
        return r.astype(np.int32)
    return x.astype(np.int16).astype(np.int32)


def fx_pair(re, im) -> Any:
    """Build a fixed-point complex16 from components (wrapped)."""
    xp = np if _np_ok(re, im) else _jnp()
    return xp.stack([fx_wrap16(re), fx_wrap16(im)], axis=-1)


def _fx_cast(v: Any) -> Any:
    """Coerce any complex-ish value to a fixed-point IQ pair."""
    if is_static(v):
        c = complex(v)
        return fx_pair(np.int64(round(c.real)), np.int64(round(c.imag)))
    if fx_is_pair(v):
        return fx_wrap16(v)
    xp = np if _np_ok(v) else _jnp()
    a = xp.asarray(v)
    if np.dtype(a.dtype).kind == "c":
        return fx_pair(xp.real(a), xp.imag(a))
    if a.ndim >= 1 and a.shape[-1] == 2:
        return fx_pair(a[..., 0], a[..., 1])   # float pairs round+wrap
    raise ZiriaRuntimeError(
        f"cannot cast value of shape {np.shape(v)} to fixed-point "
        f"complex16 (expected complex or (..., 2) pair)")


def cast_value(ty: Optional[A.Ty], v: Any, structs: Dict[str, StructDef],
               static_eval: Optional[Callable] = None,
               fxp: bool = False) -> Any:
    """Cast `v` to surface type `ty` (None = leave as-is). `fxp` is the
    Ctx.fxp_complex16 policy: complex16 becomes an int32 IQ pair."""
    if ty is None:
        return v
    jnp = _jnp()
    if isinstance(ty, A.TBase):
        if fxp and ty.name == "complex16":
            return _fx_cast(v)
        if ty.name == "bit" and is_static(v):
            return int(v) & 1
        if ty.name in ("int", "int8", "int16", "int32", "int64") \
                and is_static(v):
            # static ints stay static, but wrap to the declared width
            w = np.dtype(_INT_DTYPES[ty.name]).itemsize * 8
            x = int(v) & ((1 << w) - 1)
            return x - (1 << w) if x >= (1 << (w - 1)) else x
        if ty.name == "bool" and is_static(v):
            return bool(v)
        if ty.name == "double" and is_static(v):
            return float(v)
        if ty.name in _CPLX and is_static(v):
            return complex(v)
        dt = base_dtype(ty.name)
        xp = np if _np_ok(v) else jnp
        if ty.name == "bit":
            return xp.asarray(v).astype(np.uint8) & np.uint8(1)
        if ty.name in _CPLX and fx_is_pair(v):
            # fx pair -> float complex (the f32 interop cast, e.g. FFT)
            from ziria_tpu.ops.cplx import to_complex
            return to_complex(v, xp).astype(dt)
        return xp.asarray(v).astype(dt)
    if isinstance(ty, A.TArr):
        if fxp and isinstance(ty.elem, A.TBase) \
                and ty.elem.name == "complex16":
            arr = _fx_cast(v)
        else:
            arr = np.asarray(v) if _np_ok(v) else jnp.asarray(v)
            edt = base_dtype(ty.elem.name) \
                if isinstance(ty.elem, A.TBase) else None
            if edt is not None and arr.dtype != edt:
                arr = arr.astype(edt)
        if ty.n is not None and static_eval is not None:
            n = static_eval(ty.n)
            if int(arr.shape[0]) != int(n):
                raise ZiriaRuntimeError(
                    f"array of declared length {n} initialized with "
                    f"length {arr.shape[0]}")
        return arr
    if isinstance(ty, A.TStruct):
        sd = structs.get(ty.name)
        if sd is None:
            raise ZiriaRuntimeError(f"unknown struct type {ty.name!r}")
        if not isinstance(v, dict):
            raise ZiriaRuntimeError(
                f"struct {ty.name} initialized with non-struct value")
        out = {"__struct__": sd.name}
        for fn, fty in sd.fields:
            if fn not in v:
                raise ZiriaRuntimeError(f"struct {sd.name} missing "
                                        f"field {fn!r}")
            out[fn] = cast_value(fty, v[fn], structs, static_eval)
        return out
    raise ZiriaRuntimeError(f"cannot cast to {ty}")


def zero_value(ty: A.Ty, structs: Dict[str, StructDef],
               static_eval: Callable, fxp: bool = False) -> Any:
    if isinstance(ty, A.TBase):
        if fxp and ty.name == "complex16":
            return np.zeros(2, np.int32)
        if ty.name == "bit":
            return 0
        if ty.name in _INT_DTYPES:
            return 0
        if ty.name == "bool":
            return False
        if ty.name == "double":
            return 0.0
        if ty.name in _CPLX:
            return 0j
        raise ZiriaRuntimeError(f"no zero value for {ty.name}")
    if isinstance(ty, A.TArr):
        if ty.n is None:
            raise ZiriaRuntimeError(
                "length-polymorphic array needs an initializer")
        # numpy zeros: concrete evaluation stays in numpy; under the jit
        # backend's trace these are initial constants that promote to
        # jnp on first traced assignment
        n = int(static_eval(ty.n))
        if fxp and isinstance(ty.elem, A.TBase) \
                and ty.elem.name == "complex16":
            return np.zeros((n, 2), np.int32)
        if isinstance(ty.elem, A.TBase):
            return np.zeros((n,), base_dtype(ty.elem.name))
        inner = zero_value(ty.elem, structs, static_eval, fxp)
        return np.zeros((n,) + tuple(np.shape(inner)),
                        getattr(inner, "dtype", np.float32))
    if isinstance(ty, A.TStruct):
        sd = structs[ty.name]
        return {"__struct__": sd.name,
                **{fn: zero_value(fty, structs, static_eval, fxp)
                   for fn, fty in sd.fields}}
    raise ZiriaRuntimeError(f"no zero value for {ty}")


# --------------------------------------------------------------------------
# Scopes
# --------------------------------------------------------------------------


@dataclass
class Cell:
    value: Any
    ty: Optional[A.Ty]
    mutable: bool


class Scope:
    """Chained lexical scope over Cells; supports snapshot/merge for
    staging dynamic `if` statements."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.cells: Dict[str, Cell] = {}
        self.parent = parent

    def child(self) -> "Scope":
        return Scope(self)

    def declare(self, name: str, value: Any, ty: Optional[A.Ty] = None,
                mutable: bool = False) -> None:
        self.cells[name] = Cell(value, ty, mutable)

    def find(self, name: str) -> Optional[Cell]:
        # recurse through parent.find (not a cells-walk) so subclasses
        # (elab.RuntimeScope) can interpose env-backed lookups mid-chain
        c = self.cells.get(name)
        if c is not None:
            return c
        return self.parent.find(name) if self.parent is not None else None

    def lookup(self, name: str, loc=(0, 0)) -> Any:
        c = self.find(name)
        if c is None:
            raise _rt_err(loc, f"unbound variable {name!r}")
        return c.value

    def assign(self, name: str, value: Any, ctx: "Ctx", loc=(0, 0)) -> None:
        # delegate up the chain so subclasses (RuntimeScope) can intercept
        # at their own level — a find()-based set would write to temporary
        # view cells and silently drop the store
        if name in self.cells:
            c = self.cells[name]
            if not c.mutable:
                raise _rt_err(loc, f"assignment to immutable binding "
                                   f"{name!r} (declare it with `var`)")
            c.value = cast_value(c.ty, value, ctx.structs,
                                 lambda x: ctx.static_eval(x, self),
                                 fxp=ctx.fxp_complex16) \
                if c.ty is not None else value
            return
        if self.parent is not None:
            return self.parent.assign(name, value, ctx, loc)
        raise _rt_err(loc, f"assignment to unbound variable {name!r}")

    def own_mutable_cells(self) -> List[Tuple[str, Any]]:
        return [(n, c) for n, c in self.cells.items() if c.mutable]

    def mutable_cells(self) -> List[Any]:
        return [c for _, c in self.mutable_cells_named()]

    def mutable_cells_named(self) -> List[Tuple[str, Any]]:
        out, s, seen = [], self, set()
        while s is not None:
            for name, c in s.own_mutable_cells():
                if name not in seen:
                    seen.add(name)
                    out.append((name, c))
            s = s.parent
        return out


# --------------------------------------------------------------------------
# Evaluation context
# --------------------------------------------------------------------------


@dataclass
class FunDef:
    decl: A.DFun
    closure: Scope           # scope the fun was defined in


@dataclass
class Ctx:
    funs: Dict[str, FunDef] = field(default_factory=dict)
    exts: Dict[str, Callable] = field(default_factory=dict)
    structs: Dict[str, StructDef] = field(default_factory=dict)
    on_print: Callable[[str], None] = print
    # opt-in int16 fixed-point complex16 policy (SURVEY.md §7 hard-part
    # (b)): complex16 values are (..., 2) int32 IQ pairs — the same
    # pair-last layout ops/cplx.py uses for f32 — with C shorts
    # semantics (components promote to int32 in arithmetic, wrap to
    # int16 at assignment/cast). See fx_* helpers below.
    fxp_complex16: bool = False
    # declared ext signatures (filled by the elaborator) — under the
    # fxp policy, complex-typed ext params convert pair -> complex64 at
    # the call boundary and complex16 returns requantize, so f32 bricks
    # like v_fft keep their documented f32 interior
    ext_sigs: Dict[str, Any] = field(default_factory=dict)
    # per-node memo for _fx_ty_hint (declared types are static per
    # program point; the hint walk must not run per stream item in the
    # interpreter hot loop)
    fx_hints: Dict[int, Any] = field(default_factory=dict)
    # AutoLUT inference (frontend/lutinfer.py, the reference's
    # LUTAnalysis role): when `autolut` is set (CLI --autolut), calls to
    # pure small-bit-width funs with traced arguments stage as table
    # gathers; lut_specs memoizes per-fun verdicts and lut_tables the
    # synthesized tables (concrete device constants, safe across traces)
    autolut: bool = False
    lut_specs: Dict[str, Any] = field(default_factory=dict)
    lut_tables: Dict[str, Any] = field(default_factory=dict)

    def static_eval(self, e: A.Expr, scope: Optional[Scope] = None) -> Any:
        """Evaluate `e` and require a static Python value (array lengths,
        take counts, loop bounds)."""
        v = eval_expr(e, scope or Scope(), self)
        if hasattr(v, "dtype") and getattr(v, "shape", None) == ():
            try:
                v = v.item()
            except Exception:
                raise NotStatic(f"{e.loc[0]}:{e.loc[1]}: value is not "
                                f"compile-time static")
        if not is_static(v):
            raise NotStatic(f"{e.loc[0]}:{e.loc[1]}: value is not "
                            f"compile-time static")
        return v


# --------------------------------------------------------------------------
# Operators
# --------------------------------------------------------------------------


def _trunc_div(a, b):
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


# module-level dispatch tables: _binop runs in the interpreter's
# per-sample hot loop; rebuilding dict literals per call is measurable
_NP_OPS = {
    "+": np.add, "-": np.subtract, "*": np.multiply, "**": np.power,
    "<<": np.left_shift, ">>": np.right_shift,
    "<": np.less, "<=": np.less_equal, ">": np.greater,
    ">=": np.greater_equal, "==": np.equal, "!=": np.not_equal,
}
_NP_BOOL_OPS = {"&": np.logical_and, "|": np.logical_or,
                "^": np.logical_xor}
_NP_BIT_OPS = {"&": np.bitwise_and, "|": np.bitwise_or,
               "^": np.bitwise_xor}


# C's usual arithmetic conversions apply to COMPARISONS too: without
# them `bit > -1` or `int8 == 256` silently disagree between the
# numpy path (strong int64 scalars) and the traced path (weak int32
# demoting to the narrow dtype)
_ARITH_PROMOTE = frozenset(("+", "-", "*", "/", "%", "**", "<<", ">>",
                            "&", "|", "^",
                            "<", "<=", ">", ">=", "==", "!="))


def _promote_narrow_np(x: np.ndarray) -> np.ndarray:
    """C integer promotion: int8/int16 — and the UNSIGNED narrows,
    uint8 (the `bit` type) / uint16 — widen to int32 before arithmetic,
    so mid-expression results never wrap at the narrow width (C
    semantics; ADVICE r1 medium). Narrowing back to the declared width
    happens at assignment/cast via cast_value — exactly where C
    truncates. int32/int64 wrap at their own width (= C int / long
    long); static Python ints are unbounded until assigned, which
    diverges from C only past 2^63.

    uint8 matters beyond C-pedantry: without it the two backends
    DISAGREE — `256 * some_bit` is 256 or 0 depending on path, because
    np.asarray(256) is a strong int64 scalar while jnp.asarray(256) is
    a weak int32 that defers to uint8 (found decoding a 1000-byte
    frame: the SIGNAL length's bit-8/9 terms vanished under jit)."""
    if x.dtype in (np.int8, np.int16, np.uint8, np.uint16):
        return x.astype(np.int32)
    return x


def _fx_split(v, loc=(0, 0)):
    """(re, im) integer components of a fixed-point operand; integer
    real scalars/arrays get im = 0. Fractional real operands are an
    ERROR, not a silent round — scaling a fixed-point value by 0.5
    must be written as an explicit shift/Q15 op (the same rule C
    programmers live by)."""
    if fx_is_pair(v):
        return v[..., 0], v[..., 1]
    if is_static(v):
        c = complex(v)
        if c.real != int(c.real) or c.imag != int(c.imag):
            raise _rt_err(loc, f"cannot mix fixed-point complex16 with "
                               f"the fractional value {v!r}; scale with "
                               f"integer arithmetic, shifts, or the Q15 "
                               f"ext helpers")
        return int(c.real), int(c.imag)
    xp = np if _np_ok(v) else _jnp()
    a = xp.asarray(v)
    if np.dtype(a.dtype).kind == "c":
        return (xp.round(xp.real(a)).astype(np.int32),
                xp.round(xp.imag(a)).astype(np.int32))
    if not np.issubdtype(np.dtype(a.dtype), np.integer):
        raise _rt_err(loc, "cannot mix fixed-point complex16 with a "
                           "float array; quantize it explicitly (the "
                           "policy keeps everything in the integer "
                           "domain)")
    return a.astype(np.int32), xp.zeros(a.shape, np.int32)


def _fx_binop(op: str, a: Any, b: Any, loc):
    """Fixed-point complex16 operator semantics (C shorts model:
    components are int32 mid-expression, wrap to int16 at
    assignment/cast). Returns NotImplemented for ops whose elementwise
    fallthrough is already correct (shifts, real-scalar / and %)."""
    if op in ("==", "!="):
        ar, ai = _fx_split(a, loc)
        br, bi = _fx_split(b, loc)
        xp = np if _np_ok(ar, ai, br, bi) else _jnp()
        eq = xp.logical_and(xp.asarray(ar == br), xp.asarray(ai == bi))
        return eq if op == "==" else xp.logical_not(eq)
    if op == "*":
        ar, ai = _fx_split(a, loc)
        br, bi = _fx_split(b, loc)
        xp = np if _np_ok(ar, ai, br, bi) else _jnp()
        return xp.stack([xp.asarray(ar * br - ai * bi),
                         xp.asarray(ar * bi + ai * br)], axis=-1)
    if op in ("+", "-"):
        if fx_is_pair(a) and fx_is_pair(b):
            return NotImplemented          # elementwise is exact
        ar, ai = _fx_split(a, loc)
        br, bi = _fx_split(b, loc)
        xp = np if _np_ok(ar, ai, br, bi) else _jnp()
        if op == "+":
            return xp.stack([xp.asarray(ar + br),
                             xp.asarray(ai + bi)], axis=-1)
        return xp.stack([xp.asarray(ar - br),
                         xp.asarray(ai - bi)], axis=-1)
    if op in ("/", "%") and fx_is_pair(a) and fx_is_pair(b):
        raise _rt_err(loc, f"fixed-point complex16 has no {op!r} "
                           f"between complex values; scale by real "
                           f"scalars or use the Q15 ext helpers")
    return NotImplemented      # shifts / real-divisor ops: elementwise


def _binop(op: str, a: Any, b: Any, loc, fxp: bool = False) -> Any:
    jnp = _jnp()
    if fxp and (fx_is_pair(a) or fx_is_pair(b)):
        r = _fx_binop(op, a, b, loc)
        if r is not NotImplemented:
            return r
    both_static = is_static(a) and is_static(b)
    if op == "&&":
        return (bool(a) and bool(b)) if both_static \
            else (np if _np_ok(a, b) else jnp).logical_and(a, b)
    if op == "||":
        return (bool(a) or bool(b)) if both_static \
            else (np if _np_ok(a, b) else jnp).logical_or(a, b)
    if both_static:
        try:
            if op == "/":
                if isinstance(a, int) and isinstance(b, int):
                    return _trunc_div(a, b)     # C int division
                return a / b
            if op == "%":
                if isinstance(a, int) and isinstance(b, int):
                    return a - _trunc_div(a, b) * b   # C remainder
                return math.fmod(a, b)
            return {
                "+": lambda: a + b, "-": lambda: a - b,
                "*": lambda: a * b, "**": lambda: a ** b,
                "<<": lambda: a << b, ">>": lambda: a >> b,
                "<": lambda: a < b, "<=": lambda: a <= b,
                ">": lambda: a > b, ">=": lambda: a >= b,
                "==": lambda: a == b, "!=": lambda: a != b,
                "&": lambda: a & b, "|": lambda: a | b,
                "^": lambda: a ^ b,
            }[op]()
        except TypeError:
            pass  # e.g. complex << int — fall through for the error below
    if _np_ok(a, b):
        # concrete numpy fast path — same semantics as the jnp branch
        an, bn = np.asarray(a), np.asarray(b)
        if op in _ARITH_PROMOTE:
            an, bn = _promote_narrow_np(an), _promote_narrow_np(bn)
        fn = _NP_OPS.get(op)
        if fn is not None:
            return fn(an, bn)
        if op == "/":
            if (np.issubdtype(an.dtype, np.integer)
                    and np.issubdtype(bn.dtype, np.integer)):
                # C-style truncating int division (lax.div semantics),
                # exact for all of int64 — no float round-trip
                q = np.floor_divide(an, bn)
                rem = an - q * bn
                return q + ((rem != 0) & ((an < 0) != (bn < 0)))
            return np.divide(an, bn)
        if op == "%":
            if (np.issubdtype(an.dtype, np.integer)
                    and np.issubdtype(bn.dtype, np.integer)):
                q = np.floor_divide(an, bn)
                rem = an - q * bn
                # C remainder: sign of the dividend
                return rem - bn * ((rem != 0) & ((an < 0) != (bn < 0)))
            return np.fmod(an, bn)
        if op in ("&", "|", "^"):
            if an.dtype == np.bool_ and bn.dtype == np.bool_:
                return _NP_BOOL_OPS[op](an, bn)
            return _NP_BIT_OPS[op](an, bn)
        raise _rt_err(loc, f"unknown operator {op!r}")
    from jax import lax
    aj, bj = jnp.asarray(a), jnp.asarray(b)
    if op in _ARITH_PROMOTE:
        # C integer promotion, traced path (see _promote_narrow_np)
        if aj.dtype in (jnp.int8, jnp.int16, jnp.uint8, jnp.uint16):
            aj = aj.astype(jnp.int32)
        if bj.dtype in (jnp.int8, jnp.int16, jnp.uint8, jnp.uint16):
            bj = bj.astype(jnp.int32)
    if op in ("+", "-", "*", "**"):
        return {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
                "**": jnp.power}[op](aj, bj)
    if op == "/":
        if (jnp.issubdtype(aj.dtype, jnp.integer)
                and jnp.issubdtype(bj.dtype, jnp.integer)):
            aj, bj = jnp.broadcast_arrays(aj, bj)
            return lax.div(aj, bj)      # C-style truncating int division
        return jnp.divide(aj, bj)
    if op == "%":
        aj, bj = jnp.broadcast_arrays(aj, bj)
        return lax.rem(aj, bj)
    if op == "<<":
        return jnp.left_shift(aj, bj)
    if op == ">>":
        return jnp.right_shift(aj, bj)
    if op in ("<", "<=", ">", ">=", "==", "!="):
        return {"<": jnp.less, "<=": jnp.less_equal, ">": jnp.greater,
                ">=": jnp.greater_equal, "==": jnp.equal,
                "!=": jnp.not_equal}[op](aj, bj)
    if op in ("&", "|", "^"):
        if aj.dtype == jnp.bool_ and bj.dtype == jnp.bool_:
            return {"&": jnp.logical_and, "|": jnp.logical_or,
                    "^": jnp.logical_xor}[op](aj, bj)
        return {"&": jnp.bitwise_and, "|": jnp.bitwise_or,
                "^": jnp.bitwise_xor}[op](aj, bj)
    raise _rt_err(loc, f"unknown operator {op!r}")


# --------------------------------------------------------------------------
# Expression evaluation
# --------------------------------------------------------------------------

_BASE_TYPE_NAMES = frozenset(
    ("bit", "bool", "int", "int8", "int16", "int32", "int64", "double",
     "complex", "complex16", "complex32"))


def _fx_ty_hint(e: A.Expr, scope: Scope):
    """Does `e`'s DECLARED type say complex16 (True), say something
    non-complex (False), or say nothing (None)? Used so the fx pair
    heuristic never hijacks arithmetic on variables the program
    declared as plain int arrays."""
    if isinstance(e, A.EBin):
        ha = _fx_ty_hint(e.a, scope)
        hb = _fx_ty_hint(e.b, scope)
        if ha is True or hb is True:
            return True
        if ha is False and hb is False:
            return False
        return None
    if isinstance(e, A.ECall) and e.name in _BASE_TYPE_NAMES:
        return e.name == "complex16"
    ty = None
    if isinstance(e, A.EVar):
        c = scope.find(e.name)
        ty = c.ty if c is not None else None
    elif isinstance(e, (A.EIdx, A.ESlice)) and isinstance(e.arr, A.EVar):
        c = scope.find(e.arr.name)
        if c is not None and isinstance(c.ty, A.TArr):
            ty = c.ty.elem
    if isinstance(ty, A.TArr):
        ty = ty.elem
    if isinstance(ty, A.TBase):
        return ty.name == "complex16"
    return None


def eval_expr(e: A.Expr, scope: Scope, ctx: Ctx) -> Any:
    jnp = _jnp()
    if isinstance(e, A.EInt):
        return e.val
    if isinstance(e, A.EFloat):
        return e.val
    if isinstance(e, A.EBit):
        return e.val
    if isinstance(e, A.EBool):
        return e.val
    if isinstance(e, A.EString):
        return e.val
    if isinstance(e, A.EVar):
        return scope.lookup(e.name, e.loc)
    if isinstance(e, A.EUn):
        v = eval_expr(e.e, scope, ctx)
        xp = np if _np_ok(v) else _jnp()
        if e.op == "-":
            return -v if is_static(v) else xp.negative(v)
        if e.op == "~":
            return ~v if is_static(v) else xp.bitwise_not(v)
        if e.op == "!":
            return (not v) if is_static(v) else xp.logical_not(v)
        raise _rt_err(e.loc, f"unknown unary {e.op!r}")
    if isinstance(e, A.EBin):
        fxp = ctx.fxp_complex16
        if fxp:
            memo = ctx.fx_hints.get(id(e))
            if memo is None or memo[0] is not e:
                memo = (e, _fx_ty_hint(e, scope))
                ctx.fx_hints[id(e)] = memo
            if memo[1] is False:
                fxp = False   # declared non-complex: stay elementwise
        return _binop(e.op, eval_expr(e.a, scope, ctx),
                      eval_expr(e.b, scope, ctx), e.loc, fxp=fxp)
    if isinstance(e, A.ECond):
        c = eval_expr(e.c, scope, ctx)
        if is_static(c):
            return eval_expr(e.a if c else e.b, scope, ctx)
        a = eval_expr(e.a, scope, ctx)
        b = eval_expr(e.b, scope, ctx)
        return (np if _np_ok(c, a, b) else jnp).where(c, a, b)
    if isinstance(e, A.ECall):
        return _eval_call(e, scope, ctx)
    if isinstance(e, A.EIdx):
        arr = eval_expr(e.arr, scope, ctx)
        i = eval_expr(e.i, scope, ctx)
        if isinstance(arr, dict):
            raise _rt_err(e.loc, "cannot index a struct")
        if is_static(i):
            _check_index(int(i), arr, e.loc)
            return arr[int(i)]
        if _np_ok(arr, i):
            ia = np.asarray(i)
            if ia.ndim == 0:
                # concrete scalar index: enforce C bounds discipline (no
                # Python negative wraparound) on the numpy fast path too
                _check_index(int(ia), arr, e.loc)
                return np.asarray(arr)[int(ia)]
            return np.asarray(arr)[ia]
        return jnp.asarray(arr)[i]
    if isinstance(e, A.ESlice):
        arr = eval_expr(e.arr, scope, ctx)
        arr = np.asarray(arr) if _np_ok(arr) else jnp.asarray(arr)
        i = eval_expr(e.i, scope, ctx)
        try:
            n = ctx.static_eval(e.n, scope)
        except NotStatic:
            raise _rt_err(e.n.loc, "slice length must be compile-time "
                                   "static (x[i, n] with static n)")
        if is_static(i):
            i = int(i)
            if i < 0 or i + n > arr.shape[0]:
                raise _rt_err(e.loc, f"slice [{i}, {n}] out of bounds for "
                                     f"array of length {arr.shape[0]}")
            return arr[i:i + int(n)]
        if isinstance(arr, np.ndarray) and _np_ok(i):
            ii = int(i)
            if ii < 0 or ii + n > arr.shape[0]:
                raise _rt_err(e.loc, f"slice [{ii}, {n}] out of bounds "
                                     f"for array of length {arr.shape[0]}")
            return arr[ii:ii + int(n)]
        from jax import lax
        return lax.dynamic_slice_in_dim(arr, i, int(n))
    if isinstance(e, A.EField):
        v = eval_expr(e.e, scope, ctx)
        if ctx.fxp_complex16 and e.f in ("re", "im") and fx_is_pair(v):
            return v[..., 0] if e.f == "re" else v[..., 1]
        if isinstance(v, dict):
            if e.f not in v:
                raise _rt_err(e.loc, f"struct {v.get('__struct__')} has "
                                     f"no field {e.f!r}")
            return v[e.f]
        if e.f == "re":
            return v.real if is_static(v) or _np_ok(v) else jnp.real(v)
        if e.f == "im":
            return v.imag if is_static(v) or _np_ok(v) else jnp.imag(v)
        raise _rt_err(e.loc, f"no field {e.f!r} on a non-struct value")
    if isinstance(e, A.EArrLit):
        vals = [eval_expr(x, scope, ctx) for x in e.elems]
        if all(is_static(v) for v in vals):
            return np.array(vals)
        if _np_ok(*vals):
            return np.stack([np.asarray(v) for v in vals])
        return jnp.stack([jnp.asarray(v) for v in vals])
    if isinstance(e, A.EStructLit):
        sd = ctx.structs.get(e.name)
        if sd is None:
            raise _rt_err(e.loc, f"unknown struct {e.name!r}")
        v = {fn: eval_expr(fe, scope, ctx) for fn, fe in e.fields}
        return cast_value(A.TStruct(e.name), v, ctx.structs,
                          lambda x: ctx.static_eval(x, scope),
                          fxp=ctx.fxp_complex16)
    raise _rt_err(getattr(e, "loc", (0, 0)),
                  f"unknown expression node {type(e).__name__}")


def _ty_is_cplx(ty) -> Optional[str]:
    t = ty.elem if isinstance(ty, A.TArr) else ty
    if isinstance(t, A.TBase) and t.name in _CPLX:
        return t.name
    return None


def _fx_ext_arg(v: Any, ty) -> Any:
    """Pair -> complex64 at a complex-typed ext boundary (fxp policy:
    f32 is retained only inside explicitly complex-typed ext bricks
    such as v_fft)."""
    if _ty_is_cplx(ty) and fx_is_pair(v):
        from ziria_tpu.ops.cplx import to_complex
        return to_complex(v, np if _np_ok(v) else _jnp())
    return v


def _fx_ext_ret(v: Any, ty) -> Any:
    """complex16-typed ext results requantize back to pairs; wider
    complex return types stay in the f32 domain."""
    if _ty_is_cplx(ty) == "complex16" and not fx_is_pair(v):
        return _fx_cast(v)
    return v


def _eval_call(e: A.ECall, scope: Scope, ctx: Ctx) -> Any:
    jnp = _jnp()
    args = [eval_expr(a, scope, ctx) for a in e.args]
    name = e.name
    # casts / complex constructors
    if name in _BASE_TYPE_NAMES:
        if name in _CPLX and len(args) == 2:
            re, im = args
            if ctx.fxp_complex16 and name == "complex16":
                return fx_pair(re, im)
            if is_static(re) and is_static(im):
                return complex(re, im)
            xp = np if _np_ok(re, im) else jnp
            return (xp.asarray(re, np.float32)
                    + 1j * xp.asarray(im, np.float32)).astype(
                        np.complex64)
        if len(args) != 1:
            raise _rt_err(e.loc, f"cast {name} takes one argument")
        return cast_value(A.TBase(name), args[0], ctx.structs,
                          lambda x: ctx.static_eval(x, scope),
                          fxp=ctx.fxp_complex16)
    # user expression functions
    fd = ctx.funs.get(name)
    if fd is not None:
        if ctx.autolut and not _np_ok(*args) \
                and len(args) == len(fd.decl.params):
            # staged call with traced args: LUT-able pure funs become
            # one table gather (lutinfer, the LUTAnalysis role); arity
            # mismatches fall through to call_fun's clear error rather
            # than zip-truncating into a wrong table index
            from ziria_tpu.frontend import lutinfer
            spec = lutinfer.spec_for_fun(name, fd, ctx)
            if spec is not None \
                    and lutinfer.args_match_spec(spec, args):
                table = ctx.lut_tables.get(name)
                if table is None:
                    try:
                        table = lutinfer.build_fun_table(spec, fd, ctx)
                    except (lutinfer.TableTooLarge, ZiriaRuntimeError):
                        # output too big for the cap, or a body the
                        # domain sweep cannot evaluate — permanently
                        # fall back to the direct call
                        ctx.lut_specs[name] = None
                        spec = None
                    else:
                        ctx.lut_tables[name] = table
                if spec is not None:
                    return lutinfer.gather(
                        table, lutinfer.encode_args(spec, args))
        return call_fun(fd, args, ctx, e.loc)
    # ext / builtin functions
    fn = ctx.exts.get(name)
    if fn is not None:
        sig = ctx.ext_sigs.get(name) if ctx.fxp_complex16 else None
        if sig is not None:
            args = [_fx_ext_arg(v, p.ty)
                    for v, p in zip(args, sig.params)]
            return _fx_ext_ret(fn(*args), sig.ret_ty)
        return fn(*args)
    # print family
    if name in ("print", "println", "error"):
        msg = "".join(_fmt_value(a) for a in args)
        if name == "error":
            raise ZiriaRuntimeError(f"error: {msg}")
        ctx.on_print(msg + ("\n" if name == "println" else ""))
        return None
    raise _rt_err(e.loc, f"unknown function {name!r}")


def _check_index(i: int, arr: Any, loc) -> None:
    """C-like bounds discipline: no Python negative wraparound."""
    n = np.shape(arr)[0] if np.shape(arr) else None
    if n is None:
        raise _rt_err(loc, "cannot index a scalar")
    if i < 0 or i >= n:
        raise _rt_err(loc, f"index {i} out of bounds for array of "
                           f"length {n}")


def _fmt_value(v: Any) -> str:
    if hasattr(v, "dtype") and getattr(v, "shape", None) == ():
        try:
            v = v.item()
        except Exception:
            pass
    return str(v)


def call_fun(fd: FunDef, args: List[Any], ctx: Ctx, loc=(0, 0)) -> Any:
    d = fd.decl
    if len(args) != len(d.params):
        raise _rt_err(loc, f"{d.name}: expected {len(d.params)} args, "
                           f"got {len(args)}")
    s = fd.closure.child()
    for p, v in zip(d.params, args):
        ty = p.ty
        # length-polymorphic array params adopt the argument's length
        if ty is not None:
            v = cast_value(ty, v, ctx.structs,
                           lambda x: ctx.static_eval(x, fd.closure),
                           fxp=ctx.fxp_complex16)
        s.declare(p.name, v, ty, mutable=False)
    r = exec_stmts(d.body, s, ctx)
    v = r[1] if r is not None else None
    if d.ret_ty is not None and v is not None:
        v = cast_value(d.ret_ty, v, ctx.structs,
                       lambda x: ctx.static_eval(x, fd.closure),
                       fxp=ctx.fxp_complex16)
    return v


# --------------------------------------------------------------------------
# Statement execution
# --------------------------------------------------------------------------


def exec_stmts(stmts, scope: Scope, ctx: Ctx) -> Optional[Tuple[str, Any]]:
    """Run statements; returns ('ret', v) if a `return` fired, else None."""
    for st in stmts:
        r = exec_stmt(st, scope, ctx)
        if r is not None:
            return r
    return None


def exec_stmt(st: A.Stmt, scope: Scope, ctx: Ctx) -> Optional[Tuple[str, Any]]:
    jnp = _jnp()
    if isinstance(st, A.SVar):
        se = lambda x: ctx.static_eval(x, scope)   # noqa: E731
        if st.init is not None:
            v = cast_value(st.ty, eval_expr(st.init, scope, ctx),
                           ctx.structs, se, fxp=ctx.fxp_complex16)
        else:
            v = zero_value(st.ty, ctx.structs, se,
                           fxp=ctx.fxp_complex16)
        scope.declare(st.name, v, st.ty, mutable=True)
        return None
    if isinstance(st, A.SLet):
        v = eval_expr(st.e, scope, ctx)
        if st.ty is not None:
            v = cast_value(st.ty, v, ctx.structs,
                           lambda x: ctx.static_eval(x, scope),
                           fxp=ctx.fxp_complex16)
        scope.declare(st.name, v, st.ty, mutable=False)
        return None
    if isinstance(st, A.SAssign):
        v = eval_expr(st.e, scope, ctx)
        _assign_lval(st.lval, v, scope, ctx)
        return None
    if isinstance(st, A.SIf):
        c = eval_expr(st.c, scope, ctx)
        if is_static(c):
            return exec_stmts(st.then if c else st.els, scope.child(), ctx)
        if _is_traced(c) or np.ndim(c) >= 1:
            # traced scalar OR lane-vector condition (vectorized loop
            # mode: the loop var is a concrete arange, so var-only
            # conditions like `k >= 16` arrive concrete but
            # non-scalar): where-merge / per-lane select
            return _staged_if(c, st, scope, ctx)
        return exec_stmts(st.then if bool(c) else st.els,
                          scope.child(), ctx)      # concrete (np or jnp)
    if isinstance(st, A.SFor):
        try:
            start = ctx.static_eval(st.start, scope)
            count = ctx.static_eval(st.count, scope)
        except NotStatic:
            if _tracing() and not _has_return(st.body):
                # traced trip count inside a jit trace (e.g. a bound
                # computed from traced data): lax.fori_loop accepts
                # traced bounds, so stage instead of refusing — the C
                # backend of the reference compiles these trivially
                s_v = eval_expr(st.start, scope, ctx)
                c_v = eval_expr(st.count, scope, ctx)
                if np.size(s_v) == 1 and np.size(c_v) == 1:
                    return _staged_for(s_v, c_v, st, scope, ctx)
            raise _rt_err(st.loc, "for-loop bounds must be compile-time "
                                  "static (use while for dynamic trip "
                                  "counts)")
        if int(count) >= FORI_MIN_COUNT and _tracing() \
                and not _has_return(st.body) \
                and _reads_traced(st.body, scope):
            # large loop over traced data inside a jit trace: stage as
            # ONE lax.fori_loop instead of unrolling count copies of
            # the body into the graph (compile-time blow-up on e.g. a
            # 258x64 correlation); loops over concrete values keep the
            # Python path so they constant-fold at trace time
            return _staged_for(int(start), int(count), st, scope, ctx)
        for i in range(int(start), int(start) + int(count)):
            s = scope.child()
            s.declare(st.var, i, None, mutable=False)
            r = exec_stmts(st.body, s, ctx)
            if r is not None:
                return r
        return None
    if isinstance(st, A.SWhile):
        while True:
            c = eval_expr(st.c, scope, ctx)
            if np.size(c) != 1:
                # concrete OR traced non-scalar: a condition bug, not a
                # staging situation — diagnose it as such
                raise _rt_err(st.loc,
                              f"while condition must be a scalar "
                              f"boolean, got shape {np.shape(c)}")
            if _is_traced(c):
                # traced condition (possibly only from this iteration
                # on): stage the rest of the loop as lax.while_loop
                return _staged_while(st, scope, ctx)
            if not bool(c):
                return None
            r = exec_stmts(st.body, scope.child(), ctx)
            if r is not None:
                return r
    if isinstance(st, A.SReturn):
        return ("ret", eval_expr(st.e, scope, ctx))
    if isinstance(st, A.SExpr):
        eval_expr(st.e, scope, ctx)
        return None
    raise _rt_err(st.loc, f"unknown statement {type(st).__name__}")


# statement for-loops at or above this trip count, reading traced data
# inside a jit trace, stage as lax.fori_loop; below it they unroll
# (small bodies fuse better as straight-line code)
FORI_MIN_COUNT = 24


def _tracing() -> bool:
    """True when called under a jax trace (jit/vmap/scan staging)."""
    try:
        from jax._src.core import trace_state_clean
    except ImportError:       # public alias in some jax versions
        try:
            from jax.core import trace_state_clean  # type: ignore
        except ImportError:
            return False
    return not trace_state_clean()


def _expr_reads(e: Optional[A.Expr], acc: set) -> None:
    for x in A.iter_exprs(e):
        if isinstance(x, A.EVar):
            acc.add(x.name)


def _stmt_reads(stmts, acc: set) -> None:
    for x in A.iter_stmt_exprs(stmts):
        if isinstance(x, A.EVar):
            acc.add(x.name)


def _reads_traced(stmts, scope: Scope) -> bool:
    """Does this body read any name currently bound to a traced value?
    (Over-approximates: locally-declared names are included but resolve
    to outer cells or nothing — both harmless.)"""
    names: set = set()
    _stmt_reads(stmts, names)
    for name in names:
        c = scope.find(name)
        if c is not None and _is_traced(c.value):
            return True
    return False


def _has_return(stmts) -> bool:
    return any(isinstance(st, A.SReturn) for st in A.iter_stmts(stmts))


def _stmt_writes(stmts, acc: set) -> None:
    """Names assigned (lval roots) or var-declared in this body —
    the loop-carried set for staged for/while. Over-approximates with
    body-local declarations; those resolve to shadowing outer cells or
    nothing, both harmless."""
    for st in A.iter_stmts(stmts):
        if isinstance(st, (A.SVar, A.SLet)):
            acc.add(st.name)
        elif isinstance(st, A.SAssign):
            e = st.lval
            while isinstance(e, (A.EIdx, A.ESlice, A.EField)):
                e = e.e if isinstance(e, A.EField) else e.arr
            if isinstance(e, A.EVar):
                acc.add(e.name)


def _written_cells(stmts, scope: Scope) -> List[Any]:
    """Only the mutable cells this body can assign: the minimal carry
    for lax.fori_loop/while_loop staging. Threading every cell in scope
    (the _staged_if approach) makes carries ~25 leaves deep in real
    programs and was measured to blow both compile time and the traced
    graph size."""
    writes: set = set()
    _stmt_writes(stmts, writes)
    return [c for n, c in scope.mutable_cells_named() if n in writes]


# elementwise-safe calls a vectorized loop body may contain: base-type
# casts/constructors plus the elementwise ext math bricks. Anything
# else (user funs, v_* vector bricks, effects) bails to fori staging.
_VECTOR_SAFE_CALLS = _BASE_TYPE_NAMES | frozenset(
    ("sin", "cos", "tan", "atan", "atan2", "sqrt", "exp", "log",
     "abs", "conj", "floor", "ceil", "round", "sign"))

# kill switch for debugging / A-B timing
VECTORIZE_STMT_LOOPS = True


def _vector_loops_enabled() -> bool:
    """The ONE reading of the ZIRIA_NO_VECTOR_LOOPS escape hatch
    (combined with the module kill switch) — the designated
    single-reader form the jaxlint R4 hygiene rule enforces."""
    import os

    return VECTORIZE_STMT_LOOPS \
        and not os.environ.get("ZIRIA_NO_VECTOR_LOOPS")


class _VectorBail(Exception):
    """Body not vectorizable (analysis or runtime shape failure)."""


def _affine_in(e: A.Expr, var: str):
    """`e` as a*var + b with STATIC int a != 0 and b free of `var`.
    Returns (a, b_ast_or_int) or None. b is returned as an AST (or 0)
    to be evaluated loop-invariantly by the caller."""
    if isinstance(e, A.EVar) and e.name == var:
        return 1, 0
    if isinstance(e, A.EBin):
        if e.op == "+":
            la, ra = _affine_in(e.a, var), _affine_in(e.b, var)
            if la is not None and ra is None \
                    and var not in _free_names(e.b):
                return la[0], _add_ast(la[1], e.b)
            if ra is not None and la is None \
                    and var not in _free_names(e.a):
                return ra[0], _add_ast(ra[1], e.a)
        elif e.op == "-":
            la = _affine_in(e.a, var)
            if la is not None and var not in _free_names(e.b):
                return la[0], _sub_ast(la[1], e.b)
        elif e.op == "*":
            if isinstance(e.a, A.EInt) and isinstance(e.b, A.EVar) \
                    and e.b.name == var and e.a.val != 0:
                return int(e.a.val), 0
            if isinstance(e.b, A.EInt) and isinstance(e.a, A.EVar) \
                    and e.a.name == var and e.b.val != 0:
                return int(e.b.val), 0
    return None


def _free_names(e: Optional[A.Expr]) -> set:
    out: set = set()
    _expr_reads(e, out)
    return out


def _add_ast(b, e):
    if isinstance(b, int) and b == 0:
        return e
    ba = A.EInt(val=b) if isinstance(b, int) else b
    return A.EBin(op="+", a=ba, b=e)


def _sub_ast(b, e):
    ba = A.EInt(val=b) if isinstance(b, int) else b
    return A.EBin(op="-", a=ba, b=e)


def _vector_plan(st: A.SFor, scope: Scope, ctx: Ctx):
    """Analyze a statement for-loop body for lane-vector execution.

    Eligible bodies contain only: local SCALAR declarations, pure
    elementwise expressions (whitelisted calls), writes to body-local
    scalars, additive updates to outer scalars, and element writes to
    outer arrays whose indices are affine in the loop var with static
    stride — same-array sites (after collapsing structurally-equal
    index expressions, e.g. the two arms of an if writing the same
    element) sharing one stride with pairwise distinct static offsets
    mod stride (so scatter lanes never collide and site order is
    immaterial across lanes). No nested loops, no local arrays (their
    per-iteration privacy has no lane representation), no returns.

    Outer-scalar updates classify two ways:

    - **affine induction** (`v := v +/- c`, ONE unconditional site, c
      loop-invariant): per-lane entry values are a closed form (ints)
      or a sequential-rounding scan (floats) — the r3 machinery.
    - **general int induction** (any number of sites, conditional
      and/or var-dependent steps — the depuncture `src := src + 1`
      under `keep == 1`, the parity `par := par + sbits[t]`): per-lane
      contributions are DISCOVERED by a first vector pass over the
      body with the scalar pinned to its entry value broadcast (lane i
      then holds v0 + own-contributions); an exclusive cumsum turns
      the contributions into exact per-lane entry values for the real
      pass (VERDICT r3 next #4). Ints only — lane-summation order
      never changes an int result, while float cumsum rounds
      differently than the sequential loop. Pass-1 masks must be
      discovery-stable: no if condition and no induction step may
      (transitively through locals or written arrays) read a general
      induction var.

    Written arrays may be read (read-modify-write) when every read
    index is affine with the same stride and each (read, write) offset
    pair is either structurally identical (a lane reads only what IT
    wrote — program order within the lane is preserved by vector
    execution) or provably non-colliding ((br-bw) % stride != 0).

    Returns {"inductions": {name: (sign, step_ast)}, "gen": {names}}
    or None.
    """
    var = st.var
    decl_names: set = set()     # every name declared ANYWHERE in body
    scalar_sites: dict = {}     # name -> [(sign, step_ast, in_if)]
    arr_sites: dict = {}        # name -> [(a, b_static_or_None, idx_ast)]
    arr_reads: dict = {}        # name -> [(a, b_static_or_None, idx_ast)]
    bare_reads: set = set()     # names read other than via affine EIdx
    deps: dict = {}             # written name -> names its values read
    cond_names: set = set()     # names dynamic if-conditions read
    body_writes: set = set()    # every name the body may assign
    _stmt_writes(st.body, body_writes)

    def expr_ok(e) -> bool:
        for x in A.iter_exprs(e):
            if isinstance(x, A.ECall):
                if x.name not in _VECTOR_SAFE_CALLS:
                    return False
            elif isinstance(x, A.ESlice):
                # slice reads with var-dependent starts have no single
                # gather form; allow only var-free slices
                if var in _free_names(x.i):
                    return False
        return True

    def note_reads(e):
        # array read sites: affine gathers are provable against write
        # sites; anything else marks the array as opaquely read
        base_ids: set = set()
        for x in A.iter_exprs(e):
            if isinstance(x, A.EIdx) and isinstance(x.arr, A.EVar):
                base_ids.add(id(x.arr))
                aff = _affine_in(x.i, var)
                if aff is None:
                    bare_reads.add(x.arr.name)
                else:
                    a, b = aff
                    bs = b if isinstance(b, int) else (
                        int(b.val) if isinstance(b, A.EInt) else None)
                    arr_reads.setdefault(x.arr.name, []).append(
                        (a, bs, x.i))
            elif isinstance(x, A.ESlice) and isinstance(x.arr, A.EVar):
                base_ids.add(id(x.arr))
                bare_reads.add(x.arr.name)
            elif isinstance(x, A.EVar) and id(x) not in base_ids:
                bare_reads.add(x.name)

    def walk(stmts, in_if: bool, outer_locals: set) -> bool:
        # lexically-scoped local tracking: a declaration is visible
        # from its statement onward WITHIN this block (and nested
        # arms), and dies with the block — an arm-local must not make
        # a later outer-scalar write look local (code review r3)
        lc = set(outer_locals)
        for s in stmts:
            if isinstance(s, (A.SWhile, A.SFor, A.SReturn)):
                return False
            if isinstance(s, (A.SVar, A.SLet)):
                if s.name == var:
                    return False
                if isinstance(s.ty, A.TArr):
                    return False   # local array: no lane privacy
                init = s.init if isinstance(s, A.SVar) else s.e
                if init is not None and not expr_ok(init):
                    return False
                if init is not None:
                    note_reads(init)
                    deps.setdefault(s.name, set()).update(
                        _free_names(init))
                lc.add(s.name)
                decl_names.add(s.name)
            elif isinstance(s, A.SIf):
                # statically-decided branches (rate-dispatch literals):
                # analyze only the live arm, mirroring exec_stmt's
                # fold — dead arms would otherwise poison the plan
                # (e.g. mixed demap strides across nbpsc arms). Only
                # safe when no body-local shadows a condition name
                # (execution resolves the LOCAL, the fold saw the
                # outer) AND nothing the body writes feeds the
                # condition — a concrete pre-loop value of a variable
                # the loop updates would freeze a branch the analysis
                # then never checks while execution still runs it
                fn = _free_names(s.c)
                if not (fn & lc) and var not in fn \
                        and not (fn & body_writes):
                    try:
                        cv = ctx.static_eval(s.c, scope)
                    except Exception:
                        cv = None
                    if cv is not None and is_static(cv):
                        if not walk(s.then if cv else s.els, in_if, lc):
                            return False
                        continue
                if not expr_ok(s.c):
                    return False
                note_reads(s.c)
                cond_names.update(fn)
                if not walk(s.then, True, lc) \
                        or not walk(s.els, True, lc):
                    return False
            elif isinstance(s, A.SAssign):
                if not expr_ok(s.e):
                    return False
                note_reads(s.e)
                lv = s.lval
                if isinstance(lv, A.EVar):
                    if lv.name in lc:
                        deps.setdefault(lv.name, set()).update(
                            _free_names(s.e))
                        continue
                    cell = scope.find(lv.name)
                    if cell is None or not cell.mutable:
                        return False
                    # outer scalar: additive update sites only
                    # (v := v +/- e or v := e + v, v not in e);
                    # classification into affine vs general induction
                    # happens after the walk
                    e = s.e
                    site = None
                    if isinstance(e, A.EBin) and e.op in "+-":
                        if isinstance(e.a, A.EVar) \
                                and e.a.name == lv.name \
                                and lv.name not in _free_names(e.b) \
                                and expr_ok(e.b):
                            site = (1 if e.op == "+" else -1, e.b)
                        elif e.op == "+" and isinstance(e.b, A.EVar) \
                                and e.b.name == lv.name \
                                and lv.name not in _free_names(e.a) \
                                and expr_ok(e.a):
                            site = (1, e.a)
                    if site is None:
                        return False
                    deps.setdefault(lv.name, set()).update(
                        _free_names(site[1]))
                    scalar_sites.setdefault(lv.name, []).append(
                        (site[0], site[1], in_if))
                elif isinstance(lv, A.EIdx) \
                        and isinstance(lv.arr, A.EVar):
                    name = lv.arr.name
                    if name in lc:
                        return False   # local arrays already rejected
                    cell = scope.find(name)
                    if cell is None or not cell.mutable:
                        return False
                    if not expr_ok(lv.i):
                        return False
                    aff = _affine_in(lv.i, var)
                    if aff is None:
                        return False
                    a, b = aff
                    note_reads(lv.i)
                    deps.setdefault(name, set()).update(
                        _free_names(s.e) | _free_names(lv.i))
                    b_static = b if isinstance(b, int) else (
                        int(b.val) if isinstance(b, A.EInt) else None)
                    arr_sites.setdefault(name, []).append(
                        (a, b_static, lv.i))
                else:
                    return False
            elif isinstance(s, A.SExpr):
                return False       # call for effect: not vectorizable
            else:
                return False
        return True

    if not walk(st.body, False, set()):
        return None

    # ---- written arrays: collapse structurally-equal index sites
    # (if-arm pairs), then prove scatter lanes never collide, and
    # check every read of a written array against the RMW rules.
    # EVERY site index offset must be loop-invariant (free of names
    # the body writes or declares): a per-lane-varying offset breaks
    # the injectivity the whole collision argument rests on (code
    # review r4: `a[k - s] := a[k - s] + x` with s an induction had
    # every lane resolving to one element)
    loop_varying = set(scalar_sites) | set(arr_sites) | decl_names
    for name, sites in arr_sites.items():
        uniq: list = []
        for site in sites:
            if not any(site[2] == u[2] for u in uniq):
                uniq.append(site)
        arr_sites[name] = uniq
        for _a, _b, idx in uniq:
            if _free_names(idx) & loop_varying:
                return None
        if len(uniq) > 1:
            a0 = uniq[0][0]
            if any(a != a0 or b is None for a, b, _i in uniq):
                return None
            offs = [b % abs(a0) for _a, b, _i in uniq]
            if len(set(offs)) != len(offs):
                return None
        if name in bare_reads:
            return None
        for ra, rb, ri in arr_reads.get(name, ()):
            if _free_names(ri) & loop_varying:
                return None
            for wa, wb, wi in uniq:
                if ri == wi:
                    continue      # lane reads only what IT writes
                if ra != wa or rb is None or wb is None \
                        or (rb - wb) % abs(wa) == 0:
                    return None   # possible cross-lane collision

    # ---- outer-scalar classification: affine fast path (closed
    # form / float scan) vs general int induction (two-pass cumsum)
    inductions: dict = {}
    gen: set = set()
    written = set(arr_sites) | set(scalar_sites)
    for name, sites in scalar_sites.items():
        if len(sites) == 1 and not sites[0][2] \
                and not (_free_names(sites[0][1])
                         & ({var} | written | decl_names)):
            inductions[name] = (sites[0][0], sites[0][1])
        else:
            gen.add(name)

    if gen:
        # ints only: lane-order summation is exact for ints; float
        # cumsum rounds differently than the sequential loop
        for name in gen:
            v0 = scope.find(name).value
            dt = getattr(v0, "dtype", None)
            if dt is not None:
                if np.ndim(v0) != 0 \
                        or not np.issubdtype(dt, np.integer):
                    return None
            elif isinstance(v0, bool) or not isinstance(
                    v0, (int, np.integer)):
                return None
        # discovery stability: pass 1 runs with general vars pinned to
        # broadcast entry values, so nothing that decides which sites
        # fire (if conditions) or what they add (steps) may read a
        # general var — directly or through locals/arrays it flowed
        # into
        tainted = set(gen)
        changed = True
        while changed:
            changed = False
            for nm, srcs in deps.items():
                if nm not in tainted and srcs & tainted:
                    tainted.add(nm)
                    changed = True
        if cond_names & tainted:
            return None
        for name, sites in scalar_sites.items():
            for _sgn, step, _inif in sites:
                if _free_names(step) & tainted:
                    return None
    return {"inductions": inductions, "gen": gen}


def _vectorized_for(start: int, count: int, st: A.SFor, scope: Scope,
                    ctx: Ctx) -> bool:
    """Execute an eligible statement loop as ONE lane-vector pass:
    the loop variable becomes arange(n), scalar locals become lane
    vectors, data-dependent ifs become per-lane selects (the value-
    select machinery), and outer-array element writes become single
    scatters — the reference vectorizer's widening, applied to
    statement loops (SURVEY.md §2.1 Vectorize), which also removes
    the per-iteration while-op cost on the VPU. Returns True when it
    ran; False leaves all state untouched (caller falls back to
    lax.fori_loop staging)."""
    if not _vector_loops_enabled():
        return False
    plan = _vector_plan(st, scope, ctx)
    if plan is None:
        return False
    jnp = _jnp()
    n = int(count)
    if n <= 0:
        return False

    # rollback snapshot: every mutable cell value currently visible
    snap = [(c, c.value) for _n, c in scope.mutable_cells_named()]

    def lane_scope(gen_entries):
        """Child scope with the loop var as arange, affine-induction
        shadows at their per-lane entry values, and general-induction
        shadows at `gen_entries[name]`. Returns (scope, finals)."""
        vs = scope.child()
        i_vec = jnp.arange(start, start + n, dtype=jnp.int32)
        vs.declare(st.var, i_vec, None, mutable=False)
        finals: dict = {}
        for name, (sgn, step_ast) in plan["inductions"].items():
            v0 = scope.lookup(name, st.loc)
            c = eval_expr(step_ast, scope, ctx)     # loop-invariant
            if np.ndim(c) != 0 or np.ndim(v0) != 0:
                raise _VectorBail("non-scalar induction")
            stepv = c if sgn > 0 else -c
            if np.issubdtype(jnp.asarray(v0).dtype, np.integer) \
                    and np.issubdtype(jnp.asarray(stepv).dtype,
                                      np.integer):
                starts = v0 + jnp.arange(n) * stepv   # exact closed form
                finals[name] = v0 + n * stepv
            else:
                # float induction: reproduce SEQUENTIAL accumulation
                # bit-for-bit (closed form rounds differently)
                from jax import lax

                def acc_fn(a, _x, _c=stepv):
                    nxt = a + _c
                    return nxt, a

                end, starts = lax.scan(
                    acc_fn, jnp.asarray(v0), None, length=n)
                finals[name] = end
            # shadow cell: body updates hit the lane vector, the final
            # scalar goes to the outer cell afterwards
            vs.declare(name, starts, None, mutable=True)
        for name, entry in gen_entries.items():
            vs.declare(name, entry, None, mutable=True)
        return vs, finals

    try:
        gen = plan["gen"]
        gen_entries: dict = {}
        if gen:
            # PASS 1 (discovery): every general induction var pinned to
            # its entry value broadcast over lanes — after the pass,
            # lane i holds v0 + (its own iteration's contributions);
            # all other cell mutations are discarded. The plan's taint
            # check guarantees the contributions themselves don't
            # depend on the pinned (wrong-prefix) values.
            v0s, pins = {}, {}
            for name in gen:
                v0 = scope.lookup(name, st.loc)
                if np.ndim(v0) != 0:
                    raise _VectorBail("non-scalar induction")
                v0s[name] = v0
                pins[name] = jnp.zeros(
                    (n,), jnp.asarray(v0).dtype) + v0
            vs1, _f = lane_scope(pins)
            r = exec_stmts(st.body, vs1, ctx)
            if r is not None:
                raise _VectorBail("return inside vector loop")
            for name in gen:
                t = jnp.asarray(vs1.lookup(name))
                if t.shape != (n,):
                    raise _VectorBail("induction lost lane shape")
                t = t - v0s[name]
                # exact per-lane entry: v0 + sum of lower lanes' totals
                gen_entries[name] = (v0s[name] + jnp.cumsum(t) - t)
            for c, v in snap:          # discard pass-1 side effects
                c.value = v

        vs, finals = lane_scope(gen_entries)
        r = exec_stmts(st.body, vs, ctx)
        if r is not None:                 # pragma: no cover - walked
            raise _VectorBail("return inside vector loop")
        for name, fin in finals.items():
            scope.assign(name, fin, ctx, st.loc)
        for name in gen:
            # last lane's exit value = v0 + all contributions
            scope.assign(name, jnp.asarray(vs.lookup(name))[-1],
                         ctx, st.loc)
        return True
    except Exception:
        # any failure (analysis gap surfacing as a shape/type error)
        # restores every cell and falls back to fori staging, which
        # re-raises genuine program errors with proper diagnostics
        for c, v in snap:
            c.value = v
        return False


def _staged_for(start, count, st: A.SFor, scope: Scope,
                ctx: Ctx, try_gf2: bool = True):
    """Stage one statement for-loop as `lax.fori_loop` carrying the
    cells the body writes (same discipline as _staged_while: stable
    tree structure, entry-pinned leaf dtypes). The loop variable is the
    traced fori index; dynamic-index reads/writes lower to gathers and
    `.at[].set` via the normal expression paths. `start`/`count` may be
    ints or traced scalars (fori_loop takes both)."""
    import jax
    from jax import lax
    jnp = _jnp()

    # try the lane-vector lowering first: eligible bodies (affine
    # scatters, per-lane selects, induction closed forms) run as ONE
    # vector pass instead of `count` while-loop iterations
    if isinstance(start, int) and isinstance(count, int) \
            and _vectorized_for(start, count, st, scope, ctx):
        return None

    # then GF(2) affine-recurrence compression (frontend/gf2.py): LFSR
    # family loops (scramble/descramble/CRC) collapse to K-iteration
    # bit-matrix blocks; `try_gf2=False` marks its own remainder-tail
    # re-entry
    if try_gf2:
        from .gf2 import gf2_for
        if gf2_for(start, count, st, scope, ctx):
            return None

    cells = _written_cells(st.body, scope)

    try:
        flat0, td0 = jax.tree_util.tree_flatten(
            [c.value for c in cells])
        flat0 = [jnp.asarray(x) for x in flat0]
    except Exception:
        raise _rt_err(
            st.loc, "for-loop over traced data: a variable in scope "
                    "holds a non-stageable value; run this program on "
                    "the interpreter backend") from None
    dts = [x.dtype for x in flat0]

    def put(flat):
        vals = jax.tree_util.tree_unflatten(td0, list(flat))
        for c, v in zip(cells, vals):
            c.value = v

    def body_fn(i, flat):
        put(flat)
        s = scope.child()
        s.declare(st.var, i, None, mutable=False)
        r = exec_stmts(st.body, s, ctx)
        if r is not None:          # unreachable: _has_return pre-check
            raise _rt_err(st.loc, "return inside a staged for-loop")
        leaves, td = jax.tree_util.tree_flatten(
            [c.value for c in cells])
        if td != td0:
            raise _rt_err(
                st.loc, "staged for-loop changes a variable's "
                        "structure (struct fields) across iterations")
        return tuple(jnp.asarray(x).astype(dt)
                     for x, dt in zip(leaves, dts))

    try:
        out = lax.fori_loop(start, start + count, body_fn, tuple(flat0))
    except ZiriaRuntimeError:
        raise
    except TypeError as e:
        raise _rt_err(
            st.loc, f"staged for-loop has a loop-varying state shape "
                    f"({e}); every assigned variable must keep its "
                    f"shape") from None
    put(out)
    return None


def _staged_while(st: A.SWhile, scope: Scope, ctx: Ctx):
    """Dynamic-condition `while`: stage as `lax.while_loop` carrying
    every mutable cell visible at the loop (round 1 restricted dynamic
    while to the interpreter backend; the reference compiles it to a C
    while, so the jit backend must express it too — SURVEY.md §0).

    Carry discipline: each cell's value must be array-able with a
    loop-invariant tree structure and shape; leaf dtypes are pinned to
    their entry dtype (the same narrowing an assignment through the
    cell's declared type performs), so `int16 i; while (...) i := i+1`
    carries int16 even though the body's arithmetic promotes to int32.
    """
    import jax
    from jax import lax
    jnp = _jnp()
    # carry = cells the body writes, plus anything the CONDITION reads
    # that is mutable (it must be in the carry to drive the loop)
    cond_reads: set = set()
    _expr_reads(st.c, cond_reads)
    writes: set = set()
    _stmt_writes(st.body, writes)
    names = writes | cond_reads
    cells = [c for n, c in scope.mutable_cells_named() if n in names]

    try:
        flat0, td0 = jax.tree_util.tree_flatten(
            [c.value for c in cells])
        flat0 = [jnp.asarray(x) for x in flat0]
    except Exception:
        raise _rt_err(
            st.loc, "while condition is data-dependent and a variable "
                    "in scope holds a non-stageable value; run this "
                    "program on the interpreter backend") from None
    dts = [x.dtype for x in flat0]

    def put(flat):
        vals = jax.tree_util.tree_unflatten(td0, list(flat))
        for c, v in zip(cells, vals):
            c.value = v

    def cond_fn(flat):
        put(flat)
        return jnp.asarray(eval_expr(st.c, scope, ctx)) \
                  .astype(jnp.bool_).reshape(())

    def body_fn(flat):
        put(flat)
        r = exec_stmts(st.body, scope.child(), ctx)
        if r is not None:
            raise _rt_err(st.loc, "return inside a data-dependent while "
                                  "is not supported under staging")
        leaves, td = jax.tree_util.tree_flatten(
            [c.value for c in cells])
        if td != td0:
            raise _rt_err(
                st.loc, "data-dependent while changes a variable's "
                        "structure (struct fields) across iterations; "
                        "the loop state must keep one shape")
        return tuple(jnp.asarray(x).astype(dt)
                     for x, dt in zip(leaves, dts))

    try:
        out = lax.while_loop(cond_fn, body_fn, tuple(flat0))
    except ZiriaRuntimeError:
        raise
    except TypeError as e:
        raise _rt_err(
            st.loc, f"data-dependent while has a loop-varying state "
                    f"shape ({e}); under staging every assigned "
                    f"variable must keep its shape") from None
    put(out)
    return None


def _value_select_plans(st: A.SIf, scope: Scope, size_floor: int = 4096):
    """Big-buffer writes mergeable at VALUE level instead of buffer
    level. The default staged-if merge selects whole cell values; for
    `if c then { dep[i] := e1 } else { dep[i] := e2 }` over a 131072-
    element frame buffer that is a full-buffer select per execution —
    inside a staged loop, gigabytes of memory traffic (measured: it WAS
    the wifi receiver's entire per-symbol cost). When every write to a
    big cell is a single top-level element assignment through the SAME
    index expression (and the cell is otherwise untouched by the arms),
    the merge can instead select the scalar and store once.

    Returns [(name, lval_ast)] of rewritable cells.
    """
    def elem_writes(arm):
        out: Dict[str, List[A.SAssign]] = {}
        for s in arm:
            if isinstance(s, A.SAssign) and isinstance(s.lval, A.EIdx) \
                    and isinstance(s.lval.arr, A.EVar):
                out.setdefault(s.lval.arr.name, []).append(s)
        return out

    then_w, else_w = elem_writes(st.then), elem_writes(st.els)
    plans = []
    for name in sorted(set(then_w) | set(else_w)):
        cell = scope.find(name)
        if cell is None or not cell.mutable:
            continue
        try:
            if np.size(cell.value) <= size_floor:
                continue
        except Exception:       # pragma: no cover - exotic cell values
            continue
        wt = then_w.get(name, [])
        we = else_w.get(name, [])
        if len(wt) > 1 or len(we) > 1:
            continue
        lvs = [s.lval for s in wt + we]
        if len(lvs) == 2 and lvs[0] != lvs[1]:
            continue            # different indices: keep buffer merge
        site_stmts = set(map(id, wt + we))
        # the cell must appear NOWHERE else in the arms: not read (its
        # pre-branch slot value stands in for the untaken write), not
        # written from nested control flow
        ok = True
        for arm in (st.then, st.els):
            for s in arm:
                if id(s) in site_stmts:
                    reads: set = set()
                    _expr_reads(s.e, reads)
                    _expr_reads(s.lval.i, reads)
                    if name in reads:
                        ok = False
                else:
                    names: set = set()
                    _stmt_reads((s,), names)
                    _stmt_writes((s,), names)
                    if name in names:
                        ok = False
        if not ok:
            continue
        # deferring the store needs the index unchanged by the arms
        idx_reads: set = set()
        _expr_reads(lvs[0].i, idx_reads)
        arm_writes: set = set()
        _stmt_writes(st.then, arm_writes)
        _stmt_writes(st.els, arm_writes)
        if idx_reads & arm_writes:
            continue
        plans.append((name, lvs[0]))
    return plans


def _staged_if(cond, st: A.SIf, scope: Scope, ctx: Ctx):
    """Dynamic-condition `if`: run both arms on the live scope, snapshot
    mutable cells around each, and merge assigned cells with jnp.where —
    the staging of imperative control flow into select ops. Big-buffer
    single-site writes are first rewritten to scalar value-selects
    (`_value_select_plans`) so the merge never copies frame buffers."""
    jnp = _jnp()

    # lane-vector condition (vectorized statement loop): EVERY array
    # element write must go through the value-select rewrite — the
    # whole-cell where-merge cannot express a per-lane scatter. An
    # uncoverable write then fails the merge's shape check, which the
    # vectorizer catches to fall back to fori staging.
    vec_mode = getattr(cond, "ndim", 0) and np.ndim(cond) >= 1
    plans = _value_select_plans(st, scope,
                                size_floor=0 if vec_mode else 4096)
    if plans:
        import dataclasses
        tmps = {}
        for k, (name, lval) in enumerate(plans):
            t = f"__selv{k}_{name}"
            tmps[name] = t
            scope.declare(t, eval_expr(lval, scope, ctx), None,
                          mutable=True)

        def rw(stmts):
            out = []
            for s in stmts:
                if isinstance(s, A.SAssign) and isinstance(s.lval, A.EIdx) \
                        and isinstance(s.lval.arr, A.EVar) \
                        and s.lval.arr.name in tmps:
                    out.append(dataclasses.replace(
                        s, lval=A.EVar(name=tmps[s.lval.arr.name])))
                else:
                    out.append(s)
            return tuple(out)

        st2 = dataclasses.replace(st, then=rw(st.then), els=rw(st.els))
        _staged_if(cond, st2, scope, ctx)
        for name, lval in plans:
            _assign_lval(lval, scope.lookup(tmps[name]), scope, ctx)
            del scope.cells[tmps[name]]
        return None
    cells = scope.mutable_cells()
    before = [c.value for c in cells]

    r1 = exec_stmts(st.then, scope.child(), ctx)
    after_then = [c.value for c in cells]
    for c, v in zip(cells, before):
        c.value = v
    r2 = exec_stmts(st.els, scope.child(), ctx)
    after_else = [c.value for c in cells]

    if r1 is not None or r2 is not None:
        raise _rt_err(st.loc, "return inside a data-dependent if is not "
                              "supported under staging")
    def merge(t, f):
        # struct cells merge field-wise (field assignment is
        # copy-on-write, so whole-dict replacement is the normal case
        # even for `p.a := x`)
        if isinstance(t, dict) or isinstance(f, dict):
            if not (isinstance(t, dict) and isinstance(f, dict)
                    and set(t) == set(f)
                    and t.get("__struct__") == f.get("__struct__")):
                raise _rt_err(
                    st.loc, "data-dependent if assigns a struct in one "
                            "arm but not the other (or structs of "
                            "different types); both arms must leave the "
                            "variable with the same struct type")
            return {k: (t[k] if k == "__struct__" else merge(t[k], f[k]))
                    for k in t}
        ta, fa = jnp.asarray(t), jnp.asarray(f)
        if ta.shape != fa.shape and np.ndim(cond) == 0:
            raise _rt_err(
                st.loc, f"data-dependent if assigns incompatible shapes "
                        f"{ta.shape} vs {fa.shape} to the same variable; "
                        f"under staging both arms must produce the same "
                        f"shape (the merge is a jnp.where select)")
        c = jnp.asarray(cond)
        if c.ndim:
            # vectorized-loop mode (lane-vector condition): values may
            # carry trailing dims (fxp pairs) or still be pre-vector
            # scalars from an untaken path — right-expand the cond to
            # the wider side and let broadcasting unify; a genuine
            # incompatibility raises and the vectorizer falls back
            nd = max(ta.ndim, fa.ndim)
            if nd > c.ndim:
                c = c.reshape(c.shape + (1,) * (nd - c.ndim))
        return jnp.where(c, ta, fa)

    for c, b, t, f in zip(cells, before, after_then, after_else):
        if t is b and f is b:
            continue
        c.value = merge(t, f)
    return None


def _assign_lval(lval: A.Expr, v: Any, scope: Scope, ctx: Ctx) -> None:
    jnp = _jnp()
    if isinstance(lval, A.EVar):
        scope.assign(lval.name, v, ctx, lval.loc)
        return
    if isinstance(lval, A.EIdx):
        old = eval_expr(lval.arr, scope, ctx)
        i = eval_expr(lval.i, scope, ctx)
        if is_static(i):
            _check_index(int(i), old, lval.loc)
        elif _np_ok(i) and np.ndim(i) == 0:
            _check_index(int(np.asarray(i)), old, lval.loc)
        if _np_ok(old, i, v):
            # concrete path: copy-on-write keeps the functional
            # semantics (arrays are values) at numpy speed
            new = np.array(old)
            if np.ndim(i) > 0:       # lane-vector scatter
                new[np.asarray(i)] = np.asarray(v).astype(
                    new.dtype, copy=False)
            else:
                new[int(i)] = np.asarray(v).astype(new.dtype,
                                                   copy=False)
        else:
            new = jnp.asarray(old).at[i].set(
                jnp.asarray(v, dtype=jnp.asarray(old).dtype))
        _assign_lval(lval.arr, new, scope, ctx)
        return
    if isinstance(lval, A.ESlice):
        old = eval_expr(lval.arr, scope, ctx)
        i = eval_expr(lval.i, scope, ctx)
        try:
            n = ctx.static_eval(lval.n, scope)
        except NotStatic:
            raise _rt_err(lval.loc, "slice length must be static")
        if _np_ok(old, i, v):
            new = np.array(old)
            vv = np.asarray(v).astype(new.dtype, copy=False)
            new[int(i):int(i) + int(n)] = vv
            _assign_lval(lval.arr, new, scope, ctx)
            return
        old = jnp.asarray(old)
        vv = jnp.asarray(v, dtype=old.dtype)
        vv = jnp.broadcast_to(vv, (int(n),) + old.shape[1:])
        if is_static(i):
            new = old.at[int(i):int(i) + int(n)].set(vv)
        else:
            from jax import lax
            new = lax.dynamic_update_slice_in_dim(old, vv, i, axis=0)
        _assign_lval(lval.arr, new, scope, ctx)
        return
    if isinstance(lval, A.EField):
        old = eval_expr(lval.e, scope, ctx)
        if not isinstance(old, dict):
            raise _rt_err(lval.loc, "field assignment on a non-struct")
        new = dict(old)
        new[lval.f] = v
        _assign_lval(lval.e, new, scope, ctx)
        return
    raise _rt_err(getattr(lval, "loc", (0, 0)),
                  f"invalid assignment target {type(lval).__name__}")
