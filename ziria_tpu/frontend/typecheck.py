"""Static expression-level typechecker for the surface language.

Counterpart of the reference's ``TcExpr.hs``/``TcUnify.hs``/``TcComp.hs``
(SURVEY.md §2.1 typechecker row): dtype and array-length checking at
elaboration time, with located errors, so a `.zir` program with a wrong
array length, a bit/complex mismatch, or a bad ext-function call is
rejected before anything runs — previously these exploded at runtime
inside frontend/eval.py (VERDICT round 1, missing #3).

Design notes (TPU-first, not a Haskell port):

* The checker runs over the *surface AST* after `Elaborator.elaborate()`
  has registered structs/funs/exts and evaluated top-level `let`s, so
  global types are derived from actual values (an `arr[64]` table really
  has 64 elements) and array-length expressions are folded with the same
  static evaluator the elaborator uses — one arithmetic, two clients.
* Unknown is a first-class type: stream items bound by bare `take` are
  untyped here (stream typing is core/types.py's job), so the checker is
  *sound on what it knows* and silent where it knows nothing. Annotated
  binds (`(x : arr[64] complex16) <- takes 64`) get full checking.
* Numeric discipline is C-like where the evaluator is C-like (implicit
  int width changes wrap, int→double widens) and strict where silent
  coercion would corrupt data: complex→real, real→int-from-double,
  scalar→array and array-length mismatches are compile-time errors,
  matching the reference's no-implicit-casts spirit without breaking
  the evaluator's documented static-scalar laxity.
* Comp functions are checked at each call site with the actual argument
  types (the checker "inlines" like elab does), so lengths flow through
  `fun comp` parameters exactly as they will at elaboration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ziria_tpu.frontend import ast as A
from ziria_tpu.frontend import eval as E


from ziria_tpu.frontend.elab import ElabError


class ZiriaTypeError(ElabError):
    """A located static type error (src:line:col: message).

    Subclasses ElabError so callers treating "the program failed to
    compile" uniformly (CLI, tests) keep working; catch ZiriaTypeError
    specifically to distinguish type errors from structural ones."""


# --------------------------------------------------------------------------
# Checked types
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class TcTy:
    def show(self) -> str:
        return "?"


@dataclass(frozen=True)
class Unknown(TcTy):
    def show(self) -> str:
        return "?"


@dataclass(frozen=True)
class Unit(TcTy):
    def show(self) -> str:
        return "unit"


@dataclass(frozen=True)
class Str(TcTy):
    def show(self) -> str:
        return "string"


@dataclass(frozen=True)
class Base(TcTy):
    """A scalar base type. ``weak`` marks compile-time-static values
    (literals, fold results) that adapt to any numeric context, mirroring
    the evaluator's static-scalar policy (eval.py module docstring)."""

    name: str
    weak: bool = False

    def show(self) -> str:
        return self.name


@dataclass(frozen=True)
class Arr(TcTy):
    elem: TcTy
    n: Optional[int]          # None = unknown / length-polymorphic

    def show(self) -> str:
        ln = "" if self.n is None else str(self.n)
        return f"arr[{ln}] {self.elem.show()}"


@dataclass(frozen=True)
class Struct(TcTy):
    name: str

    def show(self) -> str:
        return self.name


UNKNOWN = Unknown()
UNIT = Unit()
STRING = Str()
BOOL = Base("bool")

# numeric kind lattice: bit < int < double < complex
_KIND = {"bit": 0, "bool": 0, "int8": 1, "int16": 1, "int32": 1,
         "int64": 1, "int": 1, "double": 2,
         "complex": 3, "complex16": 3, "complex32": 3}
_INT_RANK = {"bit": 0, "int8": 1, "int16": 2, "int32": 3, "int": 3,
             "int64": 4}


def _kind(t: Base) -> int:
    return _KIND[t.name]


def _is_int(t: Base) -> bool:
    return t.name in _INT_RANK


def _np_base_name(dt) -> str:
    dt = np.dtype(dt)
    if dt == np.uint8:
        return "bit"
    if dt == np.bool_:
        return "bool"
    if dt.kind == "i":
        return f"int{dt.itemsize * 8}"
    if dt.kind == "f":
        return "double"
    if dt.kind == "c":
        return "complex"
    if dt.kind == "u":
        return f"int{dt.itemsize * 8}"   # unsigned: treat as int kind
    raise ValueError(f"no base type for dtype {dt}")


def type_of_value(v: Any) -> TcTy:
    """Derive a checked type from a runtime value (global lets)."""
    if v is None:
        return UNIT
    if isinstance(v, str):
        return STRING
    if isinstance(v, dict):
        return Struct(v.get("__struct__", "?"))
    if isinstance(v, bool):
        return Base("bool", weak=True)
    if isinstance(v, int):
        return Base("int", weak=True)
    if isinstance(v, float):
        return Base("double", weak=True)
    if isinstance(v, complex):
        return Base("complex", weak=True)
    if hasattr(v, "dtype"):
        shape = np.shape(v)
        try:
            base = Base(_np_base_name(v.dtype))
        except ValueError:
            return UNKNOWN
        if not shape:
            return base
        t: TcTy = base
        for n in reversed(shape[1:]):
            t = Arr(t, int(n))
        return Arr(t, int(shape[0]))
    return UNKNOWN


# --------------------------------------------------------------------------
# Assignability / joins
# --------------------------------------------------------------------------


def assignable(dst: TcTy, src: TcTy) -> bool:
    """May a value of type `src` flow into a slot of type `dst` without
    an explicit cast? Unknown is compatible with everything."""
    if isinstance(dst, Unknown) or isinstance(src, Unknown):
        return True
    if isinstance(dst, Str) or isinstance(src, Str):
        return isinstance(dst, Str) and isinstance(src, Str)
    if isinstance(dst, Unit) or isinstance(src, Unit):
        return isinstance(dst, Unit) and isinstance(src, Unit)
    if isinstance(dst, Base) and isinstance(src, Base):
        if src.weak:
            return True               # static scalars adapt (eval policy)
        kd, ks = _kind(dst), _kind(src)
        if dst.name == "bool":
            return ks <= 1            # C-ish: int/bit into bool
        if ks <= 1 and kd <= 1:
            return True               # any int width ↔ any int width/bit
        return ks <= kd               # widening only across kinds
    if isinstance(dst, Arr) and isinstance(src, Arr):
        if dst.n is not None and src.n is not None and dst.n != src.n:
            return False
        return assignable(dst.elem, src.elem)
    if isinstance(dst, Arr) != isinstance(src, Arr):
        return False                  # scalar↔array never implicit
    if isinstance(dst, Struct) and isinstance(src, Struct):
        return dst.name == src.name or src.name == "?"
    return False


def join(a: TcTy, b: TcTy) -> TcTy:
    """Least common type of two branches (if/cond arms)."""
    if isinstance(a, Unknown) or isinstance(b, Unknown):
        return UNKNOWN
    if a == b:
        return a
    if isinstance(a, Base) and isinstance(b, Base):
        if a.weak and not b.weak:
            return b if assignable(b, a) else _wider(a, b)
        if b.weak and not a.weak:
            return a if assignable(a, b) else _wider(a, b)
        return _wider(a, b)
    if isinstance(a, Arr) and isinstance(b, Arr):
        n = a.n if a.n == b.n else (a.n if b.n is None else
                                    (b.n if a.n is None else None))
        if a.n is not None and b.n is not None and a.n != b.n:
            return UNKNOWN            # caller checks lengths explicitly
        return Arr(join(a.elem, b.elem), n)
    return UNKNOWN


def _wider(a: Base, b: Base) -> Base:
    ka, kb = _kind(a), _kind(b)
    if ka != kb:
        return a if ka > kb else b
    if _is_int(a) and _is_int(b):
        return a if _INT_RANK[a.name] >= _INT_RANK[b.name] else b
    return a


# --------------------------------------------------------------------------
# Scope
# --------------------------------------------------------------------------


@dataclass
class VarInfo:
    ty: TcTy
    mutable: bool


class TcScope:
    def __init__(self, parent: Optional["TcScope"] = None):
        self.vars: Dict[str, VarInfo] = {}
        self.parent = parent

    def child(self) -> "TcScope":
        return TcScope(self)

    def declare(self, name: str, ty: TcTy, mutable: bool) -> None:
        self.vars[name] = VarInfo(ty, mutable)

    def find(self, name: str) -> Optional[VarInfo]:
        s: Optional[TcScope] = self
        while s is not None:
            v = s.vars.get(name)
            if v is not None:
                return v
            s = s.parent
        return None


# --------------------------------------------------------------------------
# The checker
# --------------------------------------------------------------------------


_ARITH = ("+", "-", "*", "/", "**")
_CMP_ORD = ("<", "<=", ">", ">=")
_CMP_EQ = ("==", "!=")
_BITS = ("&", "|", "^")
_SHIFT = ("<<", ">>")
_LOGIC = ("&&", "||")


class TypeChecker:
    """Walks a surface `Program` using the elaborator's registries.

    `elab` is a `frontend.elab.Elaborator` that has already run
    `.elaborate()` — structs/funs/exts registered, top-level lets
    evaluated into `gscope`."""

    def __init__(self, elab):
        self.elab = elab
        self.src = elab.src
        self.structs: Dict[str, E.StructDef] = elab.ctx.structs
        self.funs = elab.ctx.funs
        self.ext_sigs = elab.ext_sigs
        self.exts = elab.ctx.exts
        self.comp_funs = elab.comp_funs
        self._fun_sigs: Dict[str, Tuple[List[TcTy], TcTy]] = {}
        self._comp_stack: List[str] = []
        self._checked_funs: set = set()
        # under the fixed-point policy, complex16 components are ints
        self.fxp = getattr(elab.ctx, "fxp_complex16", False)

    # ------------------------------------------------------------- errors

    def err(self, loc, msg: str) -> ZiriaTypeError:
        return ZiriaTypeError(f"{self.src}:{loc[0]}:{loc[1]}: {msg}")

    # -------------------------------------------------------- type resolve

    def _static_int(self, e: Optional[A.Expr]) -> Optional[int]:
        """Fold `e` to a static int against the global scope, or None."""
        if e is None:
            return None
        if isinstance(e, A.EInt):
            return e.val
        from ziria_tpu.frontend.elab import ElabEnv
        ok, v = self.elab.try_st_eval(e, ElabEnv(self.elab.gscope))
        if ok and isinstance(v, (int, np.integer)) \
                and not isinstance(v, bool):
            return int(v)
        return None

    def resolve_ty(self, ty: Optional[A.Ty], loc=(0, 0)) -> TcTy:
        if ty is None:
            return UNKNOWN
        if isinstance(ty, A.TBase):
            if ty.name == "unit":
                return UNIT
            if ty.name not in _KIND:
                raise self.err(loc, f"unknown base type {ty.name!r}")
            return Base(ty.name)
        if isinstance(ty, A.TArr):
            return Arr(self.resolve_ty(ty.elem, loc), self._static_int(ty.n))
        if isinstance(ty, A.TStruct):
            if ty.name not in self.structs:
                raise self.err(loc, f"unknown struct type {ty.name!r}")
            return Struct(ty.name)
        raise self.err(loc, f"unknown type {ty}")

    # -------------------------------------------------------- entry points

    def check_program(self) -> None:
        # expression fun bodies, in declaration order
        for name, fd in self.funs.items():
            self._check_fun(name, fd.decl)
        # ext declarations were already resolved against the registry by
        # elaborate(); nothing further to check until call sites.
        for name, cast in self.elab.top_comp_asts.items():
            self.check_comp(cast, TcScope())

    def _check_fun(self, name: str, d: A.DFun) -> None:
        if name in self._checked_funs:
            return
        self._checked_funs.add(name)
        scope = TcScope()
        ptys = []
        for p in d.params:
            t = self.resolve_ty(p.ty, p.loc)
            ptys.append(t)
            scope.declare(p.name, t, mutable=False)
        ret = self.resolve_ty(d.ret_ty, d.loc)
        self._fun_sigs[name] = (ptys, ret)
        got = self.check_stmts(d.body, scope)
        if d.ret_ty is not None and not isinstance(got, (Unknown, Unit)) \
                and not assignable(ret, got):
            raise self.err(d.loc,
                           f"fun {name}: returns {got.show()} but is "
                           f"declared : {ret.show()}")

    # -------------------------------------------------------- statements

    def check_stmts(self, stmts, scope: TcScope) -> TcTy:
        """Check a statement block; result = type of `return`s (joined),
        Unit when the block cannot return a value."""
        ret: TcTy = UNIT
        for st in stmts:
            r = self.check_stmt(st, scope)
            if r is not None:
                ret = r if isinstance(ret, Unit) else join(ret, r)
        return ret

    def check_stmt(self, st: A.Stmt, scope: TcScope) -> Optional[TcTy]:
        if isinstance(st, A.SVar):
            if st.ty is None:
                raise self.err(st.loc, "var needs a type annotation")
            ty = self.resolve_ty(st.ty, st.loc)
            if st.init is not None:
                it = self.infer(st.init, scope)
                self._require(ty, it, st.loc,
                              f"var {st.name} : {ty.show()}")
            scope.declare(st.name, ty, mutable=True)
            return None
        if isinstance(st, A.SLet):
            it = self.infer(st.e, scope)
            if st.ty is not None:
                ty = self.resolve_ty(st.ty, st.loc)
                self._require(ty, it, st.loc,
                              f"let {st.name} : {ty.show()}")
            else:
                ty = it
            scope.declare(st.name, ty, mutable=False)
            return None
        if isinstance(st, A.SAssign):
            self._check_assign(st.lval, self.infer(st.e, scope), scope,
                               st.loc)
            return None
        if isinstance(st, A.SIf):
            self._require_cond(st.c, scope)
            a = self.check_stmts(st.then, scope.child())
            b = self.check_stmts(st.els, scope.child())
            out = None
            for r in (a, b):
                if not isinstance(r, Unit):
                    out = r if out is None else join(out, r)
            return out
        if isinstance(st, A.SFor):
            self._require_int(st.start, scope, "for start")
            self._require_int(st.count, scope, "for count")
            s = scope.child()
            s.declare(st.var, Base("int", weak=True), mutable=False)
            r = self.check_stmts(st.body, s)
            return None if isinstance(r, Unit) else r
        if isinstance(st, A.SWhile):
            self._require_cond(st.c, scope)
            r = self.check_stmts(st.body, scope.child())
            return None if isinstance(r, Unit) else r
        if isinstance(st, A.SReturn):
            return self.infer(st.e, scope)
        if isinstance(st, A.SExpr):
            self.infer(st.e, scope)
            return None
        raise self.err(st.loc, f"unknown statement {type(st).__name__}")

    def _check_assign(self, lval: A.Expr, vt: TcTy, scope: TcScope,
                      loc) -> None:
        root = lval
        while isinstance(root, (A.EIdx, A.ESlice, A.EField)):
            root = root.arr if hasattr(root, "arr") and root.arr is not None \
                else root.e
        if isinstance(root, A.EVar):
            info = scope.find(root.name)
            if info is not None and not info.mutable:
                raise self.err(
                    loc, f"assignment to immutable binding {root.name!r} "
                         f"(declare it with `var`)")
        if isinstance(lval, A.EVar):
            info = scope.find(lval.name)
            if info is None:
                # stream-level vars are visible to do-blocks through the
                # runtime env; the comp walker pre-declares them, so an
                # unknown name here is either global (immutable) or unbound
                gv = self._global_type(lval.name)
                if gv is not None:
                    raise self.err(loc, f"assignment to immutable "
                                        f"binding {lval.name!r}")
                raise self.err(loc,
                               f"assignment to unbound variable "
                               f"{lval.name!r}")
            self._require(info.ty, vt, loc, f"{lval.name} := ...")
            return
        if isinstance(lval, A.EIdx):
            at = self.infer(lval.arr, scope)
            self._require_int(lval.i, scope, "array index")
            self._static_bounds(lval.i, at, lval.loc)
            if isinstance(at, Arr):
                self._require(at.elem, vt, loc, "element assignment")
            elif isinstance(at, Base):
                raise self.err(lval.loc, "cannot index a scalar")
            return
        if isinstance(lval, A.ESlice):
            at = self.infer(lval.arr, scope)
            self._require_int(lval.i, scope, "slice offset")
            n = self._static_int(lval.n)
            if isinstance(at, Arr):
                self._slice_bounds(lval, at, n)
                if isinstance(vt, Arr):
                    if n is not None and vt.n is not None and vt.n != n:
                        raise self.err(
                            loc, f"slice of length {n} assigned from "
                                 f"array of length {vt.n}")
                    self._require(at.elem, vt.elem, loc, "slice assignment")
                else:
                    self._require(at.elem, vt, loc, "slice assignment")
            elif isinstance(at, Base):
                raise self.err(lval.loc, "cannot slice a scalar")
            return
        if isinstance(lval, A.EField):
            et = self.infer(lval.e, scope)
            ft = self._field_type(et, lval.f, lval.loc)
            self._require(ft, vt, loc, f".{lval.f} assignment")
            return
        raise self.err(getattr(lval, "loc", loc),
                       f"invalid assignment target "
                       f"{type(lval).__name__}")

    # -------------------------------------------------------- expressions

    def infer(self, e: Optional[A.Expr], scope: TcScope) -> TcTy:
        if e is None:
            return UNKNOWN
        if isinstance(e, A.EInt):
            return Base("int", weak=True)
        if isinstance(e, A.EFloat):
            return Base("double", weak=True)
        if isinstance(e, A.EBit):
            return Base("bit", weak=True)
        if isinstance(e, A.EBool):
            return Base("bool", weak=True)
        if isinstance(e, A.EString):
            return STRING
        if isinstance(e, A.EVar):
            info = scope.find(e.name)
            if info is not None:
                return info.ty
            g = self._global_type(e.name)
            if g is not None:
                return g
            raise self.err(e.loc, f"unbound variable {e.name!r}")
        if isinstance(e, A.EUn):
            t = self.infer(e.e, scope)
            return self._check_unary(e, t)
        if isinstance(e, A.EBin):
            return self._check_binop(e, scope)
        if isinstance(e, A.ECond):
            self._require_cond(e.c, scope)
            a = self.infer(e.a, scope)
            b = self.infer(e.b, scope)
            if isinstance(a, Arr) and isinstance(b, Arr) \
                    and a.n is not None and b.n is not None and a.n != b.n:
                raise self.err(e.loc,
                               f"if-expression arms have different "
                               f"lengths ({a.n} vs {b.n})")
            if not (assignable(a, b) or assignable(b, a)):
                raise self.err(e.loc,
                               f"if-expression arms disagree: "
                               f"{a.show()} vs {b.show()}")
            return join(a, b)
        if isinstance(e, A.ECall):
            return self._check_call(e, scope)
        if isinstance(e, A.EIdx):
            at = self.infer(e.arr, scope)
            self._require_int(e.i, scope, "array index")
            if isinstance(at, Arr):
                self._static_bounds(e.i, at, e.loc)
                return at.elem
            if isinstance(at, (Base, Struct)):
                raise self.err(e.loc, f"cannot index a "
                                      f"{'scalar' if isinstance(at, Base) else 'struct'}")
            return UNKNOWN
        if isinstance(e, A.ESlice):
            at = self.infer(e.arr, scope)
            self._require_int(e.i, scope, "slice offset")
            n = self._static_int(e.n)
            if isinstance(at, Arr):
                self._slice_bounds(e, at, n)
                return Arr(at.elem, n)
            if isinstance(at, (Base, Struct)):
                raise self.err(e.loc, "cannot slice a non-array value")
            return Arr(UNKNOWN, n)
        if isinstance(e, A.EField):
            return self._field_type(self.infer(e.e, scope), e.f, e.loc)
        if isinstance(e, A.EArrLit):
            ts = [self.infer(x, scope) for x in e.elems]
            elem: TcTy = UNKNOWN
            for t in ts:
                if isinstance(t, (Arr, Struct, Str, Unit)):
                    elem = t if isinstance(elem, Unknown) else elem
                    continue
                elem = t if isinstance(elem, Unknown) else join(elem, t)
            return Arr(elem, len(e.elems))
        if isinstance(e, A.EStructLit):
            sd = self.structs.get(e.name)
            if sd is None:
                raise self.err(e.loc, f"unknown struct {e.name!r}")
            given = {fn: fe for fn, fe in e.fields}
            for fn, fty in sd.fields:
                if fn not in given:
                    raise self.err(e.loc,
                                   f"struct {e.name} literal missing "
                                   f"field {fn!r}")
                ft = self.resolve_ty(fty, e.loc)
                self._require(ft, self.infer(given.pop(fn), scope),
                              e.loc, f"field {fn} of struct {e.name}")
            if given:
                extra = sorted(given)
                raise self.err(e.loc,
                               f"struct {e.name} has no field "
                               f"{extra[0]!r}")
            return Struct(e.name)
        raise self.err(getattr(e, "loc", (0, 0)),
                       f"unknown expression node {type(e).__name__}")

    # ---------------------------------------------------------- operators

    def _check_unary(self, e: A.EUn, t: TcTy) -> TcTy:
        if isinstance(t, (Unknown,)):
            return UNKNOWN
        base = t.elem if isinstance(t, Arr) else t
        if isinstance(base, Unknown):
            return t
        if not isinstance(base, Base):
            raise self.err(e.loc, f"unary {e.op} on {t.show()}")
        if e.op == "-":
            if base.name == "bool":
                raise self.err(e.loc, "unary - on bool")
        elif e.op == "~":
            if not _is_int(base):
                raise self.err(e.loc, f"bitwise ~ needs an integer "
                                      f"operand, got {base.show()}")
        elif e.op == "!":
            if _kind(base) >= 2:
                raise self.err(e.loc, f"logical ! on {base.show()}")
            return BOOL if not isinstance(t, Arr) else Arr(BOOL, t.n)
        return t

    def _check_binop(self, e: A.EBin, scope: TcScope) -> TcTy:
        op = e.op
        ta = self.infer(e.a, scope)
        tb = self.infer(e.b, scope)
        if isinstance(ta, Unknown) or isinstance(tb, Unknown):
            if op in _CMP_ORD + _CMP_EQ + _LOGIC:
                return BOOL
            return UNKNOWN
        for t in (ta, tb):
            if isinstance(t, (Struct, Str, Unit)):
                raise self.err(e.loc, f"operator {op} on {t.show()}")

        # element/length handling for array operands
        n_out: Optional[int] = None
        arr_out = False
        if isinstance(ta, Arr) or isinstance(tb, Arr):
            arr_out = True
            if isinstance(ta, Arr) and isinstance(tb, Arr):
                if ta.n is not None and tb.n is not None and ta.n != tb.n:
                    raise self.err(
                        e.loc, f"operator {op} on arrays of different "
                               f"lengths ({ta.n} vs {tb.n})")
                n_out = ta.n if ta.n is not None else tb.n
            else:
                n_out = ta.n if isinstance(ta, Arr) else tb.n
        ba = ta.elem if isinstance(ta, Arr) else ta
        bb = tb.elem if isinstance(tb, Arr) else tb
        if isinstance(ba, Unknown) or isinstance(bb, Unknown):
            return Arr(UNKNOWN, n_out) if arr_out else UNKNOWN
        assert isinstance(ba, Base) and isinstance(bb, Base)

        def out(base: Base) -> TcTy:
            if arr_out:
                return Arr(Base(base.name), n_out)
            return base

        if op in _LOGIC:
            for b in (ba, bb):
                if _kind(b) >= 2:
                    raise self.err(e.loc, f"{op} on {b.show()}")
            return out(BOOL)
        if op in _CMP_EQ:
            if (_kind(ba) == 3) != (_kind(bb) == 3) and \
                    not (ba.weak or bb.weak):
                raise self.err(e.loc,
                               f"comparison {op} between {ba.show()} "
                               f"and {bb.show()}")
            return out(BOOL)
        if op in _CMP_ORD:
            for b in (ba, bb):
                if _kind(b) == 3:
                    raise self.err(e.loc, f"ordering {op} on complex "
                                          f"values")
            return out(BOOL)
        if op in _SHIFT:
            if not _is_int(ba) or not _is_int(bb):
                bad = ba if not _is_int(ba) else bb
                raise self.err(e.loc, f"shift {op} needs integer "
                                      f"operands, got {bad.show()}")
            return out(_result_base(ba, bb))
        if op in _BITS:
            if ba.name == "bool" and bb.name == "bool":
                return out(BOOL)
            for b in (ba, bb):
                if not _is_int(b) and b.name != "bool":
                    raise self.err(e.loc, f"bitwise {op} on {b.show()}")
            return out(_result_base(ba, bb))
        if op == "%":
            for b in (ba, bb):
                if _kind(b) == 3:
                    raise self.err(e.loc, "% on complex values")
            return out(_result_base(ba, bb))
        if op in _ARITH:
            for b in (ba, bb):
                if b.name == "bool":
                    raise self.err(e.loc, f"arithmetic {op} on bool")
            return out(_result_base(ba, bb))
        raise self.err(e.loc, f"unknown operator {op!r}")

    # -------------------------------------------------------------- calls

    def _check_call(self, e: A.ECall, scope: TcScope) -> TcTy:
        name = e.name
        args = list(e.args)
        # casts / complex constructors
        if name in E._BASE_TYPE_NAMES:
            if name in E._CPLX and len(args) == 2:
                for a in args:
                    t = self.infer(a, scope)
                    self._numeric_only(t, a, f"{name}() component")
                return Base(name)
            if len(args) != 1:
                raise self.err(e.loc, f"cast {name} takes one argument")
            t = self.infer(args[0], scope)
            self._numeric_only(t, args[0], f"cast to {name}")
            if isinstance(t, Arr):
                return Arr(Base(name), t.n)
            return Base(name)
        # print family: any printable args
        if name in ("print", "println", "error"):
            for a in args:
                self.infer(a, scope)
            return UNIT
        # user fun
        fd = self.funs.get(name)
        if fd is not None:
            self._check_fun(name, fd.decl)
            ptys, ret = self._fun_sigs[name]
            self._check_args(name, fd.decl.params, ptys, args, scope,
                             e.loc)
            return ret
        # declared ext
        sig = self.ext_sigs.get(name)
        if sig is not None:
            ptys = [self.resolve_ty(p.ty, p.loc) for p in sig.params]
            self._check_args(name, sig.params, ptys, args, scope, e.loc)
            return self.resolve_ty(sig.ret_ty, sig.loc)
        # builtins (length/abs/min/max/sum) — typed structurally
        if name in self.exts:
            return self._check_builtin(name, args, scope, e.loc)
        raise self.err(e.loc, f"unknown function {name!r}")

    def _check_args(self, name, params, ptys, args, scope, loc) -> None:
        if len(args) != len(params):
            raise self.err(loc, f"{name}: expected {len(params)} "
                                f"argument(s), got {len(args)}")
        for p, pt, a in zip(params, ptys, args):
            at = self.infer(a, scope)
            if not assignable(pt, at):
                raise self.err(
                    a.loc if a.loc != (0, 0) else loc,
                    f"{name}: argument {p.name!r} expects {pt.show()}, "
                    f"got {at.show()}")

    def _check_builtin(self, name, args, scope, loc) -> TcTy:
        ts = [self.infer(a, scope) for a in args]
        if name == "length":
            if len(ts) != 1:
                raise self.err(loc, "length takes one argument")
            if isinstance(ts[0], Base):
                raise self.err(loc, "length() of a scalar")
            return Base("int", weak=True)
        if name == "sum":
            if len(ts) == 1 and isinstance(ts[0], Arr):
                return ts[0].elem
            return UNKNOWN
        if name == "abs":
            if len(ts) == 1:
                t = ts[0]
                b = t.elem if isinstance(t, Arr) else t
                if isinstance(b, Base) and _kind(b) == 3:
                    b = Base("double")
                    return Arr(b, t.n) if isinstance(t, Arr) else b
                return t
            return UNKNOWN
        if name in ("min", "max") and len(ts) == 2:
            return join(ts[0], ts[1])
        for t, a in zip(ts, args):
            self._numeric_only(t, a, name, allow_arr=True)
        return UNKNOWN

    # ------------------------------------------------------------ helpers

    def _global_type(self, name: str) -> Optional[TcTy]:
        s = self.elab.gscope
        while s is not None:
            c = s.cells.get(name)
            if c is not None:
                return type_of_value(c.value)
            s = s.parent
        return None

    def _field_type(self, t: TcTy, f: str, loc) -> TcTy:
        if isinstance(t, Unknown):
            return UNKNOWN
        if isinstance(t, Struct):
            sd = self.structs.get(t.name)
            if sd is None:
                return UNKNOWN
            for fn, fty in sd.fields:
                if fn == f:
                    return self.resolve_ty(fty, loc)
            raise self.err(loc, f"struct {t.name} has no field {f!r}")
        if f in ("re", "im"):
            b = t.elem if isinstance(t, Arr) else t
            if isinstance(b, Base) and _kind(b) != 3 and not b.weak:
                raise self.err(loc, f".{f} on non-complex {t.show()}")
            d = Base("double")
            if self.fxp and isinstance(b, Base) and b.name == "complex16":
                d = Base("int32")      # fixed-point components are ints
            return Arr(d, t.n) if isinstance(t, Arr) else d
        raise self.err(loc, f"no field {f!r} on a non-struct value")

    def _numeric_only(self, t: TcTy, e: A.Expr, what: str,
                      allow_arr: bool = True) -> None:
        if isinstance(t, (Struct, Str, Unit)):
            raise self.err(e.loc, f"{what} applied to {t.show()}")
        if isinstance(t, Arr) and not allow_arr:
            raise self.err(e.loc, f"{what} applied to an array")

    def _require(self, dst: TcTy, src: TcTy, loc, what: str) -> None:
        if not assignable(dst, src):
            if isinstance(dst, Arr) and isinstance(src, Arr) \
                    and dst.n is not None and src.n is not None \
                    and dst.n != src.n:
                raise self.err(loc,
                               f"{what}: array length mismatch "
                               f"(expected {dst.n}, got {src.n})")
            raise self.err(loc, f"{what}: cannot use a {src.show()} "
                                f"value here without an explicit cast "
                                f"(expected {dst.show()})")

    def _require_int(self, e: Optional[A.Expr], scope: TcScope,
                     what: str) -> None:
        if e is None:
            return
        t = self.infer(e, scope)
        b = t.elem if isinstance(t, Arr) else t
        if isinstance(b, Base) and not _is_int(b) and not b.weak \
                and b.name != "bool":
            raise self.err(e.loc, f"{what} must be an integer, "
                                  f"got {b.show()}")
        if isinstance(t, (Struct, Str, Unit)):
            raise self.err(e.loc, f"{what} must be an integer, "
                                  f"got {t.show()}")

    def _require_cond(self, e: Optional[A.Expr], scope: TcScope) -> None:
        if e is None:
            return
        t = self.infer(e, scope)
        b = t.elem if isinstance(t, Arr) else t
        if isinstance(b, Base) and _kind(b) == 3:
            raise self.err(e.loc, "condition cannot be complex-valued")
        if isinstance(t, (Struct, Str)):
            raise self.err(e.loc, f"condition cannot be a {t.show()}")

    def _static_bounds(self, i: Optional[A.Expr], at: TcTy, loc) -> None:
        if not isinstance(at, Arr) or at.n is None:
            return
        iv = self._static_int(i)
        if iv is not None and (iv < 0 or iv >= at.n):
            raise self.err(loc, f"index {iv} out of bounds for array "
                                f"of length {at.n}")

    def _slice_bounds(self, e, at: Arr, n: Optional[int]) -> None:
        if at.n is None or n is None:
            return
        iv = self._static_int(e.i)
        if n > at.n or (iv is not None and (iv < 0 or iv + n > at.n)):
            i_s = "?" if iv is None else str(iv)
            raise self.err(e.loc,
                           f"slice [{i_s}, {n}] out of bounds for array "
                           f"of length {at.n}")

    # ------------------------------------------------------- computations

    def comp_ty(self, c: A.Comp, scope: TcScope) -> TcTy:
        """Check a computation and return the type of its *control value*
        (what `x <- c` binds)."""
        if isinstance(c, A.CTake):
            return UNKNOWN          # stream item type: core/types.py's job
        if isinstance(c, A.CTakes):
            self._require_int(c.n, scope, "takes count")
            return Arr(UNKNOWN, self._static_int(c.n))
        if isinstance(c, A.CEmit):
            self.infer(c.e, scope)
            return UNIT
        if isinstance(c, A.CEmits):
            t = self.infer(c.e, scope)
            if isinstance(t, (Base, Struct, Str)):
                raise self.err(c.loc, f"emits needs an array value, "
                                      f"got {t.show()}")
            return UNIT
        if isinstance(c, A.CReturn):
            return self.infer(c.e, scope)
        if isinstance(c, A.CDo):
            return self.check_stmts(c.body, scope.child())
        if isinstance(c, A.CBind):
            ft = self.comp_ty(c.first, scope)
            if c.var is None:
                return self.comp_ty(c.rest, scope)
            s = scope.child()
            if c.var_ty is not None:
                vt = self.resolve_ty(c.var_ty, c.loc)
                self._require(vt, ft, c.loc, f"{c.var} : {vt.show()} <- ...")
            else:
                vt = ft
            s.declare(c.var, vt, mutable=False)
            return self.comp_ty(c.rest, s)
        if isinstance(c, A.CVarDecl):
            if c.ty is None:
                raise self.err(c.loc, "var needs a type annotation")
            ty = self.resolve_ty(c.ty, c.loc)
            if c.init is not None:
                self._require(ty, self.infer(c.init, scope), c.loc,
                              f"var {c.name} : {ty.show()}")
            s = scope.child()
            s.declare(c.name, ty, mutable=True)
            return self.comp_ty(c.rest, s)
        if isinstance(c, A.CLetDecl):
            t = self.infer(c.e, scope)
            s = scope.child()
            s.declare(c.name, t, mutable=False)
            return self.comp_ty(c.rest, s)
        if isinstance(c, A.CLetComp):
            # the bound comp is checked when referenced (it may rely on
            # binds in scope at the use site exactly as written here)
            self.comp_ty(c.c, scope)
            return self.comp_ty(c.rest, scope)
        if isinstance(c, A.CRepeat):
            self.comp_ty(c.body, scope)
            return UNIT
        if isinstance(c, A.CMap):
            self._check_map(c)
            return UNIT
        if isinstance(c, A.CPipe):
            self.comp_ty(c.up, scope)
            return self.comp_ty(c.down, scope)
        if isinstance(c, A.CIf):
            self._require_cond(c.c, scope)
            a = self.comp_ty(c.then, scope)
            b = self.comp_ty(c.els, scope) if c.els is not None else UNIT
            return join(a, b) if not isinstance(a, Unit) else UNIT
        if isinstance(c, A.CFor):
            self._require_int(c.start, scope, "for start")
            self._require_int(c.count, scope, "for count")
            s = scope.child()
            s.declare(c.var, Base("int", weak=True), mutable=False)
            self.comp_ty(c.body, s)
            return UNIT
        if isinstance(c, A.CTimes):
            self._require_int(c.count, scope, "times count")
            self.comp_ty(c.body, scope)
            return UNIT
        if isinstance(c, A.CWhile):
            self._require_cond(c.c, scope)
            self.comp_ty(c.body, scope)
            return UNIT
        if isinstance(c, A.CUntil):
            self.comp_ty(c.body, scope)
            self._require_cond(c.c, scope)
            return UNIT
        if isinstance(c, A.CCall):
            return self._check_comp_call(c, scope)
        if isinstance(c, (A.CRead, A.CWrite)):
            return UNIT
        raise self.err(getattr(c, "loc", (0, 0)),
                       f"unknown computation {type(c).__name__}")

    def _check_map(self, c: A.CMap) -> None:
        name = c.fname
        fd = self.funs.get(name)
        if fd is not None:
            if len(fd.decl.params) != 1:
                raise self.err(c.loc, f"map {name}: needs a one-argument "
                                      f"function")
            self._check_fun(name, fd.decl)
            return
        if name in self.ext_sigs or name in self.exts:
            return
        raise self.err(c.loc, f"map: unknown function {name!r}")

    def _check_comp_call(self, c: A.CCall, scope: TcScope) -> TcTy:
        name = c.name
        d = self.comp_funs.get(name)
        if d is None:
            # comp bindings (let comp x = ...) were checked in place
            if c.args:
                for a in c.args:
                    self.infer(a, scope)
            return UNKNOWN
        if len(c.args) != len(d.params):
            raise self.err(c.loc, f"{name}: expected {len(d.params)} "
                                  f"argument(s), got {len(c.args)}")
        if name in self._comp_stack:
            return UNKNOWN         # elab rejects recursion with its own msg
        s = TcScope()              # comp funs see globals + params only
        for p, a in zip(d.params, c.args):
            at = self.infer(a, scope)
            if p.ty is not None:
                pt = self.resolve_ty(p.ty, p.loc)
                if not assignable(pt, at):
                    raise self.err(
                        a.loc if a.loc != (0, 0) else c.loc,
                        f"{name}: argument {p.name!r} expects "
                        f"{pt.show()}, got {at.show()}")
                # keep the caller's length when the sig is length-open
                if isinstance(pt, Arr) and pt.n is None \
                        and isinstance(at, Arr):
                    pt = Arr(pt.elem, at.n)
                s.declare(p.name, pt, mutable=False)
            else:
                s.declare(p.name, at, mutable=False)
        self._comp_stack.append(name)
        try:
            return self.comp_ty(d.body, s)
        finally:
            self._comp_stack.pop()

    def check_comp(self, c: A.Comp, scope: TcScope) -> None:
        self.comp_ty(c, scope)


def _result_base(a: Base, b: Base) -> Base:
    ka, kb = _kind(a), _kind(b)
    if a.weak and not b.weak:
        return Base(b.name) if kb >= ka else Base(a.name)
    if b.weak and not a.weak:
        return Base(a.name) if ka >= kb else Base(b.name)
    w = _wider(a, b)
    return Base(w.name)


def check_program(elab) -> None:
    """Entry point: statically check an elaborated program's surface AST.

    Raises ZiriaTypeError (a subclass-independent located error) on the
    first definite type error. Called by Elaborator.build()."""
    TypeChecker(elab).check_program()
