"""GF(2) affine loop compression ("autolin") — LFSRs without the loop.

The lane vectorizer (`eval._vectorized_for`) refuses true recurrences:
a loop whose iteration reads state the previous iteration wrote has no
per-lane form. But the recurrences that actually appear in PHY code —
scramblers, descramblers, CRC/FCS registers, PN generators — are all
*affine over GF(2)*: every carried bit of iteration p+1 is an XOR of
carried bits of iteration p, input-stream bits, and a constant. An
affine step composes: K iterations collapse into one matrix-vector
product over GF(2),

    s'   = M_K s  xor  B_K x  xor  c_K
    y[i] = O_i s  xor  P_i x  xor  q_i        (per-iteration outputs)

with every matrix computable at trace time. This pass

  1. symbolically executes ONE loop iteration over an affine-GF(2)
     bit domain (bits are XOR-sets of symbols; anything nonlinear
     bails),
  2. composes K=64 iterations into numpy bit matrices,
  3. stages the loop as `lax.fori_loop` over ceil(n/K) blocks of tiny
     mod-2 matmuls plus a staged remainder tail — bit-exact by
     construction, with a traced trip count fully supported.

Loop-variable comparisons (`if (p >= 16) ...`) are handled by *range
splitting*: breakpoints are discovered during symbolic execution and
the iteration domain is split until every subrange is branch-constant;
subranges that fail the analysis run through the ordinary staged path,
so engagement is never a correctness question.

Reference anchor: SURVEY.md §2.1 AutoLUT (compile-time analysis that
replaces a computation family wholesale); the reference kept LFSRs
fast by emitting them as C scalar loops — on a TPU the idiomatic
answer is linear algebra over GF(2), not a faster scalar loop.

Kill switch: ZIRIA_NO_GF2_LOOPS=1 (A/B exactness testing).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from . import ast as A

__all__ = ["gf2_for"]

K_BLOCK = 64          # iterations folded into one block step
MAX_STATE_BITS = 512  # composition cost cap (numpy, trace-time)
MAX_UNROLL = 512      # inner static-loop unroll cap (symbolic exec)
_MAX_SPLITS = 24      # range-splitting refinement rounds


class _Bail(Exception):
    """Body is not (provably) GF(2)-affine; caller falls back."""


# --------------------------------------------------------------------------
# Symbolic values
#
# SBit  ("b", mask, c): XOR of the symbols set in `mask` plus const c.
# SVec  ("v", (SBit, ...)): a bit array.
# SInt  ("i", a, b): the integer a*p + b (a == 0 => loop-invariant).
# Concrete numpy arrays / Python scalars pass through raw.
# --------------------------------------------------------------------------


def _bit(c: int):
    return ("b", 0, int(c) & 1)


def _is_sbit(v) -> bool:
    return isinstance(v, tuple) and len(v) == 3 and v[0] == "b"


def _is_svec(v) -> bool:
    return isinstance(v, tuple) and len(v) == 2 and v[0] == "v"


def _is_sint(v) -> bool:
    return isinstance(v, tuple) and len(v) == 3 and v[0] == "i"


def _xor(a, b):
    return ("b", a[1] ^ b[1], a[2] ^ b[2])


def _as_sbit(v):
    """Concrete 0/1 (int/np scalar) or SBit -> SBit. A non-0/1 value
    is NOT a bit — masking it mod 2 would silently change program
    results, so refuse (code review r4)."""
    if _is_sbit(v):
        return v
    if _is_sint(v):
        if v[1] != 0:
            raise _Bail("p-dependent value used as a bit")
        v = v[2]
    if isinstance(v, np.ndarray) and v.ndim == 0:
        v = v.item()
    if isinstance(v, (bool, int, np.integer)):
        if int(v) not in (0, 1):
            raise _Bail(f"non-bit value {int(v)} used as a bit")
        return _bit(int(v))
    raise _Bail(f"not a bit: {type(v).__name__}")


def _as_int(v) -> "Tuple[int, int]":
    """Value -> (a, b) meaning a*p + b with static ints."""
    if _is_sint(v):
        return v[1], v[2]
    if isinstance(v, (bool, int, np.integer)):
        return 0, int(v)
    if isinstance(v, np.ndarray) and v.ndim == 0 \
            and np.issubdtype(v.dtype, np.integer):
        return 0, int(v)
    raise _Bail("not a static/affine int")


def _const_of(v) -> int:
    a, b = _as_int(v)
    if a != 0:
        raise _Bail("p-dependent where loop-invariant int required")
    return b


# --------------------------------------------------------------------------
# One-iteration symbolic execution
# --------------------------------------------------------------------------

_CMP_OPS = frozenset(("<", "<=", ">", ">=", "==", "!="))


class _Sym:
    """Symbolically executes the loop body once at a representative
    iteration index, classifying outer names into state cells, input
    sites (p-affine stream reads, stride 1) and output sites
    (p-affine stream writes, stride 1, unconditional, never read).

    Produces the per-iteration affine map; collects the breakpoints of
    any loop-variable comparison it resolved so the planner can split
    the domain and re-run until branch decisions are range-constant.
    """

    def __init__(self, st: A.SFor, scope, ctx, p_rep: int):
        self.st = st
        self.var = st.var
        self.scope = scope
        self.ctx = ctx
        self.p_rep = p_rep
        self.breakpoints: Set[int] = set()
        self.state: Dict[str, Tuple[int, int, bool]] = {}  # name -> (base, nbits, scalar?)
        self.n_state = 0
        self.in_sites: Dict[Tuple[str, int], int] = {}     # (name, b) -> sym
        self.in_order: List[Tuple[str, int]] = []
        self.out_names: Set[str] = set()
        self.out_writes: Dict[str, Dict[int, tuple]] = {}  # name -> {b: SBit}
        self.n_ops = 0

    # -- classification ----------------------------------------------------

    def _classify(self) -> None:
        """Pre-classify written outer names: output arrays (every
        access is a p-indexed element write, zero reads) vs state
        cells (bit scalars / bit arrays of static shape)."""
        reads: Set[str] = set()
        writes: Dict[str, List[A.Expr]] = {}

        def note_expr(e):
            from .eval import _expr_reads
            _expr_reads(e, reads)

        def walk(stmts):
            for s in A.iter_stmts(stmts):
                if isinstance(s, A.SAssign):
                    lv = s.lval
                    if isinstance(lv, A.EIdx) and isinstance(lv.arr, A.EVar):
                        writes.setdefault(lv.arr.name, []).append(lv)
                        note_expr(lv.i)
                    elif isinstance(lv, A.ESlice) \
                            and isinstance(lv.arr, A.EVar):
                        writes.setdefault(lv.arr.name, []).append(lv)
                        note_expr(lv.i)
                        note_expr(lv.n)
                    elif isinstance(lv, A.EVar):
                        writes.setdefault(lv.name, []).append(lv)
                    else:
                        raise _Bail("unsupported lval")
                    note_expr(s.e)
                elif isinstance(s, (A.SVar,)):
                    if s.init is not None:
                        note_expr(s.init)
                elif isinstance(s, A.SLet):
                    note_expr(s.e)
                elif isinstance(s, A.SIf):
                    note_expr(s.c)
                elif isinstance(s, A.SFor):
                    note_expr(s.start)
                    note_expr(s.count)
                elif isinstance(s, A.SWhile):
                    raise _Bail("while in body")
                elif isinstance(s, (A.SExpr, A.SReturn)):
                    raise _Bail("effect/return in body")

        walk(self.st.body)

        locals_: Set[str] = set()
        for s in A.iter_stmts(self.st.body):
            if isinstance(s, (A.SVar, A.SLet)):
                locals_.add(s.name)

        for name, lvs in writes.items():
            if name in locals_:
                continue
            cell = self.scope.find(name)
            if cell is None or not cell.mutable:
                raise _Bail(f"write to non-mutable outer {name!r}")
            all_p_elem = all(
                isinstance(lv, A.EIdx)
                and self.var in _free(lv.i) for lv in lvs)
            v = cell.value
            dt = getattr(v, "dtype", None)
            if all_p_elem and name not in reads:
                # output stream: must be a 1-D bit array — any other
                # dtype has no GF(2) representation (code review r4:
                # an int32 output would be silently truncated mod 2)
                if np.ndim(v) != 1 or dt is None \
                        or np.dtype(dt) != np.uint8:
                    raise _Bail(f"output {name!r} is not a bit array")
                self.out_names.add(name)
            else:
                nd = np.ndim(v)
                if nd == 0:
                    # scalar state must itself be a bit: uint8 cells
                    # (the runtime's `bit` representation) or a python
                    # 0/1 — an int32 counter is NOT 1-bit state
                    if dt is not None:
                        if np.dtype(dt) != np.uint8:
                            raise _Bail(
                                f"state {name!r} is not a bit cell")
                    elif not (isinstance(v, (bool, int, np.integer))
                              and int(v) in (0, 1)):
                        raise _Bail(f"state {name!r} is not a bit cell")
                    nbits, scalar = 1, True
                elif nd == 1 and dt is not None \
                        and np.dtype(dt) == np.uint8:
                    nbits, scalar = int(v.shape[0]), False
                else:
                    raise _Bail(f"state {name!r} is not a bit cell")
                if self.n_state + nbits > MAX_STATE_BITS:
                    raise _Bail("state too wide")
                self.state[name] = (self.n_state, nbits, scalar)
                self.n_state += nbits

    # -- expression evaluation --------------------------------------------

    def _tick(self):
        self.n_ops += 1
        if self.n_ops > 200_000:
            raise _Bail("symbolic execution too large")

    def _in_sym(self, name: str, b: int) -> tuple:
        key = (name, b)
        sym = self.in_sites.get(key)
        if sym is None:
            cell = self.scope.find(name)
            if cell is None:
                raise _Bail(f"unknown input {name!r}")
            v = cell.value
            if np.ndim(v) != 1:
                raise _Bail(f"input {name!r} is not 1-D")
            dt = getattr(v, "dtype", None)
            if dt is None or np.dtype(dt) != np.uint8:
                raise _Bail(f"input {name!r} is not a bit stream")
            sym = MAX_STATE_BITS + len(self.in_order)
            self.in_sites[key] = sym
            self.in_order.append(key)
        return ("b", 1 << sym, 0)

    def sev(self, e: A.Expr, env: Dict[str, Any]):
        self._tick()
        if isinstance(e, A.EInt):
            return ("i", 0, int(e.val))
        if isinstance(e, A.EBit):
            return _bit(e.val)
        if isinstance(e, A.EBool):
            return ("i", 0, int(e.val))
        if isinstance(e, A.EFloat):
            raise _Bail("float in body")
        if isinstance(e, A.EVar):
            if e.name == self.var:
                return ("i", 1, 0)
            if e.name in env:
                return env[e.name]
            if e.name in self.out_names:
                raise _Bail(f"read of output array {e.name!r}")
            cell = self.scope.find(e.name)
            if cell is None:
                raise _Bail(f"unbound {e.name!r}")
            v = cell.value
            if isinstance(v, (bool, int, np.integer)):
                return ("i", 0, int(v))
            if isinstance(v, np.ndarray) and v.ndim == 0 \
                    and np.issubdtype(v.dtype, np.integer):
                return ("i", 0, int(v))
            if isinstance(v, np.ndarray):
                return v          # concrete constant array
            raise _Bail(f"opaque read of {e.name!r}")
        if isinstance(e, A.EIdx):
            if isinstance(e.arr, A.EVar) and e.arr.name not in env \
                    and e.arr.name != self.var:
                name = e.arr.name
                if name in self.state or name in self.out_names:
                    pass        # fall through to env/state handling
                else:
                    a, b = _as_int(self.sev(e.i, env))
                    if a == 0:
                        arr = self.sev(e.arr, env)
                        return self._index(arr, b)
                    if a != 1:
                        raise _Bail("input stride != 1")
                    return self._in_sym(name, b)
            arr = self.sev(e.arr, env)
            a, b = _as_int(self.sev(e.i, env))
            if a != 0:
                raise _Bail("p-indexed read of local/state array")
            return self._index(arr, b)
        if isinstance(e, A.ESlice):
            arr = self.sev(e.arr, env)
            i = _const_of(self.sev(e.i, env))
            n = _const_of(self.sev(e.n, env))
            if _is_svec(arr):
                if not (0 <= i and i + n <= len(arr[1])):
                    raise _Bail("slice out of range")
                return ("v", arr[1][i:i + n])
            if isinstance(arr, np.ndarray):
                return arr[i:i + n]
            raise _Bail("slice of non-array")
        if isinstance(e, A.EUn):
            v = self.sev(e.e, env)
            if e.op in ("!", "~"):
                b = _as_sbit(v)
                return ("b", b[1], b[2] ^ 1)
            if e.op == "-":
                a, c = _as_int(v)
                return ("i", -a, -c)
            raise _Bail(f"unary {e.op}")
        if isinstance(e, A.EBin):
            return self._binop(e, env)
        if isinstance(e, A.ECond):
            c = self.sev(e.c, env)
            cb = self._cond_value(c)
            if isinstance(cb, bool):
                return self.sev(e.a if cb else e.b, env)
            t = self.sev(e.a, env)
            f = self.sev(e.b, env)
            return self._merge_val(cb, t, f)
        if isinstance(e, A.ECall):
            raise _Bail(f"call {e.name!r} in body")
        raise _Bail(f"expr {type(e).__name__}")

    def _index(self, arr, i: int):
        if _is_svec(arr):
            if not (0 <= i < len(arr[1])):
                raise _Bail("index out of range")
            return arr[1][i]
        if isinstance(arr, np.ndarray):
            if not (0 <= i < arr.shape[0]):
                raise _Bail("index out of range")
            el = arr[i]
            if np.dtype(arr.dtype) == np.uint8:
                return _bit(int(el))
            if np.issubdtype(arr.dtype, np.integer):
                return ("i", 0, int(el))
            raise _Bail("non-integer constant array")
        raise _Bail("index of non-array")

    def _binop(self, e: A.EBin, env):
        op = e.op
        a = self.sev(e.a, env)
        b = self.sev(e.b, env)
        if op == "^":
            return _xor(_as_sbit(a), _as_sbit(b))
        if op in ("&", "&&", "|", "||"):
            # linear only when one side is constant
            sa, sb = _as_sbit(a), _as_sbit(b)
            for x, y in ((sa, sb), (sb, sa)):
                if x[1] == 0:
                    if op in ("&", "&&"):
                        return y if x[2] else _bit(0)
                    return _bit(1) if x[2] else y
            raise _Bail("nonlinear bit product")
        if op in _CMP_OPS:
            return self._compare(op, a, b)
        # integer arithmetic on affine forms
        (aa, ab), (ba, bb) = _as_int(a), _as_int(b)
        if op == "+":
            return ("i", aa + ba, ab + bb)
        if op == "-":
            return ("i", aa - ba, ab - bb)
        if op == "*":
            if aa == 0:
                return ("i", ab * ba, ab * bb)
            if ba == 0:
                return ("i", aa * bb, ab * bb)
            raise _Bail("quadratic in loop var")
        if aa != 0 or ba != 0:
            raise _Bail(f"op {op} on p-affine value")
        x, y = ab, bb
        if op == "/":
            if y == 0:
                raise _Bail("static division by zero")
            q = abs(x) // abs(y)
            return ("i", 0, q if (x >= 0) == (y >= 0) else -q)
        if op == "%":
            if y == 0:
                raise _Bail("static modulo by zero")
            q = abs(x) // abs(y)
            q = q if (x >= 0) == (y >= 0) else -q
            return ("i", 0, x - q * y)
        if op == "<<":
            return ("i", 0, x << y)
        if op == ">>":
            return ("i", 0, x >> y)
        if op == "**":
            return ("i", 0, x ** y)
        raise _Bail(f"op {op}")

    def _compare(self, op, a, b):
        if (_is_sbit(a) or _is_sbit(b)) and op in ("==", "!="):
            sa, sb = _as_sbit(a), _as_sbit(b)
            eq = ("b", sa[1] ^ sb[1], sa[2] ^ sb[2] ^ 1)
            return eq if op == "==" else ("b", eq[1], eq[2] ^ 1)
        (aa, ab), (ba, bb) = _as_int(a), _as_int(b)
        da, db = aa - ba, bb - ab          # compare da*p  vs  db
        if da == 0:
            v = {"<": db > 0, "<=": db >= 0, ">": db < 0,
                 ">=": db <= 0, "==": db == 0, "!=": db != 0}[op]
            return ("i", 0, int(v))
        # loop-variable comparison: record the crossing so the planner
        # splits the domain there, then resolve at the representative
        q = db // da                       # floor crossing of da*p == db
        for bp in (q, q + 1):
            self.breakpoints.add(int(bp))
        p = self.p_rep
        lhs, rhs = da * p, db
        v = {"<": lhs < rhs, "<=": lhs <= rhs, ">": lhs > rhs,
             ">=": lhs >= rhs, "==": lhs == rhs, "!=": lhs != rhs}[op]
        return ("i", 0, int(v))

    # -- statements --------------------------------------------------------

    def _cond_value(self, c):
        """Condition -> python bool (decided) or SBit (symbolic)."""
        if _is_sbit(c):
            if c[1] == 0:
                return bool(c[2])
            return c
        return bool(_const_of(c))

    def _merge_val(self, cond, t, f):
        """Per-bit select(cond, t, f); affine only when t xor f is a
        constant per bit: sel = f xor cond*(t xor f)."""
        if _is_svec(t) or _is_svec(f):
            if not (_is_svec(t) and _is_svec(f)
                    and len(t[1]) == len(f[1])):
                raise _Bail("branch shape mismatch")
            return ("v", tuple(self._merge_val(cond, x, y)
                               for x, y in zip(t[1], f[1])))
        if _is_sbit(t) or _is_sbit(f):
            tb, fb = _as_sbit(t), _as_sbit(f)
            d = _xor(tb, fb)
            if d[1] != 0:
                raise _Bail("branch difference not constant")
            return _xor(fb, cond) if d[2] else fb
        ta, fa = _as_int(t), _as_int(f)
        if ta != fa:
            raise _Bail("int differs across symbolic branches")
        return ("i",) + ta

    def _exec(self, stmts, env: Dict[str, Any]) -> None:
        for s in stmts:
            self._tick()
            if isinstance(s, (A.SVar, A.SLet)):
                if s.name in env:
                    # shadowing a tracked name: the inner-loop env
                    # copy-back could leak it — refuse conservatively
                    raise _Bail(f"shadowing declaration {s.name!r}")
                init = s.init if isinstance(s, A.SVar) else s.e
                if init is None:
                    env[s.name] = self._zero(s.ty)
                else:
                    env[s.name] = self.sev(init, env)
            elif isinstance(s, A.SAssign):
                self._assign(s, env)
            elif isinstance(s, A.SIf):
                c = self._cond_value(self.sev(s.c, env))
                if isinstance(c, bool):
                    self._exec(s.then if c else s.els, env)
                    continue
                saved_out = {k: dict(v)
                             for k, v in self.out_writes.items()}
                t_env = dict(env)
                self._exec(s.then, t_env)
                t_out = self.out_writes
                self.out_writes = saved_out
                f_env = dict(env)
                self._exec(s.els, f_env)
                f_out = self.out_writes
                # a stream write under a symbolic condition cannot be
                # merged without the old array value (never modeled)
                if t_out != f_out:
                    raise _Bail("conditional stream write")
                self.out_writes = t_out
                # merge environments per-bit: sel = f ^ cond&(t^f)
                for k in set(t_env) | set(f_env):
                    tv, fv = t_env.get(k), f_env.get(k)
                    if tv is None or fv is None:
                        env.pop(k, None)   # branch-local declaration
                        continue
                    if tv is fv:
                        env[k] = tv
                    elif isinstance(tv, np.ndarray) \
                            or isinstance(fv, np.ndarray):
                        if isinstance(tv, np.ndarray) \
                                and isinstance(fv, np.ndarray) \
                                and np.array_equal(tv, fv):
                            env[k] = tv
                        else:
                            raise _Bail("array differs across branches")
                    elif tv == fv:
                        env[k] = tv
                    else:
                        env[k] = self._merge_val(c, tv, fv)
            elif isinstance(s, A.SFor):
                st_i = _const_of(self.sev(s.start, env))
                cnt = _const_of(self.sev(s.count, env))
                if cnt < 0 or cnt > MAX_UNROLL:
                    raise _Bail("inner loop too long to unroll")
                for i in range(st_i, st_i + cnt):
                    inner = dict(env)
                    inner[s.var] = ("i", 0, i)
                    self._exec(s.body, inner)
                    for k, v in inner.items():
                        if k != s.var and k in env:
                            env[k] = v
            else:
                raise _Bail(f"stmt {type(s).__name__}")

    def _zero(self, ty):
        if isinstance(ty, A.TArr):
            try:
                n = self.ctx.static_eval(ty.n, self.scope)
            except Exception:
                raise _Bail("dynamic local array length")
            base = getattr(ty.elem, "name", None)
            if base == "bit":
                return ("v", tuple(_bit(0) for _ in range(int(n))))
            raise _Bail("non-bit local array")
        base = getattr(ty, "name", None)
        if base == "bit":
            return _bit(0)
        if base in ("int", "int8", "int16", "int32", "int64", "bool"):
            return ("i", 0, 0)
        raise _Bail(f"local of type {base}")

    def _assign(self, s: A.SAssign, env) -> None:
        lv = s.lval
        v = self.sev(s.e, env)
        if isinstance(lv, A.EVar):
            name = lv.name
            if name in env:
                cur = env[name]
                if _is_svec(cur):
                    if not _is_svec(v) or len(v[1]) != len(cur[1]):
                        raise _Bail("array assign shape mismatch")
                    env[name] = v
                elif _is_sbit(cur):
                    env[name] = _as_sbit(v)
                else:
                    env[name] = ("i",) + _as_int(v)
                return
            raise _Bail(f"assign to unclassified {name!r}")
        if isinstance(lv, A.EIdx) and isinstance(lv.arr, A.EVar):
            name = lv.arr.name
            if name in self.out_names:
                a, b = _as_int(self.sev(lv.i, env))
                if a != 1:
                    raise _Bail("output stride != 1")
                site = self.out_writes.setdefault(name, {})
                if b not in site and len(site) >= 1:
                    raise _Bail("multiple output sites per array")
                site[b] = _as_sbit(v)
                return
            if name in env:
                i = _const_of(self.sev(lv.i, env))
                cur = env[name]
                if not _is_svec(cur) or not (0 <= i < len(cur[1])):
                    raise _Bail("bad element write")
                bits = list(cur[1])
                bits[i] = _as_sbit(v)
                env[name] = ("v", tuple(bits))
                return
            raise _Bail(f"element write to unclassified {name!r}")
        if isinstance(lv, A.ESlice) and isinstance(lv.arr, A.EVar):
            name = lv.arr.name
            if name not in env:
                raise _Bail(f"slice write to unclassified {name!r}")
            i = _const_of(self.sev(lv.i, env))
            n = _const_of(self.sev(lv.n, env))
            cur = env[name]
            if not _is_svec(cur) or not (0 <= i and i + n <= len(cur[1])):
                raise _Bail("bad slice write")
            if _is_svec(v):
                src = v[1]
            elif isinstance(v, np.ndarray) and v.ndim == 1:
                src = tuple(_bit(int(x)) for x in v)
            else:
                raise _Bail("slice write of non-array")
            if len(src) != n:
                raise _Bail("slice write length mismatch")
            bits = list(cur[1])
            bits[i:i + n] = list(src)
            env[name] = ("v", tuple(bits))
            return
        raise _Bail("unsupported lval")

    # -- entry -------------------------------------------------------------

    def run(self):
        """Execute one iteration; return the per-iteration affine map
        as numpy bit matrices, or raise _Bail."""
        self._classify()
        if self.n_state == 0 and not self.out_names:
            raise _Bail("no state and no outputs")
        env: Dict[str, Any] = {}
        for name, (base, nbits, scalar) in self.state.items():
            if scalar:
                env[name] = ("b", 1 << base, 0)
            else:
                env[name] = ("v", tuple(("b", 1 << (base + k), 0)
                                        for k in range(nbits)))
        self.out_writes = {}
        self._exec(self.st.body, env)

        n_s, n_x = self.n_state, len(self.in_order)

        def decode(sb, rs, rx):
            mask, c = sb[1], sb[2]
            for k in range(n_s):
                if mask >> k & 1:
                    rs[k] ^= 1
            for j in range(n_x):
                if mask >> (MAX_STATE_BITS + j) & 1:
                    rx[j] ^= 1
            if mask >> (MAX_STATE_BITS + n_x):
                raise _Bail("internal: unknown symbol")
            return c

        M = np.zeros((n_s, n_s), dtype=np.uint8)
        B = np.zeros((n_s, n_x), dtype=np.uint8)
        c = np.zeros((n_s,), dtype=np.uint8)
        for name, (base, nbits, scalar) in self.state.items():
            val = env[name]
            if scalar:
                bits = (_as_sbit(val),)
            else:
                if not _is_svec(val):
                    raise _Bail("state array became non-array")
                bits = val[1]
            if len(bits) != nbits:
                raise _Bail("state shape changed")
            for k, sb in enumerate(bits):
                sb = _as_sbit(sb)
                c[base + k] = decode(sb, M[base + k], B[base + k])

        outs = []
        for name, site in self.out_writes.items():
            (b_off, sb), = site.items()
            rs = np.zeros((n_s,), dtype=np.uint8)
            rx = np.zeros((n_x,), dtype=np.uint8)
            oc = decode(sb, rs, rx)
            outs.append((name, b_off, rs, rx, oc))
        if set(self.out_writes) != self.out_names:
            raise _Bail("output array not written this subrange")
        return _IterMap(self, M, B, c, outs)


class _IterMap:
    """The extracted per-iteration affine map plus site metadata."""

    def __init__(self, sym: _Sym, M, B, c, outs):
        self.state = dict(sym.state)
        self.n_state = sym.n_state
        self.in_order = list(sym.in_order)
        self.M, self.B, self.c = M, B, c
        self.outs = outs

    def compose(self, K: int):
        """Fold K iterations: returns (MK, Xc, cK, out_rows) where Xc
        maps the K*n_x per-iteration input bits (iteration-major) into
        the final state, and out_rows[site] = (Ow (K,n_s), Pw (K,K*nx),
        qw (K,)) gives each iteration's emitted bit."""
        n_s, n_x = self.n_state, len(self.in_order)
        A_ = np.eye(n_s, dtype=np.uint8)
        X = np.zeros((n_s, K * n_x), dtype=np.uint8)
        C = np.zeros((n_s,), dtype=np.uint8)
        rows = [(np.zeros((K, n_s), np.uint8),
                 np.zeros((K, K * n_x), np.uint8),
                 np.zeros((K,), np.uint8)) for _ in self.outs]
        for i in range(K):
            for t, (_n, _b, rs, rx, oc) in enumerate(self.outs):
                Ow, Pw, qw = rows[t]
                Ow[i] = (rs @ A_) % 2
                Pw[i] = (rs @ X) % 2
                Pw[i, i * n_x:(i + 1) * n_x] ^= rx
                qw[i] = (int(rs @ C) + int(oc)) % 2
            A_ = (self.M @ A_) % 2
            X = (self.M @ X) % 2
            X[:, i * n_x:(i + 1) * n_x] ^= self.B
            C = ((self.M @ C) + self.c) % 2
        return A_, X, C, rows


# --------------------------------------------------------------------------
# Planner: range splitting to branch-constant subranges
# --------------------------------------------------------------------------


def _free(e) -> Set[str]:
    from .eval import _free_names
    return _free_names(e)


def _plan(st: A.SFor, scope, ctx, start: int,
          count_static: Optional[int]):
    """Split [start, start+count) at discovered loop-var comparison
    crossings until every subrange symbolically executes with constant
    branch decisions (or bails). Returns [(lo, hi_static_or_None,
    itermap_or_None), ...] where hi of the last subrange is None
    (bounded by the possibly-traced loop end)."""
    bps: Set[int] = set()
    for _ in range(_MAX_SPLITS):
        pts = sorted(b for b in bps
                     if b > start
                     and (count_static is None
                          or b < start + count_static))
        bounds = [start] + pts
        plans = []
        new_bps: Set[int] = set()
        for i, lo in enumerate(bounds):
            hi = bounds[i + 1] if i + 1 < len(bounds) else None
            sym = _Sym(st, scope, ctx, p_rep=lo)
            try:
                im = sym.run()
            except _Bail:
                im = None
            new_bps |= sym.breakpoints
            plans.append((lo, hi, im))
        if new_bps <= bps:
            return plans
        bps |= new_bps
    raise _Bail("range splitting did not converge")


# --------------------------------------------------------------------------
# Execution
# --------------------------------------------------------------------------


def _gf2_loops_enabled() -> bool:
    """The ONE reading of the ZIRIA_NO_GF2_LOOPS escape hatch — the
    designated single-reader form the jaxlint R4 hygiene rule
    enforces."""
    return not os.environ.get("ZIRIA_NO_GF2_LOOPS")


def gf2_for(start, count, st: A.SFor, scope, ctx) -> bool:
    """Try to run `for var in [start, count] body` as composed GF(2)
    block steps. Returns True when it fully handled the loop (state
    and outputs updated); False leaves all state untouched."""
    if not _gf2_loops_enabled():
        return False
    try:
        start_i = int(start)     # raises on a traced start: unsupported
    except Exception:
        return False
    count_static: Optional[int] = None
    if isinstance(count, (int, np.integer)) or (
            isinstance(count, np.ndarray) and count.ndim == 0):
        try:
            count_static = int(count)
        except Exception:
            return False
        if count_static < 2 * K_BLOCK:
            return False         # nothing to win
    elif not (hasattr(count, "dtype") and np.ndim(count) == 0):
        return False

    try:
        plans = _plan(st, scope, ctx, start_i, count_static)
    except _Bail:
        return False

    # worthwhile only if the open-ended (or a long static) subrange
    # compressed; otherwise let the ordinary staging handle everything
    last_ok = plans[-1][2] is not None
    any_long_static = any(
        im is not None and hi is not None and hi - lo >= 2 * K_BLOCK
        for lo, hi, im in plans)
    if not (last_ok or any_long_static):
        return False

    import jax.numpy as jnp
    from .eval import ZiriaRuntimeError, _staged_for

    end = start_i + (count_static if count_static is not None
                     else count)          # traced scalar ok

    # snapshot every mutable cell before committing any subrange: an
    # analysis gap surfacing as a shape/dtype error at execution time
    # must restore state and fall back to ordinary staging (same
    # discipline as _vectorized_for's except-Exception path)
    snap = [(c, c.value) for _n, c in scope.mutable_cells_named()]
    try:
        for lo, hi, im in plans:
            # subrange [lo, min(hi, end)) — length may be traced
            sub_hi = end if hi is None else (
                hi if count_static is not None
                else jnp.minimum(hi, end))
            sub_len = sub_hi - lo
            if count_static is not None:
                sub_len = max(0, int(sub_len))
                if sub_len == 0:
                    continue
                if im is None or sub_len < 2 * K_BLOCK:
                    _staged_for(lo, sub_len, st, scope, ctx,
                                try_gf2=False)
                    continue
            else:
                sub_len = jnp.maximum(sub_len, 0)
                # narrow bounded subranges (breakpoint slivers) are
                # not worth a block graph; only the open-ended or
                # wide ones are
                if im is None or (hi is not None
                                  and hi - lo < 2 * K_BLOCK):
                    _staged_for(lo, sub_len, st, scope, ctx,
                                try_gf2=False)
                    continue
            _run_compressed(im, lo, sub_len, st, scope, ctx)
    except ZiriaRuntimeError:
        raise                     # genuine program error: diagnose
    except Exception:
        for c, v in snap:
            c.value = v
        return False
    return True


def _find_writable(scope, name):
    """The WRITE-THROUGH cell for `name`. `scope.find` may hand back a
    snapshot view (elab's RuntimeScope wraps ir.Env refs in throwaway
    Cells); `mutable_cells_named` is the same channel the ordinary
    staging write-back uses (_written_cells), innermost-first."""
    for n, c in scope.mutable_cells_named():
        if n == name:
            return c
    return scope.find(name)


def _run_compressed(im: _IterMap, lo, sub_len, st, scope, ctx) -> None:
    from jax import lax
    import jax.numpy as jnp
    from .eval import _staged_for

    K = K_BLOCK
    n_s, n_x = im.n_state, len(im.in_order)
    MK, X, cK, rows = im.compose(K)

    # group input sites per array into contiguous windows
    arrays: Dict[str, List[int]] = {}
    for (name, b) in im.in_order:
        arrays.setdefault(name, []).append(b)
    win: Dict[str, Tuple[int, int, int]] = {}   # name -> (bmin, W, col0)
    col0 = 0
    for name, bs in arrays.items():
        bmin, bmax = min(bs), max(bs)
        W = K + (bmax - bmin)
        win[name] = (bmin, W, col0)
        col0 += W
    W_total = col0

    def remap(mat_x):
        """(r, K*n_x) iteration-major input coefficients -> (r, W_total)
        window coordinates (coefficients on a shared column XOR)."""
        out = np.zeros(mat_x.shape[:-1] + (W_total,), dtype=np.uint8)
        for i in range(K):
            for j, (name, b) in enumerate(im.in_order):
                bmin, _W, c0 = win[name]
                col = c0 + i + (b - bmin)
                out[..., col] ^= mat_x[..., i * n_x + j]
        return out

    BW = remap(X)
    out_mats = []
    for (name, b_off, _rs, _rx, _oc), (Ow, Pw, qw) in zip(im.outs, rows):
        out_mats.append((name, b_off, Ow, remap(Pw), qw))

    as_i32 = lambda a: jnp.asarray(np.ascontiguousarray(a), jnp.int32)  # noqa
    MKj, BWj, cKj = as_i32(MK), as_i32(BW), as_i32(cK)
    out_j = [(name, b, as_i32(Ow), as_i32(PW), as_i32(qw))
             for name, b, Ow, PW, qw in out_mats]

    # gather state entry vector
    cells = {name: _find_writable(scope, name) for name in im.state}
    parts = []
    for name, (base, nbits, scalar) in sorted(
            im.state.items(), key=lambda kv: kv[1][0]):
        v = jnp.asarray(cells[name].value)
        parts.append(v.reshape((nbits,)).astype(jnp.int32))
    s0 = jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.int32)

    in_vals = {name: jnp.asarray(scope.find(name).value)
               for name in arrays}
    out_cells = {name: _find_writable(scope, name) for name, *_ in out_j}
    out_bufs = [jnp.asarray(out_cells[name].value)
                for name, *_ in out_j]

    nblocks = sub_len // K
    in_names = list(arrays)

    def body(j, carry):
        s = carry[0]
        bufs = list(carry[1:])
        p0 = lo + j * K
        if W_total:
            ws = []
            for name in in_names:
                bmin, W, _c0 = win[name]
                ws.append(lax.dynamic_slice(
                    in_vals[name], (p0 + bmin,), (W,)).astype(jnp.int32))
            x = jnp.concatenate(ws)
            s2 = (MKj @ s + BWj @ x + cKj) % 2
        else:
            x = None
            s2 = (MKj @ s + cKj) % 2
        new_bufs = []
        for (name, b_off, Ow, PW, qw), buf in zip(out_j, bufs):
            y = Ow @ s + qw
            if x is not None:
                y = y + PW @ x
            y = (y % 2).astype(buf.dtype)
            new_bufs.append(lax.dynamic_update_slice(
                buf, y, (p0 + b_off,)))
        return (s2,) + tuple(new_bufs)

    res = lax.fori_loop(0, nblocks, body, (s0,) + tuple(out_bufs))
    sF = res[0]
    for (name, *_), buf in zip(out_j, res[1:]):
        out_cells[name].value = buf
    for name, (base, nbits, scalar) in im.state.items():
        piece = sF[base:base + nbits].astype(jnp.uint8)
        cells[name].value = piece[0] if scalar else piece

    # remainder tail: the original body, staged. A statically-zero
    # tail would still trace the whole uncompressed body — skip it
    tail_lo = lo + nblocks * K
    tail_n = sub_len - nblocks * K
    if not isinstance(tail_n, (int, np.integer)) or tail_n:
        _staged_for(tail_lo, tail_n, st, scope, ctx, try_gf2=False)
