"""Elaboration: surface AST → core IR.

The frontend's middle end. Computation AST nodes become core IR nodes
(core/ir.py); expressions become closures over the runtime `ir.Env`
evaluated by the staged evaluator (frontend/eval.py), so one semantics
serves the interpreter (eager) and the jit backend (traced). Comp
functions are inlined at elaboration — the role the reference's
inliner/fold pass plays before codegen (SURVEY.md §2.1) — and
`let`-bound expressions are evaluated at elaboration time when they are
static, which is the partial-evaluation half of the reference's
`Interpreter.hs`.

After elaboration, `core.localize` rewrites stateful repeats
(LetRef⁺(Repeat)) into explicit-state MapAccums so parsed programs
reach the fused jit path.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field as dfield
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from ziria_tpu.core import ir
from ziria_tpu.core.localize import localize
from ziria_tpu.frontend import ast as A
from ziria_tpu.frontend import eval as E
from ziria_tpu.frontend.externals import BUILTINS, resolve_ext
from ziria_tpu.frontend.parser import parse_program


class ElabError(Exception):
    pass


def _err(src: str, loc, msg: str) -> ElabError:
    return ElabError(f"{src}:{loc[0]}:{loc[1]}: {msg}")


# --------------------------------------------------------------------------
# Runtime scope: routes name lookup/assignment through ir.Env
# --------------------------------------------------------------------------


class _EnvRefCell:
    """Write-through view of an ir.Env ref, so eval's staged-if merge can
    snapshot and update stream-level `var`s."""

    __slots__ = ("_env", "_name")

    def __init__(self, env: ir.Env, name: str):
        self._env = env
        self._name = name

    @property
    def value(self):
        return self._env.lookup(self._name)

    @value.setter
    def value(self, v):
        self._env.set(self._name, v)


def _env_ref_names(env: ir.Env) -> List[str]:
    """Ref names visible (and writable) from `env` — a ref shadowed by
    an inner immutable bind (e.g. a comp-fun param named like an outer
    `var`) is excluded: lookup resolves to the bind, so the block can
    neither read nor legally write the outer ref, and exposing it as a
    mutable cell made the staged-if merge explode on write-back."""
    out, seen = [], set()
    e = env
    while e is not None:
        for n in e._vars:
            seen.add(n)                      # inner binds shadow
        for n in e._refs:
            if n not in seen:
                seen.add(n)
                out.append(n)
        e = e._parent
    return out


class RuntimeScope(E.Scope):
    """Evaluation scope backed by the runtime ir.Env (bind vars + refs),
    falling back to the elaborator's static scope."""

    def __init__(self, env: ir.Env, static: E.Scope, var_types: Dict,
                 ctx: E.Ctx):
        super().__init__(parent=static)
        self.env = env
        self.var_types = var_types
        self.ctx = ctx

    def find(self, name):
        if name in self.cells:      # do-block locals win
            return self.cells[name]
        try:
            v = self.env.lookup(name)
        except KeyError:
            return super().find(name)   # static (global/const) fallback
        return E.Cell(v, self.var_types.get(name), True)

    def assign(self, name, value, ctx, loc=(0, 0)):
        if name in self.cells:      # do-block local
            return super().assign(name, value, ctx, loc)
        ty = self.var_types.get(name)
        if ty is not None:
            value = E.cast_value(ty, value, ctx.structs,
                                 lambda x: ctx.static_eval(x, self),
                                 fxp=ctx.fxp_complex16)
        try:
            self.env.set(name, value)
            return
        except KeyError as e:
            if "immutable" in str(e):
                raise E.ZiriaRuntimeError(str(e)) from None
        super().assign(name, value, ctx, loc)

    def own_mutable_cells(self):
        cells = list(super().own_mutable_cells())
        cells.extend((n, _EnvRefCell(self.env, n))
                     for n in _env_ref_names(self.env))
        return cells


# --------------------------------------------------------------------------
# Free variables (expression level)
# --------------------------------------------------------------------------


def free_vars(e: Optional[A.Expr]) -> FrozenSet[str]:
    if e is None:
        return frozenset()
    out = set()

    def walk(x):
        if x is None:
            return
        if isinstance(x, A.EVar):
            out.add(x.name)
        elif isinstance(x, A.EUn):
            walk(x.e)
        elif isinstance(x, A.EBin):
            walk(x.a)
            walk(x.b)
        elif isinstance(x, A.ECond):
            walk(x.c)
            walk(x.a)
            walk(x.b)
        elif isinstance(x, A.ECall):
            for a in x.args:
                walk(a)
        elif isinstance(x, A.EIdx):
            walk(x.arr)
            walk(x.i)
        elif isinstance(x, A.ESlice):
            walk(x.arr)
            walk(x.i)
            walk(x.n)
        elif isinstance(x, A.EField):
            walk(x.e)
        elif isinstance(x, A.EArrLit):
            for a in x.elems:
                walk(a)
        elif isinstance(x, A.EStructLit):
            for _, a in x.fields:
                walk(a)

    walk(e)
    return frozenset(out)


# --------------------------------------------------------------------------
# Elaboration environment
# --------------------------------------------------------------------------


@dataclass
class ElabEnv:
    static: E.Scope
    runtime: FrozenSet[str] = frozenset()
    var_types: Dict[str, A.Ty] = dfield(default_factory=dict)
    var_lens: Dict[str, int] = dfield(default_factory=dict)
    comps: Dict[str, ir.Comp] = dfield(default_factory=dict)

    def with_runtime(self, name: str, ty: Optional[A.Ty] = None,
                     length: Optional[int] = None) -> "ElabEnv":
        vt = dict(self.var_types)
        vl = dict(self.var_lens)
        if ty is not None:
            vt[name] = ty
        else:
            vt.pop(name, None)
        if length is not None:
            vl[name] = length
        else:
            vl.pop(name, None)
        return ElabEnv(self.static, self.runtime | {name}, vt, vl,
                       dict(self.comps))

    def with_comp(self, name: str, c: ir.Comp) -> "ElabEnv":
        comps = dict(self.comps)
        comps[name] = c
        return ElabEnv(self.static, self.runtime, dict(self.var_types),
                       dict(self.var_lens), comps)

    def with_static(self, name: str, value: Any) -> "ElabEnv":
        s = self.static.child()
        s.declare(name, value, None, mutable=False)
        return ElabEnv(s, self.runtime, dict(self.var_types),
                       dict(self.var_lens), dict(self.comps))

    def static_names(self) -> FrozenSet[str]:
        out = set()
        s = self.static
        while s is not None:
            out.update(s.cells)
            s = s.parent
        return frozenset(out)


@dataclass
class CompiledProgram:
    """A compiled source program: the elaborated main pipeline plus the
    driver-facing stream item types recovered from read[t]/write[t]."""

    comp: ir.Comp
    in_ty: Optional[str] = None
    out_ty: Optional[str] = None
    name: str = "main"
    comps: Dict[str, ir.Comp] = dfield(default_factory=dict)


# file item types (runtime/buffers.py names) for read[t]/write[t]
_FILE_TY = {
    "bit": "bit", "bool": "bit",
    "int8": "int8", "int16": "int16", "int32": "int32", "int": "int32",
    "double": "float32",
    "complex16": "complex16", "complex32": "complex32",
}


# --------------------------------------------------------------------------
# The elaborator
# --------------------------------------------------------------------------


class Elaborator:
    def __init__(self, prog: A.Program, src_name: str = "<input>",
                 fxp_complex16: bool = False, autolut: bool = False):
        self.prog = prog
        self.src = src_name
        self.gscope = E.Scope()
        self.ctx = E.Ctx(exts=dict(BUILTINS),
                         fxp_complex16=fxp_complex16,
                         autolut=autolut)
        self.comp_funs: Dict[str, A.DFunComp] = {}
        # single source of truth for ext signatures: the evaluator's
        # registry (ctx.ext_sigs); self.ext_sigs aliases the SAME dict
        self.ext_sigs = self.ctx.ext_sigs
        self.top_comps: Dict[str, ir.Comp] = {}
        self.top_comp_asts: Dict[str, A.Comp] = {}
        self._inlining: List[str] = []

    # -------------------------------------------------------- static eval

    def st_eval(self, e: A.Expr, ee: Optional[ElabEnv] = None) -> Any:
        scope = ee.static if ee is not None else self.gscope
        return self.ctx.static_eval(e, scope)

    def try_st_eval(self, e: A.Expr, ee: ElabEnv) -> Tuple[bool, Any]:
        # never speculatively evaluate impure expressions (a user fun can
        # print/error — compile-time evaluation would fire the effect)
        if not _is_pure(e):
            return False, None
        fv = free_vars(e)
        # a runtime-bound name shadows any static global of the same name:
        # folding through it would silently substitute the global's value
        if not (fv <= ee.static_names()) or (fv & ee.runtime):
            return False, None
        try:
            return True, self.st_eval(e, ee)
        except (E.NotStatic, E.ZiriaRuntimeError):
            return False, None

    def st_int(self, e: A.Expr, ee: ElabEnv, what: str) -> int:
        ok, v = self.try_st_eval(e, ee)
        if not ok or not isinstance(v, (int, np.integer)) \
                or isinstance(v, bool):
            raise _err(self.src, e.loc,
                       f"{what} must be a compile-time static integer")
        return int(v)

    # -------------------------------------------------------- closures

    def closure(self, e: A.Expr, ee: ElabEnv,
                cast_ty: Optional[A.Ty] = None) -> Any:
        """Build the runtime Expr for `e`: a static value when possible,
        else a closure over ir.Env."""
        unknown = free_vars(e) - ee.static_names() - ee.runtime
        if unknown:
            raise _err(self.src, e.loc,
                       f"unbound variable(s) {sorted(unknown)}")
        # constant-fold pure closed expressions at elaboration time (the
        # partial-evaluation half of the reference's Interpreter.hs);
        # runtime-shadowed names block the fold (see try_st_eval)
        if _is_pure(e) and free_vars(e) <= ee.static_names() \
                and not (free_vars(e) & ee.runtime):
            try:
                v = E.eval_expr(e, ee.static, self.ctx)
                if cast_ty is not None:
                    v = E.cast_value(cast_ty, v, self.ctx.structs,
                                     lambda x: self.st_eval(x, ee),
                                     fxp=self.ctx.fxp_complex16)
                return v
            except Exception:
                pass
        static, vt, ctx = ee.static, ee.var_types, self.ctx

        def run(env: ir.Env, _e=e, _ty=cast_ty):
            scope = RuntimeScope(env, static, vt, ctx)
            v = E.eval_expr(_e, scope, ctx)
            if _ty is not None:
                v = E.cast_value(_ty, v, ctx.structs,
                                 lambda x: ctx.static_eval(x, scope),
                                 fxp=ctx.fxp_complex16)
            return v

        # expose the expression AST (+ Ctx for fun-body recursion) so
        # comp-level analyses (backend/chunked.py bounds, effects and
        # free-variable checks) can see through the closure
        run.z_expr = e
        run.z_ctx = ctx
        return run

    def stmts_closure(self, stmts: Tuple[A.Stmt, ...], ee: ElabEnv) -> Any:
        """Closure running a do-block; value = return value or None."""
        static, vt, ctx = ee.static, ee.var_types, self.ctx

        def run(env: ir.Env, _stmts=stmts):
            scope = RuntimeScope(env, static, vt, ctx)
            r = E.exec_stmts(_stmts, scope, ctx)
            return r[1] if r is not None else None

        # expose the statement AST (and the Ctx, for looking into called
        # funs) so the hybrid executor (backend/hybrid.py) can weigh
        # this block and decide whether to jit-compile it as a unit
        run.z_stmts = stmts
        run.z_ctx = ctx
        return run

    # -------------------------------------------------------- static_len

    def static_len(self, e: A.Expr, ee: ElabEnv) -> Optional[int]:
        """Static array length of `e`'s value, if derivable."""
        ok, v = self.try_st_eval(e, ee)
        if ok and np.shape(v):
            return int(np.shape(v)[0])
        if isinstance(e, A.EArrLit):
            return len(e.elems)
        if isinstance(e, A.ESlice):
            try:
                return self.st_int(e.n, ee, "slice length")
            except ElabError:
                return None
        if isinstance(e, A.EVar):
            if e.name in ee.var_lens:
                return ee.var_lens[e.name]
            ty = ee.var_types.get(e.name)
            return self._ty_len(ty, ee)
        if isinstance(e, A.ECall):
            if e.name in self.ctx.funs:
                return self._ty_len(self.ctx.funs[e.name].decl.ret_ty, ee)
            if e.name in self.ext_sigs:
                return self._ty_len(self.ext_sigs[e.name].ret_ty, ee)
            # casts preserve shape
            if e.name in E._BASE_TYPE_NAMES and len(e.args) == 1:
                return self.static_len(e.args[0], ee)
            return None
        if isinstance(e, A.ECond):
            a = self.static_len(e.a, ee)
            b = self.static_len(e.b, ee)
            return a if a == b else None
        if isinstance(e, A.EUn):
            return self.static_len(e.e, ee)
        if isinstance(e, A.EBin) and e.op not in ("&&", "||", "==", "!=",
                                                  "<", "<=", ">", ">="):
            return (self.static_len(e.a, ee)
                    or self.static_len(e.b, ee))
        return None

    def _ty_len(self, ty: Optional[A.Ty], ee: ElabEnv) -> Optional[int]:
        if isinstance(ty, A.TArr) and ty.n is not None:
            try:
                return self.st_int(ty.n, ee, "array length")
            except ElabError:
                return None
        return None

    # -------------------------------------------------------- comp elab

    def elab_comp(self, c: A.Comp, ee: ElabEnv) -> ir.Comp:
        if isinstance(c, A.CTake):
            return ir.take
        if isinstance(c, A.CTakes):
            return ir.takes(self.st_int(c.n, ee, "takes count"))
        if isinstance(c, A.CEmit):
            return ir.Emit(self.closure(c.e, ee))
        if isinstance(c, A.CEmits):
            n = self.static_len(c.e, ee)
            if n is None:
                raise _err(
                    self.src, c.loc,
                    "emits: cannot determine the array length statically; "
                    "annotate the source variable (var x : arr[N] t / "
                    "(x : arr[N] t) <- ...) or emit a slice x[0, N]")
            return ir.Emits(self.closure(c.e, ee), n)
        if isinstance(c, A.CReturn):
            return ir.Return(self.closure(c.e, ee))
        if isinstance(c, A.CDo):
            return ir.Return(self.stmts_closure(c.body, ee))
        if isinstance(c, A.CBind):
            first = self.elab_comp(c.first, ee)
            if c.var is None:
                return ir.Bind(first, None, self.elab_comp(c.rest, ee))
            length = None
            if isinstance(c.first, A.CTakes):
                length = self.st_int(c.first.n, ee, "takes count")
            ee2 = ee.with_runtime(c.var, c.var_ty, length)
            return ir.Bind(first, c.var, self.elab_comp(c.rest, ee2))
        if isinstance(c, A.CVarDecl):
            if c.ty is None:
                raise _err(self.src, c.loc, "var needs a type annotation")
            init = (self.closure(c.init, ee, cast_ty=c.ty)
                    if c.init is not None
                    else E.zero_value(c.ty, self.ctx.structs,
                                      lambda x: self.st_eval(x, ee),
                                      fxp=self.ctx.fxp_complex16))
            init = _device_init(init, c.ty)
            ln = self._ty_len(c.ty, ee)
            ee2 = ee.with_runtime(c.name, c.ty, ln)
            return ir.LetRef(c.name, init, self.elab_comp(c.rest, ee2))
        if isinstance(c, A.CLetDecl):
            ok, v = self.try_st_eval(c.e, ee)
            if ok and _is_pure(c.e):
                ee2 = ee.with_static(c.name, v)
                return self.elab_comp(c.rest, ee2)
            ln = self.static_len(c.e, ee)
            ee2 = ee.with_runtime(c.name, None, ln)
            return ir.Bind(ir.Return(self.closure(c.e, ee)), c.name,
                           self.elab_comp(c.rest, ee2))
        if isinstance(c, A.CLetComp):
            inner = self.elab_comp(c.c, ee)
            return self.elab_comp(c.rest, ee.with_comp(c.name, inner))
        if isinstance(c, A.CRepeat):
            return ir.Repeat(self.elab_comp(c.body, ee))
        if isinstance(c, A.CMap):
            return self._elab_map(c, ee)
        if isinstance(c, A.CPipe):
            up = self.elab_comp(c.up, ee)
            down = self.elab_comp(c.down, ee)
            return ir.ParPipe(up, down) if c.par else ir.Pipe(up, down)
        if isinstance(c, A.CIf):
            ok, v = self.try_st_eval(c.c, ee)
            if ok:
                if v:
                    return self.elab_comp(c.then, ee)
                return (self.elab_comp(c.els, ee) if c.els is not None
                        else ir.Return(None))
            els = (self.elab_comp(c.els, ee) if c.els is not None
                   else ir.Return(None))
            return ir.Branch(self.closure(c.c, ee),
                             self.elab_comp(c.then, ee), els)
        if isinstance(c, A.CFor):
            return self._elab_for(c, ee)
        if isinstance(c, A.CTimes):
            ok, n = self.try_st_eval(c.count, ee)
            count = int(n) if ok else self.closure(c.count, ee)
            return ir.For(None, count, self.elab_comp(c.body, ee))
        if isinstance(c, A.CWhile):
            return ir.While(self.closure(c.c, ee),
                            self.elab_comp(c.body, ee))
        if isinstance(c, A.CUntil):
            body = self.elab_comp(c.body, ee)
            cond = self.closure(c.c, ee)

            def neg(env, _c=cond):
                v = ir.eval_expr(_c, env)
                if E._is_traced(v):          # stageable under jit tracing
                    import jax.numpy as jnp
                    return jnp.logical_not(v)
                return not bool(v)

            neg.z_expr = A.EUn(op="!", e=c.c, loc=c.loc)
            neg.z_ctx = self.ctx
            return ir.Bind(body, None, ir.While(neg, body))
        if isinstance(c, A.CCall):
            return self._elab_call(c, ee)
        if isinstance(c, (A.CRead, A.CWrite)):
            raise _err(self.src, c.loc,
                       "read/write may only appear at the ends of the "
                       "top-level pipeline")
        raise _err(self.src, getattr(c, "loc", (0, 0)),
                   f"unknown computation node {type(c).__name__}")

    def _elab_for(self, c: A.CFor, ee: ElabEnv) -> ir.Comp:
        ok_s, start = self.try_st_eval(c.start, ee)
        ok_n, n = self.try_st_eval(c.count, ee)
        count = int(n) if ok_n else self.closure(c.count, ee)
        if ok_s and int(start) == 0:
            body = self.elab_comp(c.body, ee.with_runtime(c.var))
            return ir.For(c.var, count, body)
        # non-zero / dynamic start: hidden index + per-iteration rebind
        hidden = f"__i_{c.var}"
        ee2 = ee.with_runtime(hidden).with_runtime(c.var)
        body = self.elab_comp(c.body, ee2)
        start_c = int(start) if ok_s else self.closure(c.start, ee)

        def offset(env, _h=hidden, _s=start_c):
            s = ir.eval_expr(_s, env)
            return env.lookup(_h) + s

        return ir.For(hidden, count, ir.Bind(ir.Return(offset), c.var, body))

    def _elab_map(self, c: A.CMap, ee: ElabEnv) -> ir.Comp:
        name = c.fname
        fd = self.ctx.funs.get(name)
        if fd is not None:
            d = fd.decl
            if len(d.params) != 1:
                raise _err(self.src, c.loc,
                           f"map {name}: needs a one-argument function")
            a = self._ty_len(d.params[0].ty, ee) or 1
            b = self._ty_len(d.ret_ty, ee) or 1
            dom = _domain_of(d.params[0].ty)
            ctx = self.ctx

            def f(x, _fd=fd, _ctx=ctx):
                return E.call_fun(_fd, [x], _ctx)

            lut = None
            if dom is None:
                # inferred LUT-ability (lutinfer, LUTAnalysis role):
                # packed multi-bit items like arr[8] bit
                from ziria_tpu.frontend import lutinfer
                spec = lutinfer.spec_for_fun(name, fd, ctx)
                if spec is not None:
                    lut = lutinfer.MapLut(spec, fd, ctx)
            fxp = self.ctx.fxp_complex16
            return ir.Map(f, in_arity=a, out_arity=b, name=name,
                          in_domain=dom,
                          in_dtype=_dtype_of(d.params[0].ty, fxp),
                          out_dtype=_dtype_of(d.ret_ty, fxp),
                          lut=lut)
        if name in self.ext_sigs:
            d = self.ext_sigs[name]
            fn = self.ctx.exts[name]
            a = (self._ty_len(d.params[0].ty, ee) or 1) if d.params else 1
            b = self._ty_len(d.ret_ty, ee) or 1
            dom = _domain_of(d.params[0].ty) if d.params else None
            fxp = self.ctx.fxp_complex16
            if fxp and d.params:
                # the map form must honor the same ext-boundary policy
                # as expression calls: complex-typed params see
                # complex64, complex16 returns requantize (review r2)
                pty, rty = d.params[0].ty, d.ret_ty

                def fn(x, _fn=fn, _p=pty, _r=rty):
                    return E._fx_ext_ret(_fn(E._fx_ext_arg(x, _p)), _r)
            return ir.Map(fn, in_arity=a, out_arity=b, name=name,
                          in_domain=dom,
                          in_dtype=(_dtype_of(d.params[0].ty, fxp)
                                    if d.params else None),
                          out_dtype=_dtype_of(d.ret_ty, fxp))
        if name in self.ctx.exts:
            return ir.Map(self.ctx.exts[name], name=name)
        raise _err(self.src, c.loc, f"map: unknown function {name!r}")

    def _elab_call(self, c: A.CCall, ee: ElabEnv) -> ir.Comp:
        name = c.name
        if name in ee.comps:
            if c.args:
                raise _err(self.src, c.loc,
                           f"{name} is a computation binding, not a "
                           f"function — call it without arguments")
            return ee.comps[name]
        if name in self.top_comps:
            if c.args:
                raise _err(self.src, c.loc,
                           f"{name} is a computation binding, not a "
                           f"function — call it without arguments")
            return self.top_comps[name]
        d = self.comp_funs.get(name)
        if d is None:
            raise _err(self.src, c.loc,
                       f"unknown computation {name!r}")
        if len(c.args) != len(d.params):
            raise _err(self.src, c.loc,
                       f"{name}: expected {len(d.params)} args, got "
                       f"{len(c.args)}")
        if name in self._inlining:
            raise _err(self.src, c.loc,
                       f"recursive comp function {name!r} is not "
                       f"supported (streams recurse via repeat/while)")
        self._inlining.append(name)
        try:
            # comp funs are top-level: the body sees globals + its params
            # only. Static args bind at elaboration time; runtime args
            # bind through the env (Bind(Return(closure), name, ...)).
            ee2 = ElabEnv(self.gscope)
            runtime_binds: List[Tuple[str, Any]] = []
            for p, a in zip(d.params, c.args):
                ok, v = self.try_st_eval(a, ee)
                if ok and _is_pure(a):
                    if p.ty is not None:
                        v = E.cast_value(p.ty, v, self.ctx.structs,
                                         lambda x: self.st_eval(x, ee),
                                         fxp=self.ctx.fxp_complex16)
                    ee2 = ee2.with_static(p.name, v)
                else:
                    ln = self.static_len(a, ee)
                    ee2 = ee2.with_runtime(
                        p.name, p.ty,
                        ln or self._ty_len(p.ty, ee))
                    runtime_binds.append(
                        (p.name, self.closure(a, ee, cast_ty=p.ty)))
            body = self.elab_comp(d.body, ee2)
            # evaluate ALL argument closures before binding ANY parameter:
            # binding param i before evaluating argument j>i would let the
            # fresh binding shadow a caller variable of the same name.
            # Stage through unique temps, then alias params to them.
            temps = []
            for pname, cl in runtime_binds:
                self._tmp = getattr(self, "_tmp", 0) + 1
                temps.append((f"__arg{self._tmp}_{pname}", pname, cl))
            for tname, pname, _ in reversed(temps):
                def alias(env, _t=tname):
                    return env.lookup(_t)
                body = ir.Bind(ir.Return(alias), pname, body)
            for tname, _, cl in reversed(temps):
                body = ir.Bind(ir.Return(cl), tname, body)
            return body
        finally:
            self._inlining.pop()

    # -------------------------------------------------------- program

    def elaborate(self) -> "Elaborator":
        for d in self.prog.decls:
            if isinstance(d, A.DStruct):
                self.ctx.structs[d.name] = E.StructDef(d.name, d.fields)
            elif isinstance(d, A.DFun):
                self.ctx.funs[d.name] = E.FunDef(d, self.gscope)
            elif isinstance(d, A.DExt):
                try:
                    fn = resolve_ext(d.name)
                except KeyError as e:
                    raise _err(self.src, d.loc, str(e)) from None
                self.ctx.exts[d.name] = fn
                self.ext_sigs[d.name] = d   # aliases ctx.ext_sigs
            elif isinstance(d, A.DLet):
                v = E.eval_expr(d.e, self.gscope, self.ctx)
                self.gscope.declare(d.name, v, None, mutable=False)
            elif isinstance(d, A.DFunComp):
                self.comp_funs[d.name] = d
            elif isinstance(d, A.DLetComp):
                self.top_comp_asts[d.name] = d.c
            else:
                raise _err(self.src, d.loc,
                           f"unknown declaration {type(d).__name__}")
        return self

    def build(self, entry: str = "main",
              typecheck: bool = True) -> CompiledProgram:
        self.elaborate()
        if typecheck:
            # static expression typechecker (reference TcExpr/TcUnify
            # role, SURVEY.md §2.1): dtype + array-length checking over
            # the surface AST with located errors, before any closure
            # can fail at runtime
            from ziria_tpu.frontend.typecheck import check_program
            check_program(self)
        # elaborate non-entry top comps first, in order, so entry can
        # reference them
        base = ElabEnv(self.gscope)
        for name, cast in self.top_comp_asts.items():
            if name == entry:
                continue
            body, _, _ = self._split_io(cast)
            self.top_comps[name] = localize(self.elab_comp(body, base))
        if entry in self.top_comp_asts:
            cast = self.top_comp_asts[entry]
        elif entry in self.comp_funs and not self.comp_funs[entry].params:
            cast = self.comp_funs[entry].body
        else:
            known = sorted(set(self.top_comp_asts) | set(self.comp_funs))
            raise ElabError(
                f"{self.src}: no computation {entry!r} "
                f"(have: {', '.join(known) or 'none'}) — define "
                f"`let comp main = ...`")
        body, in_ty, out_ty = self._split_io(cast)
        comp = localize(self.elab_comp(body, base))
        fxp = self.ctx.fxp_complex16
        comp, in_name = _input_adapter(comp, in_ty, self.src, fxp)
        comp, out_name = _output_adapter(comp, out_ty, self.src, fxp)
        if typecheck:
            # stream-level discipline + item-dtype unification on the
            # final IR (core/types.py — the reference's TcComp/TcUnify
            # composition rules)
            from ziria_tpu.core.types import ZiriaTypeError as StreamTE
            from ziria_tpu.core.types import typecheck as stream_tc
            try:
                stream_tc(comp)
            except StreamTE as e:
                raise ElabError(f"{self.src}: {e}") from None
        return CompiledProgram(comp, in_name, out_name, entry,
                               dict(self.top_comps))

    def _split_io(self, c: A.Comp):
        """Strip CRead/CWrite off the ends of the top-level pipe chain."""
        segs: List[Tuple[A.Comp, bool]] = []   # (comp, par_with_next)

        def flatten(x: A.Comp, par_after: bool):
            if isinstance(x, A.CPipe):
                flatten(x.up, x.par)
                flatten(x.down, par_after)
            else:
                segs.append((x, par_after))

        flatten(c, False)
        in_ty = out_ty = None
        if segs and isinstance(segs[0][0], A.CRead):
            in_ty = segs[0][0].ty
            segs = segs[1:]
        if segs and isinstance(segs[-1][0], A.CWrite):
            out_ty = segs[-1][0].ty
            segs = segs[:-1]
        for s, _ in segs:
            if isinstance(s, (A.CRead, A.CWrite)):
                raise _err(self.src, s.loc,
                           "read/write only at pipeline ends")
        if not segs:
            raise _err(self.src, getattr(c, "loc", (0, 0)),
                       "pipeline has no computation between read and write")
        # rebuild left-assoc chain preserving par flags
        cur: A.Comp = segs[0][0]
        for k in range(1, len(segs)):
            cur = A.CPipe(getattr(segs[k][0], "loc", (0, 0)), cur,
                          segs[k][0], par=segs[k - 1][1])
        return cur, in_ty, out_ty


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------


def _is_pure(e: A.Expr) -> bool:
    """Pre-evaluating at elaboration is only sound for call-free
    expressions (a user fun could print or error)."""
    if isinstance(e, A.ECall):
        return e.name in E._BASE_TYPE_NAMES and all(
            _is_pure(a) for a in e.args)
    kids: List[A.Expr] = []
    if isinstance(e, A.EUn):
        kids = [e.e]
    elif isinstance(e, A.EBin):
        kids = [e.a, e.b]
    elif isinstance(e, A.ECond):
        kids = [e.c, e.a, e.b]
    elif isinstance(e, A.EIdx):
        kids = [e.arr, e.i]
    elif isinstance(e, A.ESlice):
        kids = [e.arr, e.i, e.n]
    elif isinstance(e, A.EField):
        kids = [e.e]
    elif isinstance(e, A.EArrLit):
        kids = list(e.elems)
    elif isinstance(e, A.EStructLit):
        kids = [a for _, a in e.fields]
    return all(_is_pure(k) for k in kids if k is not None)


def _dtype_of(ty: Optional[A.Ty], fxp: bool = False) -> Optional[str]:
    """Numpy dtype name of a surface type's items (arrays use the
    element type), feeding Map dtype hints for the stream typechecker.
    Under the fixed-point policy complex16 items are int32 pairs."""
    t = ty.elem if isinstance(ty, A.TArr) else ty
    if not isinstance(t, A.TBase):
        return None
    if fxp and t.name == "complex16":
        return "int32"
    try:
        return str(np.dtype(E.base_dtype(t.name)))
    except Exception:
        return None


def _domain_of(ty: Optional[A.Ty]) -> Optional[int]:
    """AutoLUT input domain for small scalar types (SURVEY.md §2.1)."""
    if isinstance(ty, A.TBase):
        if ty.name in ("bit", "bool"):
            return 2
        if ty.name == "int8":
            return 256
    return None


def _device_init(init: Any, ty: A.Ty) -> Any:
    """Force var-decl initializers to concrete array values with the
    declared dtype, so MapAccum carries keep a stable dtype under scan.

    numpy (not jnp): the jit backend converts carries at the scan
    boundary, while the interpreter keeps evaluating stream-level vars
    on the numpy fast path (eval._np_ok). Traced initializers (closures
    over a traced env) still yield jnp values via eval's own dispatch."""
    if callable(init):
        def run(env, _i=init, _ty=ty):
            return _to_arr(_i(env), _ty)
        return run
    return _to_arr(init, ty)


def _to_arr(v: Any, ty: A.Ty):
    if isinstance(v, dict):
        return v
    if not E._np_ok(v):
        import jax.numpy as jnp
        if E.is_static(v) and isinstance(ty, A.TBase):
            return jnp.asarray(v, E.base_dtype(ty.name))
        return jnp.asarray(v)
    if E.is_static(v) and isinstance(ty, A.TBase):
        return np.asarray(v, E.base_dtype(ty.name))
    return np.asarray(v)


def _input_adapter(comp: ir.Comp, ty: Optional[A.Ty], src: str,
                   fxp: bool = False):
    if ty is None:
        return comp, None
    name = _file_ty(ty, src)
    if fxp and name == "complex16":
        # fixed-point policy: items stay integer IQ pairs on the wire
        # AND in the program — just widen storage to int32 so C-style
        # promotion holds mid-expression
        def to_fx(p):
            xp = np if E._np_ok(p) else E._jnp()
            return xp.asarray(p, np.int32)

        return ir.Pipe(ir.Map(to_fx, name="iq_to_fx", in_dtype="int16",
                              out_dtype="int32"), comp), name
    if name in ("complex16", "complex32"):
        def to_c64(p):
            # numpy for concrete items (the interpreter's per-sample
            # loop — jnp here would drag every downstream op onto the
            # jax dispatch path), jnp under the jit backend's trace
            xp = np if E._np_ok(p) else E._jnp()
            p = xp.asarray(p, np.float32)
            return (p[0] + 1j * p[1]).astype(np.complex64)

        return ir.Pipe(ir.Map(to_c64, name="iq_to_c64",
                              in_dtype="int16", out_dtype="complex64"),
                       comp), name
    return comp, name


def _output_adapter(comp: ir.Comp, ty: Optional[A.Ty], src: str,
                    fxp: bool = False):
    if ty is None:
        return comp, None
    name = _file_ty(ty, src)
    if fxp and name == "complex16":
        def fx_to_iq(z):
            # wrap to int16 exactly as a complex16 store does; accepts
            # f32/c64 values too (rounded) for mixed f32 blocks (FFT)
            xp = np if E._np_ok(z) else E._jnp()
            a = xp.asarray(z)
            if np.dtype(a.dtype).kind == "c":
                a = xp.stack([xp.round(xp.real(a)),
                              xp.round(xp.imag(a))], axis=-1)
            return E.fx_wrap16(a).astype(np.int16)

        # no in_dtype hint: this adapter deliberately accepts BOTH
        # int32 pairs and complex64 values (mixed f32 blocks), so a
        # concrete hint would reject the complex case it supports
        return ir.Pipe(comp, ir.Map(fx_to_iq, name="fx_to_iq",
                                     out_dtype="int16")), name
    if name in ("complex16", "complex32"):
        dt = np.int16 if name == "complex16" else np.int32

        def to_iq(z, _dt=dt):
            xp = np if E._np_ok(z) else E._jnp()
            z = xp.asarray(z, np.complex64)
            return xp.stack([xp.round(z.real),
                             xp.round(z.imag)]).astype(_dt)

        return ir.Pipe(comp, ir.Map(to_iq, name="c64_to_iq",
                                     in_dtype="complex64",
                                     out_dtype=("int16" if dt is np.int16
                                                else "int32"))), name
    return comp, name


def _file_ty(ty: A.Ty, src: str) -> str:
    if isinstance(ty, A.TBase) and ty.name in _FILE_TY:
        return _FILE_TY[ty.name]
    raise ElabError(f"{src}: stream item type {ty} has no file "
                    f"representation (use bit/int*/double/complex16/32)")


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


_INCLUDE_RE = re.compile(
    r'^\s*#\s*include\s+"([^"]+)"\s*(--.*)?$')


def _load_program(src: str, src_name: str, base_dir: Optional[str],
                  seen: set) -> A.Program:
    """Parse `src` after resolving top-level `#include "path"` lines.

    The reference's programs compose via the C preprocessor — tx.blk
    pulls in the per-block files and lib/ ext declarations (SURVEY.md
    §2.3). Here includes resolve at the DECLARATION level: each
    include line is blanked in place (host line numbers stay exact),
    the included file is parsed with its OWN src_name (parse errors
    are file-accurate; type/elab/runtime diagnostics cite the host
    program's name with the included file's line numbers — Loc is
    (line, col) program-wide), and its declarations are prepended in
    include order, so a host declaration of the same name (e.g.
    `main`) overrides a library's. Paths are relative to the
    including file; each resolved path is included once per program
    (pragma-once semantics — mutual includes terminate)."""
    lines = src.split("\n")
    pre: List[A.Decl] = []
    for i, ln in enumerate(lines):
        m = _INCLUDE_RE.match(ln)
        if m is None:
            continue
        if base_dir is None:
            raise ElabError(
                f"{src_name}:{i + 1}:1: #include requires a file-based "
                f"compile (compile_file) so relative paths resolve")
        inc = os.path.normpath(os.path.join(base_dir, m.group(1)))
        lines[i] = ""
        if inc in seen:
            continue
        seen.add(inc)
        try:
            with open(inc, "r") as fh:
                inc_src = fh.read()
        except OSError as e:
            raise ElabError(
                f"{src_name}:{i + 1}:1: cannot include "
                f"{m.group(1)!r}: {e}") from None
        pre.extend(_load_program(inc_src, inc,
                                 os.path.dirname(inc), seen).decls)
    prog = parse_program("\n".join(lines), src_name)
    return A.Program(tuple(pre) + tuple(prog.decls))


def compile_source(src: str, src_name: str = "<input>",
                   entry: str = "main", typecheck: bool = True,
                   fxp_complex16: bool = False,
                   autolut: bool = False,
                   base_dir: Optional[str] = None) -> CompiledProgram:
    # seed `seen` with the root file itself so an include cycle back
    # to the host cannot re-parse it and duplicate its declarations
    seen = set()
    if base_dir is not None:
        seen.add(os.path.normpath(os.path.abspath(src_name)))
    prog = _load_program(src, src_name, base_dir, seen)
    return Elaborator(prog, src_name, fxp_complex16=fxp_complex16,
                      autolut=autolut) \
        .build(entry, typecheck=typecheck)


def compile_file(path: str, entry: str = "main", typecheck: bool = True,
                 fxp_complex16: bool = False,
                 autolut: bool = False) -> CompiledProgram:
    with open(path, "r") as fh:
        return compile_source(fh.read(), path, entry,
                              typecheck=typecheck,
                              fxp_complex16=fxp_complex16,
                              autolut=autolut,
                              base_dir=os.path.dirname(
                                  os.path.abspath(path)))
