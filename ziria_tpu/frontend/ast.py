"""Surface-syntax AST for the two-level Ziria-style language.

Counterpart of the reference's `AstExpr.hs` / `AstComp.hs` (SURVEY.md
§2.1): one AST for the first-order imperative *expression* language and
one for the *stream computation* language. Deliberately plain Python
dataclasses — the elaborator (frontend/elab.py) turns computation nodes
into the core IR (core/ir.py) and the staged evaluator (frontend/eval.py)
turns expression nodes into jnp values, so these classes carry no
behavior beyond structure + source location.

Every node has a ``loc`` (line, col) for error messages; the parser
fills it in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

Loc = Tuple[int, int]   # (line, col), 1-based


# --------------------------------------------------------------------------
# Types (surface syntax)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Ty:
    """Base surface type."""


@dataclass(frozen=True)
class TBase(Ty):
    """bit | bool | int8 | int16 | int32 | int64 | int | double |
    complex16 | complex32 | complex | unit"""

    name: str

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class TArr(Ty):
    """arr[n] t — fixed-length array. ``n`` is an expression AST that must
    elaborate to a static int (the reference's array-length arithmetic);
    None means length-polymorphic (only legal in fun params, `arr t`)."""

    n: Optional["Expr"]
    elem: Ty

    def __str__(self):
        return f"arr[{self.n}] {self.elem}"


@dataclass(frozen=True)
class TStruct(Ty):
    """A named struct type (declared with `struct Name = {...}`)."""

    name: str

    def __str__(self):
        return self.name


# --------------------------------------------------------------------------
# Expressions
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    loc: Loc = field(default=(0, 0), compare=False)


@dataclass(frozen=True)
class EInt(Expr):
    val: int = 0


@dataclass(frozen=True)
class EFloat(Expr):
    val: float = 0.0


@dataclass(frozen=True)
class EBit(Expr):
    """'0 or '1 bit literal."""

    val: int = 0


@dataclass(frozen=True)
class EBool(Expr):
    val: bool = False


@dataclass(frozen=True)
class EString(Expr):
    """Only as print/error arguments."""

    val: str = ""


@dataclass(frozen=True)
class EVar(Expr):
    name: str = ""


@dataclass(frozen=True)
class EUn(Expr):
    """Unary: - ! ~"""

    op: str = "-"
    e: Optional[Expr] = None


@dataclass(frozen=True)
class EBin(Expr):
    """Binary: + - * / % ** << >> < <= > >= == != & ^ | && ||"""

    op: str = "+"
    a: Optional[Expr] = None
    b: Optional[Expr] = None


@dataclass(frozen=True)
class ECond(Expr):
    """if c then a else b (expression form)."""

    c: Optional[Expr] = None
    a: Optional[Expr] = None
    b: Optional[Expr] = None


@dataclass(frozen=True)
class ECall(Expr):
    """f(args) — user fun, ext fun, builtin, or a cast when `name` is a
    base-type name (int16(x), double(x), complex16(re, im))."""

    name: str = ""
    args: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class EIdx(Expr):
    """x[i] — single element."""

    arr: Optional[Expr] = None
    i: Optional[Expr] = None


@dataclass(frozen=True)
class ESlice(Expr):
    """x[i, n] — n elements from offset i; n must be static (the
    reference's slice form, SURVEY.md §0)."""

    arr: Optional[Expr] = None
    i: Optional[Expr] = None
    n: Optional[Expr] = None


@dataclass(frozen=True)
class EField(Expr):
    """x.f — struct field (also .re/.im on complex)."""

    e: Optional[Expr] = None
    f: str = ""


@dataclass(frozen=True)
class EArrLit(Expr):
    """{e1, e2, ...} array literal."""

    elems: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class EStructLit(Expr):
    """Name { f1 = e1, f2 = e2 } struct literal."""

    name: str = ""
    fields: Tuple[Tuple[str, Expr], ...] = ()


# --------------------------------------------------------------------------
# Statements (imperative bodies: fun bodies and do-blocks)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Stmt:
    loc: Loc = field(default=(0, 0), compare=False)


@dataclass(frozen=True)
class SVar(Stmt):
    """var x : t [:= e]"""

    name: str = ""
    ty: Optional[Ty] = None
    init: Optional[Expr] = None


@dataclass(frozen=True)
class SLet(Stmt):
    """let x [: t] = e — immutable binding."""

    name: str = ""
    ty: Optional[Ty] = None
    e: Optional[Expr] = None


@dataclass(frozen=True)
class SAssign(Stmt):
    """lval := e. `lval` is EVar / EIdx / ESlice / EField chain."""

    lval: Optional[Expr] = None
    e: Optional[Expr] = None


@dataclass(frozen=True)
class SIf(Stmt):
    c: Optional[Expr] = None
    then: Tuple[Stmt, ...] = ()
    els: Tuple[Stmt, ...] = ()


@dataclass(frozen=True)
class SFor(Stmt):
    """for i in [start, len] { body } — reference-style range: `len`
    iterations starting at `start`."""

    var: str = ""
    start: Optional[Expr] = None
    count: Optional[Expr] = None
    body: Tuple[Stmt, ...] = ()


@dataclass(frozen=True)
class SWhile(Stmt):
    c: Optional[Expr] = None
    body: Tuple[Stmt, ...] = ()


@dataclass(frozen=True)
class SReturn(Stmt):
    e: Optional[Expr] = None


@dataclass(frozen=True)
class SExpr(Stmt):
    """Expression statement (a call evaluated for effect, e.g. print)."""

    e: Optional[Expr] = None


# --------------------------------------------------------------------------
# Stream computations
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Comp:
    loc: Loc = field(default=(0, 0), compare=False)


@dataclass(frozen=True)
class CTake(Comp):
    pass


@dataclass(frozen=True)
class CTakes(Comp):
    n: Optional[Expr] = None


@dataclass(frozen=True)
class CEmit(Comp):
    e: Optional[Expr] = None


@dataclass(frozen=True)
class CEmits(Comp):
    """emits e — emit every element of array-valued e."""

    e: Optional[Expr] = None


@dataclass(frozen=True)
class CReturn(Comp):
    e: Optional[Expr] = None


@dataclass(frozen=True)
class CDo(Comp):
    """do { stmts } — imperative block as a unit-valued computer."""

    body: Tuple[Stmt, ...] = ()


@dataclass(frozen=True)
class CBind(Comp):
    """x <- c1 ; c2  (var=None for plain seq)."""

    var: Optional[str] = None
    var_ty: Optional[Ty] = None
    first: Optional[Comp] = None
    rest: Optional[Comp] = None


@dataclass(frozen=True)
class CVarDecl(Comp):
    """var x : t := e ; rest — stream-level mutable state."""

    name: str = ""
    ty: Optional[Ty] = None
    init: Optional[Expr] = None
    rest: Optional[Comp] = None


@dataclass(frozen=True)
class CLetDecl(Comp):
    """let x = e ; rest — stream-level immutable binding."""

    name: str = ""
    e: Optional[Expr] = None
    rest: Optional[Comp] = None


@dataclass(frozen=True)
class CLetComp(Comp):
    """let comp x = c ; rest — local computation binding."""

    name: str = ""
    c: Optional[Comp] = None
    rest: Optional[Comp] = None


@dataclass(frozen=True)
class CRepeat(Comp):
    body: Optional[Comp] = None


@dataclass(frozen=True)
class CMap(Comp):
    """map f — f names an expression function (user/ext/builtin)."""

    fname: str = ""


@dataclass(frozen=True)
class CPipe(Comp):
    """c1 >>> c2 (par=False) or c1 |>>>| c2 (par=True)."""

    up: Optional[Comp] = None
    down: Optional[Comp] = None
    par: bool = False


@dataclass(frozen=True)
class CIf(Comp):
    c: Optional[Expr] = None
    then: Optional[Comp] = None
    els: Optional[Comp] = None


@dataclass(frozen=True)
class CFor(Comp):
    """for i in [start, len] body — `len` iterations (computer)."""

    var: Optional[str] = None
    start: Optional[Expr] = None
    count: Optional[Expr] = None
    body: Optional[Comp] = None


@dataclass(frozen=True)
class CTimes(Comp):
    """times n body."""

    count: Optional[Expr] = None
    body: Optional[Comp] = None


@dataclass(frozen=True)
class CWhile(Comp):
    c: Optional[Expr] = None
    body: Optional[Comp] = None


@dataclass(frozen=True)
class CUntil(Comp):
    """do body until c — body runs at least once (reference `until`)."""

    c: Optional[Expr] = None
    body: Optional[Comp] = None


@dataclass(frozen=True)
class CCall(Comp):
    """name(args) — instantiate a comp function (inlined at elaboration,
    the reference inliner's role), or a zero-arg reference to a bound
    comp name."""

    name: str = ""
    args: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class CRead(Comp):
    """read[t] — stream source (driver-provided input)."""

    ty: Optional[Ty] = None


@dataclass(frozen=True)
class CWrite(Comp):
    """write[t] — stream sink (driver-consumed output)."""

    ty: Optional[Ty] = None


# --------------------------------------------------------------------------
# Top-level declarations
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Param:
    name: str
    ty: Optional[Ty]
    loc: Loc = field(default=(0, 0), compare=False)


@dataclass(frozen=True)
class Decl:
    loc: Loc = field(default=(0, 0), compare=False)


@dataclass(frozen=True)
class DFun(Decl):
    """fun f(params) [: t] { stmts } — expression function."""

    name: str = ""
    params: Tuple[Param, ...] = ()
    ret_ty: Optional[Ty] = None
    body: Tuple[Stmt, ...] = ()


@dataclass(frozen=True)
class DFunComp(Decl):
    """fun comp f(params) { comp } — computation function."""

    name: str = ""
    params: Tuple[Param, ...] = ()
    body: Optional[Comp] = None


@dataclass(frozen=True)
class DLet(Decl):
    """let x = e — top-level constant."""

    name: str = ""
    e: Optional[Expr] = None


@dataclass(frozen=True)
class DLetComp(Decl):
    """let comp x = c — top-level computation (main is one of these)."""

    name: str = ""
    c: Optional[Comp] = None


@dataclass(frozen=True)
class DExt(Decl):
    """ext fun f(params) : t — binding to the externals registry
    (the reference's SORA `ext` declarations, SURVEY.md §2.3)."""

    name: str = ""
    params: Tuple[Param, ...] = ()
    ret_ty: Optional[Ty] = None


@dataclass(frozen=True)
class DStruct(Decl):
    """struct Name = { f1: t1; f2: t2 }"""

    name: str = ""
    fields: Tuple[Tuple[str, Ty], ...] = ()


@dataclass(frozen=True)
class Program:
    decls: Tuple[Decl, ...] = ()


# --------------------------------------------------------------------------
# Canonical traversal helpers
#
# Every analysis that walks the surface AST (purity/bit-width inference
# in lutinfer, read/write sets for staged loops in eval, weight/effect
# scans in backend/hybrid) iterates children through THESE generators,
# so the node inventory lives in exactly one place. They raise on an
# unknown node class — a future statement/expression kind breaks the
# walkers loudly instead of being silently skipped (which would, e.g.,
# let an effectful block be jit-wrapped or drop a written cell from a
# staged-loop carry).
# --------------------------------------------------------------------------

_LEAF_EXPRS = (EInt, EFloat, EBit, EBool, EString, EVar)


def child_exprs(e: Optional[Expr]):
    """Direct sub-expressions of `e` (none for leaves/None)."""
    if e is None or isinstance(e, _LEAF_EXPRS):
        return
    if isinstance(e, EUn):
        kids = (e.e,)
    elif isinstance(e, EBin):
        kids = (e.a, e.b)
    elif isinstance(e, ECond):
        kids = (e.c, e.a, e.b)
    elif isinstance(e, ECall):
        kids = e.args
    elif isinstance(e, EIdx):
        kids = (e.arr, e.i)
    elif isinstance(e, ESlice):
        kids = (e.arr, e.i, e.n)
    elif isinstance(e, EField):
        kids = (e.e,)
    elif isinstance(e, EArrLit):
        kids = e.elems
    elif isinstance(e, EStructLit):
        kids = tuple(v for _, v in e.fields)
    else:
        raise TypeError(f"child_exprs: unknown expression node "
                        f"{type(e).__name__}")
    for k in kids:
        if k is not None:
            yield k


def iter_exprs(e: Optional[Expr]):
    """`e` and every expression beneath it, depth-first."""
    if e is None:
        return
    yield e
    for k in child_exprs(e):
        yield from iter_exprs(k)


def _ty_dim_exprs(ty: Optional[Ty]):
    """Array-dimension expressions inside a type annotation — they are
    READS (a sliced environment must ship `n` for `arr[n] double`)."""
    while isinstance(ty, TArr):
        if ty.n is not None:
            yield ty.n
        ty = ty.elem


def stmt_exprs(st: Stmt):
    """Expressions appearing directly in `st` (not in nested stmts),
    including array dimensions in declared types."""
    if isinstance(st, SVar):
        kids = (st.init,) + tuple(_ty_dim_exprs(st.ty))
    elif isinstance(st, SLet):
        kids = (st.e,) + tuple(_ty_dim_exprs(st.ty))
    elif isinstance(st, SAssign):
        kids = (st.lval, st.e)
    elif isinstance(st, SIf):
        kids = (st.c,)
    elif isinstance(st, SFor):
        kids = (st.start, st.count)
    elif isinstance(st, SWhile):
        kids = (st.c,)
    elif isinstance(st, (SReturn, SExpr)):
        kids = (st.e,)
    else:
        raise TypeError(f"stmt_exprs: unknown statement node "
                        f"{type(st).__name__}")
    for k in kids:
        if k is not None:
            yield k


def child_stmt_blocks(st: Stmt):
    """Nested statement tuples of `st`."""
    if isinstance(st, SIf):
        yield st.then
        yield st.els
    elif isinstance(st, (SFor, SWhile)):
        yield st.body


def iter_stmts(stmts):
    """Every statement in the body, depth-first (including nested)."""
    for st in stmts:
        yield st
        for blk in child_stmt_blocks(st):
            yield from iter_stmts(blk)


def iter_stmt_exprs(stmts):
    """Every expression anywhere in the body, depth-first."""
    for st in iter_stmts(stmts):
        for e in stmt_exprs(st):
            yield from iter_exprs(e)
