"""Textual frontend: Ziria-style surface syntax → core IR.

The missing half of the reference's compiler stack (SURVEY.md §2.1
lexer/parser/typecheck): `.zir` source files with the two-level
language — first-order imperative expressions + stream computations
composed with take/emit/map/repeat/`>>>`/`|>>>|` — parse, typecheck,
and elaborate into the same core IR the Python-embedded DSL builds,
then run on either backend (`interp` oracle or fused `jit`).

    from ziria_tpu.frontend import compile_source
    prog = compile_source('let comp main = read[int32] >>> '
                          'map incr >>> write[int32] '
                          'fun incr(x: int32): int32 { return x + 1 }')
    # prog.comp is a core-IR pipeline; prog.in_ty/out_ty drive the CLI
"""

from ziria_tpu.frontend.elab import (CompiledProgram, ElabError,
                                     compile_file, compile_source)
from ziria_tpu.frontend.eval import ZiriaRuntimeError
from ziria_tpu.frontend.lexer import LexError, tokenize
from ziria_tpu.frontend.parser import (ParseError, parse_comp, parse_expr,
                                       parse_program)
from ziria_tpu.frontend.typecheck import ZiriaTypeError

__all__ = [
    "CompiledProgram", "ElabError", "LexError", "ParseError",
    "ZiriaRuntimeError", "ZiriaTypeError", "compile_file",
    "compile_source", "parse_comp", "parse_expr", "parse_program",
    "tokenize",
]
