"""Lexer for the Ziria-style surface syntax.

Counterpart of the reference's `BlinkLexer` (SURVEY.md §2.1). Hand-rolled
maximal-munch scanner — no generator dependency — producing a flat token
list the recursive-descent parser (frontend/parser.py) walks.

Lexical syntax:
  - line comments: ``--`` (reference style) and ``//``; block ``{- -}``
  - bit literals ``'0`` / ``'1``; ints (decimal, ``0x`` hex); floats
    (``1.5``, ``2e-3``); double-quoted strings (print/error args)
  - multi-char operators, longest match first: ``|>>>|  >>>  :=  <-
    ==  !=  <=  >=  <<  >>  &&  ||  **``
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

KEYWORDS = frozenset({
    "fun", "comp", "let", "var", "ext", "struct", "in",
    "take", "takes", "emit", "emits", "return", "do", "seq",
    "repeat", "map", "if", "then", "else", "for", "while", "until",
    "times", "read", "write", "true", "false", "not",
    "print", "println", "error",
    # type names are keywords too (they double as cast functions)
    "bit", "bool", "int", "int8", "int16", "int32", "int64",
    "double", "complex", "complex16", "complex32", "arr",
})

# longest-match-first operator/punct table
_OPS = (
    "|>>>|", ">>>",
    ":=", "<-", "==", "!=", "<=", ">=", "<<", ">>", "&&", "||", "**",
    "(", ")", "[", "]", "{", "}", ",", ";", ":", ".",
    "+", "-", "*", "/", "%", "<", ">", "=", "&", "|", "^", "~", "!",
)


@dataclass(frozen=True)
class Token:
    kind: str          # 'id' | 'kw' | 'int' | 'float' | 'bit' | 'str'
                       # | 'op' | 'eof'
    text: str
    line: int
    col: int

    @property
    def loc(self) -> Tuple[int, int]:
        return (self.line, self.col)

    def __repr__(self):
        return f"{self.kind}({self.text!r})@{self.line}:{self.col}"


class LexError(SyntaxError):
    pass


def _err(src_name: str, line: int, col: int, msg: str) -> LexError:
    return LexError(f"{src_name}:{line}:{col}: {msg}")


def tokenize(src: str, src_name: str = "<input>") -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(src)
    line, col = 1, 1

    def advance(k: int) -> None:
        nonlocal i, line, col
        for _ in range(k):
            if src[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = src[i]
        # whitespace
        if c in " \t\r\n":
            advance(1)
            continue
        # comments
        if src.startswith("--", i) or src.startswith("//", i):
            j = src.find("\n", i)
            advance((j if j >= 0 else n) - i)
            continue
        if src.startswith("{-", i):
            depth, j = 1, i + 2
            while j < n and depth:
                if src.startswith("{-", j):
                    depth += 1
                    j += 2
                elif src.startswith("-}", j):
                    depth -= 1
                    j += 2
                else:
                    j += 1
            if depth:
                raise _err(src_name, line, col, "unterminated {- comment")
            advance(j - i)
            continue
        # bit literal
        if c == "'" and i + 1 < n and src[i + 1] in "01":
            toks.append(Token("bit", src[i + 1], line, col))
            advance(2)
            continue
        # string
        if c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"',
                                "\\": "\\"}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise _err(src_name, line, col, "unterminated string")
            toks.append(Token("str", "".join(buf), line, col))
            advance(j + 1 - i)
            continue
        # numbers
        if c.isdigit():
            j = i
            if src.startswith("0x", i) or src.startswith("0X", i):
                j = i + 2
                while j < n and (src[j].isdigit()
                                 or src[j].lower() in "abcdef"):
                    j += 1
                if j == i + 2:
                    raise _err(src_name, line, col,
                               "hex literal needs digits after 0x")
                toks.append(Token("int", src[i:j], line, col))
                advance(j - i)
                continue
            while j < n and src[j].isdigit():
                j += 1
            is_float = False
            # a '.' is part of the number only if a digit follows
            # (so `0..` or `x.f` stay separate tokens)
            if j < n and src[j] == "." and j + 1 < n and src[j + 1].isdigit():
                is_float = True
                j += 1
                while j < n and src[j].isdigit():
                    j += 1
            if j < n and src[j] in "eE":
                k = j + 1
                if k < n and src[k] in "+-":
                    k += 1
                if k < n and src[k].isdigit():
                    is_float = True
                    j = k
                    while j < n and src[j].isdigit():
                        j += 1
            toks.append(Token("float" if is_float else "int",
                              src[i:j], line, col))
            advance(j - i)
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] in "_'"):
                j += 1
            word = src[i:j]
            toks.append(Token("kw" if word in KEYWORDS else "id",
                              word, line, col))
            advance(j - i)
            continue
        # operators / punctuation
        for op in _OPS:
            if src.startswith(op, i):
                toks.append(Token("op", op, line, col))
                advance(len(op))
                break
        else:
            raise _err(src_name, line, col, f"unexpected character {c!r}")

    toks.append(Token("eof", "", line, col))
    return toks
