"""Recursive-descent parser for the Ziria-style surface syntax.

Counterpart of the reference's `BlinkParseComp.hs`/`BlinkParseExpr.hs`
(SURVEY.md §2.1), hand-rolled instead of Parsec. Two-level grammar:

Top level::

    fun comp NAME(params) { C }        -- computation function
    fun NAME(params) [: ty] { stmts }  -- expression function
    let comp NAME = C                  -- computation binding (main!)
    let NAME = E                       -- constant
    ext fun NAME(params) : ty          -- external binding
    struct NAME = { f: ty; ... }

Computations (C), loosest-binding first::

    C  := S ( '>>>' S | '|>>>|' S )*
    S  := '{' item* '}' | 'seq' '{' item* '}' | atom
    item := [NAME | '(' NAME ':' ty ')'] '<-' C ';'
          | 'var' NAME ':' ty [':=' E] ';'
          | 'let' 'comp' NAME '=' C ';'
          | 'let' NAME '=' E ';'
          | C ';'
    atom := take | takes E | emit E | emits E | return E | do '{' stmts '}'
          | repeat S | map NAME | if E then S [else S]
          | for NAME in '[' E ',' E ']' S | times E S
          | while '(' E ')' S | do S until '(' E ')'
          | read ['[' ty ']'] | write ['[' ty ']']
          | NAME ['(' E,* ')'] | '(' C ')'

Expressions (E) are C-precedence with Ziria extras: bit literals
``'0/'1``, array literals ``{a, b}``, slices ``x[i,n]``, casts via
type-name calls (``int16(e)``), ``if E then E else E``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ziria_tpu.frontend import ast as A
from ziria_tpu.frontend.lexer import Token, tokenize

_BASE_TYPES = ("bit", "bool", "int", "int8", "int16", "int32", "int64",
               "double", "complex", "complex16", "complex32")

# binary operator precedence (higher binds tighter); all left-assoc
_BINOPS = {
    "||": 1, "&&": 2,
    "|": 3, "^": 4, "&": 5,
    "==": 6, "!=": 6,
    "<": 7, "<=": 7, ">": 7, ">=": 7,
    "<<": 8, ">>": 8,
    "+": 9, "-": 9,
    "*": 10, "/": 10, "%": 10,
    "**": 11,
}


class ParseError(SyntaxError):
    pass


class Parser:
    def __init__(self, src: str, src_name: str = "<input>"):
        self.toks: List[Token] = tokenize(src, src_name)
        self.pos = 0
        self.src_name = src_name

    # ------------------------------------------------------------- plumbing

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.pos + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.peek()
        self.pos += 1
        return t

    def at(self, kind: str, text: Optional[str] = None, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == kind and (text is None or t.text == text)

    def at_kw(self, *words: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == "kw" and t.text in words

    def at_op(self, *ops: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == "op" and t.text in ops

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        t = self.peek()
        if t.kind != kind or (text is not None and t.text != text):
            want = text or kind
            raise self.err(f"expected {want!r}, got {t.text or t.kind!r}")
        return self.next()

    def err(self, msg: str) -> ParseError:
        t = self.peek()
        return ParseError(f"{self.src_name}:{t.line}:{t.col}: {msg}")

    def _skip_semis(self) -> None:
        while self.at_op(";"):
            self.next()

    # ------------------------------------------------------------- types

    def parse_type(self) -> A.Ty:
        t = self.peek()
        if t.kind == "kw" and t.text in _BASE_TYPES:
            self.next()
            return A.TBase(t.text)
        if t.kind == "kw" and t.text == "arr":
            self.next()
            n = None
            if self.at_op("["):
                self.next()
                n = self.parse_expr()
                self.expect("op", "]")
            elem = self.parse_type()
            return A.TArr(n, elem)
        if t.kind == "id":
            self.next()
            return A.TStruct(t.text)
        raise self.err(f"expected a type, got {t.text!r}")

    # ------------------------------------------------------------- exprs

    def parse_expr(self) -> A.Expr:
        if self.at_kw("if"):
            loc = self.next().loc
            c = self.parse_expr()
            self.expect("kw", "then")
            a = self.parse_expr()
            self.expect("kw", "else")
            b = self.parse_expr()
            return A.ECond(loc, c, a, b)
        return self._bin_expr(0)

    def _bin_expr(self, min_prec: int) -> A.Expr:
        lhs = self._unary()
        while True:
            t = self.peek()
            if t.kind != "op" or t.text not in _BINOPS:
                break
            prec = _BINOPS[t.text]
            if prec < min_prec:
                break
            self.next()
            # left-assoc: parse rhs at prec+1
            rhs = self._bin_expr(prec + 1)
            lhs = A.EBin(t.loc, t.text, lhs, rhs)
        return lhs

    def _unary(self) -> A.Expr:
        t = self.peek()
        if self.at_op("-", "~", "!"):
            self.next()
            return A.EUn(t.loc, t.text, self._unary())
        if self.at_kw("not"):
            self.next()
            return A.EUn(t.loc, "!", self._unary())
        return self._postfix(self._atom())

    def _postfix(self, e: A.Expr) -> A.Expr:
        while True:
            if self.at_op("["):
                loc = self.next().loc
                i = self.parse_expr()
                if self.at_op(","):
                    self.next()
                    n = self.parse_expr()
                    self.expect("op", "]")
                    e = A.ESlice(loc, e, i, n)
                else:
                    self.expect("op", "]")
                    e = A.EIdx(loc, e, i)
            elif self.at_op(".") and self.peek(1).kind in ("id", "kw"):
                loc = self.next().loc
                f = self.next().text
                e = A.EField(loc, e, f)
            else:
                return e

    def _call_args(self) -> Tuple[A.Expr, ...]:
        self.expect("op", "(")
        args: List[A.Expr] = []
        while not self.at_op(")"):
            if self.at("str"):
                t = self.next()
                args.append(A.EString(t.loc, t.text))
            else:
                args.append(self.parse_expr())
            if self.at_op(","):
                self.next()
        self.expect("op", ")")
        return tuple(args)

    def _atom(self) -> A.Expr:
        t = self.peek()
        if t.kind == "int":
            self.next()
            return A.EInt(t.loc, int(t.text, 0))
        if t.kind == "float":
            self.next()
            return A.EFloat(t.loc, float(t.text))
        if t.kind == "bit":
            self.next()
            return A.EBit(t.loc, int(t.text))
        if t.kind == "str":
            self.next()
            return A.EString(t.loc, t.text)
        if self.at_kw("true"):
            self.next()
            return A.EBool(t.loc, True)
        if self.at_kw("false"):
            self.next()
            return A.EBool(t.loc, False)
        # casts / constructor calls on type keywords: int16(e), complex(a,b)
        if t.kind == "kw" and t.text in _BASE_TYPES and self.at_op("(", k=1):
            self.next()
            return A.ECall(t.loc, t.text, self._call_args())
        if t.kind == "id":
            self.next()
            if self.at_op("("):
                return A.ECall(t.loc, t.text, self._call_args())
            # struct literal: Name { f = e, ... } — only when the brace is
            # followed by `field =` (plain `=`; `==` lexes as one token),
            # so comp forms like `times n { x <- ... }` aren't swallowed
            if (self.at_op("{") and self.at("id", k=1)
                    and self.at_op("=", k=2)):
                self.next()
                fields: List[Tuple[str, A.Expr]] = []
                while not self.at_op("}"):
                    fn = self.expect("id").text
                    self.expect("op", "=")
                    fields.append((fn, self.parse_expr()))
                    if self.at_op(",") or self.at_op(";"):
                        self.next()
                self.expect("op", "}")
                return A.EStructLit(t.loc, t.text, tuple(fields))
            return A.EVar(t.loc, t.text)
        if self.at_op("{"):
            self.next()
            elems: List[A.Expr] = []
            while not self.at_op("}"):
                elems.append(self.parse_expr())
                if self.at_op(","):
                    self.next()
            self.expect("op", "}")
            return A.EArrLit(t.loc, tuple(elems))
        if self.at_op("("):
            self.next()
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        raise self.err(f"expected an expression, got {t.text or t.kind!r}")

    # ------------------------------------------------------------- stmts

    def parse_stmt_block(self) -> Tuple[A.Stmt, ...]:
        """'{' stmts '}' or a single statement."""
        if self.at_op("{"):
            self.next()
            out: List[A.Stmt] = []
            self._skip_semis()
            while not self.at_op("}"):
                out.append(self.parse_stmt())
                self._skip_semis()
            self.expect("op", "}")
            return tuple(out)
        return (self.parse_stmt(),)

    def parse_stmt(self) -> A.Stmt:
        t = self.peek()
        if self.at_kw("var"):
            self.next()
            name = self.expect("id").text
            self.expect("op", ":")
            ty = self.parse_type()
            init = None
            if self.at_op(":="):
                self.next()
                init = self.parse_expr()
            return A.SVar(t.loc, name, ty, init)
        if self.at_kw("let"):
            self.next()
            name = self.expect("id").text
            ty = None
            if self.at_op(":"):
                self.next()
                ty = self.parse_type()
            self.expect("op", "=")
            return A.SLet(t.loc, name, ty, self.parse_expr())
        if self.at_kw("if"):
            self.next()
            c = self.parse_expr()
            self.expect("kw", "then")
            then = self.parse_stmt_block()
            els: Tuple[A.Stmt, ...] = ()
            if self.at_kw("else"):
                self.next()
                els = self.parse_stmt_block()
            return A.SIf(t.loc, c, then, els)
        if self.at_kw("for"):
            self.next()
            var = self.expect("id").text
            self.expect("kw", "in")
            self.expect("op", "[")
            start = self.parse_expr()
            self.expect("op", ",")
            count = self.parse_expr()
            self.expect("op", "]")
            return A.SFor(t.loc, var, start, count, self.parse_stmt_block())
        if self.at_kw("while"):
            self.next()
            self.expect("op", "(")
            c = self.parse_expr()
            self.expect("op", ")")
            return A.SWhile(t.loc, c, self.parse_stmt_block())
        if self.at_kw("return"):
            self.next()
            return A.SReturn(t.loc, self.parse_expr())
        if self.at_kw("print", "println", "error"):
            kw = self.next().text
            args = self._call_args() if self.at_op("(") else self._bare_args()
            return A.SExpr(t.loc, A.ECall(t.loc, kw, args))
        # assignment or expression statement: parse a full expression
        # unconditionally — ':=' is not a binary operator, so parse_expr
        # stops right before it, and non-assignment statements like
        # `f(x) + g(y);` parse instead of erroring at the operator
        e = self.parse_expr()
        if self.at_op(":="):
            self.next()
            if not isinstance(e, (A.EVar, A.EIdx, A.ESlice, A.EField)):
                raise self.err("left side of := must be a variable, "
                               "element, slice, or field")
            return A.SAssign(t.loc, e, self.parse_expr())
        return A.SExpr(t.loc, e)

    def _bare_args(self) -> Tuple[A.Expr, ...]:
        """print "x", e, ... — unparenthesized argument list."""
        args: List[A.Expr] = []
        while True:
            if self.at("str"):
                tt = self.next()
                args.append(A.EString(tt.loc, tt.text))
            else:
                args.append(self.parse_expr())
            if self.at_op(","):
                self.next()
                continue
            return tuple(args)

    # ------------------------------------------------------------- comps

    def parse_comp(self) -> A.Comp:
        """C := S ( >>> S | |>>>| S )*  — left-assoc pipe chain."""
        c = self.parse_comp_seg()
        while self.at_op(">>>", "|>>>|"):
            t = self.next()
            rhs = self.parse_comp_seg()
            c = A.CPipe(t.loc, c, rhs, par=(t.text == "|>>>|"))
        return c

    def parse_comp_seg(self) -> A.Comp:
        if self.at_kw("seq") and self.at_op("{", k=1):
            self.next()
        if self.at_op("{"):
            return self._comp_block()
        return self._comp_atom()

    def _comp_block(self) -> A.Comp:
        """'{' item* '}' — right-nested bind/decl chain."""
        open_tok = self.expect("op", "{")
        items: List = []   # ('bind', loc, var, ty, comp) | ('var',...) etc.
        self._skip_semis()
        while not self.at_op("}"):
            t = self.peek()
            if self.at_kw("var"):
                self.next()
                name = self.expect("id").text
                self.expect("op", ":")
                ty = self.parse_type()
                init = None
                if self.at_op(":="):
                    self.next()
                    init = self.parse_expr()
                items.append(("var", t.loc, name, ty, init))
            elif self.at_kw("let") and self.at_kw("comp", k=1):
                self.next()
                self.next()
                name = self.expect("id").text
                self.expect("op", "=")
                items.append(("letcomp", t.loc, name, self.parse_comp()))
            elif self.at_kw("let"):
                self.next()
                name = self.expect("id").text
                self.expect("op", "=")
                items.append(("let", t.loc, name, self.parse_expr()))
            else:
                var, var_ty = self._try_bind_head()
                c = self.parse_comp()
                items.append(("bind", t.loc, var, var_ty, c))
            self._skip_semis()
        self.expect("op", "}")
        if not items:
            raise ParseError(
                f"{self.src_name}:{open_tok.line}:{open_tok.col}: "
                f"empty computation block")

        # fold right: last item is the block's value position
        last = items[-1]
        if last[0] != "bind":
            raise self.err("a computation block must end with a "
                           "computation, not a declaration")
        if last[2] is not None:
            raise ParseError(
                f"{self.src_name}:{last[1][0]}:{last[1][1]}: the final "
                f"computation in a block cannot be a bind (its value "
                f"would be unused)")
        comp: A.Comp = last[4]
        for it in reversed(items[:-1]):
            if it[0] == "bind":
                comp = A.CBind(it[1], it[2], it[3], it[4], comp)
            elif it[0] == "var":
                comp = A.CVarDecl(it[1], it[2], it[3], it[4], comp)
            elif it[0] == "let":
                comp = A.CLetDecl(it[1], it[2], it[3], comp)
            elif it[0] == "letcomp":
                comp = A.CLetComp(it[1], it[2], it[3], comp)
        return comp

    def _try_bind_head(self):
        """Recognize `NAME <-` or `(NAME : ty) <-`; returns (var, ty)."""
        if self.at("id") and self.at_op("<-", k=1):
            var = self.next().text
            self.next()
            return var, None
        if (self.at_op("(") and self.peek(1).kind == "id"
                and self.at_op(":", k=2)):
            save = self.pos
            self.next()
            var = self.next().text
            self.next()
            try:
                ty = self.parse_type()
            except ParseError:
                self.pos = save
                return None, None
            if self.at_op(")") and self.at_op("<-", k=1):
                self.next()
                self.next()
                return var, ty
            self.pos = save
        return None, None

    def _comp_atom(self) -> A.Comp:
        t = self.peek()
        if self.at_kw("take"):
            self.next()
            return A.CTake(t.loc)
        if self.at_kw("takes"):
            self.next()
            return A.CTakes(t.loc, self.parse_expr())
        if self.at_kw("emit"):
            self.next()
            return A.CEmit(t.loc, self.parse_expr())
        if self.at_kw("emits"):
            self.next()
            return A.CEmits(t.loc, self.parse_expr())
        if self.at_kw("return"):
            self.next()
            return A.CReturn(t.loc, self.parse_expr())
        if self.at_kw("do"):
            self.next()
            if self.at_op("{"):
                body = self.parse_stmt_block()
                if self.at_kw("until"):   # do S until (E)
                    return self._finish_until(t, A.CDo(t.loc, body))
                return A.CDo(t.loc, body)
            seg = self.parse_comp_seg()
            return self._finish_until(t, seg)
        if self.at_kw("repeat"):
            self.next()
            return A.CRepeat(t.loc, self.parse_comp_seg())
        if self.at_kw("map"):
            self.next()
            return A.CMap(t.loc, self.expect("id").text)
        if self.at_kw("if"):
            self.next()
            c = self.parse_expr()
            self.expect("kw", "then")
            then = self.parse_comp_arm()
            els = None
            if self.at_kw("else"):
                self.next()
                els = self.parse_comp_arm()
            return A.CIf(t.loc, c, then, els)
        if self.at_kw("for"):
            self.next()
            var = self.expect("id").text
            self.expect("kw", "in")
            self.expect("op", "[")
            start = self.parse_expr()
            self.expect("op", ",")
            count = self.parse_expr()
            self.expect("op", "]")
            return A.CFor(t.loc, var, start, count, self.parse_comp_seg())
        if self.at_kw("times"):
            self.next()
            count = self.parse_expr()
            return A.CTimes(t.loc, count, self.parse_comp_seg())
        if self.at_kw("while"):
            self.next()
            self.expect("op", "(")
            c = self.parse_expr()
            self.expect("op", ")")
            return A.CWhile(t.loc, c, self.parse_comp_seg())
        if self.at_kw("until"):
            # prefix form: until (E) S — body runs, then the condition is
            # checked (at-least-once loop, the reference's `until`)
            self.next()
            self.expect("op", "(")
            c = self.parse_expr()
            self.expect("op", ")")
            return A.CUntil(t.loc, c, self.parse_comp_seg())
        if self.at_kw("read"):
            self.next()
            ty = None
            if self.at_op("["):
                self.next()
                ty = self.parse_type()
                self.expect("op", "]")
            return A.CRead(t.loc, ty)
        if self.at_kw("write"):
            self.next()
            ty = None
            if self.at_op("["):
                self.next()
                ty = self.parse_type()
                self.expect("op", "]")
            return A.CWrite(t.loc, ty)
        if t.kind == "id":
            self.next()
            if self.at_op("("):
                return A.CCall(t.loc, t.text, self._call_args())
            return A.CCall(t.loc, t.text, ())
        if self.at_op("("):
            self.next()
            c = self.parse_comp()
            self.expect("op", ")")
            return c
        raise self.err(
            f"expected a computation, got {t.text or t.kind!r}")

    def parse_comp_arm(self) -> A.Comp:
        """An if-arm: a segment, possibly itself a pipe in parens."""
        return self.parse_comp_seg()

    def _finish_until(self, t: Token, body: A.Comp) -> A.Comp:
        self.expect("kw", "until")
        self.expect("op", "(")
        c = self.parse_expr()
        self.expect("op", ")")
        return A.CUntil(t.loc, c, body)

    # ------------------------------------------------------------- decls

    def _params(self) -> Tuple[A.Param, ...]:
        self.expect("op", "(")
        ps: List[A.Param] = []
        while not self.at_op(")"):
            t = self.expect("id")
            ty = None
            if self.at_op(":"):
                self.next()
                ty = self.parse_type()
            ps.append(A.Param(t.text, ty, t.loc))
            if self.at_op(","):
                self.next()
        self.expect("op", ")")
        return tuple(ps)

    def parse_program(self) -> A.Program:
        decls: List[A.Decl] = []
        self._skip_semis()
        while not self.at("eof"):
            decls.append(self.parse_decl())
            self._skip_semis()
        return A.Program(tuple(decls))

    def parse_decl(self) -> A.Decl:
        t = self.peek()
        if self.at_kw("fun") and self.at_kw("comp", k=1):
            self.next()
            self.next()
            name = self.expect("id").text
            params = self._params()
            body = self.parse_comp_seg()
            return A.DFunComp(t.loc, name, params, body)
        if self.at_kw("fun"):
            self.next()
            name = self.expect("id").text
            params = self._params()
            ret = None
            if self.at_op(":"):
                self.next()
                ret = self.parse_type()
            body = self.parse_stmt_block()
            return A.DFun(t.loc, name, params, ret, body)
        if self.at_kw("ext"):
            self.next()
            self.expect("kw", "fun")
            name = self.expect("id").text
            params = self._params()
            ret = None
            if self.at_op(":"):
                self.next()
                ret = self.parse_type()
            return A.DExt(t.loc, name, params, ret)
        if self.at_kw("let") and self.at_kw("comp", k=1):
            self.next()
            self.next()
            name = self.expect("id").text
            self.expect("op", "=")
            return A.DLetComp(t.loc, name, self.parse_comp())
        if self.at_kw("let"):
            self.next()
            name = self.expect("id").text
            self.expect("op", "=")
            return A.DLet(t.loc, name, self.parse_expr())
        if self.at_kw("struct"):
            self.next()
            name = self.expect("id").text
            if self.at_op("="):
                self.next()
            self.expect("op", "{")
            fields: List[Tuple[str, A.Ty]] = []
            while not self.at_op("}"):
                fn = self.expect("id").text
                self.expect("op", ":")
                fields.append((fn, self.parse_type()))
                if self.at_op(";") or self.at_op(","):
                    self.next()
            self.expect("op", "}")
            return A.DStruct(t.loc, name, tuple(fields))
        raise self.err(
            f"expected a declaration (fun/let/ext/struct), got "
            f"{t.text or t.kind!r}")


def parse_program(src: str, src_name: str = "<input>") -> A.Program:
    return Parser(src, src_name).parse_program()


def parse_comp(src: str, src_name: str = "<input>") -> A.Comp:
    p = Parser(src, src_name)
    c = p.parse_comp()
    p.expect("eof")
    return c


def parse_expr(src: str, src_name: str = "<input>") -> A.Expr:
    p = Parser(src, src_name)
    e = p.parse_expr()
    p.expect("eof")
    return e
