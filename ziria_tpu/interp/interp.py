"""Streaming interpreter — the semantic oracle.

Executes the full IR item-at-a-time with numpy values, including all the
dynamic constructs the jit backend refuses (While, dynamic For counts,
value-dependent Branch, LetRef). Plays the role the reference's
compile-time interpreter / partial evaluator plays as a reference
semantics for testing (SURVEY.md §2.1 `Interpreter.hs`, §4): every fused
jit lowering must produce output equal (to tolerance) to this interpreter
on golden inputs.

Implementation: each component runs as a Python generator that *yields*
emitted items and *returns* its control value; `take` pulls from a
`source()` thunk. Upstream termination propagates as an `UpstreamDone`
exception carrying the terminating component's value, which gives exactly
the reference semantics for `>>>`: the composite terminates, with the
value of whichever side terminated first.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ziria_tpu.core import ir
from ziria_tpu.core.ir import Env, eval_expr


class UpstreamDone(Exception):
    """Raised by a `source()` when the upstream computer terminated (or
    input hit EOF); carries the terminating value. `token` identifies which
    Pipe's upstream terminated, so that exact Pipe node catches it (and
    terminates locally with the value — reference `>>>` semantics) while
    outer-input EOF propagates all the way out."""

    def __init__(self, value: Any = None, token: Any = None):
        super().__init__("upstream terminated")
        self.value = value
        self.token = token


class Source:
    """Pull-source with pushback: the handle every `take` in a stream
    level shares. Chunked state machines (backend/chunked.py) bulk-pull
    a window of items, let the compiled step consume what it can, and
    `push_back` the unconsumed tail — which MUST remain visible to
    whatever takes next in the same stream level, hence a shared object
    rather than a bare closure. The first `UpstreamDone` is LATCHED
    (value + token) and re-raised on every later pull once pushed-back
    items drain: a re-pull of an exhausted generator would raise a
    fresh StopIteration carrying None, silently dropping the upstream
    computer's return value the original exception carried."""

    __slots__ = ("_pull", "_back", "_pending")

    def __init__(self, pull: Callable[[], Any]):
        self._pull = pull
        self._back: List[Any] = []
        self._pending: Optional[UpstreamDone] = None

    def __call__(self):
        if self._back:
            return self._back.pop()
        if self._pending is not None:
            raise UpstreamDone(self._pending.value, self._pending.token)
        try:
            return self._pull()
        except UpstreamDone as e:
            self._pending = e
            raise

    def push_back(self, items) -> None:
        """Re-enqueue `items` so the FIRST of them is the next pulled."""
        self._back.extend(reversed(list(items)))

    def pending(self) -> int:
        """Items pulled from upstream but pushed back (not yet re-taken)."""
        return len(self._back)

    def pull_block(self, n: int):
        """Pull up to `n` items; returns (items, eof). `eof` means the
        underlying stream raised UpstreamDone before `n` items arrived
        (the exception is latched and re-raises, with its original
        value/token, on the next pull past the buffered items)."""
        items: List[Any] = []
        while len(items) < n and self._back:
            items.append(self._back.pop())
        if self._pending is not None:
            return items, True
        try:
            while len(items) < n:
                items.append(self._pull())
        except UpstreamDone as e:
            self._pending = e
            return items, True
        return items, False


def _run(comp: ir.Comp, env: Env, source: Callable[[], Any], xp=np):
    """Generator: yields emitted items; returns the control value."""
    rg = getattr(comp, "run_gen", None)
    if rg is not None:
        # extension nodes (backend/chunked._ChunkLoop) drive themselves
        return (yield from rg(env, source, xp))

    if isinstance(comp, ir.Take):
        return source()
        yield  # pragma: no cover — makes this a generator

    if isinstance(comp, ir.Takes):
        if isinstance(source, Source):
            items, _eof = source.pull_block(comp.n)
            if len(items) < comp.n:
                source()  # re-raises the underlying UpstreamDone
        else:
            items = [source() for _ in range(comp.n)]
        return xp.stack([xp.asarray(x) for x in items])
        yield  # pragma: no cover

    if isinstance(comp, ir.Emit):
        yield eval_expr(comp.expr, env)
        return None

    if isinstance(comp, ir.Emits):
        arr = xp.asarray(eval_expr(comp.expr, env))
        if arr.ndim == 0 or arr.shape[0] != comp.n:
            raise ValueError(
                f"emits: declared n={comp.n} but expression has shape "
                f"{arr.shape}")
        for k in range(comp.n):
            yield arr[k]
        return None

    if isinstance(comp, ir.Return):
        return eval_expr(comp.expr, env)
        yield  # pragma: no cover

    if isinstance(comp, ir.Bind):
        v = yield from _run(comp.first, env, source, xp)
        if comp.var is not None:
            env = env.child()
            env.bind(comp.var, v)
        return (yield from _run(comp.rest, env, source, xp))

    if isinstance(comp, ir.LetRef):
        env = env.child()
        env.bind_ref(comp.var, eval_expr(comp.init, env))
        return (yield from _run(comp.body, env, source, xp))

    if isinstance(comp, ir.Assign):
        env.set(comp.var, eval_expr(comp.expr, env))
        return None
        yield  # pragma: no cover

    if isinstance(comp, (ir.Map, ir.MapAccum, ir.JaxBlock)):
        stateful = not isinstance(comp, ir.Map)
        state = comp.init_state() if stateful else None
        while True:
            if comp.in_arity == 1:
                x = source()
            else:
                x = xp.stack([xp.asarray(source())
                              for _ in range(comp.in_arity)])
            if stateful:
                state, y = comp.f(state, x)
            else:
                y = comp.f(x)
            if comp.out_arity == 1:
                yield y
            else:
                y = xp.asarray(y)
                for k in range(comp.out_arity):
                    yield y[k]

    if isinstance(comp, ir.Repeat):
        from ziria_tpu.core.card import CCard, cardinality
        c = cardinality(comp.body)
        if isinstance(c, CCard) and c.take == 0 and c.emit == 0:
            raise ValueError(
                "repeat of a computation with no stream I/O diverges "
                f"(body {comp.body.label()} has cardinality (0, 0))")
        # Runtime guard for dynamically-pure bodies the static check can't
        # see (e.g. a For with dynamic count 0): an iteration that neither
        # takes nor emits would loop forever without ever yielding control.
        takes_seen = [0]

        def counting_pull():
            takes_seen[0] += 1
            return source()

        # one Source for the whole repeat: pushback from a chunked loop
        # in one iteration stays visible to the next iteration's takes
        body_source = Source(counting_pull)

        while True:
            # net consumption = pulls minus still-pushed-back items, so a
            # bulk-pull-then-push-back cycle doesn't fake progress
            before = takes_seen[0] - body_source.pending()
            emitted = False
            it = _run(comp.body, env, body_source, xp)
            try:
                while True:
                    item = next(it)
                    emitted = True
                    yield item
            except StopIteration:
                pass
            if not emitted and takes_seen[0] - body_source.pending() == before:
                raise ValueError(
                    "repeat body made no stream progress in an iteration "
                    f"(body {comp.body.label()}): diverges")

    if isinstance(comp, ir.For):
        n = int(eval_expr(comp.count, env))
        v = None
        for i in range(n):
            e = env
            if comp.var is not None:
                e = env.child()
                e.bind(comp.var, i)
            v = yield from _run(comp.body, e, source, xp)
        return v

    if isinstance(comp, ir.While):
        v = None
        while bool(eval_expr(comp.cond, env)):
            v = yield from _run(comp.body, env, source, xp)
        return v

    if isinstance(comp, ir.Branch):
        tgt = comp.then if bool(eval_expr(comp.cond, env)) else comp.els
        return (yield from _run(tgt, env, source, xp))

    if isinstance(comp, (ir.Pipe, ir.ParPipe)):
        # ParPipe is semantically identical to Pipe here (the reference's
        # |>>>| must produce output identical to >>>; SURVEY.md §4).
        up_gen = _run(comp.up, env, source, xp)
        token = object()  # identifies THIS pipe's upstream termination

        def down_pull():
            try:
                return next(up_gen)
            except StopIteration as e:
                raise UpstreamDone(e.value, token=token) from None

        down_source = Source(down_pull)

        # `>>>` terminates as soon as either side does, with that side's
        # value: downstream termination is a plain generator return;
        # upstream termination arrives as UpstreamDone tagged with our
        # token and is caught HERE (an enclosing Bind continues with the
        # value). Untagged/foreign UpstreamDone = outer input EOF or an
        # outer pipe's upstream — propagate.
        try:
            return (yield from _run(comp.down, env, down_source, xp))
        except UpstreamDone as e:
            if e.token is token:
                return e.value
            raise

    raise TypeError(f"interpreter: unknown IR node {type(comp).__name__}")


class Result:
    """Outcome of running a computation over a finite input."""

    def __init__(self, outputs: List[Any], value: Any, consumed: int,
                 terminated_by: str):
        self.outputs = outputs
        self.value = value
        self.consumed = consumed
        self.terminated_by = terminated_by  # "computer" | "eof" | "limit"

    def out_array(self) -> np.ndarray:
        if not self.outputs:
            return np.empty((0,))
        return np.stack([np.asarray(o) for o in self.outputs])


def run(comp: ir.Comp, inputs: Iterable[Any] = (),
        max_out: Optional[int] = None, env: Optional[Env] = None) -> Result:
    """Run `comp` over `inputs` (any iterable of items).

    Stops when the computation terminates, input is exhausted while the
    computation takes (reference EOF semantics), or `max_out` outputs have
    been produced (needed for infinite transformers).
    """
    it = iter(inputs)
    consumed = [0]

    def pull():
        try:
            x = next(it)
        except StopIteration:
            raise UpstreamDone(None) from None
        consumed[0] += 1
        return x

    source = Source(pull)
    outputs: List[Any] = []
    gen = _run(comp, env or Env(), source)
    try:
        while True:
            if max_out is not None and len(outputs) >= max_out:
                return Result(outputs, None,
                              consumed[0] - source.pending(), "limit")
            outputs.append(next(gen))
    except StopIteration as e:
        return Result(outputs, e.value,
                      consumed[0] - source.pending(), "computer")
    except UpstreamDone as e:
        return Result(outputs, e.value,
                      consumed[0] - source.pending(), "eof")
