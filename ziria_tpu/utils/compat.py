"""Version-compat shims for the jax surface this image ships.

One copy, imported by every consumer — the alternative (per-module
try/except blocks) already drifted once: only one of the two copies
mapped the renamed replication-check kwarg, so the other would have
raised on the older jax the moment it started passing it.
"""

from __future__ import annotations

try:                      # newer jax exposes it at top level
    from jax import shard_map
except ImportError:       # this image's jax: experimental namespace,
    # where the replication-check kwarg is still called check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *args, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, *args, **kw)

__all__ = ["shard_map"]
