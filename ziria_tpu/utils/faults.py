"""Seeded, scoped fault injection — the chaos layer of the streaming
runtime (docs/robustness.md).

A streaming fleet that must survive "millions of users" meets bad
input and flaky devices as a matter of course: a NaN slab from a
misbehaving client, a truncated push from a dropped socket, a
transient ``XlaRuntimeError`` when the device tunnel flaps, a dispatch
that simply hangs. None of those are reproducible on demand — so this
module makes them reproducible: :func:`inject` activates a
:class:`FaultPlan` for a scope (telemetry-style activation: a module
tuple of active plans, one truthiness check per seam when nothing is
active — the same free-when-idle discipline as
:mod:`ziria_tpu.utils.telemetry`, pinned by
``tests/test_resilience.py``), and every decision is **deterministic
by (site, seed, call-index)**: the same plan over the same workload
injects the same faults at the same calls, so every chaos test
replays exactly.

Two seam families consume the plan:

- **dispatch seams** call :func:`maybe_fail(site)
  <maybe_fail>` just before firing a compiled program
  (``resilience.guarded`` does this for every guarded site): a
  matching spec raises :class:`InjectedTransientError` /
  :class:`InjectedFatalError` (status-prefixed messages shaped like
  ``XlaRuntimeError`` text, so the retry classifier exercises its real
  matching), or sleeps ``delay_s`` (``delay`` — added latency; a
  ``hang`` is the same sleep, long enough that only the guarded
  watchdog can cut it).
- **data seams** call :func:`corrupt_slab(site, arr) <corrupt_slab>`
  on an incoming sample slab (the receivers' push paths): ``nan_slab``
  NaN-poisons a deterministic fraction of the rows, ``truncate`` drops
  a deterministic tail fraction — the two input-poisoning faults the
  quarantine machinery exists to contain.
- **io seams** call :func:`io_fault(site, data) <io_fault>` on every
  byte payload the durability layer (``runtime/durability.py``) is
  about to write: ``io_torn`` truncates the write (the torn-record
  crash the CRC framing catches on replay), ``io_enospc`` raises
  ``OSError(ENOSPC)`` like a full disk.

Sites are matched by :mod:`fnmatch` pattern, so one spec can cover a
family (``"rx.push.s*"`` — note fnmatch treats ``[...]`` as a
character class, which is why the per-stream sites are dot-named)
while the per-site call counters keep every concrete site's schedule
independent.

The CLI exposes the layer as ``--chaos SPEC`` / ``ZIRIA_CHAOS``
(scoped-env pattern; :func:`env_chaos` is the single reader, jaxlint
R4). Spec grammar, semicolon-separated::

    [seed=N;]site:kind[:key=val[,key=val...]][;site:kind...]

with keys ``every=N`` (fire every Nth call), ``calls=i+j+k`` (explicit
0-based call indices), ``p=F`` (probability, hashed from
(site, seed, index)), ``count=N`` (max firings), ``delay=F`` (seconds,
for delay/hang), ``frac=F`` (slab fraction, for nan_slab/truncate),
``profile=NAME`` (a phy/profiles channel-profile name, for the
``channel`` kind — default ``hostile``).
Examples: ``ZIRIA_CHAOS="seed=3;rx.stream_chunk:transient:every=7"``,
``ZIRIA_CHAOS="rx.push.s*:channel:profile=severe,every=2"``.
"""

from __future__ import annotations

import fnmatch
import hashlib
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

_LOCK = threading.Lock()            # guards (de)activation only
_PLANS: Tuple["FaultPlan", ...] = ()

#: the injectable fault classes (docs/robustness.md taxonomy)
KINDS = ("nan_slab", "truncate", "transient", "fatal", "delay", "hang",
         "io_torn", "io_enospc", "channel")

#: kinds that act at data (push) seams vs dispatch seams vs the
#: durability write seams (journal append / snapshot file writes).
#: ``channel`` is a data kind: it passes the slab through a named
#: physical-channel profile (phy/profiles — multipath FIR, SCO
#: resample, drift phase, interference bursts) in PURE NUMPY, so the
#: chaos layer stays jax-free (tools/chaos_smoke.py's no-jax pin).
#: Applied per-slab it is a chaos corruption, not stream physics —
#: frames straddling slab boundaries see filter seams, exactly the
#: kind of hostile input the quarantine/CRC machinery must absorb
#: without crashing; the physically-continuous stimulus lives in
#: link.stream_many(channel_profile=...).
DATA_KINDS = ("nan_slab", "truncate", "channel")
DISPATCH_KINDS = ("transient", "fatal", "delay", "hang")
IO_KINDS = ("io_torn", "io_enospc")


class InjectedFault(Exception):
    """Base of the injected error classes (never raised itself)."""


class InjectedTransientError(InjectedFault):
    """An injected *transient* dispatch failure — message styled like
    a retryable ``XlaRuntimeError`` (``UNAVAILABLE: ...``) so the
    guarded-dispatch classifier exercises its real marker matching."""


class InjectedFatalError(InjectedFault):
    """An injected *fatal* dispatch failure — a non-retryable status
    (``INVALID_ARGUMENT: ...``): retrying cannot heal it, the guarded
    site must degrade or raise."""


class FaultSpec(NamedTuple):
    """One injectable fault: fire ``kind`` at sites matching the
    fnmatch pattern ``site`` on the calls selected by exactly one of
    ``calls`` (explicit 0-based per-site call indices), ``every``
    (every Nth call), or ``p`` (probability, decided by a hash of
    (site, seed, call-index) — still fully deterministic). ``count``
    bounds total firings (0 = unbounded); ``delay_s`` is the sleep of
    delay/hang kinds; ``fraction`` the slab share nan_slab/truncate
    touch."""
    site: str
    kind: str
    calls: Tuple[int, ...] = ()
    every: int = 0
    p: float = 0.0
    count: int = 0
    delay_s: float = 0.01
    fraction: float = 0.25
    #: channel-profile name for the ``channel`` kind (grammar key
    #: ``profile=NAME``; default ``hostile`` — validated against
    #: phy/profiles.CHANNEL_PROFILES at plan construction)
    profile: str = "hostile"


def _unit(site: str, seed: int, idx: int) -> float:
    """Deterministic uniform in [0, 1) from (site, seed, call-index):
    the probabilistic specs' coin, identical on every replay."""
    h = hashlib.sha256(f"{site}\x00{seed}\x00{idx}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultPlan:
    """The active decision state of one :func:`inject` scope: per-site
    call counters (thread-safe), per-spec firing counts, and a log of
    every fired fault (``fired``: (site, kind, call-index) tuples, the
    attribution record chaos benches assert against)."""

    def __init__(self, specs, seed: int = 0):
        specs = tuple(specs)
        for sp in specs:
            if sp.kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {sp.kind!r} (known: {KINDS})")
            if sum((len(sp.calls) > 0, sp.every > 0, sp.p > 0)) != 1:
                raise ValueError(
                    f"spec {sp.site}:{sp.kind} needs exactly one of "
                    f"calls=/every=/p= to select its firing calls")
            if sp.kind == "channel":
                # jax-free import (phy/profiles is plain data) —
                # unknown profile names fail at plan construction
                # with the registry's own known-names message
                from ziria_tpu.phy.profiles import get_profile
                get_profile(sp.profile)
        self.specs = specs
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._idx: Dict[str, int] = {}       # concrete site -> calls
        self._spec_fired = [0] * len(specs)
        self.fired: List[Tuple[str, str, int]] = []

    def decide(self, site: str, kinds) -> Optional[Tuple[FaultSpec, int]]:
        """Advance ``site``'s call counter and return the first
        matching spec (restricted to ``kinds``) that fires at this
        call, with the call index — or None. One counter per concrete
        site string: determinism is per (site, seed, call-index)."""
        with self._lock:
            idx = self._idx.get(site, 0)
            self._idx[site] = idx + 1
            for j, sp in enumerate(self.specs):
                if sp.kind not in kinds:
                    continue
                if sp.count and self._spec_fired[j] >= sp.count:
                    continue
                if not fnmatch.fnmatchcase(site, sp.site):
                    continue
                if sp.calls:
                    hit = idx in sp.calls
                elif sp.every:
                    hit = (idx + 1) % sp.every == 0
                else:
                    # fold the spec position in so two p-specs on one
                    # site draw independent coins
                    hit = _unit(f"{site}#{j}", self.seed, idx) < sp.p
                if hit:
                    self._spec_fired[j] += 1
                    self.fired.append((site, sp.kind, idx))
                    return sp, idx
        return None

    @property
    def total_fired(self) -> int:
        with self._lock:
            return len(self.fired)

    def fired_sites(self) -> Dict[str, int]:
        """site -> fired count (the per-stream attribution record)."""
        out: Dict[str, int] = {}
        with self._lock:
            for s, _k, _i in self.fired:
                out[s] = out.get(s, 0) + 1
        return out


def active() -> bool:
    """True when any fault plan is injecting (every seam's slow path
    gates on this; the fast path is one tuple truthiness check)."""
    return bool(_PLANS)


@contextmanager
def inject(*specs: FaultSpec, seed: int = 0,
           plan: Optional[FaultPlan] = None):
    """Activate a :class:`FaultPlan` for the block (a fresh one from
    ``specs`` + ``seed``, or the one passed in); yields the plan so
    the caller can read its firing log afterwards. Nests and overlaps
    freely — every active plan sees every seam call (the telemetry
    activation contract)."""
    global _PLANS
    p = plan if plan is not None else FaultPlan(specs, seed=seed)
    with _LOCK:
        _PLANS = _PLANS + (p,)
    try:
        yield p
    finally:
        with _LOCK:
            lst = list(_PLANS)
            for i in range(len(lst) - 1, -1, -1):
                if lst[i] is p:      # remove ONE occurrence (nesting)
                    del lst[i]
                    break
            _PLANS = tuple(lst)


def maybe_fail(site: str) -> None:
    """The dispatch seam: called just before a guarded compiled
    program fires. A matching ``delay``/``hang`` spec sleeps
    ``delay_s`` (a hang is contained only by the guarded watchdog); a
    ``transient``/``fatal`` spec raises the corresponding injected
    error. Free when no plan is active (one truthiness check)."""
    if not _PLANS:
        return
    for plan in _PLANS:
        got = plan.decide(site, DISPATCH_KINDS)
        if got is None:
            continue
        sp, idx = got
        if sp.kind in ("delay", "hang"):
            time.sleep(sp.delay_s)
        elif sp.kind == "transient":
            raise InjectedTransientError(
                f"UNAVAILABLE: injected transient fault at {site} "
                f"(call {idx})")
        else:
            raise InjectedFatalError(
                f"INVALID_ARGUMENT: injected fatal fault at {site} "
                f"(call {idx})")


def _channel_slab(arr: np.ndarray, profile: str, seed: int,
                  idx: int) -> np.ndarray:
    """The ``channel`` data kind: pass a slab through a named
    physical-channel profile in pure numpy — multipath FIR + SCO
    resample (the jax-free host twins in phy/profiles), a drift phase
    ramp from the slab's own origin, and seeded interference bursts
    (numpy RNG keyed by the plan's (site, seed, call-index) hash, so
    every replay corrupts identically). Per-slab application is a
    deterministic hostile-input FAULT (boundary seams included), not
    continuous stream physics."""
    from ziria_tpu.phy.profiles import get_profile, np_apply_drift, \
        np_apply_sco, np_apply_taps, np_burst_amp, np_burst_mask

    prof = get_profile(profile)
    x = np_apply_taps(np.asarray(arr, np.float32), prof)
    x = np_apply_sco(x, prof.sco)
    x = np_apply_drift(x, prof.drift)
    n = x.shape[0]
    if prof.burst_every and n:
        rs = np.random.default_rng(int(_unit(f"chan:{profile}", seed,
                                             idx) * (1 << 53)))
        off = int(rs.integers(0, prof.burst_every))
        in_burst = np_burst_mask(n, prof, off)
        p_sig = float(np.mean(np.square(x.astype(np.float64)))) * 2.0
        amp = np_burst_amp(p_sig, prof)
        x = (x + rs.normal(size=x.shape)
             * (amp * in_burst.astype(np.float64))[:, None]) \
            .astype(np.float32)
    return x


def corrupt_slab(site: str, arr: np.ndarray):
    """The data seam: called on an incoming (n, 2) sample slab at the
    push surfaces. A matching ``nan_slab`` spec NaN-poisons a
    deterministic ``fraction`` of the rows (row choice seeded by
    (site, seed, call-index)); ``truncate`` drops the tail
    ``fraction``; ``channel`` passes the slab through its named
    physical-channel profile (`_channel_slab` — multipath/SCO/drift/
    bursts, pure numpy). Returns ``(slab, kinds)`` — the (possibly
    copied) slab and the tuple of injected kinds (empty when nothing
    fired). Free when no plan is active."""
    if not _PLANS:
        return arr, ()
    kinds: List[str] = []
    for plan in _PLANS:
        got = plan.decide(site, DATA_KINDS)
        if got is None:
            continue
        sp, idx = got
        n = int(arr.shape[0]) if arr.ndim else 0
        if sp.kind == "nan_slab" and n:
            arr = np.array(arr, copy=True)
            k = max(1, int(n * sp.fraction))
            rs = np.random.default_rng(
                int(_unit(site, plan.seed, idx) * (1 << 53)))
            rows = rs.choice(n, size=min(k, n), replace=False)
            arr[rows] = np.nan
        elif sp.kind == "truncate" and n > 1:
            keep = max(1, n - max(1, int(n * sp.fraction)))
            arr = arr[:keep]
        elif sp.kind == "channel" and n:
            arr = _channel_slab(arr, sp.profile, plan.seed, idx)
        kinds.append(sp.kind)
    return arr, tuple(kinds)


def io_fault(site: str, data: bytes) -> bytes:
    """The durability write seam (runtime/durability.py calls this on
    every byte payload it is about to put on disk — journal record
    frames and snapshot files alike). A matching ``io_torn`` spec
    returns a TRUNCATED prefix of ``data`` (at least one byte dropped
    — the torn-write crash the CRC framing exists to catch); an
    ``io_enospc`` spec raises ``OSError(ENOSPC)`` exactly as a full
    disk would. Free when no plan is active (one truthiness check)."""
    if not _PLANS:
        return data
    import errno

    for plan in _PLANS:
        got = plan.decide(site, IO_KINDS)
        if got is None:
            continue
        sp, idx = got
        if sp.kind == "io_enospc":
            raise OSError(
                errno.ENOSPC,
                f"No space left on device (injected at {site}, "
                f"call {idx})")
        keep = min(len(data) - 1,
                   int(len(data) * (1.0 - sp.fraction)))
        data = data[: max(0, keep)]
    return data


# ----------------------------------------------------------- env knob


def parse_chaos_spec(text: str) -> Tuple[Tuple[FaultSpec, ...], int]:
    """Parse the ``--chaos`` / ``ZIRIA_CHAOS`` grammar into
    ``(specs, seed)``. Raises ValueError on malformed specs (the CLI
    surfaces it as a flag error, never a silent no-chaos run)."""
    specs: List[FaultSpec] = []
    seed = 0
    for item in (s.strip() for s in text.split(";")):
        if not item:
            continue
        if item.startswith("seed="):
            seed = int(item[5:])
            continue
        parts = item.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"chaos spec {item!r}: want site:kind[:key=val,...]")
        site, kind = parts[0], parts[1]
        kw: Dict[str, object] = {}
        for opt in ":".join(parts[2:]).split(","):
            opt = opt.strip()
            if not opt:
                continue
            if "=" not in opt:
                raise ValueError(f"chaos option {opt!r}: want key=val")
            k, v = opt.split("=", 1)
            if k == "every":
                kw["every"] = int(v)
            elif k == "calls":
                kw["calls"] = tuple(int(c) for c in v.split("+"))
            elif k == "p":
                kw["p"] = float(v)
            elif k == "count":
                kw["count"] = int(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "frac":
                kw["fraction"] = float(v)
            elif k == "profile":
                kw["profile"] = v
            else:
                raise ValueError(f"unknown chaos option {k!r}")
        if not (kw.get("calls") or kw.get("every") or kw.get("p")):
            kw["every"] = 1          # bare spec: fire every call
        specs.append(FaultSpec(site=site, kind=kind, **kw))
    # self-validate (kinds, selector combos) so EVERY consumer of the
    # grammar — the CLI flag path and a directly-exported ZIRIA_CHAOS
    # alike — fails at parse time with one clear message
    FaultPlan(specs, seed=seed)
    return tuple(specs), seed


def env_chaos() -> Optional[Tuple[Tuple[FaultSpec, ...], int]]:
    """The ONE reading of the ``ZIRIA_CHAOS`` knob (the CLI's
    ``--chaos`` writes it via the scoped-env pattern): a spec string
    means 'run this invocation under the described fault plan'.
    Returns ``(specs, seed)`` or None when unset/empty."""
    import os

    text = os.environ.get("ZIRIA_CHAOS")
    if not text:
        return None
    return parse_chaos_spec(text)
