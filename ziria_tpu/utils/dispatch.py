"""Device-dispatch observability: count the compiled calls a code
path fires, and the compile-cache growth it causes.

The frame-batching work lives and dies by TWO integers the profiler
does not hand you: how many *device dispatches* a receive path costs
(each one pays the host link round trip — the ~68 ms tax BENCH_r05
measured through the axon tunnel) and how many *fresh compiles* it
triggered (tens of seconds each on first contact). This module gives
both a first-class seam:

- :func:`count_dispatches` — a context manager; every instrumented
  call site inside the ``with`` block increments a labelled counter.
  Sites are instrumented explicitly with :func:`record` (the same
  own-call-site discipline as ``backend.chunked.STATS`` — JAX has no
  stable public hook for "a compiled program ran", so we count where
  WE launch device work; eager jnp call sites count as one dispatch
  however many primitives they fan into, making every reported bound
  a LOWER bound on real device calls). Sites wrapped with
  :func:`timed` additionally accumulate per-site *wall time*
  (``DispatchCount.times``, seconds): the host-side time spent in the
  instrumented call — dispatch plus any blocking the call does. On a
  synchronous backend (CPU) that is the stage's real wall time; on an
  async one it is a lower bound (the dispatch tax itself), which is
  exactly the number the host-link analyses need.
- :func:`cache_growth` — lru-delta measurement for the jit-factory
  caches (``rx._jit_decode_data_mixed`` etc.): the compile-count
  proxy `tests/test_rx_mixed_dispatch.py` used to hand-roll. Deltas,
  never ``cache_clear`` — the caches are process-wide shared state.

Both are reentrant and thread-safe: nested/overlapping counters each
see every event recorded while they are active (frame threads under
``framebatch.run_many`` all report into the same active counters).

The module also owns the *dispatch geometry* helpers every batched
path shares (:func:`pow2_ceil`, :func:`pow2_bucket`,
:func:`pad_lanes`): lane counts and padded sizes round up to powers
of two so XLA compiles O(log N) batch variants, not one per size —
the single padding rule behind the O(log buckets) compile-count
contracts the counters above measure. They were hoisted here from
three drifting copies (``backend/framebatch``, ``rx.acquire_many``,
and the TX batch path).
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

_LOCK = threading.Lock()
_ACTIVE: List["DispatchCount"] = []


# ------------------------------------------------------ dispatch geometry


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (and >= 1)."""
    return 1 << max(0, (int(n) - 1).bit_length())


def pow2_bucket(n: int, min_bucket: int) -> int:
    """Power-of-two size bucket with a floor: the one padding formula
    every batched path uses (symbol buckets floor at 4, capture
    buckets at 512, TX bit buckets at 128) so tiny inputs share one
    compile class instead of fragmenting the jit caches."""
    return max(int(min_bucket), pow2_ceil(n))


def pad_lanes(lanes: Sequence) -> list:
    """Pad a non-empty lane list to the next power-of-two count by
    repeating lane 0 — the shared lane-count rule of every vmapped
    batch here (XLA compiles O(log N) lane-count variants; repeated
    lane 0 is discarded by the caller, which only reads the first
    ``len(lanes)`` results)."""
    lanes = list(lanes)
    return lanes + [lanes[0]] * (pow2_ceil(len(lanes)) - len(lanes))


class DispatchCount:
    """Labelled dispatch tally filled in by :func:`record` while its
    :func:`count_dispatches` block is active. ``counts`` holds the
    per-site dispatch counts; ``times`` the per-site accumulated wall
    seconds from :func:`timed` sites (sites instrumented with bare
    :func:`record` contribute counts only); ``gauges`` the per-label
    high-water marks from :func:`record_gauge` sites (e.g. the
    streaming receiver's in-flight chunk depth — a *level*, not an
    event count, so it maxes rather than sums)."""

    def __init__(self) -> None:
        self.counts: Counter = Counter()
        self.times: Counter = Counter()      # label -> wall seconds
        self.gauges: Dict[str, float] = {}   # label -> max level seen

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    @property
    def total_time(self) -> float:
        return float(sum(self.times.values()))

    def times_ms(self) -> Dict[str, float]:
        """Per-site wall time in milliseconds, rounded for reports."""
        return {k: round(v * 1e3, 3) for k, v in sorted(
            self.times.items())}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        inner = ", ".join(f"{k}={v}" for k, v in sorted(
            self.counts.items()))
        return f"DispatchCount(total={self.total}, {inner})"


def record(label: str = "dispatch", n: int = 1,
           seconds: Optional[float] = None) -> None:
    """Report ``n`` device dispatches at an instrumented call site,
    optionally with the wall time the call took (``seconds``; the
    :func:`timed` wrapper measures and passes it).

    Free when no counter is active (one lock-free len check), so the
    hot paths carry their instrumentation permanently.
    """
    if not _ACTIVE:
        return
    with _LOCK:
        for c in _ACTIVE:
            c.counts[label] += n
            if seconds is not None:
                c.times[label] += seconds


def record_gauge(label: str, value: float) -> None:
    """Report the current *level* of an instrumented quantity (the
    streaming receiver's in-flight dispatch depth). Active counters
    keep the maximum level observed, so ``d.gauges["..."]`` after a
    :func:`count_dispatches` block is the high-water mark — the number
    that shows whether double-buffered overlap actually overlapped.
    Free when no counter is active (one lock-free len check)."""
    if not _ACTIVE:
        return
    with _LOCK:
        for c in _ACTIVE:
            if value > c.gauges.get(label, float("-inf")):
                c.gauges[label] = value


@contextmanager
def timed(label: str = "dispatch"):
    """``with timed("rx.sync"): ...`` — record ONE dispatch at the
    site plus the wall time of the block. The preferred form for
    instrumented call sites: dispatch *time*, not just count, becomes
    observable per stage (`tools/rx_dispatch_bench.py` stats blocks
    report both). Near-free when no counter is active (one clock pair
    and a len check)."""
    if not _ACTIVE:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(label, seconds=time.perf_counter() - t0)


@contextmanager
def count_dispatches():
    """``with count_dispatches() as d:`` — afterwards ``d.total`` is
    the number of instrumented device dispatches the block performed
    and ``d.counts`` the per-label breakdown."""
    c = DispatchCount()
    with _LOCK:
        _ACTIVE.append(c)
    try:
        yield c
    finally:
        with _LOCK:
            _ACTIVE.remove(c)


class CacheGrowth:
    """Per-cache ``currsize`` deltas captured on context exit."""

    def __init__(self, caches: Tuple) -> None:
        self._caches = caches
        self._before = [c.cache_info().currsize for c in caches]
        self.growth: Dict = {}

    def _finish(self) -> None:
        self.growth = {
            c: c.cache_info().currsize - b
            for c, b in zip(self._caches, self._before)}

    @property
    def total(self) -> int:
        return sum(self.growth.values())

    def __getitem__(self, cache) -> int:
        return self.growth[cache]


@contextmanager
def cache_growth(*caches):
    """``with cache_growth(rx._jit_decode_data_mixed) as g:`` — after
    the block, ``g[cache]`` / ``g.total`` give how many NEW entries
    (fresh compiled callables) the block added to each ``lru_cache``.
    Measures deltas without ever clearing: safe inside a shared-cache
    process (a full pytest run, an embedder)."""
    g = CacheGrowth(caches)
    try:
        yield g
    finally:
        g._finish()
