"""Device-dispatch observability: count the compiled calls a code
path fires, and the compile-cache growth it causes.

The frame-batching work lives and dies by TWO integers the profiler
does not hand you: how many *device dispatches* a receive path costs
(each one pays the host link round trip — the ~68 ms tax BENCH_r05
measured through the axon tunnel) and how many *fresh compiles* it
triggered (tens of seconds each on first contact). This module gives
both a first-class seam:

- :func:`count_dispatches` — a context manager; every instrumented
  call site inside the ``with`` block increments a labelled counter.
  Sites are instrumented explicitly with :func:`record` (the same
  own-call-site discipline as ``backend.chunked.STATS`` — JAX has no
  stable public hook for "a compiled program ran", so we count where
  WE launch device work; eager jnp call sites count as one dispatch
  however many primitives they fan into, making every reported bound
  a LOWER bound on real device calls). Sites wrapped with
  :func:`timed` additionally accumulate per-site *wall time*
  (``DispatchCount.times``, seconds): the host-side time spent in the
  instrumented call — dispatch plus any blocking the call does. On a
  synchronous backend (CPU) that is the stage's real wall time; on an
  async one it is a lower bound (the dispatch tax itself), which is
  exactly the number the host-link analyses need.
- :func:`cache_growth` — lru-delta measurement for the jit-factory
  caches (``rx._jit_decode_data_mixed`` etc.): the compile-count
  proxy `tests/test_rx_mixed_dispatch.py` used to hand-roll. Deltas,
  never ``cache_clear`` — the caches are process-wide shared state.

Both are reentrant and thread-safe: nested/overlapping counters each
see every event recorded while they are active (frame threads under
``framebatch.run_many`` all report into the same active counters).
Each :class:`DispatchCount` owns its OWN lock — concurrent
instrumented sites (the double-buffered streaming loop, ``run_many``
frame threads) update counters without contending on one global
mutex; the module lock only guards (de)activation.

The sites are also the emission points of the runtime telemetry layer
(:mod:`ziria_tpu.utils.telemetry`): when a trace or metrics registry
is active, :func:`timed` records a span plus a latency-histogram
observation, :func:`record` a labelled counter increment, and
:func:`record_gauge` a time-series gauge sample and a trace
counter-track point — so every instrumented surface gets
distribution-level (p50/p99) latency and plottable gauge levels with
no changes at the call sites. All of it stays free when nothing is
active (the same one-truthiness-check fast path).

The module also owns the *dispatch geometry* helpers every batched
path shares (:func:`pow2_ceil`, :func:`pow2_bucket`,
:func:`pad_lanes`): lane counts and padded sizes round up to powers
of two so XLA compiles O(log N) batch variants, not one per size —
the single padding rule behind the O(log buckets) compile-count
contracts the counters above measure. They were hoisted here from
three drifting copies (``backend/framebatch``, ``rx.acquire_many``,
and the TX batch path).
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

from ziria_tpu.utils import telemetry as _tm

_LOCK = threading.Lock()          # guards _ACTIVE mutation only
_ACTIVE: List["DispatchCount"] = []


def _idle() -> bool:
    """True when no counter, trace, or registry is collecting — the
    one check every emitter's disabled fast path takes."""
    return not (_ACTIVE or _tm._TRACES or _tm._REGISTRIES)


# ------------------------------------------------------ dispatch geometry


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (and >= 1)."""
    return 1 << max(0, (int(n) - 1).bit_length())


def pow2_bucket(n: int, min_bucket: int) -> int:
    """Power-of-two size bucket with a floor: the one padding formula
    every batched path uses (symbol buckets floor at 4, capture
    buckets at 512, TX bit buckets at 128) so tiny inputs share one
    compile class instead of fragmenting the jit caches."""
    return max(int(min_bucket), pow2_ceil(n))


def pad_lanes(lanes: Sequence) -> list:
    """Pad a non-empty lane list to the next power-of-two count by
    repeating lane 0 — the shared lane-count rule of every vmapped
    batch here (XLA compiles O(log N) lane-count variants; repeated
    lane 0 is discarded by the caller, which only reads the first
    ``len(lanes)`` results)."""
    lanes = list(lanes)
    return lanes + [lanes[0]] * (pow2_ceil(len(lanes)) - len(lanes))


class DispatchCount:
    """Labelled dispatch tally filled in by :func:`record` while its
    :func:`count_dispatches` block is active. ``counts`` holds the
    per-site dispatch counts; ``times`` the per-site accumulated wall
    seconds from :func:`timed` sites (sites instrumented with bare
    :func:`record` contribute counts only); ``gauges`` the per-label
    high-water marks from :func:`record_gauge` sites (e.g. the
    streaming receiver's in-flight chunk depth — a *level*, not an
    event count, so it maxes rather than sums). Updates go through the
    instance's OWN lock, so two counters active at once (or many
    threads reporting into one) never serialize on a shared mutex."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counts: Counter = Counter()
        self.times: Counter = Counter()      # label -> wall seconds
        self.gauges: Dict[str, float] = {}   # label -> max level seen

    def _add(self, label: str, n: int, seconds: Optional[float]) -> None:
        with self._lock:
            self.counts[label] += n
            if seconds is not None:
                self.times[label] += seconds

    def _gauge(self, label: str, value: float) -> None:
        with self._lock:
            if value > self.gauges.get(label, float("-inf")):
                self.gauges[label] = value

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    @property
    def total_time(self) -> float:
        return float(sum(self.times.values()))

    def times_ms(self) -> Dict[str, float]:
        """Per-site wall time in milliseconds, rounded for reports."""
        return {k: round(v * 1e3, 3) for k, v in sorted(
            self.times.items())}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        inner = ", ".join(f"{k}={v}" for k, v in sorted(
            self.counts.items()))
        return f"DispatchCount(total={self.total}, {inner})"


def record(label: str = "dispatch", n: int = 1,
           seconds: Optional[float] = None) -> None:
    """Report ``n`` device dispatches at an instrumented call site,
    optionally with the wall time the call took (``seconds``; the
    :func:`timed` wrapper measures and passes it). Also increments the
    per-site dispatch counter (and, when timed, the latency histogram)
    of every active telemetry registry.

    Free when nothing is collecting (one truthiness check), so the
    hot paths carry their instrumentation permanently. Active counters
    update under their own per-instance locks — no shared mutex on
    the instrumented fast path (``tuple(_ACTIVE)`` is an atomic
    snapshot under the GIL).
    """
    if _idle():
        return
    for c in tuple(_ACTIVE):
        c._add(label, n, seconds)
    if _tm._REGISTRIES:
        _tm.dispatch_event(label, n, seconds)


def record_gauge(label: str, value: float) -> None:
    """Report the current *level* of an instrumented quantity (the
    streaming receiver's in-flight dispatch depth). Active counters
    keep the maximum level observed, so ``d.gauges["..."]`` after a
    :func:`count_dispatches` block is the high-water mark — the number
    that shows whether double-buffered overlap actually overlapped.
    Active telemetry sinks additionally get EVERY sample: a
    time-series point per registry and a counter-track event per trace
    — the level over time, so a chart shows *how long* the level was
    sustained, not just that it was reached.
    Free when nothing is collecting (one truthiness check)."""
    if _idle():
        return
    for c in tuple(_ACTIVE):
        c._gauge(label, value)
    _tm.gauge_sample(label, value)


@contextmanager
def timed(label: str = "dispatch"):
    """``with timed("rx.sync"): ...`` — record ONE dispatch at the
    site plus the wall time of the block. The preferred form for
    instrumented call sites: dispatch *time*, not just count, becomes
    observable per stage (`tools/rx_dispatch_bench.py` stats blocks
    report both). With telemetry active the block is additionally a
    trace span and a latency-histogram observation — p50/p99 per site
    for free. Near-free when nothing is collecting (one truthiness
    check)."""
    if _idle():
        yield
        return
    with _tm.span(label):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            record(label, seconds=time.perf_counter() - t0)


@contextmanager
def count_dispatches():
    """``with count_dispatches() as d:`` — afterwards ``d.total`` is
    the number of instrumented device dispatches the block performed
    and ``d.counts`` the per-label breakdown."""
    c = DispatchCount()
    with _LOCK:
        _ACTIVE.append(c)
    try:
        yield c
    finally:
        with _LOCK:
            _ACTIVE.remove(c)


class CacheGrowth:
    """Per-cache ``currsize`` deltas captured on context exit. With
    telemetry active, nonzero deltas are reported as compile events
    (`telemetry.record_compile`) — fresh jit-factory entries show up
    in the trace as compile markers instead of masquerading as slow
    dispatches."""

    def __init__(self, caches: Tuple) -> None:
        self._caches = caches
        self._before = [c.cache_info().currsize for c in caches]
        self.growth: Dict = {}

    def _finish(self) -> None:
        self.growth = {
            c: c.cache_info().currsize - b
            for c, b in zip(self._caches, self._before)}
        if _tm.active():
            for c, g in self.growth.items():
                if g:
                    name = getattr(c, "__name__", None) or repr(c)
                    _tm.record_compile(f"cache_growth:{name}", n=g,
                                       args={"new_entries": g})

    @property
    def total(self) -> int:
        return sum(self.growth.values())

    def __getitem__(self, cache) -> int:
        return self.growth[cache]


@contextmanager
def cache_growth(*caches):
    """``with cache_growth(rx._jit_decode_data_mixed) as g:`` — after
    the block, ``g[cache]`` / ``g.total`` give how many NEW entries
    (fresh compiled callables) the block added to each ``lru_cache``.
    Measures deltas without ever clearing: safe inside a shared-cache
    process (a full pytest run, an embedder)."""
    g = CacheGrowth(caches)
    try:
        yield g
    finally:
        g._finish()


@contextmanager
def no_recompile(*caches):
    """``with no_recompile(rx._jit_stream_chunk): ...`` — assert the
    block added ZERO entries to each jit-factory ``lru_cache``: the
    runtime twin of the jaxlint R1 cache-key rule
    (docs/static_analysis.md). The static rule proves every knob IS in
    the key; this proves a steady-state path never mints a fresh key —
    i.e. re-dispatches compiled programs instead of recompiling.
    Raises AssertionError naming the grown caches on a clean exit; an
    exception from the block propagates unmasked (growth is not
    checked — the block didn't finish its steady state)."""
    with cache_growth(*caches) as g:
        yield g
    # only reached on a clean block exit: an exception propagates
    # through the yield and skips the growth assertion
    grown = {}
    for c, n in g.growth.items():
        if n:
            name = getattr(c, "__name__", None) or repr(c)
            mod = getattr(c, "__module__", None)
            grown[f"{mod}.{name}" if mod else name] = n
    if grown:
        raise AssertionError(
            f"no_recompile: block minted fresh compile-cache entries "
            f"{grown} — a knob or geometry is reaching the jit "
            f"factory without riding its cache key")
