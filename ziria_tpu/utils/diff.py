"""Tolerance comparator — the BlinkDiff equivalent.

The reference's golden-file tests compare program output against ground
truth with `tools/BlinkDiff`, which tolerates bounded numeric deviation
(SURVEY.md §4) because vectorization/LUT rewrites may legally perturb low
bits. Same policy here: exact equality for integer/bit streams, bounded
absolute+relative error for floats/complex, with a precise first-mismatch
report for debugging.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class DiffReport:
    ok: bool
    message: str
    n_mismatch: int = 0
    first_index: Optional[int] = None
    max_abs_err: float = 0.0

    def __bool__(self) -> bool:
        return self.ok


def stream_diff(got, want, atol: float = 0.0, rtol: float = 0.0,
                name: str = "stream") -> DiffReport:
    """Compare two streams (arrays). Integer dtypes require exactness
    regardless of atol/rtol; floats/complex use atol + rtol*|want|."""
    got = np.asarray(got)
    want = np.asarray(want)
    if got.shape != want.shape:
        return DiffReport(False,
                          f"{name}: shape mismatch got {got.shape} "
                          f"want {want.shape}")
    if got.size == 0:
        return DiffReport(True, f"{name}: empty, equal")

    def _exact_dtype(dt):
        return np.issubdtype(dt, np.integer) or dt == np.bool_

    exact = _exact_dtype(got.dtype) and _exact_dtype(want.dtype)
    if exact:
        neq = got != want
        if neq.any():
            flat = np.flatnonzero(neq.reshape(-1))
            i = int(flat[0])
            return DiffReport(
                False,
                f"{name}: {flat.size}/{got.size} integer mismatches; first "
                f"at flat index {i}: got {got.reshape(-1)[i]} want "
                f"{want.reshape(-1)[i]}",
                n_mismatch=int(flat.size), first_index=i)
        return DiffReport(True, f"{name}: {got.size} items exactly equal")

    err = np.abs(got.astype(np.complex128) - want.astype(np.complex128))
    tol = atol + rtol * np.abs(want.astype(np.complex128))
    bad = err > tol
    if bad.any():
        flat = np.flatnonzero(bad.reshape(-1))
        i = int(flat[0])
        return DiffReport(
            False,
            f"{name}: {flat.size}/{got.size} items exceed tol "
            f"(atol={atol}, rtol={rtol}); first at flat index {i}: got "
            f"{got.reshape(-1)[i]} want {want.reshape(-1)[i]} "
            f"(err {err.reshape(-1)[i]:.3g}); max err {err.max():.3g}",
            n_mismatch=int(flat.size), first_index=i,
            max_abs_err=float(err.max()))
    return DiffReport(True,
                      f"{name}: {got.size} items within tol "
                      f"(max err {float(err.max()):.3g})",
                      max_abs_err=float(err.max()))


def assert_stream_eq(got, want, atol: float = 0.0, rtol: float = 0.0,
                     name: str = "stream") -> None:
    rep = stream_diff(got, want, atol=atol, rtol=rtol, name=name)
    if not rep:
        raise AssertionError(rep.message)


# --------------------------------------------------------------------------
# CLI — the reference tools/BlinkDiff executable's role:
#   python -m ziria_tpu.utils.diff got.dbg want.ground \
#       --type=complex16 --mode=dbg --atol=1 [--prefix]
# exit 0 on match, 1 on mismatch (message on stderr).
# --------------------------------------------------------------------------


def _diff_main(argv=None) -> int:
    import argparse
    import sys

    from ziria_tpu.runtime.buffers import ITEM_TYPES, StreamSpec, \
        read_stream

    p = argparse.ArgumentParser(
        prog="python -m ziria_tpu.utils.diff",
        description="Golden-file comparator (BlinkDiff role): exact for "
                    "integer/bit streams, tolerance for floats/complex")
    p.add_argument("got")
    p.add_argument("want")
    p.add_argument("--type", default="int32", choices=ITEM_TYPES)
    p.add_argument("--mode", default="dbg", choices=["dbg", "bin"])
    p.add_argument("--atol", type=float, default=0.0)
    p.add_argument("--rtol", type=float, default=0.0)
    p.add_argument("--prefix", action="store_true",
                   help="compare only the common prefix (bin-mode bit "
                        "streams pad to byte boundaries)")
    args = p.parse_args(argv)

    got = read_stream(StreamSpec(ty=args.type, path=args.got,
                                 mode=args.mode))
    want = read_stream(StreamSpec(ty=args.type, path=args.want,
                                  mode=args.mode))
    if args.prefix:
        n = min(got.shape[0], want.shape[0])
        got, want = got[:n], want[:n]
    if args.atol or args.rtol:
        got = got.astype(np.float64)
        want = want.astype(np.float64)
    rep = stream_diff(got, want, atol=args.atol, rtol=args.rtol,
                      name=args.got)
    print(rep.message, file=sys.stderr if not rep.ok else sys.stdout)
    return 0 if rep.ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(_diff_main())
