"""Tolerance comparator — the BlinkDiff equivalent.

The reference's golden-file tests compare program output against ground
truth with `tools/BlinkDiff`, which tolerates bounded numeric deviation
(SURVEY.md §4) because vectorization/LUT rewrites may legally perturb low
bits. Same policy here: exact equality for integer/bit streams, bounded
absolute+relative error for floats/complex, with a precise first-mismatch
report for debugging.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class DiffReport:
    ok: bool
    message: str
    n_mismatch: int = 0
    first_index: Optional[int] = None
    max_abs_err: float = 0.0

    def __bool__(self) -> bool:
        return self.ok


def stream_diff(got, want, atol: float = 0.0, rtol: float = 0.0,
                name: str = "stream") -> DiffReport:
    """Compare two streams (arrays). Integer dtypes require exactness
    regardless of atol/rtol; floats/complex use atol + rtol*|want|."""
    got = np.asarray(got)
    want = np.asarray(want)
    if got.shape != want.shape:
        return DiffReport(False,
                          f"{name}: shape mismatch got {got.shape} "
                          f"want {want.shape}")
    if got.size == 0:
        return DiffReport(True, f"{name}: empty, equal")

    def _exact_dtype(dt):
        return np.issubdtype(dt, np.integer) or dt == np.bool_

    exact = _exact_dtype(got.dtype) and _exact_dtype(want.dtype)
    if exact:
        neq = got != want
        if neq.any():
            flat = np.flatnonzero(neq.reshape(-1))
            i = int(flat[0])
            return DiffReport(
                False,
                f"{name}: {flat.size}/{got.size} integer mismatches; first "
                f"at flat index {i}: got {got.reshape(-1)[i]} want "
                f"{want.reshape(-1)[i]}",
                n_mismatch=int(flat.size), first_index=i)
        return DiffReport(True, f"{name}: {got.size} items exactly equal")

    err = np.abs(got.astype(np.complex128) - want.astype(np.complex128))
    tol = atol + rtol * np.abs(want.astype(np.complex128))
    bad = err > tol
    if bad.any():
        flat = np.flatnonzero(bad.reshape(-1))
        i = int(flat[0])
        return DiffReport(
            False,
            f"{name}: {flat.size}/{got.size} items exceed tol "
            f"(atol={atol}, rtol={rtol}); first at flat index {i}: got "
            f"{got.reshape(-1)[i]} want {want.reshape(-1)[i]} "
            f"(err {err.reshape(-1)[i]:.3g}); max err {err.max():.3g}",
            n_mismatch=int(flat.size), first_index=i,
            max_abs_err=float(err.max()))
    return DiffReport(True,
                      f"{name}: {got.size} items within tol "
                      f"(max err {float(err.max()):.3g})",
                      max_abs_err=float(err.max()))


def assert_stream_eq(got, want, atol: float = 0.0, rtol: float = 0.0,
                     name: str = "stream") -> None:
    rep = stream_diff(got, want, atol=atol, rtol=rtol, name=name)
    if not rep:
        raise AssertionError(rep.message)
