"""Packed-bit utilities.

The reference carries `bit` streams through bit-packed C buffers
(`csrc/bit.c`, `buf_bit.c` — SURVEY.md §2.2). On TPU the working
representation is one bit per int8 lane (vector-friendly, XOR/AND are
native VPU ops); packing to real bytes exists for file I/O and hashing.
Bit order follows the reference's wire convention: within a byte, bit 0
(LSB) is first on the stream.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

BIT_DTYPE = jnp.uint8


def bytes_to_bits(data, xp=jnp):
    """uint8 bytes (..., N) -> bits (..., 8N), LSB-first per byte."""
    data = xp.asarray(data, dtype=xp.uint8)
    shifts = xp.arange(8, dtype=xp.uint8)
    bits = (data[..., :, None] >> shifts[None, :]) & 1
    return bits.reshape(data.shape[:-1] + (data.shape[-1] * 8,))


def bits_to_bytes(bits, xp=jnp):
    """bits (..., 8N) -> uint8 bytes (..., N), LSB-first per byte."""
    bits = xp.asarray(bits, dtype=xp.uint8)
    n = bits.shape[-1]
    if n % 8:
        raise ValueError(f"bit count {n} not a multiple of 8")
    b = bits.reshape(bits.shape[:-1] + (n // 8, 8))
    weights = (xp.asarray(1, dtype=xp.uint8) << xp.arange(8, dtype=xp.uint8))
    return (b * weights).sum(axis=-1).astype(xp.uint8)


def bits_to_uint(bits, xp=jnp, msb_first: bool = False):
    """bits (..., K) -> integer (...,), K <= 32. LSB-first by default."""
    bits = xp.asarray(bits, dtype=xp.uint32)
    k = bits.shape[-1]
    idx = xp.arange(k, dtype=xp.uint32)
    if msb_first:
        idx = idx[::-1]
    return (bits << idx).sum(axis=-1)


def uint_to_bits(vals, k: int, xp=jnp, msb_first: bool = False):
    """integers (...,) -> bits (..., k). LSB-first by default."""
    vals = xp.asarray(vals, dtype=xp.uint32)
    idx = xp.arange(k, dtype=xp.uint32)
    if msb_first:
        idx = idx[::-1]
    return ((vals[..., None] >> idx) & 1).astype(xp.uint8)


def np_bytes_to_bits(data):
    return np.asarray(bytes_to_bits(np.asarray(data, np.uint8), xp=np),
                      np.uint8)


def np_bits_to_bytes(bits):
    return np.asarray(bits_to_bytes(np.asarray(bits, np.uint8), xp=np),
                      np.uint8)
