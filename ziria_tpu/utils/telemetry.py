"""Process-wide runtime telemetry: span traces, latency histograms,
and a metrics registry behind every dispatch surface.

The dispatch observability this repo grew PR by PR (`utils/dispatch`)
answers *how many* device calls a path fires and *how much total wall
time* they took — two integers that cannot answer the questions the
serving work asks: what is the p99 chunk latency, how long was the
double-buffer overlap sustained, and was that slow dispatch a dispatch
at all or a first-contact XLA compile. This module is the
distribution-level, exportable layer those questions need. Three
cooperating pieces, each thread-safe and each *free when inactive*
(the hot paths carry their instrumentation permanently; the disabled
cost is one tuple truthiness check, pinned by
``tests/test_telemetry.py``):

- **Span tracing** — :func:`tracing` activates a :class:`Trace`;
  :func:`span` (and every ``dispatch.timed`` site) records nested,
  per-thread spans with monotonic timestamps. :meth:`Trace.export`
  writes Chrome trace-event JSON, loadable in Perfetto /
  ``chrome://tracing`` and summarizable with ``tools/trace_report.py``.
  ``Trace(annotate_device=True)`` passes each span through
  ``jax.profiler.TraceAnnotation`` so host spans line up with device
  traces when a ``jax.profiler`` capture runs concurrently.
- **Metrics** — :func:`collect` activates a :class:`MetricsRegistry`
  of :class:`CounterMetric`\\ s, time-series :class:`Gauge`\\ s (every
  sample kept, not just the high-water mark), and power-of-two
  log-bucket :class:`Histogram`\\ s whose quantiles are exact *bounds*:
  ``quantile(q)`` returns the upper edge of the bucket holding the
  rank-⌈qN⌉ sample, so the true quantile is always in
  ``(bound/2, bound]``. :meth:`MetricsRegistry.snapshot` gives plain
  dicts for JSON artifacts; :meth:`MetricsRegistry.exposition` a
  Prometheus-style text page (``--metrics-dump``).
- **Compile events** — a ``jax.monitoring`` duration listener
  (installed on first activation, dormant otherwise) surfaces XLA
  compile stalls as trace spans in the ``compile`` category, and
  ``dispatch.cache_growth`` reports fresh jit-cache entries through
  :func:`record_compile` — so a 20 s first-contact compile shows up AS
  a compile, not as a mysteriously slow dispatch span.

`utils/dispatch.record()/timed()/record_gauge()` are thin emitters
into whatever is active here, so every instrumented site of the last
six PRs (``rx.stream_chunk``, ``link.fused``, ``tx.encode_many``, the
in-flight gauge, ...) inherits tracing and histograms with no changes
at the site. Activation nests and overlaps freely: each active trace
and registry sees every event recorded while it is active (the same
reentrancy contract as ``dispatch.count_dispatches``).
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_LOCK = threading.Lock()      # guards (de)activation; never the hot path
# active sinks as immutable tuples: readers (the per-dispatch fast
# path) take a plain attribute read and a truthiness check — no lock
_TRACES: Tuple["Trace", ...] = ()
_REGISTRIES: Tuple["MetricsRegistry", ...] = ()


def active() -> bool:
    """True when any trace or registry is collecting (the slow path of
    every emitter is gated on this)."""
    return bool(_TRACES or _REGISTRIES)


# ------------------------------------------------------------- histograms


def _bucket_exp(v: float) -> int:
    """The power-of-two bucket of ``v > 0``: the exponent ``e`` with
    ``v`` in ``(2**(e-1), 2**e]`` (exact powers land in their own
    bucket's upper edge, not the next one up)."""
    m, e = math.frexp(v)          # v = m * 2**e, m in [0.5, 1)
    if m == 0.5:
        e -= 1
    return e


class Histogram:
    """Fixed power-of-two log-bucket histogram with exact quantile
    *bounds*. Bucket ``e`` holds observations in ``(2**(e-1), 2**e]``
    (non-positive values get their own underflow bucket), so the full
    float range needs ~60 sparse buckets, recording is O(1), and
    ``quantile(q)`` is an upper bound on the true q-quantile that is
    never more than 2x above it — the resolution the power-of-two
    bucket family buys. Exact ``count``/``sum``/``min``/``max`` ride
    along, so ``max`` and ``mean`` are exact, not bounds."""

    __slots__ = ("_lock", "_buckets", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._buckets: Dict[Optional[int], int] = {}  # exp -> count
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        e = _bucket_exp(v) if v > 0.0 else None       # None: v <= 0
        with self._lock:
            self._buckets[e] = self._buckets.get(e, 0) + 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def _sorted_buckets(self) -> List[Tuple[Optional[int], int]]:
        return sorted(self._buckets.items(),
                      key=lambda kv: -math.inf if kv[0] is None
                      else kv[0])

    def quantile(self, q: float) -> Optional[float]:
        """Upper BOUND on the q-quantile: the upper edge of the bucket
        holding the rank-⌈qN⌉ observation (capped at the exact max).
        The true quantile lies in ``(bound/2, bound]``. None when
        empty."""
        with self._lock:
            n = self.count
            if not n:
                return None
            rank = min(n, max(1, math.ceil(q * n)))
            c = 0
            for e, k in self._sorted_buckets():
                c += k
                if c >= rank:
                    if e is None:
                        return min(0.0, self.max)
                    return min(math.ldexp(1.0, e), self.max)
        return self.max           # pragma: no cover - loop covers n>0

    def summary(self, scale: float = 1.0,
                ndigits: int = 6) -> Dict[str, Any]:
        """The artifact block: count + exact mean/max + p50/p90/p99
        quantile bounds, all scaled (pass ``scale=1e3`` for ms)."""
        if not self.count:
            return {"count": 0}
        r = lambda v: round(v * scale, ndigits)  # noqa: E731
        return {"count": self.count,
                "mean": r(self.sum / self.count),
                "p50": r(self.quantile(0.50)),
                "p90": r(self.quantile(0.90)),
                "p99": r(self.quantile(0.99)),
                "max": r(self.max)}

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """(upper_edge, count) per occupied bucket, ascending — the
        exposition's cumulative-``le`` series is built from this."""
        with self._lock:
            return [(0.0 if e is None else math.ldexp(1.0, e), k)
                    for e, k in self._sorted_buckets()]


class CounterMetric:
    """Monotonic event counter."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Time-series gauge: every ``set`` keeps a (monotonic seconds,
    value) sample — bounded by ``maxlen`` so an unbounded stream holds
    a window, not the full history — plus the exact last and max. The
    upgrade over ``DispatchCount.gauges``' high-water mark: the series
    shows *how long* a level (the streaming receiver's overlap depth)
    was sustained, not just that it was reached once."""

    __slots__ = ("_lock", "samples", "last", "max")

    def __init__(self, maxlen: int = 4096) -> None:
        self._lock = threading.Lock()
        self.samples: deque = deque(maxlen=maxlen)
        self.last: Optional[float] = None
        self.max = -math.inf

    def set(self, value: float, t: Optional[float] = None) -> None:
        v = float(value)
        with self._lock:
            self.samples.append(
                (time.perf_counter() if t is None else t, v))
            self.last = v
            if v > self.max:
                self.max = v


def _metric_key(name: str, labels: Dict[str, str]):
    return (name, tuple(sorted(labels.items())))


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f'{k}="{v}"' for k, v in labels)


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset ([a-zA-Z0-9_:])."""
    return "".join(c if c.isalnum() or c in "_:" else "_"
                   for c in name)


class MetricsRegistry:
    """Thread-safe name+labels -> metric map. Metrics are get-or-create
    (:meth:`counter` / :meth:`gauge` / :meth:`histogram`), readable as
    a plain dict (:meth:`snapshot`, for JSON artifacts) or as a
    Prometheus-style text page (:meth:`exposition`, the
    ``--metrics-dump`` output)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, Any] = {}

    def _get(self, cls, name: str, labels: Dict[str, str]):
        key = _metric_key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls()
                self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r}{dict(labels)} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def counter(self, name: str, **labels: str) -> CounterMetric:
        return self._get(CounterMetric, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get(Histogram, name, labels)

    def metrics(self) -> List[Tuple[Tuple[str, Tuple], Any]]:
        """[(name, labels), metric] pairs, stable-sorted — the raw
        iteration surface bench tooling reads percentile blocks off."""
        with self._lock:
            return sorted(self._metrics.items(), key=lambda kv: kv[0])

    def find(self, name: str, **labels: str):
        """The metric at name+labels, or None (never creates)."""
        return self._metrics.get(_metric_key(name, labels))

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view: ``{name{labels}: value}`` for counters and
        gauges (gauges as {last, max, samples}), histogram summaries
        for histograms. JSON-serializable as-is."""
        out: Dict[str, Any] = {}
        for (name, labels), m in self.metrics():
            key = name + ("{%s}" % _label_str(labels) if labels else "")
            if isinstance(m, CounterMetric):
                out[key] = m.value
            elif isinstance(m, Gauge):
                with m._lock:
                    out[key] = {"last": m.last, "max": m.max,
                                "samples": [[round(t, 6), v]
                                            for t, v in m.samples]}
            else:
                out[key] = m.summary()
        return out

    def exposition(self) -> str:
        """Prometheus text exposition: counters and gauges as single
        samples, histograms as the standard cumulative ``_bucket{le=}``
        / ``_sum`` / ``_count`` series (bucket edges are this module's
        powers of two)."""
        by_name: Dict[str, List[Tuple[Tuple, Any]]] = {}
        for (name, labels), m in self.metrics():
            by_name.setdefault(name, []).append((labels, m))
        lines: List[str] = []
        for name, entries in sorted(by_name.items()):
            pname = _sanitize(name)
            kind = entries[0][1]
            typ = ("counter" if isinstance(kind, CounterMetric)
                   else "gauge" if isinstance(kind, Gauge)
                   else "histogram")
            lines.append(f"# TYPE {pname} {typ}")
            for labels, m in entries:
                ls = _label_str(labels)
                if isinstance(m, CounterMetric):
                    lines.append(f"{pname}{{{ls}}} {m.value}" if ls
                                 else f"{pname} {m.value}")
                elif isinstance(m, Gauge):
                    v = m.last if m.last is not None else "NaN"
                    lines.append(f"{pname}{{{ls}}} {v}" if ls
                                 else f"{pname} {v}")
                else:
                    cum = 0
                    for edge, k in m.bucket_counts():
                        cum += k
                        le = f'le="{edge!r}"'
                        full = f"{ls},{le}" if ls else le
                        lines.append(f"{pname}_bucket{{{full}}} {cum}")
                    full = f"{ls},le=\"+Inf\"" if ls else 'le="+Inf"'
                    lines.append(f"{pname}_bucket{{{full}}} {m.count}")
                    sfx = f"{{{ls}}}" if ls else ""
                    lines.append(f"{pname}_sum{sfx} {m.sum!r}")
                    lines.append(f"{pname}_count{sfx} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------------ traces


class Trace:
    """Chrome trace-event collector. Spans land as complete ("X")
    events with microsecond timestamps relative to the trace's own
    monotonic epoch; gauges as counter ("C") tracks; compile events in
    the ``compile`` category. :meth:`export` writes the standard
    ``{"traceEvents": [...]}`` JSON object (Perfetto /
    ``chrome://tracing`` / ``tools/trace_report.py``)."""

    def __init__(self, annotate_device: bool = False) -> None:
        self.annotate_device = annotate_device
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._meta: Dict[str, Any] = {}
        self._epoch = time.perf_counter()
        self._pid = os.getpid()

    def _ts(self, t: float) -> float:
        return (t - self._epoch) * 1e6          # µs, trace-relative

    def add_event(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(ev)

    def complete(self, name: str, t0: float, dur_s: float,
                 tid: Optional[int] = None, args: Optional[dict] = None,
                 cat: str = "host") -> None:
        """A finished span: began at monotonic ``t0``, ran ``dur_s``."""
        ev = {"name": name, "ph": "X", "cat": cat,
              "ts": self._ts(t0), "dur": dur_s * 1e6,
              "pid": self._pid,
              "tid": threading.get_ident() if tid is None else tid}
        if args:
            ev["args"] = args
        self.add_event(ev)

    def instant(self, name: str, args: Optional[dict] = None,
                cat: str = "host") -> None:
        ev = {"name": name, "ph": "i", "s": "t", "cat": cat,
              "ts": self._ts(time.perf_counter()), "pid": self._pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self.add_event(ev)

    def counter(self, name: str, value: float) -> None:
        """One sample of a counter track — gauge levels plotted over
        time (the in-flight depth, the carry depth, frames emitted)."""
        self.add_event({"name": name, "ph": "C",
                        "ts": self._ts(time.perf_counter()),
                        "pid": self._pid, "args": {"value": value}})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def set_metadata(self, key: str, value: Any) -> None:
        """Attach a top-level key to the exported trace object (the
        Chrome trace format ignores unknown object keys, so riders
        like the observatory's ``siteCosts`` travel with the events
        and tools/trace_report.py can join on span labels)."""
        with self._lock:
            self._meta[key] = value

    def to_json(self) -> Dict[str, Any]:
        obj: Dict[str, Any] = {"traceEvents": self.events(),
                               "displayTimeUnit": "ms"}
        with self._lock:
            obj.update(self._meta)
        return obj

    def export(self, path: Optional[str] = None) -> Dict[str, Any]:
        """The trace as a Chrome trace-event JSON object; written to
        ``path`` when given. Returns the object either way."""
        obj = self.to_json()
        if path:
            with open(path, "w") as f:
                json.dump(obj, f)
        return obj


_ANN_CLS: Any = None       # cached jax.profiler.TraceAnnotation


def _annotation_cls():
    """``jax.profiler.TraceAnnotation`` resolved once, lazily — jax is
    deliberately not imported at module load (telemetry must stay
    importable in jax-free tooling) and unavailable annotations
    degrade to plain host spans."""
    global _ANN_CLS
    if _ANN_CLS is None:
        try:
            from jax.profiler import TraceAnnotation
            _ANN_CLS = TraceAnnotation
        except Exception:          # pragma: no cover - jax-free env
            _ANN_CLS = False
    return _ANN_CLS or None


@contextmanager
def span(name: str, args: Optional[dict] = None):
    """``with span("rx.stream_chunk"): ...`` — record the block as one
    trace span in every active trace (nesting and thread identity come
    from timestamps + tid, the Chrome trace model). Free when no trace
    is active. When an active trace was built with
    ``annotate_device=True``, the block also runs under
    ``jax.profiler.TraceAnnotation(name)`` so a concurrent device
    profile shows the same label."""
    traces = _TRACES
    if not traces:
        yield
        return
    if not _listener_installed:
        # activation may have preceded the jax import (the CLI shell
        # activates before _run_cmd imports jax): retry here, BEFORE
        # the traced call — dispatch.timed enters this span ahead of
        # the jit call, so even the first compile is captured
        _install_compile_listener()
    ann = None
    if any(t.annotate_device for t in traces):
        cls = _annotation_cls()
        if cls is not None:
            ann = cls(name)
            ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        for t in traces:
            t.complete(name, t0, dur, args=args)


# ------------------------------------------------- activation / lifecycle


def _without_last(sinks: Tuple, x) -> Tuple:
    """``sinks`` minus ONE occurrence of ``x`` (the last) — so
    activating the same Trace/MetricsRegistry object in nested blocks
    stays balanced: the inner exit removes one activation, not all of
    them."""
    for i in range(len(sinks) - 1, -1, -1):
        if sinks[i] is x:
            return sinks[:i] + sinks[i + 1:]
    return sinks


@contextmanager
def tracing(path: Optional[str] = None, annotate_device: bool = False,
            trace: Optional[Trace] = None):
    """Activate a :class:`Trace` for the block (a fresh one, or the
    one passed in); on exit deactivate and — when ``path`` is given —
    export the Chrome trace JSON there (export runs even when the
    block raises: a crashed run's trace is the one you want most)."""
    global _TRACES
    t = trace if trace is not None else Trace(
        annotate_device=annotate_device)
    with _LOCK:
        _TRACES = _TRACES + (t,)
    _install_compile_listener()
    try:
        yield t
    finally:
        with _LOCK:
            _TRACES = _without_last(_TRACES, t)
        if path:
            t.export(path)


@contextmanager
def collect(registry: Optional[MetricsRegistry] = None):
    """Activate a :class:`MetricsRegistry` for the block; yields it.
    Every emitter sample recorded while active lands in it."""
    global _REGISTRIES
    r = registry if registry is not None else MetricsRegistry()
    with _LOCK:
        _REGISTRIES = _REGISTRIES + (r,)
    _install_compile_listener()
    try:
        yield r
    finally:
        with _LOCK:
            _REGISTRIES = _without_last(_REGISTRIES, r)


def env_trace_path() -> Optional[str]:
    """The ONE reading of the ZIRIA_TRACE knob (the CLI's ``--trace``
    writes it via the scoped-env pattern; exporting it directly works
    for any invocation): a path means 'trace this run and export the
    Chrome trace JSON there'."""
    return os.environ.get("ZIRIA_TRACE") or None


# -------------------------------------------------------------- emitters
#
# Thin, fixed-name funnels `utils/dispatch` (and the streaming
# receiver) pour into. All are free when nothing is active.

DISPATCH_COUNTER = "ziria_dispatches_total"
DISPATCH_HISTOGRAM = "ziria_dispatch_seconds"
GAUGE_METRIC = "ziria_gauge"
COMPILE_COUNTER = "ziria_compile_events_total"
COMPILE_HISTOGRAM = "ziria_compile_seconds"


def dispatch_event(label: str, n: int = 1,
                   seconds: Optional[float] = None) -> None:
    """One instrumented dispatch site firing: counter always,
    histogram observation when the site is timed."""
    if _REGISTRIES and not _listener_installed:
        _install_compile_listener()   # activation preceded jax import
    for r in _REGISTRIES:
        r.counter(DISPATCH_COUNTER, site=label).inc(n)
        if seconds is not None:
            r.histogram(DISPATCH_HISTOGRAM, site=label).observe(seconds)


def gauge_sample(label: str, value: float) -> None:
    """One level sample: a time-series point in every active registry
    AND a counter-track event in every active trace — the level is
    plottable over time, not just a high-water mark."""
    if not (_TRACES or _REGISTRIES):
        return
    t = time.perf_counter()
    for r in _REGISTRIES:
        r.gauge(GAUGE_METRIC, site=label).set(value, t)
    for tr in _TRACES:
        tr.counter(label, value)


def observe(name: str, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
    """One free-standing histogram observation (the resilience
    layer's backoff delays, the serving runtime's per-chunk-step
    latency): lands in every active registry's ``name`` histogram
    (label-partitioned when ``labels`` is given). Free when nothing
    is collecting."""
    if not _REGISTRIES:
        return
    for r in _REGISTRIES:
        r.histogram(name, **(labels or {})).observe(value)


def count(name: str, n: int = 1,
          total: Optional[float] = None,
          labels: Optional[Dict[str, str]] = None) -> None:
    """An event counter (frames emitted, sessions admitted):
    increments every active registry; when the caller passes its
    cumulative ``total``, active traces get a counter-track sample so
    the count is plottable over the run. ``labels`` partitions the
    counter per label set (the serving runtime's attributable
    ``serve.shed{reason=...}`` discipline) — the exposition carries
    each label series separately."""
    if not (_TRACES or _REGISTRIES):
        return
    for r in _REGISTRIES:
        r.counter(name, **(labels or {})).inc(n)
    if total is not None:
        for tr in _TRACES:
            tr.counter(name, total)


def record_compile(label: str, seconds: Optional[float] = None,
                   n: int = 1, args: Optional[dict] = None) -> None:
    """A compile-ish event. With ``seconds`` (an XLA compile stall's
    measured duration) it lands as a trace span in the ``compile``
    category ending now; without (a jit-cache growth delta) as an
    instant marker. Registries get the counter and — when timed — the
    compile-latency histogram."""
    if not (_TRACES or _REGISTRIES):
        return
    now = time.perf_counter()
    for t in _TRACES:
        if seconds:
            t.complete(label, now - seconds, seconds, cat="compile",
                       args=args)
        else:
            a = dict(args or {})
            a.setdefault("count", n)   # the marker carries its weight
            t.instant(label, args=a, cat="compile")
    for r in _REGISTRIES:
        r.counter(COMPILE_COUNTER, event=label).inc(n)
        if seconds:
            r.histogram(COMPILE_HISTOGRAM, event=label).observe(seconds)


# ------------------------------------------------- XLA compile listener

_listener_installed = False


def _on_jax_duration(event: str, duration: float, **kw) -> None:
    """jax.monitoring duration callback: surface compile-flavored
    events (backend_compile, trace/lowering stalls) into whatever is
    active. Fast no-op otherwise — the listener stays registered for
    the life of the process once installed."""
    if not (_TRACES or _REGISTRIES):
        return
    if "compile" not in event and "trace" not in event:
        return
    record_compile(f"xla:{event.strip('/')}", seconds=float(duration))


def _install_compile_listener() -> None:
    """Register the jax.monitoring duration listener once, lazily, on
    the first activation AFTER jax is in play — importing jax (or
    running without it) before any telemetry is used costs nothing,
    and a deliberately jax-free process (the serving smoke, the trace
    tooling) activating telemetry must never drag jax in: when jax is
    absent the install is deferred, and the next activation — or the
    first span/dispatch emission after a jax import (the CLI shell
    activates before its command imports jax) — picks it up."""
    global _listener_installed
    if _listener_installed:
        return
    if "jax" not in sys.modules:
        return
    _listener_installed = True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_jax_duration)
    except Exception:              # pragma: no cover - jax-free env
        pass
