"""The ONE declarative geometry object behind every jit factory.

Fifteen PRs hand-picked this tree's equivalents of the paper's
vectorization widths — ``chunk_len`` (the streaming window),
``max_frames_per_chunk`` (K), ``n_streams`` (S, the fleet width), the
power-of-two bucket floors (symbol 4 / capture 512 / TX bit 128), the
detector parameters, the Viterbi ``(window, metric, radix)`` triple,
``fused_demap``, ``sco_track`` — as constants scattered across call
sites, env knobs, and compile-cache keys. :class:`Geometry` folds all
of them into one frozen, hashable dataclass:

- **Defaults are today's constants.** ``Geometry()`` resolves to
  exactly the values every surface used before this module existed,
  so the default object is a no-op by construction: zero new compiled
  programs (``dispatch.no_recompile`` pins this in
  tests/test_geometry.py), identical checkpoint geometry
  fingerprints, identical emissions bit for bit.
- **resolve() folds CLI/env knobs exactly once.** The ``None``-valued
  decode-mode fields (viterbi window/metric/radix, fused_demap,
  sco_track) mean "read the env default"; :meth:`Geometry.resolve`
  replaces them with concrete values through this module's designated
  single-readers (``env_*`` — jaxlint R4's naming convention), and
  the resolved values are what the jit-factory caches key on. The
  legacy readers (``rx.sco_track_enabled``, ``rx.fused_demap_enabled``,
  ``externals.viterbi_mode``, ``viterbi._check_radix``) all delegate
  here, so each knob still has ONE env read in the whole tree.
- **The factories keep their scalar keys.** A ``Geometry`` is the
  *source* of the cache key, not the key object itself: drivers and
  constructors (``StreamReceiver``, ``MultiStreamReceiver``,
  ``ServeConfig``, ``link.loopback_many``, ``rx.receive``) accept a
  ``geometry=`` and derive the exact scalar tuples the ``_jit_*``
  factories cache on. Two geometries that agree on a factory's knobs
  share its compiled program (a tuned ``chunk_len`` never forks the
  decode caches), and data-dependent buckets (``n_sym_bucket`` from
  an input's length) stay derived-per-call through the bucket *rules*
  this object owns (:meth:`sym_bucket` / :meth:`capture_bucket` /
  :meth:`bit_bucket` — jaxlint R6 flags literal floors at call
  sites).
- **tuned() loads the measured per-device winner.** The autotuner
  (:mod:`ziria_tpu.utils.autotune`, ``python -m ziria_tpu autotune``)
  records winners keyed by ``device_kind`` into the bench trajectory
  ledger; :meth:`Geometry.tuned` reconstructs the latest matching
  record, falling back to the default on any miss — an absent ledger,
  an unknown device, a malformed record (docs/autotune.md).

jax-free by design (like runtime/serve and utils/telemetry): the
geometry must be constructible, resolvable, and serializable through
TPU probe hangs — ``tools/geometry_smoke.py`` is the precommit gate
for exactly that.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from ziria_tpu.utils.dispatch import pow2_bucket

#: valid Viterbi metric dtypes — ops/viterbi.METRIC_DTYPES aliases
#: this tuple, so the validation set cannot drift from the kernels
VITERBI_METRICS = ("float32", "int16", "int8")
#: valid Viterbi ACS radixes — ops/viterbi.RADIXES aliases this
VITERBI_RADIXES = (2, 4)

#: ledger file the autotuner records winners into (repo root; the
#: BENCH_TRAJECTORY env var overrides, exactly like bench.py)
TRAJECTORY_BASENAME = "BENCH_TRAJECTORY.jsonl"


# --------------------------------------------------- designated env readers
#
# jaxlint R4 allows env reads only inside designated single-reader
# functions (the `env_*`/`*_enabled`/`*_mode`/`check_*` naming
# convention). These are THE readers of the geometry knobs' env
# defaults; every legacy reader elsewhere in the tree delegates here.


def env_viterbi_window() -> int:
    """ZIRIA_VITERBI_WINDOW: sliding-window decode length, 0 = off.
    An unparseable value degrades to 0 (off, the safe default) —
    externals.viterbi_mode's long-standing contract."""
    try:
        return int(os.environ.get("ZIRIA_VITERBI_WINDOW", "0"))
    except ValueError:
        return 0


def env_viterbi_metric() -> str:
    """ZIRIA_VITERBI_METRIC: ACS metric dtype (default float32). An
    unknown metric raises — the quantized kernels are an opt-in
    accuracy trade that must never be silently dropped."""
    md = os.environ.get("ZIRIA_VITERBI_METRIC") or "float32"
    if md not in VITERBI_METRICS:
        raise ValueError(
            f"ZIRIA_VITERBI_METRIC={md!r} is not one of "
            f"{VITERBI_METRICS}")
    return md


def env_viterbi_radix() -> int:
    """ZIRIA_VITERBI_RADIX: ACS radix (default 2, the oracle). An
    unknown radix raises — an opt-in kernel rewrite must never be
    silently dropped."""
    raw = os.environ.get("ZIRIA_VITERBI_RADIX") or "2"
    try:
        radix = int(raw)
    except ValueError:
        raise ValueError(
            f"ZIRIA_VITERBI_RADIX={raw!r} is not one of "
            f"{VITERBI_RADIXES}")
    if radix not in VITERBI_RADIXES:
        raise ValueError(
            f"ZIRIA_VITERBI_RADIX={radix!r} is not one of "
            f"{VITERBI_RADIXES}")
    return radix


def env_fused_demap() -> bool:
    """ZIRIA_FUSED_DEMAP (default OFF — the XLA front end is the
    oracle): run demap+deinterleave+depuncture as an in-kernel
    prologue of the Pallas ACS, on BOTH the known-rate decode
    (`viterbi_decode_batch_fused`) and the rate-switched mixed decode
    every streaming/fleet surface runs (`viterbi_decode_mixed_fused`
    — the stacked 8-rate constant bank, row-selected in-kernel)."""
    return os.environ.get("ZIRIA_FUSED_DEMAP", "0") == "1"


def env_sco_track() -> bool:
    """ZIRIA_RX_SCO_TRACK (default OFF — the flat-profile bit-identity
    contract pins the default DATA decode bitwise): pilot phase-ramp
    tracking for sampling-clock offset."""
    return os.environ.get("ZIRIA_RX_SCO_TRACK", "0") == "1"


def env_trajectory_path() -> str:
    """The ONE reading of the BENCH_TRAJECTORY ledger-path override
    (bench.py and tools/perf_report.py honor the same variable);
    default: the repo-root ledger next to this package."""
    p = os.environ.get("BENCH_TRAJECTORY")
    if p:
        return p
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, TRAJECTORY_BASENAME)


# --------------------------------------------------------------- the object


@dataclasses.dataclass(frozen=True)
class Geometry:
    """Every tunable of the compiled transceiver, in one frozen,
    hashable value. Field defaults ARE the tree's historical
    constants; ``None`` decode-mode fields mean "resolve from env"
    (:meth:`resolve`). See the module docstring for how instances
    thread into the jit factories without forking their caches."""

    # streaming window geometry (StreamReceiver / fleet / ServeConfig)
    chunk_len: int = 1 << 13
    frame_len: int = 2048
    max_frames_per_chunk: int = 8         # K
    n_streams: int = 8                    # S, the fleet width
    # power-of-two bucket floors (the pow2_bucket rules)
    sym_bucket_min: int = 4
    capture_bucket_min: int = 512
    bit_bucket_min: int = 128
    # detector parameters (part of _jit_stream_chunk's cache key)
    threshold: float = 0.75
    min_run: int = 33
    dead_zone: int = 320
    # decode-mode knobs; None = fold the env default in resolve()
    viterbi_window: Optional[int] = None
    viterbi_metric: Optional[str] = None
    viterbi_radix: Optional[int] = None
    fused_demap: Optional[bool] = None
    sco_track: Optional[bool] = None

    # -- bucket rules (jaxlint R6: literal floors at call sites are
    # -- findings; these methods are the one place the floors live) --

    def sym_bucket(self, n_sym: int) -> int:
        """Power-of-two symbol bucket — the SHARED TX/RX rule, so a
        loopback's encode and decode geometries agree by
        construction."""
        return pow2_bucket(n_sym, self.sym_bucket_min)

    def capture_bucket(self, n: int) -> int:
        """Power-of-two capture bucket — the ONE padding formula the
        per-capture and batched/streaming acquisition paths share."""
        return pow2_bucket(n, self.capture_bucket_min)

    def bit_bucket(self, n_bits: int) -> int:
        """Power-of-two PSDU bit bucket (the floor keeps tiny frames
        — ACKs, MAC control — in one compile class)."""
        return pow2_bucket(n_bits, self.bit_bucket_min)

    # ------------------------------------------------------- resolution

    def resolve(self) -> "Geometry":
        """Fold the env defaults into every ``None`` decode-mode knob
        — the ONE place CLI/env reaches the geometry (the CLI writes
        scoped env vars; jaxlint R4 keeps every other module out of
        os.environ). Validates metric/radix; idempotent; returns a
        fully-concrete (and therefore cache-key-ready) Geometry."""
        vw = self.viterbi_window
        vm = self.viterbi_metric
        vr = self.viterbi_radix
        if vm is not None and vm not in VITERBI_METRICS:
            raise ValueError(
                f"viterbi_metric {vm!r} is not one of {VITERBI_METRICS}")
        if vr is not None and int(vr) not in VITERBI_RADIXES:
            raise ValueError(
                f"viterbi_radix {vr!r} is not one of {VITERBI_RADIXES}")
        return dataclasses.replace(
            self,
            viterbi_window=env_viterbi_window() if vw is None else int(vw),
            viterbi_metric=env_viterbi_metric() if vm is None else vm,
            viterbi_radix=env_viterbi_radix() if vr is None else int(vr),
            fused_demap=(env_fused_demap() if self.fused_demap is None
                         else bool(self.fused_demap)),
            sco_track=(env_sco_track() if self.sco_track is None
                       else bool(self.sco_track)))

    def replace(self, **changes: Any) -> "Geometry":
        """`dataclasses.replace` convenience (the autotuner's candidate
        enumeration is built from these)."""
        return dataclasses.replace(self, **changes)

    # ---------------------------------------------------- serialization

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Geometry":
        """Strict inverse of :meth:`as_dict`: unknown keys raise (a
        ledger record from a future field set must not silently drop
        a tunable — :meth:`tuned` catches and falls back)."""
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(
                f"unknown Geometry field(s): {', '.join(unknown)}")
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "Geometry":
        return cls.from_dict(json.loads(s))

    # ------------------------------------------------------ tuned winner

    @classmethod
    def tuned(cls, device_kind: Optional[str] = None,
              path: Optional[str] = None) -> "Geometry":
        """The latest autotuner winner recorded for ``device_kind``
        (default: this process's jax device kind), reconstructed from
        the bench trajectory ledger — or the default ``Geometry()``
        when there is no ledger, no matching record, or a record this
        build cannot parse. Never raises: the tuned geometry is an
        optimization, and a stale/foreign ledger must degrade to the
        hand-picked constants, not crash the receiver."""
        try:
            if device_kind is None:
                device_kind = detect_device_kind()
            rec = latest_tuned_record(device_kind, path)
            if rec is None:
                return cls()
            return cls.from_dict(rec["geometry"])
        except Exception:
            return cls()


#: the shared default instance — ctor defaults across framebatch /
#: serve / link derive from this, so "1 << 13" exists ONCE (above)
DEFAULT = Geometry()


def detect_device_kind() -> Optional[str]:
    """``jax.devices()[0].device_kind`` — lazily, so this module stays
    importable (and the smoke runnable) with no jax at all. None when
    jax or a backend is unavailable."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return None


def latest_tuned_record(device_kind: Optional[str],
                        path: Optional[str] = None) -> Optional[Dict]:
    """Scan the trajectory ledger for the newest ``stage=autotune``
    record whose ``device_kind`` matches (None matches None: a ledger
    written where jax could not name the device still serves that same
    environment). Returns the record dict, or None."""
    p = path or env_trajectory_path()
    best = None
    try:
        with open(p, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("stage") != "autotune":
                    continue
                if "geometry" not in rec:
                    continue
                if rec.get("device_kind") != device_kind:
                    continue
                if best is None or rec.get("unix", 0) >= best.get(
                        "unix", 0):
                    best = rec
    except OSError:
        return None
    return best
