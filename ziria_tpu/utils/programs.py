"""Compiled-program observatory: what did XLA actually emit, and how
close is each dispatch site to the hardware ceiling?

The tree's perf story (ROADMAP: 4.1% of HBM peak, 0.2% of FLOPs peak
on v5e) has so far rested on hand-derived byte/FLOP formulas
(bench.py's ``_roofline``) while the compiled graphs themselves carry
the exact numbers: every jitted program exposes
``lowered.compile().cost_analysis()`` (FLOPs, bytes accessed) and
``memory_analysis()`` (argument/output/temp HBM). This module turns
those into a first-class surface — and, crucially, one that works
CPU-only, so cost attribution keeps flowing through the TPU probe
hangs that have starved BENCH since r05.

Three layers:

- **Site notes** (:func:`note_site` + :func:`observing`): each
  instrumented dispatch site (the same ``utils/dispatch.timed``
  labels the telemetry layer uses) reports the jitted callable and
  its argument avals when an :class:`Observatory` is active — free
  when idle (one truthiness check), and only shapes/dtypes are held,
  never device buffers. After a run, :meth:`Observatory.analyze`
  lowers each noted program and attributes analytical cost to its
  site label, so a measured p50 latency and an analytical byte count
  join on the label: achieved GB/s / GFLOP/s *per dispatch site*
  (`tools/rx_dispatch_bench.py` stats blocks, `tools/trace_report.py`
  via the trace's embedded ``siteCosts``).

- **Factory discovery** (:func:`discovered_factories`): the compiled
  programs live behind the tree's ``@lru_cache`` jit factories. The
  factories are DISCOVERED with jaxlint R1's convention
  (`ziria_tpu.analysis`: an ``@lru_cache`` def whose body builds a
  jitted callable), never hardcoded, and :func:`coverage` maps noted
  programs back to their factories — a factory a future PR adds shows
  up as *uncovered* in the report instead of silently missing.

- **Device peaks** (:data:`DEVICE_PEAKS`): the per-``device_kind``
  peak table that replaces bench.py's hardcoded v5e constants.
  Unknown kinds report absolute achieved numbers with the ``pct_*``
  fields omitted — absent, not wrong.

CLI: ``python -m ziria_tpu programs [--json] [--hlo-dump DIR]`` pins
the CPU backend (no TPU needed, same mechanism as bench.py's parent),
drives every dispatch surface once at a tiny geometry
(:func:`run_driver`), and prints the per-program cost table.
"""

from __future__ import annotations

import ast
import json
import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

# ------------------------------------------------------------ device peaks
#
# Single-chip peaks per device_kind, seeded from the v5e constants the
# bench carried since round 3 (HBM 819 GB/s, bf16 197 TFLOP/s). Keys
# are normalized device-kind strings (`_peaks_key`); an unknown kind
# yields None and every consumer then reports achieved absolutes with
# the pct_* fields omitted — never a percentage of the wrong ceiling.

DEVICE_PEAKS: Dict[str, Dict[str, float]] = {
    "v5e": {"hbm_gbps": 819.0, "peak_tflops": 197.0},
}

#: observed device_kind spellings -> DEVICE_PEAKS key
_DEVICE_KIND_KEYS = {
    "tpu v5 lite": "v5e",
    "tpu v5e": "v5e",
    "tpu v5lite": "v5e",
    "v5e": "v5e",
    "v5litepod": "v5e",
}


def peaks_for(device_kind: Optional[str]) -> Optional[Dict[str, float]]:
    """The peak table entry for a ``jax.Device.device_kind`` string,
    or None when the kind is unknown (consumers must then omit the
    pct_* fields, not guess a ceiling)."""
    if not device_kind:
        return None
    k = str(device_kind).strip().lower()
    key = _DEVICE_KIND_KEYS.get(k, k if k in DEVICE_PEAKS else None)
    return DEVICE_PEAKS.get(key) if key else None


def roofline(seconds: float, bytes_accessed: Optional[float] = None,
             flops: Optional[float] = None,
             device_kind: Optional[str] = None) -> Dict[str, float]:
    """Achieved GB/s / GFLOP/s for one dispatch of a program whose
    analytical cost is (``bytes_accessed``, ``flops``) and whose
    measured latency is ``seconds`` — plus %-of-peak when the
    ``device_kind`` is in :data:`DEVICE_PEAKS`."""
    out: Dict[str, float] = {}
    if not seconds or seconds <= 0:
        return out
    peaks = peaks_for(device_kind)
    if bytes_accessed:
        gbps = bytes_accessed / seconds / 1e9
        out["achieved_gbps"] = round(gbps, 3)
        if peaks:
            out["pct_hbm_peak"] = round(100 * gbps / peaks["hbm_gbps"], 3)
    if flops:
        gflops = flops / seconds / 1e9
        out["achieved_gflops"] = round(gflops, 3)
        if peaks:
            out["pct_flops_peak"] = round(
                100 * gflops / 1e3 / peaks["peak_tflops"], 4)
    return out


# ------------------------------------------------------------ observatory


def _aval(x: Any) -> Any:
    """Shape/dtype skeleton of a call argument: arrays become
    ``jax.ShapeDtypeStruct`` (never holding the buffer), everything
    else (python scalars, tuples of scalars) passes through."""
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    if isinstance(x, (tuple, list)):
        return type(x)(_aval(e) for e in x)
    return x


def _sig(avals: Tuple, kwavals: Dict) -> str:
    """Stable geometry signature for dedupe: one record per (label,
    argument geometry), however many times the site fired."""
    def one(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return f"{getattr(a, 'dtype', '?')}{tuple(a.shape)}"
        return repr(a)

    parts = [one(a) for a in avals]
    parts += [f"{k}={one(v)}" for k, v in sorted(kwavals.items())]
    return ",".join(parts)


@dataclass
class ProgramNote:
    """One live compiled program a dispatch site reported: the jitted
    callable plus the argument geometry it was fired at."""
    label: str
    fn: Any
    avals: Tuple
    kwavals: Dict[str, Any]
    calls: int = 0

    @property
    def jit_name(self) -> Tuple[str, str]:
        """(module, qualname) of the traced python function behind the
        jitted callable — the linkage :func:`coverage` matches against
        the AST-discovered factories."""
        w = getattr(self.fn, "__wrapped__", None)
        return (getattr(w, "__module__", "") or "",
                getattr(w, "__qualname__", "") or "")


class Observatory:
    """Collects :class:`ProgramNote` entries while active (see
    :func:`observing`) and turns them into cost/memory records."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.notes: Dict[Tuple[str, str], ProgramNote] = {}

    def _note(self, label: str, fn: Any, avals: Tuple,
              kwavals: Dict[str, Any]) -> None:
        key = (label, _sig(avals, kwavals))
        with self._lock:
            n = self.notes.get(key)
            if n is None:
                n = self.notes[key] = ProgramNote(label, fn, avals,
                                                  kwavals)
            n.calls += 1

    def analyze(self, hlo_dump: Optional[str] = None) -> List[Dict]:
        """One cost/memory record per noted program (lowered and
        compiled at the noted avals — CPU-only safe). A program that
        fails to lower yields an ``error`` record instead of killing
        the sweep."""
        out = []
        for (label, sig), n in sorted(self.notes.items()):
            mod, qual = n.jit_name
            rec: Dict[str, Any] = {
                "label": label, "module": mod, "jit_qualname": qual,
                "in_avals": sig, "calls": n.calls,
            }
            try:
                rec.update(cost_of(n.fn, *n.avals, **n.kwavals))
                if hlo_dump:
                    os.makedirs(hlo_dump, exist_ok=True)
                    fname = f"{label.replace('/', '_')}_{abs(hash(sig)) & 0xffffff:06x}.hlo.txt"
                    path = os.path.join(hlo_dump, fname)
                    with open(path, "w") as f:
                        f.write(hlo_text(n.fn, *n.avals, **n.kwavals))
                    rec["hlo_path"] = path
            except Exception as e:      # pragma: no cover - backend oddity
                rec["error"] = repr(e)
            out.append(rec)
        return out

    def site_costs(self) -> Dict[str, Dict]:
        """Per-site analytical cost: the LARGEST-bytes geometry noted
        per label (the steady-state dispatch; warm-up oddities at
        smaller geometry lose). The join key for a site's measured
        p50 latency."""
        best: Dict[str, Dict] = {}
        for rec in self.analyze():
            if rec.get("error") or not rec.get("bytes_accessed"):
                continue
            cur = best.get(rec["label"])
            if cur is None or rec["bytes_accessed"] > cur["bytes_accessed"]:
                best[rec["label"]] = rec
        return best


_LOCK = threading.Lock()
_ACTIVE: Tuple[Observatory, ...] = ()


def note_site(label: str, fn: Any, *args: Any, **kwargs: Any) -> None:
    """Report a dispatch site's jitted callable + call geometry to
    every active observatory. Free when none is active (one truthiness
    check) — the hot paths carry the annotation permanently, like
    their ``dispatch.timed`` wrapper."""
    if not _ACTIVE:
        return
    avals = tuple(_aval(a) for a in args)
    kwavals = {k: _aval(v) for k, v in kwargs.items()}
    for o in _ACTIVE:
        o._note(label, fn, avals, kwavals)


@contextmanager
def observing(obs: Optional[Observatory] = None):
    """Activate an :class:`Observatory` for the block; yields it."""
    global _ACTIVE
    o = obs if obs is not None else Observatory()
    with _LOCK:
        _ACTIVE = _ACTIVE + (o,)
    try:
        yield o
    finally:
        with _LOCK:
            lst = list(_ACTIVE)
            for i in range(len(lst) - 1, -1, -1):
                if lst[i] is o:
                    del lst[i]
                    break
            _ACTIVE = tuple(lst)


# ------------------------------------------------------------ cost analysis

_COST_MEMO: Dict[Tuple[int, str], Dict] = {}


def cost_of(fn: Any, *args: Any, **kwargs: Any) -> Dict[str, float]:
    """XLA's own accounting for ONE dispatch of ``fn`` at the given
    (aval or concrete) arguments: ``flops`` and ``bytes_accessed``
    from ``cost_analysis()``, argument/output/temp HBM from
    ``memory_analysis()`` (``peak_bytes`` = their sum — the resident
    footprint of one dispatch). Memoized per (callable, geometry);
    lowering + compiling happens off the jit fast path, so the first
    call per geometry pays a compile (cheap on CPU, persistent-cached
    where enabled)."""
    avals = tuple(_aval(a) for a in args)
    kwavals = {k: _aval(v) for k, v in kwargs.items()}
    key = (id(fn), _sig(avals, kwavals))
    hit = _COST_MEMO.get(key)
    if hit is not None:
        return dict(hit)
    compiled = fn.lower(*avals, **kwavals).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    out: Dict[str, float] = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }
    if ca.get("transcendentals"):
        out["transcendentals"] = float(ca["transcendentals"])
    try:
        ma = compiled.memory_analysis()
    except Exception:                    # pragma: no cover - plugin gap
        ma = None
    if ma is not None:
        arg_b = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
        out_b = float(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp_b = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
        out["argument_bytes"] = arg_b
        out["output_bytes"] = out_b
        out["temp_bytes"] = tmp_b
        out["peak_bytes"] = arg_b + out_b + tmp_b
    _COST_MEMO[key] = dict(out)
    return out


def hlo_text(fn: Any, *args: Any, **kwargs: Any) -> str:
    """The program's post-optimization HLO text (falls back to the
    pre-optimization lowering where the backend withholds it)."""
    avals = tuple(_aval(a) for a in args)
    kwavals = {k: _aval(v) for k, v in kwargs.items()}
    lowered = fn.lower(*avals, **kwavals)
    try:
        return lowered.compile().as_text()
    except Exception:                    # pragma: no cover - plugin gap
        return lowered.as_text()


# ------------------------------------------------------ factory discovery


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _module_name(path: str, root: str) -> str:
    """Dotted module name of a source file under the package root
    (``.../ziria_tpu/phy/wifi/rx.py`` -> ``ziria_tpu.phy.wifi.rx``)."""
    rel = os.path.relpath(path, os.path.dirname(root))
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _jit_target_names(fac: ast.FunctionDef) -> List[str]:
    """Identifier names appearing inside the arguments of the
    factory's ``*jit(...)`` calls — for a factory that jits a named
    module-level function (``jax.jit(sync_frame)``,
    ``jax.jit(jax.vmap(acquire_frame_graph))``), the traced
    function's name survives into the jitted callable's
    ``__wrapped__.__qualname__``, which is how :func:`coverage` links
    a note back here."""
    names: List[str] = []
    for node in ast.walk(fac):
        if isinstance(node, ast.Call) and isinstance(
                node.func, (ast.Name, ast.Attribute)):
            fname = (node.func.id if isinstance(node.func, ast.Name)
                     else node.func.attr)
            if fname.endswith("jit"):
                for a in node.args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name):
                            names.append(sub.id)
    return names


def discovered_factories(root: Optional[str] = None) -> List[Dict]:
    """Every ``@lru_cache`` jit factory under ``root`` (default: the
    ziria_tpu package), discovered with jaxlint R1's convention
    (`analysis.rules._jit_factories`) — never a hardcoded list, so
    factories future PRs add are covered (or reported uncovered)
    automatically."""
    from ziria_tpu.analysis.engine import iter_py_files
    from ziria_tpu.analysis.rules import _jit_factories

    root = root or _package_root()
    out: List[Dict] = []
    for path in iter_py_files([root]):
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for fac in _jit_factories(tree):
            out.append({
                "module": _module_name(path, root),
                "name": fac.name,
                "line": fac.lineno,
                "jit_targets": _jit_target_names(fac),
            })
    return out


def coverage(records: List[Dict],
             factories: Optional[List[Dict]] = None) -> Dict[str, List]:
    """Map analyzed program records back to the discovered factories:
    a factory is *covered* when some record's traced function either
    is one of the factory's jit targets (``jax.jit(sync_frame)``
    style) or is defined inside the factory
    (``_jit_stream_chunk.<locals>.f`` style). Returns
    ``{"covered": [...], "uncovered": [...]}`` of
    ``module.name`` strings — an uncovered factory means the driver
    workloads never exercised it, i.e. a blind spot, not an error."""
    factories = discovered_factories() if factories is None else factories
    seen = [(r.get("module", ""), r.get("jit_qualname", ""))
            for r in records if not r.get("error")]
    covered, uncovered = [], []
    for fac in factories:
        fq = f"{fac['module']}.{fac['name']}"
        hit = False
        for mod, qual in seen:
            if mod != fac["module"] or not qual:
                continue
            top = qual.split(".", 1)[0]
            if qual.startswith(fac["name"] + ".<locals>") or \
                    top in fac["jit_targets"]:
                hit = True
                break
        (covered if hit else uncovered).append(fq)
    return {"covered": covered, "uncovered": uncovered}


# ------------------------------------------------------------ driver


def run_driver() -> None:
    """Exercise every instrumented dispatch surface once at a tiny
    geometry, so an active observatory sees the tree's live compiled
    programs. CPU-safe (the whole point: cost attribution must not
    need the TPU), and sized to ride the tier-1 suite's shared
    compiled geometries where possible."""
    import numpy as np

    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy import channel, link
    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(23)
    n_bytes = 12
    rates = [6, 54]
    psdus = [rng.integers(0, 256, n_bytes).astype(np.uint8)
             for _ in rates]

    # per-frame path: encode_frame + sync/signal/decode_bucketed
    from ziria_tpu.phy.wifi import rx
    cap = np.concatenate(
        [np.zeros((50, 2), np.float32),
         np.asarray(tx.encode_frame(psdus[0], rates[0]))], axis=0)
    rx.receive(cap)

    # batched path: acquire_many + gather + decode_mixed + crc_many
    caps = [np.concatenate(
        [np.zeros((50, 2), np.float32),
         np.asarray(tx.encode_frame(p, m, add_fcs=True))], axis=0)
        for p, m in zip(psdus, rates)]
    framebatch.receive_many(caps, check_fcs=True, batched_acquire=True)

    # loopback: staged (encode_many + impair_many) and fused
    kw = dict(snr_db=30.0, cfo=1e-4, delay=12, seed=5,
              add_fcs=True, check_fcs=True)
    link.loopback_many(psdus, rates, fused=False, batched_tx=True, **kw)
    link.loopback_many(psdus, rates, fused=True, **kw)

    # per-frame channel oracle
    channel.impair_one(cap, 30.0, 1e-4, 3, 7, 0, out_len=1024)

    # single-rate batch + sweeps: encode_batch / awgn / decode_batch /
    # the one-scan BER sweep
    pb = np.stack(psdus)
    link.loopback_ber_bits(pb, rates[0], 8.0, 7)
    link.sweep_ber(pb, (rates[0],), (8.0,), (7,))

    # streaming receiver: stream_chunk + stream_decode at the suite's
    # canonical (K=8, 4096-chunk, 1024-window, 8-symbol) geometry
    stream, _starts = link.stream_many(
        psdus, rates, snr_db=30.0, cfo=1e-4, delay=60, seed=8,
        add_fcs=True, tail=1024)
    framebatch.receive_stream(stream, chunk_len=4096, frame_len=1024,
                              max_frames_per_chunk=8, check_fcs=True,
                              streaming=True)

    # multi-stream fleet: the stream-axis twins (stream_chunk_multi +
    # stream_decode_multi) over a 2-stream load at the same geometry
    streams, _st = link.stream_many_multi(
        [psdus[:1], psdus[1:]], [rates[:1], rates[1:]],
        snr_db=30.0, cfo=1e-4, delay=60, seed=9, add_fcs=True,
        tail=1024)
    framebatch.receive_streams(streams, chunk_len=4096, frame_len=1024,
                               max_frames_per_chunk=8, check_fcs=True,
                               multi=True)


def collect_programs(hlo_dump: Optional[str] = None,
                     driver=run_driver) -> Dict[str, Any]:
    """The one-call observatory sweep: run ``driver`` under a fresh
    observatory, analyze every noted program, and cross-check coverage
    against the AST-discovered factories. Returns the JSON-ready
    report the CLI and bench.py's ``programs`` stage share."""
    with observing() as obs:
        driver()
    records = obs.analyze(hlo_dump=hlo_dump)
    facs = discovered_factories()
    cov = coverage(records, facs)
    ok = [r for r in records if not r.get("error")]
    return {
        "programs": records,
        "programs_analyzed": len(ok),
        "factories_discovered": len(facs),
        "factories_covered": len(cov["covered"]),
        "uncovered": cov["uncovered"],
        "total_flops": round(sum(r.get("flops", 0.0) for r in ok), 1),
        "total_bytes_accessed": round(
            sum(r.get("bytes_accessed", 0.0) for r in ok), 1),
        "device_peaks": DEVICE_PEAKS,
    }


# ------------------------------------------------------------ CLI


def _format_table(report: Dict[str, Any]) -> str:
    rows = []
    for r in report["programs"]:
        if r.get("error"):
            rows.append((r["label"], r.get("in_avals", "")[:34],
                         "ERROR", r["error"][:40], "", ""))
            continue
        rows.append((
            r["label"], r.get("in_avals", "")[:34],
            f"{r.get('flops', 0):.3e}",
            f"{r.get('bytes_accessed', 0):.3e}",
            f"{r.get('peak_bytes', 0):.3e}",
            str(r.get("calls", 0)),
        ))
    w0 = max([len("label")] + [len(r[0]) for r in rows])
    w1 = max([len("in_avals")] + [len(r[1]) for r in rows])
    lines = [f"{'label':<{w0}} {'in_avals':<{w1}} {'flops':>11} "
             f"{'bytes_acc':>11} {'peak_bytes':>11} {'calls':>5}"]
    for r in rows:
        lines.append(f"{r[0]:<{w0}} {r[1]:<{w1}} {r[2]:>11} "
                     f"{r[3]:>11} {r[4]:>11} {r[5]:>5}")
    lines.append(
        f"{report['programs_analyzed']} program(s) analyzed; "
        f"{report['factories_covered']}/"
        f"{report['factories_discovered']} jit factories covered"
        + (f"; uncovered: {', '.join(report['uncovered'])}"
           if report["uncovered"] else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m ziria_tpu programs`` — the no-TPU-needed compiled
    program listing. Pins the CPU backend before first device contact
    (the axon plugin's probe hang must never gate cost attribution)
    and enables the persistent compile cache so repeat runs are
    cheap."""
    import argparse

    p = argparse.ArgumentParser(
        prog="ziria_tpu programs",
        description="compiled-program observatory: XLA cost/memory "
                    "attribution per jit factory, CPU-only "
                    "(docs/observability.md)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--hlo-dump", metavar="DIR", default=None,
                   help="write each program's optimized HLO text "
                        "under DIR")
    args = p.parse_args(argv)

    import jax
    try:
        # same mechanism as bench.py's parent / tests/conftest.py: the
        # config update wins over the plugin; a no-op (raise) when a
        # backend is already initialized in-process
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(_package_root()), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:
        pass

    report = collect_programs(hlo_dump=args.hlo_dump)
    dev = jax.devices()[0]
    report["platform"] = dev.platform
    report["device_kind"] = getattr(dev, "device_kind", "?")
    # the RESOLVED single peaks entry (or null for unknown kinds), in
    # the same key tools/trace_report.py reads off exported traces —
    # so `trace_report --costs <this report>` renders %-of-peak too
    report["devicePeaks"] = peaks_for(report["device_kind"])
    if args.json:
        print(json.dumps(report))
    else:
        print(_format_table(report))
    return 0
