"""Geometry autotuner: cost-pruned measured search over the tunables.

The search loop the ISSUE 16 tentpole adds on top of
:mod:`ziria_tpu.utils.geometry` — three stages, each riding machinery
an earlier PR already shipped:

1. **Enumerate** candidate geometries around the default
   (:func:`default_candidates`): the chunk-length ladder (halving
   raises the overlap fraction, doubling amortizes it) and the
   radix-4 Viterbi ACS (bit-identical to radix-2 at float32 by
   construction — ops/viterbi's pinned contract — so it is a legal
   identity-preserving candidate).
2. **Prune analytically** (:func:`stream_chunk_cost`): XLA's own
   ``cost_analysis`` for the candidate's chunk-scan program (the PR 9
   observatory's `programs.cost_of` — aval-lowered, no hardware, no
   data) normalized per OWNED stream sample. A candidate whose
   analytical bytes/flops per sample regress past the default never
   reaches a device: the halved chunk pays double the overlap
   fraction and dies here, by arithmetic instead of by stopwatch.
3. **Measure survivors** (:class:`Measurer`): the PR 7 telemetry
   harness on the two hot surfaces — the streaming receiver over a
   synthesized multi-frame stream (aggregate samples/s + per-chunk
   p50/p99 off the dispatch histograms) and the fused link (frames/s)
   — under the existing identity gates: a candidate's emissions must
   be bit-identical to the default's, field for field, or it is
   rejected no matter how fast it ran.

The winner (best streaming samples/s among identity-clean survivors;
the default itself competes) lands in the bench trajectory ledger
(``BENCH_TRAJECTORY.jsonl``, the ``BENCH_TRAJECTORY`` env override
honored via geometry's designated reader) as a ``stage="autotune"``
record keyed by ``device_kind`` — the record
:meth:`ziria_tpu.utils.geometry.Geometry.tuned` reconstructs, and
``tools/perf_report.py --check`` gates (device_kind-matched, so a v5e
winner never gates a CPU smoke). ``cost_fn`` / ``measure_fn`` are
injectable, so tests drive the whole pipeline deterministically with
fakes (tests/test_geometry.py).

Run it as ``python -m ziria_tpu autotune`` (pre-argparse dispatch,
like ``lint`` and ``programs``) or through bench.py's never-fatal
``autotune`` stage. docs/autotune.md walks the record format.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ziria_tpu.utils.geometry import (Geometry, detect_device_kind,
                                      env_trajectory_path)

#: analytical slack: a candidate may cost up to this fraction MORE
#: bytes/flops per owned sample than the default before the prune
#: rejects it (keeps exact-cost rewrites like radix-4 alive through
#: cost-model noise)
PRUNE_SLACK = 0.02

Candidate = Tuple[str, Geometry]


# ------------------------------------------------------------ enumeration


def default_candidates(base: Geometry) -> List[Candidate]:
    """The search neighborhood around ``base`` (which must be
    resolved): the chunk-length ladder, the radix-4 ACS, the fused
    demap front end (now a MEASURED axis — the rate-switched fused
    mixed decode covers the streaming surfaces this harness times),
    and the joint ``chunk_len x fused_demap`` move (the fused
    decode's VMEM residency shifts the scan/decode balance, so the
    chunk length that wins unfused need not win fused). Every
    candidate keeps ``frame_len``/detector params fixed — those are
    part of the identity contract's geometry, not throughput
    tunables."""
    out: List[Candidate] = []
    for cl in (base.chunk_len // 2, base.chunk_len * 2,
               base.chunk_len * 4):
        if cl > base.frame_len:
            out.append((f"chunk{cl}", base.replace(chunk_len=cl)))
    if base.viterbi_radix != 4:
        out.append(("radix4", base.replace(viterbi_radix=4)))
    if not base.fused_demap:
        out.append(("fused_demap", base.replace(fused_demap=True)))
        cl2 = base.chunk_len * 2
        if cl2 > base.frame_len:
            out.append((f"chunk{cl2}_fused",
                        base.replace(chunk_len=cl2, fused_demap=True)))
    return out


# ---------------------------------------------------------- analytical cost


def stream_chunk_cost(geo: Geometry) -> Dict[str, float]:
    """Analytical cost of the candidate's chunk-scan program per OWNED
    stream sample (a chunk re-reads ``frame_len`` overlap samples, so
    the honest denominator is ``chunk_len - frame_len``). Pure
    lowering through the PR 9 observatory — no dispatch, no data, no
    accelerator required."""
    import jax

    from ziria_tpu.phy.wifi import rx as _rx
    from ziria_tpu.utils import programs

    n_sym_bucket = geo.sym_bucket(
        max(1, (geo.frame_len - _rx.FRAME_DATA_START) // 80))
    fn = _rx._jit_stream_chunk(
        geo.max_frames_per_chunk, geo.frame_len, n_sym_bucket,
        float(geo.threshold), int(geo.min_run), int(geo.dead_zone))
    chunk = jax.ShapeDtypeStruct((geo.chunk_len, 2), np.float32)
    scalar = jax.ShapeDtypeStruct((), np.int32)
    c = programs.cost_of(fn, chunk, scalar, scalar, scalar)
    owned = geo.chunk_len - geo.frame_len
    return {
        "bytes_per_sample": c.get("bytes_accessed", 0.0) / owned,
        "flops_per_sample": c.get("flops", 0.0) / owned,
    }


def prune(candidates: Sequence[Candidate], base_cost: Dict[str, float],
          cost_fn: Callable[[Geometry], Dict[str, float]],
          slack: float = PRUNE_SLACK):
    """Split ``candidates`` into (survivors, rejected) on the
    analytical cost model: a candidate whose bytes/sample OR
    flops/sample regress past ``slack`` over the default is rejected
    before any hardware time is spent on it."""
    survivors: List[Tuple[str, Geometry, Dict[str, float]]] = []
    rejected: List[Dict[str, Any]] = []
    for label, geo in candidates:
        c = cost_fn(geo)
        worse_bytes = c["bytes_per_sample"] > \
            base_cost["bytes_per_sample"] * (1.0 + slack)
        worse_flops = c["flops_per_sample"] > \
            base_cost["flops_per_sample"] * (1.0 + slack)
        if worse_bytes or worse_flops:
            rejected.append({
                "label": label, "reason": "cost",
                "bytes_per_sample": round(c["bytes_per_sample"], 3),
                "flops_per_sample": round(c["flops_per_sample"], 3),
            })
        else:
            survivors.append((label, geo, c))
    return survivors, rejected


# ------------------------------------------------------------- measurement


def _stream_fingerprint(frames) -> Tuple:
    """Field-for-field emission fingerprint of a streaming run — the
    identity gate's comparand (failures included: a lane failing
    identically in both geometries is identity, not divergence)."""
    return tuple(
        (int(f.start), bool(f.result.ok), bool(f.result.crc_ok),
         int(f.result.rate_mbps), int(f.result.length_bytes),
         np.asarray(f.result.psdu_bits).tobytes())
        for f in frames)


def _link_fingerprint(results) -> Tuple:
    return tuple(
        (bool(r.ok), bool(r.crc_ok), int(r.rate_mbps),
         int(r.length_bytes), np.asarray(r.psdu_bits).tobytes())
        for r in results)


def _chunk_latency_ms(reg) -> Dict[str, float]:
    """p50/p99 of the streaming chunk-scan dispatch site off the
    telemetry registry's histogram layer (upper-bound bucket
    quantiles — the PR 7 numbers, not summed means)."""
    from ziria_tpu.utils import telemetry

    for (name, labels), m in reg.metrics():
        if name == telemetry.DISPATCH_HISTOGRAM and \
                dict(labels).get("site") == "rx.stream_chunk":
            s = m.summary(scale=1e3, ndigits=4)
            return {"p50_ms": s.get("p50"), "p99_ms": s.get("p99")}
    return {}


class Measurer:
    """The default (hardware) measurer: one shared stimulus, then per
    candidate a warmed+timed streaming pass and fused-link pass with
    telemetry latency capture and emission fingerprints. Callable so
    tests can swap in a deterministic fake with the same signature."""

    def __init__(self, n_frames: int = 8, n_bytes: int = 24,
                 seed: int = 8, reps: int = 2):
        self.n_frames = int(n_frames)
        self.n_bytes = int(n_bytes)
        self.seed = int(seed)
        self.reps = max(1, int(reps))
        self._stim = None

    def _stimulus(self):
        if self._stim is None:
            from ziria_tpu.phy import link
            from ziria_tpu.phy.wifi.params import RATES

            rng = np.random.default_rng(self.seed)
            rates = (sorted(RATES)
                     * (-(-self.n_frames // len(RATES))))[:self.n_frames]
            psdus = [rng.integers(0, 256, self.n_bytes).astype(np.uint8)
                     for _ in range(self.n_frames)]
            stream, starts = link.stream_many(
                psdus, rates, snr_db=30.0, cfo=1e-4, delay=60,
                seed=self.seed, add_fcs=True, tail=2048)
            self._stim = (stream, starts, psdus, rates)
        return self._stim

    def __call__(self, geo: Geometry) -> Dict[str, Any]:
        from ziria_tpu.backend import framebatch
        from ziria_tpu.phy import link
        from ziria_tpu.utils import telemetry

        stream, _starts, psdus, rates = self._stimulus()
        kw = dict(geometry=geo, check_fcs=True, streaming=True)
        frames, _ = framebatch.receive_stream(stream, **kw)  # warm
        with telemetry.collect() as reg:
            t0 = time.perf_counter()
            for _ in range(self.reps):
                frames, _ = framebatch.receive_stream(stream, **kw)
            dt = time.perf_counter() - t0
        sps = stream.shape[0] * self.reps / dt if dt > 0 else 0.0

        res = link.loopback_many(psdus, rates, add_fcs=True,
                                 check_fcs=True, geometry=geo)  # warm
        t0 = time.perf_counter()
        for _ in range(self.reps):
            res = link.loopback_many(psdus, rates, add_fcs=True,
                                     check_fcs=True, geometry=geo)
        dt = time.perf_counter() - t0
        fps = len(psdus) * self.reps / dt if dt > 0 else 0.0

        out: Dict[str, Any] = {
            "sps": sps, "fps": fps,
            "fingerprint": (_stream_fingerprint(frames),
                            _link_fingerprint(res)),
        }
        out.update(_chunk_latency_ms(reg))
        return out


# -------------------------------------------------------------- the search


def run(base: Optional[Geometry] = None,
        candidates: Optional[Sequence[Candidate]] = None,
        cost_fn: Optional[Callable] = None,
        measure_fn: Optional[Callable] = None,
        n_frames: int = 8, n_bytes: int = 24, seed: int = 8,
        reps: int = 2, slack: float = PRUNE_SLACK,
        record: bool = True, path: Optional[str] = None,
        device_kind: Optional[str] = None,
        platform: Optional[str] = None,
        log: Callable[[str], None] = print) -> Dict[str, Any]:
    """The whole pipeline: enumerate -> cost-prune -> measure ->
    identity-gate -> pick winner -> (optionally) record. Deterministic
    given injected ``cost_fn``/``measure_fn``; the returned dict is
    the bench stage's evidence record."""
    base = (base if base is not None else Geometry()).resolve()
    cands = list(candidates if candidates is not None
                 else default_candidates(base))
    cost_fn = cost_fn or stream_chunk_cost
    measure_fn = measure_fn or Measurer(n_frames=n_frames,
                                        n_bytes=n_bytes, seed=seed,
                                        reps=reps)

    base_cost = cost_fn(base)
    survivors, pruned = prune(cands, base_cost, cost_fn, slack)
    log(f"autotune: {len(cands)} candidate(s), cost-pruned "
        f"{len(pruned)} ({', '.join(r['label'] for r in pruned) or '-'})"
        f", measuring {len(survivors)} + default")

    base_m = measure_fn(base)
    base_fp = base_m.get("fingerprint")
    measured = [{"label": "default", "sps": base_m["sps"],
                 "fps": base_m.get("fps"),
                 "p50_ms": base_m.get("p50_ms"),
                 "p99_ms": base_m.get("p99_ms")}]
    best_label, best_geo, best_sps = "default", base, base_m["sps"]
    identity_rejected: List[str] = []
    for label, geo, _cost in survivors:
        m = measure_fn(geo)
        if base_fp is not None and m.get("fingerprint") != base_fp:
            identity_rejected.append(label)
            log(f"autotune: {label} REJECTED — emissions diverge from "
                f"the default geometry (identity gate)")
            continue
        measured.append({"label": label, "sps": m["sps"],
                         "fps": m.get("fps"), "p50_ms": m.get("p50_ms"),
                         "p99_ms": m.get("p99_ms")})
        log(f"autotune: {label}: {m['sps']:.0f} sps "
            f"({m['sps'] / base_m['sps']:.2f}x default)")
        if m["sps"] > best_sps:
            best_label, best_geo, best_sps = label, geo, m["sps"]

    speedup = best_sps / base_m["sps"] if base_m["sps"] else 1.0
    if device_kind is None:
        device_kind = detect_device_kind()
    if platform is None:
        platform = _platform()
    rec = {
        "run_id": f"autotune-{int(time.time())}",
        "unix": round(time.time(), 1),
        "stage": "autotune", "metric": "sps_tuned",
        "value": best_sps, "platform": platform, "partial": False,
        "direction": "higher", "source": "autotune",
        "device_kind": device_kind,
        "geometry": best_geo.as_dict(),
        "winner": best_label,
        "baseline_sps": base_m["sps"],
        "speedup": round(speedup, 4),
    }
    out = {
        "winner": best_label, "geometry": best_geo.as_dict(),
        "sps_tuned": best_sps, "baseline_sps": base_m["sps"],
        "speedup": round(speedup, 4), "device_kind": device_kind,
        "platform": platform, "candidates": len(cands),
        "pruned": pruned, "identity_rejected": identity_rejected,
        "measured": measured, "record": rec,
    }
    if record:
        p = path or env_trajectory_path()
        try:
            with open(p, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(rec) + "\n")
            out["recorded_to"] = p
            log(f"autotune: winner '{best_label}' "
                f"({speedup:.2f}x default) recorded for "
                f"device_kind={device_kind!r} -> {p}")
        except OSError as e:   # an unwritable ledger never fails a run
            out["record_error"] = repr(e)
            log(f"autotune: ledger unwritable ({e!r}); winner not "
                f"recorded")
    return out


def _platform() -> Optional[str]:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return None


# -------------------------------------------------------------------- cli


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m ziria_tpu autotune``: the measured search, sized
    for a smoke by default (a handful of frames; pass --frames/--reps
    up for a real tuning run on hardware)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ziria_tpu autotune",
        description="cost-pruned measured geometry search; winners "
                    "land per-device in the bench trajectory ledger "
                    "(BENCH_TRAJECTORY.jsonl) for Geometry.tuned()")
    ap.add_argument("--frames", type=int, default=8,
                    help="stimulus frames per measurement (default 8)")
    ap.add_argument("--bytes", type=int, default=24, dest="n_bytes",
                    help="PSDU bytes per stimulus frame (default 24)")
    ap.add_argument("--reps", type=int, default=2,
                    help="timed repetitions per candidate (default 2)")
    ap.add_argument("--seed", type=int, default=8)
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default: BENCH_TRAJECTORY env "
                         "or the repo-root BENCH_TRAJECTORY.jsonl)")
    ap.add_argument("--dry-run", action="store_true",
                    help="search and report but do not record")
    args = ap.parse_args(argv)

    out = run(n_frames=args.frames, n_bytes=args.n_bytes,
              reps=args.reps, seed=args.seed,
              record=not args.dry_run, path=args.ledger)
    tuned = Geometry.tuned(out["device_kind"],
                           path=None if args.dry_run else args.ledger)
    print(json.dumps({k: out[k] for k in
                      ("winner", "sps_tuned", "baseline_sps",
                       "speedup", "device_kind", "platform")},
                     default=str))
    if not args.dry_run and out.get("recorded_to"):
        ok = tuned.as_dict() == out["geometry"]
        print(f"Geometry.tuned({out['device_kind']!r}) "
              f"{'reproduces the winner' if ok else 'MISMATCH'}")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":   # pragma: no cover - python -m entry
    raise SystemExit(main())
