"""jaxlint rules: the six JAX-discipline checks tuned to this tree.

Each rule encodes one recurring bug class of the repo's own history
(docs/static_analysis.md carries the motivating incident per rule):

  R1  cache-key completeness  — a knob read inside an ``@lru_cache``
      jit factory that is not one of the factory's parameters cannot
      be part of the compile-cache key (ADVICE r5 #1, PR 6's hand
      re-threading).
  R2  host-sync in the hot path — ``float()/int()/bool()/.item()/
      np.asarray()/.block_until_ready()`` on a jit result inside a
      ``dispatch.timed()`` region makes a device wait masquerade as
      dispatch time (Sora's nothing-synchronizes discipline).
  R3  untimed dispatch — a cached ``_jit_*`` callable fired outside
      ``dispatch.timed()`` is invisible to the telemetry layer's
      per-site latency histograms (PR 7).
  R4  env-read hygiene — ``os.environ`` read at import time, or
      outside a designated single-reader function / the cli's
      scoped-env pattern; plus any environment WRITE outside it.
  R5  cache hygiene — ``lru_cache`` keyed on (or closing over) array
      arguments: unhashable keys at best, an unbounded per-array
      cache at worst.
  R6  geometry hygiene — a numeric literal for a known tunable
      (chunk_len / K / S / viterbi window / radix / bucket floors) at
      a jit-factory call site, or a literal ``pow2_bucket`` floor,
      bypasses `utils/geometry.Geometry` and forks the compiled
      geometry from the autotuner's tuned winner (ISSUE 16).

Jit factories are DISCOVERED (an ``@lru_cache`` def whose body calls
``jax.jit``), never hardcoded, so the rules keep covering factories
future PRs add. The designated env readers are a NAMING convention —
``*_enabled`` / ``*_mode`` / ``env_*`` / ``_check_*`` — the one-reader
discipline every knob in the tree already follows; R4 enforces that
new knobs follow it too.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ziria_tpu.analysis.engine import (ENV_WRITE_METHODS, Context, Rule,
                                       env_write_target, in_timed_block,
                                       is_env_read, is_lru_cached,
                                       last_component, qual_name,
                                       subtree_contains_jit)

#: designated env single-readers (matched on the last dotted
#: component, leading underscores stripped): the ONE place a knob's
#: env default may be read, by naming convention
DESIGNATED_READER = re.compile(
    r"(_enabled$|_mode$|^env_|^check_)")

#: mode-resolver call patterns R1 refuses inside a jit factory: these
#: read process state (env / module knobs) when passed None, so a
#: factory calling one bakes an un-keyed mode into its cached program
MODE_RESOLVER = re.compile(
    r"(_enabled$|_mode$|^env_|^resolve_|^check_)")

SYNC_BUILTINS = ("float", "int", "bool")
SYNC_METHODS = ("item", "block_until_ready")
ARRAY_PULLS = ("asarray", "array")          # np.asarray(jit_result)
ARRAY_ANNOTATIONS = re.compile(
    r"(ndarray|\bArray\b|jnp\.|jax\.Array|DeviceArray)")

JIT_CALLABLE = re.compile(r"^_jit_")        # the repo's factory naming


def _jit_factories(tree: ast.Module) -> List[ast.FunctionDef]:
    """Module-level (or nested) ``@lru_cache`` defs that build jitted
    callables — the compile-cache keyed factories R1/R5 police."""
    return [n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)
            and is_lru_cached(n) and subtree_contains_jit(n)]


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class CacheKeyCompleteness(Rule):
    id = "R1"
    name = "cache-key-completeness"
    why = ("a knob read inside a jit factory body is not part of its "
           "lru_cache key: an in-process change silently reuses the "
           "stale compiled program (ADVICE r5 #1)")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        knobs = self._module_knobs(mod.tree)
        for fac in _jit_factories(mod.tree):
            params = _param_names(fac)
            for node in ast.walk(fac):
                if node is fac:
                    continue
                if is_env_read(node):
                    ctx.report(node, (
                        f"env read inside jit factory '{fac.name}' is "
                        f"not part of its compile-cache key; resolve "
                        f"in the caller and pass the value as a "
                        f"factory parameter"))
                elif isinstance(node, ast.Call):
                    name = last_component(qual_name(node.func))
                    if MODE_RESOLVER.search(name):
                        ctx.report(node, (
                            f"mode resolver '{qual_name(node.func)}' "
                            f"called inside jit factory '{fac.name}': "
                            f"the resolved mode never reaches the "
                            f"lru_cache key; resolve before keying"))
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in knobs and node.id not in params:
                    ctx.report(node, (
                        f"module-level knob '{node.id}' read inside "
                        f"jit factory '{fac.name}' without being a "
                        f"factory parameter (it is mutable process "
                        f"state, not a compile-time constant)"))

    @staticmethod
    def _module_knobs(tree: ast.Module) -> Set[str]:
        """Names that behave like process-wide knobs: module-level
        assignments whose value reads the environment, plus any name
        rebound via a ``global`` statement somewhere in the module."""
        knobs: Set[str] = set()
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                if value is not None and any(
                        is_env_read(n) for n in ast.walk(value)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            knobs.add(t.id)
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                knobs.update(node.names)
        return knobs


def _device_bound_names(fn: ast.FunctionDef,
                        jit_locals: Set[str]) -> Set[str]:
    """Names in ``fn`` assigned from firing a cached jit callable —
    the values R2 treats as device-resident."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if not _is_jit_dispatch(node.value, jit_locals):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                out.update(e.id for e in t.elts
                           if isinstance(e, ast.Name))
    return out


def _jit_factory_locals(fn: ast.FunctionDef) -> Set[str]:
    """Local names bound to a ``_jit_*(...)`` factory result inside
    ``fn`` (``dec = _jit_decode(...)``) — calling them is a device
    dispatch."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                JIT_CALLABLE.match(
                    qual_name(node.value.func).rsplit(".", 1)[-1]):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _class_jit_attrs(tree: ast.Module) -> Set[str]:
    """Attributes assigned ``self.X = [mod.]_jit_*(...)`` anywhere —
    ``self.X(...)`` is then a cached-jit dispatch (the StreamReceiver
    pattern)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                JIT_CALLABLE.match(
                    qual_name(node.value.func).rsplit(".", 1)[-1]):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.add(t.attr)
    return out


def _is_jit_dispatch(call: ast.Call, jit_locals: Set[str],
                     jit_attrs: Optional[Set[str]] = None) -> bool:
    """True when ``call`` fires a cached jit callable: a direct
    ``_jit_foo(...)(args)`` double call, a local bound from a
    ``_jit_*`` factory, or a ``self.attr`` bound from one."""
    f = call.func
    if isinstance(f, ast.Call):           # _jit_foo(key...)(operands)
        return bool(JIT_CALLABLE.match(
            qual_name(f.func).rsplit(".", 1)[-1]))
    if isinstance(f, ast.Name) and f.id in jit_locals:
        return True
    if jit_attrs is not None and isinstance(f, ast.Attribute) and \
            isinstance(f.value, ast.Name) and f.value.id == "self" \
            and f.attr in jit_attrs:
        return True
    return False


class HostSyncInHotPath(Rule):
    id = "R2"
    name = "host-sync-in-hot-path"
    why = ("a host sync inside a dispatch.timed() region blocks on "
           "the device there, so the per-site latency histogram "
           "reports device wait as dispatch time — and on the "
           "streaming hot loop it serializes the double buffer")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.FunctionDef)]:
            jit_locals = _jit_factory_locals(fn)
            device = _device_bound_names(fn, jit_locals)
            if not (jit_locals or device):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                sync = self._sync_target(node, device, jit_locals)
                if sync is None:
                    continue
                if in_timed_block(mod, node):
                    ctx.report(node, (
                        f"host sync '{sync}' on a jit result inside a "
                        f"dispatch.timed() region: move the "
                        f"conversion out of the timed block so the "
                        f"site times the dispatch, not the device "
                        f"wait"))

    @staticmethod
    def _sync_target(call: ast.Call, device: Set[str],
                     jit_locals: Set[str]) -> Optional[str]:
        def is_device_expr(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in device
            if isinstance(e, (ast.Subscript, ast.Attribute)):
                return is_device_expr(e.value)
            if isinstance(e, ast.Call):
                return _is_jit_dispatch(e, jit_locals)
            return False

        f = call.func
        if isinstance(f, ast.Name) and f.id in SYNC_BUILTINS and \
                call.args and is_device_expr(call.args[0]):
            return f.id
        if isinstance(f, ast.Attribute):
            if f.attr in SYNC_METHODS and is_device_expr(f.value):
                return f".{f.attr}()"
            if f.attr in ARRAY_PULLS and call.args and \
                    is_device_expr(call.args[0]):
                q = qual_name(f)
                if q.split(".", 1)[0] in ("np", "numpy", "onp"):
                    return q
        return None


class UntimedDispatch(Rule):
    id = "R3"
    name = "untimed-dispatch"
    why = ("a cached _jit_* callable fired outside dispatch.timed() "
           "is invisible to the telemetry layer: no per-site latency "
           "histogram, no dispatch counter, no trace span")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        jit_attrs = _class_jit_attrs(mod.tree)
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if is_lru_cached(fn) or any(
                    isinstance(a, ast.FunctionDef) and is_lru_cached(a)
                    for a in mod.ancestors(fn)):
                continue   # a factory's inner graph fn is traced code,
                #            not a host dispatch site
            jit_locals = _jit_factory_locals(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not _is_jit_dispatch(node, jit_locals, jit_attrs):
                    continue
                if not in_timed_block(mod, node):
                    name = qual_name(node.func)
                    if not name and isinstance(node.func, ast.Call):
                        name = qual_name(node.func.func) + "(...)"
                    ctx.report(node, (
                        f"cached jit callable '{name or '<call>'}' "
                        f"dispatched outside dispatch.timed(): wrap "
                        f"the call site so its latency and count are "
                        f"observable"))


class EnvReadHygiene(Rule):
    id = "R4"
    name = "env-read-hygiene"
    why = ("an env read at import time (or scattered outside a "
           "designated *_enabled/*_mode/env_* single reader) escapes "
           "the cli scoped-env pattern: the flag stops being "
           "overridable per invocation, and two readers can disagree")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        for node in ast.walk(mod.tree):
            w = env_write_target(node)
            if w is not None:
                ctx.report(w, (
                    "environment write outside the cli scoped-env "
                    "pattern: mutate os.environ only through a "
                    "scoped write+restore (runtime/cli.main)"))
                continue
            if not is_env_read(node):
                continue
            # a write's environ mention is reported above, once
            par = mod.parent_of(node)
            if isinstance(par, ast.Attribute) and \
                    par.attr in ENV_WRITE_SKIP:
                continue
            if isinstance(par, ast.Subscript) and \
                    not isinstance(par.ctx, ast.Load):
                continue       # os.environ[k] = / del: the write rule
            chain = mod.enclosing_functions(node)
            if not chain:
                ctx.report(node, (
                    "env read at import time: module import order "
                    "decides the value and the cli scoped-env "
                    "pattern cannot override it; read at call time "
                    "inside a designated single-reader function"))
            elif not any(DESIGNATED_READER.search(
                    f.name.lstrip("_")) for f in chain):
                ctx.report(node, (
                    f"env read inside "
                    f"'{chain[0].name}', which is not a designated "
                    f"single-reader (*_enabled / *_mode / env_* / "
                    f"_check_*): hoist the read into ONE reader "
                    f"function so every surface agrees on the knob"))


#: attribute accesses on environ that the write check reports — the
#: read check must not double-report their `environ` mention
ENV_WRITE_SKIP = set(ENV_WRITE_METHODS)


class CacheHygiene(Rule):
    id = "R5"
    name = "cache-hygiene"
    why = ("lru_cache keyed on (or closing over) arrays is a leak: "
           "array keys are unhashable or compare by id, so the cache "
           "grows per call and pins device buffers forever")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        cached = [n for n in ast.walk(mod.tree)
                  if isinstance(n, ast.FunctionDef) and is_lru_cached(n)]
        cached_names = {n.name for n in cached}
        for fn in cached:
            for p in fn.args.posonlyargs + fn.args.args \
                    + fn.args.kwonlyargs:
                ann = p.annotation
                if ann is not None and ARRAY_ANNOTATIONS.search(
                        ast.unparse(ann)):
                    ctx.report(p, (
                        f"lru_cache'd '{fn.name}' takes array-typed "
                        f"parameter '{p.arg}': arrays are not hashable "
                        f"cache keys — key on shape/dtype/mode "
                        f"scalars and pass the array to the returned "
                        f"callable"))
            if any(isinstance(a, ast.FunctionDef)
                   for a in mod.ancestors(fn)):
                ctx.report(fn, (
                    f"lru_cache'd '{fn.name}' is defined inside "
                    f"another function: every outer call makes a NEW "
                    f"cache closing over that call's locals (arrays "
                    f"included) — hoist the cached def to module "
                    f"scope"))
        # call-site check: obviously-array arguments to a cached
        # factory defined in this module
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if qual_name(node.func).rsplit(".", 1)[-1] \
                    not in cached_names:
                continue
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Call):
                    q = qual_name(a.func)
                    if q.rsplit(".", 1)[-1] in ARRAY_PULLS and \
                            q.split(".", 1)[0] in ("np", "numpy",
                                                   "jnp", "jax"):
                        ctx.report(a, (
                            f"array argument "
                            f"'{ast.unparse(a)[:40]}' keys the "
                            f"lru_cache of "
                            f"'{qual_name(node.func)}': the cache "
                            f"grows one entry per array object"))


#: tunable names R6 refuses as literal keyword arguments at jit-factory
#: call sites — each has one home on the Geometry dataclass, and a
#: literal here silently forks the tree's compiled geometry
KNOWN_TUNABLES = frozenset({
    "chunk_len", "frame_len", "max_frames_per_chunk", "n_streams",
    "viterbi_window", "viterbi_radix", "min_bucket",
})


def _is_numeric_literal(node: ast.AST) -> bool:
    """A compile-time number: ``8192``, ``1 << 13``, ``-1``, or any
    BinOp/UnaryOp tree over such constants."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) and \
            not isinstance(node.value, bool)
    if isinstance(node, ast.BinOp):
        return _is_numeric_literal(node.left) and \
            _is_numeric_literal(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_numeric_literal(node.operand)
    return False


class GeometryHygiene(Rule):
    id = "R6"
    name = "geometry-hygiene"
    why = ("a numeric literal for a known tunable at a jit-factory "
           "call site (or a literal pow2_bucket floor) bypasses the "
           "Geometry object: the literal and Geometry's default can "
           "drift apart, and the autotuner's tuned() winner never "
           "reaches that surface")

    def check(self, ctx: Context) -> None:
        mod = ctx.module
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = qual_name(node.func).rsplit(".", 1)[-1]
            if name == "pow2_bucket":
                floor = None
                if len(node.args) >= 2:
                    floor = node.args[1]
                for k in node.keywords:
                    if k.arg == "min_bucket":
                        floor = k.value
                if floor is not None and _is_numeric_literal(floor):
                    ctx.report(floor, (
                        "literal pow2_bucket floor "
                        f"'{ast.unparse(floor)}': bucket minimums live "
                        "on the Geometry object (sym_bucket / "
                        "capture_bucket / bit_bucket) — a literal here "
                        "forks the bucketing rule from the tuned "
                        "geometry"))
            elif JIT_CALLABLE.match(name):
                for k in node.keywords:
                    if k.arg in KNOWN_TUNABLES and \
                            _is_numeric_literal(k.value):
                        ctx.report(k.value, (
                            f"literal '{k.arg}="
                            f"{ast.unparse(k.value)}' at jit-factory "
                            f"call site '{qual_name(node.func)}': "
                            f"thread the value from a Geometry "
                            f"(utils/geometry) so the compile key and "
                            f"the tuned geometry cannot disagree"))


ALL_RULES = (CacheKeyCompleteness(), HostSyncInHotPath(),
             UntimedDispatch(), EnvReadHygiene(), CacheHygiene(),
             GeometryHygiene())

RULES_BY_ID = {r.id: r for r in ALL_RULES}
