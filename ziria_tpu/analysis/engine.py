"""jaxlint engine: pure-``ast`` static analysis over the tree.

The machinery under ``python -m ziria_tpu.analysis`` (and the CLI's
``lint`` subcommand): walk Python sources, hand each registered rule a
parsed module with parent links, collect :class:`Finding`\\ s, apply
``# ziria: lint-ignore[<rule>] reason`` suppression pragmas, and render
text or JSON. Deliberately **jax-free**: the whole point of an
ahead-of-time analysis (Ziria's SDF cardinality check before codegen —
PAPERS.md) is that it runs before — and without — the runtime it
polices, so the lint gate works even when the TPU backend probe hangs.

Rules live in :mod:`ziria_tpu.analysis.rules`; adding one is: write a
``Rule`` subclass with a unique ``id`` and a ``check(ctx)`` that calls
``ctx.report(node, message)``, append it to ``rules.ALL_RULES``
(docs/static_analysis.md walks through it).

Pragma grammar (suppressions the gate treats as reviewed, so every
one must carry a justification — a bare pragma is itself a finding,
and so is a pragma that no longer suppresses anything; only real
COMMENT tokens register, so a docstring or string literal quoting the
syntax — like this one — can never suppress anything):

    # ziria: lint-ignore[R1] why this finding is safe      (this line
                                                            or the next)
    # ziria: lint-ignore-file[R4] why for the whole file
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(
    r"#\s*ziria:\s*lint-ignore(?P<file>-file)?"
    r"\[(?P<rules>[A-Za-z0-9_,\s]+)\]\s*(?P<reason>.*\S)?")

#: rule id reserved for engine-level findings (unparseable file,
#: reasonless pragma) — not suppressible by design
META_RULE = "lint"


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    file: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: " \
               f"{self.rule} {self.message}"


@dataclass
class Pragma:
    line: int
    rules: Tuple[str, ...]
    reason: str
    file_level: bool
    used: bool = False


class Module:
    """One parsed source file with the lookups rules need: parent
    links (``parent_of``), the raw lines, and the module-level
    assignment/`global` tables the cache-key rule reads."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parent: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parent[id(child)] = node

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent_of(node)
        while cur is not None:
            yield cur
            cur = self.parent_of(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing function definitions
        (empty == module level, i.e. import time)."""
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))]


class Context:
    """Per-file rule context: ``report`` accumulates findings for the
    rule currently running."""

    def __init__(self, module: Module):
        self.module = module
        self.findings: List[Finding] = []
        self._rule_id = META_RULE

    def report(self, node: ast.AST, message: str,
               rule_id: Optional[str] = None) -> None:
        self.findings.append(Finding(
            self.module.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0) + 1,
            rule_id or self._rule_id, message))


class Rule:
    """Base class: subclass with a class-level ``id``/``name``/``why``
    and implement :meth:`check`."""

    id = "R0"
    name = "unnamed"
    #: one-line motivation shown by --list-rules
    why = ""

    def check(self, ctx: Context) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


# ----------------------------------------------------------- AST helpers
#
# Shared by the rules; kept here so a new rule composes them instead of
# re-deriving dotted-name plumbing.


def qual_name(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain
    chain): ``jax.jit`` -> "jax.jit", ``self._jit1`` -> "self._jit1"."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_component(name: str) -> str:
    """Final dotted component, leading underscores stripped — the
    form the naming-convention patterns match against."""
    return name.rsplit(".", 1)[-1].lstrip("_")


def decorator_names(fn: ast.AST) -> List[str]:
    out = []
    for d in getattr(fn, "decorator_list", []):
        if isinstance(d, ast.Call):
            d = d.func
        q = qual_name(d)
        if q:
            out.append(q)
    return out


def is_lru_cached(fn: ast.AST) -> bool:
    return any(q.rsplit(".", 1)[-1] in ("lru_cache", "cache")
               for q in decorator_names(fn))


def in_timed_block(module: Module, node: ast.AST) -> bool:
    """True when ``node`` sits inside a ``with dispatch.timed(...)``
    (or bare ``timed(...)``) block body."""
    for anc in module.ancestors(node):
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call) and \
                    last_component(qual_name(expr.func)) == "timed":
                return True
    return False


def is_env_read(node: ast.AST) -> bool:
    """An ``os.environ`` access or ``os.getenv`` call (any alias whose
    chain ends in .environ / .getenv, or a bare imported ``environ`` /
    ``getenv``)."""
    if isinstance(node, ast.Call):
        return qual_name(node.func).rsplit(".", 1)[-1] == "getenv"
    q = qual_name(node)
    return bool(q) and q.rsplit(".", 1)[-1] == "environ"


ENV_WRITE_METHODS = ("update", "pop", "setdefault", "clear")


def env_write_target(node: ast.AST) -> Optional[ast.AST]:
    """The offending node when ``node`` mutates the process
    environment: ``os.environ[k] = v`` / ``del os.environ[k]`` (an
    Assign/Delete whose target subscripts environ), or a call to
    ``os.environ.update/pop/setdefault/clear`` / ``os.putenv``."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and is_env_read(t.value):
                return t
    if isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and is_env_read(t.value):
                return t
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in ENV_WRITE_METHODS and is_env_read(f.value):
                return node
            if f.attr == "putenv":
                return node
    return None


def subtree_contains_jit(fn: ast.AST) -> bool:
    """True when the function body builds a jitted callable — a call
    whose name ends in ``jit`` (``jax.jit(f)``, ``jit(f, ...)``).
    This is how jit factories are DISCOVERED (never hardcoded): an
    ``@lru_cache`` def containing one is a compile-cache keyed
    factory, and rules R1/R5 police its key."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                qual_name(node.func).rsplit(".", 1)[-1] == "jit":
            return True
    return False


# ------------------------------------------------------------ file driver


def collect_pragmas(source: str) -> List[Pragma]:
    """Pragmas from the file's real COMMENT tokens only — a docstring
    or string literal that merely *quotes* the pragma syntax must
    never register as a live suppression (engine.py's own module
    docstring is the proof case)."""
    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return []          # unparseable: lint_source reports it first
    for i, text in comments:
        m = PRAGMA_RE.search(text)
        if m:
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            out.append(Pragma(i, rules, (m.group("reason") or "").strip(),
                              bool(m.group("file"))))
    return out


@dataclass
class FileResult:
    path: str
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[Rule]] = None) -> FileResult:
    """Lint one source string. Parse failures are reported as a
    ``lint`` finding, never an exception — a broken file must fail
    the gate, not crash it."""
    from ziria_tpu.analysis.rules import ALL_RULES

    res = FileResult(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        res.findings.append(Finding(
            path, e.lineno or 0, (e.offset or 0), META_RULE,
            f"syntax error: {e.msg}"))
        return res
    module = Module(path, source, tree)
    ctx = Context(module)
    for rule in (rules if rules is not None else ALL_RULES):
        ctx._rule_id = rule.id
        rule.check(ctx)
    # rules that walk per-function see nested defs twice (once from
    # the outer walk): identical findings collapse to one
    ctx.findings = list(dict.fromkeys(ctx.findings))

    pragmas = collect_pragmas(source)
    file_pragmas: Dict[str, List[Pragma]] = {}
    line_rules: Dict[int, List[Pragma]] = {}
    for p in pragmas:
        if p.file_level:
            for r in p.rules:
                file_pragmas.setdefault(r, []).append(p)
        else:
            line_rules.setdefault(p.line, []).append(p)

    kept: List[Finding] = []
    for f in ctx.findings:
        if f.rule != META_RULE and f.rule in file_pragmas:
            for p in file_pragmas[f.rule]:
                p.used = True
            res.suppressed += 1
            continue
        hit = None
        for p in line_rules.get(f.line, []) + \
                line_rules.get(f.line - 1, []):
            if f.rule != META_RULE and f.rule in p.rules:
                hit = p
                break
        if hit is not None:
            hit.used = True
            res.suppressed += 1
            continue
        kept.append(f)
    # the gate's contract is that every pragma is a reviewed trade:
    # one without a justification is itself a finding, and so is one
    # that no longer suppresses anything (the fixed-finding creep a
    # stale pragma would otherwise silently mask forever). Unused is
    # only decidable for rules that actually RAN — under a --rules
    # subset, pragmas for unrun rules are left alone.
    ran = {r.id for r in (rules if rules is not None else ALL_RULES)}
    for p in pragmas:
        if not p.reason:
            kept.append(Finding(
                path, p.line, 1, META_RULE,
                "lint-ignore pragma without a justification "
                "(write WHY the finding is safe to suppress)"))
        elif not p.used and set(p.rules) <= ran:
            kept.append(Finding(
                path, p.line, 1, META_RULE,
                f"unused lint-ignore pragma "
                f"[{','.join(p.rules)}]: it suppresses no finding — "
                f"the issue was fixed, so remove the pragma"))
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    res.findings = kept
    return res


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__",)
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            out.append(p)
    return out


@dataclass
class LintResult:
    findings: List[Finding]
    files: int
    suppressed: int

    @property
    def counts(self) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for f in self.findings:
            c[f.rule] = c.get(f.rule, 0) + 1
        return c

    def to_json(self) -> str:
        return json.dumps({
            "version": 1,
            "files": self.files,
            "suppressed": self.suppressed,
            "counts": self.counts,
            "findings": [{
                "file": f.file, "line": f.line, "col": f.col,
                "rule": f.rule, "message": f.message,
            } for f in self.findings],
        }, indent=2, sort_keys=True)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[Rule]] = None) -> LintResult:
    """Lint every ``.py`` under ``paths`` (files or directories).
    The library entry the CLI, the tier-1 gate
    (tests/test_lint_clean.py), and bench.py's ``lint`` stage share."""
    findings: List[Finding] = []
    suppressed = 0
    files = iter_py_files(paths)
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            findings.append(Finding(path, 0, 0, META_RULE,
                                    f"unreadable: {e}"))
            continue
        res = lint_source(src, path, rules=rules)
        findings.extend(res.findings)
        suppressed += res.suppressed
    return LintResult(findings, len(files), suppressed)
