"""jaxlint: ahead-of-time static analysis for the jit disciplines.

The tree's most recurring bug class is a knob that reaches a jitted
graph without being folded into its compile-cache key (ADVICE r5 #1;
PR 6 re-threaded three knobs through every ``_jit_*`` factory by
hand). Ziria's contribution is exactly this kind of pre-codegen
program analysis (SDF cardinality checking, PAPERS.md), and Sora's
dedicated-core discipline only works because nothing in the hot loop
silently synchronizes with the host — both statically checkable here.

Entry points:

    python -m ziria_tpu.analysis [paths...]     # pure AST, no jax
    python -m ziria_tpu lint [paths...]         # same, via the CLI
    from ziria_tpu.analysis import lint_paths   # library / gate / bench

Rule catalog, pragma syntax, and how to add a rule:
docs/static_analysis.md. The tier-1 gate is
tests/test_lint_clean.py (zero findings over ``ziria_tpu/``).
"""

from ziria_tpu.analysis.engine import (Finding, LintResult,  # noqa: F401
                                       lint_paths, lint_source)
from ziria_tpu.analysis.rules import ALL_RULES, RULES_BY_ID  # noqa: F401
