"""CLI of the jaxlint static analysis: ``python -m ziria_tpu.analysis``.

Pure-AST — never imports jax — so the gate runs even when the TPU
backend probe hangs (the exact situation in which you most want a
host-only check). Exit status: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _default_target() -> str:
    """The package's own source tree — `python -m ziria_tpu.analysis`
    with no arguments lints the checkout it runs from."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    from ziria_tpu.analysis.engine import lint_paths
    from ziria_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

    p = argparse.ArgumentParser(
        prog="ziria_tpu.analysis",
        description="jaxlint: AST static analysis for jit-cache-key "
                    "completeness, host-sync leaks, and knob hygiene "
                    "(docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "ziria_tpu package directory)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (findings, per-rule "
                        "counts, suppressed count)")
    p.add_argument("--rules", metavar="R1,R2,...",
                   help="run only these rule ids")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}  {r.name}: {r.why}")
        return 0

    rules = None
    if args.rules:
        ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in ids if r not in RULES_BY_ID]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)} "
                  f"(known: {', '.join(RULES_BY_ID)})", file=sys.stderr)
            return 2
        rules = [RULES_BY_ID[r] for r in ids]

    paths = args.paths or [_default_target()]
    missing = [q for q in paths if not os.path.exists(q)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    res = lint_paths(paths, rules=rules)
    if args.json:
        print(res.to_json())
    else:
        for f in res.findings:
            print(f.render())
        counts = " ".join(f"{k}={v}" for k, v in
                          sorted(res.counts.items()))
        print(f"jaxlint: {len(res.findings)} finding(s) "
              f"[{counts or 'clean'}] across {res.files} file(s), "
              f"{res.suppressed} suppressed", file=sys.stderr)
    return 1 if res.findings else 0


if __name__ == "__main__":
    sys.exit(main())
