"""ziria-tpu: a TPU-native stream-computation framework.

A from-scratch re-design of the capabilities of the reference system
(moxfun/Ziria, a DSL + optimizing compiler for wireless PHY stream
processing — see SURVEY.md): a Python-embedded component/combinator DSL
(`take`/`emit`/`map`, `>>>` pipelines, `|>>>|` parallel pipelines), a
cardinality (synchronous-dataflow rate) analysis, and two execution
backends:

- an *interpreter* backend — the semantic oracle, streaming item-at-a-time;
- a *jit* backend — static-rate pipeline segments fuse into a single
  `jax.jit` step function (reshape/vmap/scan compositions), with chunk
  widths chosen by the vectorization planner becoming array axes, frames
  batched over a `jax.sharding.Mesh` data axis, and parallel-pipeline
  stages sharded over chips;
- a *hybrid* executor for dynamic-control programs — stream-control
  loops compile into chunked masked `lax.while_loop` state machines
  (backend/chunked.py), heavy do-blocks into cached jit fns, statement
  loops lane-vectorize (including reductions, conditional inductions
  and read-modify-write arrays), and N independent streams batch their
  device steps into single vmapped calls (backend/framebatch.py).

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

    core/      IR node types, cardinality analysis, pipeline planning
    interp/    streaming interpreter (oracle)
    backend/   JAX lowering: fused jit step functions, vectorization planner
    ops/       DSP primitive library (FFT, FIR, Viterbi incl. Pallas kernel,
               bit/CRC/scrambler/coding utilities)
    phy/       802.11a/g PHY: TX chain, RX chain (f32 + Q15 integer
               interior via rx.receive(fxp=True)), channel models,
               loopback
    parallel/  mesh construction, frame-batch sharding, stage sharding
    runtime/   host driver loop, typed stream file I/O, params/CLI
    utils/     dtype policy, tolerance differ (BlinkDiff equivalent), bits
"""

__version__ = "0.1.0"

from ziria_tpu.core.ir import (  # noqa: F401
    Comp,
    take,
    takes,
    emit,
    emit1,
    emits,
    ret,
    seq,
    let,
    let_ref,
    assign,
    zmap,
    map_accum,
    repeat,
    pipe,
    par_pipe,
    for_loop,
    while_loop,
    branch,
    jax_block,
)
from ziria_tpu.core.card import Card, cardinality  # noqa: F401
from ziria_tpu.core.types import (  # noqa: F401
    CTy,
    TTy,
    ZiriaTypeError,
    typecheck,
)
from ziria_tpu.core.opt import fold, fold_with_stats  # noqa: F401
from ziria_tpu.core.autolut import autolut  # noqa: F401
from ziria_tpu.core.vectorize import (  # noqa: F401
    VectPlan,
    mitigator,
    vectorize,
    widen,
)
