from ziria_tpu.parallel.autosplit import (AutoSplitError, auto_pipeline,
                                          balanced_partition)
from ziria_tpu.parallel.batch import data_parallel, frame_mesh, shard_batch
from ziria_tpu.parallel.multihost import (build_mesh, init_multihost,
                                          mesh_info)
from ziria_tpu.parallel.stages import PPLowered, lower_stage_parallel
from ziria_tpu.parallel.streampar import (StreamParError, sliding_parallel,
                                          stream_mesh, stream_parallel,
                                          stream_parallel_batched)

__all__ = [
    "AutoSplitError",
    "PPLowered",
    "StreamParError",
    "auto_pipeline",
    "balanced_partition",
    "build_mesh",
    "data_parallel",
    "frame_mesh",
    "init_multihost",
    "lower_stage_parallel",
    "mesh_info",
    "shard_batch",
    "sliding_parallel",
    "stream_mesh",
    "stream_parallel",
    "stream_parallel_batched",
]
