from ziria_tpu.parallel.batch import data_parallel, frame_mesh, shard_batch
from ziria_tpu.parallel.stages import PPLowered, lower_stage_parallel

__all__ = [
    "PPLowered",
    "data_parallel",
    "frame_mesh",
    "lower_stage_parallel",
    "shard_batch",
]
