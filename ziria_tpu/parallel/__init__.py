from ziria_tpu.parallel.batch import data_parallel, frame_mesh, shard_batch
from ziria_tpu.parallel.multihost import (build_mesh, init_multihost,
                                          mesh_info)
from ziria_tpu.parallel.stages import PPLowered, lower_stage_parallel

__all__ = [
    "PPLowered",
    "build_mesh",
    "data_parallel",
    "frame_mesh",
    "init_multihost",
    "lower_stage_parallel",
    "mesh_info",
    "shard_batch",
]
