"""Auto-pipelining: place an UNANNOTATED pipeline across devices.

The reference's auto-pipelining pass splits the computation graph at
`|>>>|` into threads (SURVEY.md §2.1, §3.3) — but the programmer has
to write the `|>>>|`. This pass writes it for them: given a mesh axis
of K devices, partition the flattened stage list into K contiguous
segments balancing estimated per-iteration cost, insert the ParPipe
boundaries, and hand the result to `parallel/stages.py`'s existing
stage-parallel lowering (one segment per device, chunks advancing via
`ppermute` over ICI).

Cost model: items moved per steady-state iteration
(`reps * (in_arity + out_arity)`) — a bandwidth proxy that weights
rate-expanded stages correctly without needing per-op FLOP counts.
Callers with better knowledge (e.g. measured stage times from
`--profile`) pass their own `cost_fn`; the balanced-partition DP is
cost-model-agnostic.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from ziria_tpu.core import ir
from ziria_tpu.core.card import CCard, TCard, cardinality, steady_state


class AutoSplitError(ValueError):
    pass


def _flatten(comp: ir.Comp) -> List[ir.Comp]:
    """Fully decompose >>> AND |>>>| into the leaf stage list — to a
    fixpoint, so a ParPipe nested under a Pipe (parenthesized source)
    can never survive as an opaque 'stage'."""
    if isinstance(comp, (ir.Pipe, ir.ParPipe)):
        return _flatten(comp.up) + _flatten(comp.down)
    return [comp]


def default_stage_cost(stage: ir.Comp, reps: int) -> float:
    """Items moved per steady-state iteration — the bandwidth proxy.
    Rates come from the cardinality analysis (a `repeat { takes 64;
    emit .. }` moves 65 items per firing, not 2), falling back to the
    arity fields only when no static cardinality exists."""
    c = cardinality(stage)
    if isinstance(c, TCard):
        i, o = c.i, c.o
    elif isinstance(c, CCard):
        i, o = c.take, c.emit
    else:
        i = getattr(stage, "in_arity", 1) or 1
        o = getattr(stage, "out_arity", 1) or 1
    return float(reps * (max(i, 1) + max(o, 1)))


def balanced_partition(costs: Sequence[float], k: int) -> List[int]:
    """Split `costs` into k contiguous groups minimizing the maximum
    group sum; returns the k-1 cut indices (group j = costs[cut[j-1]:
    cut[j]]). Classic O(n^2 k) DP — stage lists are tiny."""
    n = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def seg(i, j):                    # cost of stages [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[j][m] = minimal max-cost splitting first j stages into m groups
    best = [[INF] * (k + 1) for _ in range(n + 1)]
    cut = [[0] * (k + 1) for _ in range(n + 1)]
    best[0][0] = 0.0
    for m in range(1, k + 1):
        for j in range(m, n - (k - m) + 1):
            for i in range(m - 1, j):
                v = max(best[i][m - 1], seg(i, j))
                if v < best[j][m]:
                    best[j][m] = v
                    cut[j][m] = i
    cuts = []
    j, m = n, k
    while m > 1:
        i = cut[j][m]
        cuts.append(i)
        j, m = i, m - 1
    cuts.reverse()
    return cuts


def stage_runner(st: ir.Comp, cur, width: Optional[int] = None):
    """A zero-arg callable running ONE stage over `cur`: the fused jit
    path when the stage lowers, else the hybrid executor (hybridized
    ONCE so a warm-up call actually warms the _JitDo caches and a
    later timed call measures execution, not recompilation). Shared by
    the `--profile` breakdown and `measured_stage_costs` so the
    stage-timing discipline cannot drift between them."""
    import numpy as np

    from ziria_tpu.backend.execute import run_jit_carry
    from ziria_tpu.backend.lower import LowerError, lower

    try:
        lower(st, width=width)                # plan only (cheap)

        def go(_st=st, _cur=cur):
            ys, _ = run_jit_carry(_st, _cur, width=width)
            return np.asarray(ys)
    except LowerError:
        from ziria_tpu.backend.hybrid import hybridize
        from ziria_tpu.interp.interp import run as _irun
        hyb = hybridize(st)

        def go(_st=hyb, _cur=cur):
            return np.asarray(_irun(_st, list(_cur)).out_array())
    return go


def measured_stage_costs(flat: Sequence[ir.Comp], sample,
                         width: Optional[int] = None,
                         reps: int = 3) -> List[float]:
    """Wall-time each leaf stage on a sample of the REAL input (one
    warm pass to absorb compilation, then min-of-`reps` timed passes —
    the min discards scheduler preemption spikes, which on a loaded
    host otherwise misrank same-rate stages), cascading each stage's
    output into the next — the measured replacement for the
    items-moved proxy (`--pp-costs=measured`; ROADMAP r4 §4)."""
    import time as _time

    import numpy as np

    costs: List[float] = []
    cur = np.asarray(sample)
    for st in flat:
        if cur.shape[0] == 0:
            # an empty cascade would time every remaining stage on
            # nothing and report noise as a "measured" partition
            raise AutoSplitError(
                f"measured costs need a non-empty sample at every "
                f"stage; stage {st.label()} received 0 items (sample "
                f"too short for the upstream take rates?)")
        go = stage_runner(st, cur, width=width)
        out = go()                            # warm-up / compile
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = _time.perf_counter()
            out = go()
            best = min(best, _time.perf_counter() - t0)
        costs.append(max(best, 1e-9))
        cur = out
    return costs


def auto_pipeline(comp: ir.Comp, n_segments: int,
                  cost_fn: Optional[Callable] = None,
                  sample=None,
                  width: Optional[int] = None) -> ir.Comp:
    """Rewrite `comp` (a static-rate `>>>` pipeline) into `n_segments`
    ParPipe segments with balanced estimated cost. Existing ParPipe
    annotations are flattened and re-decided — this IS the decision
    pass. Returns the annotated comp for `lower_stage_parallel`.

    Costs come from (highest priority first): `sample` — measured
    per-stage wall time over that input sample; `cost_fn(stage, reps)`;
    or the items-moved proxy."""
    flat = _flatten(comp)
    if n_segments < 1:
        raise AutoSplitError("need at least one segment")
    if n_segments > len(flat):
        raise AutoSplitError(
            f"cannot split {len(flat)} stage(s) into {n_segments} "
            f"segments; reduce the axis or widen the program")
    ss = steady_state(flat)
    if ss is None:
        raise AutoSplitError(
            "auto-pipelining needs a static steady state; dynamic "
            "pipelines run on the hybrid executor instead")
    if sample is not None:
        costs = measured_stage_costs(flat, sample, width=width)
    else:
        fn = cost_fn or default_stage_cost
        costs = [fn(s, r) for s, r in zip(flat, ss.reps)]
    cuts = [0] + balanced_partition(costs, n_segments) + [len(flat)]
    groups = []
    for a, b in zip(cuts[:-1], cuts[1:]):
        seg_stages = flat[a:b]
        g = seg_stages[0]
        for s in seg_stages[1:]:
            g = ir.Pipe(g, s)
        groups.append(g)
    return ir.par_pipe(*groups)
