"""Frame batching across chips — the framework's data-parallel axis.

The reference has no batch axis at all (streams are sequential,
SURVEY.md §2.4); independent frames across a TPU mesh is the new
capability that buys the headline throughput: `pjit` shards the frame
axis over 'dp', every chip decodes its shard, no collectives needed in
steady state (only at host gather).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def frame_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """A 1-D device mesh over the first `n_devices` devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_batch(mesh: Mesh, x, axis: str = "dp"):
    """Place `x` with its leading (frame) axis sharded over `axis`."""
    spec = P(axis, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def data_parallel(fn: Callable, mesh: Mesh, axis: str = "dp") -> Callable:
    """jit `fn` (batched: leading axis = frames) with the frame axis
    sharded over `axis` on `mesh` for both inputs and outputs.

    `fn` must be shardable along its leading axis (vmap-style); XLA then
    runs each chip's shard independently — the |>>>|-free scale-out path.
    """

    def in_sharding(a):
        return NamedSharding(mesh, P(axis, *([None] * (np.ndim(a) - 1))))

    def run(*args):
        shardings = jax.tree.map(in_sharding, args)
        return jax.jit(fn, in_shardings=shardings)(*args)

    return run
