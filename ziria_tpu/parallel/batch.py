"""Frame batching across chips — the framework's data-parallel axis.

The reference has no batch axis at all (streams are sequential,
SURVEY.md §2.4); independent frames across a TPU mesh is the new
capability that buys the headline throughput: `pjit` shards the frame
axis over 'dp', every chip decodes its shard, no collectives needed in
steady state (only at host gather). `phy/link.sweep_ber_sharded`
rides exactly this pattern for the serving workload: the BER sweep's
frame-lane axis placed with :func:`shard_batch`, every chip sweeping
its shard of lanes, ONE integer all-reduce per sweep for the counts.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def frame_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """A 1-D device mesh over the first `n_devices` devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def largest_divisor(n: int, cap: int) -> int:
    """The largest divisor of ``n`` that is <= ``cap`` — the mesh
    width an S-lane fleet can actually use (the stream axis must
    shard EVENLY, `shard_batch`'s rule). >= 1 always (every fleet
    runs on one device)."""
    if n < 1 or cap < 1:
        raise ValueError(f"need n >= 1 and cap >= 1, got ({n}, {cap})")
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


def elastic_mesh(n_streams: int, n_devices: Optional[int] = None,
                 axis: str = "dp") -> Optional[Mesh]:
    """The ELASTIC placement rule (ISSUE 14 failover): build the
    widest dp mesh the surviving device fleet supports for an
    ``n_streams``-lane receiver — the largest divisor of S that fits
    the visible (or capped) device count. Returns None when that is
    one device (an unsharded receiver is the correct degenerate
    mesh), so recovery onto a shrunken ``--devices`` — or a machine
    that lost a chip — rebuilds the fleet on whatever is left instead
    of refusing to start."""
    avail = len(jax.devices()) if n_devices is None \
        else min(n_devices, len(jax.devices()))
    m = largest_divisor(n_streams, max(1, avail))
    return None if m <= 1 else frame_mesh(m, axis)


def lane_sharding(mesh: Mesh, ndim: int, axis: str = "dp") -> NamedSharding:
    """The ONE placement rule of every dp surface: leading (frame/lane)
    axis sharded over `axis`, everything else replicated."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def shard_batch(mesh: Mesh, x, axis: str = "dp"):
    """Place `x` with its leading (frame) axis sharded over `axis`."""
    return jax.device_put(x, lane_sharding(mesh, np.ndim(x), axis))


def stream_specs(ndims, axis: str = "dp"):
    """`shard_map` PartitionSpecs for leading-axis sharding: one spec
    per rank in `ndims`, each sharding axis 0 over `axis` and
    replicating the rest — the shard_map twin of :func:`lane_sharding`
    (the multi-stream receiver's chunk and decode programs pass their
    argument/result ranks through this so the stream axis always
    lands on dp, never hand-written per program)."""
    from jax.sharding import PartitionSpec as P
    return tuple(P(axis, *([None] * (int(n) - 1))) for n in ndims)


def data_parallel(fn: Callable, mesh: Mesh, axis: str = "dp") -> Callable:
    """jit `fn` (batched: leading axis = frames) with the frame axis
    sharded over `axis` on `mesh` for both inputs and outputs.

    `fn` must be shardable along its leading axis (vmap-style); XLA then
    runs each chip's shard independently — the |>>>|-free scale-out path.
    """

    def run(*args):
        shardings = jax.tree.map(
            lambda a: lane_sharding(mesh, np.ndim(a), axis), args)
        return jax.jit(fn, in_shardings=shardings)(*args)

    return run
