"""Multi-host scale-out: process bring-up + DCN/ICI-aware meshes.

The reference has NO distributed backend — all cross-stage traffic is
shared-memory SPSC queues on one machine (SURVEY.md §2.5). This module
is the new framework's equivalent of what an NCCL/MPI layer would be,
done the TPU way: ``jax.distributed`` brings up the multi-process
runtime over DCN, and mesh construction lays the parallel axes out so
the *latency-sensitive* axis rides ICI while the *embarrassingly
parallel* axis crosses DCN:

- ``pp`` (stage parallelism, ``|>>>|``) moves a chunk between adjacent
  stages via ``ppermute`` every macro step — it must live on ICI
  (within a host/slice), or every stream item pays a network hop;
- ``dp`` (frame batching) has NO steady-state collectives (shards are
  independent until the host gather), so it is the axis that can span
  hosts over DCN for free.

``build_mesh`` encodes that policy: single-process it defers to
``mesh_utils.create_device_mesh`` (which optimizes ICI adjacency);
multi-process it uses ``create_hybrid_device_mesh`` with the dp axis
on the DCN dimension. The same (dp, pp) mesh then drives
``parallel.batch`` and ``parallel.stages`` unchanged — the collectives
are inserted by XLA from the shardings, never hand-written.

.. warning:: EXPERIMENTAL (VERDICT r3 weak #8): the multi-process
   bring-up path has only ever executed in simulation
   (tests/test_multihost.py fakes the process set); no real
   multi-host job has run for lack of hardware. The single-process
   mesh-construction path is exercised everywhere.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   auto: bool = False,
                   **kwargs) -> bool:
    """Bring up the multi-process JAX runtime (DCN).

    Three modes:
    - no arguments: safe NO-OP (returns False) — the single-process
      dev/test case never touches the backend;
    - ``auto=True``: call ``jax.distributed.initialize()`` with no
      arguments and let JAX auto-detect the cluster from the
      environment (the TPU-pod path);
    - explicit coordinator/num_processes/process_id: CPU/GPU clusters.

    Counterpart of the reference's (nonexistent) NCCL/MPI init — the
    rest of the framework never sees processes, only the global device
    list."""
    if not auto and num_processes in (None, 1) \
            and coordinator_address is None:
        return False
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id, **kwargs)
    return True


def build_mesh(dp: int = 1, pp: int = 1,
               axis_names: Tuple[str, str] = ("dp", "pp"),
               devices: Optional[Sequence] = None) -> Mesh:
    """A (dp, pp) mesh with DCN/ICI-aware layout (see module doc).

    dp*pp devices are used. Multi-process: dp spans the process (DCN)
    dimension — it must be a multiple of the process count; pp stays
    inside each process's ICI domain. Single-process: the mesh comes
    from create_device_mesh, which orders devices for ICI adjacency on
    real TPU topologies (and is a plain reshape on CPU/virtual
    devices)."""
    import jax
    from jax.experimental import mesh_utils

    devices = list(devices if devices is not None else jax.devices())
    n = dp * pp
    if len(devices) < n:
        raise ValueError(
            f"build_mesh(dp={dp}, pp={pp}) needs {n} devices; "
            f"{len(devices)} visible")
    devices = devices[:n]
    n_proc = len({d.process_index for d in devices})
    if n_proc > 1:
        if dp % n_proc:
            raise ValueError(
                f"dp={dp} must be a multiple of the process count "
                f"({n_proc}): dp is the axis that crosses DCN; pp "
                f"must stay inside one host's ICI domain")
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(dp // n_proc, pp),
            dcn_mesh_shape=(n_proc, 1),
            devices=devices)
    else:
        arr = mesh_utils.create_device_mesh((dp, pp), devices=devices)
    return Mesh(np.asarray(arr), axis_names)


def mesh_info(mesh: Mesh) -> dict:
    """Inspectable layout summary (which axis crosses processes)."""
    devs = np.asarray(mesh.devices)
    procs = np.vectorize(lambda d: d.process_index)(devs)
    return {
        "shape": dict(zip(mesh.axis_names, devs.shape)),
        "n_processes": int(len(np.unique(procs))),
        # an axis is DCN-crossing if process_index varies along it
        "dcn_axes": [
            name for k, name in enumerate(mesh.axis_names)
            if np.any(np.diff(procs, axis=k) != 0)
        ],
    }
