"""Stage-parallel pipeline execution: the `|>>>|` analogue on a mesh.

The reference runs each `|>>>|` segment on its own core with SPSC
"thread-separator" queues between (SURVEY.md §3.3 — the only concurrency
boundary it has). TPU-native redesign: each segment is fused by the jit
backend (backend/lower.py) and placed on one device of a mesh axis;
chunks advance segment-to-segment with `lax.ppermute` over ICI (the
SPSC-queue analogue: one nearest-neighbor collective per macro step),
and the whole software-pipelined loop is ONE `shard_map`-ped
`lax.scan`.

Cost model (measured, VERDICT r1 weak #4): every device's program
contains all K `lax.switch` branches, so program size grows O(K x
segment size) — but compile time at realistic K is benign (virtual
8-way CPU mesh, trivial segments: 0.36 s at K=2, 0.35 s at K=4,
0.52 s at K=8 end-to-end including the first run; pinned by
tests/test_parallel.test_compile_time_scaling_bounded). The masked
psum output broadcast runs every macro step by construction; its cost
is one K-way reduction of an output chunk per step. ICI behavior of
the ppermute on real multi-chip hardware remains unmeasured (single
tunnelled chip only) — revisit when a multi-chip slice is available.

SPMD encoding of the MPMD pipeline:

- every device holds the full tuple of segment carries but only evolves
  its own (selected with `lax.switch` on `axis_index` — switch executes
  a single branch, so there is no wasted compute);
- inter-segment chunks live in a K-1 tuple of boundary "slots"; device k
  fills slot k, the whole tuple ppermute-shifts k -> k+1 each macro
  step, device k+1 reads slot k. Dtypes/shapes per boundary are
  preserved exactly (no flatten-to-f32 carrier);
- the last segment's output is broadcast with a masked `psum`, so the
  scan's stacked output is replicated and the host reads it once.

Latency/fill: with K segments, output m corresponds to input m-(K-1);
the driver feeds K-1 trailing dummy chunks and trims the first K-1
outputs (classic pipeline fill/drain bubbles).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ziria_tpu.utils.compat import shard_map

from ziria_tpu.core import ir
from ziria_tpu.core.card import TCard, cardinality
from ziria_tpu.backend.lower import Lowered, LowerError, lower


def _lcm(a: int, b: int) -> int:
    from math import gcd
    return a * b // gcd(a, b)


def _segment_widths(segs: Sequence[ir.Comp], width: int) -> list:
    """Per-segment lowering widths that rate-match every boundary.

    Each segment's own steady state consumes/produces (take_k, emit_k)
    per iteration; the boundary between k and k+1 balances when
    emit_k * w_k == take_{k+1} * w_{k+1} — the same SDF repetition
    solve as core.card.steady_state, one level up.
    """
    rates = []
    for s in segs:
        c = cardinality(s)
        if not isinstance(c, TCard) or c.i == 0 or c.o == 0:
            raise LowerError(
                f"stage-parallel segment {s.label()} needs a static "
                f"transformer rate with nonzero input and output")
        rates.append(c)
    w = [1] * len(segs)
    for k in range(len(segs) - 1):
        prod = rates[k].o * w[k]
        need = rates[k + 1].i
        l = _lcm(prod, need)
        if l // prod != 1:
            for j in range(k + 1):
                w[j] *= l // prod
            prod = l
        w[k + 1] = prod // need
    return [wi * width for wi in w]


@dataclass
class PPLowered:
    """A stage-parallel pipeline bound to a mesh axis.

    ``run(xs)``: xs (M, take, *item) -> (M, emit, *out_item); M macro
    steps of input, same M of output (fill/drain handled internally).

    ``run_carry(xs)``: (ys, fused_carry) — additionally returns the
    segments' exit carries flattened to the FUSED single-device
    lowering's per-stage tuple (``lower(pipe(*segments))``'s carry
    order), so a sub-macro-chunk input remainder can continue on the
    single-device path with exact state (the reference's queues had no
    length restriction; SURVEY.md §2.2 TS queues). Fill/drain bubbles
    never step segment carries (two-sided masking), so the exit
    carries equal the sequential run's after the same items.
    """

    run: Callable
    run_carry: Callable
    take: int
    emit: int
    n_stages: int
    labels: Tuple[str, ...]


def lower_stage_parallel(comp: ir.Comp, mesh: Mesh, axis: str = "pp",
                         in_item: jax.ShapeDtypeStruct = None,
                         width: int = 1,
                         batch_axis: Optional[str] = None) -> PPLowered:
    """Lower a ParPipe pipeline onto `mesh[axis]`, one segment per device.

    `in_item` is the shape/dtype of ONE input stream item (default: f32
    scalar). The number of ParPipe segments must equal the axis size.

    With ``batch_axis`` set (a second mesh axis, e.g. a (dp, pp) 2-D
    mesh), ``run`` takes a BATCH of independent streams — shape
    (B, M, take, *item) — sharded over `batch_axis`; every dp row runs
    its own software-pipelined stream over the pp axis. This composes
    the framework's two parallel axes (SURVEY.md §2.4): frame/stream
    batching × stage parallelism, on one mesh.
    """
    segs = ir.par_segments(comp)
    K = len(segs)
    n_dev = mesh.shape[axis]
    if K != n_dev:
        raise LowerError(
            f"{K} |>>>| segments but mesh axis {axis!r} has {n_dev} "
            f"devices; split the pipeline to match (or batch frames over "
            f"'dp' instead)")
    if in_item is None:
        in_item = jax.ShapeDtypeStruct((), jnp.float32)

    widths = _segment_widths(segs, width)
    lows = [lower(s, width=w) for s, w in zip(segs, widths)]

    # probe boundary chunk shapes with abstract evaluation
    chunk_structs = []
    cur = jax.ShapeDtypeStruct((lows[0].take,) + tuple(in_item.shape),
                               in_item.dtype)
    for lo in lows:
        _, out = jax.eval_shape(lo.step, lo.init_carry, cur)
        chunk_structs.append(cur)
        cur = jax.ShapeDtypeStruct(tuple(out.shape), out.dtype)
    out_struct = cur

    def zeros_like_struct(s):
        return jnp.zeros(s.shape, s.dtype)

    init_carries = tuple(lo.init_carry for lo in lows)
    init_slots = tuple(zeros_like_struct(chunk_structs[k + 1])
                       for k in range(K - 1))
    perm = [(k, k + 1) for k in range(K - 1)]

    def make_branch(k):
        lo = lows[k]

        def br(operand):
            carries, slots, x_in, m, m_real = operand
            my_in = x_in if k == 0 else slots[k - 1]

            # Input m reaches segment k at macro step m+k, so the live
            # window for segment k is k <= m < m_real + k; outside it
            # the chunk is a fill/drain bubble (zeros) and a stateful
            # segment must NOT step its carry on it — fill bubbles
            # would diverge from the fused >>> lowering, and drain
            # bubbles would corrupt the exit carries run_carry hands
            # to the single-device remainder path.
            def live(cx):
                c, out = lo.step(cx[0], cx[1])
                return c, out

            def bubble(cx):
                return cx[0], zeros_like_struct(
                    chunk_structs[k + 1] if k < K - 1 else out_struct)

            alive = jnp.logical_and(m >= k, m < m_real + k)
            c, out = lax.cond(alive, live, bubble, (carries[k], my_in))
            carries = tuple(c if j == k else carries[j] for j in range(K))
            if k < K - 1:
                slots = tuple(out if j == k else slots[j]
                              for j in range(K - 1))
                final = zeros_like_struct(out_struct)
            else:
                final = out
            return carries, slots, final

        return br

    branches = [make_branch(k) for k in range(K)]

    def _mask_psum(leaf, keep):
        """Replicate `leaf` from the device where `keep` holds (exact:
        the other devices contribute zeros of the same dtype)."""
        if leaf.dtype == jnp.bool_:
            z = jnp.where(keep, leaf.astype(jnp.int32), 0)
            return lax.psum(z, axis).astype(jnp.bool_)
        return lax.psum(jnp.where(keep, leaf, jnp.zeros_like(leaf)), axis)

    def spmd_one(xs):
        """Per-device program; xs replicated (M+K-1, take, *item).
        Returns (ys, carries) with carries replicated (each segment's
        exit state gathered from its owning device)."""
        idx = lax.axis_index(axis)
        m_real = xs.shape[0] - (K - 1)      # static: real macro steps

        def macro(state, xm):
            x, m = xm
            carries, slots = state
            carries, slots, final = lax.switch(
                idx, branches, (carries, slots, x, m, m_real))
            if K > 1:
                slots = lax.ppermute(slots, axis, perm)
            # replicate the tail device's output to everyone (exact in
            # the native dtype; non-tail devices contribute zeros)
            final = lax.psum(
                jnp.where(idx == K - 1, final, jnp.zeros_like(final)),
                axis)
            return (carries, slots), final

        steps = jnp.arange(xs.shape[0], dtype=jnp.int32)
        (carries, _), ys = lax.scan(
            macro, (init_carries, init_slots), (xs, steps))
        carries = tuple(
            jax.tree_util.tree_map(
                lambda lf: _mask_psum(lf, idx == k), carries[k])
            for k in range(K))
        return ys, carries

    if batch_axis is None:
        spec_in = P()
        carry_specs = jax.tree_util.tree_map(lambda _: P(), init_carries)
        spec_out = (P(*([None] * (len(out_struct.shape) + 1))),
                    carry_specs)
        spmd = spmd_one
    else:
        # each dp row holds its local shard of streams; vmap runs the
        # pipeline per stream (the pp collectives batch under vmap).
        # Exit carries ARE exposed, one per stream (leading batch axis
        # on every carry leaf): the bubble masking already keeps them
        # exact, so each stream can hand its own remainder to the
        # single-device continuation (VERDICT r3 next #6).
        spec_in = P(batch_axis)
        carry_specs = jax.tree_util.tree_map(
            lambda _: P(batch_axis), init_carries)
        spec_out = (P(batch_axis, *([None] *
                                    (len(out_struct.shape) + 1))),
                    carry_specs)

        def spmd(xs_b):
            return jax.vmap(spmd_one)(xs_b)

    mapped = shard_map(spmd, mesh=mesh, in_specs=spec_in,
                       out_specs=spec_out, check_vma=False)
    jitted = jax.jit(mapped)

    t_axis = 0 if batch_axis is None else 1

    def _call(xs):
        xs = jnp.asarray(xs)
        if K > 1:  # trailing dummies flush the pipeline
            pad_shape = list(xs.shape)
            pad_shape[t_axis] = K - 1
            xs = jnp.concatenate(
                [xs, jnp.zeros(pad_shape, xs.dtype)], axis=t_axis)
        out = jitted(xs)
        ys, carries = out
        if K > 1:
            ys = ys[K - 1:] if batch_axis is None else ys[:, K - 1:]
        return ys, carries

    def run(xs):
        return _call(xs)[0]

    def run_carry(xs):
        """(ys, carry) — carry is a run_jit_carry-compatible dict whose
        "stages" tuple follows lower(pipe(*segments))'s stage order.
        On the batched (dp x pp) path, a LIST of such dicts, one per
        stream (row of xs)."""
        from itertools import chain
        ys, carries = _call(xs)
        if batch_axis is None:
            return ys, {"stages": tuple(chain.from_iterable(carries))}
        per_stream = []
        for b in range(int(ys.shape[0])):
            cb = jax.tree_util.tree_map(lambda x, b=b: x[b], carries)
            per_stream.append(
                {"stages": tuple(chain.from_iterable(cb))})
        return ys, per_stream

    return PPLowered(run=run, run_carry=run_carry, take=lows[0].take,
                     emit=lows[-1].emit, n_stages=K,
                     labels=tuple(s.label() for s in segs))
