"""Stream (sequence) parallelism: ONE long stream split across chips.

The reference scales a stream only in time (vectorized chunks) and by
pipeline stages (`|>>>|` threads); a TPU pod adds the axis the task's
long-context requirement asks for — split one long stream's ITEMS
contiguously over an `sp` mesh axis, the way sequence parallelism
splits a long sequence across devices (jax-ml scaling-book recipe:
pick a mesh, annotate shardings, let XLA place collectives on ICI).

Two entry points:

- :func:`stream_parallel` — run a static-rate pipeline over one
  stream with the item axis sharded. Stateless stages (after fold:
  chains of `Map`s, e.g. demap → deinterleave tables, LUT gathers)
  shard freely: each device runs the SAME fused step the single-chip
  backend uses (`backend/lower.py`) on its contiguous slice — no
  collectives in steady state. Stateful stages join in when their
  state evolves independently of the data and declares a closed-form
  fast-forward (``MapAccum.advance(state, n)``: LFSR scramblers are
  M^n·s over GF(2), CFO derotators are ph + n·eps) — each device's
  entry state is fast-forwarded to its shard offset, the parallel-
  prefix trick specialized to constant per-item transforms. Stages
  with FINITE input memory (``MapAccum.memory=K``: FIR delay lines,
  sliding windows) are seeded by an exact warmup scan over the K
  items before each shard — requirements cascade (sum) down the
  pipeline. Truly sequential unbounded state (a cumsum) is refused
  with the dp/pp guidance.

- :func:`sliding_parallel` — the halo-exchange form for windowed ops
  (correlation, FIR, sliding sums: `ops/sync.py`). Each device holds a
  contiguous shard plus `window-1` items of LEFT halo fetched from its
  neighbor with ONE `ppermute` over ICI (the sequence-parallel
  neighbor exchange), then maps a plain array function over
  shard+halo. Valid (full) outputs only: N - window + 1 results for N
  items, exactly like the host-side op.

Both are validated on the 8-device virtual CPU mesh
(tests/test_streampar.py) and by `__graft_entry__.dryrun_multichip`.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from ziria_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ziria_tpu.backend.lower import lower
from ziria_tpu.core import ir


class StreamParError(ValueError):
    """Pipeline not stream-parallelizable (stateful, or shapes that
    cannot align to the mesh)."""


# ---------------------------------------------------------------------
# Device-side warmup helpers, shared by the single-stream and dp x sp
# paths (a drifting copy of warmup logic would be a silent
# backend-divergence risk — same discipline as _stage_plan).


def _carry_sig(c):
    """Shape/dtype signature of a carry pytree — the warm scan steps
    width-1 carries into a wider lowering's entry carry, which only
    works while the carry pytree is width-independent (ADVICE r3)."""
    return jax.tree_util.tree_map(
        lambda x: (jnp.shape(x), jnp.asarray(x).dtype), c)


def _gather_warm_window(flat, axis: str, n_dev: int, n_hops: int,
                        warm_take: int):
    """The last `warm_take` items of the stream BEFORE this device's
    shard, collected from the `n_hops` left neighbors — one ppermute
    per spanned shard, each sending only what the window needs (the
    furthest shard contributes just its tail). Devices whose prefix is
    shorter than the window receive zero filler for the missing lead;
    callers mask those iterations off in the warm scan."""
    shard_items = flat.shape[0]
    parts = []
    for hop in range(n_hops, 0, -1):
        send = flat
        if hop == n_hops:
            need = min(shard_items,
                       warm_take - (n_hops - 1) * shard_items)
            send = flat[shard_items - need:]
        parts.append(jax.lax.ppermute(
            send, axis, [(i, i + hop) for i in range(n_dev - hop)]))
    window = jnp.concatenate(parts, axis=0)
    if window.shape[0] < warm_take:
        # window longer than every gatherable prefix (hop count is
        # capped at n_dev-1): the missing lead is before-stream for
        # ALL devices and always masked — zeros are shape filler only
        pad = jnp.zeros((warm_take - window.shape[0],)
                        + window.shape[1:], window.dtype)
        window = jnp.concatenate([pad, window], axis=0)
    return window


def _masked_warm_scan(small, carry, wchunks, first):
    """Scan `small.step` over the warm window, holding the carry
    through the leading iterations a short left prefix doesn't have
    (`first` = number of invalid leading iterations, 0 on devices with
    a full window)."""
    def mstep(c, inp):
        i, x = inp
        c2, _ = small.step(c, x)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(i >= first, a, b), c2, c), 0

    idx = jnp.arange(wchunks.shape[0], dtype=jnp.int32)
    return jax.lax.scan(mstep, carry, (idx, wchunks))[0]


def stream_mesh(n_devices: Optional[int] = None, axis: str = "sp") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise StreamParError(
                f"need {n_devices} devices, only {len(devs)} visible")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _stage_plan(comp: ir.Comp, big):
    """Classify every carried stage for sharding: stateless (None),
    `advance` fast-forward, or finite `memory` (accumulating the
    cascaded warmup budget). The single source of truth for both the
    single-stream and the batched (dp x sp) paths — a drifting copy
    was itself a backend-divergence risk.

    Memory requirements CASCADE down the pipeline: a stage's inputs
    are only correct once every upstream memory stage has itself
    settled, so the totals ADD (a max would feed this stage the
    upstream's cold-start outputs — caught by the executor-agreement
    fuzzer, seed 4).
    """
    stages = ir.pipeline_stages(comp)
    advances = []
    warm_iters = 0
    for j, (s, c0) in enumerate(zip(stages, big.init_carry)):
        if not jax.tree_util.tree_leaves(c0):
            advances.append(None)
            continue
        adv = getattr(s, "advance", None)
        mem = getattr(s, "memory", None)
        if adv is not None:
            advances.append(adv)
        elif mem is not None:
            if int(mem) != mem or int(mem) < 1:
                raise StreamParError(
                    f"stage {s.label()}: memory={mem!r} must be a "
                    f"positive integer (items of input history)")
            per_iter = big.ss.reps[j] * max(1, s.in_arity)
            warm_iters += -(-int(mem) // per_iter)
            advances.append(None)
        else:
            raise StreamParError(
                f"stage {s.label()} has loop-carried state and neither "
                f"an advance(state, n) fast-forward nor a finite "
                f"`memory` declaration; a sequential carry cannot "
                f"split across a stream — use frame batching "
                f"(parallel/batch.py) / stage pipelining "
                f"(parallel/stages.py)")
    return stages, advances, warm_iters


def _fast_forward_carry(stages, big, advances, n_iters: int):
    """Entry carries after `n_iters` iterations, using analytic
    fast-forward for advance-stages and init for everything else
    (memory stages get their warmup applied by the caller)."""
    out = []
    for j, (s, c0, adv) in enumerate(
            zip(stages, big.init_carry, advances)):
        if adv is None:
            out.append(c0)
        else:
            st = adv(s.init_state(), n_iters * big.ss.reps[j])
            out.append(jax.tree_util.tree_map(jnp.asarray, st))
    return tuple(out)


def _entry_carry_fn(comp, big, stages, advances, warm_iters: int):
    """carry_at(iters_done, items) shared by the single-stream and
    batched paths: analytic fast-forward plus (when any stage declares
    finite memory) a warmup scan over the `items` just before the
    shard. `items` is the stream the shard belongs to — for the
    batched path, each FRAME's own items."""
    small = lower(comp, width=1) if warm_iters else None
    warm_scan = jax.jit(small.scan_steps()) if warm_iters else None

    def carry_at(iters_done: int, items):
        warm = min(warm_iters, iters_done)
        base = _fast_forward_carry(stages, big, advances,
                                   iters_done - warm)
        if not warm:
            return base
        t1 = big.ss.take
        seg = items[(iters_done - warm) * t1: iters_done * t1]
        chunks = jnp.asarray(
            seg.reshape((warm, small.take) + items.shape[1:]))
        carry, _ = warm_scan(base, chunks)
        return carry

    return carry_at


def stream_parallel(comp: ir.Comp, inputs, mesh: Mesh,
                    axis: str = "sp", width: Optional[int] = None):
    """Run pipeline `comp` over `inputs` (one stream, leading axis =
    items) with the stream split contiguously across `mesh`; returns
    the full output stream (numpy).

    Stages must be stateless, or stateful with a declared fast-forward
    (``MapAccum.advance(state, n)`` — data-independent state evolution:
    LFSR scramblers, phase accumulators) or finite input memory
    (``MapAccum.memory=K`` — FIR delay lines; entry state seeded by an
    exact warmup scan over the K preceding items). Each device's entry
    state is reconstructed at its shard's first firing, so the result
    is exactly the sequential one. Iterations that don't divide evenly
    (and the sub-iteration tail) run on the single-chip path with the
    reconstructed tail state, so the result equals `run_jit` on any
    length.
    """
    n_dev = mesh.shape[axis]
    big = lower(comp, width=width)
    inputs = np.asarray(inputs)
    n_iters = inputs.shape[0] // big.ss.take
    if n_iters == 0:
        # below one steady-state iteration: delegate entirely so the
        # empty-output conventions match the single-chip path exactly
        from ziria_tpu.backend.execute import run_jit
        return run_jit(comp, inputs, width=1)

    # each device gets `per` steady-state iterations, grouped into
    # bulk steps of `width` iterations = big.take items; when the
    # planned width exceeds a device's share, re-plan at the share so
    # short streams still shard instead of falling to the tail path.
    # The stage plan and entry-carry closure are built AFTER the
    # re-plan: today ss.reps/init_carry are width-independent, but
    # deriving them from the final lowering removes the silent
    # assumption (ADVICE r2)
    share = n_iters // n_dev
    if 0 < share < big.width:
        big = lower(comp, width=share)
    stages, advances, warm_iters = _stage_plan(comp, big)
    stateful = any(jax.tree_util.tree_leaves(c0)
                   for c0 in big.init_carry)
    _carry_at = _entry_carry_fn(comp, big, stages, advances, warm_iters)

    def carry_at(iters_done: int):
        return _carry_at(iters_done, inputs)
    per = share // big.width * big.width
    outs = []
    if per:
        steps = per // big.width
        body_items = n_dev * per * big.ss.take
        bulk = jnp.asarray(
            inputs[:body_items].reshape(
                (n_dev * steps, big.take) + inputs.shape[1:]))
        scan = big.scan_steps()

        # memory-stage warmup runs ON DEVICE: each device gathers the
        # warm window (the last warm_take items of the stream before
        # its shard) from its left neighbors — ONE ppermute hop per
        # shard the window spans — and seeds its entry carry with a
        # masked warm scan over it (VERDICT r2 weak #4; the multi-hop
        # generalization closes r3 weak #6's "window must fit one
        # shard" condition). Devices whose left prefix is shorter than
        # the window (device 0 above all) mask the missing leading
        # iterations so the scan starts from their fast-forward base.
        device_warm = warm_iters > 0 and n_dev > 1
        if device_warm:
            small = lower(comp, width=1)
            if _carry_sig(small.init_carry) != _carry_sig(
                    big.init_carry):
                device_warm = False   # host fallback beats corruption
        if device_warm:
            warm_take = warm_iters * small.take
            shard_items = per * big.ss.take
            n_hops = min(n_dev - 1, -(-warm_take // shard_items))
            carries = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[_fast_forward_carry(stages, big, advances,
                                      max(0, d * per - warm_iters))
                  for d in range(n_dev)])
        else:
            # host path: no memory stages (or carry-shape mismatch) —
            # carry_at does any warmup scans
            carries = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[carry_at(d * per) for d in range(n_dev)])

        def shard_body(carry_stack, chunks):
            # chunks: (steps, take, ...) local; carry leaves: (1, ...)
            carry = jax.tree_util.tree_map(lambda x: x[0], carry_stack)
            if device_warm:
                flat = chunks.reshape((steps * big.take,)
                                      + chunks.shape[2:])
                wflat = _gather_warm_window(flat, axis, n_dev, n_hops,
                                            warm_take)
                wchunks = wflat.reshape((warm_iters, small.take)
                                        + wflat.shape[1:])
                first = jnp.maximum(
                    warm_iters - jax.lax.axis_index(axis) * per, 0)
                carry = _masked_warm_scan(small, carry, wchunks, first)
            _, ys = scan(carry, chunks)
            return ys

        # out_specs uses bare P(axis): unmentioned trailing dims are
        # unsharded, and the OUTPUT rank may differ from the input rank
        # (pairs in -> scalar bits out; ADVICE r2 reproduced the
        # failure with the input-rank spec)
        spec = P(axis, *([None] * (bulk.ndim - 1)))
        run = jax.jit(shard_map(
            shard_body, mesh=mesh, in_specs=(P(axis), spec),
            out_specs=P(axis)))
        with mesh:
            ys = np.asarray(run(carries, bulk))
        outs.append(ys.reshape((n_dev * steps * big.emit,)
                               + ys.shape[2:]))
        done_iters = n_dev * per
    else:
        done_iters = 0

    if done_iters < n_iters:                  # remainder on one device
        from ziria_tpu.backend.execute import run_jit_carry
        pos = done_iters * big.ss.take
        rem = inputs[pos: n_iters * big.ss.take]
        tail_carry = carry_at(done_iters) if stateful else None
        # carry structure is width-independent (execute.py), so let the
        # planner pick the tail width rather than forcing 1
        tail, _ = run_jit_carry(comp, rem, carry=tail_carry, width=width)
        outs.append(np.asarray(tail))
    # n_iters >= 1 here, so either the bulk or the tail branch ran
    return np.concatenate(outs, axis=0)


def stream_parallel_batched(comp: ir.Comp, batch, mesh: Mesh,
                            dp_axis: str = "dp", sp_axis: str = "sp",
                            width: Optional[int] = None):
    """Both new axes at once: a BATCH of independent streams (leading
    axis = frames) sharded over `dp_axis`, each stream's items split
    over `sp_axis` — the 2-D composition (dp × sp) of frame batching
    and sequence parallelism on one mesh.

    Same stage discipline as :func:`stream_parallel`: stateless,
    `advance` (frame-independent analytic fast-forward), or finite
    `memory` — whose entry state is seeded per (frame, shard) by a
    warmup scan over that FRAME's own preceding items, host-side.
    Frames must divide over dp (frames % dp == 0); per-frame length
    may be RAGGED relative to sp x width — the sp*width-aligned bulk
    runs on the 2-D mesh and the remaining iterations finish per
    frame with the single-stream path's carry-seeded host tail
    (VERDICT r3 next #6; the reference's queues had no length
    restriction, SURVEY.md §2.2). Items beyond a whole steady-state
    iteration (N % take) are never consumed, matching the lowered
    semantics everywhere else.
    """
    n_dp = mesh.shape[dp_axis]
    n_sp = mesh.shape[sp_axis]
    batch = np.asarray(batch)
    if batch.ndim < 2:
        raise StreamParError("batch needs (frames, items, ...)")
    B, N = batch.shape[0], batch.shape[1]
    if B % n_dp:
        raise StreamParError(f"{B} frames do not divide over "
                             f"{n_dp} dp devices")
    big = lower(comp, width=width)
    n_iters = N // big.ss.take
    if n_iters == 0:
        raise StreamParError(
            f"{N} items are fewer than one steady-state take "
            f"({big.ss.take})")
    share = n_iters // n_sp
    if 0 < share < big.width:
        big = lower(comp, width=share)
    per = share // big.width * big.width
    done_iters = n_sp * per

    stages, advances, warm_iters = _stage_plan(comp, big)
    stateful = any(jax.tree_util.tree_leaves(c0)
                   for c0 in big.init_carry)
    if per == 0:
        # too short to shard over sp: every frame runs as a plain
        # carry-seeded host run (still exact, still one code path)
        from ziria_tpu.backend.execute import run_jit_carry
        outs = []
        for f in range(B):
            t, _ = run_jit_carry(
                comp, batch[f, : n_iters * big.ss.take], width=width)
            outs.append(np.asarray(t))
        return np.stack(outs)
    # memory-stage warmup runs ON DEVICE: each frame's warm window is
    # gathered from the left sp-neighbors inside the shard_map (one
    # ppermute hop per shard the window spans — multi-hop r4, closing
    # r3 weak #6's fits-one-shard condition) and a masked warm scan
    # seeds the entry carry — the host never feeds B x n_sp per-frame
    # warmup scans (VERDICT r2 weak #4). Advance-stage fast-forward
    # stays host-side (closed-form, data-independent,
    # frame-independent — and user advance fns may not be traceable).
    device_warm = warm_iters > 0 and n_sp > 1
    if device_warm:
        small = lower(comp, width=1)
        if _carry_sig(small.init_carry) != _carry_sig(big.init_carry):
            device_warm = False          # host fallback beats corruption
    lf = B // n_dp
    if device_warm:
        warm_take = warm_iters * small.take
        shard_items = per * big.ss.take
        n_hops = min(n_sp - 1, -(-warm_take // shard_items))
        base_sp = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[_fast_forward_carry(stages, big, advances,
                                  max(0, d * per - warm_iters))
              for d in range(n_sp)])                # (n_sp, ...)
        carries = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                x[None, None], (n_dp, lf) + x.shape),
            base_sp)                                # (dp, B/dp, sp, ...)
    else:
        carry_at = _entry_carry_fn(comp, big, stages, advances,
                                   warm_iters)
        # per-(frame, shard) entry carries; without memory stages every
        # frame's set is identical, but building B copies keeps ONE path
        per_frame = [
            jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[carry_at(d * per, batch[f]) for d in range(n_sp)])
            for f in range(B)]
        carries = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_frame)      # (B, n_sp, ...)
        carries = jax.tree_util.tree_map(
            lambda x: x.reshape((n_dp, lf, n_sp) + x.shape[2:]),
            carries)

    steps = per // big.width
    scan = big.scan_steps()
    # aligned bulk: (B, done*take, ...) -> (dp, B/dp, sp, steps, take, ..)
    bulk = batch[:, : done_iters * big.ss.take]
    shaped = bulk.reshape((n_dp, B // n_dp, n_sp, steps, big.take)
                          + batch.shape[2:])
    shaped = jnp.asarray(shaped)

    def shard_body(carry_stack, chunks):
        # chunks: (1, B/dp, 1, steps, take, ...) local block;
        # carry leaves: (1, B/dp, 1, ...) — one carry per local frame
        car_f = jax.tree_util.tree_map(lambda x: x[0, :, 0],
                                       carry_stack)
        loc = chunks[0, :, 0]                  # (B/dp, steps, take, ..)
        if device_warm:
            flat = loc.reshape((loc.shape[0], steps * big.take)
                               + loc.shape[3:])
            first = jnp.maximum(
                warm_iters - jax.lax.axis_index(sp_axis) * per, 0)

            def warm_one(b_carry, b_flat):
                # per-frame: the same gather + masked scan the
                # single-stream path runs (ppermute batches under vmap)
                wflat = _gather_warm_window(b_flat, sp_axis, n_sp,
                                            n_hops, warm_take)
                wchunks = wflat.reshape((warm_iters, small.take)
                                        + wflat.shape[1:])
                return _masked_warm_scan(small, b_carry, wchunks,
                                         first)

            car_f = jax.vmap(warm_one)(car_f, flat)

        def one_frame(fr, car):
            _, ys = scan(car, fr)
            return ys

        ys = jax.vmap(one_frame)(loc, car_f)
        return ys[None, :, None]

    cspec = P(dp_axis, None, sp_axis)
    dspec = P(dp_axis, None, sp_axis)
    run2 = jax.jit(shard_map(shard_body, mesh=mesh,
                             in_specs=(cspec, dspec),
                             out_specs=dspec))
    with mesh:
        ys = np.asarray(run2(carries, shaped))
    # (dp, B/dp, sp, steps, emit, ...) -> (B, sp*steps*emit, ...)
    ys = ys.reshape((B, n_sp * steps * big.emit) + ys.shape[5:])

    if done_iters < n_iters:
        # ragged tail: the iterations past the sp*width-aligned bulk
        # finish per frame on the host path, carry-seeded at the bulk
        # boundary — identical machinery to the single-stream tail
        from ziria_tpu.backend.execute import run_jit_carry
        carry_fn = _entry_carry_fn(comp, big, stages, advances,
                                   warm_iters)
        tails = []
        for f in range(B):
            rem = batch[f, done_iters * big.ss.take:
                        n_iters * big.ss.take]
            tc = carry_fn(done_iters, batch[f]) if stateful else None
            t, _ = run_jit_carry(comp, rem, carry=tc, width=width)
            tails.append(np.asarray(t))
        ys = np.concatenate([ys, np.stack(tails)], axis=1)
    return ys


def sliding_parallel(fn: Callable, xs, window: int, mesh: Mesh,
                     axis: str = "sp"):
    """Apply windowed `fn` to one long stream split across the mesh.

    `fn(block) -> outs` must map a contiguous block of M items to the
    M - window + 1 full-window results (e.g. a correlator: outs[i] =
    f(block[i : i+window])). Each device computes over its shard plus
    window-1 items of left halo from its neighbor — one `ppermute`
    hop over ICI, the sequence-parallel halo exchange.

    Returns the N - window + 1 results for the full stream. The stream
    length must divide evenly by the mesh size (pad upstream if not);
    shards must be at least window-1 items.
    """
    if window < 1:
        raise StreamParError("window must be >= 1")
    xs = jnp.asarray(xs)
    n_dev = mesh.shape[axis]
    n = xs.shape[0]
    if n % n_dev:
        raise StreamParError(
            f"stream length {n} does not divide over {n_dev} devices; "
            f"pad to a multiple first")
    shard = n // n_dev
    halo = window - 1
    if halo and shard < halo:
        raise StreamParError(
            f"shards of {shard} items are smaller than the "
            f"window-1 = {halo} halo")

    def body(local):
        # local: (shard, ...) — fetch the last `halo` items of the LEFT
        # neighbor (device i-1 sends to i); device 0 pads with zeros,
        # whose windows are dropped below
        if halo:
            tail = local[-halo:]
            perm = [(i, i + 1) for i in range(n_dev - 1)]
            recv = jax.lax.ppermute(tail, axis, perm)
            block = jnp.concatenate([recv, local], axis=0)
        else:
            block = local
        outs = fn(block)                      # (shard + halo) - halo
        want = shard
        if outs.shape[0] != want:
            raise StreamParError(
                f"fn returned {outs.shape[0]} results for a "
                f"{block.shape[0]}-item block; expected "
                f"block - window + 1 = {want}")
        return outs

    spec = P(axis, *([None] * (xs.ndim - 1)))
    # outputs may have a different rank than inputs (e.g. complex pairs
    # in, scalar metric out): shard only their leading axis
    run = jax.jit(shard_map(body, mesh=mesh, in_specs=spec,
                            out_specs=P(axis)))
    with mesh:
        ys = np.asarray(run(xs))
    # device 0's first `halo` outputs looked into the zero padding —
    # the stream's true full windows start at item 0
    return ys[halo:] if halo else ys
