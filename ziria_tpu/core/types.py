"""Stream-type checker: the computer/transformer discipline.

Counterpart of the reference's type system (SURVEY.md §0, §2.1 —
`TcComp.hs`/`TcUnify.hs`): every stream term is either a **computer**
``ST (C v) a b`` (consumes `a`s, produces `b`s, terminates with a control
value of type `v`) or a **transformer** ``ST T a b`` (runs forever), and
composition enforces:

- ``bind``/``seq`` sequences *computers* (a transformer never yields
  control, so binding it is a type error);
- ``c1 >>> c2`` requires the item types to agree and **at most one side
  to be a computer** — that side holds the control position; two
  computers racing to terminate is the classic Ziria type error;
- ``repeat c`` needs a computer body (re-run forever = a transformer);
- ``for``/``while`` bodies are computers; ``branch`` arms must have the
  same kind.

Item types are structural: opaque type variables unified across
composition (the expression layer is host Python over jnp arrays, so
checking dtypes statically would be fiction — what the reference's
unifier buys is exactly this wiring discipline, which is also what the
jit backend assumes when it fuses). `Map`-family nodes may carry
concrete item dtypes in the future; unification is written to absorb
that without surgery.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional, Union

from ziria_tpu.core import ir


class ZiriaTypeError(TypeError):
    """A stream-composition type error, with the offending node named."""


# --------------------------------------------------------------------------
# Item types: opaque variables with union-find unification
# --------------------------------------------------------------------------

_fresh = itertools.count()


class TVar:
    """An item-type variable (union-find node), optionally bound to a
    concrete item dtype (a numpy dtype name string declared by a
    Map-family node's in_dtype/out_dtype)."""

    __slots__ = ("id", "_parent", "dtype")

    def __init__(self, dtype: Optional[str] = None):
        self.id = next(_fresh)
        self._parent: Optional["TVar"] = None
        self.dtype = dtype

    def find(self) -> "TVar":
        t = self
        while t._parent is not None:
            t = t._parent
        # path compression
        u = self
        while u._parent is not None:
            u._parent, u = t, u._parent
        return t

    def __repr__(self):
        r = self.find()
        d = f":{r.dtype}" if r.dtype else ""
        return f"t{r.id}{d}"


def _dtype_class(name: str) -> str:
    """Coarse item-type class for conflict detection. Width changes
    between integer/float stages are legal implicit casts in this
    language (the evaluator casts at fun boundaries), so only the
    complex/real boundary — where silent numpy broadcasting corrupts
    data instead of casting it — is a hard conflict (the exact failure
    VERDICT r1 weak #6 cites: a bit producer feeding a complex
    consumer)."""
    import numpy as np
    return "complex" if np.dtype(name).kind == "c" else "real"


def unify(a: TVar, b: TVar) -> None:
    """Union two item-type variables; concretely-declared dtypes must
    be of the same class (the TcUnify scalar case — VERDICT r1 weak
    #6)."""
    ra, rb = a.find(), b.find()
    if ra is rb:
        return
    if ra.dtype is not None and rb.dtype is not None \
            and _dtype_class(ra.dtype) != _dtype_class(rb.dtype):
        # site-neutral message: the caller (Pipe/Branch/Bind) adds the
        # composition context — unify itself cannot know which side
        # produces and which consumes
        raise ZiriaTypeError(
            f"stream item dtype mismatch: {ra.dtype!r} vs {rb.dtype!r}")
    if rb.dtype is None:
        rb.dtype = ra.dtype
    ra._parent = rb


# --------------------------------------------------------------------------
# Stream types
# --------------------------------------------------------------------------


@dataclass
class CTy:
    """Computer: ST (C v) a b. `v` is opaque (host value)."""

    a: TVar
    b: TVar

    def kind(self) -> str:
        return "computer"

    def __repr__(self):
        return f"ST (C _) {self.a!r} {self.b!r}"


@dataclass
class TTy:
    """Transformer: ST T a b."""

    a: TVar
    b: TVar

    def kind(self) -> str:
        return "transformer"

    def __repr__(self):
        return f"ST T {self.a!r} {self.b!r}"


SType = Union[CTy, TTy]


def _err(node: ir.Comp, msg: str) -> ZiriaTypeError:
    return ZiriaTypeError(f"{node.label()}: {msg}")


# --------------------------------------------------------------------------
# The checker
# --------------------------------------------------------------------------


def typecheck(comp: ir.Comp) -> SType:
    """Infer the stream type of `comp`, raising ZiriaTypeError on a
    composition-discipline violation. Returns CTy or TTy with unified
    item-type variables (compare identity via .find())."""

    if isinstance(comp, (ir.Take, ir.Takes)):
        return CTy(TVar(), TVar())
    if isinstance(comp, (ir.Emit, ir.Emits)):
        return CTy(TVar(), TVar())
    if isinstance(comp, (ir.Return, ir.Assign)):
        return CTy(TVar(), TVar())

    if isinstance(comp, ir.Bind):
        t1 = typecheck(comp.first)
        if not isinstance(t1, CTy):
            raise _err(
                comp, "bind/seq sequences computers, but the first "
                "component is a transformer (it never terminates, so "
                "there is no control value to bind); wrap a finite "
                "prefix with take/for instead")
        t2 = typecheck(comp.rest)
        try:
            unify(t1.a, t2.a)
            unify(t1.b, t2.b)
        except ZiriaTypeError as e:
            raise _err(comp, f"{e} (both halves of a bind read/write "
                             f"the same streams)") from None
        return type(t2)(t2.a, t2.b)

    if isinstance(comp, ir.LetRef):
        return typecheck(comp.body)

    if isinstance(comp, (ir.Map, ir.MapAccum, ir.JaxBlock)):
        return TTy(TVar(getattr(comp, "in_dtype", None)),
                   TVar(getattr(comp, "out_dtype", None)))

    if isinstance(comp, ir.Repeat):
        t = typecheck(comp.body)
        if not isinstance(t, CTy):
            raise _err(
                comp, "repeat needs a computer body (a transformer "
                "already runs forever — repeating it is meaningless)")
        return TTy(t.a, t.b)

    if isinstance(comp, ir.For):
        t = typecheck(comp.body)
        if not isinstance(t, CTy):
            raise _err(comp, "for-loop body must be a computer (each "
                             "iteration must terminate)")
        return CTy(t.a, t.b)

    if isinstance(comp, ir.While):
        t = typecheck(comp.body)
        if not isinstance(t, CTy):
            raise _err(comp, "while-loop body must be a computer (each "
                             "iteration must terminate)")
        return CTy(t.a, t.b)

    if isinstance(comp, ir.Branch):
        t1, t2 = typecheck(comp.then), typecheck(comp.els)
        if t1.kind() != t2.kind():
            raise _err(
                comp, f"branch arms disagree: then-arm is a {t1.kind()}, "
                f"else-arm is a {t2.kind()}")
        try:
            unify(t1.a, t2.a)
            unify(t1.b, t2.b)
        except ZiriaTypeError as e:
            raise _err(comp, f"{e} (branch arms must stream the same "
                             f"item types)") from None
        return type(t1)(t1.a, t1.b)

    if isinstance(comp, (ir.Pipe, ir.ParPipe)):
        t1, t2 = typecheck(comp.up), typecheck(comp.down)
        try:
            unify(t1.b, t2.a)  # up's output items feed down's input
        except ZiriaTypeError as e:
            raise _err(comp, f"{e} (upstream output feeding downstream "
                             f"input)") from None
        if isinstance(t1, CTy) and isinstance(t2, CTy):
            raise _err(
                comp, "both sides of >>> are computers; at most one side "
                "may hold the control position (the reference's TcComp "
                "rule) — make one side `repeat`ed or restructure with "
                "bind")
        if isinstance(t1, CTy) or isinstance(t2, CTy):
            return CTy(t1.a, t2.b)
        return TTy(t1.a, t2.b)

    raise _err(comp, f"unknown IR node {type(comp).__name__}")
