"""AutoLUT: compile small-domain pure maps into lookup tables.

Counterpart of the reference's AutoLUT pass (SURVEY.md §2.1,
`AutoLUT.hs`/`LUTAnalysis.hs`/`CgLUT.hs`): it analyzes pure expression
functions whose inputs have small bit-width and synthesizes compile-time
lookup tables. TPU-native redesign: the "analysis" is a *declared*
domain (`zmap(f, in_domain=256)` — the role the reference's `int8`-style
types play), and "table synthesis" is one vmapped evaluation of `f` over
``arange(domain)`` at pass time; the rewritten map is a gather
``table[x]``, which XLA lowers to a fast dynamic-gather (tiny tables
live comfortably in VMEM and the gather vectorizes across the planner's
batch axis).

When a LUT map sits next to other maps, the fold pass's map-map fusion
(core/opt.py) composes the gather with its neighbors, so
``autolut(fold(p))`` or ``fold(autolut(p))`` both end in fused stages.
"""

from __future__ import annotations

from typing import Optional

from ziria_tpu.core import ir

# NOTE: jax is imported inside the functions below so that
# `import ziria_tpu` (which re-exports `autolut`) stays cheap — the
# package's core IR layer deliberately avoids jax at import time.


class LutError(ValueError):
    pass


MAX_TABLE_ITEMS = 1 << 22  # refuse absurd tables (16 MB of f32)


def build_table(m: ir.Map):
    """Evaluate m.f over its whole declared domain: (domain, *out_item)."""
    import jax
    import jax.numpy as jnp

    if m.in_domain is None:
        raise LutError(f"map {m.label()} has no declared in_domain")
    if m.in_arity != 1:
        raise LutError(
            f"map {m.label()}: AutoLUT needs scalar input items "
            f"(in_arity == 1); got in_arity={m.in_arity}")
    dom = int(m.in_domain)
    if dom <= 0:
        raise LutError(f"map {m.label()}: in_domain must be positive")
    if dom > MAX_TABLE_ITEMS:
        # table.size >= dom always, so refuse before evaluating anything
        raise LutError(
            f"map {m.label()}: domain {dom} exceeds the "
            f"{MAX_TABLE_ITEMS}-item cap; narrow the domain")
    table = jax.vmap(m.f)(jnp.arange(dom))
    if table.size > MAX_TABLE_ITEMS:
        raise LutError(
            f"map {m.label()}: table of {table.size} items exceeds the "
            f"{MAX_TABLE_ITEMS}-item cap; narrow the domain")
    return table


def lut_map(m: ir.Map) -> ir.Map:
    """Rewrite one LUT-able Map into a table gather: either a declared
    scalar in_domain, or an inferred packed-bits adapter
    (`m.lut`, frontend/lutinfer.MapLut — the LUTAnalysis role)."""
    import jax
    import jax.numpy as jnp

    if m.lut is not None:
        # the adapter's build enforces the item cap upfront (lutinfer.
        # build_fun_table via eval_shape) and memoizes per function on
        # the program Ctx; an oversize table — or a body that cannot be
        # evaluated over its domain at all (unstageable + too big for
        # the concrete fallback) — means "leave un-LUT'd", matching the
        # expression-call path's fallback and the no-flag behavior
        from ziria_tpu.frontend.eval import ZiriaRuntimeError
        from ziria_tpu.frontend.lutinfer import TableTooLarge
        try:
            table = m.lut.build_table()
        except (TableTooLarge, ZiriaRuntimeError):
            return m

        enc = m.lut.encoder()      # closes over the spec only, not the
                                   # FunDef/Ctx the adapter carries

        def gather(x, _t=table, _enc=enc):
            idx = _enc(x)
            return jax.tree_util.tree_map(lambda t: t[idx], _t)

        return ir.Map(gather, in_arity=m.in_arity, out_arity=m.out_arity,
                      name=f"lut[{m.label()}]")

    table = build_table(m)

    def gather(x, _t=table):
        return _t[jnp.asarray(x, jnp.int32)]

    return ir.Map(gather, in_arity=1, out_arity=m.out_arity,
                  name=f"lut[{m.label()}]")


def autolut(comp: ir.Comp) -> ir.Comp:
    """Rewrite every Map with a declared in_domain (or an inferred
    lutinfer adapter) into its LUT form. Structure-preserving everywhere
    else; semantics identical (tested against the un-LUT'd program on
    both backends)."""
    def walk(c: ir.Comp) -> ir.Comp:
        if isinstance(c, ir.Map) and (c.in_domain is not None
                                      or c.lut is not None):
            return lut_map(c)
        return ir.map_children(c, lambda ch, _binds: walk(ch))

    return walk(comp)
