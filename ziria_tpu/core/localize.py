"""State localization: LetRef⁺(Repeat body) → MapAccum.

The reference's C codegen moves every component-local `var` into the
global state struct its tick/process functions thread through
(SURVEY.md §2.1 CgMonad "global state struct"). The TPU-first analogue:
a stateful repeat written with mutable refs

    LetRef v1 ... LetRef vk (Repeat body)

becomes an explicit-state ``MapAccum`` whose carry is the tuple of ref
values — the shape `lax.scan` wants — so parsed/handwritten stateful
blocks reach the fused jit path instead of being interpreter-only.

The firing function reuses the streaming interpreter with ``xp=jnp``
(exactly like backend/lower.firing_fn): the oracle and the compiler
share one semantics. Conditions for the rewrite:

- the body has static cardinality (take ≥ 1, emit ≥ 1);
- the ref initializers evaluate without any enclosing runtime
  environment (checked by just trying);
- the chain is not under an enclosing binder that could be captured by
  body closures (same conservative scoping rule as opt.py's R3).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from ziria_tpu.core import ir
from ziria_tpu.core.card import CCard, cardinality
from ziria_tpu.core.ir import Env, eval_expr


def _try_localize(c: ir.Comp) -> Optional[ir.Comp]:
    names: List[str] = []
    inits: List[Any] = []
    node = c
    while isinstance(node, ir.LetRef):
        names.append(node.var)
        inits.append(node.init)
        node = node.body
    if not names or not isinstance(node, ir.Repeat):
        return None
    body = node.body
    card = cardinality(body)
    if not isinstance(card, CCard) or card.take < 1 or card.emit < 1:
        return None

    # initializers must be closed (no enclosing runtime env): evaluate in
    # an Env seeded only with earlier refs of this same chain
    try:
        env0 = Env()
        vals = []
        for n, e in zip(names, inits):
            v = eval_expr(e, env0)
            env0.bind_ref(n, v)
            vals.append(v)
    except Exception:
        return None

    import jax.numpy as jnp
    from ziria_tpu.interp.interp import _run

    init_state = tuple(jnp.asarray(v) for v in vals)
    n_take, n_emit = card.take, card.emit
    _names = tuple(names)

    def f(state, chunk, _body=body, _names=_names,
          _n_take=n_take, _n_emit=n_emit):
        env = Env()
        for n, v in zip(_names, state):
            env.bind_ref(n, v)
        idx = [0]

        def src():
            x = chunk if _n_take == 1 else chunk[idx[0]]
            idx[0] += 1
            return x

        outs = []
        gen = _run(_body, env, src, xp=jnp)
        try:
            while True:
                outs.append(next(gen))
        except StopIteration:
            pass
        new_state = tuple(jnp.asarray(env.lookup(n)) for n in _names)
        if _n_emit == 1:
            return new_state, jnp.asarray(outs[0])
        return new_state, jnp.stack([jnp.asarray(o) for o in outs])

    label = "state[" + ",".join(names) + "]"
    return ir.MapAccum(f, init_state, in_arity=n_take, out_arity=n_emit,
                       name=label)


def localize(comp: ir.Comp) -> ir.Comp:
    """Rewrite every unscoped LetRef⁺(Repeat) chain into a MapAccum."""

    def walk(c: ir.Comp, scoped: bool = False) -> ir.Comp:
        if not scoped and isinstance(c, ir.LetRef):
            r = _try_localize(c)
            if r is not None:
                return r
        return ir.map_children(c, lambda ch, binds: walk(ch, scoped or binds))

    return walk(comp)
