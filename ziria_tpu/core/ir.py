"""IR for the two-level stream-computation language.

The reference language (SURVEY.md §0) has an *expression* level (first-order
imperative code over scalars/arrays) and a *stream computation* level whose
terms are either **computers** — consume/produce stream items and terminate
with a control value — or **transformers** — run forever. This module is the
stream level. The expression level is ordinary Python functions over
numpy/jnp arrays, closed over an environment of bound control values
(`Bind`) and mutable refs (`LetRef`).

Design notes (TPU-first, deliberately NOT a port of the reference's
Haskell AST):

- Components carry *explicit* state (``map_accum``) instead of ambient
  mutable globals, so every static-rate pipeline segment lowers to a pure
  ``(state, in_chunk) -> (state, out_chunk)`` function — exactly the shape
  ``jax.lax.scan`` and ``jax.jit`` want.
- Cardinality analysis (core/card.py) computes synchronous-dataflow rates.
  Where the reference *rewrites* the AST to vectorize (its `Vectorize.hs`
  pass), we *plan*: rates become reshape/vmap axes at lowering time
  (backend/lower.py), and the chosen batching width is a planner knob, not
  a program transformation.
- Expressions take the environment as an argument (`lambda env: ...`) so
  the IR stays first-order and analyzable; no higher-order continuation
  tricks that would block cardinality analysis.

Combinator surface (reference counterparts in parens):

    take / takes(n)            (take / takes n)
    emit1(e) / emits(e, n)     (emit / emits)
    ret(e)                     (return e)
    seq(c1, c2, ...)           (c1 ; c2 ; ...)
    let(name, c1, c2)          (name <- c1 ; c2)
    zmap(f)                    (map f)
    map_accum(f, init)         (stateful map: var st; repeat { x<-take; ... })
    repeat(c)                  (repeat c)
    a >> b  == pipe(a, b)      (a >>> b)
    par_pipe(a, b)             (a |>>>| b) — placement hint: stage boundary
    for_loop(n, body)          (times / for)
    while_loop(cond, body)     (while)
    branch(cond, t, f)         (if/then/else)
    jax_block(fn, ...)         escape hatch: chunk-level jax function
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# Environments: bindings from `let` plus mutable refs from `let_ref`.
# --------------------------------------------------------------------------


class Env:
    """Lexically scoped environment. `bind` makes immutable bindings (from
    monadic `let`); `bind_ref` makes mutable cells (from `let_ref`). Only
    refs are assignable — `Assign` to a let-binding is an error, so a
    typo'd assignment can never silently corrupt a bound value."""

    __slots__ = ("_vars", "_refs", "_parent")

    def __init__(self, parent: Optional["Env"] = None):
        self._vars = {}
        self._refs = {}
        self._parent = parent

    def child(self) -> "Env":
        return Env(self)

    def bind(self, name: str, value: Any) -> None:
        self._vars[name] = value

    def bind_ref(self, name: str, value: Any) -> None:
        self._refs[name] = value

    def lookup(self, name: str) -> Any:
        e = self
        while e is not None:
            if name in e._vars:
                return e._vars[name]
            if name in e._refs:
                return e._refs[name]
            e = e._parent
        raise KeyError(f"unbound variable {name!r}")

    def __getitem__(self, name: str) -> Any:
        return self.lookup(name)

    def set(self, name: str, value: Any) -> None:
        """Assign to an existing ref (let_ref) binding, innermost first."""
        e = self
        while e is not None:
            if name in e._refs:
                e._refs[name] = value
                return
            if name in e._vars:
                raise KeyError(
                    f"assignment to immutable let-binding {name!r} "
                    f"(use let_ref for mutable state)")
            e = e._parent
        raise KeyError(f"assignment to unbound variable {name!r}")


# Expression: a Python callable from Env to a value. Plain (non-callable)
# values are accepted anywhere an expression is and treated as constants.
Expr = Any


def eval_expr(expr: Expr, env: Env) -> Any:
    return expr(env) if callable(expr) else expr


# --------------------------------------------------------------------------
# IR nodes
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Comp:
    """Base class for stream computations."""

    def __rshift__(self, other: "Comp") -> "Comp":
        return Pipe(self, other)

    def label(self) -> str:
        return type(self).__name__


@dataclass(frozen=True)
class Take(Comp):
    """Computer: consume one item; terminates with that item as value."""


@dataclass(frozen=True)
class Takes(Comp):
    """Computer: consume `n` items; value is the length-n array of them."""

    n: int


@dataclass(frozen=True)
class Emit(Comp):
    """Computer: emit one item (the value of `expr`); value is None."""

    expr: Expr


@dataclass(frozen=True)
class Emits(Comp):
    """Computer: emit the `n` elements of array-valued `expr`; value None.

    `n` must be static — it feeds cardinality analysis the same way the
    reference's cardinality pass needs static take/emit multiplicities.
    """

    expr: Expr
    n: int


@dataclass(frozen=True)
class Return(Comp):
    """Computer: no stream I/O; terminates immediately with `expr`'s value."""

    expr: Expr


@dataclass(frozen=True)
class Bind(Comp):
    """Computer: run `first`, bind its value to `var`, then run `rest`."""

    first: Comp
    var: Optional[str]
    rest: Comp


@dataclass(frozen=True)
class LetRef(Comp):
    """Computer: introduce a mutable ref `var` (initial `init`) around `body`.

    Counterpart of the reference's local `var` declarations. The jit backend
    only supports refs that are threaded through `map_accum` state; LetRef
    is interpreter-general.
    """

    var: str
    init: Expr
    body: Comp


@dataclass(frozen=True)
class Assign(Comp):
    """Computer: env[var] := expr; value None."""

    var: str
    expr: Expr


@dataclass(frozen=True)
class Map(Comp):
    """Transformer: apply `f` to each input chunk of `in_arity` items,
    producing a chunk of `out_arity` items.

    in_arity == 1 means scalar items (f: item -> item); in_arity > 1 means
    f takes an array of shape (in_arity, ...) — this is how already-
    vectorized blocks (e.g. a 64-point FFT) appear, and the unit the
    backend's planner multiplies into batch axes.

    `in_domain`, if set, declares that input items are integers in
    [0, in_domain) — the analogue of the reference's small-bit-width
    types that drive AutoLUT (core/autolut.py turns such maps into
    table gathers).

    `in_dtype`/`out_dtype`, if set (numpy dtype names, e.g. "uint8",
    "complex64"), declare the item dtypes this stage consumes/produces;
    the stream typechecker (core/types.py) propagates them across `>>>`
    and rejects mismatched compositions — the item-type half of the
    reference's TcUnify that round 1 left opaque (VERDICT r1 weak #6).

    `lut`, if set, is an inferred-LUT adapter (frontend/lutinfer.MapLut,
    the reference's LUTAnalysis role): it generalizes `in_domain` to
    packed multi-bit items (e.g. `arr[8] bit`), providing `.domain`,
    `.build_table()` and `.encode(item) -> index` for core/autolut.py.
    """

    f: Callable[..., Any]
    in_arity: int = 1
    out_arity: int = 1
    name: Optional[str] = None
    in_domain: Optional[int] = None
    in_dtype: Optional[str] = None
    out_dtype: Optional[str] = None
    lut: Optional[Any] = field(default=None, compare=False)

    def label(self) -> str:
        return self.name or getattr(self.f, "__name__", "Map")


@dataclass(frozen=True)
class MapAccum(Comp):
    """Stateful transformer: f(state, chunk) -> (state, out_chunk).

    The workhorse for DSP blocks with carried state (scramblers, FIR delay
    lines, phase trackers). Lowers to `jax.lax.scan` over chunks.
    `init` produces the initial state (callable taking no args, or value).

    `advance`, if set, is ``advance(state, n) -> state`` — the state
    after `n` firings with ANY inputs, for stages whose state evolves
    independently of the data (LFSR scramblers: M^n·s over GF(2); CFO
    phase accumulators: ph + n·eps). It lets stream/sequence
    parallelism (parallel/streampar.py) fast-forward each device's
    entry state instead of refusing the stage as sequential.

    `memory`, if set, declares FINITE input memory: the state after
    processing any >= `memory` input items is independent of what came
    before them (FIR delay lines: memory = taps-1; sliding windows:
    the window length). Stream parallelism then seeds each device's
    entry state with a short warmup scan over the `memory` items
    preceding its shard — exact, no approximation.
    """

    f: Callable[..., Any]
    init: Any
    in_arity: int = 1
    out_arity: int = 1
    name: Optional[str] = None
    in_dtype: Optional[str] = None
    out_dtype: Optional[str] = None
    advance: Optional[Callable[[Any, int], Any]] = field(
        default=None, compare=False)
    memory: Optional[int] = field(default=None, compare=False)

    def label(self) -> str:
        return self.name or getattr(self.f, "__name__", "MapAccum")

    def init_state(self):
        return self.init() if callable(self.init) else self.init


@dataclass(frozen=True)
class Repeat(Comp):
    """Transformer: run computer `body` over and over forever."""

    body: Comp


@dataclass(frozen=True)
class Pipe(Comp):
    """`up >>> down`: up's output stream feeds down's input stream.

    Terminates (with the terminator's value) as soon as either side does.
    """

    up: Comp
    down: Comp


@dataclass(frozen=True)
class ParPipe(Comp):
    """`up |>>>| down`: semantically identical to Pipe, but a *placement*
    directive — the reference spawns a thread per side with an SPSC queue
    between (SURVEY.md §3.3); our backend treats it as a stage boundary for
    sharding across devices (ppermute over ICI) instead of fusing.
    """

    up: Comp
    down: Comp


@dataclass(frozen=True)
class For(Comp):
    """Computer: run `body` `count` times; loop index bound to `var`.

    `count` may be an Expr (dynamic in the interpreter); static ints keep
    the node jit-lowerable.
    """

    var: Optional[str]
    count: Expr
    body: Comp


@dataclass(frozen=True)
class While(Comp):
    """Computer: run `body` while `cond` holds. Dynamic — interpreter (and
    frame-level jit patterns via masking), never inside fused static
    segments."""

    cond: Expr
    body: Comp


@dataclass(frozen=True)
class Branch(Comp):
    """Computer/transformer: if cond then a else b."""

    cond: Expr
    then: Comp
    els: Comp


@dataclass(frozen=True)
class JaxBlock(Comp):
    """Escape hatch transformer: an arbitrary chunk-level jax function.

    f(state, chunk[(in_arity,...)]) -> (state, out_chunk[(out_arity,...)]).
    Used for blocks whose inner structure isn't worth expressing in the IR
    (e.g. a whole Pallas kernel). Equivalent role to the reference's `ext`
    C functions bound from SORA (SURVEY.md §2.2).
    """

    f: Callable[..., Any]
    init: Any
    in_arity: int
    out_arity: int
    name: Optional[str] = None

    def label(self) -> str:
        return self.name or getattr(self.f, "__name__", "JaxBlock")

    def init_state(self):
        return self.init() if callable(self.init) else self.init


# --------------------------------------------------------------------------
# Smart constructors / user surface
# --------------------------------------------------------------------------

take = Take()


def takes(n: int) -> Comp:
    if n <= 0:
        raise ValueError("takes(n) needs n >= 1")
    return Takes(n)


def emit1(expr: Expr) -> Comp:
    return Emit(expr)


# `emit` kept as an alias for the single-item form, matching reference syntax.
emit = emit1


def emits(expr: Expr, n: int) -> Comp:
    return Emits(expr, n)


def ret(expr: Expr) -> Comp:
    return Return(expr)


def seq(*comps: Comp) -> Comp:
    """c1 ; c2 ; ... — sequencing discarding intermediate values."""
    if not comps:
        raise ValueError("seq needs at least one computation")
    out = comps[-1]
    for c in reversed(comps[:-1]):
        out = Bind(c, None, out)
    return out


def let(var: str, first: Comp, rest: Comp) -> Comp:
    """var <- first ; rest"""
    return Bind(first, var, rest)


def let_ref(var: str, init: Expr, body: Comp) -> Comp:
    return LetRef(var, init, body)


def assign(var: str, expr: Expr) -> Comp:
    return Assign(var, expr)


def zmap(f: Callable, in_arity: int = 1, out_arity: int = 1,
         name: Optional[str] = None, in_domain: Optional[int] = None,
         in_dtype: Optional[str] = None,
         out_dtype: Optional[str] = None) -> Comp:
    return Map(f, in_arity, out_arity, name, in_domain, in_dtype,
               out_dtype)


def map_accum(f: Callable, init: Any, in_arity: int = 1, out_arity: int = 1,
              name: Optional[str] = None, in_dtype: Optional[str] = None,
              out_dtype: Optional[str] = None,
              advance: Optional[Callable] = None,
              memory: Optional[int] = None) -> Comp:
    if memory is not None and (int(memory) != memory or int(memory) < 1):
        # validate at construction so every consumer (fold's rescale,
        # widening, stream_parallel's warmup budget) sees a sane value
        raise ValueError(f"map_accum {name or f!r}: memory={memory!r} "
                         f"must be a positive integer (items of input "
                         f"history)")
    return MapAccum(f, init, in_arity, out_arity, name, in_dtype,
                    out_dtype, advance, memory)


def repeat(body: Comp) -> Comp:
    return Repeat(body)


def pipe(*comps: Comp) -> Comp:
    if not comps:
        raise ValueError("pipe needs at least one computation")
    out = comps[0]
    for c in comps[1:]:
        out = Pipe(out, c)
    return out


def par_pipe(*comps: Comp) -> Comp:
    if not comps:
        raise ValueError("par_pipe needs at least one computation")
    out = comps[0]
    for c in comps[1:]:
        out = ParPipe(out, c)
    return out


def for_loop(count: Expr, body: Comp, var: Optional[str] = None) -> Comp:
    return For(var, count, body)


def while_loop(cond: Expr, body: Comp) -> Comp:
    return While(cond, body)


def branch(cond: Expr, then: Comp, els: Comp) -> Comp:
    return Branch(cond, then, els)


def jax_block(f: Callable, init: Any = None, in_arity: int = 1,
              out_arity: int = 1, name: Optional[str] = None) -> Comp:
    return JaxBlock(f, init, in_arity, out_arity, name)


# --------------------------------------------------------------------------
# Structural helpers
# --------------------------------------------------------------------------


def map_children(c: Comp, f: Callable[[Comp, bool], Comp]) -> Comp:
    """Rebuild `c` with `f` applied to each direct child computation.

    `f(child, binds)` — `binds` is True when the construct introduces a
    binding visible inside that child (Bind's rest under a named var,
    LetRef's body, For's body under a loop var). Returns `c` itself when
    no child changed, so rewrite passes can detect fixpoints by
    identity. The single structural walker shared by the fold pass and
    AutoLUT — add new container nodes HERE, once.
    """
    if isinstance(c, Bind):
        a = f(c.first, False)
        b = f(c.rest, c.var is not None)
        return c if a is c.first and b is c.rest else Bind(a, c.var, b)
    if isinstance(c, LetRef):
        b = f(c.body, True)
        return c if b is c.body else LetRef(c.var, c.init, b)
    if isinstance(c, Repeat):
        b = f(c.body, False)
        return c if b is c.body else Repeat(b)
    if isinstance(c, Pipe):
        a, b = f(c.up, False), f(c.down, False)
        return c if a is c.up and b is c.down else Pipe(a, b)
    if isinstance(c, ParPipe):
        a, b = f(c.up, False), f(c.down, False)
        return c if a is c.up and b is c.down else ParPipe(a, b)
    if isinstance(c, For):
        b = f(c.body, c.var is not None)
        return c if b is c.body else For(c.var, c.count, b)
    if isinstance(c, While):
        b = f(c.body, False)
        return c if b is c.body else While(c.cond, b)
    if isinstance(c, Branch):
        a, b = f(c.then, False), f(c.els, False)
        return c if a is c.then and b is c.els else Branch(c.cond, a, b)
    return c


def pipeline_stages(comp: Comp) -> Sequence[Comp]:
    """Flatten nested Pipe into a left-to-right stage list (Pipe only —
    ParPipe boundaries are preserved as units; see parallel/stages.py)."""
    if isinstance(comp, Pipe):
        return list(pipeline_stages(comp.up)) + list(pipeline_stages(comp.down))
    return [comp]


def par_segments(comp: Comp) -> Sequence[Comp]:
    """Split at ParPipe boundaries into the reference's thread-stage units."""
    if isinstance(comp, ParPipe):
        return list(par_segments(comp.up)) + list(par_segments(comp.down))
    return [comp]
