"""Cardinality analysis: static take/emit multiplicities.

Counterpart of the reference's cardinality pass (SURVEY.md §2.1,
`CardAnalysis.hs` — the prerequisite for its vectorizer). Re-designed as a
synchronous-dataflow (SDF) rate analysis, because that is the form the TPU
backend consumes: a transformer with rate ``i -> o`` firing ``r`` times per
steady-state iteration becomes a reshape to ``(r, i, ...)`` plus a
``vmap``/``scan`` at lowering time.

Results:

- computers get a total ``CCard(take, emit)`` over their whole run;
- transformers get a per-firing ``TCard(i, o)`` rate;
- anything data-dependent is ``DYN`` (interpreter-only, or handled by
  frame-level patterns in phy/).
"""

from __future__ import annotations

from dataclasses import dataclass
from math import gcd
from typing import Optional, Union

from ziria_tpu.core import ir


@dataclass(frozen=True)
class CCard:
    """Computer cardinality: total items taken/emitted before termination."""

    take: int
    emit: int


@dataclass(frozen=True)
class TCard:
    """Transformer cardinality: items taken/emitted per firing."""

    i: int
    o: int


@dataclass(frozen=True)
class Dyn:
    """Unknown / data-dependent cardinality."""


DYN = Dyn()
Card = Union[CCard, TCard, Dyn]


def _lcm(a: int, b: int) -> int:
    return a * b // gcd(a, b)


def cardinality(comp: ir.Comp) -> Card:
    """Compute the cardinality of `comp`. Never raises on dynamic
    structure — returns DYN instead, mirroring how the reference's
    vectorizer simply skips segments without static cardinalities."""
    if isinstance(comp, ir.Take):
        return CCard(1, 0)
    if isinstance(comp, ir.Takes):
        return CCard(comp.n, 0)
    if isinstance(comp, ir.Emit):
        return CCard(0, 1)
    if isinstance(comp, ir.Emits):
        return CCard(0, comp.n)
    if isinstance(comp, (ir.Return, ir.Assign)):
        return CCard(0, 0)
    if isinstance(comp, ir.Bind):
        a, b = cardinality(comp.first), cardinality(comp.rest)
        if isinstance(a, CCard) and isinstance(b, CCard):
            return CCard(a.take + b.take, a.emit + b.emit)
        return DYN
    if isinstance(comp, ir.LetRef):
        return cardinality(comp.body)
    if isinstance(comp, (ir.Map, ir.MapAccum, ir.JaxBlock)):
        return TCard(comp.in_arity, comp.out_arity)
    if isinstance(comp, ir.Repeat):
        b = cardinality(comp.body)
        if isinstance(b, CCard):
            if b.take == 0 and b.emit == 0:
                return DYN  # repeat of pure computer: no steady-state rate
            return TCard(b.take, b.emit)
        return DYN
    if isinstance(comp, ir.For):
        if not isinstance(comp.count, int):
            return DYN
        b = cardinality(comp.body)
        if isinstance(b, CCard):
            return CCard(b.take * comp.count, b.emit * comp.count)
        return DYN
    if isinstance(comp, ir.While):
        return DYN
    if isinstance(comp, ir.Branch):
        a, b = cardinality(comp.then), cardinality(comp.els)
        return a if a == b else DYN
    if isinstance(comp, (ir.Pipe, ir.ParPipe)):
        return _pipe_card(cardinality(comp.up), cardinality(comp.down))
    return DYN


def _pipe_card(a: Card, b: Card) -> Card:
    # transformer >>> transformer: steady-state SDF composition
    if isinstance(a, TCard) and isinstance(b, TCard):
        l = _lcm(a.o, b.i) if a.o and b.i else 0
        if l == 0:
            return DYN
        ra, rb = l // a.o, l // b.i
        return TCard(ra * a.i, rb * b.o)
    # computer upstream of a transformer: the composite is a computer that
    # terminates when the upstream does; totals only line up when upstream
    # emission count is a multiple of the transformer's input rate.
    if isinstance(a, CCard) and isinstance(b, TCard):
        if b.i and a.emit % b.i == 0:
            return CCard(a.take, (a.emit // b.i) * b.o)
        return DYN
    if isinstance(a, TCard) and isinstance(b, CCard):
        if a.o and b.take % a.o == 0:
            return CCard((b.take // a.o) * a.i, b.emit)
        return DYN
    return DYN


@dataclass(frozen=True)
class SteadyState:
    """Steady-state firing plan for a flattened transformer pipeline:
    stage k fires reps[k] times per iteration; the iteration consumes
    `take` input items and produces `emit` output items."""

    reps: tuple
    take: int
    emit: int


def steady_state(stages) -> Optional[SteadyState]:
    """Compute the SDF repetition vector for a list of transformer stages.

    Returns None if any stage lacks a static transformer rate. This plan is
    what the jit backend fuses into a single step function: the reference's
    vectorizer searched (in,out)-width scale factors per segment
    (SURVEY.md §2.1 `VecSF.hs`); here the widths fall out of the repetition
    vector and the planner's chosen outer batching factor.
    """
    stages = list(stages)
    if not stages:
        return None
    cards = [cardinality(s) for s in stages]
    if not all(isinstance(c, TCard) for c in cards):
        return None
    # A zero rate on an interior edge (a sink mid-chain, or a pure source
    # downstream of anything) has no steady state — not plannable.
    for k, c in enumerate(cards):
        if k < len(cards) - 1 and c.o == 0:
            return None
        if k > 0 and c.i == 0:
            return None
    reps = [1] * len(stages)
    for k in range(len(stages) - 1):
        prod = cards[k].o * reps[k]
        need = cards[k + 1].i
        l = _lcm(prod, need)
        scale_up = l // prod
        if scale_up != 1:
            for j in range(k + 1):
                reps[j] *= scale_up
            prod = l
        reps[k + 1] = prod // need
    return SteadyState(tuple(reps), reps[0] * cards[0].i,
                       reps[-1] * cards[-1].o)
