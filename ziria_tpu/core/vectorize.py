"""Vectorizer: scale-factor search, utility model, widening, mitigators.

Counterpart of the reference's headline optimization (SURVEY.md §2.1:
`Vectorize.hs` / `VecM.hs` / `VecSF.hs`) — there, a search over per-
component (in-width, out-width) scale factors, scored by a utility
function, rewriting `take -> takes` / `emit -> emits` and inserting
reshaping "mitigators" between mismatched widths, so the generated C
loop body is fat enough for SSE and per-item overhead is amortized.

TPU-first re-design. The knobs and their hardware meaning change:

- The SDF steady state (core/card.py) already ties the per-stage firing
  counts together via the repetition vector, so the *free* scale factor
  is ``W`` — how many steady-state iterations one fused jit step
  processes. Widths are then ``reps[k] * W`` firings per stage.
- The utility model scores W against the TPU cost structure instead of
  SSE lane width: per-step dispatch/loop overhead amortization, VPU
  lane fill (a stateless stage's firings run as one vmapped batch —
  widening is ~free until the 8x128 lanes saturate), sequential scan
  cost of stateful stages (widening buys no parallelism there), and a
  VMEM footprint cap on the live chunk.
- Widening is available BOTH as planning (pass ``W`` to
  ``backend.lower`` — no AST change) and as an explicit rewrite
  (``widen``): the take->takes analogue, where the stream item type
  changes from ``T`` to "array of w T" and every stage is rewritten to
  consume/emit blocks. ``mitigator(w_in, w_out)`` is the reshape node
  placed between stages widened by different factors.
- Pipelines with dynamic-rate stages in the middle are split into
  maximal static segments (the reference's vectorizer likewise skips
  components without static cardinalities); `backend.execute.run_vect`
  runs static segments fused under jit and bridges dynamic segments
  through the interpreter.

`VectPlan.dump()` is the ``--ddump-vect`` analogue: the scored
candidate table per segment.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from math import gcd
from typing import Dict, List, Optional, Sequence, Tuple

from ziria_tpu.core import ir
from ziria_tpu.core.card import SteadyState, TCard, cardinality, steady_state

# Model constants (relative "item-equivalents", not seconds). See the
# utility() docstring for how they enter the score. These two module
# globals are the TPU *architectural estimates*; the platform-keyed
# table below carries measured fits where calibration artifacts exist
# (VERDICT r4 next #6: constants must have a measured pedigree).
VPU_PARALLEL = 8 * 128  # one VPU tile of lanes: widening stateless work
#                         is ~free below this many parallel firings
STEP_OVERHEAD = 4096.0  # fixed per-step cost: host loop + while-loop
#                         iteration + dispatch, in item-equivalents
DEFAULT_VMEM_BUDGET = 4 << 20  # keep live chunks well under v5e's 16MB

# Per-platform utility-model constants. "measured" rows come from
# tools/calibrate_vect.py's per-regime lstsq fit (see its
# _fit_constants docstring) over committed probe tables; the TPU row
# stays an architectural estimate until a chip window lands
# VECT_CALIB.json, whose fitted_constants block model_constants()
# prefers automatically.
MODEL_CONSTANTS = {
    "tpu": {"vpu_parallel": float(VPU_PARALLEL),
            "step_overhead": STEP_OVERHEAD,
            "pedigree": "architectural estimate (one 8x128 VPU tile; "
                        "~4096 item-equivalents of dispatch); refit "
                        "pending VECT_CALIB.json"},
    "cpu": {"vpu_parallel": 18.0, "step_overhead": 20000.0,
            "pedigree": "measured: per-regime lstsq fit of "
                        "VECT_CALIB_CPU.json probe tables "
                        "(2026-07-31; vmapped work ~18x cheaper per "
                        "item than scan work, ~20k seq-item-"
                        "equivalents per-step overhead)"},
}

_CALIB_ARTIFACTS = {
    "tpu": "VECT_CALIB.json",
    "cpu": "VECT_CALIB_CPU.json",
}
_FITTED_CACHE: Dict[str, Optional[dict]] = {}


def active_platform() -> str:
    """The platform whose cost structure the plan should assume:
    "cpu" when jax is pinned to cpu (tests, --platform=cpu), else
    "tpu" (the design target; the axon plugin is a TPU)."""
    try:
        import jax
        first = (getattr(jax.config, "jax_platforms", None)
                 or "").split(",")[0].strip()
        if first == "cpu":
            return "cpu"
    except Exception:
        pass
    return "tpu"


def _fitted_from_artifact(key: str) -> Optional[dict]:
    """fitted_constants from the committed calibration artifact for
    this platform, if one exists and carries a clean fit."""
    if key in _FITTED_CACHE:
        return _FITTED_CACHE[key]
    fc = None
    try:
        import json
        import os
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        with open(os.path.join(root, _CALIB_ARTIFACTS[key])) as f:
            j = json.load(f)
        cand = j.get("fitted_constants") or {}
        if ("VPU_PARALLEL" in cand and "STEP_OVERHEAD" in cand
                and cand["VPU_PARALLEL"] > 0
                and cand["STEP_OVERHEAD"] > 0):
            fc = cand
    except Exception:
        fc = None
    _FITTED_CACHE[key] = fc
    return fc


def model_constants(platform: Optional[str] = None) -> dict:
    """Resolve {vpu_parallel, step_overhead, pedigree} for a platform
    (default: the active one). A fitted_constants block in the
    platform's committed calibration artifact wins over the built-in
    row, so landing VECT_CALIB.json retires the TPU guess without a
    code change."""
    plat = platform or active_platform()
    key = "cpu" if plat == "cpu" else "tpu"
    out = dict(MODEL_CONSTANTS[key])
    fc = _fitted_from_artifact(key)
    if fc:
        out.update(
            vpu_parallel=float(fc["VPU_PARALLEL"]),
            step_overhead=float(fc["STEP_OVERHEAD"]),
            pedigree=(f"measured: fitted_constants in "
                      f"{_CALIB_ARTIFACTS[key]} "
                      f"({fc.get('method', 'fit')})"))
    return out


_STATEFUL = (ir.MapAccum, ir.JaxBlock)


def _lcm(a: int, b: int) -> int:
    return a * b // gcd(a, b)


# --------------------------------------------------------------------------
# Utility model (the VecSF scoring analogue)
# --------------------------------------------------------------------------


def utility(ss: SteadyState, stages: Sequence[ir.Comp], W: int,
            item_bytes: int = 4,
            vmem_budget: int = DEFAULT_VMEM_BUDGET,
            constants: Optional[dict] = None) -> Tuple[float, str]:
    """Score scale factor W for one static segment; returns (utility, note).

    utility = items_per_step / time_proxy, where

    - items_per_step = ss.take * W (amortizes STEP_OVERHEAD);
    - each stateless stage contributes max(F/VPU_PARALLEL, 1) — its F
      firings run as one vmapped batch, so cost is flat until the VPU
      lanes fill, then linear;
    - each stateful stage contributes F — a lax.scan fires sequentially,
      so widening adds latency without parallelism (it still helps by
      amortizing the per-step overhead, which the model captures);
    - candidates whose largest live chunk exceeds vmem_budget are
      infeasible (utility -inf, note says why). Chunk size is estimated
      as the max over inter-stage edges of items-on-edge * item_bytes.

    The note string goes into the --ddump-vect style dump.
    """
    cards = [cardinality(s) for s in stages]
    # largest inter-stage edge, in items per step
    edge_items = [ss.take * W]
    run = ss.take * W
    for c, r in zip(cards, ss.reps):
        assert isinstance(c, TCard)
        run = c.o * r * W
        edge_items.append(run)
    max_edge = max(edge_items)
    bytes_live = max_edge * item_bytes
    if bytes_live > vmem_budget:
        return float("-inf"), (
            f"infeasible: live chunk {bytes_live}B > VMEM budget "
            f"{vmem_budget}B")
    c = constants or model_constants()
    time_proxy = c["step_overhead"]
    for stage, r in zip(stages, ss.reps):
        F = r * W
        if isinstance(stage, _STATEFUL):
            time_proxy += float(F)
        else:
            time_proxy += max(float(F) / c["vpu_parallel"], 1.0)
    u = (ss.take * W) / time_proxy
    return u, f"chunk={max_edge} items ({bytes_live}B)"


def search_width(ss: SteadyState, stages: Sequence[ir.Comp],
                 item_bytes: int = 4,
                 vmem_budget: int = DEFAULT_VMEM_BUDGET,
                 max_width: int = 1 << 20,
                 constants: Optional[dict] = None):
    """Enumerate candidate scale factors (powers of two) and score them.

    Returns (best_W, candidates) with candidates a list of
    (W, utility, note). Tie-break: the SMALLEST W within 1% of the best
    utility wins — beyond the amortization knee extra width only adds
    latency and memory (the reference's utility similarly penalized
    overly wide rewrites).
    """
    constants = constants or model_constants()
    cands: List[Tuple[int, float, str]] = []
    W = 1
    while W <= max_width:
        u, note = utility(ss, stages, W, item_bytes, vmem_budget,
                          constants)
        cands.append((W, u, note))
        if u == float("-inf"):
            break  # wider only grows the chunk further
        W *= 2
    best_u = max(u for _, u, _ in cands)
    if best_u == float("-inf"):
        # even W=1 blows the VMEM budget: fall back to width 1 but say so
        # in the dump rather than presenting it as a model choice
        cands.append((1, 0.0, "fallback: every candidate infeasible; "
                              "running at width 1 anyway"))
        return 1, cands
    best_W = 1
    for W, u, _ in cands:
        if u != float("-inf") and u >= 0.99 * best_u:
            best_W = W
            break
    return best_W, cands


# --------------------------------------------------------------------------
# Segmentation: maximal static runs, dynamic stages bridged
# --------------------------------------------------------------------------


@dataclass
class Segment:
    """A maximal run of consecutive pipeline stages. ``ss`` is the SDF
    steady state for static (jit-fusable) segments, None for dynamic
    segments (single stage, interpreter-executed)."""

    stages: Tuple[ir.Comp, ...]
    start: int
    ss: Optional[SteadyState]
    width: int = 1
    candidates: Tuple[Tuple[int, float, str], ...] = ()

    @property
    def dynamic(self) -> bool:
        return self.ss is None

    @property
    def comp(self) -> ir.Comp:
        return ir.pipe(*self.stages)


@dataclass
class VectPlan:
    """The vectorizer's output: segments with chosen widths."""

    segments: List[Segment] = field(default_factory=list)
    constants: dict = field(default_factory=dict)

    def dump(self) -> str:
        """--ddump-vect analogue: scored candidate table per segment."""
        lines = []
        if self.constants:
            lines.append(
                f"model constants: vpu_parallel="
                f"{self.constants['vpu_parallel']:g} step_overhead="
                f"{self.constants['step_overhead']:g} "
                f"[{self.constants['pedigree']}]")
        for i, seg in enumerate(self.segments):
            labels = " >>> ".join(s.label() for s in seg.stages)
            if seg.dynamic:
                lines.append(f"segment {i}: DYNAMIC [{labels}] -> "
                             f"interpreter (no static cardinality)")
                continue
            lines.append(
                f"segment {i}: [{labels}] reps={seg.ss.reps} "
                f"take={seg.ss.take} emit={seg.ss.emit} -> width {seg.width}")
            for W, u, note in seg.candidates:
                mark = "*" if W == seg.width else " "
                u_s = "-inf" if u == float("-inf") else f"{u:.4f}"
                lines.append(f"  {mark} W={W:<8d} utility={u_s:<10s} {note}")
        return "\n".join(lines)


def _split_static_runs(stages: Sequence[ir.Comp]):
    """Group stages into maximal runs with a combined static steady state.

    Greedy: extend the current run while ``steady_state`` of the run
    stays defined; a stage that breaks it (dynamic cardinality, or a
    rate mismatch with the run) closes the run. Dynamic single stages
    become their own segments.
    """
    runs: List[Tuple[int, List[ir.Comp], Optional[SteadyState]]] = []
    cur: List[ir.Comp] = []
    cur_start = 0
    cur_ss: Optional[SteadyState] = None
    for k, s in enumerate(stages):
        trial = steady_state(cur + [s])
        if trial is not None:
            if not cur:
                cur_start = k
            cur.append(s)
            cur_ss = trial
            continue
        if cur:
            runs.append((cur_start, cur, cur_ss))
            cur, cur_ss = [], None
        solo = steady_state([s])
        if solo is not None:
            cur, cur_start, cur_ss = [s], k, solo
        else:
            runs.append((k, [s], None))
    if cur:
        runs.append((cur_start, cur, cur_ss))
    return runs


def vectorize(comp: ir.Comp, item_bytes: int = 4,
              vmem_budget: int = DEFAULT_VMEM_BUDGET,
              max_width: int = 1 << 20) -> VectPlan:
    """Plan vectorization for a pipeline: split into segments, search a
    scale factor for each static segment. Pure planning — no IR rewrite;
    feed the plan to ``backend.execute.run_vect`` (or use a segment's
    ``width`` with ``backend.lower``)."""
    stages = ir.pipeline_stages(comp)
    plan = VectPlan()
    plan.constants = model_constants()
    for start, run, ss in _split_static_runs(stages):
        if ss is None:
            plan.segments.append(Segment(tuple(run), start, None))
            continue
        W, cands = search_width(ss, run, item_bytes, vmem_budget,
                                max_width, plan.constants)
        plan.segments.append(
            Segment(tuple(run), start, ss, W, tuple(cands)))
    return plan


# --------------------------------------------------------------------------
# Widening rewrite (take -> takes analogue) + mitigators
# --------------------------------------------------------------------------


def _widen_stateless(f, a: int, b: int, w: int):
    """Widen a per-firing function (a items -> b items) by w: the widened
    function maps a blocks of w items to b blocks of w items, applying f
    to each of the w interleaved firings via vmap. Block layout keeps raw
    stream order: block j element l is raw item j*w + l, so flattening a
    stacked (a, w, *item) input IS raw stream order."""
    import jax

    def g(xs):
        if a == 1:
            apps = xs  # (w, *item)
        else:
            flat = xs.reshape((a * w,) + xs.shape[2:])
            apps = flat.reshape((w, a) + flat.shape[1:])
        ys = jax.vmap(f)(apps)
        if b == 1:
            return ys
        flat_out = ys.reshape((w * b,) + ys.shape[2:])
        return flat_out.reshape((b, w) + flat_out.shape[1:])
    return g


def _widen_stateful(f, a: int, b: int, w: int):
    """Widen a stateful per-firing function: the w firings inside one
    widened firing run sequentially under lax.scan (state dependences
    are preserved exactly)."""
    from jax import lax

    def g(state, xs):
        if a == 1:
            apps = xs
        else:
            flat = xs.reshape((a * w,) + xs.shape[2:])
            apps = flat.reshape((w, a) + flat.shape[1:])
        state, ys = lax.scan(f, state, apps)
        if b == 1:
            return state, ys
        flat_out = ys.reshape((w * b,) + ys.shape[2:])
        return state, flat_out.reshape((b, w) + flat_out.shape[1:])
    return g


def mitigator(w_in: int, w_out: int, name: Optional[str] = None) -> ir.Comp:
    """Reshape node between stages widened by different factors — the
    reference's mitigator (SURVEY.md §2.1). Takes lcm/w_in blocks of
    w_in items, emits lcm/w_out blocks of w_out items, identity on the
    underlying item stream."""
    L = _lcm(w_in, w_out)
    a, b = L // w_in, L // w_out

    def g(xs):
        # normalize the input window to flat (L, *item) raw order;
        # width 1 means bare (unblocked) items on that side
        if w_in == 1:
            flat = xs if a > 1 else xs[None]
        elif a == 1:
            flat = xs  # one block of (w_in, *item) == (L, *item)
        else:
            flat = xs.reshape((L,) + xs.shape[2:])
        if w_out == 1:
            return flat if b > 1 else flat[0]
        if b == 1:
            return flat  # one block of (w_out, *item)
        return flat.reshape((b, w_out) + flat.shape[1:])

    return ir.Map(g, a, b, name or f"mitigate[{w_in}->{w_out}]")


def widen_stage(stage: ir.Comp, w: int) -> ir.Comp:
    """Rewrite one pipeline stage to operate on w-item blocks."""
    if w == 1:
        return stage
    if isinstance(stage, ir.Map):
        return ir.Map(_widen_stateless(stage.f, stage.in_arity,
                                             stage.out_arity, w),
                      stage.in_arity, stage.out_arity,
                      f"{stage.label()}^{w}")
    if isinstance(stage, (ir.MapAccum, ir.JaxBlock)):
        g = _widen_stateful(stage.f, stage.in_arity, stage.out_arity, w)
        if isinstance(stage, ir.MapAccum):
            adv = stage.advance
            if adv is not None:
                # one widened firing = w original firings
                def adv_w(s, n, _a=adv, _w=w):
                    return _a(s, n * _w)
            else:
                adv_w = None
            return ir.MapAccum(g, stage.init, stage.in_arity,
                               stage.out_arity, f"{stage.label()}^{w}",
                               advance=adv_w, memory=stage.memory)
        return ir.JaxBlock(g, stage.init, stage.in_arity, stage.out_arity,
                           f"{stage.label()}^{w}")
    if isinstance(stage, ir.Repeat):
        from ziria_tpu.backend.lower import firing_fn
        fire, a, b = firing_fn(stage.body)
        return ir.Map(_widen_stateless(fire, a, b, w), a, b,
                      f"repeat({stage.body.label()})^{w}")
    raise ValueError(
        f"widen_stage: stage {stage.label()} ({type(stage).__name__}) has "
        f"no static widening; leave it at width 1")


def widen(comp: ir.Comp, w, insert_mitigators: bool = True) -> ir.Comp:
    """The take->takes / emit->emits rewrite: return a pipeline over
    w-item blocks. ``w`` is an int (uniform width) or a dict mapping
    stage index -> width; with per-stage widths, mitigators are inserted
    between mismatched neighbors (when ``insert_mitigators``).

    Feeding the widened pipeline: reshape the raw stream (N, *item) to
    (N/w, w, *item); flatten the output blocks back. The test suite's
    flag matrix asserts exact agreement with the unwidened pipeline on
    both backends.
    """
    stages = ir.pipeline_stages(comp)
    if isinstance(w, int):
        widths = [w] * len(stages)
    else:
        widths = [w.get(k, 1) for k in range(len(stages))]
    out: List[ir.Comp] = []
    prev_w: Optional[int] = None
    for k, (s, wk) in enumerate(zip(stages, widths)):
        if prev_w is not None and prev_w != wk and insert_mitigators:
            out.append(mitigator(prev_w, wk))
        out.append(widen_stage(s, wk))
        prev_w = wk
    return ir.pipe(*out)
