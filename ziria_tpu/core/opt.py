"""Fold/fusion optimizer: rewrite rules iterated to fixpoint.

Counterpart of the reference's `PassFold.hs` (SURVEY.md §2.1) — its
rewrite engine inlines, fuses `map f >>> map g`, simplifies
return/bind, and re-runs to fixpoint. TPU-first difference: XLA already
fuses elementwise chains *inside* one traced graph, so the payoff here
is **structural**, earlier in the pipeline: fewer IR stages means fewer
scan/vmap wrappers at lowering time, and rewriting `repeat(take;emit)`
into `Map` unlocks the parallel (vmap) lowering path where the generic
repeat body would otherwise be traced per-firing.

Rules (each preserves streaming semantics exactly — the test suite's
flag matrix asserts optimized == unoptimized output on both backends):

  R1  bind-assoc       Bind(Bind(a,x,b), y, c) -> Bind(a, x, Bind(b,y,c))
  R2  return-left      Bind(Return(e), None, rest) -> rest
  R3  repeat-take-emit repeat(x <- take(s) ; emit(s)(f x)) -> Map f
  R4  map-map fusion   Map f >>> Map g -> Map (g . f)   [rates matching]
  R5  map-accum fusion Map f >>> MapAccum g -> MapAccum (g . f)
                       MapAccum g >>> Map f -> MapAccum (f . g)
  R6  const-branch     Branch(const, t, e) -> t | e
  R7  pipe-assoc       canonical right-nesting of Pipe (stable fusion
                       scan order; ParPipe boundaries never crossed)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from ziria_tpu.core import ir
from ziria_tpu.core.ir import Env, eval_expr


# --------------------------------------------------------------------------
# Individual rules: each returns a rewritten node or None (no match)
# --------------------------------------------------------------------------


def _bind_assoc(c: ir.Comp) -> Optional[ir.Comp]:
    if (isinstance(c, ir.Bind) and isinstance(c.first, ir.Bind)
            and c.first.var is None):
        # seq-only association: when the inner bind names a variable,
        # re-association would widen its scope over `c.rest` and could
        # shadow an identically-named outer binding (closures are
        # opaque, so usage can't be checked) — those stay as-is
        inner = c.first
        return ir.Bind(inner.first, None,
                       ir.Bind(inner.rest, c.var, c.rest))
    return None


def _return_left(c: ir.Comp) -> Optional[ir.Comp]:
    if (isinstance(c, ir.Bind) and isinstance(c.first, ir.Return)
            and c.var is None and not callable(c.first.expr)):
        # only constant returns are dropped: a callable expr could read
        # refs set by earlier Assigns — dropping it is safe too (Return
        # has no effects), but keep the conservative constant-only form
        return c.rest
    return None


def _repeat_take_emit(c: ir.Comp) -> Optional[ir.Comp]:
    """repeat { x <- take/takes n ; emit/emits m (f x) }  ->  Map(f, n, m).

    The emit expression is a closure over the body's Env; the fused Map
    evaluates it in a fresh one-binding Env, which is exactly the body's
    environment shape (take binds one var, nothing else is in scope).
    """
    if not isinstance(c, ir.Repeat):
        return None
    b = c.body
    if not (isinstance(b, ir.Bind) and b.var is not None):
        return None
    if isinstance(b.first, ir.Take):
        n = 1
    elif isinstance(b.first, ir.Takes):
        n = b.first.n
    else:
        return None
    if isinstance(b.rest, ir.Emit):
        m, expr = 1, b.rest.expr
    elif isinstance(b.rest, ir.Emits):
        m, expr = b.rest.n, b.rest.expr
    else:
        return None
    var = b.var

    def fused(x, _expr=expr, _var=var):
        env = Env()
        env.bind(_var, x)
        return eval_expr(_expr, env)

    return ir.Map(fused, in_arity=n, out_arity=m,
                  name=f"fold[take{n}->emit{m}]")


def _compose_maps(f: Callable, g: Callable) -> Callable:
    def h(x):
        return g(f(x))
    return h


def _map_fusions(c: ir.Comp) -> Optional[ir.Comp]:
    if not isinstance(c, ir.Pipe):
        return None
    up, down = c.up, c.down
    if (isinstance(up, ir.Map) and isinstance(down, ir.Map)
            and up.out_arity == down.in_arity):
        # the fused map's input domain IS the upstream's declared domain,
        # so AutoLUT still applies after fusion
        return ir.Map(_compose_maps(up.f, down.f), up.in_arity,
                      down.out_arity,
                      name=f"{down.label()}.{up.label()}",
                      in_domain=up.in_domain,
                      in_dtype=up.in_dtype, out_dtype=down.out_dtype)
    if (isinstance(up, ir.Map) and isinstance(down, ir.MapAccum)
            and up.out_arity == down.in_arity):
        def fa(s, x, _f=up.f, _g=down.f):
            return _g(s, _f(x))
        # the fused stage carries the SAME state with the same
        # evolution, so the fast-forward stays valid; finite memory
        # rescales from accum-input items to map-input items
        # (ceil(mem / b) firings x a items each)
        mem = down.memory
        if mem is not None and down.in_arity:
            mem = -(-int(mem) // down.in_arity) * up.in_arity
        return ir.MapAccum(fa, down.init, up.in_arity, down.out_arity,
                           name=f"{down.label()}.{up.label()}",
                           in_dtype=up.in_dtype,
                           out_dtype=down.out_dtype,
                           advance=down.advance, memory=mem)
    if (isinstance(up, ir.MapAccum) and isinstance(down, ir.Map)
            and up.out_arity == down.in_arity):
        def fb(s, x, _f=up.f, _g=down.f):
            s2, y = _f(s, x)
            return s2, _g(y)
        return ir.MapAccum(fb, up.init, up.in_arity, down.out_arity,
                           name=f"{down.label()}.{up.label()}",
                           in_dtype=up.in_dtype,
                           out_dtype=down.out_dtype,
                           advance=up.advance, memory=up.memory)
    return None


def _const_branch(c: ir.Comp) -> Optional[ir.Comp]:
    if isinstance(c, ir.Branch) and not callable(c.cond):
        return c.then if c.cond else c.els
    return None


def _pipe_assoc(c: ir.Comp) -> Optional[ir.Comp]:
    if isinstance(c, ir.Pipe) and isinstance(c.up, ir.Pipe):
        return ir.Pipe(c.up.up, ir.Pipe(c.up.down, c.down))
    return None


# R3 is only sound where the emit closure cannot see outer bindings:
# under an enclosing LetRef / binder, `emit(f x)` may read those names,
# and the fused Map's fresh one-binding Env would lose them. The walker
# tracks scope and drops R3 inside any enclosing binder (conservative —
# closures are opaque, so "does it read y?" is unanswerable statically).
_RULES: Tuple[Callable, ...] = (
    _bind_assoc, _return_left, _map_fusions, _const_branch, _pipe_assoc,
)
_RULES_UNSCOPED: Tuple[Callable, ...] = _RULES + (_repeat_take_emit,)


# --------------------------------------------------------------------------
# Fixpoint driver
# --------------------------------------------------------------------------


def _rewrite_node(c: ir.Comp, rules) -> Tuple[ir.Comp, int]:
    n = 0
    changed = True
    while changed:
        changed = False
        for rule in rules:
            r = rule(c)
            if r is not None:
                c, n, changed = r, n + 1, True
    return c, n


def _rebuild(c: ir.Comp, f: Callable[[ir.Comp, bool], ir.Comp],
             scoped: bool) -> ir.Comp:
    """Apply f to each child via the shared walker (ir.map_children),
    threading `scoped` — True once any enclosing construct introduced a
    binding visible to descendants."""
    return ir.map_children(c, lambda ch, binds: f(ch, scoped or binds))


@dataclass
class FoldStats:
    rewrites: int
    passes: int


def fold(comp: ir.Comp, max_passes: int = 20) -> ir.Comp:
    """Optimize `comp` to fixpoint. Semantics-preserving by construction;
    the flag-matrix tests assert it."""
    out, _ = fold_with_stats(comp, max_passes)
    return out


def fold_with_stats(comp: ir.Comp,
                    max_passes: int = 20) -> Tuple[ir.Comp, FoldStats]:
    total = 0
    passes = 0
    for _ in range(max_passes):
        passes += 1
        count = [0]

        def walk(c: ir.Comp, scoped: bool = False) -> ir.Comp:
            c = _rebuild(c, walk, scoped)
            c, k = _rewrite_node(
                c, _RULES if scoped else _RULES_UNSCOPED)
            count[0] += k
            return c

        comp = walk(comp)
        total += count[0]
        if count[0] == 0:
            break
    return comp, FoldStats(rewrites=total, passes=passes)
