"""802.11a block interleaver / deinterleaver.

Counterpart of the reference's `interleaving.blk` / `deinterleaving.blk`
(SURVEY.md §2.3). The two standard permutations (adjacent coded bits to
nonadjacent subcarriers; adjacent bits alternate between significant/
less-significant constellation positions) are *precomputed as one gather
index per (n_cbps, n_bpsc)* at trace time — on TPU the interleaver is a
single vectorized gather over each OFDM symbol's bit block, batched over
symbols.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=None)
def interleave_perm(n_cbps: int, n_bpsc: int) -> np.ndarray:
    """perm[j] = k : output position j carries input bit k (one symbol).

    Built from the standard's two index maps (k->i then i->j), inverted
    into a single gather.
    """
    s = max(n_bpsc // 2, 1)
    k = np.arange(n_cbps)
    i = (n_cbps // 16) * (k % 16) + k // 16
    j = s * (i // s) + (i + n_cbps - (16 * i // n_cbps)) % s
    # bit k of the input lands at output position j[k]; gather wants the
    # inverse: out[j] = in[k]
    perm = np.zeros(n_cbps, np.int32)
    perm[j] = k
    return perm


@lru_cache(maxsize=None)
def deinterleave_perm(n_cbps: int, n_bpsc: int) -> np.ndarray:
    p = interleave_perm(n_cbps, n_bpsc)
    inv = np.zeros_like(p)
    inv[p] = np.arange(n_cbps, dtype=np.int32)
    return inv


@lru_cache(maxsize=None)
def deinterleave_slots(n_cbps: int, n_bpsc: int):
    """(subcarrier, bit) source of each DEinterleaved soft value — the
    static index view of :func:`deinterleave` the in-kernel fused
    front end (ops/viterbi_pallas) bakes into its one-hot gather
    tables — both the known-rate `_front_tables` and the stacked
    8-rate `mixed_front_tables` bank of the rate-switched decode. Position ``q`` of the per-symbol deinterleaved stream
    reads demapped LLR ``r = deinterleave_perm[q]``, and demap's
    ``(..., 48 * n_bpsc)`` layout puts subcarrier ``r // n_bpsc`` bit
    ``r % n_bpsc`` there. Returns ``(sub, bit)`` int32 arrays of
    length ``n_cbps``."""
    perm = deinterleave_perm(n_cbps, n_bpsc)
    return (perm // n_bpsc).astype(np.int32), \
        (perm % n_bpsc).astype(np.int32)


def interleave(bits, n_cbps: int, n_bpsc: int) -> jnp.ndarray:
    """Interleave a stream of whole symbols: (..., m*n_cbps) -> same shape."""
    return _permute(bits, interleave_perm(n_cbps, n_bpsc), n_cbps)


def deinterleave(vals, n_cbps: int, n_bpsc: int) -> jnp.ndarray:
    """Inverse; also used on soft values in RX (works on any dtype)."""
    return _permute(vals, deinterleave_perm(n_cbps, n_bpsc), n_cbps)


def _permute(vals, perm: np.ndarray, n_cbps: int) -> jnp.ndarray:
    vals = jnp.asarray(vals)
    n = vals.shape[-1]
    if n % n_cbps:
        raise ValueError(f"length {n} not a multiple of n_cbps={n_cbps}")
    blocks = vals.reshape(vals.shape[:-1] + (n // n_cbps, n_cbps))
    out = blocks[..., jnp.asarray(perm)]
    return out.reshape(vals.shape)


def np_interleave_ref(bits: np.ndarray, n_cbps: int,
                      n_bpsc: int) -> np.ndarray:
    """Independent oracle: direct per-bit index computation. Tests only."""
    bits = np.asarray(bits)
    assert bits.size % n_cbps == 0
    s = max(n_bpsc // 2, 1)
    out = np.empty_like(bits)
    for blk in range(bits.size // n_cbps):
        base = blk * n_cbps
        for k in range(n_cbps):
            i = (n_cbps // 16) * (k % 16) + k // 16
            j = s * (i // s) + (i + n_cbps - (16 * i // n_cbps)) % s
            out[base + j] = bits[base + k]
    return out
