"""Constellation mapping (BPSK/QPSK/16-QAM/64-QAM, 802.11 Gray labels).

Counterpart of the reference's `modulating.blk` (SURVEY.md §2.3).
TPU-native: bits group into per-axis Gray indices, then one LUT gather
per I/Q axis — no per-symbol branching; the constellation tables are the
AutoLUT analogue, precomputed in numpy.

Dtype policy: symbols are real pairs (..., 2) float32 (see ops/cplx —
the axon TPU backend has no complex support, and the reference's SORA
likewise carries complex16 as integer pairs). The numpy oracle
(np_modulate_ref) speaks complex64 for test readability.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.utils.bits import bits_to_uint

# per-axis Gray maps: bits (LSB..MSB along axis) -> amplitude level
_GRAY2 = np.array([-3.0, -1.0, 3.0, 1.0])  # b0 b1 -> level, 16-QAM axis
# 64-QAM axis, 3 bits b0b1b2 (b0 most significant per standard order):
# 000->-7 001->-5 011->-3 010->-1 110->1 111->3 101->5 100->7
_GRAY3 = np.zeros(8)
for _bits, _lvl in [((0, 0, 0), -7), ((0, 0, 1), -5), ((0, 1, 1), -3),
                    ((0, 1, 0), -1), ((1, 1, 0), 1), ((1, 1, 1), 3),
                    ((1, 0, 1), 5), ((1, 0, 0), 7)]:
    _GRAY3[(_bits[0] << 2) | (_bits[1] << 1) | _bits[2]] = _lvl

_KMOD = {1: 1.0, 2: 1.0 / np.sqrt(2.0), 4: 1.0 / np.sqrt(10.0),
         6: 1.0 / np.sqrt(42.0)}


def modulate(bits, n_bpsc: int) -> jnp.ndarray:
    """bits (..., m*n_bpsc) -> pair symbols (..., m, 2) float32.

    Bit order within a symbol follows the standard: first bits map to I,
    remaining to Q, most-significant first.
    """
    bits = jnp.asarray(bits, jnp.uint8)
    n = bits.shape[-1]
    if n % n_bpsc:
        raise ValueError(f"bit count {n} not a multiple of n_bpsc={n_bpsc}")
    g = bits.reshape(bits.shape[:-1] + (n // n_bpsc, n_bpsc))
    if n_bpsc == 1:
        i = 2.0 * g[..., 0] - 1.0
        q = jnp.zeros_like(i)
    elif n_bpsc == 2:
        i = 2.0 * g[..., 0] - 1.0
        q = 2.0 * g[..., 1] - 1.0
    elif n_bpsc == 4:
        lut = jnp.asarray(_GRAY2)
        i = lut[bits_to_uint(g[..., 0:2], msb_first=True)]
        q = lut[bits_to_uint(g[..., 2:4], msb_first=True)]
    elif n_bpsc == 6:
        lut = jnp.asarray(_GRAY3)
        i = lut[bits_to_uint(g[..., 0:3], msb_first=True)]
        q = lut[bits_to_uint(g[..., 3:6], msb_first=True)]
    else:
        raise ValueError(f"unsupported n_bpsc {n_bpsc}")
    sym = jnp.stack([i, q], axis=-1) * _KMOD[n_bpsc]
    return sym.astype(jnp.float32)


def np_modulate_ref(bits: np.ndarray, n_bpsc: int) -> np.ndarray:
    """Independent oracle: per-symbol python loop over the standard's
    Gray tables. Tests only."""
    bits = np.asarray(bits, np.uint8).reshape(-1, n_bpsc)
    out = np.empty(bits.shape[0], np.complex64)
    kmod = _KMOD[n_bpsc]
    for s, b in enumerate(bits):
        if n_bpsc == 1:
            out[s] = kmod * (2 * int(b[0]) - 1)
        elif n_bpsc == 2:
            out[s] = kmod * ((2 * int(b[0]) - 1) + 1j * (2 * int(b[1]) - 1))
        elif n_bpsc == 4:
            i = _GRAY2[(int(b[0]) << 1) | int(b[1])]
            q = _GRAY2[(int(b[2]) << 1) | int(b[3])]
            out[s] = kmod * (i + 1j * q)
        else:
            i = _GRAY3[(int(b[0]) << 2) | (int(b[1]) << 1) | int(b[2])]
            q = _GRAY3[(int(b[3]) << 2) | (int(b[4]) << 1) | int(b[5])]
            out[s] = kmod * (i + 1j * q)
    return out
