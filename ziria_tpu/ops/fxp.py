"""Q15 fixed-point DSP primitives: the integer compute core of the
fixed-point RX interior (phy/wifi/rx_fxp.py).

Counterpart of the reference's fixed-point SORA bricks (SURVEY.md §2.2:
`csrc/ext_math.c`, the SSE FFT, and the fixed-point demapper inside the
RX chain): the reference ran its whole PHY in int16 "complex16" math
with LUT trig. This module rebuilds that discipline TPU-first:

- all arithmetic is int32 adds/muls/shifts on (..., 2) IQ pairs —
  every op is exact, so results are **bit-identical across backends,
  jit/interp, and vmap widths** (the property the f32 path cannot
  promise, and the reason a fixed-point interior exists at all);
- the DFT is an integer *matmul* against split Q14 twiddles (hi/lo
  int8-range factors, two int32 GEMMs) — the MXU-native formulation of
  a fixed-point FFT, not a butterfly network;
- trig is pure-integer CORDIC (vectoring for atan2/magnitude, rotation
  for derotation); ext_math.atan2_int16 delegates to the vectoring
  kernel here, so the DSL's fixed-point atan2 shares the same
  backend-bit-stable implementation.

Number formats (documented per function): int16 at API boundaries,
int32 inside; shifts use round-half-up (`rsra`), the single rounding
rule of the whole module.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
I16 = jnp.int16

Q15_HALF_TURN = 32768          # int16 turn angle units per pi radians
CORDIC_ITERS = 16              # gain K = prod sqrt(1 + 2^-2i) ~ 1.64676

# atan(2^-i) in Q15 turn units (host-side table; exact integers)
_CORDIC_ANGLES = np.round(
    np.arctan(2.0 ** -np.arange(CORDIC_ITERS))
    * (Q15_HALF_TURN / np.pi)).astype(np.int32)


_TRACE_PROBE_WARNED = False


def _in_trace() -> bool:
    """True while some JAX transformation is tracing. Private-API probe
    with a conservative fallback (assume tracing -> never cache). The
    fallback is correct but silently disables the device-constant
    cache, so it warns once (tests assert the probe works on the
    pinned JAX version — a version bump that moves the attribute is
    noticed, not absorbed as a perf regression)."""
    try:
        import jax._src.core as _core
        return _core.trace_ctx.trace is not _core.eval_trace
    except Exception:
        global _TRACE_PROBE_WARNED
        if not _TRACE_PROBE_WARNED:
            _TRACE_PROBE_WARNED = True
            import warnings
            warnings.warn(
                "ziria_tpu.ops.fxp: the jax._src.core.trace_ctx probe "
                "failed on this JAX version; the device-constant cache "
                "is disabled (correctness unaffected, Q14 twiddle "
                "tables rebuild on every call). Update _in_trace().")
        return True


_CONST_CACHE: dict = {}


def _const(key, build):
    """Device-constant memo that is safe against lazy import inside a
    jit trace: this module can be first imported while the hybrid
    backend is tracing a do-block (ext resolution is lazy), and values
    created at that point are trace-scoped — caching one leaks its
    tracer into every later caller (observed as UnexpectedTracerError
    from the wifi_rx_fxp golden). So constants are cached only when
    built OUTSIDE a trace; inside a trace they are rebuilt per call,
    where they fold into the jaxpr as ordinary constants."""
    v = _CONST_CACHE.get(key)
    if v is None:
        v = build()
        if not _in_trace():
            _CONST_CACHE[key] = v
    return v


def rsra(x, s: int):
    """Rounding arithmetic right shift (round half up): the module's
    one rounding rule. s == 0 is the identity."""
    x = jnp.asarray(x, I32)
    if s == 0:
        return x
    return (x + (1 << (s - 1))) >> s


def sat16(x):
    """Saturate int32 to the int16 range (stays int32 dtype)."""
    return jnp.clip(jnp.asarray(x, I32), -32768, 32767)


def quantize_q(x, frac_bits: int):
    """Float -> int32 Q(frac_bits) with round-half-up + int16
    saturation. The fixed-point boundary for float-domain captures.
    NaN quantizes to 0 and +-inf saturates to the rails (a float->int
    astype of non-finite values is implementation-defined)."""
    x = jnp.nan_to_num(jnp.asarray(x, jnp.float32),
                       nan=0.0, posinf=32767.0, neginf=-32768.0)
    return sat16(jnp.floor(x * (1 << frac_bits) + 0.5).astype(I32))


# --------------------------------------------------------------- CORDIC

def cordic_atan2(y, x):
    """Pure-integer CORDIC vectoring: Q15 turn angle of (y, x).

    Inputs int32 with |x|,|y| <= 2^28 (the x1.6467*sqrt(2) growth must
    stay inside int32). Returns (angle_q15 int32 in [-32768, 32767],
    magnitude int32 ~= 1.6467 * sqrt(x^2 + y^2)).
    Angle error <= ~2 Q15 steps at large magnitudes; exactly
    reproducible everywhere.
    """
    x = jnp.asarray(x, I32)
    y = jnp.asarray(y, I32)
    # quadrant fold: CORDIC converges for |angle| <= ~0.55 half-turns
    neg_x = x < 0
    z0 = jnp.where(neg_x & (y >= 0), I32(Q15_HALF_TURN),
                   jnp.where(neg_x, I32(-Q15_HALF_TURN), I32(0)))
    x0 = jnp.where(neg_x, -x, x)
    y0 = jnp.where(neg_x, -y, y)
    angles = _const("angles", lambda: jnp.asarray(_CORDIC_ANGLES))

    def body(i, c):
        xc, yc, zc = c
        d_pos = yc >= 0                       # rotate towards y == 0
        xs, ys = xc >> i, yc >> i
        a = angles[i]
        xn = jnp.where(d_pos, xc + ys, xc - ys)
        yn = jnp.where(d_pos, yc - xs, yc + xs)
        zn = jnp.where(d_pos, zc + a, zc - a)
        return xn, yn, zn

    xf, _yf, zf = jax.lax.fori_loop(0, CORDIC_ITERS, body, (x0, y0, z0))
    # wrap into the int16 turn range (z can reach +-(32768 + eps));
    # the degenerate (0, 0) input has no angle — pin it to 0 (the
    # iterations above would otherwise sum the whole angle table)
    zf = ((zf + Q15_HALF_TURN) & 0xFFFF) - Q15_HALF_TURN
    zf = jnp.where((x == 0) & (y == 0), 0, zf)
    return zf, xf


def cordic_rotate(pair, angle_q15, kinv_bits: int = 15):
    """Pure-integer CORDIC rotation of IQ `pair` (..., 2) by a Q15 turn
    angle (broadcastable to pair[..., 0]).

    The x1.6467 CORDIC gain is compensated up front by the
    Q(kinv_bits) reciprocal; the compensation multiply is the input
    limit: |re|,|im| < 2^31 / ceil(2^kinv_bits / 1.6467). kinv_bits=15
    (default) allows ~2^16.7 inputs at ~3e-5 gain error; kinv_bits=10
    allows ~2^21.7 at ~8e-4 — callers pick headroom vs precision.
    Result is the rotated input at unchanged scale; worst-case error
    ~1e-3 relative (angle-table rounding) + the gain-reciprocal error."""
    p = jnp.asarray(pair, I32)
    a = jnp.asarray(angle_q15, I32)
    kinv = I32(int(round((1 << kinv_bits) / 1.646760258121)))
    # pre-compensate the gain while magnitudes are smallest
    x = rsra(p[..., 0] * kinv, kinv_bits)
    y = rsra(p[..., 1] * kinv, kinv_bits)
    # quadrant fold to the convergence range
    big = jnp.abs(a) > (Q15_HALF_TURN // 2)
    x = jnp.where(big, -x, x)
    y = jnp.where(big, -y, y)
    z = jnp.where(big, a - jnp.sign(a) * Q15_HALF_TURN, a)

    angles = _const("angles", lambda: jnp.asarray(_CORDIC_ANGLES))

    def body(i, c):
        xc, yc, zc = c
        d_pos = zc >= 0                       # rotate residual to zero
        xs, ys = xc >> i, yc >> i
        ang = angles[i]
        xn = jnp.where(d_pos, xc - ys, xc + ys)
        yn = jnp.where(d_pos, yc + xs, yc - xs)
        zn = jnp.where(d_pos, zc - ang, zc + ang)
        return xn, yn, zn

    xf, yf, _zf = jax.lax.fori_loop(0, CORDIC_ITERS, body, (x, y, z))
    return jnp.stack([xf, yf], axis=-1)


# ------------------------------------------------- integer DFT (matmul)

def _dft_twiddles_q14(n: int, inverse: bool = False,
                      scale: float = 1.0):
    """DFT matrix exp(-+2*pi*i*j*k/n) * scale in Q14, split into
    (hi, lo) int factors with W == hi * 128 + lo, |hi| <= 128 and
    lo in [0, 127] (NOTE: hi reaches +128 for the unit twiddle — the
    factors are 8-bit-magnitude, not storable as int8) — the two-GEMM
    trick that keeps a 64-term int32 accumulation inside int32
    (64 * 2^15 * 2^14 would need 36 bits unsplit)."""
    jk = np.outer(np.arange(n), np.arange(n))
    w = np.exp((2j if inverse else -2j) * np.pi * jk / n) * scale
    wq = np.round(w.real * (1 << 14)).astype(np.int32), \
        np.round(w.imag * (1 << 14)).astype(np.int32)
    out = []
    for m in wq:
        hi = m >> 7                       # arithmetic: lo in [0, 127]
        lo = m - (hi << 7)
        out.append((hi.astype(np.int32), lo.astype(np.int32)))
    return out  # [(re_hi, re_lo), (im_hi, im_lo)]


_TW64 = _dft_twiddles_q14(64)
# inverse twiddles with the 802.11 OFDM time scale folded in:
# time = IDFT_sum(bins) * (TIME_SCALE / 64) = IDFT_sum / sqrt(52)
_ITW64_WIFI = _dft_twiddles_q14(64, inverse=True,
                                scale=1.0 / np.sqrt(52.0))


def _gemm_q14(x, hi, lo):
    """x (..., 64) int32 @ split-Q14 matrix -> int32, result scaled by
    2^-7 (the lo half is rounded in, then the hi half is added at its
    natural 2^7 weight): (x @ hi) + rsra(x @ lo, 7)."""
    dot = lambda a, b: jax.lax.dot_general(
        a, b, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=I32)
    return dot(x, hi) + rsra(dot(x, lo), 7)


def _cdft_q14(pair, key: str, table, shift: int):
    """The one complex split-Q14 GEMM body shared by the forward and
    inverse DFTs: four int32 GEMMs + the module's rounding rule."""
    p = jnp.asarray(pair, I32)
    xr, xi = p[..., 0], p[..., 1]
    (rh, rl), (ih, il) = _const(key, lambda: tuple(
        (jnp.asarray(h), jnp.asarray(l)) for h, l in table))
    re = _gemm_q14(xr, rh, rl) - _gemm_q14(xi, ih, il)
    im = _gemm_q14(xr, ih, il) + _gemm_q14(xi, rh, rl)
    return jnp.stack([rsra(re, shift), rsra(im, shift)], axis=-1)


def dft64_q14(pair, shift: int = 7):
    """Integer 64-point DFT of int IQ pairs (..., 64, 2) via four int32
    GEMMs against split Q14 twiddles.

    Input |values| <= 2^15 (int16-range). Output = DFT(x) * 2^(7-shift)
    (the twiddle Q14 scale minus the internal 2^-7, minus `shift` more
    rounding bits). shift=7 returns the unnormalized DFT at input
    scale: bins = sum_n x[n] w^(nk) exactly (to the documented
    rounding)."""
    return _cdft_q14(pair, "tw64", _TW64, shift)


def idft64_wifi_q14(pair):
    """Integer 64-point OFDM symbol synthesis: inverse DFT with the
    802.11 time scale folded into the twiddles —
    out = round-ish(IDFT_sum(bins) / sqrt(52)), i.e. integer bins at
    wire scale S produce time samples at the same wire scale the f32
    chain's ifft * TIME_SCALE * S produces. Same split-Q14 GEMM
    machinery (and rounding rule) as the forward dft64_q14."""
    return _cdft_q14(pair, "itw64", _ITW64_WIFI, 7)


# ------------------------------------------------------ pair arithmetic

def cmul_conj_i32(a, b, shift: int):
    """a * conj(b) for int IQ pairs, each product rsra'd by `shift`
    BEFORE the add so intermediates stay in int32 when
    |a|*|b| <= 2^30."""
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    re = rsra(ar * br, shift) + rsra(ai * bi, shift)
    im = rsra(ai * br, shift) - rsra(ar * bi, shift)
    return jnp.stack([re, im], axis=-1)


def cabs2_i32(p, shift: int):
    """|p|^2 for int IQ pairs with the same pre-add rounding shift."""
    return (rsra(p[..., 0] * p[..., 0], shift)
            + rsra(p[..., 1] * p[..., 1], shift))


def isqrt_u32(x):
    """Integer floor square root of non-negative int32 (bitwise
    restoring method, 16 fixed iterations — exact)."""
    x = jnp.asarray(x, I32)

    def body(i, c):
        rem, res = c
        bit = I32(1) << (30 - 2 * i)
        take = rem >= res + bit
        rem = jnp.where(take, rem - (res + bit), rem)
        res = jnp.where(take, (res >> 1) + bit, res >> 1)
        return rem, res

    _rem, root = jax.lax.fori_loop(0, 16, body,
                                   (x, jnp.zeros_like(x)))
    return root
