"""Fixed-point math library: the reference's ext_math.c equivalents.

The reference binds C `ext` functions for fixed-point trig/math
(`csrc/ext_math.c` + `sora_ext_lib.c`, SURVEY.md §2.2): sine/cosine/
atan2 over int16 angles, sqrt, log — LUT-backed where the bit-width is
small, because the SDR pipelines do phase tracking and CFO correction
in int16 Q-format, not doubles. TPU-first re-design:

- angles are int16 in the **Q15 turn format**: -32768..32767 maps to
  -π..π (wrap-around ≡ phase wrap, so angle arithmetic is plain int16
  add/sub — the reason SDR code loves this format);
- `sin_int16`/`cos_int16` return Q14 (-16384..16384 ≡ -1..1), computed
  by a 1024-entry quarter-resolution LUT gather (VMEM-resident, the
  TPU analogue of SORA's table) — gathers vectorize over any shape;
- `atan2_int16` returns the Q15 turn angle from int16 (y, x) — used by
  pilot phase tracking; pure-integer CORDIC (ops/fxp), bit-identical
  on every backend;
- `usqrt`/`ulog2` integer helpers mirror the reference's integer math.

All functions are jnp-traceable (usable inside jit/scan/vmap) and are
registered as frontend externals, so `.zir` sources can declare e.g.
`ext fun sin_int16(x: int16) : int16`.
"""

from __future__ import annotations

import numpy as np

_Q15_PI = 32768.0           # int16 angle units per π radians
_Q14_ONE = 16384.0          # unit amplitude

_SIN_BITS = 10              # 1024-entry LUT: step = 2π/65536*64 rad
_SIN_N = 1 << _SIN_BITS

# module-level host table; gathered on device (constant-folded into the
# executable by XLA on first use)
_SIN_LUT = np.round(
    _Q14_ONE * np.sin(2.0 * np.pi * np.arange(_SIN_N) / _SIN_N)
).astype(np.int16)


def _jnp():
    # cached module lookup (hot on every ext call; see frontend/eval)
    global _JNP_MOD
    if _JNP_MOD is None:
        import jax.numpy as jnp
        _JNP_MOD = jnp
    return _JNP_MOD


_JNP_MOD = None


# --------------------------------------------------------------------------
# Q15 angle helpers
# --------------------------------------------------------------------------


def rad_to_q15(theta) -> np.ndarray:
    """Radians → int16 turn angle (host-side helper for tests/config)."""
    t = np.asarray(theta, np.float64) / (2 * np.pi)
    t = t - np.round(t)
    return np.round(t * 65536.0).astype(np.int64).astype(np.int16)


def q15_to_rad(a):
    return np.asarray(a, np.float64) * (np.pi / _Q15_PI)


# --------------------------------------------------------------------------
# sine / cosine (LUT gather)
# --------------------------------------------------------------------------


def sin_int16(a):
    """Q14 sine of a Q15 turn angle (int16 → int16).

    LUT index = top 10 bits of the 16-bit angle; max error vs the real
    sine is one LUT step (~0.4% of full scale), same order as the
    reference's table-based fixed-point sine.
    """
    jnp = _jnp()
    a = jnp.asarray(a, jnp.int16)
    idx = (a.astype(jnp.uint16) >> (16 - _SIN_BITS)).astype(jnp.int32)
    return jnp.asarray(_SIN_LUT)[idx]


def cos_int16(a):
    jnp = _jnp()
    a = jnp.asarray(a, jnp.int16)
    # cos x = sin(x + π/2); +16384 wraps naturally in int16
    return sin_int16(a + jnp.int16(16384))


def sincos_int16(a):
    return sin_int16(a), cos_int16(a)


# --------------------------------------------------------------------------
# atan2 (pure-integer CORDIC, Q15 result)
# --------------------------------------------------------------------------


def atan2_int16(y, x):
    """Q15 turn angle of (y, x) — int16 in, int16 out.

    Pure-integer CORDIC vectoring (ops/fxp.cordic_atan2), so the result
    is bit-identical on every backend — an f32 arctan2 differs by ulps
    between CPU and TPU, which can flip the quantized angle by one
    step. Inputs are pre-scaled by 2^12 (angle-invariant; full int16
    inputs stay inside the vectoring bound) so shift truncation stays
    below a couple of Q15 steps even for unit-magnitude vectors."""
    jnp = _jnp()
    from ziria_tpu.ops import fxp
    ang, _mag = fxp.cordic_atan2(jnp.asarray(y, jnp.int32) << 12,
                                 jnp.asarray(x, jnp.int32) << 12)
    return ang.astype(jnp.int16)


# --------------------------------------------------------------------------
# integer sqrt / log2 (reference integer-math helpers)
# --------------------------------------------------------------------------


def usqrt(x):
    """floor(sqrt(x)) for non-negative int32, exact.

    f32 sqrt has enough mantissa only below 2^24, so refine the rounded
    estimate by ±1 with integer compares — branch-free, VPU-friendly.
    """
    jnp = _jnp()
    x = jnp.asarray(x, jnp.int32)
    r = jnp.sqrt(x.astype(jnp.float32)).astype(jnp.int32)
    r = jnp.maximum(r, 0)
    # correct both directions of f32 rounding with overflow-free integer
    # compares: r*r > x  ⟺  r > x//r  (r^2 would overflow int32 at the
    # top of the range, x//r never does)
    from jax import lax
    rp = r + 1
    r = jnp.where(rp <= lax.div(x, jnp.maximum(rp, 1)), rp, r)
    r = jnp.where(r > lax.div(x, jnp.maximum(r, 1)), r - 1, r)
    return r


def ulog2(x):
    """floor(log2(x)) for positive int32 (0 for x <= 1)."""
    jnp = _jnp()
    x = jnp.asarray(x, jnp.int32)
    n = jnp.zeros_like(x)
    v = x
    for shift in (16, 8, 4, 2, 1):       # unrolled binary search
        big = v >= (1 << shift)
        n = jnp.where(big, n + shift, n)
        v = jnp.where(big, v >> shift, v)
    return n


# --------------------------------------------------------------------------
# frontend externals registration
# --------------------------------------------------------------------------


def _ext_c64_to_pair(x):
    """complex16 ext boundary -> exact int32 IQ pairs (values at the
    boundary are integer-valued complex64; see dft64_fxp)."""
    jnp = _jnp()
    arr = jnp.asarray(x)
    if jnp.iscomplexobj(arr):
        return jnp.stack(
            [jnp.round(arr.real).astype(jnp.int32),
             jnp.round(arr.imag).astype(jnp.int32)], axis=-1)
    return jnp.round(arr).astype(jnp.int32)     # pair layout (defensive)


def _ext_pair_to_c64(out):
    jnp = _jnp()
    return (out[..., 0].astype(jnp.float32)
            + 1j * out[..., 1].astype(jnp.float32))


def dft64_fxp(x):
    """Integer 64-pt DFT brick for fixed-point programs: the fxp
    counterpart of the `v_fft` ext (the reference's SORA FFT was
    itself fixed-point). Declared `ext fun dft64_fxp(x: arr[64]
    complex16) : arr[64] complex16`.

    At the ext boundary complex16 arrives as complex64 carrying exact
    int16 IQ; this converts back to integer pairs, runs
    ops/fxp.dft64_q14 (split-Q14 GEMM DFT, shift 10: output = DFT *
    2^-3), and returns integer-valued complex so the requantize wrap
    at the boundary is exact. Q schedule: Q11-quantized unit-power
    samples give bins of ~2^11.2 per unit bin amplitude — inside
    int16 for channel gains up to ~4x."""
    from ziria_tpu.ops import fxp as _fxp
    return _ext_pair_to_c64(_fxp.dft64_q14(_ext_c64_to_pair(x),
                                           shift=10))


def idft64_fxp(x):
    """Integer OFDM symbol synthesis brick for fixed-point programs:
    inverse DFT with the 802.11 TIME_SCALE/64 folded into the split
    Q14 twiddles (ops/fxp.idft64_wifi_q14) — integer bins at wire
    scale in, integer time samples at the same wire scale out.
    Declared `ext fun idft64_fxp(x: arr[64] complex16) : arr[64]
    complex16`; exact at the c64 boundary like dft64_fxp."""
    from ziria_tpu.ops import fxp as _fxp
    return _ext_pair_to_c64(_fxp.idft64_wifi_q14(_ext_c64_to_pair(x)))


def register() -> None:
    from ziria_tpu.frontend.externals import register_external
    for name, fn in (
        ("sin_int16", sin_int16),
        ("cos_int16", cos_int16),
        ("atan2_int16", atan2_int16),
        ("usqrt", usqrt),
        ("ulog2", ulog2),
        ("dft64_fxp", dft64_fxp),
        ("idft64_fxp", idft64_fxp),
    ):
        register_external(name, fn)


register()
