"""Convolutional encoding (K=7, g0=133o, g1=171o) and puncturing.

Counterpart of the reference's `encoding.blk` (1/2-rate encoder +
puncturing to 2/3 and 3/4 — SURVEY.md §2.3). TPU-native: the encoder is
a binary convolution — both generator outputs computed as one
``jnp.convolve`` (integer) mod 2 over the whole bit stream, no per-bit
state machine; puncturing/depuncturing are reshape+mask index maps
precomputed per rate.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

# generator taps, delay order (tap[d] multiplies x_{k-d})
G0 = np.array([1, 0, 1, 1, 0, 1, 1], np.int32)  # 133 octal
G1 = np.array([1, 1, 1, 1, 0, 0, 1], np.int32)  # 171 octal
K = 7

# puncturing patterns over one period of coded (A,B) pairs:
# rate 1/2: keep all; rate 2/3: [A0 B0 A1 .]; rate 3/4: [A0 B0 A1 . . B2]
PUNCTURE_KEEP = {
    "1/2": np.array([1, 1], bool),
    "2/3": np.array([1, 1, 1, 0], bool),
    "3/4": np.array([1, 1, 1, 0, 0, 1], bool),
}


def conv_encode(bits) -> jnp.ndarray:
    """Rate-1/2 encode: (n,) bits -> (2n,) coded bits interleaved
    A0 B0 A1 B1 ... (encoder starts in the all-zero state)."""
    x = jnp.asarray(bits, jnp.int32)
    a = jnp.convolve(x, jnp.asarray(G0))[: x.shape[0]] % 2
    b = jnp.convolve(x, jnp.asarray(G1))[: x.shape[0]] % 2
    return jnp.stack([a, b], axis=1).reshape(-1).astype(jnp.uint8)


def puncture(coded, rate: str) -> jnp.ndarray:
    """Drop coded bits per the standard pattern for '2/3' or '3/4'
    ('1/2' is the identity). Input length must be a multiple of the
    pattern period."""
    keep = PUNCTURE_KEEP[rate]
    if rate == "1/2":
        return jnp.asarray(coded, jnp.uint8)
    coded = jnp.asarray(coded, jnp.uint8)
    p = keep.size
    if coded.shape[0] % p:
        raise ValueError(
            f"punctured block length {coded.shape[0]} not a multiple of "
            f"pattern period {p}")
    blocks = coded.reshape(-1, p)
    return blocks[:, np.flatnonzero(keep)].reshape(-1)


def depuncture(bits, rate: str, fill=0.0) -> jnp.ndarray:
    """Inverse of puncture for soft values: re-insert `fill` (erasure,
    0 LLR) at dropped positions. Works on float LLR arrays."""
    keep = PUNCTURE_KEEP[rate]
    vals = jnp.asarray(bits)
    if rate == "1/2":
        return vals
    p = keep.size
    kept = int(keep.sum())
    if vals.shape[0] % kept:
        raise ValueError(
            f"depuncture input length {vals.shape[0]} not a multiple of "
            f"kept-count {kept}")
    nblk = vals.shape[0] // kept
    out = jnp.full((nblk, p), fill, vals.dtype)
    out = out.at[:, np.flatnonzero(keep)].set(vals.reshape(nblk, kept))
    return out.reshape(-1)


def np_conv_encode_ref(bits: np.ndarray) -> np.ndarray:
    """Independent oracle: explicit shift-register loop. Tests only."""
    sr = [0] * (K - 1)
    out = []
    for b in np.asarray(bits, np.uint8):
        window = [int(b)] + sr
        a = sum(g * w for g, w in zip(G0, window)) % 2
        bb = sum(g * w for g, w in zip(G1, window)) % 2
        out += [a, bb]
        sr = window[:-1]
    return np.array(out, np.uint8)
