"""Real-pair complex arithmetic and matmul DFTs — the framework's
canonical sample representation.

Two reasons this exists:

1. It mirrors the reference: SORA carries `complex16`/`complex32` as
   integer re/im pairs, never a hardware complex type (SURVEY.md §2.2
   `numerics.c`). The TPU analogue is a trailing axis of size 2 over
   f32/bf16 (or int16 for the fixed-point path).
2. The axon TPU backend has **no complex64 support at all** — any
   complex op fails `UNIMPLEMENTED` — so jnp.complex64 may appear only
   in CPU-side test oracles, never on the device path.

FFTs on this representation are DFT matrix multiplies: at n=64 (the
802.11 symbol size) a pair of 64x64 f32 matmuls per re/im component is
exactly the MXU's shape, and batching over symbols/frames makes it one
big GEMM — faster than a generic small-FFT on TPU and the reason the
reference's SSE FFT brick maps so well here.

Convention: ``p[..., 0]`` = real, ``p[..., 1]`` = imag.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np


def cpack(re, im):
    return jnp.stack([re, im], axis=-1)


def cre(p):
    return p[..., 0]


def cim(p):
    return p[..., 1]


def conj(p):
    return jnp.stack([p[..., 0], -p[..., 1]], axis=-1)


def cmul(a, b):
    """Elementwise complex multiply of pair arrays."""
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    return jnp.stack([ar * br - ai * bi, ar * bi + ai * br], axis=-1)


def cmul_conj(a, b):
    """a * conj(b)."""
    ar, ai = a[..., 0], a[..., 1]
    br, bi = b[..., 0], b[..., 1]
    return jnp.stack([ar * br + ai * bi, ai * br - ar * bi], axis=-1)


def cscale(p, s):
    return p * jnp.asarray(s)[..., None]

def cabs2(p):
    return p[..., 0] ** 2 + p[..., 1] ** 2


def cdiv(a, b, eps: float = 1e-12):
    """a / b (pairwise); eps regularizes |b|^2 so a zero divisor (e.g. a
    dead subcarrier in an estimated channel) yields 0, not NaN."""
    num = cmul_conj(a, b)
    den = cabs2(b) + eps
    return num / den[..., None]


def cexp(theta):
    """unit phasor pair from angle(s)."""
    return jnp.stack([jnp.cos(theta), jnp.sin(theta)], axis=-1)


def cangle(p):
    return jnp.arctan2(p[..., 1], p[..., 0])


# ----------------------------------------------------------------- dft

@lru_cache(maxsize=None)
def _dft_mats(n: int, inverse: bool):
    k = np.arange(n)
    ang = 2.0 * np.pi * np.outer(k, k) / n
    sign = 1.0 if inverse else -1.0
    c = np.cos(ang).astype(np.float32)
    s = (sign * np.sin(ang)).astype(np.float32)
    if inverse:
        c /= n
        s /= n
    return c, s


def dft_pair(p, inverse: bool = False, axis: int = -2):
    """DFT along `axis` of a pair array (axis counts among the non-pair
    dims; default: the axis right before the re/im axis). numpy-fft
    convention: forward unscaled, inverse scaled by 1/n."""
    p = jnp.asarray(p)
    if axis != -2:
        p = jnp.moveaxis(p, axis, -2)
    n = p.shape[-2]
    c, s = _dft_mats(n, inverse)
    c = jnp.asarray(c)
    s = jnp.asarray(s)
    xr, xi = p[..., 0], p[..., 1]
    # W = C + iS; y = W x
    yr = xr @ c.T - xi @ s.T
    yi = xr @ s.T + xi @ c.T
    out = jnp.stack([yr, yi], axis=-1)
    if axis != -2:
        out = jnp.moveaxis(out, -2, axis)
    return out


def fft_pair(p, axis: int = -2):
    return dft_pair(p, inverse=False, axis=axis)


def ifft_pair(p, axis: int = -2):
    return dft_pair(p, inverse=True, axis=axis)


# ------------------------------------------------- host-side conversion

def from_complex(c, xp=np):
    """complex array -> pair array (host/test use)."""
    c = xp.asarray(c)
    return xp.stack([c.real, c.imag], axis=-1).astype(xp.float32)


def to_complex(p, xp=np):
    """pair array -> complex array (host/test use)."""
    p = xp.asarray(p)
    return (p[..., 0] + 1j * p[..., 1]).astype(xp.complex64)
