"""CRC-32 (the 802.11 FCS) over bit streams.

Counterpart of the reference's `crc.blk` in the TX chain (SURVEY.md
§2.3). Parameters are the standard FCS ones: polynomial 0x04C11DB7,
init all-ones, LSB-first bit order, final complement.

TPU-native design: instead of a per-bit LFSR loop, bits are grouped into
bytes and a 256-entry lookup table drives a ``lax.scan`` over bytes —
the table plays exactly the role of the reference's AutoLUT-generated
tables (SURVEY.md §2.1 AutoLUT), precomputed here at module load.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.utils.bits import bits_to_bytes, uint_to_bits

_POLY = 0xEDB88320  # 0x04C11DB7 bit-reflected (LSB-first algorithm)


def _make_table() -> np.ndarray:
    tab = np.zeros(256, np.uint32)
    for b in range(256):
        c = b
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if (c & 1) else 0)
        tab[b] = c
    return tab


_TABLE = _make_table()


def crc32_bytes(data) -> jnp.ndarray:
    """CRC-32 of a uint8 byte array; returns uint32 scalar."""
    data = jnp.asarray(data, jnp.uint8)
    tab = jnp.asarray(_TABLE)

    def step(crc, byte):
        idx = (crc ^ byte.astype(jnp.uint32)) & 0xFF
        return (crc >> 8) ^ tab[idx], None

    crc, _ = jax.lax.scan(step, jnp.uint32(0xFFFFFFFF), data)
    return crc ^ jnp.uint32(0xFFFFFFFF)


def crc32_bits(bits) -> jnp.ndarray:
    """CRC-32 of a bit stream (multiple of 8 bits, LSB-first per byte);
    returns the 32 FCS bits in transmission order (LSB-first)."""
    crc = crc32_bytes(bits_to_bytes(bits))
    return uint_to_bits(crc, 32)


def append_crc32(bits) -> jnp.ndarray:
    """Append the 32-bit FCS to a bit stream (the TX `crc` block)."""
    bits = jnp.asarray(bits, jnp.uint8)
    return jnp.concatenate([bits, crc32_bits(bits)])


def check_crc32(bits) -> jnp.ndarray:
    """True iff the trailing 32 bits are the correct FCS of the rest."""
    bits = jnp.asarray(bits, jnp.uint8)
    body, fcs = bits[:-32], bits[-32:]
    return jnp.all(crc32_bits(body) == fcs)


def crc32_bytes_masked(data, n_bytes) -> jnp.ndarray:
    """CRC-32 of the first ``n_bytes`` (TRACED int32) of a padded uint8
    byte array: the same table-driven ``lax.scan`` as
    :func:`crc32_bytes`, with steps at or past ``n_bytes`` leaving the
    register untouched — so one fixed-length compiled scan serves every
    true length, and a batch of mixed-length streams rides one ``vmap``
    (the batched-FCS dispatch of ``framebatch._mixed_decode_tail`` and
    the fused loopback link). Bit-identical to ``crc32_bytes`` of the
    unpadded prefix."""
    data = jnp.asarray(data, jnp.uint8)
    tab = jnp.asarray(_TABLE)
    n_bytes = jnp.asarray(n_bytes, jnp.int32)

    def step(crc, ji):
        j, byte = ji
        idx = (crc ^ byte.astype(jnp.uint32)) & 0xFF
        nxt = (crc >> 8) ^ tab[idx]
        return jnp.where(j < n_bytes, nxt, crc), None

    crc, _ = jax.lax.scan(
        step, jnp.uint32(0xFFFFFFFF),
        (jnp.arange(data.shape[0], dtype=jnp.int32), data))
    return crc ^ jnp.uint32(0xFFFFFFFF)


def check_crc32_masked(bits, n_bits) -> jnp.ndarray:
    """Traced-length twin of :func:`check_crc32`: ``bits`` is a padded
    bit stream whose first ``n_bits`` (TRACED int32, a multiple of 8)
    are body+FCS; returns True iff bits[n_bits-32 : n_bits] is the
    FCS of bits[: n_bits-32]. Fixed shapes — one compile per padded
    length, every true length and (under ``vmap``) every lane of a
    mixed-length batch served by it.

    A stream too short to even hold the 32-bit FCS (n_bits < 32 — a
    noise-corrupted SIGNAL claiming a 1..3-byte PSDU) reports False:
    no valid FCS can exist. (The eager :func:`check_crc32` cannot
    classify that case at all — its fixed slices raise a shape error —
    so this is the one place the masked twin is defined on strictly
    more inputs rather than bit-identical.)"""
    bits = jnp.asarray(bits, jnp.uint8)
    n_bits = jnp.asarray(n_bits, jnp.int32)
    crc = crc32_bytes_masked(bits_to_bytes(bits),
                             jnp.maximum(n_bits - 32, 0) // 8)
    fcs = jax.lax.dynamic_slice(
        bits, (jnp.maximum(n_bits - 32, 0),), (32,))
    return jnp.logical_and(n_bits >= 32,
                           jnp.all(uint_to_bits(crc, 32) == fcs))


def np_crc32_bits_ref(bits: np.ndarray) -> np.ndarray:
    """Independent oracle: per-bit LFSR, straight from the CRC definition.
    Used only by tests."""
    reg = 0xFFFFFFFF
    for bit in np.asarray(bits, np.uint8):
        fb = (reg ^ int(bit)) & 1
        reg >>= 1
        if fb:
            reg ^= _POLY
    reg ^= 0xFFFFFFFF
    return np.array([(reg >> k) & 1 for k in range(32)], np.uint8)
