"""CRC-32 (the 802.11 FCS) over bit streams.

Counterpart of the reference's `crc.blk` in the TX chain (SURVEY.md
§2.3). Parameters are the standard FCS ones: polynomial 0x04C11DB7,
init all-ones, LSB-first bit order, final complement.

TPU-native design: instead of a per-bit LFSR loop, bits are grouped into
bytes and a 256-entry lookup table drives a ``lax.scan`` over bytes —
the table plays exactly the role of the reference's AutoLUT-generated
tables (SURVEY.md §2.1 AutoLUT), precomputed here at module load.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.utils.bits import bits_to_bytes, uint_to_bits

_POLY = 0xEDB88320  # 0x04C11DB7 bit-reflected (LSB-first algorithm)


def _make_table() -> np.ndarray:
    tab = np.zeros(256, np.uint32)
    for b in range(256):
        c = b
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if (c & 1) else 0)
        tab[b] = c
    return tab


_TABLE = _make_table()


def crc32_bytes(data) -> jnp.ndarray:
    """CRC-32 of a uint8 byte array; returns uint32 scalar."""
    data = jnp.asarray(data, jnp.uint8)
    tab = jnp.asarray(_TABLE)

    def step(crc, byte):
        idx = (crc ^ byte.astype(jnp.uint32)) & 0xFF
        return (crc >> 8) ^ tab[idx], None

    crc, _ = jax.lax.scan(step, jnp.uint32(0xFFFFFFFF), data)
    return crc ^ jnp.uint32(0xFFFFFFFF)


def crc32_bits(bits) -> jnp.ndarray:
    """CRC-32 of a bit stream (multiple of 8 bits, LSB-first per byte);
    returns the 32 FCS bits in transmission order (LSB-first)."""
    crc = crc32_bytes(bits_to_bytes(bits))
    return uint_to_bits(crc, 32)


def append_crc32(bits) -> jnp.ndarray:
    """Append the 32-bit FCS to a bit stream (the TX `crc` block)."""
    bits = jnp.asarray(bits, jnp.uint8)
    return jnp.concatenate([bits, crc32_bits(bits)])


def check_crc32(bits) -> jnp.ndarray:
    """True iff the trailing 32 bits are the correct FCS of the rest."""
    bits = jnp.asarray(bits, jnp.uint8)
    body, fcs = bits[:-32], bits[-32:]
    return jnp.all(crc32_bits(body) == fcs)


def np_crc32_bits_ref(bits: np.ndarray) -> np.ndarray:
    """Independent oracle: per-bit LFSR, straight from the CRC definition.
    Used only by tests."""
    reg = 0xFFFFFFFF
    for bit in np.asarray(bits, np.uint8):
        fb = (reg ^ int(bit)) & 1
        reg >>= 1
        if fb:
            reg ^= _POLY
    reg ^= 0xFFFFFFFF
    return np.array([(reg >> k) & 1 for k in range(32)], np.uint8)
