"""802.11 data scrambler / descrambler.

Counterpart of the reference's `scramble.blk` / descrambler (SURVEY.md
§2.3). The scrambler is the 7-bit LFSR x^7 + x^4 + 1 whose output
sequence is XORed onto the data bits (additive scrambling), seeded per
frame; the same primitive with an all-ones seed generates the 127-bit
pilot-polarity sequence.

TPU-native design: x^7+x^4+1 is primitive, so every nonzero seed
generates the same maximal-length 127-bit sequence at some phase. We
scan the LFSR for exactly 127 steps (tiny), then *tile* the period over
the frame and XOR — one fused elementwise op over the whole bit stream
instead of a per-bit sequential loop. Seed recovery for the descrambler
is a 128-row precomputed table match (the SERVICE field's first 7 bits
are zero, so the received first 7 bits expose the sequence phase) —
AutoLUT-style precomputation (SURVEY.md §2.1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.utils.bits import uint_to_bits


def np_lfsr_sequence_127(seed_bits: np.ndarray) -> np.ndarray:
    """Host-side (numpy) version of the 127-bit sequence, for module-load
    constants (pilot polarity, precomputed scrambling tables) — avoids a
    JAX dispatch at import time."""
    s = list(np.asarray(seed_bits, np.uint8))
    out = []
    for _ in range(127):
        fb = s[6] ^ s[3]
        out.append(fb)
        s = [fb] + s[:6]
    return np.array(out, np.uint8)


def lfsr_sequence_127(seed_bits) -> jnp.ndarray:
    """One period (127 bits) of the scrambler sequence from a 7-bit seed.

    seed_bits: (7,) uint8, seed_bits[k] = x_{k+1} of the standard's
    initial state (seed_bits[6] is x7). Output bit t is
    x7(t) XOR x4(t); state shifts with that bit fed back into x1.
    """
    seed_bits = jnp.asarray(seed_bits, jnp.uint8)

    def step(s, _):
        fb = s[6] ^ s[3]  # x7 xor x4
        s = jnp.concatenate([fb[None], s[:6]])
        return s, fb

    _, seq = jax.lax.scan(step, seed_bits, None, length=127)
    return seq


def scramble_bits(bits, seed_bits) -> jnp.ndarray:
    """XOR the data bits with the scrambler sequence (additive)."""
    bits = jnp.asarray(bits, jnp.uint8)
    n = bits.shape[0]
    period = lfsr_sequence_127(seed_bits)
    reps = -(-n // 127)
    seq = jnp.tile(period, reps)[:n]
    return bits ^ seq


# descrambling is the same XOR
descramble_bits = scramble_bits


def _seed_table() -> np.ndarray:
    """first 7 sequence bits for every 7-bit seed (numpy at import)."""
    tab = np.zeros((128, 7), np.uint8)
    for seed in range(128):
        s = [(seed >> k) & 1 for k in range(7)]
        out = []
        for _ in range(7):
            fb = s[6] ^ s[3]
            out.append(fb)
            s = [fb] + s[:6]
        tab[seed] = out
    return tab


_SEED_TABLE = _seed_table()


def recover_seed(first7_bits) -> jnp.ndarray:
    """Recover the scrambler seed from the first 7 received (descrambler
    input) bits, which equal the sequence bits because the SERVICE field
    starts with zeros. Returns (7,) uint8 seed bits."""
    first7 = jnp.asarray(first7_bits, jnp.uint8)
    tab = jnp.asarray(_SEED_TABLE)
    match = jnp.all(tab == first7[None, :], axis=1)
    seed = jnp.argmax(match).astype(jnp.uint32)
    return uint_to_bits(seed, 7)


def np_scramble_ref(bits: np.ndarray, seed_bits: np.ndarray) -> np.ndarray:
    """Independent oracle: per-bit LFSR loop. Tests only."""
    s = list(np.asarray(seed_bits, np.uint8))
    out = []
    for b in np.asarray(bits, np.uint8):
        fb = s[6] ^ s[3]
        out.append(b ^ fb)
        s = [fb] + s[:6]
    return np.array(out, np.uint8)
