"""Pallas TPU kernel for the K=7 soft-decision Viterbi decoder.

Counterpart of the reference's SORA SSE Viterbi brick (`sora_ext_viterbi.c`,
SURVEY.md §2.2) — its ACS is parallel across SSE lanes; here the trellis
state axis (64) lives on VPU sublanes and **frames are batched across the
128 lanes**, so one ACS step is a handful of (64, 128) vector ops with the
path metrics held in a VMEM scratch accumulator for the whole time sweep
(no HBM round-trip per trellis step, unlike a lax.scan whose carry XLA may
spill).

Trellis layout trick: state ``t``'s two predecessors are the *consecutive*
states ``2*(t%32)`` and ``2*(t%32)+1`` (shift-register structure), so the
gather ``metrics[pred]`` is a reshape-(32,2,B)-and-slice, never a real
gather. Traceback avoids per-lane gathers the same way: the per-state
decision bit is selected with a one-hot sum over the state axis, and the
predecessor is computed arithmetically as ``((s & 31) << 1) | d``.

Two kernels:
  1. ACS sweep  — grid (batch_tiles, T); streams per-step decision planes
     to HBM **bit-packed 8 states per byte** ((T, 8, 128) uint8 — an 8x
     cut in the kernel's dominant HBM stream vs storing the raw (64, 128)
     plane), keeps metrics (64, 128) f32 in scratch.
  2. Traceback — grid (batch_tiles, T) with a reversed index map; walks
     the packed planes backward (one-hot row select + per-lane variable
     shift unpacks the survivor bit), one (128,)-lane state vector in
     scratch, emitting one bit plane per step.

The module-level tables come from ops/viterbi.py so the Pallas kernel and
the lax.scan reference implementation can never disagree on the trellis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ziria_tpu.ops.coding import G0, G1
from ziria_tpu.ops.viterbi import (I16_MAX, I16_MIN, N_STATES,
                                   _check_metric_dtype, quantize_llrs)

LANES = 128
_NEG = -1e30


def _branch_coeffs(dtype=jnp.float32):
    """(A0, A1, B0, B1): ±1 branch-metric coefficient columns (64, 1).

    Computed from an iota inside the trace (Pallas kernels cannot capture
    array constants); matches ops.viterbi._edge_tables exactly — the edge
    into state t with predecessor-low-bit d carries encoder window
    [b, s5..s0] where b = t>>5 and s = ((t & 31) << 1) | d.
    """
    tt = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, 1), 0)
    b = tt >> 5
    cols = []
    for d in (0, 1):
        s = ((tt & 31) << 1) | d
        win = [b] + [(s >> (5 - i)) & 1 for i in range(6)]
        for taps in (G0, G1):
            acc = sum(int(g) * w for g, w in zip(taps, win)) % 2
            cols.append((2 * acc - 1).astype(dtype))
    a0, b0, a1, b1 = cols
    return a0, a1, b0, b1


# trellis steps processed per grid step: the per-step ACS is ~15 vector
# ops on (64, 128) tiles — far too little work to amortize a Mosaic grid
# step, which made the r1 kernel grid-overhead-bound (measured 4.6 ms
# for T=8208 at B=128). Unrolling K steps into one kernel body cuts the
# grid by K at ~K x program size.
UNROLL = 64


def _pack_sel():
    """(8, 64) bit-packing matrix: sel[i, s] is (1 << (s & 7)) when s
    lives in byte i (s >> 3 == i), else 0, so sel @ dec gives byte i =
    sum_j dec[8i+j] << j exactly (all values are small ints, exact in
    f32). ONE MXU matmul per step replaces 64 row-slice VPU ops — the
    kernel is issue-bound, not FLOP-bound. Shared by both metric-dtype
    kernels so the packed decision format can never diverge."""
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (8, N_STATES), 1)
    b_idx = jax.lax.broadcasted_iota(jnp.int32, (8, N_STATES), 0)
    return jnp.where((s_idx >> 3) == b_idx,
                     (1 << (s_idx & 7)).astype(jnp.float32), 0.0)


def _acs_kernel(llr_ref, dec_ref, metrics_out_ref, m_ref):
    """UNROLL trellis time-steps for one batch tile.

    llr_ref: (1, UNROLL, 2, 128) this block's (A, B) soft inputs/lane.
    dec_ref: (1, UNROLL, 8, 128) uint8 packed decision planes out:
      byte i, bit j holds the survivor bit of state 8*i + j.
    metrics_out_ref: (64, 128) f32 — final metrics (last write wins).
    m_ref: (64, 128) f32 VMEM scratch — path metrics across the sweep.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        rows = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, LANES), 0)
        m_ref[:] = jnp.where(rows == 0, 0.0, _NEG).astype(jnp.float32)

    a0, a1, b0, b1 = _branch_coeffs()
    sel = _pack_sel()

    m = m_ref[:]                                  # (64, 128)
    for j in range(UNROLL):
        la = llr_ref[0, j, 0:1, :]                # (1, 128)
        lb = llr_ref[0, j, 1:2, :]

        pairs = m.reshape(32, 2, LANES)
        ev = jnp.concatenate([pairs[:, 0, :]] * 2, axis=0)  # pred d=0
        od = jnp.concatenate([pairs[:, 1, :]] * 2, axis=0)  # pred d=1

        cand0 = ev + a0 * la + b0 * lb
        cand1 = od + a1 * la + b1 * lb

        dec = cand1 > cand0
        m = jnp.maximum(cand0, cand1)

        packed = jax.lax.dot(sel, dec.astype(jnp.float32),
                             precision=jax.lax.Precision.HIGHEST)
        # Mosaic has no f32->u8 cast; round-trip through int32
        dec_ref[0, j] = packed.astype(jnp.int32).astype(jnp.uint8)
    # renorm once per block, not per step: decisions depend only on
    # metric *differences*, and metrics drift by at most
    # UNROLL * max|llr| between renorms — far inside f32 range
    m = m - jnp.max(m, axis=0, keepdims=True)
    m_ref[:] = m

    @pl.when(t == pl.num_programs(1) - 1)
    def _flush():
        metrics_out_ref[0] = m_ref[:]


def _acs_kernel_i16(llr_ref, dec_ref, metrics_out_ref, m_ref):
    """int16 saturating-metric ACS sweep — the SORA trade (SURVEY.md
    §2.2: the reference brick ran 16-bit path metrics across SSE
    lanes). Same trellis walk and packed decision format as
    _acs_kernel; what changes is storage width:

    llr_ref: (1, UNROLL, 2, 128) int16 — QUANTIZED soft inputs
      (ops.viterbi.quantize_llrs, |q| <= QUANT_MAX), HALF the f32
      kernel's dominant HBM input stream.
    m_ref: (64, 128) int16 VMEM scratch — half the metric footprint,
      doubling sublane density of the resident state.
    metrics_out_ref: (64, 128) int32 (traceback only argmaxes it).

    Arithmetic runs in int32 vregs across the UNROLL block (exact: the
    in-block drift is <= UNROLL * 2 * QUANT_MAX = 16256 from a
    renormed max of 0, far inside int32); the once-per-block renorm
    pins the max at 0 and the store back to int16 SATURATES — which
    only ever clips unreachable/floored states, never the surviving
    path (docs/quantized_viterbi.md has the bound), so the decode
    matches the f32 kernel bit-for-bit on the same quantized inputs.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        rows = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, LANES), 0)
        m_ref[:] = jnp.where(rows == 0, 0, I16_MIN).astype(jnp.int16)

    a0, a1, b0, b1 = _branch_coeffs(jnp.int32)
    sel = _pack_sel()

    m = m_ref[:].astype(jnp.int32)                # (64, 128)
    for j in range(UNROLL):
        la = llr_ref[0, j, 0:1, :].astype(jnp.int32)   # (1, 128)
        lb = llr_ref[0, j, 1:2, :].astype(jnp.int32)

        pairs = m.reshape(32, 2, LANES)
        ev = jnp.concatenate([pairs[:, 0, :]] * 2, axis=0)  # pred d=0
        od = jnp.concatenate([pairs[:, 1, :]] * 2, axis=0)  # pred d=1

        cand0 = ev + a0 * la + b0 * lb
        cand1 = od + a1 * la + b1 * lb

        dec = cand1 > cand0
        m = jnp.maximum(cand0, cand1)

        packed = jax.lax.dot(sel, dec.astype(jnp.float32),
                             precision=jax.lax.Precision.HIGHEST)
        dec_ref[0, j] = packed.astype(jnp.int32).astype(jnp.uint8)
    m = m - jnp.max(m, axis=0, keepdims=True)
    m_ref[:] = jnp.clip(m, I16_MIN, I16_MAX).astype(jnp.int16)

    @pl.when(t == pl.num_programs(1) - 1)
    def _flush():
        metrics_out_ref[0] = m_ref[:].astype(jnp.int32)


def _traceback_kernel(dec_ref, metrics_ref, bits_ref, s_ref):
    """UNROLL backward steps: select the survivor decision at the
    current state (one-hot sum — no per-lane gather), emit the decoded
    bit, move to the predecessor.

    dec_ref: (1, UNROLL, 8, 128) packed decision planes for trellis
      steps [T-(t+1)*UNROLL, T-t*UNROLL), walked in reverse within the
      block.
    metrics_ref: (64, 128) final path metrics (used only at t == 0).
    bits_ref: (1, UNROLL, 8, 128) int32 out — decoded bit planes, row 0
      of each (8, 128) plane carries it (8 sublanes keeps the store
      tile-aligned).
    s_ref: (8, 128) int32 scratch — row 0 is the current state per lane.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        end = jnp.argmax(metrics_ref[0], axis=0).astype(jnp.int32)  # (128,)
        s_ref[:] = jnp.broadcast_to(end[None, :], (8, LANES))

    rows = jax.lax.broadcasted_iota(jnp.int32, (8, LANES), 0)
    state = s_ref[0:1, :]                              # (1, 128)
    for j in reversed(range(UNROLL)):
        packed = dec_ref[0, j].astype(jnp.int32)       # (8, 128)
        onehot = (rows == (state >> 3)).astype(jnp.int32)  # byte row
        byte = jnp.sum(packed * onehot, axis=0, keepdims=True)  # (1,128)
        d = (byte >> (state & 7)) & 1                  # unpack bit

        bits_ref[0, j] = jnp.broadcast_to(state >> 5, (8, LANES))
        state = ((state & 31) << 1) | d
    s_ref[0:1, :] = state


def _interpret_default() -> bool:
    # the axon-tunnelled chip registers its backend as 'tpu' (verified:
    # Mosaic compiles these kernels there), so this only falls back to
    # interpret mode on genuinely non-TPU backends (CPU tests)
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("interpret", "metric_dtype"))
def _decode_tiles(llrs, interpret: bool, metric_dtype: str = "float32"):
    """(nb, T, 2, 128) f32|int16 -> (nb, T, 128) uint8 decoded bit
    planes. ``metric_dtype`` picks the ACS kernel: "float32" (oracle/
    default, f32 llr tiles) or "int16" (quantized llr tiles, int16
    saturating metrics)."""
    i16 = metric_dtype == "int16"
    nb, T = llrs.shape[0], llrs.shape[1]
    # pad the trellis to a multiple of UNROLL with zero LLRs (erasures:
    # they add no likelihood, so the surviving path over the real prefix
    # is unchanged); the garbage pad bits are sliced off below
    Tp = -(-T // UNROLL) * UNROLL
    if Tp != T:
        llrs = jnp.pad(llrs, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))
    TB = Tp // UNROLL                       # grid blocks per trellis

    dec, metrics = pl.pallas_call(
        _acs_kernel_i16 if i16 else _acs_kernel,
        grid=(nb, TB),
        in_specs=[pl.BlockSpec((1, UNROLL, 2, LANES),
                               lambda b, t: (b, t, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, UNROLL, 8, LANES), lambda b, t: (b, t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.uint8),
            jax.ShapeDtypeStruct((nb, N_STATES, LANES),
                                 jnp.int32 if i16 else jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((N_STATES, LANES),
                                   jnp.int16 if i16 else jnp.float32)],
        interpret=interpret,
    )(llrs)

    bits = pl.pallas_call(
        _traceback_kernel,
        grid=(nb, TB),
        in_specs=[
            pl.BlockSpec((1, UNROLL, 8, LANES),
                         lambda b, t, _n=TB: (b, _n - 1 - t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, UNROLL, 8, LANES),
                               lambda b, t, _n=TB: (b, _n - 1 - t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, LANES), jnp.int32)],
        interpret=interpret,
    )(dec, metrics)

    return bits[:, :T, 0, :].astype(jnp.uint8)


def viterbi_decode_batch(llrs, n_bits: int = None, interpret: bool = None,
                         metric_dtype: str = None):
    """Batched soft decode: llrs (B, T, 2) or (B, 2T) -> (B, T) bits.

    Same contract as ops.viterbi.viterbi_decode but over a whole batch of
    frames — the bench/TPU fast path. Lanes are padded to a multiple of
    128 with zero LLRs (erasures), which decode to garbage in the pad
    lanes and are sliced off.

    ``metric_dtype="int16"`` quantizes the LLRs at the kernel boundary
    (ops.viterbi.quantize_llrs, PER-frame scale) and runs the int16
    saturating-metric ACS kernel: half the llr HBM stream, half the
    metric VMEM footprint. Already-int16 input is taken as
    pre-quantized and passed through untouched (the windowed decode
    quantizes before cutting windows). Default/"float32" is the exact
    oracle kernel.
    """
    if interpret is None:
        interpret = _interpret_default()
    md = _check_metric_dtype(metric_dtype)
    llrs = jnp.asarray(llrs)
    if llrs.ndim == 2:
        llrs = llrs.reshape(llrs.shape[0], -1, 2)
    if md != "int16":
        llrs = llrs.astype(jnp.float32)
    elif llrs.dtype != jnp.int16:
        llrs, _scale = quantize_llrs(llrs)              # int16 (B, T, 2)
    B, T = llrs.shape[0], llrs.shape[1]
    Bp = -(-B // LANES) * LANES
    # (B, T, 2) -> (T, 2, B) -> lane tiles (nb, T, 2, 128)
    x = jnp.transpose(llrs, (1, 2, 0))
    x = jnp.pad(x, ((0, 0), (0, 0), (0, Bp - B)))
    x = x.reshape(T, 2, Bp // LANES, LANES).transpose(2, 0, 1, 3)
    bits = _decode_tiles(x, interpret, md)              # (nb, T, 128)
    bits = bits.transpose(0, 2, 1).reshape(Bp, T)[:B]
    if n_bits is not None:
        bits = bits[:, :n_bits]
    return bits


DEFAULT_WINDOW_OVERLAP = 96   # ~14 constraint lengths of warmup


def viterbi_decode_batch_opt(llrs, n_bits: int = None,
                             window: int = None,
                             interpret: bool = None,
                             metric_dtype: str = None):
    """ONE dispatch for the batch decode's window/metric options
    (review r5: the if/else was copied at every call site):
    ``window=None/0`` runs the exact kernel, ``window=N`` the
    sliding-window parallel decode below; ``metric_dtype`` selects the
    f32 oracle or int16 saturating kernel either way."""
    if window:
        return viterbi_decode_batch_windowed(
            llrs, n_bits=n_bits, window=window, interpret=interpret,
            metric_dtype=metric_dtype)
    return viterbi_decode_batch(llrs, n_bits=n_bits, interpret=interpret,
                                metric_dtype=metric_dtype)


def viterbi_decode_batch_windowed(llrs, n_bits: int = None,
                                  window: int = 1024,
                                  overlap: int = DEFAULT_WINDOW_OVERLAP,
                                  interpret: bool = None,
                                  metric_dtype: str = None,
                                  _decode=None):
    """Sliding-window PARALLEL decode: cut the T-step dependency chain
    into ceil(T/window) overlapping windows and run them as EXTRA BATCH
    LANES of the same kernel.

    The full-frame decode is dependency-chain-bound on TPU: 64 states
    fill half a VPU sublane tile while T (~8k for a 1000-byte frame)
    ACS steps run strictly sequentially, leaving the chip ~96% idle at
    B=128 (BENCH r4 roofline). Windowing converts that serial depth
    into batch parallelism: sequential depth drops from T to
    window + 2*overlap, and B*nwin lanes fill the idle lane tiles.

    Accuracy is the standard truncated-Viterbi argument (the
    reference's SORA brick likewise decodes with finite traceback
    depth): survivor paths of a K=7 code merge within ~5-10 constraint
    lengths with overwhelming probability, so each window's kept
    region [overlap, overlap+window) is decoded from fully-merged
    survivors; ``overlap`` defaults to 96 ≈ 14 constraint lengths.
    Boundary semantics match the full decode exactly where it matters:
    window 0 starts at position 0 with the kernel's known-state-0 init
    (its span is [0, window+2*overlap) and it keeps [0, window)), and
    every window ends on argmax metrics like the full decode; frames
    short enough for one window fall through to the exact path. On
    clean or operating-SNR inputs the output is bit-identical to
    ``viterbi_decode_batch`` (pinned by tests); on arbitrary
    adversarial inputs it is the windowed approximation, which is why
    this is an opt-in variant rather than the default.
    """
    if interpret is None:
        interpret = _interpret_default()
    md = _check_metric_dtype(metric_dtype)
    if _decode is None:
        # the production engine; tools/windowed_ber.py injects the
        # lax.scan engine so the BER study measures exactly this
        # windowing math without interpret-mode Pallas cost on CPU
        def _decode(x):
            return viterbi_decode_batch(x, interpret=interpret,
                                        metric_dtype=md)
    llrs = jnp.asarray(llrs)
    if llrs.ndim == 2:
        llrs = llrs.reshape(llrs.shape[0], -1, 2)
    if md == "int16":
        # quantize PER FRAME **before** cutting windows: every window
        # then slices the exact integers the full-frame decode sees
        # (the batch decode passes int16 through untouched), so
        # windowed int16 == full int16 by the same survivor-merge
        # argument as f32 — and no lane's scale depends on its
        # batch-mates. An injected _decode must accept int16 input.
        if llrs.dtype != jnp.int16:
            llrs, _scale = quantize_llrs(llrs)
    else:
        llrs = llrs.astype(jnp.float32)
    B, T = llrs.shape[0], llrs.shape[1]
    ext = window + 2 * overlap
    if T <= ext:
        bits = _decode(llrs)
        return bits[:, :n_bits] if n_bits is not None else bits
    nwin = -(-T // window)
    starts = np.arange(nwin) * window - overlap
    starts[0] = 0            # window 0 keeps the known-state-0 start
    idx = jnp.asarray(starts)[:, None] + jnp.arange(ext)[None, :]
    # out-of-frame positions become zero-LLR erasures — the same
    # "adds no likelihood" padding the full decode uses for T%UNROLL.
    # idx >= 0 matters when window < overlap (review r5): without it,
    # negative warmup positions clip to 0 and feed repeated
    # full-confidence position-0 LLRs into the warmup instead of
    # neutral erasures
    valid = (idx >= 0) & (idx < T)
    wins = jnp.where(valid[None, :, :, None],
                     llrs[:, jnp.clip(idx, 0, T - 1), :],
                     jnp.zeros((), llrs.dtype))
    bits = _decode(wins.reshape(B * nwin, ext, 2))
    bits = bits.reshape(B, nwin, ext)
    keep = (jnp.where(jnp.arange(nwin) == 0, 0, overlap)[:, None]
            + jnp.arange(window)[None, :])             # (nwin, window)
    bits = jnp.take_along_axis(
        bits, jnp.broadcast_to(keep[None], (B, nwin, window)), axis=2)
    bits = bits.reshape(B, nwin * window)[:, :T]
    if n_bits is not None:
        bits = bits[:, :n_bits]
    return bits
