"""Pallas TPU kernel for the K=7 soft-decision Viterbi decoder.

Counterpart of the reference's SORA SSE Viterbi brick (`sora_ext_viterbi.c`,
SURVEY.md §2.2) — its ACS is parallel across SSE lanes; here the trellis
state axis (64) lives on VPU sublanes and **frames are batched across the
128 lanes**, so one ACS step is a handful of (64, 128) vector ops with the
path metrics held in a VMEM scratch accumulator for the whole time sweep
(no HBM round-trip per trellis step, unlike a lax.scan whose carry XLA may
spill).

Trellis layout trick: state ``t``'s two predecessors are the *consecutive*
states ``2*(t%32)`` and ``2*(t%32)+1`` (shift-register structure), so the
gather ``metrics[pred]`` is a reshape-(32,2,B)-and-slice, never a real
gather. The radix-4 sweep extends it one level: ``t``'s four
grand-predecessors are the consecutive states ``4*(t%16)+j``, a
reshape-(16,4,B)-and-slice. Traceback avoids per-lane gathers the same
way: the per-state decision bit is selected with a one-hot sum over the
state axis, and the predecessor is computed arithmetically as
``((s & 31) << 1) | d``.

Three stacked levers on the ACS sweep (ISSUE 6 — the decode core is
dependency-chain-bound, not FLOP-bound, so every lever attacks issue
count or serial depth):

- **radix-4** (``radix=4``): TWO trellis steps per kernel iteration,
  butterfly pairs collapsed into a 4-way compare and both decision
  planes packed by ONE MXU matmul — half the sequential m -> m
  dependency chains per trellis step, decode bit-identical to radix 2
  at float32 and int16 (derivation at `_acs_pair_r4` /
  `_acs_pair_lut_int`).
- **LUT branch metrics** (the int paths): a step's branch metric is one
  of only FOUR values ±la±lb, so the per-state coefficient multiplies
  collapse into a 4-entry (16-entry for a radix-4 pair) combo table
  gathered per state with a one-hot MXU dot (`_lut_sel`) — Sora's
  precomputed branch-metric tables, TPU-shaped (`core/autolut.py`'s
  table-gather rewrite, lowered onto the MXU because Mosaic has no
  cheap per-sublane gather).
- **int8 saturating metrics** (``metric_dtype="int8"``): metrics resident
  as (64, 128) int8 — half the int16 path's VMEM state again — with
  soft inputs quantized to ±INT8_QUANT_MAX. The shallow int8 rail makes
  this a statistical trade (BER envelope), not a bit-identity one; see
  ops/viterbi.py and docs/quantized_viterbi.md §int8.

On top, the **fused front end** (`viterbi_decode_batch_fused`): demap +
deinterleave + depuncture run as an in-kernel prologue over the symbol
tile (`_make_fused_acs_kernel`), so the DATA LLRs are produced and
consumed in VMEM and never round-trip HBM between the receiver's
front-end dispatch and the ACS — the kernel's dominant HBM input stream
drops from 2 f32 LLRs per trellis step to the raw equalized subcarriers
(~4-9x smaller at the high rates). Its rate-SWITCHED twin
(`viterbi_decode_mixed_fused`) extends the prologue to the mixed-rate
decode every fleet surface runs: all 8 rates' slot tables stacked into
one static constant bank, row-selected per lane in-kernel from the
traced rate index.

Two kernels either way:
  1. ACS sweep  — grid (batch_tiles, T); streams per-step decision planes
     to HBM **bit-packed 8 states per byte** ((T, 8, 128) uint8 — an 8x
     cut in the kernel's dominant HBM stream vs storing the raw (64, 128)
     plane), keeps metrics (64, 128) in scratch.
  2. Traceback — grid (batch_tiles, T) with a reversed index map; walks
     the packed planes backward (one-hot row select + per-lane variable
     shift unpacks the survivor bit), one (128,)-lane state vector in
     scratch, emitting one bit plane per step.

The module-level tables come from ops/viterbi.py so the Pallas kernel and
the lax.scan reference implementation can never disagree on the trellis.
"""

from __future__ import annotations

import functools
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ziria_tpu.ops.coding import G0, G1
from ziria_tpu.ops.viterbi import (I8_MAX, I8_MIN, I16_MAX, I16_MIN,
                                   INT8_QUANT_MAX, N_STATES, QUANT_MAX,
                                   _check_metric_dtype, _check_radix,
                                   quantize_llrs)

LANES = 128
_NEG = -1e30
_HI = jax.lax.Precision.HIGHEST


def _edge_window(state, d):
    """Encoder window [b, s5..s0] of the edge into `state` with
    pred-low-bit `d` (iota-friendly: `state` may be a traced column).
    Matches ops.viterbi._edge_tables exactly."""
    b = state >> 5
    s = ((state & 31) << 1) | d
    return [b] + [(s >> (5 - i)) & 1 for i in range(6)]


def _edge_parities(state, d):
    """(acc_a, acc_b): the two coded output bits of that edge."""
    win = _edge_window(state, d)
    return tuple(sum(int(g) * w for g, w in zip(taps, win)) % 2
                 for taps in (G0, G1))


def _branch_coeffs(dtype=jnp.float32):
    """(A0, A1, B0, B1): ±1 branch-metric coefficient columns (64, 1).

    Computed from an iota inside the trace (Pallas kernels cannot capture
    array constants); matches ops.viterbi._edge_tables exactly — the edge
    into state t with predecessor-low-bit d carries encoder window
    [b, s5..s0] where b = t>>5 and s = ((t & 31) << 1) | d.
    """
    tt = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, 1), 0)
    cols = []
    for d in (0, 1):
        for acc in _edge_parities(tt, d):
            cols.append((2 * acc - 1).astype(dtype))
    a0, b0, a1, b1 = cols
    return a0, a1, b0, b1


def _branch_coeffs_r4(dtype=jnp.float32):
    """Radix-4 coefficient columns (64, 1): for final state t and
    grand-predecessor selector j = (d2 << 1) | d1 the two-step path is
    step 1 into intermediate state u = ((t & 31) << 1) | d2 with
    pred-low-bit d1, then step 2 into t with pred-low-bit d2 (so t's
    grand-predecessor is the consecutive state 4*(t & 15) + j).
    Returns (step1, step2): step1[j] = (a, b) columns of the step-1
    edge, step2[d2] = those of the step-2 edge — the same VALUES
    _branch_coeffs computes, re-indexed, so the radix-4 candidates are
    expression-for-expression the radix-2 ones."""
    tt = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, 1), 0)

    def cols(state, d):
        return tuple((2 * acc - 1).astype(dtype)
                     for acc in _edge_parities(state, d))

    step1 = [cols(((tt & 31) << 1) | (j >> 1), j & 1) for j in range(4)]
    step2 = [cols(tt, d2) for d2 in (0, 1)]
    return step1, step2


def _branch_pattern(state, d):
    """Sign-pattern index of that edge's branch metric in the
    `_combos4` row order: 0 = la+lb, 1 = la-lb, 2 = -la+lb,
    3 = -la-lb (a = +1 exactly when acc = 1)."""
    acc_a, acc_b = _edge_parities(state, d)
    return (1 - acc_a) * 2 + (1 - acc_b)


def _branch_patterns_r4():
    """Combined 2-step pattern index columns (64, 1) int32 per
    grand-predecessor selector j: pat1 * 4 + pat2, indexing the
    16-entry outer-sum combo table of `_acs_pair_lut_int`."""
    tt = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, 1), 0)
    pats = []
    for j in range(4):
        d2, d1 = j >> 1, j & 1
        u = ((tt & 31) << 1) | d2
        pats.append(_branch_pattern(u, d1) * 4 + _branch_pattern(tt, d2))
    return pats


def _lut_sel(pat, n: int):
    """(64, n) f32 one-hot rows selecting combo row ``pat[t]`` per
    state — the branch-metric "table lookup" lowered onto the MXU:
    ``sel @ combos`` gathers every state's metric in ONE matmul
    (exact: each row sums a single value * 1.0). Sora's LUT
    discipline, TPU-shaped — `core/autolut.py` rewrites small-domain
    maps into table gathers; inside a Mosaic kernel the gather is a
    one-hot dot because there is no cheap per-sublane gather."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, n), 1)
    return (cols == pat).astype(jnp.float32)


def _combos4(la, lb):
    """(4, LANES) int32 branch-metric table of one trellis step: the
    only four values ±la±lb can take, in `_branch_pattern`'s row
    order. Two adds + two negates replace 64-state coefficient
    multiplies; `_lut_sel` dots gather per state."""
    s = la + lb
    d = la - lb
    return jnp.concatenate([s, d, -d, -s], axis=0)


# trellis steps processed per grid step: the per-step ACS is ~15 vector
# ops on (64, 128) tiles — far too little work to amortize a Mosaic grid
# step, which made the r1 kernel grid-overhead-bound (measured 4.6 ms
# for T=8208 at B=128). Unrolling K steps into one kernel body cuts the
# grid by K at ~K x program size.
UNROLL = 64


def _pack_sel():
    """(8, 64) bit-packing matrix: sel[i, s] is (1 << (s & 7)) when s
    lives in byte i (s >> 3 == i), else 0, so sel @ dec gives byte i =
    sum_j dec[8i+j] << j exactly (all values are small ints, exact in
    f32). ONE MXU matmul per step replaces 64 row-slice VPU ops — the
    kernel is issue-bound, not FLOP-bound. Shared by every ACS kernel
    so the packed decision format can never diverge."""
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (8, N_STATES), 1)
    b_idx = jax.lax.broadcasted_iota(jnp.int32, (8, N_STATES), 0)
    return jnp.where((s_idx >> 3) == b_idx,
                     (1 << (s_idx & 7)).astype(jnp.float32), 0.0)


def _pack_planes(pack, *decs):
    """Bit-pack one or two (64, LANES) bool decision planes with a
    SINGLE MXU matmul (planes concatenated along lanes — the radix-4
    "2 steps per write"). Returns the (8, LANES) uint8 plane(s)."""
    cat = decs[0].astype(jnp.float32) if len(decs) == 1 else \
        jnp.concatenate([d.astype(jnp.float32) for d in decs], axis=1)
    packed = jax.lax.dot(pack, cat, precision=_HI)
    # Mosaic has no f32->u8 cast; round-trip through int32
    packed = packed.astype(jnp.int32).astype(jnp.uint8)
    return packed if len(decs) == 1 else \
        tuple(packed[:, i * LANES:(i + 1) * LANES]
              for i in range(len(decs)))


# ------------------------------------------------------------ step bodies
#
# Shared by the plain lane-tile kernels and the fused front-end kernel,
# so a radix/metric combination has exactly ONE arithmetic definition.


def _acs_step_f32(m, la, lb, coeffs, pack):
    """One radix-2 f32 ACS step: (new metrics, packed decision plane).
    The oracle step body every other variant is judged against."""
    a0, a1, b0, b1 = coeffs
    pairs = m.reshape(32, 2, LANES)
    ev = jnp.concatenate([pairs[:, 0, :]] * 2, axis=0)  # pred d=0
    od = jnp.concatenate([pairs[:, 1, :]] * 2, axis=0)  # pred d=1
    cand0 = ev + a0 * la + b0 * lb
    cand1 = od + a1 * la + b1 * lb
    dec = cand1 > cand0
    m = jnp.maximum(cand0, cand1)
    return m, _pack_planes(pack, dec)


def _interleave_dec1(cA, cB):
    """Re-index the radix-4 step-1 comparisons from final-state rows t
    to intermediate-state rows u: u = 2*(t & 31) + d2, and rows
    [32:64) duplicate [0:32) (same intermediate states), so the plane
    is the 2-way interleave of the first 32 rows of each."""
    return jnp.concatenate([cA[:32, None, :], cB[:32, None, :]],
                           axis=1).reshape(N_STATES, LANES)


def _acs_pair_r4_f32(m, la1, lb1, la2, lb2, step1, step2, pack):
    """TWO radix-2 f32 steps as one 4-way butterfly, bit-identical to
    `_acs_step_f32` twice. p[2*d2+d1][t] is built with the exact
    radix-2 expression shape ``g + a*la + b*lb``, so it equals the
    radix-2 step-1 candidate at intermediate state u(t, d2) bit for
    bit; max() commutes with the identically-applied (monotone)
    step-2 adds, so the step-2 comparison and metrics also match bit
    for bit. What radix-4 saves is serial structure: one
    reshape/concat fan-out of m instead of two, one packing matmul
    for both decision planes, and the second step's adds no longer
    wait on a reshape of the first step's max."""
    quads = m.reshape(16, 4, LANES)
    p = []
    for j in range(4):
        g = jnp.concatenate([quads[:, j, :]] * 4, axis=0)
        a, b = step1[j]
        p.append(g + a * la1 + b * lb1)
    dec1 = _interleave_dec1(p[1] > p[0], p[3] > p[2])
    m01 = jnp.maximum(p[0], p[1])      # == m1[u(t, 0)] per row t
    m23 = jnp.maximum(p[2], p[3])      # == m1[u(t, 1)]
    (a0, b0), (a1, b1) = step2
    cand0 = m01 + a0 * la2 + b0 * lb2
    cand1 = m23 + a1 * la2 + b1 * lb2
    dec2 = cand1 > cand0
    m = jnp.maximum(cand0, cand1)
    pk1, pk2 = _pack_planes(pack, dec1, dec2)
    return m, pk1, pk2


def _acs_step_lut_int(m, la, lb, sels4, pack):
    """One radix-2 integer ACS step with LUT branch metrics: the
    4-entry ±la±lb table (`_combos4`) gathered per state by one-hot
    MXU dots. Integer arithmetic is exact, so decisions equal the
    coefficient-multiply step's bit for bit."""
    s4 = _combos4(la, lb).astype(jnp.float32)
    pairs = m.reshape(32, 2, LANES)
    ev = jnp.concatenate([pairs[:, 0, :]] * 2, axis=0)
    od = jnp.concatenate([pairs[:, 1, :]] * 2, axis=0)
    cand0 = ev + jax.lax.dot(sels4[0], s4, precision=_HI).astype(jnp.int32)
    cand1 = od + jax.lax.dot(sels4[1], s4, precision=_HI).astype(jnp.int32)
    dec = cand1 > cand0
    m = jnp.maximum(cand0, cand1)
    return m, _pack_planes(pack, dec)


def _acs_pair_lut_int(m, la1, lb1, la2, lb2, sels16, pack):
    """TWO integer trellis steps as one 4-way butterfly with COMBINED
    2-step LUT branch metrics: the 16 possible values of
    (±la1±lb1) + (±la2±lb2) are built once as an outer sum of the two
    4-entry step tables and gathered per state with one-hot MXU dots.
    Exact integers make every comparison identical to two radix-2
    steps: the step-1 plane compares candidates whose shared step-2
    term cancels, the step-2 plane compares the d1-maxima (max
    distributes over the common addend), and the pair's metrics equal
    the two-step result — so int16/int8 radix-4 decodes are
    bit-identical to their radix-2 twins by construction. The serial
    m -> m chain per 2 steps drops to concat -> add -> max -> max."""
    s1 = _combos4(la1, lb1)
    s2 = _combos4(la2, lb2)
    s16 = (s1.reshape(4, 1, LANES) + s2.reshape(1, 4, LANES)
           ).reshape(16, LANES).astype(jnp.float32)
    quads = m.reshape(16, 4, LANES)
    cand = []
    for j in range(4):
        g = jnp.concatenate([quads[:, j, :]] * 4, axis=0)
        bm = jax.lax.dot(sels16[j], s16, precision=_HI)
        cand.append(g + bm.astype(jnp.int32))
    dec1 = _interleave_dec1(cand[1] > cand[0], cand[3] > cand[2])
    m01 = jnp.maximum(cand[0], cand[1])
    m23 = jnp.maximum(cand[2], cand[3])
    dec2 = m23 > m01
    m = jnp.maximum(m01, m23)
    pk1, pk2 = _pack_planes(pack, dec1, dec2)
    return m, pk1, pk2


# ------------------------------------------------------------ ACS kernels


def _acs_kernel(llr_ref, dec_ref, metrics_out_ref, m_ref):
    """UNROLL trellis time-steps for one batch tile (f32, radix 2 —
    the oracle kernel).

    llr_ref: (1, UNROLL, 2, 128) this block's (A, B) soft inputs/lane.
    dec_ref: (1, UNROLL, 8, 128) uint8 packed decision planes out:
      byte i, bit j holds the survivor bit of state 8*i + j.
    metrics_out_ref: (64, 128) f32 — final metrics (last write wins).
    m_ref: (64, 128) f32 VMEM scratch — path metrics across the sweep.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        rows = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, LANES), 0)
        m_ref[:] = jnp.where(rows == 0, 0.0, _NEG).astype(jnp.float32)

    coeffs = _branch_coeffs()
    pack = _pack_sel()

    m = m_ref[:]                                  # (64, 128)
    for j in range(UNROLL):
        la = llr_ref[0, j, 0:1, :]                # (1, 128)
        lb = llr_ref[0, j, 1:2, :]
        m, packed = _acs_step_f32(m, la, lb, coeffs, pack)
        dec_ref[0, j] = packed
    # renorm once per block, not per step: decisions depend only on
    # metric *differences*, and metrics drift by at most
    # UNROLL * max|llr| between renorms — far inside f32 range
    m = m - jnp.max(m, axis=0, keepdims=True)
    m_ref[:] = m

    @pl.when(t == pl.num_programs(1) - 1)
    def _flush():
        metrics_out_ref[0] = m_ref[:]


def _acs_kernel_r4(llr_ref, dec_ref, metrics_out_ref, m_ref):
    """Radix-4 f32 ACS sweep: UNROLL trellis steps as UNROLL/2
    butterfly pairs — bit-identical to `_acs_kernel` (the pair body
    derives it) with HALF the sequential m -> m fan-out/renorm
    structure per trellis step and one packing matmul per pair."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        rows = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, LANES), 0)
        m_ref[:] = jnp.where(rows == 0, 0.0, _NEG).astype(jnp.float32)

    step1, step2 = _branch_coeffs_r4()
    pack = _pack_sel()

    m = m_ref[:]
    for j in range(UNROLL // 2):
        la1 = llr_ref[0, 2 * j, 0:1, :]
        lb1 = llr_ref[0, 2 * j, 1:2, :]
        la2 = llr_ref[0, 2 * j + 1, 0:1, :]
        lb2 = llr_ref[0, 2 * j + 1, 1:2, :]
        m, pk1, pk2 = _acs_pair_r4_f32(m, la1, lb1, la2, lb2,
                                       step1, step2, pack)
        dec_ref[0, 2 * j] = pk1
        dec_ref[0, 2 * j + 1] = pk2
    m = m - jnp.max(m, axis=0, keepdims=True)
    m_ref[:] = m

    @pl.when(t == pl.num_programs(1) - 1)
    def _flush():
        metrics_out_ref[0] = m_ref[:]


def _acs_kernel_i16(llr_ref, dec_ref, metrics_out_ref, m_ref):
    """int16 saturating-metric ACS sweep — the SORA trade (SURVEY.md
    §2.2: the reference brick ran 16-bit path metrics across SSE
    lanes). Same trellis walk and packed decision format as
    _acs_kernel; what changes is storage width:

    llr_ref: (1, UNROLL, 2, 128) int16 — QUANTIZED soft inputs
      (ops.viterbi.quantize_llrs, |q| <= QUANT_MAX), HALF the f32
      kernel's dominant HBM input stream.
    m_ref: (64, 128) int16 VMEM scratch — half the metric footprint,
      doubling sublane density of the resident state.
    metrics_out_ref: (64, 128) int32 (traceback only argmaxes it).

    Arithmetic runs in int32 vregs across the UNROLL block (exact: the
    in-block drift is <= UNROLL * 2 * QUANT_MAX = 16256 from a
    renormed max of 0, far inside int32); the once-per-block renorm
    pins the max at 0 and the store back to int16 SATURATES — which
    only ever clips unreachable/floored states, never the surviving
    path (docs/quantized_viterbi.md has the bound), so the decode
    matches the f32 kernel bit-for-bit on the same quantized inputs.
    """
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        rows = jax.lax.broadcasted_iota(jnp.int32, (N_STATES, LANES), 0)
        m_ref[:] = jnp.where(rows == 0, 0, I16_MIN).astype(jnp.int16)

    a0, a1, b0, b1 = _branch_coeffs(jnp.int32)
    pack = _pack_sel()

    m = m_ref[:].astype(jnp.int32)                # (64, 128)
    for j in range(UNROLL):
        la = llr_ref[0, j, 0:1, :].astype(jnp.int32)   # (1, 128)
        lb = llr_ref[0, j, 1:2, :].astype(jnp.int32)
        m, packed = _acs_step_f32(m, la, lb, (a0, a1, b0, b1), pack)
        dec_ref[0, j] = packed
    m = m - jnp.max(m, axis=0, keepdims=True)
    m_ref[:] = jnp.clip(m, I16_MIN, I16_MAX).astype(jnp.int16)

    @pl.when(t == pl.num_programs(1) - 1)
    def _flush():
        metrics_out_ref[0] = m_ref[:].astype(jnp.int32)


def _make_acs_kernel_int_lut(radix: int, lo: int, hi: int, sdtype):
    """Integer LUT-branch-metric ACS kernel factory: radix 2 or 4,
    saturation rails (lo, hi) and scratch dtype select the int16 or
    int8 storage discipline. Arithmetic is int32 in-block either way
    (exact — decisions can never round); the once-per-block renorm
    pins the max at 0 and the store saturates into [lo, hi]. For
    int16 that clip provably never touches the surviving path; for
    int8 the rail is shallow and the contract is the BER envelope
    (docs/quantized_viterbi.md §int8)."""

    def kernel(llr_ref, dec_ref, metrics_out_ref, m_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (N_STATES, LANES), 0)
            m_ref[:] = jnp.where(rows == 0, 0, lo).astype(sdtype)

        pack = _pack_sel()
        m = m_ref[:].astype(jnp.int32)
        if radix == 2:
            sels4 = [_lut_sel(_branch_pattern(
                jax.lax.broadcasted_iota(jnp.int32, (N_STATES, 1), 0),
                d), 4) for d in (0, 1)]
            for j in range(UNROLL):
                la = llr_ref[0, j, 0:1, :].astype(jnp.int32)
                lb = llr_ref[0, j, 1:2, :].astype(jnp.int32)
                m, packed = _acs_step_lut_int(m, la, lb, sels4, pack)
                dec_ref[0, j] = packed
        else:
            sels16 = [_lut_sel(p, 16) for p in _branch_patterns_r4()]
            for j in range(UNROLL // 2):
                la1 = llr_ref[0, 2 * j, 0:1, :].astype(jnp.int32)
                lb1 = llr_ref[0, 2 * j, 1:2, :].astype(jnp.int32)
                la2 = llr_ref[0, 2 * j + 1, 0:1, :].astype(jnp.int32)
                lb2 = llr_ref[0, 2 * j + 1, 1:2, :].astype(jnp.int32)
                m, pk1, pk2 = _acs_pair_lut_int(m, la1, lb1, la2, lb2,
                                                sels16, pack)
                dec_ref[0, 2 * j] = pk1
                dec_ref[0, 2 * j + 1] = pk2
        m = m - jnp.max(m, axis=0, keepdims=True)
        m_ref[:] = jnp.clip(m, lo, hi).astype(sdtype)

        @pl.when(t == pl.num_programs(1) - 1)
        def _flush():
            metrics_out_ref[0] = m_ref[:].astype(jnp.int32)

    return kernel


_acs_kernel_i16_r4 = _make_acs_kernel_int_lut(4, I16_MIN, I16_MAX,
                                              jnp.int16)
_acs_kernel_i8 = _make_acs_kernel_int_lut(2, I8_MIN, I8_MAX, jnp.int8)
_acs_kernel_i8_r4 = _make_acs_kernel_int_lut(4, I8_MIN, I8_MAX,
                                             jnp.int8)

_ACS_KERNELS = {
    ("float32", 2): _acs_kernel,
    ("float32", 4): _acs_kernel_r4,
    ("int16", 2): _acs_kernel_i16,
    ("int16", 4): _acs_kernel_i16_r4,
    ("int8", 2): _acs_kernel_i8,
    ("int8", 4): _acs_kernel_i8_r4,
}
_SCRATCH_DTYPE = {"float32": jnp.float32, "int16": jnp.int16,
                  "int8": jnp.int8}


@lru_cache(maxsize=None)
def _make_traceback_kernel(unroll: int):
    """Traceback kernel body for ``unroll`` backward steps per grid
    block: select the survivor decision at the current state (one-hot
    sum — no per-lane gather), emit the decoded bit, move to the
    predecessor. The plain lane-tile decode uses UNROLL-step blocks;
    the fused front-end decode uses symbol-aligned blocks
    (spb * n_dbps steps), hence the factory.

    dec_ref: (1, unroll, 8, 128) packed decision planes for trellis
      steps [T-(t+1)*unroll, T-t*unroll), walked in reverse within the
      block.
    metrics_ref: (64, 128) final path metrics (used only at t == 0).
    bits_ref: (1, unroll, 8, 128) int32 out — decoded bit planes, row 0
      of each (8, 128) plane carries it (8 sublanes keeps the store
      tile-aligned).
    s_ref: (8, 128) int32 scratch — row 0 is the current state per lane.
    """
    def kernel(dec_ref, metrics_ref, bits_ref, s_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            end = jnp.argmax(metrics_ref[0], axis=0).astype(jnp.int32)
            s_ref[:] = jnp.broadcast_to(end[None, :], (8, LANES))

        rows = jax.lax.broadcasted_iota(jnp.int32, (8, LANES), 0)
        state = s_ref[0:1, :]                          # (1, 128)
        for j in reversed(range(unroll)):
            packed = dec_ref[0, j].astype(jnp.int32)   # (8, 128)
            onehot = (rows == (state >> 3)).astype(jnp.int32)
            byte = jnp.sum(packed * onehot, axis=0,
                           keepdims=True)              # (1, 128)
            d = (byte >> (state & 7)) & 1              # unpack bit
            bits_ref[0, j] = jnp.broadcast_to(state >> 5, (8, LANES))
            state = ((state & 31) << 1) | d
        s_ref[0:1, :] = state

    return kernel


def _interpret_default() -> bool:
    # the axon-tunnelled chip registers its backend as 'tpu' (verified:
    # Mosaic compiles these kernels there), so this only falls back to
    # interpret mode on genuinely non-TPU backends (CPU tests)
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit,
                   static_argnames=("interpret", "metric_dtype", "radix"))
def _acs_tiles(llrs, interpret: bool, metric_dtype: str = "float32",
               radix: int = 2):
    """ACS sweep alone: (nb, Tp, 2, 128) lane tiles (Tp already a
    multiple of UNROLL) -> (packed decision planes, final metrics).
    Split from `_decode_tiles` so the bench breakdown can time the two
    kernels separately (tools/rx_dispatch_bench.viterbi_breakdown —
    the `bench.py:722` "dependency-chain-bound, but WHERE?" answer)."""
    i_in = metric_dtype in ("int16", "int8")
    nb, Tp = llrs.shape[0], llrs.shape[1]
    TB = Tp // UNROLL                       # grid blocks per trellis
    return pl.pallas_call(
        _ACS_KERNELS[(metric_dtype, radix)],
        grid=(nb, TB),
        in_specs=[pl.BlockSpec((1, UNROLL, 2, LANES),
                               lambda b, t: (b, t, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, UNROLL, 8, LANES), lambda b, t: (b, t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.uint8),
            jax.ShapeDtypeStruct((nb, N_STATES, LANES),
                                 jnp.int32 if i_in else jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((N_STATES, LANES),
                                   _SCRATCH_DTYPE[metric_dtype])],
        interpret=interpret,
    )(llrs)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _traceback_tiles(dec, metrics, interpret: bool):
    """Traceback alone over UNROLL-step blocks: packed decision planes
    + final metrics -> (nb, Tp, 8, 128) bit planes (row 0 carries the
    decoded bit)."""
    nb, Tp = dec.shape[0], dec.shape[1]
    TB = Tp // UNROLL
    return pl.pallas_call(
        _make_traceback_kernel(UNROLL),
        grid=(nb, TB),
        in_specs=[
            pl.BlockSpec((1, UNROLL, 8, LANES),
                         lambda b, t, _n=TB: (b, _n - 1 - t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, UNROLL, 8, LANES),
                               lambda b, t, _n=TB: (b, _n - 1 - t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, LANES), jnp.int32)],
        interpret=interpret,
    )(dec, metrics)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "metric_dtype", "radix"))
def _decode_tiles(llrs, interpret: bool, metric_dtype: str = "float32",
                  radix: int = 2):
    """(nb, T, 2, 128) f32|int16 -> (nb, T, 128) uint8 decoded bit
    planes. ``metric_dtype`` picks the ACS kernel ("float32" the
    oracle, "int16"/"int8" the quantized saturating paths — quantized
    llr tiles either way); ``radix`` picks 1 or 2 trellis steps per
    kernel iteration (bit-identical at float32/int16)."""
    nb, T = llrs.shape[0], llrs.shape[1]
    # pad the trellis to a multiple of UNROLL with zero LLRs (erasures:
    # they add no likelihood, so the surviving path over the real prefix
    # is unchanged); the garbage pad bits are sliced off below
    Tp = -(-T // UNROLL) * UNROLL
    if Tp != T:
        llrs = jnp.pad(llrs, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))
    dec, metrics = _acs_tiles(llrs, interpret, metric_dtype, radix)
    bits = _traceback_tiles(dec, metrics, interpret)
    return bits[:, :T, 0, :].astype(jnp.uint8)


def _to_tiles(llrs):
    """(B, T, 2) -> lane tiles (nb, T, 2, 128): frames across the 128
    VPU lanes, lane count padded to a multiple of 128 with zero-LLR
    (erasure) rows. Returns (tiles, B)."""
    B, T = llrs.shape[0], llrs.shape[1]
    Bp = -(-B // LANES) * LANES
    x = jnp.transpose(llrs, (1, 2, 0))
    x = jnp.pad(x, ((0, 0), (0, 0), (0, Bp - B)))
    return x.reshape(T, 2, Bp // LANES, LANES).transpose(2, 0, 1, 3), B


def _quantize_for(md: str, llrs):
    """Quantize float LLRs at the kernel boundary for a quantized
    metric mode (PER-frame scale; already-int16 input passes through
    as pre-quantized — the windowed decode quantizes before cutting
    windows). int8 mode quantizes to ±INT8_QUANT_MAX but keeps the
    int16 storage dtype: the proven (1, UNROLL, 2, 128) int16 tile
    shape carries it, and the kernel's int32 upcast is identical.

    CAVEAT on the passthrough: int16 input is trusted to already be at
    THIS mode's quantization level (|q| <= 15 for int8, <= 127 for
    int16) — there is no runtime range check because the values may be
    traced. Feeding ±127-level integers into the int8 kernel would run
    its shallow saturation rail far outside the documented envelope;
    the only in-repo producer of pre-quantized input (the windowed
    decode above) quantizes with the mode's own qmax."""
    if llrs.dtype == jnp.int16:
        return llrs
    qmax = QUANT_MAX if md == "int16" else INT8_QUANT_MAX
    q, _scale = quantize_llrs(llrs, qmax=qmax)
    return q


def viterbi_decode_batch(llrs, n_bits: int = None, interpret: bool = None,
                         metric_dtype: str = None, radix: int = None):
    """Batched soft decode: llrs (B, T, 2) or (B, 2T) -> (B, T) bits.

    Same contract as ops.viterbi.viterbi_decode but over a whole batch of
    frames — the bench/TPU fast path. Lanes are padded to a multiple of
    128 with zero LLRs (erasures), which decode to garbage in the pad
    lanes and are sliced off.

    ``metric_dtype="int16"`` quantizes the LLRs at the kernel boundary
    (ops.viterbi.quantize_llrs, PER-frame scale) and runs the int16
    saturating-metric ACS kernel: half the llr HBM stream, half the
    metric VMEM footprint. ``"int8"`` quantizes to ±INT8_QUANT_MAX and
    runs the int8 saturating kernel with LUT branch metrics — half the
    resident metric state again, BER-envelope accuracy
    (docs/quantized_viterbi.md §int8). Already-int16 input is taken as
    pre-quantized and passed through untouched (the windowed decode
    quantizes before cutting windows). Default/"float32" is the exact
    oracle kernel.

    ``radix=4`` runs the two-steps-per-iteration ACS — bit-identical
    to radix 2 at float32 and int16 (and to the int8 radix-2 kernel on
    the same quantized inputs), half the sequential dependency chain.
    """
    if interpret is None:
        interpret = _interpret_default()
    md = _check_metric_dtype(metric_dtype)
    radix = _check_radix(radix)
    llrs = jnp.asarray(llrs)
    if llrs.ndim == 2:
        llrs = llrs.reshape(llrs.shape[0], -1, 2)
    if md == "float32":
        llrs = llrs.astype(jnp.float32)
    else:
        llrs = _quantize_for(md, llrs)                # int16 (B, T, 2)
    x, B = _to_tiles(llrs)
    bits = _decode_tiles(x, interpret, md, radix)     # (nb, T, 128)
    bits = bits.transpose(0, 2, 1).reshape(-1, llrs.shape[1])[:B]
    if n_bits is not None:
        bits = bits[:, :n_bits]
    return bits


DEFAULT_WINDOW_OVERLAP = 96   # ~14 constraint lengths of warmup


def viterbi_decode_batch_opt(llrs, n_bits: int = None,
                             window: int = None,
                             interpret: bool = None,
                             metric_dtype: str = None,
                             radix: int = None):
    """ONE dispatch for the batch decode's window/metric/radix options
    (review r5: the if/else was copied at every call site):
    ``window=None/0`` runs the exact kernel, ``window=N`` the
    sliding-window parallel decode below; ``metric_dtype`` selects the
    f32 oracle or a quantized saturating kernel and ``radix`` the
    steps-per-iteration either way."""
    if window:
        return viterbi_decode_batch_windowed(
            llrs, n_bits=n_bits, window=window, interpret=interpret,
            metric_dtype=metric_dtype, radix=radix)
    return viterbi_decode_batch(llrs, n_bits=n_bits, interpret=interpret,
                                metric_dtype=metric_dtype, radix=radix)


def viterbi_decode_batch_windowed(llrs, n_bits: int = None,
                                  window: int = 1024,
                                  overlap: int = DEFAULT_WINDOW_OVERLAP,
                                  interpret: bool = None,
                                  metric_dtype: str = None,
                                  radix: int = None,
                                  _decode=None):
    """Sliding-window PARALLEL decode: cut the T-step dependency chain
    into ceil(T/window) overlapping windows and run them as EXTRA BATCH
    LANES of the same kernel.

    The full-frame decode is dependency-chain-bound on TPU: 64 states
    fill half a VPU sublane tile while T (~8k for a 1000-byte frame)
    ACS steps run strictly sequentially, leaving the chip ~96% idle at
    B=128 (BENCH r4 roofline). Windowing converts that serial depth
    into batch parallelism: sequential depth drops from T to
    window + 2*overlap, and B*nwin lanes fill the idle lane tiles.

    Accuracy is the standard truncated-Viterbi argument (the
    reference's SORA brick likewise decodes with finite traceback
    depth): survivor paths of a K=7 code merge within ~5-10 constraint
    lengths with overwhelming probability, so each window's kept
    region [overlap, overlap+window) is decoded from fully-merged
    survivors; ``overlap`` defaults to 96 ≈ 14 constraint lengths.
    Boundary semantics match the full decode exactly where it matters:
    window 0 starts at position 0 with the kernel's known-state-0 init
    (its span is [0, window+2*overlap) and it keeps [0, window)), and
    every window ends on argmax metrics like the full decode; frames
    short enough for one window fall through to the exact path. On
    clean or operating-SNR inputs the output is bit-identical to
    ``viterbi_decode_batch`` (pinned by tests); on arbitrary
    adversarial inputs it is the windowed approximation, which is why
    this is an opt-in variant rather than the default.
    """
    if interpret is None:
        interpret = _interpret_default()
    md = _check_metric_dtype(metric_dtype)
    rdx = _check_radix(radix)
    if _decode is None:
        # the production engine; tools/windowed_ber.py injects the
        # lax.scan engine so the BER study measures exactly this
        # windowing math without interpret-mode Pallas cost on CPU
        def _decode(x):
            return viterbi_decode_batch(x, interpret=interpret,
                                        metric_dtype=md, radix=rdx)
    llrs = jnp.asarray(llrs)
    if llrs.ndim == 2:
        llrs = llrs.reshape(llrs.shape[0], -1, 2)
    if md != "float32":
        # quantize PER FRAME **before** cutting windows: every window
        # then slices the exact integers the full-frame decode sees
        # (the batch decode passes int16 through untouched), so
        # windowed int16/int8 == full int16/int8 by the same survivor-
        # merge argument as f32 — and no lane's scale depends on its
        # batch-mates. An injected _decode must accept int16 input.
        llrs = _quantize_for(md, llrs)
    else:
        llrs = llrs.astype(jnp.float32)
    B, T = llrs.shape[0], llrs.shape[1]
    ext = window + 2 * overlap
    if T <= ext:
        bits = _decode(llrs)
        return bits[:, :n_bits] if n_bits is not None else bits
    nwin = -(-T // window)
    starts = np.arange(nwin) * window - overlap
    starts[0] = 0            # window 0 keeps the known-state-0 start
    idx = jnp.asarray(starts)[:, None] + jnp.arange(ext)[None, :]
    # out-of-frame positions become zero-LLR erasures — the same
    # "adds no likelihood" padding the full decode uses for T%UNROLL.
    # idx >= 0 matters when window < overlap (review r5): without it,
    # negative warmup positions clip to 0 and feed repeated
    # full-confidence position-0 LLRs into the warmup instead of
    # neutral erasures
    valid = (idx >= 0) & (idx < T)
    wins = jnp.where(valid[None, :, :, None],
                     llrs[:, jnp.clip(idx, 0, T - 1), :],
                     jnp.zeros((), llrs.dtype))
    bits = _decode(wins.reshape(B * nwin, ext, 2))
    bits = bits.reshape(B, nwin, ext)
    keep = (jnp.where(jnp.arange(nwin) == 0, 0, overlap)[:, None]
            + jnp.arange(window)[None, :])             # (nwin, window)
    bits = jnp.take_along_axis(
        bits, jnp.broadcast_to(keep[None], (B, nwin, window)), axis=2)
    bits = bits.reshape(B, nwin * window)[:, :T]
    if n_bits is not None:
        bits = bits[:, :n_bits]
    return bits


# ------------------------------------------------------ fused front end
#
# The steady-state DATA decode's front end (demap -> deinterleave ->
# depuncture) is position-LOCAL per OFDM symbol: a symbol's n_cbps
# demapped LLRs land in exactly that symbol's 2*n_dbps depunctured
# slots (the deinterleaver permutes within the symbol; the puncture
# pattern period divides the symbol's slot count for every 802.11a
# rate). So for a KNOWN rate the whole front end is a static per-slot
# table — which subcarrier, which component, which level formula,
# which gain, erasure or not — and can run as an in-kernel prologue
# over the raw equalized symbol tile: one one-hot MXU gather for the
# component values, one for the gains, a handful of elementwise level
# ops, and the ACS consumes the LLRs straight out of VMEM. The LLR
# stream (the ACS kernel's dominant HBM input, 8 B per trellis step
# per lane) never exists in HBM at all.
#
# The tables are rate-static — but that is no longer a scope boundary:
# `viterbi_decode_mixed_fused` (below) stacks all 8 rates' tables into
# ONE constant bank and row-selects per lane IN-KERNEL from the traced
# rate index, so the mixed-rate lax.switch decode keeps its one
# rate-agnostic Viterbi across the batch AND gets the VMEM-resident
# LLR prologue (docs/architecture.md's decode-roofline section).


@lru_cache(maxsize=None)
def _front_tables(n_bpsc: int, n_cbps: int, n_dbps: int, coding: str):
    """Static one-symbol slot tables of the fused in-kernel front end.

    For depunctured slot p in [0, 2*n_dbps) of one OFDM symbol:
    ``sel_x`` (T2, 96) one-hot picks the slot's component value from
    the flattened (48 subcarriers x I/Q) symbol vector, ``sel_g``
    (T2, 48) its subcarrier's |H|^2 gain, and ``lcols`` (T2, 8) packs
    the per-slot constants (cols 0-2: level one-hot, col 3: level-1
    amplitude, col 4: depuncture validity — punctured slots stay
    all-zero and decode as exact 0.0 erasures). Composed from the SAME
    primitives the XLA front end runs (`demap.demap_bit_layout`,
    `interleave.deinterleave_slots`, `coding.PUNCTURE_KEEP`), so the
    two front ends cannot drift."""
    from ziria_tpu.ops.coding import PUNCTURE_KEEP
    from ziria_tpu.ops.demap import demap_bit_layout
    from ziria_tpu.ops.interleave import deinterleave_slots

    T2 = 2 * n_dbps
    keep = PUNCTURE_KEEP[coding]
    period, kept = keep.size, int(keep.sum())
    sub, bit = deinterleave_slots(n_cbps, n_bpsc)
    comp, lev, amp_b = demap_bit_layout(n_bpsc)
    sel_x = np.zeros((T2, 96), np.float32)
    sel_g = np.zeros((T2, 48), np.float32)
    lcols = np.zeros((T2, 8), np.float32)
    nkeep_before = np.cumsum(keep) - keep
    for p in range(T2):
        blk, off = divmod(p, period)
        if not keep[off]:
            continue
        q = blk * kept + int(nkeep_before[off])
        c, b = int(sub[q]), int(bit[q])
        sel_x[p, 2 * c + int(comp[b])] = 1.0
        sel_g[p, c] = 1.0
        lcols[p, int(lev[b])] = 1.0
        lcols[p, 3] = float(amp_b[b])
        lcols[p, 4] = 1.0
    return sel_x, sel_g, lcols


@lru_cache(maxsize=None)
def _make_fused_acs_kernel(spb: int, n_dbps: int, norm: float,
                           radix: int):
    """Fused front-end + ACS kernel for one rate (f32 metrics): each
    grid block covers ``spb`` OFDM symbols (chosen so a block is >=
    UNROLL trellis steps), demaps/deinterleaves/depunctures them in
    VMEM via the static slot tables, then runs the radix-2 or radix-4
    ACS over the block's spb*n_dbps steps. Per-lane true bit counts
    arrive as an input row: slots at/after a lane's count become exact
    0.0 erasures, the same mask decode_data_bucketed applies."""
    T2 = 2 * n_dbps

    def kernel(sym_ref, gain_ref, nbits_ref, selx_ref, selg_ref,
               lcol_ref, dec_ref, metrics_out_ref, m_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (N_STATES, LANES), 0)
            m_ref[:] = jnp.where(rows == 0, 0.0, _NEG).astype(jnp.float32)

        pack = _pack_sel()
        if radix == 2:
            coeffs = _branch_coeffs()
        else:
            step1, step2 = _branch_coeffs_r4()
        l0 = lcol_ref[:, 0:1]
        l1 = lcol_ref[:, 1:2]
        l2 = lcol_ref[:, 2:3]
        amp = lcol_ref[:, 3:4]
        valid = lcol_ref[:, 4:5]
        nb_row = nbits_ref[0, 0:1, :]                  # (1, 128)
        srow = jax.lax.broadcasted_iota(jnp.int32, (T2, LANES), 0) >> 1

        m = m_ref[:]
        for k in range(spb):
            # demap: one-hot MXU gathers are exact (each row sums one
            # value * 1.0), and the level formulas/multiply order are
            # demap()'s own, so the LLRs match the XLA front end bit
            # for bit (zero-sign differences at erasures aside, which
            # no comparison can see)
            x = jax.lax.dot(selx_ref[:], sym_ref[0, k], precision=_HI)
            g = jax.lax.dot(selg_ref[:], gain_ref[0], precision=_HI)
            xs = x * norm
            ax = jnp.abs(xs)
            f = l0 * xs + l1 * (amp - ax) + l2 * (2.0 - jnp.abs(ax - 4.0))
            llr = f * g * valid
            step0 = (t * spb + k) * n_dbps
            llr = jnp.where(step0 + srow < nb_row, llr, 0.0)
            base = k * n_dbps
            if radix == 2:
                for jj in range(n_dbps):
                    la = llr[2 * jj:2 * jj + 1, :]
                    lb = llr[2 * jj + 1:2 * jj + 2, :]
                    m, packed = _acs_step_f32(m, la, lb, coeffs, pack)
                    dec_ref[0, base + jj] = packed
            else:
                for jj in range(n_dbps // 2):
                    la1 = llr[4 * jj:4 * jj + 1, :]
                    lb1 = llr[4 * jj + 1:4 * jj + 2, :]
                    la2 = llr[4 * jj + 2:4 * jj + 3, :]
                    lb2 = llr[4 * jj + 3:4 * jj + 4, :]
                    m, pk1, pk2 = _acs_pair_r4_f32(
                        m, la1, lb1, la2, lb2, step1, step2, pack)
                    dec_ref[0, base + 2 * jj] = pk1
                    dec_ref[0, base + 2 * jj + 1] = pk2
        m = m - jnp.max(m, axis=0, keepdims=True)
        m_ref[:] = m

        @pl.when(t == pl.num_programs(1) - 1)
        def _flush():
            metrics_out_ref[0] = m_ref[:]

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("spb", "n_dbps", "norm", "radix",
                                    "interpret"))
def _fused_decode_tiles(x, g, nbits, sel_x, sel_g, lcols, spb: int,
                        n_dbps: int, norm: float, radix: int,
                        interpret: bool):
    """Fused-front-end decode over lane tiles: symbol tiles
    (nb, n_sym_p, 96, 128) + gain (nb, 48, 128) + per-lane bit counts
    -> (nb, Tp, 128) decoded bit planes."""
    nb, n_sym_p = x.shape[0], x.shape[1]
    NB = n_sym_p // spb
    steps = spb * n_dbps
    Tp = NB * steps
    T2 = 2 * n_dbps
    dec, metrics = pl.pallas_call(
        _make_fused_acs_kernel(spb, n_dbps, norm, radix),
        grid=(nb, NB),
        in_specs=[
            pl.BlockSpec((1, spb, 96, LANES), lambda b, t: (b, t, 0, 0)),
            pl.BlockSpec((1, 48, LANES), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, 8, LANES), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((T2, 96), lambda b, t: (0, 0)),
            pl.BlockSpec((T2, 48), lambda b, t: (0, 0)),
            pl.BlockSpec((T2, 8), lambda b, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, steps, 8, LANES), lambda b, t: (b, t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.uint8),
            jax.ShapeDtypeStruct((nb, N_STATES, LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((N_STATES, LANES), jnp.float32)],
        interpret=interpret,
    )(x, g, nbits, sel_x, sel_g, lcols)

    bits = pl.pallas_call(
        _make_traceback_kernel(steps),
        grid=(nb, NB),
        in_specs=[
            pl.BlockSpec((1, steps, 8, LANES),
                         lambda b, t, _n=NB: (b, _n - 1 - t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, steps, 8, LANES),
                               lambda b, t, _n=NB: (b, _n - 1 - t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, Tp, 8, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, LANES), jnp.int32)],
        interpret=interpret,
    )(dec, metrics)
    return bits[:, :, 0, :].astype(jnp.uint8)


def viterbi_decode_batch_fused(data, gain, rate, n_bits: int = None,
                               nbits_real=None, radix: int = None,
                               interpret: bool = None):
    """Fused-front-end batch decode: equalized, pilot-tracked DATA
    subcarriers -> decoded bits, with demap + deinterleave +
    depuncture executed as an IN-KERNEL prologue of the ACS sweep —
    the LLRs live and die in VMEM.

    data: (B, n_sym, 48, 2) equalized data-subcarrier pairs (the
    output of rx._front_symbols under vmap); gain: (B, 48) |H|^2
    reliability weights; rate: the RateParams of the ONE rate — the
    slot tables are rate-static, which is the fused path's scope
    boundary (the mixed-rate switch keeps the XLA front end);
    nbits_real: per-lane traced true data-bit counts (slots at/after
    become exact 0.0 erasures — decode_data_bucketed's mask), default
    everything real.

    float32 metrics only: the quantized paths scale by the whole
    frame's LLR peak before the first ACS step, which the in-kernel
    prologue never materializes; callers fall back to the unfused
    front for int16/int8. Decoded bits are bit-identical to the
    unfused decode on operating inputs (the demap arithmetic is
    expression-identical; only zero-sign noise at erasures and the
    block-cadence renorm differ, neither of which moves a comparison
    at operating SNR — pinned by tests/test_viterbi_radix4.py)."""
    if interpret is None:
        interpret = _interpret_default()
    radix = _check_radix(radix)
    data = jnp.asarray(data, jnp.float32)
    gain = jnp.asarray(gain, jnp.float32)
    B, n_sym = data.shape[0], data.shape[1]
    n_dbps = rate.n_dbps
    # symbols per grid block: lowest count giving >= UNROLL trellis
    # steps, so low rates (n_dbps 24..48) still amortize the Mosaic
    # grid step the way the plain kernel's UNROLL does
    spb = -(-UNROLL // n_dbps)
    n_sym_p = -(-n_sym // spb) * spb
    if n_sym_p != n_sym:
        # pad symbols produce garbage LLRs, but every pad slot is at/
        # after each lane's nbits and masks to a 0.0 erasure
        data = jnp.pad(data,
                       ((0, 0), (0, n_sym_p - n_sym), (0, 0), (0, 0)))
    T = n_sym * n_dbps
    if nbits_real is None:
        nbits = jnp.full((B,), T, jnp.int32)
    else:
        nbits = jnp.broadcast_to(
            jnp.asarray(nbits_real, jnp.int32), (B,))
    Bp = -(-B // LANES) * LANES
    nb_tiles = Bp // LANES
    x = data.reshape(B, n_sym_p, 96)          # (48, I/Q) -> 2c + comp
    x = jnp.pad(x, ((0, Bp - B), (0, 0), (0, 0)))
    x = x.transpose(1, 2, 0).reshape(n_sym_p, 96, nb_tiles, LANES) \
         .transpose(2, 0, 1, 3)
    g = jnp.pad(gain, ((0, Bp - B), (0, 0)))
    g = g.transpose(1, 0).reshape(48, nb_tiles, LANES).transpose(1, 0, 2)
    nbp = jnp.pad(nbits, (0, Bp - B)).reshape(nb_tiles, 1, LANES)
    nbp = jnp.broadcast_to(nbp, (nb_tiles, 8, LANES))
    sel_x, sel_g, lcols = _front_tables(rate.n_bpsc, rate.n_cbps,
                                        rate.n_dbps, rate.coding)
    from ziria_tpu.ops.demap import _NORM
    bits = _fused_decode_tiles(
        x, g, nbp, jnp.asarray(sel_x), jnp.asarray(sel_g),
        jnp.asarray(lcols), spb, n_dbps, float(_NORM[rate.n_bpsc]),
        radix, interpret)
    bits = bits.transpose(0, 2, 1).reshape(Bp, -1)[:B, :T]
    if n_bits is not None:
        bits = bits[:, :n_bits]
    return bits


# ------------------------------------------- rate-switched fused front end
#
# The mixed-rate decode (phy/wifi/rx.decode_data_mixed) runs ONE
# rate-agnostic Viterbi over a batch whose lanes carry different rates;
# until ISSUE 20 its front end stayed in XLA because the fused tables
# above are rate-static. The scheduling fact that un-blocks it: every
# 802.11a n_dbps (24, 36, 48, 72, 96, 144, 192, 216) is a multiple of
# 12, so any 12-trellis-step window starting at a multiple of 12 lies
# inside exactly ONE OFDM symbol at EVERY rate, covering a 24-slot
# stretch of that rate's depunctured stream that starts at a multiple
# of 24. Chop each rate's (2*n_dbps, ...) slot tables into
# n_dbps/12 <= 18 chunks of 24 rows, stack them as one
# (8, 18, 24, ...) constant bank, and a kernel block of 72 steps
# (6 sub-blocks; 72 divides every bucket's n_sym_bucket * 216 trellis)
# needs only LEADING-dim indexing — static rate row, traced chunk
# index — to fetch the right 24 rows: the banks stay static to Mosaic
# and there is no per-lane gather. Per sub-block the kernel computes
# all 8 rates' LLRs and lane-selects with the traced rate index — the
# SAME 8-way compute-then-select the vmapped lax.switch lowers to, so
# nothing is wasted relative to the unfused graph, while the LLRs (the
# ACS kernel's dominant HBM input) and the 8-way-redundant XLA front
# end both disappear from HBM: the fused graph runs ONE rate-
# independent `rx._front_symbols` per lane instead of 8 per-rate
# branches.
#
# Gains ride the SAME one-hot: sel_x rows pick component 2*c + comp of
# the flattened symbol, and a (96, LANES) gain plane with row
# 2*c + u = gain[c] makes `sel_x @ gain2` the exact |H|^2 gather — no
# separate gain bank, keeping the constant-bank bytes (~1.4 MB) below
# the LLR bytes they remove.

#: trellis steps per mixed-fused sub-block: gcd of all 8 rates' n_dbps
MIXED_SUB = 12
#: trellis steps per mixed-fused grid block (6 sub-blocks; divides
#: n_sym_bucket * MAX_DBPS for every bucket since 72 | 216)
MIXED_UNROLL = 72
#: chunks per rate row in the stacked bank: max n_dbps / MIXED_SUB
MIXED_CHUNKS = 18


@lru_cache(maxsize=None)
def _mixed_rate_geometry():
    """(n_dbps, norm) per rate in RATE_MBPS_ORDER — the static per-rate
    constants the mixed-fused kernel unrolls over. Imported lazily so
    ops/ keeps no import-time dependency on phy/."""
    from ziria_tpu.ops.demap import _NORM
    from ziria_tpu.phy.wifi.params import RATE_MBPS_ORDER, RATES
    ndbps = tuple(RATES[m].n_dbps for m in RATE_MBPS_ORDER)
    norms = tuple(float(_NORM[RATES[m].n_bpsc]) for m in RATE_MBPS_ORDER)
    return ndbps, norms


@lru_cache(maxsize=None)
def mixed_front_tables():
    """The stacked all-rates slot-table bank of the rate-switched fused
    front end: ``bank_x`` (8, 18, 24, 96) and ``bank_l`` (8, 18, 24, 8)
    float32, where row r is rate RATE_MBPS_ORDER[r] and chunk c holds
    depunctured slot rows [24c, 24c + 24) of that rate's `_front_tables`
    (chunks at/after n_dbps[r]/12 stay zero — they are never selected).
    Row-selecting (r, c) reproduces the per-rate tables
    `demap.demap_bit_layout` / `interleave.deinterleave_slots` /
    `coding.PUNCTURE_KEEP` emit today, which is the jax-free pin in
    tests/test_viterbi_fused_mixed.py. Numpy only — no trace, no
    compile."""
    from ziria_tpu.phy.wifi.params import RATE_MBPS_ORDER, RATES
    ndbps, _norms = _mixed_rate_geometry()
    bank_x = np.zeros((8, MIXED_CHUNKS, 2 * MIXED_SUB, 96), np.float32)
    bank_l = np.zeros((8, MIXED_CHUNKS, 2 * MIXED_SUB, 8), np.float32)
    for r, m in enumerate(RATE_MBPS_ORDER):
        rate = RATES[m]
        sel_x, _sel_g, lcols = _front_tables(rate.n_bpsc, rate.n_cbps,
                                             rate.n_dbps, rate.coding)
        for c in range(ndbps[r] // MIXED_SUB):
            rows = slice(2 * MIXED_SUB * c, 2 * MIXED_SUB * (c + 1))
            bank_x[r, c] = sel_x[rows]
            bank_l[r, c] = lcols[rows]
    return bank_x, bank_l


@lru_cache(maxsize=None)
def _make_mixed_fused_acs_kernel(n_sym_p: int, radix: int):
    """Rate-switched fused front-end + ACS kernel (f32 metrics): each
    grid block covers MIXED_UNROLL trellis steps of the bucket-maximal
    mixed trellis. Per 12-step sub-block and per rate (a STATIC 8-way
    unroll — the same 8-way compute the vmapped lax.switch lowers to),
    the symbol index and bank chunk are computed from the traced block
    position, the 24-slot tables fetched by leading-dim indexing, the
    demap expression evaluated in VMEM, and the lanes running that rate
    selected with `where` on the traced rate-index row. Slots at/after
    a lane's true bit count become exact 0.0 erasures (the mask
    decode_data_mixed applies), which also covers the clamped
    symbol-index reads past a low-rate lane's bucket."""
    ndbps, norms = _mixed_rate_geometry()
    nsub = MIXED_UNROLL // MIXED_SUB
    T2 = 2 * MIXED_SUB

    def kernel(sym_ref, gain_ref, nbits_ref, ridx_ref, *refs):
        bx_refs = refs[:8]                 # per-rate (cyc_r, 24, 96)
        bl_refs = refs[8:16]               # per-rate (cyc_r, 24, 8)
        dec_ref, metrics_out_ref, m_ref = refs[16:]
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            rows = jax.lax.broadcasted_iota(jnp.int32,
                                            (N_STATES, LANES), 0)
            m_ref[:] = jnp.where(rows == 0, 0.0, _NEG).astype(jnp.float32)

        pack = _pack_sel()
        if radix == 2:
            coeffs = _branch_coeffs()
        else:
            step1, step2 = _branch_coeffs_r4()
        nb_row = nbits_ref[0, 0:1, :]                  # (1, 128)
        r_row = ridx_ref[0, 0:1, :]                    # (1, 128) int32
        srow = jax.lax.broadcasted_iota(jnp.int32, (T2, LANES), 0) >> 1
        gain = gain_ref[0]                             # (48, 128)
        # (96, 128) plane with row 2c+u = gain[c]: sel_x @ gain2 is
        # then the exact per-slot |H|^2 gather (one-hot rows sum a
        # single value * 1.0) — no separate gain bank needed
        gain2 = jnp.concatenate([gain[:, None, :], gain[:, None, :]],
                                axis=1).reshape(96, LANES)

        # the sub-block walk is a lax.fori_loop, not a python unroll:
        # the lowered (and analytically costed) loop body is ONE
        # 12-step sub-block — 8 per-rate table reads + 16 small MXU
        # dots + 12 ACS steps — instead of MIXED_UNROLL steps of
        # straight-line code. Decision planes go straight to dec_ref
        # at a traced leading-dim offset (supported store form).
        def _sub_block(j, m):
            s0 = (t * nsub + j) * MIXED_SUB            # traced scalar
            llr = jnp.zeros((T2, LANES), jnp.float32)
            for r in range(8):
                ndb = ndbps[r]
                # this sub-block's symbol at rate r, clamped into the
                # resident tile: a low-rate lane's trellis ends at
                # n_sym_p * ndb < s0 for the clamped region, so every
                # clamped read feeds only nbits-masked erasure steps
                k_r = jnp.minimum(s0 // ndb, n_sym_p - 1)
                c_r = (s0 % ndb) // MIXED_SUB          # bank chunk
                selx = bx_refs[r][c_r]                 # (24, 96)
                lc = bl_refs[r][c_r]                   # (24, 8)
                x = jax.lax.dot(selx, sym_ref[0, k_r], precision=_HI)
                g = jax.lax.dot(selx, gain2, precision=_HI)
                xs = x * norms[r]
                ax = jnp.abs(xs)
                f = (lc[:, 0:1] * xs + lc[:, 1:2] * (lc[:, 3:4] - ax)
                     + lc[:, 2:3] * (2.0 - jnp.abs(ax - 4.0)))
                # where, not multiply: the vmapped switch also computes
                # every branch and SELECTS — NaN/Inf in a non-selected
                # rate's arithmetic must not leak across lanes
                llr = jnp.where(r_row == r, f * g * lc[:, 4:5], llr)
            llr = jnp.where(s0 + srow < nb_row, llr, 0.0)
            base = j * MIXED_SUB
            if radix == 2:
                for jj in range(MIXED_SUB):
                    la = llr[2 * jj:2 * jj + 1, :]
                    lb = llr[2 * jj + 1:2 * jj + 2, :]
                    m, packed = _acs_step_f32(m, la, lb, coeffs, pack)
                    dec_ref[0, base + jj] = packed
            else:
                for jj in range(MIXED_SUB // 2):
                    la1 = llr[4 * jj:4 * jj + 1, :]
                    lb1 = llr[4 * jj + 1:4 * jj + 2, :]
                    la2 = llr[4 * jj + 2:4 * jj + 3, :]
                    lb2 = llr[4 * jj + 3:4 * jj + 4, :]
                    m, pk1, pk2 = _acs_pair_r4_f32(
                        m, la1, lb1, la2, lb2, step1, step2, pack)
                    dec_ref[0, base + 2 * jj] = pk1
                    dec_ref[0, base + 2 * jj + 1] = pk2
            return m

        m = jax.lax.fori_loop(0, nsub, _sub_block, m_ref[:])
        m = m - jnp.max(m, axis=0, keepdims=True)
        m_ref[:] = m

        @pl.when(t == pl.num_programs(1) - 1)
        def _flush():
            metrics_out_ref[0] = m_ref[:]

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("n_sym_p", "t_max", "radix",
                                    "interpret"))
def _mixed_fused_decode_tiles(x, g, nbits, ridx, bx, bl, n_sym_p: int,
                              t_max: int, radix: int, interpret: bool):
    """Rate-switched fused decode over lane tiles: whole-resident
    symbol tiles (nb, n_sym_p, 96, 128) + gain (nb, 48, 128) + per-lane
    bit-count/rate-index rows + the stacked table bank ->
    (nb, t_max, 128) decoded bit planes.

    The stacked (8, MIXED_CHUNKS, ...) bank enters the kernel as 8
    per-rate operands trimmed to each rate's real chunk count
    (n_dbps/12): the in-kernel chunk read then dynamic-slices one
    small per-rate table, never the whole bank — rate r's row is a
    trace-time static slice, so nothing is gathered at runtime."""
    nb = x.shape[0]
    NB = t_max // MIXED_UNROLL
    ndbps, _norms = _mixed_rate_geometry()
    cyc = [n // MIXED_SUB for n in ndbps]
    bxr = [bx[r, :cyc[r]] for r in range(8)]
    blr = [bl[r, :cyc[r]] for r in range(8)]
    bank_specs = (
        [pl.BlockSpec((cyc[r], 2 * MIXED_SUB, 96),
                      lambda b, t: (0, 0, 0)) for r in range(8)]
        + [pl.BlockSpec((cyc[r], 2 * MIXED_SUB, 8),
                        lambda b, t: (0, 0, 0)) for r in range(8)])
    dec, metrics = pl.pallas_call(
        _make_mixed_fused_acs_kernel(n_sym_p, radix),
        grid=(nb, NB),
        in_specs=[
            pl.BlockSpec((1, n_sym_p, 96, LANES),
                         lambda b, t: (b, 0, 0, 0)),
            pl.BlockSpec((1, 48, LANES), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, 8, LANES), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, 8, LANES), lambda b, t: (b, 0, 0)),
        ] + bank_specs,
        out_specs=[
            pl.BlockSpec((1, MIXED_UNROLL, 8, LANES),
                         lambda b, t: (b, t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, t_max, 8, LANES), jnp.uint8),
            jax.ShapeDtypeStruct((nb, N_STATES, LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((N_STATES, LANES), jnp.float32)],
        interpret=interpret,
    )(x, g, nbits, ridx, *bxr, *blr)

    bits = pl.pallas_call(
        _make_traceback_kernel(MIXED_UNROLL),
        grid=(nb, NB),
        in_specs=[
            pl.BlockSpec((1, MIXED_UNROLL, 8, LANES),
                         lambda b, t, _n=NB: (b, _n - 1 - t, 0, 0)),
            pl.BlockSpec((1, N_STATES, LANES), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, MIXED_UNROLL, 8, LANES),
                               lambda b, t, _n=NB: (b, _n - 1 - t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, t_max, 8, LANES), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, LANES), jnp.int32)],
        interpret=interpret,
    )(dec, metrics)
    return bits[:, :, 0, :].astype(jnp.uint8)


def viterbi_decode_mixed_fused(data, gain, rate_idx, nbits_real,
                               radix: int = None,
                               interpret: bool = None):
    """Rate-SWITCHED fused-front-end batch decode: equalized,
    pilot-tracked DATA subcarriers of a mixed-rate batch -> decoded
    bits over the bucket-maximal trellis, with demap + deinterleave +
    depuncture executed as an in-kernel prologue that row-selects each
    lane's slot tables from the stacked all-rates bank — the LLRs live
    and die in VMEM on the path every fleet surface actually runs.

    data: (B, n_sym_bucket, 48, 2) equalized data-subcarrier pairs
    (rx._front_symbols under ONE rate-independent vmap — the fused
    graph's whole XLA front end, vs 8 per-rate branches unfused);
    gain: (B, 48) |H|^2 weights; rate_idx: (B,) traced indices into
    RATE_MBPS_ORDER; nbits_real: (B,) traced true data-bit counts.
    Returns (B, n_sym_bucket * MAX_DBPS) raw decoded bits — the same
    shape/semantics as the unfused mixed trellis, so the descramble
    tail is shared.

    float32 metrics only, radix 2 or 4 (the quantized paths scale by
    the whole frame's LLR peak the prologue never materializes;
    decode_data_mixed falls back to the unfused front for them).
    Bit-identity contract vs the unfused mixed decode matches the
    known-rate fused path's: expression-identical demap arithmetic and
    the identical erasure mask, renorm cadence MIXED_UNROLL instead of
    UNROLL (pinned lane-for-lane at the test seeds across all 8 rates;
    tests/test_viterbi_fused_mixed.py)."""
    if interpret is None:
        interpret = _interpret_default()
    radix = _check_radix(radix)
    ndbps, _norms = _mixed_rate_geometry()
    data = jnp.asarray(data, jnp.float32)
    gain = jnp.asarray(gain, jnp.float32)
    B, n_sym_b = data.shape[0], data.shape[1]
    t_max = n_sym_b * max(ndbps)
    Bp = -(-B // LANES) * LANES
    nb_tiles = Bp // LANES
    x = data.reshape(B, n_sym_b, 96)          # (48, I/Q) -> 2c + comp
    x = jnp.pad(x, ((0, Bp - B), (0, 0), (0, 0)))
    x = x.transpose(1, 2, 0).reshape(n_sym_b, 96, nb_tiles, LANES) \
         .transpose(2, 0, 1, 3)
    g = jnp.pad(gain, ((0, Bp - B), (0, 0)))
    g = g.transpose(1, 0).reshape(48, nb_tiles, LANES).transpose(1, 0, 2)

    def _rows(v):
        # pad lanes ride rate 0 / nbits 0: every step masks to an
        # erasure, the unfused path's zero-LLR pad-lane semantics
        vp = jnp.pad(jnp.broadcast_to(jnp.asarray(v, jnp.int32), (B,)),
                     (0, Bp - B)).reshape(nb_tiles, 1, LANES)
        return jnp.broadcast_to(vp, (nb_tiles, 8, LANES))

    bank_x, bank_l = mixed_front_tables()
    bits = _mixed_fused_decode_tiles(
        x, g, _rows(nbits_real), _rows(rate_idx), jnp.asarray(bank_x),
        jnp.asarray(bank_l), n_sym_b, t_max, radix, interpret)
    return bits.transpose(0, 2, 1).reshape(Bp, -1)[:B]
