"""Soft demapper: equalized symbols -> per-bit LLRs.

Counterpart of the reference RX's per-rate soft demapping blocks
(SURVEY.md §2.3). Max-log approximate LLRs for the 802.11 Gray
constellations, fully vectorized over subcarriers/symbols/frames; the
channel gain |H|^2 weights each subcarrier's reliability so the Viterbi
metric is SNR-aware after zero-forcing equalization.

Sign convention matches ops/viterbi: positive LLR = bit more likely 1.
Level-domain formulas (y = equalized amplitude in integer level units):

    axis bit 0 (sign):        y
    axis bit 1 (16/64-QAM):   2 - |y|        (16-QAM)  /  4 - |y| (64-QAM)
    axis bit 2 (64-QAM):      2 - ||y| - 4|
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_NORM = {1: 1.0, 2: np.sqrt(2.0), 4: np.sqrt(10.0), 6: np.sqrt(42.0)}


def demap(syms, n_bpsc: int, gain=None) -> jnp.ndarray:
    """(..., m, 2) equalized pair symbols -> (..., m*n_bpsc) LLRs.

    gain: optional (..., m) per-symbol reliability weight (|H|^2 after
    zero-forcing); defaults to 1.
    """
    syms = jnp.asarray(syms, jnp.float32)
    i = syms[..., 0] * _NORM[n_bpsc]
    q = syms[..., 1] * _NORM[n_bpsc]
    if n_bpsc == 1:
        bits = i[..., None]
    elif n_bpsc == 2:
        bits = jnp.stack([i, q], axis=-1)
    elif n_bpsc == 4:
        bits = jnp.stack([i, 2.0 - jnp.abs(i),
                          q, 2.0 - jnp.abs(q)], axis=-1)
    elif n_bpsc == 6:
        bits = jnp.stack([i, 4.0 - jnp.abs(i), 2.0 - jnp.abs(jnp.abs(i) - 4.0),
                          q, 4.0 - jnp.abs(q), 2.0 - jnp.abs(jnp.abs(q) - 4.0)],
                         axis=-1)
    else:
        raise ValueError(f"unsupported n_bpsc {n_bpsc}")
    if gain is not None:
        bits = bits * jnp.asarray(gain, jnp.float32)[..., None]
    return bits.reshape(syms.shape[:-2] + (syms.shape[-2] * n_bpsc,))


def demap_bit_layout(n_bpsc: int):
    """Static per-bit demap descriptors for the IN-KERNEL fused front
    end (ops/viterbi_pallas' demap→deinterleave→depuncture prologue).

    For bit index b within one subcarrier's ``n_bpsc`` demapped LLRs,
    returns ``(comp, lev, amp)`` numpy arrays: ``comp[b]`` selects the
    component (0 = I, 1 = Q), ``lev[b]`` the level-domain formula the
    module docstring lists (0: ``x``; 1: ``amp - |x|``;
    2: ``2 - ||x| - 4|``), ``amp[b]`` the level-1 constant. The tables
    live HERE, next to :func:`demap`, so the kernel's formulas and the
    XLA demap can never drift — tests pin the fused decode bit-for-bit
    against the demap()+deinterleave()+depuncture() pipeline. Both
    fused fronts build from these descriptors: the known-rate
    `_front_tables` AND the rate-switched `mixed_front_tables` bank
    (all 8 rates stacked, row-selected in-kernel —
    tests/test_viterbi_fused_mixed.py pins the bank rows to exactly
    these layouts, jax-free)."""
    if n_bpsc == 1:
        comp, lev, amp = [0], [0], [0.0]
    elif n_bpsc == 2:
        comp, lev, amp = [0, 1], [0, 0], [0.0, 0.0]
    elif n_bpsc == 4:
        comp, lev, amp = [0, 0, 1, 1], [0, 1, 0, 1], [0.0, 2.0, 0.0, 2.0]
    elif n_bpsc == 6:
        comp = [0, 0, 0, 1, 1, 1]
        lev = [0, 1, 2, 0, 1, 2]
        amp = [0.0, 4.0, 2.0, 0.0, 4.0, 2.0]
    else:
        raise ValueError(f"unsupported n_bpsc {n_bpsc}")
    return (np.asarray(comp, np.int32), np.asarray(lev, np.int32),
            np.asarray(amp, np.float32))


def np_demap_hard_ref(syms_c: np.ndarray, n_bpsc: int) -> np.ndarray:
    """Independent hard-decision oracle: nearest constellation point via
    the modulator's own tables, returning its bit label. Tests only."""
    from ziria_tpu.ops.modulate import np_modulate_ref
    pts = []
    labels = []
    for v in range(1 << n_bpsc):
        bits = np.array([(v >> (n_bpsc - 1 - k)) & 1
                         for k in range(n_bpsc)], np.uint8)
        pts.append(np_modulate_ref(bits, n_bpsc)[0])
        labels.append(bits)
    pts = np.asarray(pts)
    out = []
    for s in np.asarray(syms_c).reshape(-1):
        out.append(labels[int(np.argmin(np.abs(pts - s)))])
    return np.concatenate(out)
