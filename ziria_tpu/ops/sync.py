"""Packet detection, CFO estimation/correction, channel estimation.

Counterpart of the reference RX's front half (SURVEY.md §2.3, §3.4):
packet detect via STS autocorrelation, coarse/fine CFO from STS/LTS
lag products, channel estimation from the two LTS symbols. All in pair
representation, all expressed as whole-array ops (short convolutions
for sliding correlations — see _sliding_sum for why not cumsum) so a
frame's worth of samples is one fused graph.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx
from ziria_tpu.ops.ofdm import LTS_FREQ, N_FFT


def _sliding_sum(x, w: int):
    """Sliding window sums along axis 0: out[k] = sum(x[k:k+w]).

    Computed as a w-tap convolution, NOT a global cumsum difference:
    prefix sums accumulate f32 rounding along the whole stream and the
    window value c[k+w]-c[k] is a catastrophic cancellation once the
    prefix dwarfs the window (measured ~0.2% metric error at 14k
    samples, and host vs stream-sharded results diverged). The conv
    accumulates only the w local terms, is position-independent — so
    `parallel/streampar.sliding_parallel` shards bit-compatibly — and
    a 48-tap conv is nothing on the VPU/MXU.
    """
    import jax
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        # integer windows: cumsum differences are EXACT (no rounding),
        # and jnp.convolve would promote to float
        c = jnp.cumsum(x, axis=0)
        c = jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0)
        return c[w:] - c[:-w]
    k = jnp.ones((w,), x.dtype)

    def conv1(col):
        return jnp.convolve(col, k, mode="valid")

    if x.ndim == 1:
        return conv1(x)
    flat = x.reshape(x.shape[0], -1)
    out = jax.vmap(conv1, in_axes=1, out_axes=1)(flat)
    return out.reshape((out.shape[0],) + x.shape[1:])


def sts_autocorr(samples, window: int = 48):
    """Normalized lag-16 autocorrelation metric over a sample stream.

    samples: (n, 2). Returns (metric (n-16-window+1,), corr pairs).
    metric ~ 1 inside the short preamble's periodic region.
    """
    x = jnp.asarray(samples, jnp.float32)
    a, b = x[:-16], x[16:]
    prod = cplx.cmul_conj(b, a)            # r[k+16] * conj(r[k])
    corr = _sliding_sum(prod, window)      # (n-16-window+1, 2)
    energy = _sliding_sum(cplx.cabs2(b), window)
    metric = jnp.sqrt(cplx.cabs2(corr)) / (energy + 1e-9)
    return metric, corr


def detect_packet(samples, window: int = 48, threshold: float = 0.75):
    """Return (detected?, start_index) — the first index where the STS
    autocorrelation metric crosses the threshold (start of the plateau).
    Data-dependent only in the returned index, so it jits (lax-friendly
    argmax over a boolean ramp)."""
    metric, _ = sts_autocorr(samples, window)
    above = metric > threshold
    detected = jnp.any(above)
    start = jnp.argmax(above).astype(jnp.int32)  # first True
    return detected, start


def estimate_cfo_sts(samples, n_pairs: int = 96):
    """CFO estimate (rad/sample) from the short preamble region of an
    aligned frame (samples[0] = frame start). Uses lag-16 products over
    the STS body."""
    x = jnp.asarray(samples, jnp.float32)[: 160]
    prod = cplx.cmul_conj(x[16:16 + n_pairs], x[:n_pairs])
    s = jnp.sum(prod, axis=0)
    return cplx.cangle(s) / 16.0


def estimate_cfo_lts(samples):
    """Fine CFO from the two aligned LTS symbols (samples[0] = frame
    start; LTS symbols at 192..256..320). Lag-64 product."""
    x = jnp.asarray(samples, jnp.float32)
    l1 = x[192:256]
    l2 = x[256:320]
    s = jnp.sum(cplx.cmul_conj(l2, l1), axis=0)
    return cplx.cangle(s) / 64.0


def correct_cfo(samples, eps):
    """Multiply samples by e^{-j*eps*n}."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    rot = cplx.cexp(-eps * n)
    return cplx.cmul(x, rot)


def estimate_channel(samples):
    """Channel estimate from the two LTS symbols of an aligned,
    CFO-corrected frame (samples[0] = frame start). Returns H as
    (64, 2) pairs (zero on unused bins), normalized to the same scale
    ofdm_demodulate uses, so H == 1 for an identity channel."""
    from ziria_tpu.ops.ofdm import TIME_SCALE

    x = jnp.asarray(samples, jnp.float32)
    l1 = cplx.fft_pair(x[192:256])
    l2 = cplx.fft_pair(x[256:320])
    avg = (l1 + l2) * (0.5 / TIME_SCALE)
    # known LTS is real +-1 (0 on unused): H = Y / X = Y * X (X real unit)
    ref = np.zeros(N_FFT, np.float32)
    ref[(np.arange(-26, 27) % N_FFT)] = LTS_FREQ.astype(np.float32)
    return avg * jnp.asarray(ref)[:, None]
