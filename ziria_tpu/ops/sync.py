"""Packet detection, CFO estimation/correction, channel estimation.

Counterpart of the reference RX's front half (SURVEY.md §2.3, §3.4):
packet detect via STS autocorrelation, coarse/fine CFO from STS/LTS
lag products, channel estimation from the two LTS symbols. All in pair
representation, all expressed as whole-array ops (short convolutions
for sliding correlations — see _sliding_sum for why not cumsum) so a
frame's worth of samples is one fused graph.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx
from ziria_tpu.ops.ofdm import LTS_FREQ, N_FFT, lts_time_symbol


def _sliding_sum(x, w: int):
    """Sliding window sums along axis 0: out[k] = sum(x[k:k+w]).

    Computed as a w-tap convolution, NOT a global cumsum difference:
    prefix sums accumulate f32 rounding along the whole stream and the
    window value c[k+w]-c[k] is a catastrophic cancellation once the
    prefix dwarfs the window (measured ~0.2% metric error at 14k
    samples, and host vs stream-sharded results diverged). The conv
    accumulates only the w local terms, is position-independent — so
    `parallel/streampar.sliding_parallel` shards bit-compatibly — and
    a 48-tap conv is nothing on the VPU/MXU.
    """
    import jax
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        # integer windows: cumsum differences are EXACT (no rounding),
        # and jnp.convolve would promote to float
        c = jnp.cumsum(x, axis=0)
        c = jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0)
        return c[w:] - c[:-w]
    k = jnp.ones((w,), x.dtype)

    def conv1(col):
        return jnp.convolve(col, k, mode="valid")

    if x.ndim == 1:
        return conv1(x)
    flat = x.reshape(x.shape[0], -1)
    out = jax.vmap(conv1, in_axes=1, out_axes=1)(flat)
    return out.reshape((out.shape[0],) + x.shape[1:])


def sts_autocorr(samples, window: int = 48):
    """Normalized lag-16 autocorrelation metric over a sample stream.

    samples: (n, 2). Returns (metric (n-16-window+1,), corr pairs).
    metric ~ 1 inside the short preamble's periodic region.
    """
    x = jnp.asarray(samples, jnp.float32)
    a, b = x[:-16], x[16:]
    prod = cplx.cmul_conj(b, a)            # r[k+16] * conj(r[k])
    corr = _sliding_sum(prod, window)      # (n-16-window+1, 2)
    energy = _sliding_sum(cplx.cabs2(b), window)
    metric = jnp.sqrt(cplx.cabs2(corr)) / (energy + 1e-9)
    return metric, corr


def detect_packet(samples, window: int = 48, threshold: float = 0.75,
                  limit=None):
    """Return (detected?, start_index) — the first index where the STS
    autocorrelation metric crosses the threshold (start of the plateau).
    Data-dependent only in the returned index, so it jits (lax-friendly
    argmax over a boolean ramp).

    ``limit`` (static or traced) caps the considered positions to
    those a LIMIT-length capture would evaluate — see
    :func:`locate_frame`, the one caller that needs it. This is THE
    detection gate: `locate_frame` delegates here, so the threshold/
    window defaults live in exactly one place."""
    metric, _ = sts_autocorr(samples, window)
    above = metric > threshold
    if limit is not None:
        above = above \
            & (jnp.arange(above.shape[0]) < limit - 16 - window + 1)
    detected = jnp.any(above)
    start = jnp.argmax(above).astype(jnp.int32)  # first True
    return detected, start


def estimate_cfo_sts(samples, n_pairs: int = 96):
    """CFO estimate (rad/sample) from the short preamble region of an
    aligned frame (samples[0] = frame start). Uses lag-16 products over
    the STS body."""
    x = jnp.asarray(samples, jnp.float32)[: 160]
    prod = cplx.cmul_conj(x[16:16 + n_pairs], x[:n_pairs])
    s = jnp.sum(prod, axis=0)
    return cplx.cangle(s) / 16.0


def estimate_cfo_lts(samples):
    """Fine CFO from the two aligned LTS symbols (samples[0] = frame
    start; LTS symbols at 192..256..320). Lag-64 product."""
    x = jnp.asarray(samples, jnp.float32)
    l1 = x[192:256]
    l2 = x[256:320]
    s = jnp.sum(cplx.cmul_conj(l2, l1), axis=0)
    return cplx.cangle(s) / 64.0


def correct_cfo(samples, eps):
    """Multiply samples by e^{-j*eps*n}."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    rot = cplx.cexp(-eps * n)
    return cplx.cmul(x, rot)


def locate_frame(samples, limit=None, window: int = 48,
                 threshold: float = 0.75):
    """Locate and align a frame in a sample stream: STS detection
    gate, LTS cross-correlation timing, coarse+fine CFO. Returns
    (found, frame_start_index, cfo_estimate).

    Whole-array ops at fixed shapes, data-dependent only in *values*
    (argmax index, dynamic_slice at the traced start), so it jits —
    and, crucially for the one-dispatch batched acquisition
    (phy/wifi/rx.acquire_many), it runs under ``vmap``: N captures'
    detects, peak-picks, and CFO estimates become ONE batched graph.

    ``limit`` (static or traced, default: the full length) caps the
    positions the detection gate and the peak-pick consider to those
    a LIMIT-length capture would evaluate. Values at positions below
    the cap depend only on their local window, so trailing zero
    padding never changes them — but a LONGER array also has MORE
    positions, whose windows can overlap the capture's last real
    samples. The batched acquisition pads every lane to one COMMON
    bucket, so each lane passes its OWN power-of-two bucket as
    ``limit`` and its detect/argmax stay bit-identical to the
    per-capture path padded to that bucket.
    """
    import jax

    x = jnp.asarray(samples, jnp.float32)
    n = x.shape[0]
    lim = n if limit is None else limit

    # STS detection gate (the coarse start is superseded by the LTS
    # timing below)
    detected, _coarse = detect_packet(x, window, threshold, limit=limit)

    # LTS timing: cross-correlate with the known long symbol; the two
    # LTS peaks are 64 apart; first LTS starts at frame_start + 192
    lts = jnp.asarray(lts_time_symbol())                # (64, 2)

    def xcorr(sig):
        # correlation of sig against lts at all lags (valid region)
        ref = cplx.conj(lts)[::-1]                      # reversed conj

        def conv1(u, v):
            return jnp.convolve(u, v, precision="highest")

        re = conv1(sig[:, 0], ref[:, 0]) - conv1(sig[:, 1], ref[:, 1])
        im = conv1(sig[:, 0], ref[:, 1]) + conv1(sig[:, 1], ref[:, 0])
        # full conv index 63+k = correlation at lag k
        return (re[63:n] ** 2 + im[63:n] ** 2)

    c = xcorr(x)                                        # (n-63,)
    pair = c[:-64] + c[64:]                             # two-peak sum
    # cap the peak-pick the same way (pair values are >= 0, so -1
    # sentinels can never win argmax while any in-cap position exists)
    pair = jnp.where(jnp.arange(pair.shape[0]) < lim - 127, pair, -1.0)
    lts1 = jnp.argmax(pair).astype(jnp.int32)
    frame_start = jnp.maximum(lts1 - 192, 0)

    # CFO from the aligned preamble: coarse (lag-16 STS, wide range)
    # then fine (lag-64 LTS, 4x resolution) on the coarse-corrected
    # head
    frame_head = jax.lax.dynamic_slice(x, (frame_start, 0), (320, 2))
    eps_c = estimate_cfo_sts(frame_head)
    head2 = correct_cfo(frame_head, eps_c)
    eps_f = estimate_cfo_lts(head2)
    return detected, frame_start, eps_c + eps_f


def estimate_channel(samples):
    """Channel estimate from the two LTS symbols of an aligned,
    CFO-corrected frame (samples[0] = frame start). Returns H as
    (64, 2) pairs (zero on unused bins), normalized to the same scale
    ofdm_demodulate uses, so H == 1 for an identity channel."""
    from ziria_tpu.ops.ofdm import TIME_SCALE

    x = jnp.asarray(samples, jnp.float32)
    l1 = cplx.fft_pair(x[192:256])
    l2 = cplx.fft_pair(x[256:320])
    avg = (l1 + l2) * (0.5 / TIME_SCALE)
    # known LTS is real +-1 (0 on unused): H = Y / X = Y * X (X real unit)
    ref = np.zeros(N_FFT, np.float32)
    ref[(np.arange(-26, 27) % N_FFT)] = LTS_FREQ.astype(np.float32)
    return avg * jnp.asarray(ref)[:, None]
