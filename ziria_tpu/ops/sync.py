"""Packet detection, CFO estimation/correction, channel estimation.

Counterpart of the reference RX's front half (SURVEY.md §2.3, §3.4):
packet detect via STS autocorrelation, coarse/fine CFO from STS/LTS
lag products, channel estimation from the two LTS symbols. All in pair
representation, all expressed as whole-array ops (short convolutions
for sliding correlations — see _sliding_sum for why not cumsum) so a
frame's worth of samples is one fused graph.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx
from ziria_tpu.ops.ofdm import LTS_FREQ, N_FFT, lts_time_symbol


def _sliding_sum(x, w: int):
    """Sliding window sums along axis 0: out[k] = sum(x[k:k+w]).

    Computed as a w-tap convolution, NOT a global cumsum difference:
    prefix sums accumulate f32 rounding along the whole stream and the
    window value c[k+w]-c[k] is a catastrophic cancellation once the
    prefix dwarfs the window (measured ~0.2% metric error at 14k
    samples, and host vs stream-sharded results diverged). The conv
    accumulates only the w local terms, is position-independent — so
    `parallel/streampar.sliding_parallel` shards bit-compatibly — and
    a 48-tap conv is nothing on the VPU/MXU.
    """
    import jax
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        # integer windows: cumsum differences are EXACT (no rounding),
        # and jnp.convolve would promote to float
        c = jnp.cumsum(x, axis=0)
        c = jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0)
        return c[w:] - c[:-w]
    k = jnp.ones((w,), x.dtype)

    def conv1(col):
        return jnp.convolve(col, k, mode="valid")

    if x.ndim == 1:
        return conv1(x)
    flat = x.reshape(x.shape[0], -1)
    out = jax.vmap(conv1, in_axes=1, out_axes=1)(flat)
    return out.reshape((out.shape[0],) + x.shape[1:])


def sts_autocorr(samples, window: int = 48):
    """Normalized lag-16 autocorrelation metric over a sample stream.

    samples: (n, 2). Returns (metric (n-16-window+1,), corr pairs).
    metric ~ 1 inside the short preamble's periodic region.
    """
    x = jnp.asarray(samples, jnp.float32)
    a, b = x[:-16], x[16:]
    prod = cplx.cmul_conj(b, a)            # r[k+16] * conj(r[k])
    corr = _sliding_sum(prod, window)      # (n-16-window+1, 2)
    energy = _sliding_sum(cplx.cabs2(b), window)
    metric = jnp.sqrt(cplx.cabs2(corr)) / (energy + 1e-9)
    return metric, corr


def detect_packet(samples, window: int = 48, threshold: float = 0.75,
                  limit=None):
    """Return (detected?, start_index) — the first index where the STS
    autocorrelation metric crosses the threshold (start of the plateau).
    Data-dependent only in the returned index, so it jits (lax-friendly
    argmax over a boolean ramp).

    This is the K=1, first-crossing special case of the multi-peak
    :func:`locate_frames` scan: one threshold crossing, no plateau
    `min_run` gate, no dead-zone suppression — exactly what a
    pre-segmented one-frame capture needs, and the detection gate the
    per-capture oracle (:func:`locate_frame`) keeps. The streaming
    receiver's chunk scan generalizes it to "every plateau in a long
    chunk"; this single-crossing form stays the oracle the K=1 lane of
    that scan is judged against.

    ``limit`` (static or traced) caps the considered positions to
    those a LIMIT-length capture would evaluate — see
    :func:`locate_frame`, the one caller that needs it. This is THE
    detection gate: `locate_frame` delegates here, so the threshold/
    window defaults live in exactly one place."""
    metric, _ = sts_autocorr(samples, window)
    above = metric > threshold
    if limit is not None:
        above = above \
            & (jnp.arange(above.shape[0]) < limit - 16 - window + 1)
    detected = jnp.any(above)
    start = jnp.argmax(above).astype(jnp.int32)  # first True
    return detected, start


def estimate_cfo_sts(samples, n_pairs: int = 96):
    """CFO estimate (rad/sample) from the short preamble region of an
    aligned frame (samples[0] = frame start). Uses lag-16 products over
    the STS body."""
    x = jnp.asarray(samples, jnp.float32)[: 160]
    prod = cplx.cmul_conj(x[16:16 + n_pairs], x[:n_pairs])
    s = jnp.sum(prod, axis=0)
    return cplx.cangle(s) / 16.0


def estimate_cfo_lts(samples):
    """Fine CFO from the two aligned LTS symbols (samples[0] = frame
    start; LTS symbols at 192..256..320). Lag-64 product."""
    x = jnp.asarray(samples, jnp.float32)
    l1 = x[192:256]
    l2 = x[256:320]
    s = jnp.sum(cplx.cmul_conj(l2, l1), axis=0)
    return cplx.cangle(s) / 64.0


def correct_cfo(samples, eps):
    """Multiply samples by e^{-j*eps*n}."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    rot = cplx.cexp(-eps * n)
    return cplx.cmul(x, rot)


def lts_pair_metric(samples, limit=None):
    """The LTS timing metric shared by the single-frame and streaming
    locators: cross-correlate the stream against the known long
    training symbol and sum the two 64-apart peak candidates, so
    ``pair[k]`` is large exactly when the first LTS starts at ``k``
    (frame start = k - 192). samples: (n, 2). Returns (n - 127,) f32,
    all values >= 0 except ``limit``-masked tail positions, which are
    -1 sentinels (a LIMIT-length capture would never evaluate them;
    they can never win an argmax while any in-cap position exists).

    Each value depends only on its 128-sample local window — the
    position-locality that lets the chunked streaming scan and the
    per-capture path read bit-identical values off differently-sized
    arrays covering the same samples."""
    x = jnp.asarray(samples, jnp.float32)
    n = x.shape[0]
    lim = n if limit is None else limit
    lts = jnp.asarray(lts_time_symbol())                # (64, 2)
    ref = cplx.conj(lts)[::-1]                          # reversed conj

    def conv1(u, v):
        return jnp.convolve(u, v, precision="highest")

    re = conv1(x[:, 0], ref[:, 0]) - conv1(x[:, 1], ref[:, 1])
    im = conv1(x[:, 0], ref[:, 1]) + conv1(x[:, 1], ref[:, 0])
    # full conv index 63+k = correlation at lag k
    c = re[63:n] ** 2 + im[63:n] ** 2                   # (n-63,)
    pair = c[:-64] + c[64:]                             # two-peak sum
    return jnp.where(jnp.arange(pair.shape[0]) < lim - 127, pair, -1.0)


def locate_frame(samples, limit=None, window: int = 48,
                 threshold: float = 0.75):
    """Locate and align a frame in a sample stream: STS detection
    gate, LTS cross-correlation timing, coarse+fine CFO. Returns
    (found, frame_start_index, cfo_estimate).

    Whole-array ops at fixed shapes, data-dependent only in *values*
    (argmax index, dynamic_slice at the traced start), so it jits —
    and, crucially for the one-dispatch batched acquisition
    (phy/wifi/rx.acquire_many), it runs under ``vmap``: N captures'
    detects, peak-picks, and CFO estimates become ONE batched graph.

    ``limit`` (static or traced, default: the full length) caps the
    positions the detection gate and the peak-pick consider to those
    a LIMIT-length capture would evaluate. Values at positions below
    the cap depend only on their local window, so trailing zero
    padding never changes them — but a LONGER array also has MORE
    positions, whose windows can overlap the capture's last real
    samples. The batched acquisition pads every lane to one COMMON
    bucket, so each lane passes its OWN power-of-two bucket as
    ``limit`` and its detect/argmax stay bit-identical to the
    per-capture path padded to that bucket.
    """
    import jax

    x = jnp.asarray(samples, jnp.float32)
    n = x.shape[0]
    lim = n if limit is None else limit

    # STS detection gate (the coarse start is superseded by the LTS
    # timing below)
    detected, _coarse = detect_packet(x, window, threshold, limit=limit)

    # LTS timing: cross-correlate with the known long symbol; the two
    # LTS peaks are 64 apart; first LTS starts at frame_start + 192.
    # The peak-pick is capped the same way as the detect gate (the
    # shared metric masks out-of-cap positions to -1 sentinels).
    pair = lts_pair_metric(x, limit=lim)
    lts1 = jnp.argmax(pair).astype(jnp.int32)
    frame_start = jnp.maximum(lts1 - 192, 0)

    # CFO from the aligned preamble: coarse (lag-16 STS, wide range)
    # then fine (lag-64 LTS, 4x resolution) on the coarse-corrected
    # head
    frame_head = jax.lax.dynamic_slice(x, (frame_start, 0), (320, 2))
    eps_c = estimate_cfo_sts(frame_head)
    head2 = correct_cfo(frame_head, eps_c)
    eps_f = estimate_cfo_lts(head2)
    return detected, frame_start, eps_c + eps_f


# ----------------------------------------------------- streaming detection
#
# The chunked streaming receiver (backend/framebatch.receive_stream)
# needs the detection front end as "every frame in a LONG multi-frame
# chunk", not "the first frame of a pre-segmented capture".
# `locate_frames` is that generalization, fully traced so a chunk's
# whole scan rides one dispatch; `locate_frame` above stays the K=1
# first-peak oracle (single crossing, global peak-pick) that the
# per-capture receive path — and the identity contract of every
# streaming test — is judged against.


def locate_frames(samples, k: int, limit=None, window: int = 48,
                  threshold: float = 0.75, min_run: int = 33,
                  dead_zone: int = 320, align_back: int = 32,
                  align_span: int = 416, overflow_limit=None):
    """Locate up to ``k`` frame starts in a multi-frame sample chunk:
    top-K STS plateau extraction with dead-zone suppression, each
    candidate LTS-aligned by a local peak-pick. Returns
    ``(found (k,), starts (k,), overflow ())`` — `starts` are exact
    frame-start indices (ascending; -1 on not-found lanes), `overflow`
    is True when an eligible plateau remains beyond the K extracted
    (the caller must report it — frames are never silently dropped).

    The scan (all whole-array ops at fixed shapes, `k` static — jits
    and vmaps):

    1. **plateau gate**: a candidate needs ``min_run`` consecutive
       above-``threshold`` autocorrelation positions — the traced twin
       of `phy/search.find_packets`' host plateau rule (the energy
       roll-off at a frame's END can spike the normalized metric for a
       few positions; a real STS plateau spans ~96).
    2. **top-K extraction**: iteratively take the FIRST eligible
       plateau start, then suppress positions within ``dead_zone``
       samples of it. The dead zone must exceed the plateau run
       (~96 + noise slack, so one frame never yields two candidates)
       and stay under the minimum frame spacing (480 samples, a
       1-symbol frame at zero gap) minus the partial-preamble overhang
       a chunk boundary can introduce — 320, the preamble length,
       satisfies both.
    3. **local LTS alignment**: the shared :func:`lts_pair_metric` is
       computed ONCE over the chunk; each candidate's start is the
       two-peak argmax within ``[d - align_back, d - align_back +
       align_span)`` of its crossing ``d`` minus the 192-sample
       preamble offset. The restriction to a local window is what
       keeps K frames from stealing each other's peaks — and with one
       frame in the chunk it picks the same global peak
       :func:`locate_frame` does (the K=1 oracle relationship;
       :func:`detect_packet` is the matching single-crossing gate).

    ``limit`` (static or traced) caps both the plateau gate and the
    peak-pick to positions a LIMIT-length capture would evaluate,
    exactly as in :func:`locate_frame` — chunk zero-padding (a final
    partial chunk) never manufactures or perturbs candidates.

    ``overflow_limit`` (static or traced, default: everything) caps
    the positions the OVERFLOW scan considers: a streaming chunk owns
    only its first `stride` samples, and a leftover plateau in the
    deferred overlap region is the NEXT chunk's frame, not a drop —
    without the cap it would flag healthy streams. The cap uses the
    plateau crossing index (within ~an alignment span of the exact
    start), which is exact enough for a widen-K diagnostic."""
    import jax

    x = jnp.asarray(samples, jnp.float32)
    n = x.shape[0]
    lim = n if limit is None else limit

    metric, _ = sts_autocorr(x, window)
    above = metric > threshold
    above = above & (jnp.arange(above.shape[0]) < lim - 16 - window + 1)
    # ok[p] <=> positions [p, p+min_run) all above: integer sliding sum
    # (exact cumsum-difference path of _sliding_sum)
    runs = _sliding_sum(above.astype(jnp.int32), min_run)
    ok = runs == min_run
    idx = jnp.arange(ok.shape[0])

    def body(next_free, _):
        cand = ok & (idx >= next_free)
        found = jnp.any(cand)
        d = jnp.argmax(cand).astype(jnp.int32)   # first eligible start
        return jnp.where(found, d + dead_zone, next_free), (found, d)

    next_free, (found, d) = jax.lax.scan(
        body, jnp.int32(0), None, length=k)
    rem = ok & (idx >= next_free)
    if overflow_limit is not None:
        rem = rem & (idx < overflow_limit)
    overflow = jnp.any(rem)

    pair = lts_pair_metric(x, limit=lim)
    pidx = jnp.arange(pair.shape[0])

    def align(di):
        lo = di - align_back
        local = jnp.where((pidx >= lo) & (pidx < lo + align_span),
                          pair, -1.0)
        return jnp.argmax(local).astype(jnp.int32) - 192

    starts = jax.vmap(align)(d)
    starts = jnp.where(found, starts, jnp.int32(-1))
    return found, starts, overflow


def estimate_channel(samples):
    """Channel estimate from the two LTS symbols of an aligned,
    CFO-corrected frame (samples[0] = frame start). Returns H as
    (64, 2) pairs (zero on unused bins), normalized to the same scale
    ofdm_demodulate uses, so H == 1 for an identity channel."""
    from ziria_tpu.ops.ofdm import TIME_SCALE

    x = jnp.asarray(samples, jnp.float32)
    l1 = cplx.fft_pair(x[192:256])
    l2 = cplx.fft_pair(x[256:320])
    avg = (l1 + l2) * (0.5 / TIME_SCALE)
    # known LTS is real +-1 (0 on unused): H = Y / X = Y * X (X real unit)
    ref = np.zeros(N_FFT, np.float32)
    ref[(np.arange(-26, 27) % N_FFT)] = LTS_FREQ.astype(np.float32)
    return avg * jnp.asarray(ref)[:, None]
